"""Shape bucketing for the inference server.

The executable-set problem: XLA specializes one compiled program per
input geometry, so a server fed arbitrary (batch, seq, ...) shapes
recompiles without bound — the inference twin of the training-path
problem PR 1's structure-keyed CompileCache solved. The fix is the same
discipline production servers use (TF Serving's allowed_batch_sizes,
Triton's preferred_batch_size ladder): pad every micro-batch up to a
small fixed ladder of power-of-two *buckets* in the batch dimension
(and, for variable-length inputs, the sequence dimension), so the set
of geometries that ever reach the compiler is finite and steady-state
serving runs with zero recompiles.

Cost model: padding wastes at most 50% of rows at pow2 granularity
(usually far less under load, where batches fill), while an unbounded
shape set costs a multi-ms XLA compile on every novel geometry — three
orders of magnitude more than the padded FLOPs at serving batch sizes.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

__all__ = ["BucketSpec", "pow2_ladder", "decode_buckets"]


def pow2_ladder(bound: int) -> List[int]:
    """The canonical bucket ladder: powers of two below ``bound``, plus
    ``bound`` itself (so the largest bucket is exact, pow2 or not)."""
    if bound < 1:
        raise ValueError("bucket bound must be >= 1, got %d" % bound)
    ladder = []
    b = 1
    while b < bound:
        ladder.append(b)
        b <<= 1
    ladder.append(int(bound))
    return ladder


def decode_buckets(max_seq_len: int, page: int,
                   spec: Optional[str] = None) -> List[int]:
    """The decode sequence-length bucket ladder: every bucket is a page
    multiple (the int8 per-page scale grid requires it), capped at
    ``max_seq_len``. ``spec`` is the ``MXNET_TPU_SERVE_DECODE_BUCKETS``
    grammar (comma-separated ints); empty/None = the pow2 ladder from
    ``page`` up, with ``max_seq_len`` itself as the last rung."""
    if page < 1 or max_seq_len < page:
        raise ValueError("kv page %d must satisfy 1 <= page <= max_seq_len"
                         " %d" % (page, max_seq_len))
    if max_seq_len % page:
        raise ValueError("max_seq_len %d is not a multiple of the kv page "
                         "%d" % (max_seq_len, page))
    if spec:
        try:
            ladder = sorted(set(int(s) for s in spec.split(",") if s.strip()))
        except ValueError:
            raise ValueError("MXNET_TPU_SERVE_DECODE_BUCKETS must be a "
                             "comma-separated int list, got %r" % (spec,))
        if not ladder:
            raise ValueError("empty decode bucket spec %r" % (spec,))
    else:
        ladder = [b for b in pow2_ladder(max_seq_len) if b >= page]
    for b in ladder:
        if b % page:
            raise ValueError("decode bucket %d is not a multiple of the kv "
                             "page %d" % (b, page))
        if not 0 < b <= max_seq_len:
            raise ValueError("decode bucket %d outside (0, max_seq_len=%d]"
                             % (b, max_seq_len))
    if ladder[-1] != max_seq_len:
        ladder.append(int(max_seq_len))
    return ladder


class BucketSpec:
    """Maps request shapes onto the finite bucket grid.

    Parameters
    ----------
    max_batch_size : int
        Largest micro-batch bucket (the coalescing row bound).
    batch_buckets : sequence of int, optional
        Explicit batch-bucket ladder; default is the powers of two up to
        ``max_batch_size`` (``[1, 2, 4, ..., max_batch_size]``, with
        ``max_batch_size`` itself appended when it is not a power of
        two).
    seq_axis : int, optional
        Sample-shape axis (non-negative, 0-based, batch dim excluded)
        that may vary per request — sequence length for text, boxes for
        detection. ``None`` (default) means sample shapes must match a
        bucket head exactly to coalesce; every distinct sample shape is
        its own bucket, which is only bounded when client shapes are.

        Seq-padding contract: requests are padded with ``pad_value``
        along this axis up to the bucket length, the model runs on the
        PADDED input, and outputs come back at bucket geometry (callers
        slice to their real length). The model must therefore be
        padding-invariant along this axis at the real positions —
        masked attention, length-aware pooling, or pad-neutral
        reductions. A model where pad positions bleed into real ones
        (unmasked encoder attention, plain mean-pooling) will silently
        differ from unpadded serving; such models need the padding
        masked in-model or ``seq_axis=None``.
    max_seq_len : int, optional
        Required with ``seq_axis``: the admission bound on the dynamic
        axis; longer requests are rejected at submit.
    seq_buckets : sequence of int, optional
        Explicit ladder for the dynamic axis; default powers of two up
        to ``max_seq_len`` (plus ``max_seq_len`` itself).
    pad_value : float
        Fill for padded rows/positions.
    """

    def __init__(self, max_batch_size: int = 32,
                 batch_buckets: Optional[Sequence[int]] = None,
                 seq_axis: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 seq_buckets: Optional[Sequence[int]] = None,
                 pad_value: float = 0.0):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        self.max_batch_size = int(max_batch_size)
        if batch_buckets is None:
            batch_buckets = pow2_ladder(self.max_batch_size)
        self.batch_buckets: List[int] = sorted(set(int(b)
                                                   for b in batch_buckets))
        if self.batch_buckets[-1] != self.max_batch_size:
            raise ValueError("largest batch bucket %d != max_batch_size %d"
                             % (self.batch_buckets[-1], self.max_batch_size))
        if seq_axis is not None and seq_axis < 0:
            # the sample rank is unknown here, so a numpy-style negative
            # axis cannot be normalized — and left as-is it would read
            # the right dim but never match the enumerate() rewrite in
            # sample_bucket, silently disabling padding (one executable
            # per novel length: the exact regime bucketing exists to
            # prevent)
            raise ValueError(
                "seq_axis must be a non-negative index into the sample "
                "shape (batch dim excluded); got %d" % seq_axis)
        self.seq_axis = seq_axis
        self.pad_value = pad_value
        if seq_axis is not None:
            if max_seq_len is None:
                raise ValueError("seq_axis needs max_seq_len (the "
                                 "admission bound on the dynamic axis)")
            self.max_seq_len = int(max_seq_len)
            if seq_buckets is None:
                seq_buckets = pow2_ladder(self.max_seq_len)
            self.seq_buckets: Optional[List[int]] = sorted(
                set(int(s) for s in seq_buckets))
            if self.seq_buckets[-1] != self.max_seq_len:
                raise ValueError("largest seq bucket %d != max_seq_len %d"
                                 % (self.seq_buckets[-1], self.max_seq_len))
        else:
            self.max_seq_len = None
            self.seq_buckets = None

    # ----------------------------------------------------------- lookup
    def batch_bucket(self, rows: int) -> int:
        """Smallest batch bucket holding ``rows`` rows."""
        for b in self.batch_buckets:
            if rows <= b:
                return b
        raise ValueError("batch of %d rows exceeds max_batch_size %d"
                         % (rows, self.max_batch_size))

    def sample_bucket(self, sample_shape: Tuple[int, ...]) -> Tuple[int, ...]:
        """The padded sample geometry a request of ``sample_shape`` is
        served at (batch dim excluded). Identity without ``seq_axis``."""
        if self.seq_axis is None:
            return tuple(sample_shape)
        ax = self.seq_axis
        if ax >= len(sample_shape):
            raise ValueError("seq_axis %d out of range for sample shape %s"
                             % (ax, (sample_shape,)))
        n = sample_shape[ax]
        for s in self.seq_buckets:
            if n <= s:
                return tuple(s if i == ax else d
                             for i, d in enumerate(sample_shape))
        raise ValueError("dynamic axis %d of length %d exceeds max_seq_len "
                         "%d" % (ax, n, self.max_seq_len))

    def executable_bound(self) -> Optional[int]:
        """Upper bound on distinct padded geometries (None when the
        sample-shape set is client-controlled, i.e. no seq_axis)."""
        if self.seq_buckets is None:
            return None
        return len(self.batch_buckets) * len(self.seq_buckets)

"""``mx.serve.InferenceServer`` — dynamic-batching inference serving.

The reference deployment story stops at the synchronous, single-request
predict API (``c_predict_api.h:77-178``: SetInput -> Forward ->
GetOutput); production traffic is concurrent and batch-1 dispatch wastes
the accelerator. This module is the serving layer the ROADMAP's
"millions of users" north star needs, built the way production servers
do it (NVIDIA Triton's dynamic batcher, TF Serving's BatchingSession,
Clipper's adaptive batching):

* concurrent callers ``submit()`` single requests and get futures;
* a bounded queue coalesces them into micro-batches under a
  ``max_batch_size`` / ``max_delay_us`` window;
* every batch is padded onto the finite pow2 bucket grid
  (:mod:`.bucketing`) so the jitted executable set is finite and
  steady-state serving does **zero recompiles**;
* results are split back per request, futures resolve after the device
  sync, so recorded latency is real end-to-end time.

Robustness: per-request deadlines (``DeadlineExceeded``), admission
control with load-shedding (``QueueFull``), graceful drain on ``close``,
and the ``MXNET_TPU_SERVE`` kill switch + per-request eager fallback
mirroring the fused-trainer pattern (``_fused.py``): a structure whose
batched build fails is negative-cached with bounded retry and its
traffic degrades to eager per-request forwards instead of erroring.

Observability: per-bucket compile/hit counters ride the shared
:class:`CompileCache` discipline under the ``serve_*`` profiler prefix;
queue depth and batch occupancy are profiler gauges; ``stats()``
snapshots p50/p95/p99 latency, throughput accounting and the per-bucket
table.
"""
from __future__ import annotations

import collections
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import lockcheck as _lockcheck
from .. import ndarray as nd_mod
from .. import profiler as _profiler
from ..obs import compiles as _obs_compiles
from ..obs.http import maybe_start_from_knob as _maybe_metrics
from .._fused import CompileCache, structural_failure
from ..base import MXNetError
from ..context import Context, current_context
from .bucketing import BucketSpec
from .stats import LatencyStats, monotonic

__all__ = ["InferenceServer", "GenerativeServer", "GenerateHandle",
           "ServeError", "ServerClosed", "QueueFull", "DeadlineExceeded",
           "wrap_model"]

# per-bucket stats table bound; the tail aggregates under "(other)"
_MAX_BUCKET_STATS = 1024


class ServeError(MXNetError):
    """Base class for serving errors."""


class ServerClosed(ServeError):
    """submit() after close()."""


class QueueFull(ServeError):
    """Load shed: the admission bound was exceeded (clients should back
    off / retry against another replica — erroring fast beats queueing
    into a latency collapse)."""


class DeadlineExceeded(ServeError):
    """The request's deadline passed before its batch launched."""


def wrap_model(model) -> Callable:
    """Normalize the served model to ``fn(NDArray batch) -> outputs``.

    Accepts a :class:`~mxnet_tpu.predictor.Predictor` (single declared
    input), a bound :class:`~mxnet_tpu.module.BaseModule`, a gluon
    ``Block``, or any callable taking an NDArray batch. Batch geometry
    varies per call (the bucket grid), so the Predictor/Module paths
    feed the underlying executor directly — jit re-specializes once per
    bucket, exactly the finite set the server maintains.

    Ownership: serving a Predictor/Module hands its executor to the
    server (all server-side calls are serialized by the model lock, and
    the Predictor's bound input geometry is restored after each batch).
    Do NOT call ``forward``/``set_input`` on it from other threads
    WHILE it is being served — direct use is safe again after
    ``close()``.
    """
    from ..predictor import Predictor
    from ..module.base_module import BaseModule

    if isinstance(model, Predictor):
        names = sorted(model._input_shapes)
        if len(names) != 1:
            raise ValueError(
                "serve: Predictor has inputs %s; the dynamic batcher "
                "coalesces a single request tensor — wrap multi-input "
                "models in a callable" % (names,))
        name = names[0]

        def predictor_fn(x):
            # restore the bound input buffer afterwards: the bucket
            # batch would otherwise permanently replace the declared
            # (1, ...) geometry, and a later DIRECT predictor.forward()
            # would silently broadcast its input across the bucket rows
            buf = model._exec.arg_dict[name]
            saved = buf._data
            try:
                return list(model._exec.forward(is_train=False,
                                                **{name: x}))
            finally:
                buf._data = saved
                buf._version += 1

        return predictor_fn
    if isinstance(model, BaseModule):
        from .. import io as io_mod

        def module_fn(x):
            model.forward(io_mod.DataBatch(data=[x]), is_train=False)
            return list(model.get_outputs())

        return module_fn
    if callable(model):
        return model
    raise TypeError("serve: cannot wrap %r — expected Predictor, Module, "
                    "gluon Block, or callable" % (type(model).__name__,))


def _resolve(fut: Future, value=None, exc: Optional[BaseException] = None):
    """Complete a future, tolerating caller-side cancel(): a cancelled
    future must never kill the batcher thread."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(value)
    except Exception:                                       # noqa: BLE001
        pass


def _serve_loop(server_ref):
    """Batcher thread body. While IDLE it sleeps holding only the
    server's condition variable, never the server itself — so an
    abandoned (un-closed) server is garbage-collectable and the thread
    exits on its next wake instead of pinning the model and polling
    forever. While a batch is pending it holds the server normally."""
    while True:
        srv = server_ref()
        if srv is None:
            return
        cond = srv._cond
        queue = srv._queue          # stable identity (mutated in place)
        with cond:
            has_work = bool(queue)
            closed = srv._closed
        if not has_work:
            if closed:
                return
            srv = None              # the idle sleep must not pin the server
            with cond:
                if not queue:       # re-check under the lock: a submit
                    cond.wait(0.05)  # in the gap must not lose its wakeup
            continue
        try:
            batch = srv._take_batch()
            if batch is None:
                return
            if batch:
                srv._run_batch(batch)
        except Exception:                                  # noqa: BLE001
            # the batcher must never die: _run_batch routes errors into
            # the affected futures; anything that escapes is a bug, but
            # killing the worker would turn it into a silent hang for
            # every later request
            pass
        del srv


class _Request:
    __slots__ = ("data", "rows", "batched", "sample_shape", "bucket_key",
                 "future", "t_submit", "deadline", "flow")

    def __init__(self, data, rows, batched, sample_shape, bucket_key,
                 deadline, flow=None):
        self.data = data
        self.rows = rows
        self.batched = batched
        self.sample_shape = sample_shape
        self.bucket_key = bucket_key
        self.future: Future = Future()
        self.t_submit = monotonic()
        self.deadline = deadline
        self.flow = flow    # trace flow id linking submit -> launch


class InferenceServer:
    """Thread-safe dynamic-batching server over one model.

    Parameters
    ----------
    model : Predictor | Module | Block | callable
        Forward function taking an NDArray batch (leading row axis) and
        returning an NDArray or list of NDArrays with the same leading
        row count. Inference must be row-independent (eval-mode nets
        are) — padded rows must not bleed into real ones.
    max_batch_size, max_delay_us, queue_bound : int, optional
        Coalescing row bound, batching window, and admission bound.
        Defaults come from the ``MXNET_TPU_SERVE_*`` env knobs.
    buckets : BucketSpec, optional
        Full bucket control (explicit ladders, dynamic seq axis). When
        given, ``max_batch_size`` must be left None — the spec owns it.
    ctx : Context, optional
        Device requests are staged to (default: current context).
    name : str
        Prefix for profiler counters/gauges (default ``"serve"``; give
        each server a distinct name to split dashboards).
    metrics_port : int, optional
        Opt-in Prometheus ``/metrics`` endpoint (mx.obs exposition):
        ``None`` defers to the ``MXNET_TPU_OBS_METRICS_PORT`` knob,
        ``-1`` = off, ``0`` = ephemeral port (read ``.metrics_port``
        back), ``>0`` = fixed port. Closed with the server.
    """

    def __init__(self, model, max_batch_size: Optional[int] = None,
                 max_delay_us: Optional[int] = None,
                 queue_bound: Optional[int] = None,
                 buckets: Optional[BucketSpec] = None,
                 ctx: Optional[Context] = None,
                 name: str = "serve",
                 metrics_port: Optional[int] = None):
        from .. import config as _config
        if buckets is not None and max_batch_size is not None:
            raise ValueError("pass max_batch_size or buckets, not both")
        if buckets is None:
            buckets = BucketSpec(max_batch_size if max_batch_size is not None
                                 else _config.get("MXNET_TPU_SERVE_MAX_BATCH"))
        self.buckets = buckets
        self.max_delay_s = (max_delay_us if max_delay_us is not None else
                            _config.get("MXNET_TPU_SERVE_MAX_DELAY_US")) * 1e-6
        self.queue_bound = (queue_bound if queue_bound is not None else
                            _config.get("MXNET_TPU_SERVE_QUEUE_BOUND"))
        self.name = name
        self._model = wrap_model(model)
        self._ctx = ctx or current_context()
        self._single_output: Optional[bool] = None
        # sig -> padded-dispatch runner; counters ride the shared
        # CompileCache scheme (<name>_compile / _cache_hit / ...), so
        # "zero recompiles after warmup" is a counter assertion. The
        # table must hold the WHOLE bucket grid: eviction of a live
        # geometry would re-count its next dispatch as a compile and
        # falsify that observable (4x headroom covers multiple dtypes;
        # unbounded client shape sets — no seq bucketing — get a large
        # table, mirroring the underlying jit cache they also grow).
        grid = self.buckets.executable_bound()
        self.cache = CompileCache(
            name, max_entries=max(4 * grid, 128) if grid else 4096)
        # latency rides the shared obs histogram registry (same-name
        # servers aggregate, mirroring the <name>_* counter discipline)
        # so the Prometheus exposition includes it without extra wiring
        self.latency = LatencyStats(name=name + "_latency_seconds")
        # opt-in Prometheus /metrics endpoint (arg wins over the
        # MXNET_TPU_OBS_METRICS_PORT knob; resolved < 0 = off). This
        # server is deliberately collectable without close() (the worker
        # holds only a weakref) — the finalizer keeps that true for the
        # endpoint too, releasing the bound port when the server is GC'd
        try:
            self._metrics = _maybe_metrics(metrics_port)
        except OSError as exc:
            # an observability knob must never take down the serving
            # path: a port conflict (second server on a fixed port,
            # another process) degrades to no endpoint, loudly
            import logging
            logging.getLogger(__name__).warning(
                "serve[%s]: /metrics endpoint disabled (%s)", name, exc)
            _profiler.incr_counter(name + "_metrics_bind_failed")
            self._metrics = None
        self.metrics_port = self._metrics.port if self._metrics else None
        self._metrics_finalizer = weakref.finalize(
            self, self._metrics.close) if self._metrics else None
        # serializes ALL model invocations: Predictor/Module adapters
        # mutate shared executor state (arg_dict -> forward -> outputs),
        # so a kill-switch eager call in a caller thread must never
        # interleave with the worker's batched call or another caller.
        # Uncontended on the hot batched path (worker-only). allow_sync:
        # _call_model fetches outputs under it by design (the adapter's
        # shared executor state is what the lock serializes — see the
        # mx-lint allow(lock-host-sync) at the call site).
        self._model_lock = _lockcheck.Lock(name="serve.model_lock",
                                           allow_sync=True)
        self._lock = _lockcheck.Lock(name="serve.queue_lock")
        self._cond = _lockcheck.Condition(self._lock)
        self._queue: collections.deque = collections.deque()
        self._closed = False
        self._batches = 0
        self._served = 0
        self._padded_rows = 0
        self._per_bucket: Dict[Tuple, Dict[str, int]] = {}
        # the loop holds only a WEAK reference between iterations: a
        # server dropped without close() must be collectable (a strong
        # ref from a live thread would pin the model + params and poll
        # forever) — the thread exits on the first wake after GC
        self._worker = threading.Thread(
            target=_serve_loop, args=(weakref.ref(self),), daemon=True,
            name="mxnet_tpu.serve[%s]" % name)
        self._worker.start()

    # ------------------------------------------------------------ submit
    def submit(self, data, batched: bool = False,
               timeout: Optional[float] = None) -> Future:
        """Enqueue one request; returns a ``concurrent.futures.Future``.

        ``data`` is one sample (no batch dim) by default; with
        ``batched=True`` its leading axis is rows and the result keeps
        it. The future resolves with host numpy arrays (zero-copy row
        views of the batch fetch — serving results cross a process
        boundary anyway, and per-request device slicing costs more
        than the batched forward). ``timeout`` (seconds) is the request
        deadline: if its batch has not launched by then the future
        fails with :class:`DeadlineExceeded`.

        Raises :class:`QueueFull` (load shed) when the queue is at the
        admission bound, :class:`ServerClosed` after ``close()``.
        """
        from .. import config as _config
        from .. import faults as _faults
        if _faults.ARMED:
            # robustness drill: an injected submit failure must surface
            # on THIS request only — the server keeps serving
            _faults.fire("serve.submit", default_kind="raise")
        x = np.asarray(data.asnumpy() if isinstance(data, nd_mod.NDArray)
                       else data)
        if batched:
            if x.ndim < 1:
                raise ValueError("batched request needs a leading row axis")
            rows, sample_shape = int(x.shape[0]), tuple(x.shape[1:])
            if rows > self.buckets.max_batch_size:
                raise ValueError(
                    "request of %d rows exceeds max_batch_size %d — split "
                    "it client-side" % (rows, self.buckets.max_batch_size))
        else:
            rows, sample_shape = 1, tuple(x.shape)
        # admission-time shape validation: sample_bucket raises on
        # over-long dynamic axes, so bad requests fail fast in the
        # caller, not in the batcher thread
        padded_sample = self.buckets.sample_bucket(sample_shape)
        bucket_key = (padded_sample, str(x.dtype))
        deadline = None if timeout is None else monotonic() + timeout

        if self._closed:
            raise ServerClosed("submit() after close()")
        if not _config.get("MXNET_TPU_SERVE"):
            # kill switch: per-request eager forward in the caller
            # thread — no queue, no batching, no bucketing
            return self._eager_future(x, rows, batched)

        fid = _profiler.new_flow() if _profiler.spans_enabled() else None
        req = _Request(x, rows, batched, sample_shape, bucket_key, deadline,
                       flow=fid)
        with _profiler.span("serve_submit", "serve", flow=fid):
            with self._cond:
                if self._closed:
                    raise ServerClosed("submit() after close()")
                if len(self._queue) >= self.queue_bound:
                    _profiler.incr_counter(self.name + "_shed")
                    raise QueueFull(
                        "queue depth %d at admission bound %d"
                        % (len(self._queue), self.queue_bound))
                self._queue.append(req)
                _profiler.set_gauge(self.name + "_queue_depth",
                                    len(self._queue))
                self._cond.notify_all()
        return req.future

    def __call__(self, data, batched: bool = False,
                 timeout: Optional[float] = None):
        """Blocking convenience: ``submit(...).result()``."""
        return self.submit(data, batched=batched, timeout=timeout).result()

    # ------------------------------------------------------------- close
    def close(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop accepting requests. ``drain=True`` (default) serves
        everything already queued before the worker exits; ``False``
        fails queued requests with :class:`ServerClosed`. Idempotent:
        a second close only joins — it must not drop requests a prior
        ``close(drain=True)`` promised to serve."""
        with self._cond:
            already = self._closed
            self._closed = True
            if already:
                self._cond.notify_all()
                drain = True        # first close's promise stands
            if not drain:
                dropped = list(self._queue)
                self._queue.clear()
            else:
                dropped = []
            self._cond.notify_all()
        for req in dropped:
            _resolve(req.future, exc=ServerClosed("server closed"))
        self._worker.join(timeout)
        if self._metrics_finalizer is not None:
            self._metrics_finalizer()    # idempotent: detaches after one call
            self._metrics = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=not any(exc))
        return False

    # ------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        """Point-in-time serving snapshot (thread-safe)."""
        with self._lock:
            depth = len(self._queue)
            batches, served = self._batches, self._served
            padded = self._padded_rows
            per_bucket = {
                (key if isinstance(key, str)
                 else "%s/%s" % ("x".join(map(str, key[0])), key[1])):
                dict(rec)
                for key, rec in self._per_bucket.items()}
        dispatched = sum(r["rows"] for r in per_bucket.values())
        return {
            "requests": served,
            "batches": batches,
            "queue_depth": depth,
            "avg_batch_rows": round(dispatched / batches, 3) if batches
            else None,
            "occupancy": round(dispatched / (dispatched + padded), 4)
            if dispatched else None,
            "buckets": per_bucket,
            "compiles": _profiler.get_counter(self.name + "_compile"),
            "cache_hits": _profiler.get_counter(self.name + "_cache_hit"),
            "shed": _profiler.get_counter(self.name + "_shed"),
            "deadline_expired": _profiler.get_counter(
                self.name + "_deadline_expired"),
            "eager_fallback": _profiler.get_counter(self.name + "_eager"),
            "latency": self.latency.snapshot(),
        }

    # ----------------------------------------------------------- batcher
    def _take_batch(self) -> Optional[List[_Request]]:
        """Wait (bounded) for a batch: [] when nothing is ready yet
        (caller re-checks liveness and retries), None when the worker
        should exit (closed and drained)."""
        with self._cond:
            if not self._queue:
                if self._closed:
                    return None
                # bounded wait so _serve_loop can drop its strong ref
                # and re-check server liveness between idle ticks
                self._cond.wait(0.05)
                if not self._queue:
                    return None if self._closed else []
            head = self._queue[0]
            _t_co = time.perf_counter() if _profiler.spans_enabled() \
                else None
            window_end = head.t_submit + self.max_delay_s
            while not self._closed:
                now = monotonic()
                if now >= window_end:
                    break
                if self._compatible_rows(head.bucket_key) >= \
                        self.buckets.max_batch_size:
                    break
                # a queued deadline must fire ~when promised, not up to
                # a full batching window late: wake at the earliest of
                # window end / next deadline / the 10 ms arrival tick
                dls = [r.deadline for r in self._queue
                       if r.deadline is not None]
                next_dl = min(dls) if dls else None
                if next_dl is not None and now >= next_dl:
                    break
                tick = window_end - now
                if next_dl is not None:
                    tick = min(tick, next_dl - now)
                self._cond.wait(min(tick, 0.01))
            # pop the head's bucket-mates FIFO, honoring the row bound;
            # other buckets keep their queue positions
            batch, rows, kept = [], 0, []
            now = monotonic()
            expired = []
            for req in self._queue:
                if req.future.cancelled():
                    continue
                if req.deadline is not None and now > req.deadline:
                    expired.append(req)
                    continue
                if req.bucket_key == head.bucket_key and \
                        rows + req.rows <= self.buckets.max_batch_size and \
                        req.future.set_running_or_notify_cancel():
                    batch.append(req)
                    rows += req.rows
                else:
                    kept.append(req)
            # in-place: _serve_loop's idle path holds this deque by
            # identity, so the queue object must never be rebound
            self._queue.clear()
            self._queue.extend(kept)
            _profiler.set_gauge(self.name + "_queue_depth",
                                len(self._queue))
        for req in expired:
            _profiler.incr_counter(self.name + "_deadline_expired")
            _resolve(req.future, exc=DeadlineExceeded(
                "deadline passed %.1f ms before batch launch"
                % ((now - req.deadline) * 1e3)))
        if batch and _t_co is not None:
            # batching-window slice on the batcher lane, linked to the
            # head request's flow (idle ticks emit nothing)
            _profiler.record_span("serve_coalesce", _t_co,
                                  time.perf_counter(), "serve",
                                  flow=batch[0].flow)
        return batch

    def _compatible_rows(self, bucket_key) -> int:
        return sum(r.rows for r in self._queue
                   if r.bucket_key == bucket_key)

    # ---------------------------------------------------------- dispatch
    def _call_model(self, x: nd_mod.NDArray) -> List[np.ndarray]:
        """Run the model and fetch each output to host ONCE. Results are
        numpy: per-request splitting must be zero-copy views — slicing
        NDArrays would dispatch one eager device op per request, which
        measured ~10x the whole batched forward at MLP sizes. The fetch
        doubles as the device sync, so recorded latency is real."""
        # the lock-held host sync is the design here: all model
        # invocations serialize on _model_lock (shared executor state),
        # and fetching inside it is what makes recorded latency real
        with self._model_lock:  # mx-lint: allow(lock-host-sync)
            outs = self._model(x)
            outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
            if self._single_output is None:
                # one output -> callers get the bare array, not a 1-list
                # (Predictor/Module adapters always hand back lists)
                self._single_output = len(outs) == 1
            return [np.asarray(o.asnumpy()) for o in outs]

    def _assemble(self, batch: List[_Request], bucket_rows: int):
        padded_sample = batch[0].bucket_key[0]
        buf = np.full((bucket_rows,) + padded_sample,
                      self.buckets.pad_value, batch[0].data.dtype)
        r0 = 0
        for req in batch:
            block = req.data if req.batched else req.data[None]
            sl = (slice(r0, r0 + req.rows),) + tuple(
                slice(0, d) for d in req.sample_shape)
            buf[sl] = block
            r0 += req.rows
        return buf, r0

    def _run_batch(self, batch: List[_Request]):
        rows = sum(r.rows for r in batch)
        try:
            bucket_rows = self.buckets.batch_bucket(rows)
            sig = (batch[0].bucket_key, bucket_rows)
            if self.cache.should_skip(sig):
                # negative-cached geometry: its traffic runs eager
                self._fallback_eager(batch)
                return
            buf, _ = self._assemble(batch, bucket_rows)
            # NOTE: the cached "runner" is always _call_model — the real
            # per-geometry executable lives in jax's jit cache, keyed by
            # the same padded shape this sig encodes. CompileCache here
            # supplies the rest of its contract: first-dispatch/hit
            # counters (the zero-recompile observable), bounded-retry
            # negative caching, and the eager-fallback gate.
            runner = self.cache.get(sig)
            fresh = runner is None
            if fresh:
                runner = self._call_model
            try:
                with _profiler.span("serve_launch", "serve",
                                    flow=batch[0].flow) as _sp:
                    for req in batch[1:]:
                        _sp.mark_flow(req.flow)
                    with _obs_compiles.scope(self.name, sig):
                        outs = runner(nd_mod.array(buf, ctx=self._ctx))
            except Exception as exc:                       # noqa: BLE001
                self.cache.mark_failed(sig,
                                       permanent=structural_failure(exc))
                self._fallback_eager(batch)
                return
            if fresh:
                self.cache.put(sig, runner)
            else:
                self.cache.note_success(sig)
        except Exception as exc:                           # noqa: BLE001
            for req in batch:
                _resolve(req.future, exc=exc)
            return
        with self._lock:
            self._batches += 1
            self._served += len(batch)
            self._padded_rows += bucket_rows - rows
            # bounded like every sibling structure (CompileCache table,
            # LatencyStats ring): client-controlled shape sets must not
            # grow the stats table monotonically — the tail aggregates
            key = sig[0]
            if key not in self._per_bucket and \
                    len(self._per_bucket) >= _MAX_BUCKET_STATS:
                key = "(other)"
            rec = self._per_bucket.setdefault(
                key, {"batches": 0, "requests": 0, "rows": 0})
            rec["batches"] += 1
            rec["requests"] += len(batch)
            rec["rows"] += rows
        _profiler.incr_counter(self.name + "_batches")
        _profiler.incr_counter(self.name + "_requests", len(batch))
        _profiler.set_gauge(self.name + "_batch_occupancy",
                            rows / bucket_rows)
        done = monotonic()
        r0 = 0
        try:
            with _profiler.span("serve_resolve", "serve",
                                flow=batch[0].flow):
                for req in batch:
                    if self._single_output:
                        res = outs[0][r0:r0 + req.rows] if req.batched \
                            else outs[0][r0]
                    else:
                        res = [o[r0:r0 + req.rows] if req.batched else o[r0]
                               for o in outs]
                    r0 += req.rows
                    self.latency.record(done - req.t_submit)
                    _resolve(req.future, res)
        except Exception as exc:                           # noqa: BLE001
            # row-contract violation (output leading axis != input rows):
            # every future must still resolve — a dead batcher thread
            # would hang all pending AND future requests silently. The
            # geometry is structurally broken, so pin it to the eager
            # path, where the same error surfaces per request.
            self.cache.mark_failed(sig, permanent=True)
            for req in batch:
                _resolve(req.future, exc=exc)

    # ------------------------------------------------------ eager paths
    def _eager_one(self, x: np.ndarray, batched: bool):
        nd_in = nd_mod.array(x if batched else x[None], ctx=self._ctx)
        outs = self._call_model(nd_in)
        _profiler.incr_counter(self.name + "_eager")
        if self._single_output:
            return outs[0] if batched else outs[0][0]
        return outs if batched else [o[0] for o in outs]

    def _eager_future(self, x, rows, batched) -> Future:
        fut: Future = Future()
        t0 = monotonic()
        try:
            res = self._eager_one(x, batched)
        except Exception as exc:                           # noqa: BLE001
            fut.set_exception(exc)
            return fut
        self.latency.record(monotonic() - t0)
        with self._lock:
            self._served += 1
        fut.set_result(res)
        return fut

    def _fallback_eager(self, batch: List[_Request]):
        """Per-request eager forwards for a batch whose bucketed
        dispatch is unavailable (build failed / negative-cached) — the
        serving twin of the fused trainer's per-param fallback."""
        done_extra = 0
        for req in batch:
            # same deadline contract as the healthy path: a request
            # whose deadline lapsed while earlier fallback forwards ran
            # fails DeadlineExceeded instead of resolving arbitrarily
            # late (callers key retry/hedging logic on that error)
            if req.deadline is not None and monotonic() > req.deadline:
                _profiler.incr_counter(self.name + "_deadline_expired")
                _resolve(req.future, exc=DeadlineExceeded(
                    "deadline passed before eager-fallback dispatch"))
                continue
            try:
                res = self._eager_one(req.data, req.batched)
            except Exception as exc:                       # noqa: BLE001
                _resolve(req.future, exc=exc)
                continue
            self.latency.record(monotonic() - req.t_submit)
            _resolve(req.future, res)
            done_extra += 1
        with self._lock:
            self._served += done_extra


# ===================================================================
# Generative serving: continuous batching over the bucketed KV cache
# ===================================================================


class GenerateHandle:
    """Per-request streaming future: tokens arrive as they are decoded.

    The continuous-batching analogue of ``submit()``'s Future — one
    handle per ``submit_generate()`` call. Iterate it for streaming
    (``for tok in handle: ...`` blocks until each next token), or call
    :meth:`result` for the whole sequence. ``on_token`` (if given) is
    invoked from the scheduler thread per token — it must be fast and
    must not call back into the server.
    """

    def __init__(self, on_token: Optional[Callable[[int], None]] = None):
        self._cond = _lockcheck.Condition(name="serve.stream_cond")
        self._tokens: List[int] = []
        self._done = False
        self._exc: Optional[BaseException] = None
        self._on_token = on_token
        self._cancelled = False

    # ------------------------------------------------- scheduler side
    def _put(self, token: int) -> None:
        with self._cond:
            self._tokens.append(int(token))
            self._cond.notify_all()
        if self._on_token is not None:
            try:
                self._on_token(int(token))
            except Exception:                               # noqa: BLE001
                # a client callback must never kill the scheduler
                pass

    def _finish(self, exc: Optional[BaseException] = None) -> None:
        with self._cond:
            if self._done:
                return
            self._done = True
            self._exc = exc
            self._cond.notify_all()

    # ---------------------------------------------------- caller side
    def cancel(self) -> None:
        """Request eviction at the next step boundary (the sequence's
        pages free there; already-streamed tokens remain valid)."""
        with self._cond:
            self._cancelled = True

    def done(self) -> bool:
        with self._cond:
            return self._done

    @property
    def exception(self) -> Optional[BaseException]:
        with self._cond:
            return self._exc

    def tokens_so_far(self) -> List[int]:
        with self._cond:
            return list(self._tokens)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the sequence finishes; the full token list, or
        raises the sequence's error (an injected decode fault, a
        deadline, ServerClosed)."""
        deadline = None if timeout is None else monotonic() + timeout
        with self._cond:
            while not self._done:
                left = None if deadline is None else deadline - monotonic()
                if left is not None and left <= 0:
                    raise TimeoutError("generation still running after "
                                       "%.1fs" % timeout)
                self._cond.wait(0.1 if left is None else min(left, 0.1))
            if self._exc is not None:
                raise self._exc
            return list(self._tokens)

    def __iter__(self):
        """Stream tokens in decode order; raises the sequence's error
        (if any) after the last streamed token."""
        i = 0
        while True:
            with self._cond:
                while i >= len(self._tokens) and not self._done:
                    self._cond.wait(0.1)
                if i < len(self._tokens):
                    tok = self._tokens[i]
                else:
                    if self._exc is not None:
                        raise self._exc
                    return
            i += 1
            yield tok


class _GenRequest:
    __slots__ = ("prompt", "max_new_tokens", "eos_id", "temperature",
                 "seed", "deadline", "handle", "t_submit")

    def __init__(self, prompt, max_new_tokens, eos_id, temperature, seed,
                 deadline, handle):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.temperature = temperature
        self.seed = seed
        self.deadline = deadline
        self.handle = handle
        self.t_submit = monotonic()


class _ActiveSeq:
    __slots__ = ("slot", "handle", "pos", "generated", "max_new_tokens",
                 "eos_id", "temperature", "rng", "token", "t_last")

    def __init__(self, slot, handle, pos, max_new_tokens, eos_id,
                 temperature, seed, token):
        self.slot = slot
        self.handle = handle
        self.pos = pos                  # next cache write position
        self.generated = 1              # prefill samples the first token
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.temperature = temperature
        self.rng = np.random.default_rng(seed) if seed is not None else None
        self.token = token              # freshest sampled token
        self.t_last = monotonic()


def _gen_loop(server_ref):
    """Scheduler thread body — same weakref discipline as _serve_loop:
    idle waits hold only the condition variable, so an abandoned server
    is collectable and the thread exits on its next wake."""
    while True:
        srv = server_ref()
        if srv is None:
            return
        cond = srv._cond
        with cond:
            busy = bool(srv._active) or bool(srv._waiting)
            closed = srv._closed
        if not busy:
            if closed:
                return
            waiting, active = srv._waiting, srv._active
            srv = None              # the idle sleep must not pin the server
            with cond:
                if not waiting and not active:  # re-check under the lock:
                    cond.wait(0.05)             # a submit in the gap must
            continue                            # not lose its wakeup
        try:
            srv._iteration()
        except Exception:                                   # noqa: BLE001
            # _iteration routes errors into the affected handles; an
            # escape is a bug but must not silently hang every later
            # request by killing the scheduler
            pass
        del srv


class GenerativeServer:
    """Continuous-batching autoregressive decode server.

    New requests join the RUNNING decode batch at step granularity
    (Orca's iteration-level scheduling): between two decode steps the
    scheduler admits waiting prompts into free KV-cache slots — prefill
    work per gap is bounded by the ``MXNET_TPU_SERVE_PREFILL_TOKENS``
    budget so joins cannot starve resident sequences' inter-token
    latency — and finished sequences evict immediately, freeing their
    pages for the next join. Every geometry that reaches the compiler
    is a bucket (|prompt buckets| + |decode buckets| programs total),
    so steady-state decode does ZERO recompiles, counter-asserted.

    Parameters
    ----------
    model : Module | (arg, aux) | dict
        The zoo-transformer parameter source
        (:func:`~mxnet_tpu.serve.decode.extract_params` naming).
    n_heads : int
        Attention head count (not shape-derivable).
    max_sequences : int, optional
        Resident decode sequences = preallocated KV slots (default
        ``MXNET_TPU_SERVE_MAX_SEQUENCES``).
    int8, page : optional
        KV-cache quantized mode / page size (default the
        ``MXNET_TPU_SERVE_KV_INT8`` / ``MXNET_TPU_SERVE_KV_PAGE``
        knobs).
    prefill_tokens : int, optional
        Per-iteration prefill token budget (bucket-padded; default the
        ``MXNET_TPU_SERVE_PREFILL_TOKENS`` knob).
    seq_buckets : sequence of int, optional
        Decode bucket ladder (default ``MXNET_TPU_SERVE_DECODE_BUCKETS``
        or pow2 up to the model's max sequence).
    mesh, layout : optional
        Shard the cache's head axis over the layout's ``tp`` axis
        (``island_specs("serve")``); AOT warm starts are skipped for
        sharded caches (the multi-device fence).

    The decode path (kv_cache/decode modules) imports lazily here: a
    process that only uses InferenceServer never pays for it — the CI
    zero-cost gate asserts ``mxnet_tpu.serve.decode`` stays unimported.
    """

    def __init__(self, model, n_heads: int,
                 max_sequences: Optional[int] = None,
                 int8: Optional[bool] = None, page: Optional[int] = None,
                 prefill_tokens: Optional[int] = None,
                 queue_bound: Optional[int] = None,
                 seq_buckets: Optional[List[int]] = None,
                 prefill_chunk: int = 512,
                 name: str = "serve_gen",
                 metrics_port: Optional[int] = None,
                 mesh=None, layout=None):
        from .. import config as _config
        from .kv_cache import KVCache                       # lazy: the
        from .decode import (DecodeEngine, extract_params,  # zero-cost
                             config_from_params, sample_token)  # gate
        self.name = name
        self._sample_token = sample_token
        params = extract_params(model)
        cfg = config_from_params(params, n_heads)
        self.max_sequences = int(
            max_sequences if max_sequences is not None
            else _config.get("MXNET_TPU_SERVE_MAX_SEQUENCES"))
        self.prefill_tokens = int(
            prefill_tokens if prefill_tokens is not None
            else _config.get("MXNET_TPU_SERVE_PREFILL_TOKENS"))
        self.queue_bound = (queue_bound if queue_bound is not None else
                            _config.get("MXNET_TPU_SERVE_QUEUE_BOUND"))
        spec = _config.get("MXNET_TPU_SERVE_DECODE_BUCKETS")
        if seq_buckets is None and spec:
            from .bucketing import decode_buckets
            pg = int(page if page is not None
                     else _config.get("MXNET_TPU_SERVE_KV_PAGE"))
            seq_buckets = decode_buckets(cfg.max_seq, pg, spec)
        self.cache = KVCache(cfg.num_layers, cfg.n_heads, cfg.d_head,
                             self.max_sequences, cfg.max_seq, page=page,
                             int8=int8, name=name, mesh=mesh,
                             layout=layout)
        # hbm-budget audit of the reservation at server START — strict
        # analyze mode rejects an over-budget cache naming it, before
        # the first request ever lands
        self.hbm_audit = self.cache.audit()
        grid_bound = 4 * (len(seq_buckets) * 2 if seq_buckets else 64)
        self.compile_cache = CompileCache(name,
                                          max_entries=max(grid_bound, 128))
        self.engine = DecodeEngine(
            params, n_heads, self.cache, self.compile_cache, name=name,
            seq_buckets=seq_buckets, prefill_chunk=prefill_chunk)
        self.stats_latency = None       # kept None: ttft/tpot supersede
        from .stats import DecodeLatencyStats
        self.latency = DecodeLatencyStats(name=name)
        try:
            self._metrics = _maybe_metrics(metrics_port)
        except OSError as exc:
            import logging
            logging.getLogger(__name__).warning(
                "serve[%s]: /metrics endpoint disabled (%s)", name, exc)
            _profiler.incr_counter(name + "_metrics_bind_failed")
            self._metrics = None
        self.metrics_port = self._metrics.port if self._metrics else None
        self._metrics_finalizer = weakref.finalize(
            self, self._metrics.close) if self._metrics else None
        self._lock = _lockcheck.Lock(name="serve.gen_lock")
        self._cond = _lockcheck.Condition(self._lock)
        self._waiting: collections.deque = collections.deque()
        self._active: List[_ActiveSeq] = []
        self._closed = False
        self._drain = True
        self._worker = threading.Thread(
            target=_gen_loop, args=(weakref.ref(self),), daemon=True,
            name="mxnet_tpu.serve.gen[%s]" % name)
        self._worker.start()

    # ------------------------------------------------------------ submit
    def submit_generate(self, prompt, max_new_tokens: int = 32,
                        eos_id: Optional[int] = None,
                        timeout: Optional[float] = None,
                        temperature: float = 0.0,
                        seed: Optional[int] = None,
                        on_token: Optional[Callable[[int], None]] = None
                        ) -> GenerateHandle:
        """Enqueue one prompt for generation; returns a streaming
        :class:`GenerateHandle`.

        ``timeout`` is the TIME-TO-FIRST-TOKEN deadline (queue + prefill;
        once a sequence is resident it decodes to completion — evicting
        a half-decoded sequence wastes its whole KV footprint).
        Raises :class:`QueueFull` at the admission bound,
        :class:`ServerClosed` after ``close()``.
        """
        from .. import faults as _faults
        if _faults.ARMED:
            _faults.fire("serve.submit", default_kind="raise")
        prompt = np.asarray(
            prompt.asnumpy() if isinstance(prompt, nd_mod.NDArray)
            else prompt).astype(np.int64).ravel()
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if prompt.size >= self.cache.max_seq:
            raise ValueError(
                "prompt of %d tokens leaves no room to generate under "
                "max_seq %d" % (prompt.size, self.cache.max_seq))
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        deadline = None if timeout is None else monotonic() + timeout
        handle = GenerateHandle(on_token=on_token)
        req = _GenRequest(prompt, int(max_new_tokens), eos_id,
                          float(temperature), seed, deadline, handle)
        with self._cond:
            if self._closed:
                raise ServerClosed("submit_generate() after close()")
            if len(self._waiting) >= self.queue_bound:
                _profiler.incr_counter(self.name + "_shed")
                raise QueueFull("queue depth %d at admission bound %d"
                                % (len(self._waiting), self.queue_bound))
            self._waiting.append(req)
            _profiler.incr_counter(self.name + "_requests")
            _profiler.set_gauge(self.name + "_waiting",
                                len(self._waiting))
            self._cond.notify_all()
        return handle

    # --------------------------------------------------------- scheduler
    def _iteration(self):
        """One continuous-batching step: admit joins under the prefill
        budget, one decode step over every resident sequence, evict the
        finished. Runs only on the scheduler thread."""
        from .. import faults as _faults
        self._admit()
        with self._lock:
            active = list(self._active)
        # cancelled handles evict at step granularity
        for seq in active:
            if seq.handle._cancelled:
                self._evict(seq, exc=None)
        with self._lock:
            active = list(self._active)
        if not active:
            return
        # capacity-exhausted sequences finish (truncated) BEFORE the
        # step: position max_seq does not exist in the cache
        for seq in active:
            if seq.pos >= self.cache.max_seq:
                self._evict(seq, exc=None)
        with self._lock:
            active = list(self._active)
        if not active:
            return
        if _faults.ARMED:
            try:
                _faults.fire("serve.decode", default_kind="raise")
            except _faults.FaultInjected as exc:
                # the drill contract: an injected decode fault kills ONE
                # sequence's stream with a legible error — the lowest
                # resident slot, deterministically — NEVER the batch
                victim = min(active, key=lambda s: s.slot)
                self._evict(victim, exc=ServeError(
                    "injected fault at serve.decode killed the sequence "
                    "in slot %d (%s); co-resident sequences kept "
                    "decoding" % (victim.slot, exc)))
                with self._lock:
                    active = list(self._active)
                if not active:
                    return
        tokens = np.zeros((self.cache.max_slots,), np.int32)
        pos = np.zeros((self.cache.max_slots,), np.int32)
        mask = np.zeros((self.cache.max_slots,), bool)
        for seq in active:
            tokens[seq.slot] = seq.token
            pos[seq.slot] = seq.pos
            mask[seq.slot] = True
        try:
            logits = self.engine.decode_step(tokens, pos, mask)
        except Exception as exc:                            # noqa: BLE001
            # a REAL decode failure cannot be attributed to one row —
            # every resident sequence fails legibly and frees its pages
            for seq in active:
                self._evict(seq, exc=ServeError(
                    "decode step failed for resident batch: %r" % (exc,)))
            return
        now = monotonic()
        finished = []
        for seq in active:
            tok = self._sample_token(logits[seq.slot], seq.temperature,
                                     seq.rng)
            self.latency.tpot.record(now - seq.t_last)
            seq.t_last = now
            seq.handle._put(tok)
            self.cache.grow(seq.slot)
            seq.pos += 1
            seq.generated += 1
            seq.token = tok
            _profiler.incr_counter(self.name + "_tokens")
            if seq.generated >= seq.max_new_tokens or \
                    (seq.eos_id is not None and tok == seq.eos_id):
                finished.append(seq)
        for seq in finished:
            self._evict(seq, exc=None)
        _profiler.incr_counter(self.name + "_decode_steps")

    def _admit(self):
        """Join waiting requests into free slots under the prefill token
        budget (bucket-padded accounting — padded FLOPs are the cost the
        budget bounds)."""
        budget = self.prefill_tokens
        while True:
            with self._cond:
                if not self._waiting:
                    return
                if self.cache.ledger.slots_in_use >= self.cache.max_slots:
                    return
                req = self._waiting.popleft()
                _profiler.set_gauge(self.name + "_waiting",
                                    len(self._waiting))
            if req.handle._cancelled:
                req.handle._finish()
                continue
            now = monotonic()
            if req.deadline is not None and now > req.deadline:
                _profiler.incr_counter(self.name + "_deadline_expired")
                req.handle._finish(DeadlineExceeded(
                    "TTFT deadline passed %.1f ms before prefill"
                    % ((now - req.deadline) * 1e3)))
                continue
            bucket = self.engine.prompt_bucket(int(req.prompt.size))
            if bucket > budget and budget < self.prefill_tokens:
                # budget spent this gap: requeue at the FRONT (FIFO
                # order survives) and let the decode batch take a step
                with self._cond:
                    self._waiting.appendleft(req)
                    _profiler.set_gauge(self.name + "_waiting",
                                        len(self._waiting))
                return
            slot = self.cache.acquire(int(req.prompt.size))
            if slot is None:
                with self._cond:
                    self._waiting.appendleft(req)
                    _profiler.set_gauge(self.name + "_waiting",
                                        len(self._waiting))
                return
            budget -= bucket
            try:
                logits = self.engine.prefill(req.prompt, slot)
            except Exception as exc:                        # noqa: BLE001
                self.cache.release(slot)
                req.handle._finish(ServeError(
                    "prefill failed: %r" % (exc,)))
                continue
            rng = np.random.default_rng(req.seed) \
                if req.seed is not None else None
            tok = self._sample_token(logits, req.temperature, rng)
            self.latency.ttft.record(monotonic() - req.t_submit)
            seq = _ActiveSeq(slot, req.handle, int(req.prompt.size),
                             req.max_new_tokens, req.eos_id,
                             req.temperature, req.seed, tok)
            seq.rng = rng
            req.handle._put(tok)
            _profiler.incr_counter(self.name + "_tokens")
            if seq.generated >= seq.max_new_tokens or \
                    (seq.eos_id is not None and tok == seq.eos_id):
                # sequence finished at its first token: pages free now
                self._evict_prefill_only(seq)
                continue
            with self._lock:
                self._active.append(seq)
                _profiler.set_gauge(self.name + "_active_sequences",
                                    len(self._active))
            if budget <= 0:
                return

    # ---------------------------------------------------------- eviction
    def _evict(self, seq: _ActiveSeq, exc: Optional[BaseException]):
        """Remove a sequence from the running batch, ALWAYS freeing its
        pages (the injected-evict drill asserts no leak), then resolve
        its handle."""
        from .. import faults as _faults
        with self._lock:
            if seq in self._active:
                self._active.remove(seq)
            _profiler.set_gauge(self.name + "_active_sequences",
                                len(self._active))
        fault_exc = None
        try:
            if _faults.ARMED:
                _faults.fire("serve.evict", default_kind="raise")
        except _faults.FaultInjected as fe:
            fault_exc = ServeError(
                "injected fault at serve.evict while evicting slot %d "
                "(%s); pages were still freed" % (seq.slot, fe))
        finally:
            self.cache.release(seq.slot)
            _profiler.incr_counter(self.name + "_evicted")
        seq.handle._finish(exc if exc is not None else fault_exc)

    def _evict_prefill_only(self, seq: _ActiveSeq):
        """A sequence that finished at its prefill token never joined
        the active list — free its slot and resolve."""
        from .. import faults as _faults
        fault_exc = None
        try:
            if _faults.ARMED:
                _faults.fire("serve.evict", default_kind="raise")
        except _faults.FaultInjected as fe:
            fault_exc = ServeError(
                "injected fault at serve.evict while evicting slot %d "
                "(%s); pages were still freed" % (seq.slot, fe))
        finally:
            self.cache.release(seq.slot)
            _profiler.incr_counter(self.name + "_evicted")
        seq.handle._finish(fault_exc)

    # ------------------------------------------------------------- close
    def close(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop accepting requests. ``drain=True`` (default) decodes
        every waiting AND resident sequence to completion first;
        ``False`` fails waiting requests with :class:`ServerClosed` and
        cancels resident sequences at the next step (their pages free
        there). Idempotent: a second close only joins — it must not
        drop requests a prior ``close(drain=True)`` promised to serve.

        Submits racing the close lose cleanly: ``submit_generate``
        checks ``_closed`` under the same condition variable that sets
        it here, so a request issued mid-drain raises
        :class:`ServerClosed` immediately instead of enqueueing behind
        a scheduler that is about to exit."""
        with self._cond:
            already = self._closed
            self._closed = True
            if already:
                drain = True        # first close's promise stands
            if not drain:
                dropped = list(self._waiting)
                self._waiting.clear()
                for seq in self._active:
                    seq.handle._cancelled = True
            else:
                dropped = []
            self._cond.notify_all()
        for req in dropped:
            req.handle._finish(ServerClosed("server closed"))
        self._worker.join(timeout)
        if not self._worker.is_alive():
            # belt-and-braces: if anything slipped into the queue after
            # the scheduler exited (or the join raced an admit), fail it
            # legibly — a handle left in a dead server's queue would
            # hang its caller forever
            with self._cond:
                leftover = list(self._waiting)
                self._waiting.clear()
            for req in leftover:
                req.handle._finish(ServerClosed("server closed"))
        if self._metrics_finalizer is not None:
            self._metrics_finalizer()
            self._metrics = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=not any(exc))
        return False

    # ------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        """Decode-serving snapshot. Superset discipline: the counter
        keys shared with InferenceServer.stats() (requests / compiles /
        cache_hits / shed / deadline_expired) keep their meaning, and
        new keys only ADD — the schema regression test pins both."""
        with self._lock:
            active = len(self._active)
            waiting = len(self._waiting)
        led = self.cache.ledger
        return {
            "requests": _profiler.get_counter(self.name + "_requests"),
            "tokens": _profiler.get_counter(self.name + "_tokens"),
            "decode_steps": _profiler.get_counter(
                self.name + "_decode_steps"),
            "active_sequences": active,
            "waiting": waiting,
            "evicted": _profiler.get_counter(self.name + "_evicted"),
            "compiles": _profiler.get_counter(self.name + "_compile"),
            "cache_hits": _profiler.get_counter(self.name + "_cache_hit"),
            "shed": _profiler.get_counter(self.name + "_shed"),
            "deadline_expired": _profiler.get_counter(
                self.name + "_deadline_expired"),
            "executable_bound": self.engine.executable_bound(),
            "kv": {
                "slots_in_use": led.slots_in_use,
                "pages_in_use": led.pages_in_use,
                "total_pages": led.total_pages,
                "occupancy": round(led.occupancy(), 4),
                "max_slots": self.cache.max_slots,
                "page": self.cache.page,
                "int8": self.cache.int8,
                "hbm_bytes": self.cache.hbm_bytes(),
            },
            "buckets": {"prompt": list(self.engine.prompt_buckets),
                        "decode": list(self.engine.seq_buckets)},
            "ttft": self.latency.ttft.snapshot(),
            "tpot": self.latency.tpot.snapshot(),
        }

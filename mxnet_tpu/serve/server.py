"""``mx.serve.InferenceServer`` — dynamic-batching inference serving.

The reference deployment story stops at the synchronous, single-request
predict API (``c_predict_api.h:77-178``: SetInput -> Forward ->
GetOutput); production traffic is concurrent and batch-1 dispatch wastes
the accelerator. This module is the serving layer the ROADMAP's
"millions of users" north star needs, built the way production servers
do it (NVIDIA Triton's dynamic batcher, TF Serving's BatchingSession,
Clipper's adaptive batching):

* concurrent callers ``submit()`` single requests and get futures;
* a bounded queue coalesces them into micro-batches under a
  ``max_batch_size`` / ``max_delay_us`` window;
* every batch is padded onto the finite pow2 bucket grid
  (:mod:`.bucketing`) so the jitted executable set is finite and
  steady-state serving does **zero recompiles**;
* results are split back per request, futures resolve after the device
  sync, so recorded latency is real end-to-end time.

Robustness: per-request deadlines (``DeadlineExceeded``), admission
control with load-shedding (``QueueFull``), graceful drain on ``close``,
and the ``MXNET_TPU_SERVE`` kill switch + per-request eager fallback
mirroring the fused-trainer pattern (``_fused.py``): a structure whose
batched build fails is negative-cached with bounded retry and its
traffic degrades to eager per-request forwards instead of erroring.

Observability: per-bucket compile/hit counters ride the shared
:class:`CompileCache` discipline under the ``serve_*`` profiler prefix;
queue depth and batch occupancy are profiler gauges; ``stats()``
snapshots p50/p95/p99 latency, throughput accounting and the per-bucket
table.
"""
from __future__ import annotations

import collections
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import ndarray as nd_mod
from .. import profiler as _profiler
from ..obs import compiles as _obs_compiles
from ..obs.http import maybe_start_from_knob as _maybe_metrics
from .._fused import CompileCache, structural_failure
from ..base import MXNetError
from ..context import Context, current_context
from .bucketing import BucketSpec
from .stats import LatencyStats, monotonic

__all__ = ["InferenceServer", "ServeError", "ServerClosed", "QueueFull",
           "DeadlineExceeded", "wrap_model"]

# per-bucket stats table bound; the tail aggregates under "(other)"
_MAX_BUCKET_STATS = 1024


class ServeError(MXNetError):
    """Base class for serving errors."""


class ServerClosed(ServeError):
    """submit() after close()."""


class QueueFull(ServeError):
    """Load shed: the admission bound was exceeded (clients should back
    off / retry against another replica — erroring fast beats queueing
    into a latency collapse)."""


class DeadlineExceeded(ServeError):
    """The request's deadline passed before its batch launched."""


def wrap_model(model) -> Callable:
    """Normalize the served model to ``fn(NDArray batch) -> outputs``.

    Accepts a :class:`~mxnet_tpu.predictor.Predictor` (single declared
    input), a bound :class:`~mxnet_tpu.module.BaseModule`, a gluon
    ``Block``, or any callable taking an NDArray batch. Batch geometry
    varies per call (the bucket grid), so the Predictor/Module paths
    feed the underlying executor directly — jit re-specializes once per
    bucket, exactly the finite set the server maintains.

    Ownership: serving a Predictor/Module hands its executor to the
    server (all server-side calls are serialized by the model lock, and
    the Predictor's bound input geometry is restored after each batch).
    Do NOT call ``forward``/``set_input`` on it from other threads
    WHILE it is being served — direct use is safe again after
    ``close()``.
    """
    from ..predictor import Predictor
    from ..module.base_module import BaseModule

    if isinstance(model, Predictor):
        names = sorted(model._input_shapes)
        if len(names) != 1:
            raise ValueError(
                "serve: Predictor has inputs %s; the dynamic batcher "
                "coalesces a single request tensor — wrap multi-input "
                "models in a callable" % (names,))
        name = names[0]

        def predictor_fn(x):
            # restore the bound input buffer afterwards: the bucket
            # batch would otherwise permanently replace the declared
            # (1, ...) geometry, and a later DIRECT predictor.forward()
            # would silently broadcast its input across the bucket rows
            buf = model._exec.arg_dict[name]
            saved = buf._data
            try:
                return list(model._exec.forward(is_train=False,
                                                **{name: x}))
            finally:
                buf._data = saved
                buf._version += 1

        return predictor_fn
    if isinstance(model, BaseModule):
        from .. import io as io_mod

        def module_fn(x):
            model.forward(io_mod.DataBatch(data=[x]), is_train=False)
            return list(model.get_outputs())

        return module_fn
    if callable(model):
        return model
    raise TypeError("serve: cannot wrap %r — expected Predictor, Module, "
                    "gluon Block, or callable" % (type(model).__name__,))


def _resolve(fut: Future, value=None, exc: Optional[BaseException] = None):
    """Complete a future, tolerating caller-side cancel(): a cancelled
    future must never kill the batcher thread."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(value)
    except Exception:                                       # noqa: BLE001
        pass


def _serve_loop(server_ref):
    """Batcher thread body. While IDLE it sleeps holding only the
    server's condition variable, never the server itself — so an
    abandoned (un-closed) server is garbage-collectable and the thread
    exits on its next wake instead of pinning the model and polling
    forever. While a batch is pending it holds the server normally."""
    while True:
        srv = server_ref()
        if srv is None:
            return
        cond = srv._cond
        queue = srv._queue          # stable identity (mutated in place)
        with cond:
            has_work = bool(queue)
            closed = srv._closed
        if not has_work:
            if closed:
                return
            srv = None              # the idle sleep must not pin the server
            with cond:
                if not queue:       # re-check under the lock: a submit
                    cond.wait(0.05)  # in the gap must not lose its wakeup
            continue
        try:
            batch = srv._take_batch()
            if batch is None:
                return
            if batch:
                srv._run_batch(batch)
        except Exception:                                  # noqa: BLE001
            # the batcher must never die: _run_batch routes errors into
            # the affected futures; anything that escapes is a bug, but
            # killing the worker would turn it into a silent hang for
            # every later request
            pass
        del srv


class _Request:
    __slots__ = ("data", "rows", "batched", "sample_shape", "bucket_key",
                 "future", "t_submit", "deadline", "flow")

    def __init__(self, data, rows, batched, sample_shape, bucket_key,
                 deadline, flow=None):
        self.data = data
        self.rows = rows
        self.batched = batched
        self.sample_shape = sample_shape
        self.bucket_key = bucket_key
        self.future: Future = Future()
        self.t_submit = monotonic()
        self.deadline = deadline
        self.flow = flow    # trace flow id linking submit -> launch


class InferenceServer:
    """Thread-safe dynamic-batching server over one model.

    Parameters
    ----------
    model : Predictor | Module | Block | callable
        Forward function taking an NDArray batch (leading row axis) and
        returning an NDArray or list of NDArrays with the same leading
        row count. Inference must be row-independent (eval-mode nets
        are) — padded rows must not bleed into real ones.
    max_batch_size, max_delay_us, queue_bound : int, optional
        Coalescing row bound, batching window, and admission bound.
        Defaults come from the ``MXNET_TPU_SERVE_*`` env knobs.
    buckets : BucketSpec, optional
        Full bucket control (explicit ladders, dynamic seq axis). When
        given, ``max_batch_size`` must be left None — the spec owns it.
    ctx : Context, optional
        Device requests are staged to (default: current context).
    name : str
        Prefix for profiler counters/gauges (default ``"serve"``; give
        each server a distinct name to split dashboards).
    metrics_port : int, optional
        Opt-in Prometheus ``/metrics`` endpoint (mx.obs exposition):
        ``None`` defers to the ``MXNET_TPU_OBS_METRICS_PORT`` knob,
        ``-1`` = off, ``0`` = ephemeral port (read ``.metrics_port``
        back), ``>0`` = fixed port. Closed with the server.
    """

    def __init__(self, model, max_batch_size: Optional[int] = None,
                 max_delay_us: Optional[int] = None,
                 queue_bound: Optional[int] = None,
                 buckets: Optional[BucketSpec] = None,
                 ctx: Optional[Context] = None,
                 name: str = "serve",
                 metrics_port: Optional[int] = None):
        from .. import config as _config
        if buckets is not None and max_batch_size is not None:
            raise ValueError("pass max_batch_size or buckets, not both")
        if buckets is None:
            buckets = BucketSpec(max_batch_size if max_batch_size is not None
                                 else _config.get("MXNET_TPU_SERVE_MAX_BATCH"))
        self.buckets = buckets
        self.max_delay_s = (max_delay_us if max_delay_us is not None else
                            _config.get("MXNET_TPU_SERVE_MAX_DELAY_US")) * 1e-6
        self.queue_bound = (queue_bound if queue_bound is not None else
                            _config.get("MXNET_TPU_SERVE_QUEUE_BOUND"))
        self.name = name
        self._model = wrap_model(model)
        self._ctx = ctx or current_context()
        self._single_output: Optional[bool] = None
        # sig -> padded-dispatch runner; counters ride the shared
        # CompileCache scheme (<name>_compile / _cache_hit / ...), so
        # "zero recompiles after warmup" is a counter assertion. The
        # table must hold the WHOLE bucket grid: eviction of a live
        # geometry would re-count its next dispatch as a compile and
        # falsify that observable (4x headroom covers multiple dtypes;
        # unbounded client shape sets — no seq bucketing — get a large
        # table, mirroring the underlying jit cache they also grow).
        grid = self.buckets.executable_bound()
        self.cache = CompileCache(
            name, max_entries=max(4 * grid, 128) if grid else 4096)
        # latency rides the shared obs histogram registry (same-name
        # servers aggregate, mirroring the <name>_* counter discipline)
        # so the Prometheus exposition includes it without extra wiring
        self.latency = LatencyStats(name=name + "_latency_seconds")
        # opt-in Prometheus /metrics endpoint (arg wins over the
        # MXNET_TPU_OBS_METRICS_PORT knob; resolved < 0 = off). This
        # server is deliberately collectable without close() (the worker
        # holds only a weakref) — the finalizer keeps that true for the
        # endpoint too, releasing the bound port when the server is GC'd
        try:
            self._metrics = _maybe_metrics(metrics_port)
        except OSError as exc:
            # an observability knob must never take down the serving
            # path: a port conflict (second server on a fixed port,
            # another process) degrades to no endpoint, loudly
            import logging
            logging.getLogger(__name__).warning(
                "serve[%s]: /metrics endpoint disabled (%s)", name, exc)
            _profiler.incr_counter(name + "_metrics_bind_failed")
            self._metrics = None
        self.metrics_port = self._metrics.port if self._metrics else None
        self._metrics_finalizer = weakref.finalize(
            self, self._metrics.close) if self._metrics else None
        # serializes ALL model invocations: Predictor/Module adapters
        # mutate shared executor state (arg_dict -> forward -> outputs),
        # so a kill-switch eager call in a caller thread must never
        # interleave with the worker's batched call or another caller.
        # Uncontended on the hot batched path (worker-only).
        self._model_lock = threading.Lock()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: collections.deque = collections.deque()
        self._closed = False
        self._batches = 0
        self._served = 0
        self._padded_rows = 0
        self._per_bucket: Dict[Tuple, Dict[str, int]] = {}
        # the loop holds only a WEAK reference between iterations: a
        # server dropped without close() must be collectable (a strong
        # ref from a live thread would pin the model + params and poll
        # forever) — the thread exits on the first wake after GC
        self._worker = threading.Thread(
            target=_serve_loop, args=(weakref.ref(self),), daemon=True,
            name="mxnet_tpu.serve[%s]" % name)
        self._worker.start()

    # ------------------------------------------------------------ submit
    def submit(self, data, batched: bool = False,
               timeout: Optional[float] = None) -> Future:
        """Enqueue one request; returns a ``concurrent.futures.Future``.

        ``data`` is one sample (no batch dim) by default; with
        ``batched=True`` its leading axis is rows and the result keeps
        it. The future resolves with host numpy arrays (zero-copy row
        views of the batch fetch — serving results cross a process
        boundary anyway, and per-request device slicing costs more
        than the batched forward). ``timeout`` (seconds) is the request
        deadline: if its batch has not launched by then the future
        fails with :class:`DeadlineExceeded`.

        Raises :class:`QueueFull` (load shed) when the queue is at the
        admission bound, :class:`ServerClosed` after ``close()``.
        """
        from .. import config as _config
        from .. import faults as _faults
        if _faults.ARMED:
            # robustness drill: an injected submit failure must surface
            # on THIS request only — the server keeps serving
            _faults.fire("serve.submit", default_kind="raise")
        x = np.asarray(data.asnumpy() if isinstance(data, nd_mod.NDArray)
                       else data)
        if batched:
            if x.ndim < 1:
                raise ValueError("batched request needs a leading row axis")
            rows, sample_shape = int(x.shape[0]), tuple(x.shape[1:])
            if rows > self.buckets.max_batch_size:
                raise ValueError(
                    "request of %d rows exceeds max_batch_size %d — split "
                    "it client-side" % (rows, self.buckets.max_batch_size))
        else:
            rows, sample_shape = 1, tuple(x.shape)
        # admission-time shape validation: sample_bucket raises on
        # over-long dynamic axes, so bad requests fail fast in the
        # caller, not in the batcher thread
        padded_sample = self.buckets.sample_bucket(sample_shape)
        bucket_key = (padded_sample, str(x.dtype))
        deadline = None if timeout is None else monotonic() + timeout

        if self._closed:
            raise ServerClosed("submit() after close()")
        if not _config.get("MXNET_TPU_SERVE"):
            # kill switch: per-request eager forward in the caller
            # thread — no queue, no batching, no bucketing
            return self._eager_future(x, rows, batched)

        fid = _profiler.new_flow() if _profiler.spans_enabled() else None
        req = _Request(x, rows, batched, sample_shape, bucket_key, deadline,
                       flow=fid)
        with _profiler.span("serve_submit", "serve", flow=fid):
            with self._cond:
                if self._closed:
                    raise ServerClosed("submit() after close()")
                if len(self._queue) >= self.queue_bound:
                    _profiler.incr_counter(self.name + "_shed")
                    raise QueueFull(
                        "queue depth %d at admission bound %d"
                        % (len(self._queue), self.queue_bound))
                self._queue.append(req)
                _profiler.set_gauge(self.name + "_queue_depth",
                                    len(self._queue))
                self._cond.notify_all()
        return req.future

    def __call__(self, data, batched: bool = False,
                 timeout: Optional[float] = None):
        """Blocking convenience: ``submit(...).result()``."""
        return self.submit(data, batched=batched, timeout=timeout).result()

    # ------------------------------------------------------------- close
    def close(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop accepting requests. ``drain=True`` (default) serves
        everything already queued before the worker exits; ``False``
        fails queued requests with :class:`ServerClosed`. Idempotent:
        a second close only joins — it must not drop requests a prior
        ``close(drain=True)`` promised to serve."""
        with self._cond:
            already = self._closed
            self._closed = True
            if already:
                self._cond.notify_all()
                drain = True        # first close's promise stands
            if not drain:
                dropped = list(self._queue)
                self._queue.clear()
            else:
                dropped = []
            self._cond.notify_all()
        for req in dropped:
            _resolve(req.future, exc=ServerClosed("server closed"))
        self._worker.join(timeout)
        if self._metrics_finalizer is not None:
            self._metrics_finalizer()    # idempotent: detaches after one call
            self._metrics = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=not any(exc))
        return False

    # ------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        """Point-in-time serving snapshot (thread-safe)."""
        with self._lock:
            depth = len(self._queue)
            batches, served = self._batches, self._served
            padded = self._padded_rows
            per_bucket = {
                (key if isinstance(key, str)
                 else "%s/%s" % ("x".join(map(str, key[0])), key[1])):
                dict(rec)
                for key, rec in self._per_bucket.items()}
        dispatched = sum(r["rows"] for r in per_bucket.values())
        return {
            "requests": served,
            "batches": batches,
            "queue_depth": depth,
            "avg_batch_rows": round(dispatched / batches, 3) if batches
            else None,
            "occupancy": round(dispatched / (dispatched + padded), 4)
            if dispatched else None,
            "buckets": per_bucket,
            "compiles": _profiler.get_counter(self.name + "_compile"),
            "cache_hits": _profiler.get_counter(self.name + "_cache_hit"),
            "shed": _profiler.get_counter(self.name + "_shed"),
            "deadline_expired": _profiler.get_counter(
                self.name + "_deadline_expired"),
            "eager_fallback": _profiler.get_counter(self.name + "_eager"),
            "latency": self.latency.snapshot(),
        }

    # ----------------------------------------------------------- batcher
    def _take_batch(self) -> Optional[List[_Request]]:
        """Wait (bounded) for a batch: [] when nothing is ready yet
        (caller re-checks liveness and retries), None when the worker
        should exit (closed and drained)."""
        with self._cond:
            if not self._queue:
                if self._closed:
                    return None
                # bounded wait so _serve_loop can drop its strong ref
                # and re-check server liveness between idle ticks
                self._cond.wait(0.05)
                if not self._queue:
                    return None if self._closed else []
            head = self._queue[0]
            _t_co = time.perf_counter() if _profiler.spans_enabled() \
                else None
            window_end = head.t_submit + self.max_delay_s
            while not self._closed:
                now = monotonic()
                if now >= window_end:
                    break
                if self._compatible_rows(head.bucket_key) >= \
                        self.buckets.max_batch_size:
                    break
                # a queued deadline must fire ~when promised, not up to
                # a full batching window late: wake at the earliest of
                # window end / next deadline / the 10 ms arrival tick
                dls = [r.deadline for r in self._queue
                       if r.deadline is not None]
                next_dl = min(dls) if dls else None
                if next_dl is not None and now >= next_dl:
                    break
                tick = window_end - now
                if next_dl is not None:
                    tick = min(tick, next_dl - now)
                self._cond.wait(min(tick, 0.01))
            # pop the head's bucket-mates FIFO, honoring the row bound;
            # other buckets keep their queue positions
            batch, rows, kept = [], 0, []
            now = monotonic()
            expired = []
            for req in self._queue:
                if req.future.cancelled():
                    continue
                if req.deadline is not None and now > req.deadline:
                    expired.append(req)
                    continue
                if req.bucket_key == head.bucket_key and \
                        rows + req.rows <= self.buckets.max_batch_size and \
                        req.future.set_running_or_notify_cancel():
                    batch.append(req)
                    rows += req.rows
                else:
                    kept.append(req)
            # in-place: _serve_loop's idle path holds this deque by
            # identity, so the queue object must never be rebound
            self._queue.clear()
            self._queue.extend(kept)
            _profiler.set_gauge(self.name + "_queue_depth",
                                len(self._queue))
        for req in expired:
            _profiler.incr_counter(self.name + "_deadline_expired")
            _resolve(req.future, exc=DeadlineExceeded(
                "deadline passed %.1f ms before batch launch"
                % ((now - req.deadline) * 1e3)))
        if batch and _t_co is not None:
            # batching-window slice on the batcher lane, linked to the
            # head request's flow (idle ticks emit nothing)
            _profiler.record_span("serve_coalesce", _t_co,
                                  time.perf_counter(), "serve",
                                  flow=batch[0].flow)
        return batch

    def _compatible_rows(self, bucket_key) -> int:
        return sum(r.rows for r in self._queue
                   if r.bucket_key == bucket_key)

    # ---------------------------------------------------------- dispatch
    def _call_model(self, x: nd_mod.NDArray) -> List[np.ndarray]:
        """Run the model and fetch each output to host ONCE. Results are
        numpy: per-request splitting must be zero-copy views — slicing
        NDArrays would dispatch one eager device op per request, which
        measured ~10x the whole batched forward at MLP sizes. The fetch
        doubles as the device sync, so recorded latency is real."""
        # the lock-held host sync is the design here: all model
        # invocations serialize on _model_lock (shared executor state),
        # and fetching inside it is what makes recorded latency real
        with self._model_lock:  # mx-lint: allow(lock-host-sync)
            outs = self._model(x)
            outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
            if self._single_output is None:
                # one output -> callers get the bare array, not a 1-list
                # (Predictor/Module adapters always hand back lists)
                self._single_output = len(outs) == 1
            return [np.asarray(o.asnumpy()) for o in outs]

    def _assemble(self, batch: List[_Request], bucket_rows: int):
        padded_sample = batch[0].bucket_key[0]
        buf = np.full((bucket_rows,) + padded_sample,
                      self.buckets.pad_value, batch[0].data.dtype)
        r0 = 0
        for req in batch:
            block = req.data if req.batched else req.data[None]
            sl = (slice(r0, r0 + req.rows),) + tuple(
                slice(0, d) for d in req.sample_shape)
            buf[sl] = block
            r0 += req.rows
        return buf, r0

    def _run_batch(self, batch: List[_Request]):
        rows = sum(r.rows for r in batch)
        try:
            bucket_rows = self.buckets.batch_bucket(rows)
            sig = (batch[0].bucket_key, bucket_rows)
            if self.cache.should_skip(sig):
                # negative-cached geometry: its traffic runs eager
                self._fallback_eager(batch)
                return
            buf, _ = self._assemble(batch, bucket_rows)
            # NOTE: the cached "runner" is always _call_model — the real
            # per-geometry executable lives in jax's jit cache, keyed by
            # the same padded shape this sig encodes. CompileCache here
            # supplies the rest of its contract: first-dispatch/hit
            # counters (the zero-recompile observable), bounded-retry
            # negative caching, and the eager-fallback gate.
            runner = self.cache.get(sig)
            fresh = runner is None
            if fresh:
                runner = self._call_model
            try:
                with _profiler.span("serve_launch", "serve",
                                    flow=batch[0].flow) as _sp:
                    for req in batch[1:]:
                        _sp.mark_flow(req.flow)
                    with _obs_compiles.scope(self.name, sig):
                        outs = runner(nd_mod.array(buf, ctx=self._ctx))
            except Exception as exc:                       # noqa: BLE001
                self.cache.mark_failed(sig,
                                       permanent=structural_failure(exc))
                self._fallback_eager(batch)
                return
            if fresh:
                self.cache.put(sig, runner)
            else:
                self.cache.note_success(sig)
        except Exception as exc:                           # noqa: BLE001
            for req in batch:
                _resolve(req.future, exc=exc)
            return
        with self._lock:
            self._batches += 1
            self._served += len(batch)
            self._padded_rows += bucket_rows - rows
            # bounded like every sibling structure (CompileCache table,
            # LatencyStats ring): client-controlled shape sets must not
            # grow the stats table monotonically — the tail aggregates
            key = sig[0]
            if key not in self._per_bucket and \
                    len(self._per_bucket) >= _MAX_BUCKET_STATS:
                key = "(other)"
            rec = self._per_bucket.setdefault(
                key, {"batches": 0, "requests": 0, "rows": 0})
            rec["batches"] += 1
            rec["requests"] += len(batch)
            rec["rows"] += rows
        _profiler.incr_counter(self.name + "_batches")
        _profiler.incr_counter(self.name + "_requests", len(batch))
        _profiler.set_gauge(self.name + "_batch_occupancy",
                            rows / bucket_rows)
        done = monotonic()
        r0 = 0
        try:
            with _profiler.span("serve_resolve", "serve",
                                flow=batch[0].flow):
                for req in batch:
                    if self._single_output:
                        res = outs[0][r0:r0 + req.rows] if req.batched \
                            else outs[0][r0]
                    else:
                        res = [o[r0:r0 + req.rows] if req.batched else o[r0]
                               for o in outs]
                    r0 += req.rows
                    self.latency.record(done - req.t_submit)
                    _resolve(req.future, res)
        except Exception as exc:                           # noqa: BLE001
            # row-contract violation (output leading axis != input rows):
            # every future must still resolve — a dead batcher thread
            # would hang all pending AND future requests silently. The
            # geometry is structurally broken, so pin it to the eager
            # path, where the same error surfaces per request.
            self.cache.mark_failed(sig, permanent=True)
            for req in batch:
                _resolve(req.future, exc=exc)

    # ------------------------------------------------------ eager paths
    def _eager_one(self, x: np.ndarray, batched: bool):
        nd_in = nd_mod.array(x if batched else x[None], ctx=self._ctx)
        outs = self._call_model(nd_in)
        _profiler.incr_counter(self.name + "_eager")
        if self._single_output:
            return outs[0] if batched else outs[0][0]
        return outs if batched else [o[0] for o in outs]

    def _eager_future(self, x, rows, batched) -> Future:
        fut: Future = Future()
        t0 = monotonic()
        try:
            res = self._eager_one(x, batched)
        except Exception as exc:                           # noqa: BLE001
            fut.set_exception(exc)
            return fut
        self.latency.record(monotonic() - t0)
        with self._lock:
            self._served += 1
        fut.set_result(res)
        return fut

    def _fallback_eager(self, batch: List[_Request]):
        """Per-request eager forwards for a batch whose bucketed
        dispatch is unavailable (build failed / negative-cached) — the
        serving twin of the fused trainer's per-param fallback."""
        done_extra = 0
        for req in batch:
            # same deadline contract as the healthy path: a request
            # whose deadline lapsed while earlier fallback forwards ran
            # fails DeadlineExceeded instead of resolving arbitrarily
            # late (callers key retry/hedging logic on that error)
            if req.deadline is not None and monotonic() > req.deadline:
                _profiler.incr_counter(self.name + "_deadline_expired")
                _resolve(req.future, exc=DeadlineExceeded(
                    "deadline passed before eager-fallback dispatch"))
                continue
            try:
                res = self._eager_one(req.data, req.batched)
            except Exception as exc:                       # noqa: BLE001
                _resolve(req.future, exc=exc)
                continue
            self.latency.record(monotonic() - req.t_submit)
            _resolve(req.future, res)
            done_extra += 1
        with self._lock:
            self._served += done_extra

"""``mx.serve`` — dynamic-batching inference serving.

The deployment layer above :class:`~mxnet_tpu.predictor.Predictor`:
concurrent requests are coalesced into bucket-padded micro-batches so a
finite set of jitted executables serves arbitrary traffic with zero
steady-state recompiles. See :mod:`.server` for the design and
``docs/architecture/serving.md`` for the full matrix.

    server = mx.serve.InferenceServer(net, max_batch_size=32)
    futures = [server.submit(x) for x in requests]
    results = [f.result() for f in futures]
    server.stats()   # p50/p95/p99, occupancy, per-bucket compiles
    server.close()   # graceful drain

Generative decode (continuous batching over a preallocated bucketed KV
cache — :mod:`.kv_cache` / :mod:`.decode`):

    gen = mx.serve.GenerativeServer(module, n_heads=8)
    handle = gen.submit_generate(prompt_ids, max_new_tokens=64)
    for tok in handle:      # per-token streaming
        ...

Kill switch: ``MXNET_TPU_SERVE=0`` degrades every ``submit`` to an
eager per-request forward in the caller thread (the bisection fallback,
mirroring ``MXNET_TPU_FUSED_TRAINER``).

Zero-cost gate: importing this package does NOT import the decode path
(:mod:`.kv_cache` / :mod:`.decode`) — those load lazily on first
``GenerativeServer`` construction or attribute access below, so batch
serving never pays for generative machinery it doesn't use (CI asserts
this).
"""
from .bucketing import BucketSpec, decode_buckets
from .server import (DeadlineExceeded, GenerateHandle, GenerativeServer,
                     InferenceServer, QueueFull, ServeError, ServerClosed,
                     wrap_model)
from .stats import DecodeLatencyStats, LatencyStats

__all__ = [
    "InferenceServer", "GenerativeServer", "GenerateHandle", "BucketSpec",
    "decode_buckets", "LatencyStats", "DecodeLatencyStats", "wrap_model",
    "ServeError", "ServerClosed", "QueueFull", "DeadlineExceeded",
    "KVCache", "PageLedger", "max_slots_for", "DecodeEngine",
]

# lazy decode-path exports: module-level __getattr__ keeps kv_cache /
# decode unimported until someone actually reaches for them
_LAZY = {
    "KVCache": ("kv_cache", "KVCache"),
    "PageLedger": ("kv_cache", "PageLedger"),
    "CacheFull": ("kv_cache", "CacheFull"),
    "max_slots_for": ("kv_cache", "max_slots_for"),
    "DecodeEngine": ("decode", "DecodeEngine"),
    "DecodeConfig": ("decode", "DecodeConfig"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod, attr = _LAZY[name]
        return getattr(importlib.import_module("." + mod, __name__), attr)
    raise AttributeError("module %r has no attribute %r" % (__name__, name))

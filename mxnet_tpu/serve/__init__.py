"""``mx.serve`` — dynamic-batching inference serving.

The deployment layer above :class:`~mxnet_tpu.predictor.Predictor`:
concurrent requests are coalesced into bucket-padded micro-batches so a
finite set of jitted executables serves arbitrary traffic with zero
steady-state recompiles. See :mod:`.server` for the design and
``docs/architecture/serving.md`` for the full matrix.

    server = mx.serve.InferenceServer(net, max_batch_size=32)
    futures = [server.submit(x) for x in requests]
    results = [f.result() for f in futures]
    server.stats()   # p50/p95/p99, occupancy, per-bucket compiles
    server.close()   # graceful drain

Kill switch: ``MXNET_TPU_SERVE=0`` degrades every ``submit`` to an
eager per-request forward in the caller thread (the bisection fallback,
mirroring ``MXNET_TPU_FUSED_TRAINER``).
"""
from .bucketing import BucketSpec
from .server import (DeadlineExceeded, InferenceServer, QueueFull,
                     ServeError, ServerClosed, wrap_model)
from .stats import LatencyStats

__all__ = [
    "InferenceServer", "BucketSpec", "LatencyStats", "wrap_model",
    "ServeError", "ServerClosed", "QueueFull", "DeadlineExceeded",
]

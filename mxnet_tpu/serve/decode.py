"""Prefill/decode program split for generative serving.

The zoo transformer (``models/transformer.py``) trains as a Symbol over
fixed ``(N, T)`` geometry; autoregressive serving needs two different
programs, both drawn from a FINITE bucket universe so steady-state
decode is a counter-asserted zero-recompile regime:

* **prefill** — one jitted program per pow2 prompt bucket ``T_b``:
  runs the full causal forward on one padded prompt (dense attention at
  short buckets, :func:`~mxnet_tpu.parallel.ring_attention
  .chunked_causal_attention` — the ring kernel's online-softmax block
  loop, single-device — past ``prefill_chunk``), writes the prompt's
  K/V into the cache slot IN-PROGRAM (the state operand is donated, so
  the update is in-place on TPU), and returns only the last real
  token's logits (one ``(D,)`` row through the LM head, not a
  ``(T_b, V)`` matmul).
* **decode** — ONE jitted step per sequence bucket ``S_b`` over the
  WHOLE slot array: embed the freshest token of every resident
  sequence, append its K/V at the per-slot write position via a vmapped
  ``lax.dynamic_update_slice`` (gather-free; finished/empty slots write
  into reclaimed space that the next prefill overwrites — a masked
  no-op by construction), attend against the static ``[0:S_b]`` cache
  slice with per-slot length masking, and return ``(slots, V)`` logits.

The executable set is exactly |prompt buckets| + |decode buckets| (the
server's CompileCache counters assert it), and each program is
AOT-warm-startable through :mod:`mxnet_tpu.aot` — a restarted server
reaches its first token with zero backend compiles (the CI drill
asserts the obs compile accounting stays empty).

The decode forward is a pure-jax reimplementation of the Symbol graph,
consuming the SAME parameter dict ``Module.get_params()`` returns —
parity with the training forward is pinned by
``tests/test_serve_decode.py`` (softmax outputs at the last real
position, f32 atol 1e-4). int8 KV mode quantizes pages on write with
requantize-on-scale-growth (fresh scale on page entry, so a page never
inherits a stale tenant's dynamic range) and dequantizes with one
broadcast multiply per read — tolerance documented in the same test.
"""
from __future__ import annotations

from contextlib import nullcontext as _nullcontext
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import profiler as _profiler
from ..base import MXNetError
from ..obs import compiles as _obs_compiles

__all__ = ["DecodeConfig", "DecodeEngine", "extract_params",
           "config_from_params", "sample_token"]

_LN_EPS = 1e-5          # ops/nn.py layer_norm default


class DecodeConfig:
    """Static geometry of the served transformer (shapes the programs
    specialize on)."""

    __slots__ = ("num_layers", "d_model", "n_heads", "d_head", "d_ff",
                 "vocab_size", "max_seq")

    def __init__(self, num_layers: int, d_model: int, n_heads: int,
                 d_ff: int, vocab_size: int, max_seq: int):
        if d_model % n_heads:
            raise ValueError("d_model %d not divisible by n_heads %d"
                             % (d_model, n_heads))
        self.num_layers = int(num_layers)
        self.d_model = int(d_model)
        self.n_heads = int(n_heads)
        self.d_head = int(d_model) // int(n_heads)
        self.d_ff = int(d_ff)
        self.vocab_size = int(vocab_size)
        self.max_seq = int(max_seq)

    def sig(self) -> Tuple:
        return (self.num_layers, self.d_model, self.n_heads, self.d_ff,
                self.vocab_size, self.max_seq)


def extract_params(source) -> Dict[str, Any]:
    """Normalize the served parameters to ``name -> f32 jnp array``.

    Accepts a bound Module (``get_params()``), an ``(arg, aux)`` tuple,
    or a plain dict of NDArray/numpy arrays — the exact naming the zoo
    transformer Symbol binds (``tok_embed_weight``,
    ``layer%d_att_qkv_weight``, ...).
    """
    import jax.numpy as jnp
    from .. import ndarray as nd_mod
    if hasattr(source, "get_params"):
        arg, aux = source.get_params()
        merged = dict(arg)
        merged.update(aux or {})
    elif isinstance(source, tuple) and len(source) == 2:
        merged = dict(source[0])
        merged.update(source[1] or {})
    else:
        merged = dict(source)
    out = {}
    for name, arr in merged.items():
        if isinstance(arr, nd_mod.NDArray):
            arr = arr.asnumpy()
        out[name] = jnp.asarray(np.asarray(arr), jnp.float32)
    return out


def config_from_params(params: Dict[str, Any],
                       n_heads: int) -> DecodeConfig:
    """Infer the transformer geometry from the bound parameter shapes
    (head count is not shape-derivable — the caller states it)."""
    need = ("tok_embed_weight", "pos_embed_weight", "lm_head_weight",
            "layer0_ff1_weight")
    for k in need:
        if k not in params:
            raise MXNetError(
                "serve decode: parameter %r missing — GenerativeServer "
                "serves the zoo transformer naming convention "
                "(models/transformer.py); found %d params"
                % (k, len(params)))
    vocab, d_model = params["tok_embed_weight"].shape
    max_seq = params["pos_embed_weight"].shape[0]
    d_ff = params["layer0_ff1_weight"].shape[0]
    n_layers = 0
    while ("layer%d_att_qkv_weight" % n_layers) in params:
        n_layers += 1
    return DecodeConfig(n_layers, int(d_model), int(n_heads), int(d_ff),
                        int(vocab), int(max_seq))


def sample_token(logits: np.ndarray, temperature: float = 0.0,
                 rng: Optional[np.random.Generator] = None) -> int:
    """Host-side sampling: greedy at ``temperature=0`` (deterministic —
    the batch-composition-invariance test keys on it), else softmax
    sampling from the caller's per-request generator."""
    if temperature <= 0.0:
        return int(np.argmax(logits))
    z = logits.astype(np.float64) / float(temperature)
    z -= z.max()
    p = np.exp(z)
    p /= p.sum()
    gen = rng or np.random.default_rng()
    return int(gen.choice(len(p), p=p))


# --------------------------------------------------------------- forward


def _ln(x, gamma, beta):
    """LayerNorm matching ops/nn.py semantics: f32 one-pass stats."""
    import jax.numpy as jnp
    from jax import lax
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    msq = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    var = jnp.maximum(msq - jnp.square(mean), 0.0)
    return (x32 - mean) * lax.rsqrt(var + _LN_EPS) * gamma + beta


def _fc(x, params, name):
    return x @ params[name + "_weight"].T + params[name + "_bias"]


def _quantize_pages(x, page: int):
    """(H, T, d) f32 -> (int8 (H, T, d), scales (H, T // page)) — one
    symmetric scale per (head, page), the quantized-paged-KV layout."""
    import jax.numpy as jnp
    h, t, d = x.shape
    pg = x.reshape(h, t // page, page, d)
    scale = jnp.maximum(jnp.max(jnp.abs(pg), axis=(2, 3)) / 127.0, 1e-8)
    q = jnp.clip(jnp.round(pg / scale[:, :, None, None]), -127, 127)
    return q.reshape(h, t, d).astype(jnp.int8), scale


class DecodeEngine:
    """The program table: builds, AOT-warm-starts and dispatches the
    per-bucket prefill/decode executables over one :class:`KVCache`.

    NOT thread-safe by design: every method runs on the owning
    GenerativeServer's scheduler thread (the cache state tuple is
    donated through each dispatch and re-bound from the result — a
    second dispatcher would race the donation).
    """

    def __init__(self, params: Dict[str, Any], n_heads: int, cache,
                 compile_cache, name: str = "serve",
                 prompt_buckets: Optional[Sequence[int]] = None,
                 seq_buckets: Optional[Sequence[int]] = None,
                 prefill_chunk: int = 512):
        self.params = params
        self.cfg = config_from_params(params, n_heads)
        self.cache = cache
        self.compile_cache = compile_cache
        self.name = name
        self.prefill_chunk = int(prefill_chunk)
        from .bucketing import decode_buckets as _ladder
        self.seq_buckets: List[int] = list(
            seq_buckets if seq_buckets is not None
            else _ladder(cache.max_seq, cache.page))
        self.prompt_buckets: List[int] = list(
            prompt_buckets if prompt_buckets is not None
            else self.seq_buckets)
        for b in self.prompt_buckets:
            if b % cache.page:
                raise ValueError("prompt bucket %d not a multiple of the "
                                 "kv page %d" % (b, cache.page))
        # multi-device (sharded cache) programs are AOT-fenced exactly
        # like the executor forward (aot_skip_multidevice)
        self._multi_device = cache._sharding is not None

    def executable_bound(self) -> int:
        return len(self.prompt_buckets) + len(self.seq_buckets)

    def prompt_bucket(self, n: int) -> int:
        for b in self.prompt_buckets:
            if n <= b:
                return b
        raise MXNetError("prompt of %d tokens exceeds max bucket %d"
                         % (n, self.prompt_buckets[-1]))

    def seq_bucket(self, needed: int) -> int:
        for b in self.seq_buckets:
            if needed <= b:
                return b
        raise MXNetError("sequence needs %d cache positions, max bucket %d"
                         % (needed, self.seq_buckets[-1]))

    # ---------------------------------------------------------- builders
    def _attention_full(self, q, k, v):
        """Causal attention over one prompt: q/k/v (H, T, d)."""
        import jax.numpy as jnp
        t = q.shape[1]
        if t > self.prefill_chunk and t % self.prefill_chunk == 0:
            from ..parallel.ring_attention import chunked_causal_attention
            return chunked_causal_attention(q[None], k[None], v[None],
                                            chunk=self.prefill_chunk)[0]
        scale = 1.0 / np.sqrt(self.cfg.d_head)
        s = jnp.einsum("htd,hkd->htk", q, k,
                       preferred_element_type=jnp.float32) * scale
        pos = jnp.arange(t)
        future = (pos[None, :] > pos[:, None]).astype(jnp.float32)
        s = s + future[None] * -1e9      # the training graph's causal bias
        att = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
        att = att / jnp.sum(att, axis=-1, keepdims=True)
        return jnp.einsum("htk,hkd->htd", att, v)

    def _build_prefill(self, t_b: int):
        import jax
        import jax.numpy as jnp
        from jax import lax
        cfg = self.cfg
        int8 = self.cache.int8
        page = self.cache.page

        def write_layer(state, li, slot, k, v):
            # k/v: (H, T_b, d) -> cache block [li, slot, :, 0:T_b, :]
            if int8:
                ks, vs = state[2], state[3]
                kq, ksc = _quantize_pages(k, page)
                vq, vsc = _quantize_pages(v, page)
                return (
                    lax.dynamic_update_slice(
                        state[0], kq[None, None], (li, slot, 0, 0, 0)),
                    lax.dynamic_update_slice(
                        state[1], vq[None, None], (li, slot, 0, 0, 0)),
                    lax.dynamic_update_slice(
                        ks, ksc[None, None], (li, slot, 0, 0)),
                    lax.dynamic_update_slice(
                        vs, vsc[None, None], (li, slot, 0, 0)),
                )
            return (
                lax.dynamic_update_slice(
                    state[0], k[None, None], (li, slot, 0, 0, 0)),
                lax.dynamic_update_slice(
                    state[1], v[None, None], (li, slot, 0, 0, 0)),
            )

        def fn(params, state, tokens, slot, true_len):
            # tokens (T_b,) int32; slot, true_len scalar int32
            x = params["tok_embed_weight"][tokens] \
                + params["pos_embed_weight"][:t_b]          # (T_b, D)
            for li in range(cfg.num_layers):
                pfx = "layer%d" % li
                h = _ln(x, params[pfx + "_ln1_gamma"],
                        params[pfx + "_ln1_beta"])
                qkv = _fc(h, params, pfx + "_att_qkv")      # (T_b, 3D)
                qkv = qkv.reshape(t_b, 3, cfg.n_heads, cfg.d_head)
                q = qkv[:, 0].transpose(1, 0, 2)            # (H, T_b, d)
                k = qkv[:, 1].transpose(1, 0, 2)
                v = qkv[:, 2].transpose(1, 0, 2)
                state = write_layer(state, li, slot, k, v)
                ctx = self._attention_full(q, k, v)         # (H, T_b, d)
                ctx = ctx.transpose(1, 0, 2).reshape(t_b, cfg.d_model)
                x = x + _fc(ctx, params, pfx + "_att_proj")
                h2 = _ln(x, params[pfx + "_ln2_gamma"],
                         params[pfx + "_ln2_beta"])
                h2 = jax.nn.relu(_fc(h2, params, pfx + "_ff1"))
                x = x + _fc(h2, params, pfx + "_ff2")
            # only the last REAL token goes through the LM head
            row = lax.dynamic_slice(
                x, (jnp.maximum(true_len - 1, 0), 0), (1, cfg.d_model))
            row = _ln(row, params["final_ln_gamma"],
                      params["final_ln_beta"])
            logits = _fc(row, params, "lm_head")[0]         # (V,)
            return logits, state

        return jax.jit(fn, donate_argnums=(1,))

    def _read_bucket(self, state, li: int, s_b: int):
        """Cache slice [0:S_b] of layer ``li``, dequantized:
        (slots, H, S_b, d) f32 pair."""
        import jax.numpy as jnp
        page = self.cache.page
        k = state[0][li, :, :, :s_b, :]
        v = state[1][li, :, :, :s_b, :]
        if not self.cache.int8:
            return k, v
        pb = s_b // page
        slots, h = k.shape[0], k.shape[1]
        ks = state[2][li, :, :, :pb]
        vs = state[3][li, :, :, :pb]

        def deq(q, sc):
            f = q.astype(jnp.float32).reshape(slots, h, pb, page, -1)
            return (f * sc[..., None, None]).reshape(slots, h, s_b, -1)

        return deq(k, ks), deq(v, vs)

    def _build_decode(self, s_b: int):
        import jax
        import jax.numpy as jnp
        from jax import lax
        cfg = self.cfg
        int8 = self.cache.int8
        page = self.cache.page
        scale = 1.0 / np.sqrt(cfg.d_head)

        def write_one_f32(cache_s, kn, p):
            # cache_s (H, S, d), kn (H, d), p scalar write position
            return lax.dynamic_update_slice(cache_s, kn[:, None, :],
                                            (0, p, 0))

        def write_one_i8(cache_s, scale_s, kn, p):
            # requantize-on-write: page entry resets the scale (a fresh
            # page must not inherit a stale tenant's dynamic range);
            # in-page growth merges scales upward and requantizes the
            # page — with an unchanged scale the round-trip is exact
            h = cfg.n_heads
            pi = p // page
            off = p % page
            pg = lax.dynamic_slice(cache_s, (0, pi * page, 0),
                                   (h, page, cfg.d_head))
            old = lax.dynamic_slice(scale_s, (0, pi), (h, 1))[:, 0]
            entering = (off == 0)
            deq = jnp.where(entering, 0.0,
                            pg.astype(jnp.float32) * old[:, None, None])
            needed = jnp.maximum(
                jnp.max(jnp.abs(kn), axis=-1) / 127.0, 1e-8)    # (H,)
            new_scale = jnp.where(entering, needed,
                                  jnp.maximum(old, needed))
            deq = lax.dynamic_update_slice(deq, kn[:, None, :], (0, off, 0))
            q = jnp.clip(jnp.round(deq / new_scale[:, None, None]),
                         -127, 127).astype(jnp.int8)
            return (lax.dynamic_update_slice(cache_s, q, (0, pi * page, 0)),
                    lax.dynamic_update_slice(scale_s, new_scale[:, None],
                                             (0, pi)))

        def write_token(state, li, k_new, v_new, pos):
            # k_new/v_new (slots, H, d); pos (slots,) — vmapped over the
            # slot axis, so every sequence writes at ITS OWN position in
            # one gather-free program (empty slots write into reclaimed
            # space the next prefill overwrites: a no-op by construction)
            if int8:
                nk, nks = jax.vmap(write_one_i8)(state[0][li], state[2][li],
                                                 k_new, pos)
                nv, nvs = jax.vmap(write_one_i8)(state[1][li], state[3][li],
                                                 v_new, pos)
                return (state[0].at[li].set(nk), state[1].at[li].set(nv),
                        state[2].at[li].set(nks), state[3].at[li].set(nvs))
            nk = jax.vmap(write_one_f32)(state[0][li], k_new, pos)
            nv = jax.vmap(write_one_f32)(state[1][li], v_new, pos)
            return (state[0].at[li].set(nk), state[1].at[li].set(nv))

        def fn(params, state, tokens, pos, active):
            # tokens/pos (slots,) int32; active (slots,) bool
            pos_c = jnp.clip(pos, 0, cfg.max_seq - 1)
            x = params["tok_embed_weight"][tokens] \
                + params["pos_embed_weight"][pos_c]         # (slots, D)
            for li in range(cfg.num_layers):
                pfx = "layer%d" % li
                h = _ln(x, params[pfx + "_ln1_gamma"],
                        params[pfx + "_ln1_beta"])
                qkv = _fc(h, params, pfx + "_att_qkv")
                qkv = qkv.reshape(-1, 3, cfg.n_heads, cfg.d_head)
                q, k_new, v_new = qkv[:, 0], qkv[:, 1], qkv[:, 2]
                state = write_token(state, li, k_new, v_new, pos_c)
                kb, vb = self._read_bucket(state, li, s_b)
                s = jnp.einsum("shd,shkd->shk", q, kb,
                               preferred_element_type=jnp.float32) * scale
                # keys at 0..pos inclusive (the token just written
                # attends to itself, matching the training graph)
                mask = jnp.arange(s_b)[None, :] <= pos_c[:, None]
                s = jnp.where(mask[:, None, :], s, -1e9)
                att = jax.nn.softmax(s, axis=-1)
                ctx = jnp.einsum("shk,shkd->shd", att, vb)
                ctx = ctx.reshape(-1, cfg.d_model)
                x = x + _fc(ctx, params, pfx + "_att_proj")
                h2 = _ln(x, params[pfx + "_ln2_gamma"],
                         params[pfx + "_ln2_beta"])
                h2 = jax.nn.relu(_fc(h2, params, pfx + "_ff1"))
                x = x + _fc(h2, params, pfx + "_ff2")
            x = _ln(x, params["final_ln_gamma"], params["final_ln_beta"])
            logits = _fc(x, params, "lm_head")              # (slots, V)
            # finished/empty slots carry garbage rows; mask them so a
            # scheduler bug downstream surfaces as -inf-ish logits, not
            # a plausible token
            logits = jnp.where(active[:, None], logits, -1e30)
            return logits, state

        return jax.jit(fn, donate_argnums=(1,))

    # ---------------------------------------------------------- dispatch
    def _sig_parts(self, kind: str, bucket: int) -> Tuple:
        shapes = tuple(sorted((k, tuple(v.shape), str(v.dtype))
                              for k, v in self.params.items()))
        return ("serve", kind, bucket, self.cfg.sig(), shapes,
                self.cache.int8, self.cache.page, self.cache.max_slots,
                self.cache.max_seq, self.prefill_chunk)

    def _dispatch(self, kind: str, bucket: int, builder, args: Tuple):
        """Bucket-program dispatch under the CompileCache counter
        discipline: first arrival builds (``<name>_compile``), every
        later arrival is ``<name>_cache_hit`` — zero steady-state
        recompiles is an assertable counter delta, exactly like
        InferenceServer's stateless path."""
        from .. import aot
        sig = ("gen_" + kind, bucket)
        prog = self.compile_cache.get(sig)
        fresh = prog is None
        if fresh:
            jitted = builder(bucket)
            use_aot = (not self._multi_device and aot.enabled() is not None
                       and aot.supported())
            hit = False
            if use_aot:
                key = aot.digest(self._sig_parts(kind, bucket))
                with _obs_compiles.scope(self.name, sig):
                    prog, hit = aot.load_or_compile(
                        "serve_%s" % kind, key, jitted, *args)
                if hit:
                    # first call of a LOADED executable runs on copies
                    # of the donated cache state: a bad entry must not
                    # invalidate the live buffers (the _fused
                    # discipline). The copy happens OUTSIDE the obs
                    # scope — its incidental jit(copy) must not show up
                    # as a serve-attributed backend compile in the
                    # warm-restart drill.
                    import jax.numpy as jnp
                    args = (args[0],
                            tuple(jnp.array(a) for a in args[1])) \
                        + args[2:]
            else:
                prog = jitted
            with _obs_compiles.scope(self.name, sig) if not hit \
                    else _nullcontext():
                out = prog(*args)
            self.compile_cache.put(sig, prog)
            return out
        with _obs_compiles.scope(self.name, sig):
            out = prog(*args)
        self.compile_cache.note_success(sig)
        return out

    def prefill(self, prompt: np.ndarray, slot: int) -> np.ndarray:
        """Run one prompt through its bucket's prefill program, writing
        its K/V into ``slot``; returns the last real token's logits as
        host numpy (the fetch is the device fence)."""
        n = int(prompt.shape[0])
        t_b = self.prompt_bucket(n)
        tokens = np.zeros((t_b,), np.int32)
        tokens[:n] = np.asarray(prompt, np.int32)
        logits, new_state = self._dispatch(
            "prefill", t_b, self._build_prefill,
            (self.params, self.cache.state(), tokens,
             np.int32(slot), np.int32(n)))
        self.cache.set_state(new_state)
        return np.asarray(logits)

    def decode_step(self, tokens: np.ndarray, pos: np.ndarray,
                    active: np.ndarray) -> np.ndarray:
        """One decode step over the whole slot array; returns
        ``(slots, V)`` logits on host. ``pos[s]`` is the write position
        (current length) of slot ``s``; inactive slots pass 0/False."""
        needed = int(pos[active].max()) + 1 if active.any() else 1
        s_b = self.seq_bucket(needed)
        logits, new_state = self._dispatch(
            "decode", s_b, self._build_decode,
            (self.params, self.cache.state(),
             np.asarray(tokens, np.int32), np.asarray(pos, np.int32),
             np.asarray(active, bool)))
        self.cache.set_state(new_state)
        return np.asarray(logits)

"""Preallocated bucketed KV cache for generative decode.

The TPU-native answer to vLLM's PagedAttention allocator under the
finite-executable constraint: instead of a dynamic block table indexed
by gathers (a different program per table shape), the cache is ONE
device-resident block per layer —

    K, V: (num_layers, max_slots, n_heads, max_seq, d_head)

— preallocated at server start, so geometry never changes, every decode
step is gather-free (``lax.dynamic_update_slice`` at per-slot write
positions), and the executable universe stays |prefill buckets| +
|decode buckets|. What *is* paged is the accounting: a host-side
:class:`PageLedger` tracks per-slot sequence lengths in page-sized
chunks (``MXNET_TPU_SERVE_KV_PAGE`` tokens per page), drives the
occupancy gauges, and catches leaks/double-frees loudly — the property
test randomizes join/finish interleavings against it.

int8 mode (``MXNET_TPU_SERVE_KV_INT8``): K/V store as int8 with one f32
scale per (slot, head, page) — the quantized-paged-attention layout —
shrinking the reservation ~4x, which roughly doubles the resident
sequences a fixed ``MXNET_TPU_ANALYZE_HBM_BUDGET`` admits (the
acceptance test pins exactly 2x via :func:`max_slots_for`). Scales ride
separate planes ``(L, slots, H, n_pages)``; dequantization is a reshape
to ``(..., n_pages, page, d)`` times the broadcast scale — no gathers.

Budget audit: :meth:`KVCache.audit` runs the analyzer's
``hbm-budget`` reservation check (``analysis.memory_passes
.check_reservation``) at server start — strict mode rejects an
over-budget cache NAMING it before any device allocation; the analysis
package stays unimported while ``MXNET_TPU_ANALYZE=off`` (zero-cost
gate, same discipline as the bind-time passes).
"""
from __future__ import annotations

import math
import threading
from typing import Any, Dict, List, Optional, Tuple

from .. import lockcheck as _lockcheck
from .. import profiler as _profiler
from ..base import MXNetError

__all__ = ["KVCache", "PageLedger", "CacheFull", "max_slots_for"]


class CacheFull(MXNetError):
    """acquire() with every slot resident (callers queue, not error)."""


def max_slots_for(budget_bytes: int, num_layers: int, n_heads: int,
                  d_head: int, max_seq: int, page: int,
                  int8: bool = False) -> int:
    """Largest ``max_slots`` whose cache reservation fits the budget —
    the capacity-planning inverse of :meth:`KVCache.hbm_bytes` (the two
    are consistency-tested against each other)."""
    per_slot = 2 * num_layers * n_heads * max_seq * d_head  # K and V elems
    if int8:
        bytes_slot = per_slot * 1 \
            + 2 * num_layers * n_heads * (max_seq // page) * 4
    else:
        bytes_slot = per_slot * 4
    return max(0, int(budget_bytes) // bytes_slot)


class PageLedger:
    """Host-side page accounting for the preallocated slot array.

    Pure Python on purpose: the property test drives thousands of
    randomized acquire/grow/release interleavings against it without
    touching a device, and the occupancy gauges the server exports are
    asserted to match this model EXACTLY.

    Invariants (checked by :meth:`check`, raised on violation):
    every slot is free or resident, never both; ``pages_in_use`` equals
    the sum over resident slots of ``ceil(len / page)``; release of a
    free slot (double-free) and growth past ``max_seq`` raise.
    """

    def __init__(self, max_slots: int, max_seq: int, page: int):
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1, got %d" % max_slots)
        if max_seq % page:
            raise ValueError("max_seq %d not a multiple of page %d"
                             % (max_seq, page))
        self.max_slots = int(max_slots)
        self.max_seq = int(max_seq)
        self.page = int(page)
        self.total_pages = self.max_slots * (self.max_seq // self.page)
        self._free: List[int] = list(range(self.max_slots - 1, -1, -1))
        self._len: Dict[int, int] = {}      # resident slot -> seq length
        self._lock = _lockcheck.Lock(name="serve.kv_cache_lock")

    def _pages(self, length: int) -> int:
        return max(1, math.ceil(length / self.page))

    # ------------------------------------------------------------ lifecycle
    def acquire(self, length: int) -> Optional[int]:
        """Claim a free slot for a sequence of ``length`` tokens; None
        when every slot is resident (the scheduler keeps the request
        queued — admission pressure is load-shed at submit, not here)."""
        if not 0 < length <= self.max_seq:
            raise ValueError("sequence length %d outside (0, max_seq=%d]"
                             % (length, self.max_seq))
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop()
            self._len[slot] = int(length)
            return slot

    def grow(self, slot: int) -> int:
        """One decoded token appended to ``slot``; returns the new
        length. Raises when the slot is not resident or full."""
        with self._lock:
            if slot not in self._len:
                raise MXNetError("kv ledger: grow of non-resident slot %d"
                                 % slot)
            if self._len[slot] >= self.max_seq:
                raise MXNetError("kv ledger: slot %d already at max_seq %d"
                                 % (slot, self.max_seq))
            self._len[slot] += 1
            return self._len[slot]

    def release(self, slot: int) -> int:
        """Free ``slot``'s pages; returns the page count released.
        A release of a non-resident slot is a DOUBLE-FREE and raises —
        silent tolerance here is how allocators leak."""
        with self._lock:
            if slot not in self._len:
                raise MXNetError(
                    "kv ledger: double-free of slot %d (not resident)"
                    % slot)
            pages = self._pages(self._len.pop(slot))
            self._free.append(slot)
            return pages

    # ------------------------------------------------------------- queries
    @property
    def slots_in_use(self) -> int:
        with self._lock:
            return len(self._len)

    @property
    def pages_in_use(self) -> int:
        with self._lock:
            return sum(self._pages(n) for n in self._len.values())

    def length(self, slot: int) -> int:
        with self._lock:
            return self._len[slot]

    def lengths(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._len)

    def occupancy(self) -> float:
        return self.pages_in_use / self.total_pages

    def check(self) -> None:
        """Invariant audit (the property test calls this after every
        step): slot sets partition, page accounting is consistent."""
        with self._lock:
            free = set(self._free)
            used = set(self._len)
            if free & used:
                raise MXNetError("kv ledger: slots both free and resident: "
                                 "%s" % sorted(free & used))
            if len(free) != len(self._free):
                raise MXNetError("kv ledger: duplicate free slots")
            if free | used != set(range(self.max_slots)):
                raise MXNetError("kv ledger: lost slots: %s"
                                 % sorted(set(range(self.max_slots))
                                          - free - used))
            for slot, n in self._len.items():
                if not 0 < n <= self.max_seq:
                    raise MXNetError("kv ledger: slot %d length %d out of "
                                     "range" % (slot, n))


class KVCache:
    """The device-resident cache blocks + the ledger + the gauges.

    ``state()``/``set_state()`` expose the arrays as a flat tuple so the
    jitted prefill/decode programs take and return them as donated
    operands (double-buffer-free in-place update, the fused-step
    discipline). f32 state is ``(k, v)``; int8 adds the scale planes:
    ``(k, v, k_scale, v_scale)``.
    """

    def __init__(self, num_layers: int, n_heads: int, d_head: int,
                 max_slots: int, max_seq: int, page: Optional[int] = None,
                 int8: Optional[bool] = None, name: str = "serve",
                 mesh=None, layout=None):
        from .. import config as _config
        import jax.numpy as jnp
        self.page = int(page if page is not None
                        else _config.get("MXNET_TPU_SERVE_KV_PAGE"))
        self.int8 = bool(_config.get("MXNET_TPU_SERVE_KV_INT8")
                         if int8 is None else int8)
        self.num_layers = int(num_layers)
        self.n_heads = int(n_heads)
        self.d_head = int(d_head)
        self.max_slots = int(max_slots)
        self.max_seq = int(max_seq)
        self.name = name
        self.ledger = PageLedger(self.max_slots, self.max_seq, self.page)
        self.n_pages = self.max_seq // self.page
        shape = (self.num_layers, self.max_slots, self.n_heads,
                 self.max_seq, self.d_head)
        sshape = (self.num_layers, self.max_slots, self.n_heads,
                  self.n_pages)
        self._sharding = self._resolve_sharding(mesh, layout)
        kv_dtype = jnp.int8 if self.int8 else jnp.float32
        self.k = self._place(jnp.zeros(shape, kv_dtype), "kv_cache")
        self.v = self._place(jnp.zeros(shape, kv_dtype), "kv_cache")
        if self.int8:
            # scales start at 1: dequantizing an untouched (zero) page
            # stays zero, and the requantize-on-write max() never sees 0
            self.k_scale = self._place(jnp.ones(sshape, jnp.float32),
                                       "kv_scale")
            self.v_scale = self._place(jnp.ones(sshape, jnp.float32),
                                       "kv_scale")
        else:
            self.k_scale = self.v_scale = None
        self._update_gauges()

    # ---------------------------------------------------------- sharding
    def _resolve_sharding(self, mesh, layout):
        if mesh is None:
            return None
        from jax.sharding import NamedSharding
        from ..parallel.layout import island_specs
        specs = island_specs("serve", layout)
        # leading layer axis prepends to the per-layer claim
        def lift(spec):
            from jax.sharding import PartitionSpec as P
            return P(None, *spec)
        return {
            "kv_cache": NamedSharding(mesh, lift(specs["kv_cache"])),
            "kv_scale": NamedSharding(mesh, lift(specs["kv_scale"])),
        }

    def _place(self, arr, kind: str):
        if self._sharding is None:
            return arr
        import jax
        return jax.device_put(arr, self._sharding[kind])

    # ------------------------------------------------------------- state
    def state(self) -> Tuple:
        if self.int8:
            return (self.k, self.v, self.k_scale, self.v_scale)
        return (self.k, self.v)

    def set_state(self, state: Tuple) -> None:
        if self.int8:
            self.k, self.v, self.k_scale, self.v_scale = state
        else:
            self.k, self.v = state

    def hbm_bytes(self) -> int:
        """The reservation's device footprint (K + V + scale planes)."""
        n = sum(int(a.size) * a.dtype.itemsize for a in self.state())
        return n

    # ----------------------------------------------------------- lifecycle
    def acquire(self, length: int) -> Optional[int]:
        slot = self.ledger.acquire(length)
        if slot is not None:
            self._update_gauges()
        return slot

    def grow(self, slot: int) -> int:
        n = self.ledger.grow(slot)
        self._update_gauges()
        return n

    def release(self, slot: int) -> int:
        pages = self.ledger.release(slot)
        self._update_gauges()
        return pages

    def _update_gauges(self) -> None:
        _profiler.set_gauge(self.name + "_kv_slots_in_use",
                            self.ledger.slots_in_use)
        _profiler.set_gauge(self.name + "_kv_pages_in_use",
                            self.ledger.pages_in_use)
        _profiler.set_gauge(self.name + "_kv_occupancy",
                            self.ledger.occupancy())

    # --------------------------------------------------------------- audit
    def audit(self) -> Dict[str, Any]:
        """hbm-budget audit of the reservation at server start. The
        analysis package is imported ONLY when the analyze knob is on —
        the zero-cost gate the CI job asserts."""
        from .. import config as _config
        if _config.get("MXNET_TPU_ANALYZE") == "off":
            return {"budget_bytes": 0, "reserved_bytes": self.hbm_bytes(),
                    "fits": True}
        from ..analysis.memory_passes import check_reservation
        detail = ("serve KV cache %s: %d layers x %d slots x %d heads x "
                  "%d seq x %d d_head, %s"
                  % (self.name, self.num_layers, self.max_slots,
                     self.n_heads, self.max_seq,
                     self.d_head, "int8+scales" if self.int8 else "f32"))
        return check_reservation("%s_kv_cache" % self.name,
                                 self.hbm_bytes(), detail=detail)

"""Serving observability: latency distribution + throughput accounting.

Latencies land in the shared bounded histogram primitive
(:class:`mxnet_tpu.profiler.Histogram` — fixed log-spaced buckets,
factor ``2^0.25`` so quantile estimates stay within one bucket (≤19%) of
the exact order statistic, parity-tested against ``numpy.percentile`` in
``tests/test_obs.py``). That replaces the previous private sample ring:
memory is O(buckets) at any request volume, ``record`` is O(log buckets)
under a per-histogram lock, and because the histogram lives in the
profiler registry under ``<server name>_latency_seconds`` it shows up in
the Prometheus exposition (``mx.obs.render_prometheus()`` / the serve
``/metrics`` endpoint) for free — same-name servers aggregate, exactly
like the ``<name>_*`` serve counters always have.

Percentiles are computed on snapshot, not on record — the submit path
stays O(1)-ish under the lock. ``reset()`` drops the accumulated
distribution (e.g. after warmup, so compile-time latencies don't pollute
steady-state percentiles); unlike the old fixed-capacity ring there is
no sliding window, so long-lived servers should reset at rollup
boundaries if they want recent-behavior percentiles.
"""
from __future__ import annotations

import time
from typing import Dict, Optional

from .. import profiler as _profiler

__all__ = ["LatencyStats", "DecodeLatencyStats"]


class LatencyStats:
    """Thread-safe latency distribution (seconds) over the shared
    registry histogram ``name``."""

    def __init__(self, capacity: int = 4096,
                 name: str = "serve_latency_seconds"):
        # ``capacity`` survives for API compatibility with the old
        # sample-ring; boundedness now comes from the fixed bucket grid
        self.capacity = int(capacity)
        self.name = name
        self._hist = _profiler.histogram(name)

    def record(self, seconds: float) -> None:
        self._hist.observe(seconds)

    @property
    def count(self) -> int:
        return self._hist.count

    def reset(self) -> None:
        """Drop the retained distribution (e.g. after warmup, so
        compile-time latencies don't pollute steady-state percentiles)."""
        self._hist.reset()

    def snapshot(self) -> Optional[Dict[str, float]]:
        """{p50, p95, p99, mean, max, window} in milliseconds since the
        last reset; None before the first request."""
        snap = self._hist.snapshot()
        n = snap["count"]
        if n == 0:
            return None
        p50, p95, p99 = (
            _profiler._snapshot_quantile(snap, q)
            for q in (0.50, 0.95, 0.99))
        return {
            "p50_ms": round(float(p50) * 1e3, 4),
            "p95_ms": round(float(p95) * 1e3, 4),
            "p99_ms": round(float(p99) * 1e3, 4),
            "mean_ms": round(snap["sum"] / n * 1e3, 4),
            "max_ms": round(float(snap["max"]) * 1e3, 4),
            "window": int(n),
        }


class DecodeLatencyStats:
    """The generative-serving latency pair: time-to-first-token and
    time-per-output-token, each a :class:`LatencyStats` over its own
    registry histogram (``<name>_ttft_seconds`` / ``<name>_tpot_seconds``
    — the Prometheus exposition picks both up for free, same as the
    batch server's ``_latency_seconds``).

    TTFT spans submit → first streamed token (queueing + prefill +
    first sample); TPOT is the inter-token gap inside steady-state
    decode — the pair is the standard decomposition because continuous
    batching trades them off (admitting a join costs resident
    sequences one prefill of TPOT).
    """

    def __init__(self, name: str = "serve"):
        self.name = name
        self.ttft = LatencyStats(name=name + "_ttft_seconds")
        self.tpot = LatencyStats(name=name + "_tpot_seconds")

    def reset(self) -> None:
        self.ttft.reset()
        self.tpot.reset()

    def snapshot(self) -> Dict[str, Optional[Dict[str, float]]]:
        """{"ttft": ..., "tpot": ...} — each side a LatencyStats
        snapshot (or None before its first sample)."""
        return {"ttft": self.ttft.snapshot(), "tpot": self.tpot.snapshot()}


def monotonic() -> float:
    """The one clock every serve timestamp uses (monotonic: deadlines
    must survive wall-clock steps)."""
    return time.monotonic()

"""Serving observability: latency distribution + throughput accounting.

Latencies land in a bounded ring (recent-window reservoir, the same
bounded-memory discipline as CompileCache) so a long-lived server's
``stats()`` reflects current behavior, not its lifetime average, and
memory stays O(capacity) at any request volume. Percentiles are computed
on snapshot, not on record — the submit path stays O(1) under the lock.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import numpy as np

__all__ = ["LatencyStats"]


class LatencyStats:
    """Thread-safe bounded reservoir of per-request latencies (seconds)."""

    def __init__(self, capacity: int = 4096):
        self.capacity = int(capacity)
        self._ring = np.zeros(self.capacity, np.float64)
        self._n = 0            # total recorded (monotonic)
        self._lock = threading.Lock()

    def record(self, seconds: float) -> None:
        with self._lock:
            self._ring[self._n % self.capacity] = seconds
            self._n += 1

    @property
    def count(self) -> int:
        return self._n

    def reset(self) -> None:
        """Drop the retained window (e.g. after warmup, so compile-time
        latencies don't pollute steady-state percentiles)."""
        with self._lock:
            self._n = 0

    def snapshot(self) -> Optional[Dict[str, float]]:
        """{p50, p95, p99, mean, max, window} in milliseconds over the
        retained window; None before the first request."""
        with self._lock:
            n = min(self._n, self.capacity)
            if n == 0:
                return None
            window = self._ring[:n].copy()
        p50, p95, p99 = np.percentile(window, [50.0, 95.0, 99.0])
        return {
            "p50_ms": round(float(p50) * 1e3, 4),
            "p95_ms": round(float(p95) * 1e3, 4),
            "p99_ms": round(float(p99) * 1e3, 4),
            "mean_ms": round(float(window.mean()) * 1e3, 4),
            "max_ms": round(float(window.max()) * 1e3, 4),
            "window": int(n),
        }


def monotonic() -> float:
    """The one clock every serve timestamp uses (monotonic: deadlines
    must survive wall-clock steps)."""
    return time.monotonic()

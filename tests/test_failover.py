"""Leader fail-over (ISSUE 12): the probe ring, the re-hostable PodKV
control plane, deterministic election, partition adjudication, the
dist.kv fault site's bounded retry, the successor finalize/abort of a
mid-commit-orphaned pod save, and the heartbeat/monotonic-clock edge
cases the liveness math must honor.

The end-to-end 3-host drills (leader-kill, cascade, coordsvc) live in
tools/pod_smoke.py (CI ``multihost`` job); these are the unit-level
contracts every piece keeps on its own.
"""
import json
import os
import time
import zlib

import numpy as np
import pytest

from mxnet_tpu import config as mx_config
from mxnet_tpu import faults, profiler
from mxnet_tpu.parallel import dist
from mxnet_tpu.checkpoint import format as ckpt_format
from mxnet_tpu.checkpoint import (finalize_staged_pod_saves,
                                  list_checkpoints, load_latest,
                                  read_checkpoint)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_dist_state():
    dist.reset_liveness()
    yield
    dist.heartbeat_stop()
    dist.set_kv_backend(None)
    dist.reset_liveness()
    faults.clear()


# ------------------------------------------------------------ probe ring

def test_probe_ring_statuses():
    ring = dist.ProbeRing()
    try:
        assert dist.probe_peer("127.0.0.1:%d" % ring.port,
                               timeout=2.0) == "live"
    finally:
        ring.stop()
    time.sleep(0.05)
    # the listener is gone but the machine answers: POSITIVELY dead
    assert dist.probe_peer("127.0.0.1:%d" % ring.port,
                           timeout=2.0) == "dead"
    # no route / timeout: ambiguous — dead host and partition look alike
    assert dist.probe_peer("10.255.255.1:19999", timeout=0.2) \
        == "unreachable"
    # an unpublished port can never be probed
    assert dist.probe_peer(None) == "unreachable"
    assert dist.probe_peer("h:0") == "unreachable"


def test_probe_rejects_recycled_port():
    """A foreign service answering the probe port is NOT our
    coordinator: a wrong banner reads as dead, not live."""
    srv = dist.PodKVServer()      # speaks KV, not the probe magic
    try:
        assert dist.probe_peer("127.0.0.1:%d" % srv.port,
                               timeout=2.0) == "dead"
    finally:
        srv.stop()


def test_elect_leader_is_lowest_live():
    assert dist.elect_leader([2, 1, 5]) == 1
    assert dist.elect_leader({3}) == 3


# -------------------------------------------------------- PodKV service

def test_podkv_set_get_and_blocking_wait():
    import threading
    srv = dist.PodKVServer()
    cli = dist.PodKVClient("127.0.0.1:%d" % srv.port)
    try:
        assert cli.ping(2.0)
        cli.set("mxpod/k", json.dumps({"a": 1}))
        assert json.loads(cli.get("mxpod/k", 500)) == {"a": 1}
        assert cli.get("absent", 200) is None
        got = []
        t = threading.Thread(
            target=lambda: got.append(cli.get("later", 5000)))
        t.start()
        time.sleep(0.2)
        cli.set("later", "v")
        t.join(10.0)
        assert got == ["v"]
    finally:
        srv.stop()
    time.sleep(0.05)
    # a dead server: GET degrades to None (reads as a dead rank), SET
    # raises (the caller's bounded retry owns the policy)
    assert cli.get("mxpod/k", 200) is None
    with pytest.raises(OSError):
        cli.set("x", "y")


def test_podkv_backend_drives_heartbeats_and_dead_ranks():
    srv = dist.PodKVServer()
    cli = dist.PodKVClient("127.0.0.1:%d" % srv.port)
    try:
        dist.set_kv_backend(cli)
        assert dist.heartbeat_start(period=0.05, as_rank=3)
        deadline = time.monotonic() + 5.0
        while dist.dead_ranks(stale_after=10.0, ranks=[3]) == [3]:
            assert time.monotonic() < deadline, "beat never landed"
            time.sleep(0.05)
        # an unknown rank never beat: dead immediately
        assert dist.dead_ranks(stale_after=10.0, ranks=[3, 9]) == [9]
    finally:
        dist.heartbeat_stop()
        srv.stop()
        dist.set_kv_backend(None)


# ---------------------------------------------------- dist.kv fault site

class _RecordingKV(object):
    def __init__(self):
        self.sets = []
        self.store = {}

    def set(self, key, value):
        self.sets.append(key)
        self.store[key] = value

    def get(self, key, timeout_ms):
        return self.store.get(key)


def test_kv_set_retries_injected_flake_then_succeeds():
    """The satellite contract: bounded-retry on KV flakes is PROVABLE —
    one injected EINTR costs exactly one dist_kv_retry and the write
    still lands."""
    backend = _RecordingKV()
    dist.set_kv_backend(backend)
    base = profiler.get_counter("dist_kv_retry")
    faults.install("dist.kv@1:eintr")
    dist.kv_set("k", "v")
    assert backend.store["k"] == "v"
    assert profiler.get_counter("dist_kv_retry") == base + 1


def test_kv_get_retries_injected_flake_then_succeeds():
    backend = _RecordingKV()
    backend.store["k"] = "v"
    dist.set_kv_backend(backend)
    base = profiler.get_counter("dist_kv_retry")
    faults.install("dist.kv@1:raise")
    assert dist.kv_get("k", 100) == "v"
    assert profiler.get_counter("dist_kv_retry") == base + 1


def test_kv_flake_budget_is_bounded(monkeypatch):
    """A persistent flake exhausts MXNET_TPU_KV_RETRIES and propagates —
    never an unbounded retry loop."""
    monkeypatch.setenv("MXNET_TPU_KV_RETRIES", "2")
    backend = _RecordingKV()
    dist.set_kv_backend(backend)
    base = profiler.get_counter("dist_kv_retry")
    faults.install("dist.kv:raise")          # EVERY arrival flakes
    with pytest.raises(faults.FaultInjected):
        dist.kv_set("k", "v")
    assert profiler.get_counter("dist_kv_retry") == base + 2
    assert backend.sets == []               # the write never went through


def test_kv_get_absent_key_is_not_a_flake():
    backend = _RecordingKV()
    dist.set_kv_backend(backend)
    base = profiler.get_counter("dist_kv_retry")
    assert dist.kv_get("absent", 50) is None
    assert profiler.get_counter("dist_kv_retry") == base


# ------------------------------------------------- partition adjudication

def _coordinator(monkeypatch, rank, world):
    from mxnet_tpu.elastic import PodCoordinator
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", "9999")
    monkeypatch.setenv("DMLC_NUM_WORKER", str(world))
    monkeypatch.setenv("DMLC_WORKER_ID", str(rank))
    return PodCoordinator(["true"], stale_after=0.5,
                          rendezvous_window=0.5)


def _patch_probes(monkeypatch, statuses):
    """statuses: probe address -> live|dead|unreachable."""
    monkeypatch.setattr(
        dist, "probe_peer",
        lambda addr, timeout=None: statuses.get(addr, "unreachable"))


def test_adjudicate_majority_recovers_in_place(monkeypatch):
    """The satellite fix: dead_ranks() reporting EVERY member must no
    longer read as "I am partitioned" when the probe ring shows a
    healthy majority — the pod fails over instead of dying."""
    coord = _coordinator(monkeypatch, 1, 3)
    coord.peer_info = {0: {"host": "h0", "probe": 70},
                       1: {"host": "h1", "probe": 71},
                       2: {"host": "h2", "probe": 72}}
    _patch_probes(monkeypatch, {"h0:70": "dead", "h2:72": "live"})
    assert coord._adjudicate([0, 1, 2]) == "leader-lost"
    assert coord._failover_live == [1, 2]


def test_adjudicate_minority_partition_exits(monkeypatch):
    """...and a true minority partition (peers unreachable, not
    positively dead) still drains for a job restart."""
    coord = _coordinator(monkeypatch, 1, 3)
    coord.peer_info = {0: {"host": "h0", "probe": 70},
                       1: {"host": "h1", "probe": 71},
                       2: {"host": "h2", "probe": 72}}
    _patch_probes(monkeypatch, {})           # everything times out
    assert coord._adjudicate([0, 1, 2]) == "control-plane-lost"


def test_adjudicate_confirmed_dead_shrinks_electorate(monkeypatch):
    """The cascade shape: a 2-member pod whose leader is POSITIVELY
    dead (connection refused) leaves a 1-member electorate — the lone
    survivor may continue at world 1. An UNREACHABLE leader (could be
    a partition) must not."""
    coord = _coordinator(monkeypatch, 2, 3)
    coord.members = [1, 2]
    coord.peer_info = {1: {"host": "h1", "probe": 71},
                       2: {"host": "h2", "probe": 72}}
    _patch_probes(monkeypatch, {"h1:71": "dead"})
    assert coord._adjudicate([1, 2]) == "leader-lost"
    assert coord._failover_live == [2]
    _patch_probes(monkeypatch, {"h1:71": "unreachable"})
    assert coord._adjudicate([1, 2]) == "control-plane-lost"


def test_failover_rehosts_on_elected_survivor(monkeypatch):
    """A real (single-process) fail-over: the elected leader binds its
    published fail-over port, heartbeats restart on the new control
    plane, membership shrinks to the survivors, and the counters/gauge
    record the election."""
    coord = _coordinator(monkeypatch, 1, 3)
    port = dist.free_port()
    coord.peer_info = {1: {"host": "127.0.0.1", "probe": 0,
                           "failover": port}}
    coord._failover_live = [1]
    base = profiler.get_counter("elastic_leader_failover")
    try:
        assert coord._failover()
        assert coord.members == [1]
        assert coord.leader == 1
        assert coord.cp_addr == "127.0.0.1:%d" % port
        assert coord.leader_failovers == 1
        assert profiler.get_counter("elastic_leader_failover") == base + 1
        # the re-hosted control plane is real: a fresh client talks to it
        cli = dist.PodKVClient(coord.cp_addr)
        assert cli.ping(2.0)
        # ...and our own heartbeat landed under the ORIGINAL pod rank
        deadline = time.monotonic() + 5.0
        while cli.get("mxnet_hb/1", 200) is None:
            assert time.monotonic() < deadline
            time.sleep(0.05)
    finally:
        dist.heartbeat_stop()
        if coord._kv_server is not None:
            coord._kv_server.stop()
        dist.set_kv_backend(None)


def test_failover_fails_legibly_when_new_leader_never_comes_up(
        monkeypatch):
    """A follower whose elected leader dies mid-fail-over must give up
    within the bootstrap window (→ exit 1 for a job restart), never
    hang."""
    coord = _coordinator(monkeypatch, 2, 3)
    coord.bootstrap_timeout = 1.0
    coord.peer_info = {1: {"host": "127.0.0.1", "probe": 0,
                           "failover": dist.free_port()}}
    coord._failover_live = [1, 2]
    assert not coord._failover()


def test_rendezvous_publishes_peer_info(monkeypatch):
    """The generation record carries each member's host, probe port and
    fail-over port — everything a later election needs with the control
    plane dark."""
    store = {}
    monkeypatch.setattr(dist, "kv_set",
                        lambda k, v: store.__setitem__(k, v))
    monkeypatch.setattr(dist, "kv_get",
                        lambda k, timeout_ms: store.get(k))
    monkeypatch.setattr(dist, "dead_ranks", lambda **kw: [])
    coord = _coordinator(monkeypatch, 0, 2)
    store["mxpod/g0/join/1"] = json.dumps(
        {"host": "h1", "probe": 71, "failover": 81})
    rec = coord._rendezvous(0)
    assert rec["ranks"] == [0, 1]
    assert rec["peers"]["1"] == {"host": "h1", "probe": 71,
                                 "failover": 81}
    join0 = json.loads(store["mxpod/g0/join/0"])
    assert set(join0) == {"host", "probe", "failover"}
    assert coord.peer_info[1]["failover"] == 81
    assert coord.leader == 0


# ------------------------------------- successor finalize / abort matrix

def _crc(arr):
    return zlib.crc32(memoryview(np.ascontiguousarray(arr)).cast("B")) \
        & 0xFFFFFFFF


def _stage_pod_save(base, step, gen, ranks, world, w, meta=None):
    """Hand-build a pod staging dir the way _write_checkpoint_pod leaves
    it when the leader dies mid-commit: per-rank arrays + fsynced
    record files, NO manifest."""
    tmp = os.path.join(base, ".tmp-ckpt-%010d.pod.g%s" % (step, gen))
    os.makedirs(tmp, exist_ok=True)
    for r in ranks:
        piece = w[r:r + 1]
        fname = "arrays-p%d.npz" % r
        with open(os.path.join(tmp, fname), "wb") as f:
            np.savez(f, **{"w@p%d.s0" % r: piece})
        rec = {"file": fname, "process_index": r, "world_size": world,
               "size": os.path.getsize(os.path.join(tmp, fname)),
               "arrays": {"w@p%d.s0" % r: {
                   "shape": list(piece.shape), "dtype": str(piece.dtype),
                   "crc32": _crc(piece), "nbytes": int(piece.nbytes)}},
               "tensors": {"w": {
                   "kind": "sharded", "shape": list(w.shape),
                   "dtype": str(w.dtype), "mesh": {"data": world},
                   "spec": "('data',)",
                   "shards": [{"key": "w@p%d.s0" % r,
                               "index": [[r, r + 1], None],
                               "process_index": r}]}},
               "meta": meta or {}}
        with open(os.path.join(tmp, "record-p%d.json" % r), "w") as f:
            json.dump(rec, f)
    return tmp


def test_successor_finalizes_complete_staging(tmp_path):
    """Ordering (a): the leader died AFTER every shard record was
    published — the successor commits exactly the manifest the leader
    would have, provenance-tagged, and load_latest sees it."""
    w = np.arange(8, dtype=np.float32).reshape(2, 4)
    meta = {"step": 5, "loop": {"epoch": 2, "batches_done": 4}}
    _stage_pod_save(str(tmp_path), 5, "1", [0, 1], 2, w, meta=meta)
    out = finalize_staged_pod_saves(str(tmp_path), by_rank=1)
    assert len(out) == 1 and out[0].endswith("ckpt-0000000005")
    path, tensors, man = load_latest(str(tmp_path))
    np.testing.assert_array_equal(tensors["w"], w)
    assert man["meta"]["loop"] == {"epoch": 2, "batches_done": 4}
    assert man["meta"]["pod_commit"] == {"committed_by": 1,
                                         "path": "successor", "gen": "1"}
    # idempotent: a second audit finds nothing left to do
    assert finalize_staged_pod_saves(str(tmp_path)) == []


def test_successor_aborts_incomplete_staging(tmp_path):
    """Ordering (b): the leader died BEFORE its own record landed — the
    successor must NOT commit (rank 0's windows would be missing) and
    must leave the staging dir for GC; load_latest never sees a torn
    manifest."""
    w = np.arange(8, dtype=np.float32).reshape(2, 4)
    ckpt_format.write_checkpoint(str(tmp_path), 4, {"w": w})
    tmp = _stage_pod_save(str(tmp_path), 5, "1", [1], 2, w)
    assert finalize_staged_pod_saves(str(tmp_path)) == []
    assert os.path.isdir(tmp)                  # left for GC
    path, _t, _m = load_latest(str(tmp_path))
    assert path.endswith("ckpt-0000000004")    # fell back, not torn
    assert [s for s, _p in list_checkpoints(str(tmp_path))] == [4]


def test_successor_aborts_size_mismatched_shard(tmp_path):
    w = np.arange(8, dtype=np.float32).reshape(2, 4)
    tmp = _stage_pod_save(str(tmp_path), 6, "1", [0, 1], 2, w)
    with open(os.path.join(tmp, "arrays-p1.npz"), "ab") as f:
        f.write(b"junk")                      # size no longer matches
    assert finalize_staged_pod_saves(str(tmp_path)) == []
    assert os.path.isdir(tmp)


def test_successor_skips_current_generation(tmp_path, monkeypatch):
    """A staging dir of the CURRENT generation may be a live save in
    flight: the audit must not race the real commit."""
    w = np.arange(8, dtype=np.float32).reshape(2, 4)
    tmp = _stage_pod_save(str(tmp_path), 7, "3", [0, 1], 2, w)
    monkeypatch.setenv("MXNET_TPU_POD_GEN", "3")
    assert finalize_staged_pod_saves(str(tmp_path)) == []
    assert os.path.isdir(tmp)
    monkeypatch.setenv("MXNET_TPU_POD_GEN", "4")
    assert len(finalize_staged_pod_saves(str(tmp_path))) == 1


def test_finalized_checkpoint_reads_like_any_other(tmp_path):
    w = np.arange(8, dtype=np.float32).reshape(2, 4)
    _stage_pod_save(str(tmp_path), 8, "2", [0, 1], 2, w)
    finalize_staged_pod_saves(str(tmp_path))
    path = os.path.join(str(tmp_path), "ckpt-0000000008")
    assert ckpt_format.probe_valid(path)
    tensors, man = read_checkpoint(path)
    np.testing.assert_array_equal(tensors["w"], w)
    assert man["world_size"] == 2


# ------------------------------------------------ heartbeat edge cases

class _FakeClient(object):
    def __init__(self):
        self.store = {}

    def key_value_set(self, key, value, allow_overwrite=False):
        self.store[key] = value

    def key_value_delete(self, key):
        self.store.pop(key, None)

    def blocking_key_value_get(self, key, timeout_ms):
        if key not in self.store:
            raise KeyError(key)
        return self.store[key]


@pytest.fixture()
def fake_pod(monkeypatch):
    client = _FakeClient()
    monkeypatch.setattr(dist, "_client", lambda: client)
    monkeypatch.setattr(dist, "num_workers", lambda: 2)
    monkeypatch.setattr(dist, "rank", lambda: 0)
    return client


def test_deadline_expiry_exactly_at_boundary(fake_pod, monkeypatch):
    """Staleness is STRICT: a counter frozen for exactly stale_after
    seconds is still live (the deadline has not *passed*); one tick more
    and it is dead. The two-observation rule holds throughout."""
    now = [50.0]
    monkeypatch.setattr("time.monotonic", lambda: now[0])
    fake_pod.store["mxnet_hb/0"] = "3"
    fake_pod.store["mxnet_hb/1"] = "3"
    assert dist.dead_ranks(stale_after=5.0, timeout_ms=10) == []
    now[0] += 5.0                      # EXACTLY the deadline
    fake_pod.store["mxnet_hb/0"] = "4"
    assert dist.dead_ranks(stale_after=5.0, timeout_ms=10) == []
    now[0] += 0.001                    # past it
    fake_pod.store["mxnet_hb/0"] = "5"
    assert dist.dead_ranks(stale_after=5.0, timeout_ms=10) == [1]


def test_rejoin_racing_the_deadline(fake_pod, monkeypatch):
    """A beat that advances in the same observation where the deadline
    would have expired wins: the rank is live and the staleness window
    re-arms from this observation."""
    now = [10.0]
    monkeypatch.setattr("time.monotonic", lambda: now[0])
    fake_pod.store["mxnet_hb/0"] = "1"
    fake_pod.store["mxnet_hb/1"] = "7"
    assert dist.dead_ranks(stale_after=5.0, timeout_ms=10) == []
    now[0] += 6.0                      # deadline passed...
    fake_pod.store["mxnet_hb/0"] = "2"
    fake_pod.store["mxnet_hb/1"] = "8"     # ...but the beat advanced
    assert dist.dead_ranks(stale_after=5.0, timeout_ms=10) == []
    now[0] += 6.0                      # frozen from HERE: dead now
    fake_pod.store["mxnet_hb/0"] = "3"
    assert dist.dead_ranks(stale_after=5.0, timeout_ms=10) == [1]


def test_liveness_never_reads_the_wall_clock(fake_pod, monkeypatch):
    """An NTP step must not expire deadlines or resurrect corpses: the
    liveness math may only read time.monotonic(). time.time() is booby-
    trapped for the duration."""
    def _bomb():
        raise AssertionError("liveness math read the wall clock")

    monkeypatch.setattr("time.time", _bomb)
    fake_pod.store["mxnet_hb/0"] = "1"
    fake_pod.store["mxnet_hb/1"] = "1"
    assert dist.dead_ranks(stale_after=5.0, timeout_ms=10) == []
    dist.reset_liveness()


def test_wall_clock_lint_holds_over_liveness_modules():
    """The satellite wiring: the existing wall-clock lint rule runs over
    parallel/dist.py + elastic.py — every deadline there must be
    monotonic (the stall watchdog's st_mtime comparison carries an
    explicit, justified allow)."""
    from mxnet_tpu.analysis.lint import lint_paths
    report = lint_paths([
        os.path.join(REPO, "mxnet_tpu", "parallel", "dist.py"),
        os.path.join(REPO, "mxnet_tpu", "elastic.py"),
    ])
    wall = [f for f in report.findings if f.code == "wall-clock"]
    assert not wall, ["%s:%s %s" % (f.path, f.line, f.message)
                      for f in wall]

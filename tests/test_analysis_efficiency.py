"""Efficiency auditor (ISSUE 8): memory/remat, sharding/comm, roofline.

Coverage contract (acceptance criteria):

* every new pass has a fires/stays-silent pair (over-budget vs fits,
  resharding thrash vs clean TP layout, replicated-param vs
  FSDP-sharded, signal-unsafe handler vs flag-only handler);
* the zoo transformer's remat report's top suggestion, applied as a
  ``jax.checkpoint`` policy, measurably reduces the program's analyzed
  peak activation memory (``analyze_program_memory``);
* the TP mesh module's audit reports per-axis comm bytes matching a
  hand-computed value for a known collective (the Megatron fc2
  all-reduce);
* strict mode rejects an over-HBM-budget bind with a finding naming the
  offending arrays;
* the grouped/depthwise-conv and pooling FLOP rules parity-test against
  closed forms;
* a model-zoo audit run (MLP, resnet8, transformer, TP mesh module)
  produces zero ERROR findings and non-empty remat/comm reports.

The ``MXNET_TPU_ANALYZE=off`` zero-import gate lives in
``tests/test_analysis.py::test_analyze_off_is_zero_cost`` and now covers
the new pass families for free (they are part of the same package).
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.analysis import (Severity, analyze_collectives,
                                analyze_module_sharding,
                                analyze_program_memory, analyze_symbol,
                                check_islands, check_replicated,
                                check_specs, lint_source, parse_bytes,
                                roofline, stale_baseline, write_baseline,
                                load_baseline)
from mxnet_tpu.parallel import P, make_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

needs_8_devices = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh")


def codes(report, code=None):
    if code is None:
        return [f.code for f in report]
    return [f for f in report if f.code == code]


def _transformer():
    from mxnet_tpu.models import transformer
    net = transformer.get_symbol(vocab_size=128, num_layers=2,
                                 d_model=32, n_heads=2, seq_len=16)
    return net, {"data": (2, 16), "softmax_label": (2, 16)}


def _tp_module():
    data = sym.Variable("data")
    h = sym.FullyConnected(data, num_hidden=32, name="fc1")
    h = sym.Activation(h, act_type="tanh")
    h = sym.FullyConnected(h, num_hidden=2, name="fc2")
    net = sym.SoftmaxOutput(h, name="softmax")
    mod = mx.mod.Module(net, context=[mx.cpu(i) for i in range(8)],
                        mesh_shape={"data": 2, "model": 4},
                        param_shardings={"fc1_weight": P("model", None),
                                         "fc1_bias": P("model"),
                                         "fc2_weight": P(None, "model")})
    mod.bind(data_shapes=[("data", (64, 6))],
             label_shapes=[("softmax_label", (64,))])
    mod.init_params(mx.init.Uniform(0.01))
    return mod


# ================================================== cost-model satellites


def test_pooling_flops_closed_form():
    """max pool: one compare per window element per output element; the
    old per-element fallback undercounted by prod(kernel)."""
    d = sym.Variable("data")
    net = sym.Pooling(d, kernel=(3, 3), pool_type="max", name="pool")
    report = analyze_symbol(net, input_shapes={"data": (2, 4, 16, 16)})
    assert not report.errors
    out_elems = 2 * 4 * 14 * 14
    assert report.extras["cost"]["flops"] == out_elems * 9


def test_avg_pooling_adds_divide():
    d = sym.Variable("data")
    net = sym.Pooling(d, kernel=(2, 2), pool_type="avg", name="pool")
    report = analyze_symbol(net, input_shapes={"data": (2, 4, 8, 8)})
    out_elems = 2 * 4 * 7 * 7
    assert report.extras["cost"]["flops"] == out_elems * 4 + out_elems


def test_global_pooling_uses_input_window():
    d = sym.Variable("data")
    net = sym.Pooling(d, global_pool=True, kernel=(1, 1),
                      pool_type="max", name="pool")
    report = analyze_symbol(net, input_shapes={"data": (2, 4, 8, 8)})
    assert report.extras["cost"]["flops"] == 2 * 4 * (8 * 8)


def test_grouped_conv_flops_closed_form():
    """grouped conv weight is (nf, cin/g, *k): 2 * out * cin/g * k*k."""
    d = sym.Variable("data")
    net = sym.Convolution(d, num_filter=8, kernel=(3, 3), num_group=4,
                          no_bias=True, name="conv")
    report = analyze_symbol(net, input_shapes={"data": (2, 8, 8, 8)})
    assert not report.errors
    out_elems = 2 * 8 * 6 * 6
    assert report.extras["cost"]["flops"] == 2 * out_elems * (8 // 4) * 9


def test_depthwise_conv_flops_closed_form():
    d = sym.Variable("data")
    net = sym.Convolution(d, num_filter=8, kernel=(3, 3), num_group=8,
                          no_bias=True, name="conv")
    report = analyze_symbol(net, input_shapes={"data": (2, 8, 8, 8)})
    out_elems = 2 * 8 * 6 * 6
    assert report.extras["cost"]["flops"] == 2 * out_elems * 1 * 9


def test_deconv_flops_use_cin_not_nf():
    """Deconvolution weight is (cin, nf/g, *k): the contraction depth is
    cin/g — pricing through w[1:] would charge nf/g instead."""
    d = sym.Variable("data")
    net = sym.Deconvolution(d, num_filter=6, kernel=(3, 3), no_bias=True,
                            name="deconv")
    report = analyze_symbol(net, input_shapes={"data": (2, 4, 8, 8)})
    assert not report.errors
    out_elems = 2 * 6 * 10 * 10
    assert report.extras["cost"]["flops"] == 2 * out_elems * 4 * 9


# ========================================================= memory passes


def test_parse_bytes_suffixes():
    assert parse_bytes("") == 0 and parse_bytes(None) == 0
    assert parse_bytes("1024") == 1024
    assert parse_bytes("4K") == 4096
    assert parse_bytes("1.5M") == int(1.5 * (1 << 20))
    assert parse_bytes("16G") == 16 << 30
    # natural spellings parse too
    assert parse_bytes("16GB") == 16 << 30
    assert parse_bytes("512 MiB") == 512 << 20
    with pytest.raises(ValueError, match="16Q"):
        parse_bytes("16Q")


def test_hbm_budget_typo_degrades_not_crashes():
    """A config typo must not brick binds: warn-mode contract is 'log
    and proceed', so garbage degrades to a WARNING naming the knob."""
    from mxnet_tpu.models import mlp
    net = mlp.get_symbol(num_classes=10)
    mx.config.set("MXNET_TPU_ANALYZE_HBM_BUDGET", "lots")
    try:
        report = analyze_symbol(net, input_shapes={"data": (32, 784),
                                                   "softmax_label": (32,)})
    finally:
        mx.config.reset("MXNET_TPU_ANALYZE_HBM_BUDGET")
    hits = codes(report, "hbm-budget")
    assert hits and hits[0].severity == Severity.WARNING
    assert "NOT being enforced" in hits[0].message


def test_cli_lint_no_paths_is_usage_error():
    from mxnet_tpu.analysis.__main__ import main
    assert main(["lint"]) == 2


def test_cli_audit_typo_target_is_usage_error(capsys):
    from mxnet_tpu.analysis.__main__ import main
    assert main(["audit", "transfromer"]) == 2
    assert "unknown zoo model" in capsys.readouterr().err


def test_negative_budget_rejected_not_silent():
    with pytest.raises(ValueError, match="negative"):
        parse_bytes("-16G")
    from mxnet_tpu.models import mlp
    mx.config.set("MXNET_TPU_ANALYZE_HBM_BUDGET", "-16G")
    try:
        report = analyze_symbol(mlp.get_symbol(num_classes=10),
                                input_shapes={"data": (32, 784),
                                              "softmax_label": (32,)})
    finally:
        mx.config.reset("MXNET_TPU_ANALYZE_HBM_BUDGET")
    hits = codes(report, "hbm-budget")
    assert hits and "NOT being enforced" in hits[0].message


def test_program_memory_unused_output_dies_immediately():
    """An eqn output nothing consumes (dropped tuple element) must not
    stay 'live' to the end of the program — it would inflate every
    later point of the high-water walk."""
    def f(x):
        a, v = jax.lax.sort_key_val(x, x * 2.0)   # v is never used
        big = jnp.concatenate([a, a, a, a], axis=0)
        return jnp.sum(big)

    x = jnp.ones((256, 256), jnp.float32)
    mem = analyze_program_memory(f, x).extras["program_memory"]
    buf = 256 * 256 * 4
    # peak is at the concat output (a + 4a); the sort moment holds
    # m + a + v = 3 bufs. If the unused v leaked, the concat point
    # would count a + 4a + v = 6 bufs.
    assert mem["activation_peak_bytes"] == 5 * buf


def test_hbm_budget_fires_and_names_offenders():
    from mxnet_tpu.models import mlp
    net = mlp.get_symbol(num_classes=10)
    mx.config.set("MXNET_TPU_ANALYZE_HBM_BUDGET", "100K")
    try:
        report = analyze_symbol(net, input_shapes={"data": (32, 784),
                                                   "softmax_label": (32,)})
    finally:
        mx.config.reset("MXNET_TPU_ANALYZE_HBM_BUDGET")
    hits = codes(report, "hbm-budget")
    assert hits and hits[0].severity == Severity.ERROR
    # the finding names the offending arrays — the fc1 weight dominates
    assert "fc1_weight" in hits[0].message
    assert not report.extras["hbm_budget"]["fits"]


def test_hbm_budget_fits_stays_silent():
    from mxnet_tpu.models import mlp
    net = mlp.get_symbol(num_classes=10)
    mx.config.set("MXNET_TPU_ANALYZE_HBM_BUDGET", "1G")
    try:
        report = analyze_symbol(net, input_shapes={"data": (32, 784),
                                                   "softmax_label": (32,)})
    finally:
        mx.config.reset("MXNET_TPU_ANALYZE_HBM_BUDGET")
    assert not codes(report, "hbm-budget")
    assert report.extras["hbm_budget"]["fits"]


def test_hbm_budget_unset_no_extras():
    from mxnet_tpu.models import mlp
    report = analyze_symbol(mlp.get_symbol(num_classes=10),
                            input_shapes={"data": (32, 784),
                                          "softmax_label": (32,)})
    assert "hbm_budget" not in report.extras


def test_strict_mode_rejects_over_budget_bind():
    """The acceptance drill: strict mode rejects an over-HBM-budget bind
    before any compile, naming the offending arrays."""
    d = sym.Variable("data")
    net = sym.FullyConnected(d, num_hidden=256, name="fc_big")
    mx.config.set("MXNET_TPU_ANALYZE", "strict")
    mx.config.set("MXNET_TPU_ANALYZE_HBM_BUDGET", "64K")
    try:
        with pytest.raises(mx.MXNetError, match="hbm-budget") as exc_info:
            net.simple_bind(mx.cpu(), data=(16, 512))
        assert "fc_big_weight" in str(exc_info.value)
        # and the same bind FITS a real budget
        mx.config.set("MXNET_TPU_ANALYZE_HBM_BUDGET", "16G")
        ex = net.simple_bind(mx.cpu(), data=(16, 512))
        assert ex.forward()[0].shape == (16, 256)
    finally:
        mx.config.reset("MXNET_TPU_ANALYZE")
        mx.config.reset("MXNET_TPU_ANALYZE_HBM_BUDGET")


def test_remat_report_transformer_nonempty():
    net, shapes = _transformer()
    report = analyze_symbol(net, input_shapes=shapes)
    assert codes(report, "remat-opportunity")
    remat = report.extras["remat"]
    assert remat["candidates"]
    sug = remat["suggestion"]
    assert hasattr(jax.checkpoint_policies, sug["policy"])
    assert "jax.checkpoint" in sug["hint"]


def test_remat_silent_on_tiny_graph():
    d = sym.Variable("data")
    net = sym.FullyConnected(d, num_hidden=4, name="fc")
    report = analyze_symbol(net, input_shapes={"data": (2, 8)})
    assert not codes(report, "remat-opportunity")
    assert "remat" not in report.extras


def test_program_memory_hand_computed_chain():
    def f(a):
        b = a + 1.0
        return b * 2.0

    x = jnp.ones((256, 256), jnp.float32)
    report = analyze_program_memory(f, x)
    mem = report.extras["program_memory"]
    buf = 256 * 256 * 4
    # b and the output coexist for one step: the 2-buffer peak
    assert mem["activation_peak_bytes"] == 2 * buf
    assert mem["arg_bytes"] == buf
    assert mem["top_live"]


def test_remat_top_suggestion_reduces_analyzed_peak():
    """THE acceptance criterion: the zoo transformer's top remat
    suggestion, applied as a jax.checkpoint policy (per repeated block,
    as the hint instructs), measurably reduces the grad program's
    analyzed peak activation memory."""
    net, shapes = _transformer()
    sug = analyze_symbol(net, input_shapes=shapes) \
        .extras["remat"]["suggestion"]
    policy = getattr(jax.checkpoint_policies, sug["policy"])

    # a transformer-block-shaped program (attention internals T x T >>
    # the T x d block boundary — the regime the suggestion targets)
    T, D, L = 128, 16, 4

    def block(x, w):
        s = jax.nn.softmax((x @ x.T) / np.sqrt(D))
        return jnp.tanh(s @ x @ w)

    def plain(params, x):
        for w in params:
            x = block(x, w)
        return jnp.sum(x)

    def rematted(params, x):
        ck = jax.checkpoint(block, policy=policy)
        for w in params:
            x = ck(x, w)
        return jnp.sum(x)

    params = [jnp.ones((D, D), jnp.float32) for _ in range(L)]
    x = jnp.ones((T, D), jnp.float32)
    peak_plain = analyze_program_memory(
        jax.grad(plain), params, x).extras["program_memory"][
        "activation_peak_bytes"]
    peak_remat = analyze_program_memory(
        jax.grad(rematted), params, x).extras["program_memory"][
        "activation_peak_bytes"]
    assert peak_remat < 0.95 * peak_plain, \
        "suggested policy %s did not reduce analyzed peak (%d -> %d)" \
        % (sug["policy"], peak_plain, peak_remat)


# ======================================================= sharding passes


@needs_8_devices
def test_spec_audit_fires_and_stays_silent():
    mesh = make_mesh({"data": 2, "model": 4})
    shapes = {"w": (32, 6), "b": (6, 32)}
    # unknown axis fires
    r = check_specs(mesh, {"w": P("expert", None)}, shapes)
    assert codes(r, "spec-axis") and r.errors
    # over-ranked spec fires
    r = check_specs(mesh, {"w": P("model", None, None)}, shapes)
    assert codes(r, "spec-rank")
    # non-dividing dim fires (6 rows over 4 shards)
    r = check_specs(mesh, {"b": P("model", None)}, shapes)
    assert codes(r, "spec-divisibility")
    # one axis on two dims fires
    r = check_specs(mesh, {"w": P("model", "model")}, shapes)
    assert codes(r, "spec-duplicate-axis")
    # a clean TP layout is silent
    r = check_specs(mesh, {"w": P("model", None), "b": P(None, "model")},
                    {"w": (32, 6), "b": (6, 32)})
    assert not r.findings


@needs_8_devices
def test_reshard_thrash_fires_vs_clean_layout():
    mesh = make_mesh({"data": 2, "model": 4})
    # the same activation declared with different layouts in two stages:
    # every boundary crossing reshards it
    islands = {"stage0": {"x": P("data", None)},
               "stage1": {"x": P(None, "model")}}
    r = check_islands(islands, mesh=mesh, shapes={"x": (64, 32)})
    hits = codes(r, "reshard-thrash")
    assert hits and hits[0].severity == Severity.WARNING
    assert "stage0" in hits[0].message and "stage1" in hits[0].message
    # a clean TP layout (same spec everywhere) is silent
    r = check_islands({"stage0": {"x": P("data", None)},
                       "stage1": {"x": P("data", None)}}, mesh=mesh)
    assert not codes(r, "reshard-thrash")


@needs_8_devices
def test_fsdp_opportunity_fires_vs_sharded():
    mesh = make_mesh({"data": 2, "model": 4})
    shapes = {"big_weight": (1024, 1024), "small_bias": (32,)}
    r = check_replicated(mesh, {}, shapes)
    hits = codes(r, "fsdp-opportunity")
    assert len(hits) == 1 and hits[0].node == "big_weight"
    # 4 MiB replicated over 8 devices: 7/8 recoverable
    assert hits[0].detail["recovered_bytes_per_device"] == \
        1024 * 1024 * 4 * 7 // 8
    # the sharded version of the same param is silent
    r = check_replicated(mesh, {"big_weight": P("model", None)}, shapes)
    assert not codes(r, "fsdp-opportunity")


def test_islands_cross_check_runs():
    from mxnet_tpu.parallel import sharding_islands
    islands = sharding_islands()
    assert {"mesh", "dist", "moe", "pipeline", "ring_attention"} \
        <= set(islands)
    # since the SpecLayout unification (ISSUE 14) every island draws
    # from ONE canonical layout: zero disagreements, with or without a
    # mesh (tests/test_layout.py pins the with-mesh form too)
    r = check_islands(islands)
    assert not codes(r, "reshard-thrash")
    assert not r.errors


@needs_8_devices
def test_collective_walk_hand_computed_all_reduce():
    """Row-parallel matmul: contraction over the model-sharded K dim
    with a replicated output forces exactly one all-reduce of the
    output buffer — bytes and ring link traffic are hand-computable."""
    from jax.sharding import NamedSharding
    mesh = make_mesh({"data": 2, "model": 4})
    B, K, N = 16, 64, 32
    xs = NamedSharding(mesh, P(None, "model"))
    ws = NamedSharding(mesh, P("model", None))
    x = jax.device_put(jnp.ones((B, K)), xs)
    w = jax.device_put(jnp.ones((K, N)), ws)
    r = analyze_collectives(lambda a, b: a @ b, x, w, mesh=mesh,
                            out_shardings=NamedSharding(mesh, P()))
    comm = r.extras["comm"]
    model = comm["per_axis"]["model"]
    assert model["count"] == 1
    assert model["bytes"] == B * N * 4                    # 2048
    assert model["link_bytes"] == 2 * (4 - 1) * B * N * 4 // 4   # ring
    assert comm["est_total_us"] > 0
    ar = [c for c in comm["collectives"] if c["kind"] == "all-reduce"]
    assert ar and ar[0]["axes"] == ["model"]


@needs_8_devices
def test_tp_module_audit_comm_matches_hand_value():
    """The Megatron MLP forward has ONE all-reduce over `model` (fc2's
    row-parallel contraction) of the (64, 2) f32 logits = 512 bytes."""
    mod = _tp_module()
    report = analyze_module_sharding(mod)
    assert not report.errors, report.format(Severity.ERROR)
    comm = report.extras["comm"]
    assert comm["collectives"], "comm report must be non-empty"
    model = comm["per_axis"]["model"]
    assert model["bytes"] == 64 * 2 * 4
    assert model["link_bytes"] == 2 * (4 - 1) * 64 * 2 * 4 // 4


@needs_8_devices
def test_module_analyze_sharding_surface():
    mod = _tp_module()
    report = mod.analyze(sharding=True)
    # graph passes AND spec audit ride one report; zero errors on the
    # healthy TP layout
    assert not report.errors, report.format(Severity.ERROR)
    assert "cost" in report.extras
    # fc1_bias (8,) over model=4: divisible; nothing to flag
    assert not codes(report, "spec-axis")


def _conflict_module(param_shardings):
    data = sym.Variable("data")
    net = sym.SoftmaxOutput(
        sym.FullyConnected(data, num_hidden=32, name="fc1"),
        name="softmax")
    mod = mx.mod.Module(net, context=[mx.cpu(i) for i in range(8)],
                        mesh_shape={"data": 2, "model": 4},
                        param_shardings=param_shardings)
    mod.bind(data_shapes=[("data", (64, 8))],
             label_shapes=[("softmax_label", (64,))])
    mod.init_params(mx.init.Uniform(0.01))
    return mod


@needs_8_devices
def test_module_spec_conflict_regex_layering():
    """Two overlapping regexes with different specs are ambiguous (dict
    order decides the layout) — flagged."""
    mod = _conflict_module({r"fc1_w.*": P("model", None),
                            r"fc1_.*ght": P(None, "model")})
    report = analyze_module_sharding(mod, collectives=False)
    hits = codes(report, "spec-conflict")
    assert hits and "fc1_weight" in hits[0].message


@needs_8_devices
def test_module_audit_does_not_flag_batch_inputs_as_fsdp():
    """data/label are batch-sharded per step by the placer — a big
    batch input must not show up as a 'replicated parameter' FSDP
    opportunity."""
    data = sym.Variable("data")
    net = sym.SoftmaxOutput(
        sym.FullyConnected(data, num_hidden=32, name="fc1"),
        name="softmax")
    mod = mx.mod.Module(net, context=[mx.cpu(i) for i in range(8)],
                        mesh_shape={"data": 2, "model": 4})
    mod.bind(data_shapes=[("data", (4096, 784))],
             label_shapes=[("softmax_label", (4096,))])
    mod.init_params(mx.init.Uniform(0.01))
    report = analyze_module_sharding(mod, collectives=False)
    assert not codes(report, "fsdp-opportunity"), \
        report.format(Severity.WARNING)


@needs_8_devices
def test_module_spec_exact_key_beats_regex_silently():
    """An exact key wins unconditionally in _sharding_for — an
    overlapping regex is NOT a conflict (mirrors bind resolution)."""
    mod = _conflict_module({"fc1_weight": P("model", None),
                            r"fc1_w.*": P(None, "model")})
    report = analyze_module_sharding(mod, collectives=False)
    assert not codes(report, "spec-conflict")


# ============================================================== roofline


def test_roofline_classification_pair():
    """A fat matmul classifies compute-bound, an elementwise add
    memory-bound, against a knob-pinned device roofline."""
    mx.config.set("MXNET_TPU_OBS_PEAK_FLOPS", 1e12)     # 1 TFLOP/s
    mx.config.set("MXNET_TPU_ANALYZE_HBM_GBPS", 100.0)  # balance = 10
    try:
        r = roofline.analyze_executable(
            lambda a, b: a @ b, jnp.ones((256, 256)), jnp.ones((256, 256)))
        roof = r.extras["roofline"]
        assert roof["bound"] == "compute"
        assert roof["attainable_mfu"] == 1.0
        r = roofline.analyze_executable(
            lambda a, b: a + b, jnp.ones((256, 256)), jnp.ones((256, 256)))
        roof = r.extras["roofline"]
        assert roof["bound"] == "memory"
        assert roof["attainable_mfu"] < 0.05
    finally:
        mx.config.reset("MXNET_TPU_OBS_PEAK_FLOPS")
        mx.config.reset("MXNET_TPU_ANALYZE_HBM_GBPS")


def test_flop_model_drift_fires_and_stays_silent():
    a, b = jnp.ones((128, 128)), jnp.ones((128, 128))
    true_flops = 2 * 128 * 128 * 128
    # an undercounting model (the per-element shape) fires
    r = roofline.analyze_executable(lambda a, b: a @ b, a, b,
                                    model_flops=128 * 128)
    assert codes(r, "flop-model-drift")
    # the correct closed form is silent
    r = roofline.analyze_executable(lambda a, b: a @ b, a, b,
                                    model_flops=true_flops)
    assert not codes(r, "flop-model-drift")
    assert abs(r.extras["roofline"]["model_ratio"] - 1.0) <= 0.25


def test_roofline_explain_reconciles_measured_mfu():
    mx.config.set("MXNET_TPU_OBS_PEAK_FLOPS", 1e12)
    mx.config.set("MXNET_TPU_ANALYZE_HBM_GBPS", 100.0)
    try:
        # memory-bound program already at its roofline: the why says
        # raise intensity, not scheduling
        out = roofline.explain(flops=1e9, bytes_moved=1e9,
                               measured_mfu=0.1)
        assert out["bound"] == "memory"
        assert "intensity" in out["why"]
        # far below an attainable roofline: the why blames scheduling
        out = roofline.explain(flops=1e9, bytes_moved=1e7,
                               measured_mfu=0.05)
        assert out["bound"] == "compute"
        assert "scheduling" in out["why"]
    finally:
        mx.config.reset("MXNET_TPU_OBS_PEAK_FLOPS")
        mx.config.reset("MXNET_TPU_ANALYZE_HBM_GBPS")


def test_obs_report_carries_roofline_why():
    """mx.obs.report() attaches the roofline reconciliation to each
    executor record — the PR 6 MFU numbers come with a why attached."""
    from mxnet_tpu.initializer import Uniform
    data = sym.Variable("data")
    net = sym.SoftmaxOutput(sym.FullyConnected(data, num_hidden=8,
                                               name="fc1"), name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu(),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (8, 16))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(Uniform(0.01))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    rng = np.random.RandomState(0)
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rng.uniform(-1, 1, (8, 16)))],
        label=[mx.nd.array(rng.randint(0, 8, (8,)))])
    mx.config.set("MXNET_TPU_OBS_PEAK_FLOPS", 1e12)
    mx.config.set("MXNET_TPU_ANALYZE_HBM_GBPS", 100.0)
    try:
        for _ in range(4):
            mod._fit_step(batch)
        mx.obs.report()                      # opens the rate window
        for _ in range(3):
            mod._fit_step(batch)
        rep = mx.obs.report()
    finally:
        mx.config.reset("MXNET_TPU_OBS_PEAK_FLOPS")
        mx.config.reset("MXNET_TPU_ANALYZE_HBM_GBPS")
    recs = [r for r in rep["executors"]
            if r["name"].startswith("fused_step") and r.get("roofline")]
    assert recs, rep["executors"]
    roof = recs[-1]["roofline"]
    assert roof["bound"] in ("compute", "memory")
    assert "why" in roof and roof["measured_mfu"] is not None


# ===================================================== signal-unsafe lint


SIG_BAD = """
import signal, threading, logging
lock = threading.Lock()

def handler(signum, frame):
    with lock:
        logging.warning("dying")

signal.signal(signal.SIGTERM, handler)
"""

SIG_OK = """
import signal

class Mgr:
    def install(self):
        def _handler(signum, frame):
            self._preempt = True       # flag-only: the PR 5 discipline
        signal.signal(signal.SIGTERM, _handler)
"""


def test_signal_unsafe_fires():
    report = lint_source(SIG_BAD, path="s.py")
    hits = codes(report, "signal-unsafe")
    assert len(hits) == 2
    sev = {f.severity for f in hits}
    assert Severity.ERROR in sev         # the lock acquisition
    assert Severity.WARNING in sev       # the logging call
    assert all(f.func == "handler" for f in hits)


def test_signal_unsafe_flag_only_stays_silent():
    assert not codes(lint_source(SIG_OK, path="s.py"), "signal-unsafe")


def test_signal_unsafe_method_handler_and_queue():
    src = """
import signal

class Mgr:
    def install(self):
        signal.signal(signal.SIGTERM, self._on_term)

    def _on_term(self, signum, frame):
        self._queue.put(1)             # blocks on the queue lock
"""
    hits = codes(lint_source(src, path="m.py"), "signal-unsafe")
    assert hits and hits[0].severity == Severity.ERROR
    assert "_queue.put" in hits[0].message


def test_signal_unsafe_same_code_outside_handler_silent():
    src = """
import threading, logging
lock = threading.Lock()

def not_a_handler():
    with lock:
        logging.warning("fine: nobody registered this with signal")
"""
    assert not codes(lint_source(src, path="n.py"), "signal-unsafe")


def test_signal_unsafe_inline_suppression():
    src = SIG_BAD.replace(
        "with lock:",
        "with lock:  # mx-lint: allow(signal-unsafe)")
    hits = codes(lint_source(src, path="s.py"), "signal-unsafe")
    assert len(hits) == 1                # only the logging WARNING left


def test_checkpoint_manager_handler_is_clean():
    """The PR 5 SIGTERM handler dodges this hazard class by hand; the
    rule must agree."""
    from mxnet_tpu.analysis import lint_paths
    path = os.path.join(REPO, "mxnet_tpu", "checkpoint", "manager.py")
    assert not codes(lint_paths([path]), "signal-unsafe")


# ======================================================= baseline drift


def test_stale_baseline_detected(tmp_path):
    locked = """
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()

    def fetch(self, arr):
        with self._lock:
            return arr.asnumpy()
"""
    src = tmp_path / "s.py"
    src.write_text(locked)
    report = lint_source(locked, path=str(src))
    bl = str(tmp_path / "bl.json")
    write_baseline(report, bl, str(tmp_path))
    # the debt gets paid off: the baseline is now stale
    fixed = locked.replace("with self._lock:\n            return",
                           "if True:\n            return")
    src.write_text(fixed)
    clean = lint_source(fixed, path=str(src))
    stale = stale_baseline(clean, load_baseline(bl), str(tmp_path))
    assert stale and list(stale.values()) == [1]
    # and the CLI gate fails on it (drift in the shrinking direction)
    from mxnet_tpu.analysis.__main__ import main
    assert main(["lint", str(src), "--root", str(tmp_path),
                 "--baseline", bl]) == 1


def test_baseline_gate_passes_when_in_sync(tmp_path):
    src = tmp_path / "ok.py"
    src.write_text("x = 1\n")
    from mxnet_tpu.analysis.__main__ import main
    bl = str(tmp_path / "bl.json")
    assert main(["lint", str(src), "--root", str(tmp_path),
                 "--write-baseline", bl]) == 0
    assert main(["lint", str(src), "--root", str(tmp_path),
                 "--baseline", bl]) == 0


# ========================================================== zoo audit


@needs_8_devices
def test_zoo_audit_zero_errors_nonempty_reports():
    """The model-zoo audit: MLP, resnet8, transformer and the TP mesh
    module produce zero ERROR findings, non-empty remat reports for the
    nets and a non-empty comm report for the mesh module."""
    from mxnet_tpu.analysis.__main__ import _zoo_symbol
    for name in ("mlp", "resnet8", "transformer"):
        net, shapes = _zoo_symbol(name)
        report = analyze_symbol(net, input_shapes=shapes, context=name)
        assert not report.errors, report.format(Severity.ERROR)
        assert report.extras.get("remat", {}).get("candidates"), \
            "%s: remat report empty" % name
    mod = _tp_module()
    report = analyze_module_sharding(mod)
    assert not report.errors, report.format(Severity.ERROR)
    assert report.extras["comm"]["collectives"]


@needs_8_devices
def test_cli_audit_default_targets():
    from mxnet_tpu.analysis.__main__ import main
    assert main(["audit"]) == 0


def test_cli_audit_single_zoo_target(capsys):
    from mxnet_tpu.analysis.__main__ import main
    assert main(["audit", "transformer"]) == 0
    out = capsys.readouterr().out
    assert "remat:" in out and "suggestion:" in out and "roofline:" in out


def test_cli_audit_accepts_zoo_prefix():
    from mxnet_tpu.analysis.__main__ import main
    assert main(["audit", "zoo:mlp"]) == 0


@needs_8_devices
def test_axis_groups_prefer_smallest_subset():
    """On a mesh with a size-1 axis the ('model',) and ('data','model')
    replica groups coincide; attribution must pick the axis users grep
    for, not the multi-axis key."""
    from mxnet_tpu.analysis.sharding_passes import _axis_groups
    mesh = make_mesh({"data": 1, "model": 8})
    groups = frozenset([frozenset(range(8))])
    assert _axis_groups(mesh)[groups] == ("model",)


def test_shape_bytes_async_start_tuple():
    """Async *-start collectives return (operand-alias, result[, ctx])
    tuples; only the result buffer moves — summing double-counts."""
    from mxnet_tpu.analysis.sharding_passes import _shape_bytes
    tup = "(f32[64,2]{1,0}, f32[64,2]{1,0}, u32[], u32[])"
    assert _shape_bytes(tup, largest_only=True) == 64 * 2 * 4
    assert _shape_bytes("f32[64,2]{1,0}") == 64 * 2 * 4

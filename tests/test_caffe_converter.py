"""Caffe prototxt conversion (tools/caffe_converter.py): the common
deploy-net subset parses, builds, and runs; weights flow through the
reference-format checkpoint into Predictor."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LENET_PROTOTXT = """
name: "LeNet"
layer { name: "data" type: "Input" top: "data"
  input_param { shape { dim: 2 dim: 1 dim: 28 dim: 28 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 8 kernel_size: 5 stride: 1 } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "relu1" }
layer { name: "pool1" type: "Pooling" bottom: "relu1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layer { name: "ip1" type: "InnerProduct" bottom: "pool1" top: "ip1"
  inner_product_param { num_output: 32 } }
layer { name: "relu2" type: "ReLU" bottom: "ip1" top: "relu2" }
layer { name: "ip2" type: "InnerProduct" bottom: "relu2" top: "ip2"
  inner_product_param { num_output: 10 } }
layer { name: "prob" type: "Softmax" bottom: "ip2" top: "prob" }
"""


def test_caffe_converter_end_to_end(tmp_path):
    proto = tmp_path / "lenet.prototxt"
    proto.write_text(LENET_PROTOTXT)
    rng = np.random.RandomState(0)
    weights = {
        "conv1_weight": rng.randn(8, 1, 5, 5).astype(np.float32) * 0.1,
        "conv1_bias": rng.randn(8).astype(np.float32) * 0.1,
        "ip1_weight": rng.randn(32, 8 * 12 * 12).astype(np.float32) * 0.01,
        "ip1_bias": rng.randn(32).astype(np.float32) * 0.1,
        "ip2_weight": rng.randn(10, 32).astype(np.float32) * 0.1,
        "ip2_bias": rng.randn(10).astype(np.float32) * 0.1,
    }
    wpath = tmp_path / "w.npz"
    np.savez(wpath, **weights)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "caffe_converter.py"),
         str(proto), str(tmp_path / "lenet"), "--weights", str(wpath)],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    assert (tmp_path / "lenet-symbol.json").exists()
    assert (tmp_path / "lenet-0000.params").exists()

    pred = mx.predictor.Predictor(
        str(tmp_path / "lenet-symbol.json"),
        str(tmp_path / "lenet-0000.params"),
        {"data": (2, 1, 28, 28)}, ctx=mx.cpu(0))
    x = rng.rand(2, 1, 28, 28).astype(np.float32)
    out = pred.forward(data=x)[0].asnumpy()
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-5)

    # numpy oracle for the conv->relu->pool->fc stack
    from numpy.lib.stride_tricks import sliding_window_view
    w, b = weights["conv1_weight"], weights["conv1_bias"]
    windows = sliding_window_view(x, (5, 5), axis=(2, 3))  # (2,1,24,24,5,5)
    conv = np.einsum("nchwij,ocij->nohw", windows[:, 0][:, None], w) + \
        b[None, :, None, None]
    relu = np.maximum(conv, 0)
    pool = relu.reshape(2, 8, 12, 2, 12, 2).max((3, 5))
    h = np.maximum(pool.reshape(2, -1) @ weights["ip1_weight"].T
                   + weights["ip1_bias"], 0)
    logits = h @ weights["ip2_weight"].T + weights["ip2_bias"]
    p_ref = np.exp(logits - logits.max(1, keepdims=True))
    p_ref /= p_ref.sum(1, keepdims=True)
    np.testing.assert_allclose(out, p_ref, rtol=1e-4, atol=1e-5)


def test_caffe_converter_rejects_unknown_layer(tmp_path):
    from tools.caffe_converter import parse_prototxt, convert
    net = parse_prototxt("""
layer { name: "data" type: "Input" top: "data"
  input_param { shape { dim: 1 dim: 3 } } }
layer { name: "x" type: "FancyLayer" bottom: "data" top: "x" }
""")
    with pytest.raises(NotImplementedError, match="FancyLayer"):
        convert(net)


def test_caffe_parser_colon_brace_and_bn_names(tmp_path):
    from tools.caffe_converter import parse_prototxt, convert
    # 'field: { ... }' colon-before-brace form must parse identically
    net = parse_prototxt("""
layer { name: "data" type: "Input" top: "data"
  input_param: { shape: { dim: 2 dim: 4 } } }
layer { name: "fc" type: "InnerProduct" bottom: "data" top: "fc"
  inner_product_param: { num_output: 3 } }
layer { name: "bn1" type: "BatchNorm" bottom: "fc" top: "bn1" }
layer { name: "sc1" type: "Scale" bottom: "bn1" top: "sc1" }
layer { name: "prob" type: "Softmax" bottom: "sc1" top: "prob" }
""")
    sym, in_shape = convert(net)
    assert in_shape == (2, 4)
    args = sym.list_arguments()
    assert "fc_weight" in args and "bn1_gamma" in args
    assert "bn1_moving_mean" in sym.list_auxiliary_states()

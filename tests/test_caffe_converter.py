"""Caffe prototxt conversion (tools/caffe_converter.py): the common
deploy-net subset parses, builds, and runs; weights flow through the
reference-format checkpoint into Predictor."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LENET_PROTOTXT = """
name: "LeNet"
layer { name: "data" type: "Input" top: "data"
  input_param { shape { dim: 2 dim: 1 dim: 28 dim: 28 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 8 kernel_size: 5 stride: 1 } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "relu1" }
layer { name: "pool1" type: "Pooling" bottom: "relu1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layer { name: "ip1" type: "InnerProduct" bottom: "pool1" top: "ip1"
  inner_product_param { num_output: 32 } }
layer { name: "relu2" type: "ReLU" bottom: "ip1" top: "relu2" }
layer { name: "ip2" type: "InnerProduct" bottom: "relu2" top: "ip2"
  inner_product_param { num_output: 10 } }
layer { name: "prob" type: "Softmax" bottom: "ip2" top: "prob" }
"""


def test_caffe_converter_end_to_end(tmp_path):
    proto = tmp_path / "lenet.prototxt"
    proto.write_text(LENET_PROTOTXT)
    rng = np.random.RandomState(0)
    weights = {
        "conv1_weight": rng.randn(8, 1, 5, 5).astype(np.float32) * 0.1,
        "conv1_bias": rng.randn(8).astype(np.float32) * 0.1,
        "ip1_weight": rng.randn(32, 8 * 12 * 12).astype(np.float32) * 0.01,
        "ip1_bias": rng.randn(32).astype(np.float32) * 0.1,
        "ip2_weight": rng.randn(10, 32).astype(np.float32) * 0.1,
        "ip2_bias": rng.randn(10).astype(np.float32) * 0.1,
    }
    wpath = tmp_path / "w.npz"
    np.savez(wpath, **weights)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "caffe_converter.py"),
         str(proto), str(tmp_path / "lenet"), "--weights", str(wpath)],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    assert (tmp_path / "lenet-symbol.json").exists()
    assert (tmp_path / "lenet-0000.params").exists()

    pred = mx.predictor.Predictor(
        str(tmp_path / "lenet-symbol.json"),
        str(tmp_path / "lenet-0000.params"),
        {"data": (2, 1, 28, 28)}, ctx=mx.cpu(0))
    x = rng.rand(2, 1, 28, 28).astype(np.float32)
    out = pred.forward(data=x)[0].asnumpy()
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-5)

    # numpy oracle for the conv->relu->pool->fc stack
    from numpy.lib.stride_tricks import sliding_window_view
    w, b = weights["conv1_weight"], weights["conv1_bias"]
    windows = sliding_window_view(x, (5, 5), axis=(2, 3))  # (2,1,24,24,5,5)
    conv = np.einsum("nchwij,ocij->nohw", windows[:, 0][:, None], w) + \
        b[None, :, None, None]
    relu = np.maximum(conv, 0)
    pool = relu.reshape(2, 8, 12, 2, 12, 2).max((3, 5))
    h = np.maximum(pool.reshape(2, -1) @ weights["ip1_weight"].T
                   + weights["ip1_bias"], 0)
    logits = h @ weights["ip2_weight"].T + weights["ip2_bias"]
    p_ref = np.exp(logits - logits.max(1, keepdims=True))
    p_ref /= p_ref.sum(1, keepdims=True)
    np.testing.assert_allclose(out, p_ref, rtol=1e-4, atol=1e-5)


def test_caffe_converter_rejects_unknown_layer(tmp_path):
    from tools.caffe_converter import parse_prototxt, convert
    net = parse_prototxt("""
layer { name: "data" type: "Input" top: "data"
  input_param { shape { dim: 1 dim: 3 } } }
layer { name: "x" type: "FancyLayer" bottom: "data" top: "x" }
""")
    with pytest.raises(NotImplementedError, match="FancyLayer"):
        convert(net)


def test_caffe_parser_colon_brace_and_bn_names(tmp_path):
    from tools.caffe_converter import parse_prototxt, convert
    # 'field: { ... }' colon-before-brace form must parse identically
    net = parse_prototxt("""
layer { name: "data" type: "Input" top: "data"
  input_param: { shape: { dim: 2 dim: 4 } } }
layer { name: "fc" type: "InnerProduct" bottom: "data" top: "fc"
  inner_product_param: { num_output: 3 } }
layer { name: "bn1" type: "BatchNorm" bottom: "fc" top: "bn1" }
layer { name: "sc1" type: "Scale" bottom: "bn1" top: "sc1" }
layer { name: "prob" type: "Softmax" bottom: "sc1" top: "prob" }
""")
    sym, in_shape = convert(net)
    assert in_shape == (2, 4)
    args = sym.list_arguments()
    assert "fc_weight" in args and "bn1_gamma" in args
    assert "bn1_moving_mean" in sym.list_auxiliary_states()


# ------------------------------------------------ binary caffemodel reader


def _enc_varint(n):
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        out += bytes([b7 | (0x80 if n else 0)])
        if not n:
            return out


def _enc_field(fno, wt, payload):
    key = _enc_varint((fno << 3) | wt)
    if wt == 0:
        return key + _enc_varint(payload)
    return key + _enc_varint(len(payload)) + payload


def _enc_blob(arr, legacy=False, packed=True):
    import numpy as np
    arr = np.asarray(arr, np.float32)
    msg = b""
    if legacy:
        dims = ([1] * (4 - arr.ndim)) + list(arr.shape)
        for fno, d in zip((1, 2, 3, 4), dims):
            msg += _enc_field(fno, 0, int(d))
    else:
        shape_msg = b"".join(_enc_varint(d) for d in arr.shape)
        msg += _enc_field(7, 2, _enc_field(1, 2, shape_msg))
    if packed:
        msg += _enc_field(5, 2, arr.ravel().astype("<f4").tobytes())
    else:
        for v in arr.ravel():
            msg += _enc_varint((5 << 3) | 5) + \
                np.float32(v).astype("<f4").tobytes()
    return msg


def _enc_layer(name, blobs, v1=False, **blob_kw):
    nf, bf = (4, 6) if v1 else (1, 7)
    msg = _enc_field(nf, 2, name.encode())
    for b in blobs:
        msg += _enc_field(bf, 2, _enc_blob(b, **blob_kw))
    return _enc_field(2 if v1 else 100, 2, msg)


def test_caffemodel_reader_roundtrip(tmp_path):
    """Full binary path: hand-encoded NetParameter (independent of the
    reader) -> converter -> Module forward matches numpy (reference:
    tools/caffe_converter/convert_model.py reads the same message)."""
    import subprocess
    import sys as _sys
    rng = np.random.RandomState(0)
    W = rng.randn(3, 2, 3, 3).astype(np.float32) * 0.2
    bW = rng.randn(3).astype(np.float32)
    mean = rng.rand(3).astype(np.float32)
    var = (rng.rand(3) + 0.5).astype(np.float32)
    gamma = rng.rand(3).astype(np.float32) + 0.5
    beta = rng.randn(3).astype(np.float32)
    fc = rng.randn(4, 3 * 4 * 4).astype(np.float32) * 0.1
    fcb = rng.randn(4).astype(np.float32)

    prototxt = """
name: "tiny"
input: "data"
input_dim: 1
input_dim: 2
input_dim: 4
input_dim: 4
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 3 kernel_size: 3 pad: 1 } }
layer { name: "bn1" type: "BatchNorm" bottom: "conv1" top: "bn1" }
layer { name: "sc1" type: "Scale" bottom: "bn1" top: "sc1"
  scale_param { bias_term: true } }
layer { name: "fc1" type: "InnerProduct" bottom: "sc1" top: "fc1"
  inner_product_param { num_output: 4 } }
"""
    sf = 2.0   # caffe scale-factor blob: stored stats are sf * true stats
    model = b"".join([
        _enc_layer("conv1", [W, bW]),
        _enc_layer("bn1", [mean * sf, var * sf, np.array([sf])],
                   legacy=True, packed=False),   # legacy dims + unpacked
        _enc_layer("sc1", [gamma, beta], v1=True),  # V1 'layers' form
        _enc_layer("fc1", [fc.reshape(1, 1, 4, 3 * 4 * 4), fcb],
                   legacy=True),
    ])
    proto_path = tmp_path / "tiny.prototxt"
    proto_path.write_text(prototxt)
    model_path = tmp_path / "tiny.caffemodel"
    model_path.write_bytes(model)
    prefix = str(tmp_path / "out")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [_sys.executable, os.path.join(ROOT, "tools", "caffe_converter.py"),
         str(proto_path), prefix, "--caffemodel", str(model_path)],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "parsed 8 parameter tensors" in proc.stdout

    import mxnet_tpu as mx
    sym = mx.sym.load(prefix + "-symbol.json")
    params = mx.nd.load(prefix + "-0000.params")
    args = {k[4:]: v for k, v in params.items() if k.startswith("arg:")}
    aux = {k[4:]: v for k, v in params.items() if k.startswith("aux:")}
    x = rng.randn(1, 2, 4, 4).astype(np.float32)
    args["data"] = mx.nd.array(x)
    ex = sym.bind(mx.cpu(0), args, aux_states=aux)
    got = ex.forward(is_train=False)[0].asnumpy()

    # numpy reference with the TRUE (unscaled) statistics
    import numpy as np2
    from numpy.lib.stride_tricks import sliding_window_view
    xp = np2.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    win = sliding_window_view(xp, (3, 3), axis=(2, 3))  # (1,2,4,4,3,3)
    conv = np2.einsum("nchwij,ocij->nohw", win, W) + bW[None, :, None, None]
    bnv = (conv - mean[None, :, None, None]) / np2.sqrt(
        var[None, :, None, None] + 1e-5)
    bnv = bnv * gamma[None, :, None, None] + beta[None, :, None, None]
    want = bnv.reshape(1, -1) @ fc.T + fcb
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)

"""mx.serve — dynamic-batching inference serving (ISSUE 2 tentpole).

The contract under test: concurrent submits produce BIT-IDENTICAL
results to sequential batch-1 prediction (padding must never bleed),
the bucket grid keeps the executable set finite (profiler-counter
asserted: zero recompiles on a 500-request mixed-shape load after
warmup), and the robustness matrix holds — deadlines, load shedding,
graceful drain, kill-switch fallback, eager degradation on batched
failure.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import config as cfg
from mxnet_tpu import profiler
from mxnet_tpu import serve


def _mlp(seed=0):
    """Deterministic small MLP (the doc-evidence network's shape)."""
    from mxnet_tpu.gluon import nn
    net = nn.Sequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
    net.initialize(mx.init.Xavier(rnd_type="uniform"))
    net(mx.nd.array(np.zeros((1, 24), np.float32)))   # shape probe
    return net


def _samples(n, dim=24, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.rand(dim).astype(np.float32) for _ in range(n)]


# ------------------------------------------------------------ correctness

def test_batch_split_bit_for_bit():
    """Coalescing + splitting is EXACT: a burst that fills one bucket
    returns, per request, the identical bits of the model run directly
    on the stacked batch."""
    net = _mlp()
    xs = _samples(16)
    direct = np.asarray(net(mx.nd.array(np.stack(xs))).asnumpy())
    srv = serve.InferenceServer(net, max_batch_size=16,
                                max_delay_us=300_000,   # hold the window
                                name="serve_t_split")
    try:
        futs = [srv.submit(x) for x in xs]    # all 16 land in one batch
        got = [np.asarray(f.result(timeout=60)) for f in futs]
    finally:
        srv.close()
    assert srv.stats()["batches"] == 1
    for i in range(16):
        assert np.array_equal(got[i], direct[i]), \
            "row %d differs from the stacked-batch bits" % i


def test_padded_rows_bit_for_bit():
    """Padding up to the bucket must not perturb real rows: serving 3
    requests at bucket 4 returns the bits of the model on the
    zero-padded 4-row buffer."""
    net = _mlp()
    xs = _samples(3, seed=11)
    buf = np.zeros((4, 24), np.float32)
    buf[:3] = np.stack(xs)
    direct = np.asarray(net(mx.nd.array(buf)).asnumpy())
    srv = serve.InferenceServer(net, max_batch_size=4,
                                max_delay_us=300_000,
                                name="serve_t_pad")
    try:
        futs = [srv.submit(x) for x in xs]
        got = [np.asarray(f.result(timeout=60)) for f in futs]
    finally:
        srv.close()
    for i in range(3):
        assert np.array_equal(got[i], direct[i]), "padding bled into row %d" % i


def test_concurrent_requests_match_sequential():
    """N threads x M requests: every request is served, none mixed up,
    and values match sequential batch-1 prediction. (Bit-for-bit holds
    at fixed geometry — the two tests above; across DIFFERENT batch
    geometries XLA does not promise bitwise-identical row results, so
    cross-geometry parity is tight-tolerance.)"""
    net = _mlp()
    xs = _samples(200)
    seq = [np.asarray(net(mx.nd.array(x[None])).asnumpy())[0] for x in xs]
    with serve.InferenceServer(net, max_batch_size=16, max_delay_us=500,
                               name="serve_t_conc") as srv:
        results = [None] * len(xs)
        errors = []

        def client(tid, lo, hi):
            try:
                futs = [(i, srv.submit(xs[i])) for i in range(lo, hi)]
                for i, f in futs:
                    results[i] = np.asarray(f.result(timeout=60))
            except Exception as exc:               # noqa: BLE001
                errors.append((tid, exc))

        n_threads = 8
        chunk = len(xs) // n_threads
        threads = [threading.Thread(target=client,
                                    args=(t, t * chunk, (t + 1) * chunk))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errors, errors
        assert srv.stats()["requests"] == n_threads * chunk
    for i in range(n_threads * chunk):
        np.testing.assert_allclose(
            results[i], seq[i], rtol=1e-5, atol=1e-6,
            err_msg="row %d differs from sequential batch-1 predict" % i)


def test_batched_submit_roundtrip():
    net = _mlp()
    rng = np.random.RandomState(1)
    x = rng.rand(3, 24).astype(np.float32)
    seq = np.asarray(net(mx.nd.array(x)).asnumpy())
    with serve.InferenceServer(net, max_batch_size=8, max_delay_us=200,
                               name="serve_t_batched") as srv:
        got = np.asarray(srv.submit(x, batched=True)
                         .result(timeout=60))
    assert got.shape == (3, 8)
    assert np.array_equal(got, seq)


def test_oversized_batched_request_rejected():
    net = _mlp()
    with serve.InferenceServer(net, max_batch_size=4,
                               name="serve_t_oversize") as srv:
        with pytest.raises(ValueError, match="exceeds max_batch_size"):
            srv.submit(np.zeros((5, 24), np.float32), batched=True)


# -------------------------------------------------------------- bucketing

def test_bucketing_bounds_executable_count():
    """Mixed-length load through a seq-bucketed server: the compile
    counter (one per (batch bucket, seq bucket) geometry) must stay
    under the grid bound however many distinct request shapes arrive."""
    def model(x):          # (B, T, 4) -> (B, 4); row-independent
        return mx.nd.sum(x, axis=1)

    spec = serve.BucketSpec(max_batch_size=8, seq_axis=0, max_seq_len=32)
    rng = np.random.RandomState(2)
    with serve.InferenceServer(model, buckets=spec, max_delay_us=500,
                               name="serve_t_bucket") as srv:
        futs = []
        for _ in range(120):
            t = int(rng.randint(1, 33))
            futs.append((t, srv.submit(
                rng.rand(t, 4).astype(np.float32))))
        for t, f in futs:
            f.result(timeout=60)
        stats = srv.stats()
    bound = spec.executable_bound()
    assert bound == len(spec.batch_buckets) * len(spec.seq_buckets)
    assert profiler.get_counter("serve_t_bucket_compile") <= bound
    # 120 distinct-ish shapes landed on few geometries
    assert len(stats["buckets"]) <= len(spec.seq_buckets)


def test_bucketed_padding_matches_unpadded_values():
    def model(x):
        return mx.nd.sum(x, axis=1)     # zero-padding is sum-neutral

    spec = serve.BucketSpec(max_batch_size=4, seq_axis=0, max_seq_len=16)
    rng = np.random.RandomState(3)
    xs = [rng.rand(int(t), 4).astype(np.float32)
          for t in rng.randint(1, 17, size=20)]
    with serve.InferenceServer(model, buckets=spec, max_delay_us=200,
                               name="serve_t_padval") as srv:
        got = [np.asarray(srv.submit(x).result(60)) for x in xs]
    for x, g in zip(xs, got):
        np.testing.assert_allclose(g, x.sum(axis=0), rtol=1e-6)


def test_negative_seq_axis_rejected():
    """Review finding: a numpy-style negative seq_axis would silently
    never pad (every length its own bucket — unbounded executables)."""
    with pytest.raises(ValueError, match="non-negative"):
        serve.BucketSpec(max_batch_size=4, seq_axis=-1, max_seq_len=16)


def test_overlong_dynamic_axis_rejected_at_submit():
    spec = serve.BucketSpec(max_batch_size=4, seq_axis=0, max_seq_len=8)
    with serve.InferenceServer(lambda x: x, buckets=spec,
                               name="serve_t_long") as srv:
        with pytest.raises(ValueError, match="max_seq_len"):
            srv.submit(np.zeros((9, 4), np.float32))


def test_steady_state_serves_with_zero_recompiles():
    """Acceptance criterion: warm the bucket grid, then a 500-request
    mixed-shape load must leave the compile counter UNCHANGED."""
    def model(x):
        return mx.nd.sum(x, axis=1)

    spec = serve.BucketSpec(max_batch_size=8, seq_axis=0, max_seq_len=16)
    rng = np.random.RandomState(4)
    with serve.InferenceServer(model, buckets=spec, max_delay_us=300,
                               name="serve_t_steady") as srv:
        # warmup: touch every (batch bucket, seq bucket) geometry —
        # submit exactly bucket-sized batched requests one at a time
        for b in spec.batch_buckets:
            for s in spec.seq_buckets:
                srv.submit(np.zeros((b, s, 4), np.float32),
                           batched=True).result(timeout=60)
        compiles_warm = profiler.get_counter("serve_t_steady_compile")
        assert compiles_warm == spec.executable_bound()
        futs = []
        for _ in range(500):
            t = int(rng.randint(1, 17))
            futs.append(srv.submit(rng.rand(t, 4).astype(np.float32)))
        for f in futs:
            f.result(timeout=60)
        assert profiler.get_counter("serve_t_steady_compile") == \
            compiles_warm, "steady-state load recompiled"
        assert profiler.get_counter("serve_t_steady_cache_hit") > 0
        lat = srv.stats()["latency"]
    assert lat and lat["p50_ms"] > 0 and lat["p99_ms"] >= lat["p50_ms"]


# ------------------------------------------------------------- robustness

def test_deadline_exceeded_before_launch():
    net = _mlp()
    # long window + empty traffic: a 1 ms deadline dies in the queue —
    # and must fire ~when promised, not a full 300 ms window later
    with serve.InferenceServer(net, max_batch_size=16,
                               max_delay_us=300_000,
                               name="serve_t_deadline") as srv:
        t0 = time.monotonic()
        f = srv.submit(_samples(1)[0], timeout=0.001)
        with pytest.raises(serve.DeadlineExceeded):
            f.result(timeout=30)
        elapsed = time.monotonic() - t0
    assert elapsed < 0.15, \
        "deadline fired %.0f ms late (window-late, review finding)" \
        % (elapsed * 1e3)
    assert profiler.get_counter("serve_t_deadline_deadline_expired") >= 1


def test_queue_full_load_shed():
    net = _mlp()
    srv = serve.InferenceServer(net, max_batch_size=2, queue_bound=2,
                                max_delay_us=500_000,
                                name="serve_t_shed")
    try:
        xs = _samples(8)
        accepted, shed = [], 0
        for x in xs:
            try:
                accepted.append(srv.submit(x))
            except serve.QueueFull:
                shed += 1
        assert shed >= 1, "admission bound never tripped"
        assert profiler.get_counter("serve_t_shed_shed") == shed
        for f in accepted:
            f.result(timeout=60)    # accepted traffic still completes
    finally:
        srv.close()


def test_graceful_close_drains_inflight():
    net = _mlp()
    srv = serve.InferenceServer(net, max_batch_size=4,
                                max_delay_us=200_000,
                                name="serve_t_drain")
    futs = [srv.submit(x) for x in _samples(10)]
    srv.close(drain=True)           # window is 200 ms out: queue is hot
    for f in futs:
        assert f.result(timeout=60) is not None
    with pytest.raises(serve.ServerClosed):
        srv.submit(_samples(1)[0])


def test_close_without_drain_fails_queued():
    net = _mlp()
    srv = serve.InferenceServer(net, max_batch_size=4,
                                max_delay_us=500_000,
                                name="serve_t_nodrain")
    futs = [srv.submit(x) for x in _samples(6)]
    srv.close(drain=False)
    outcomes = []
    for f in futs:
        try:
            f.result(timeout=30)
            outcomes.append("done")
        except serve.ServerClosed:
            outcomes.append("closed")
    # everything resolves promptly; whatever was already mid-batch may
    # finish, the rest must fail fast with ServerClosed
    assert "closed" in outcomes


def test_batched_failure_degrades_to_eager():
    """A model that cannot run the padded batch geometry: the server
    negative-caches the structure and serves its traffic per-request
    eagerly — requests succeed, nothing hangs."""
    def fragile(x):
        if x.shape[0] == 4:         # the padded bucket size
            raise RuntimeError("no batch-4 for you")
        return mx.nd.sum(x, axis=1)

    with serve.InferenceServer(fragile, max_batch_size=4,
                               max_delay_us=200,
                               name="serve_t_fragile") as srv:
        x = np.random.RandomState(5).rand(3, 6).astype(np.float32)
        got = np.asarray(srv.submit(x, batched=True)
                         .result(timeout=60))
        np.testing.assert_allclose(got, x.sum(axis=1), rtol=1e-6)
    assert profiler.get_counter("serve_t_fragile_compile_failed") >= 1
    assert profiler.get_counter("serve_t_fragile_eager") >= 1


def test_row_contract_violation_errors_do_not_kill_batcher():
    """A model whose output leading axis != input rows (review finding):
    the split fails, but every future must resolve with the error and
    the batcher thread must SURVIVE — a dead worker silently hangs all
    later requests."""
    def broken(x):
        return mx.nd.sum(x)            # scalar: no row axis at all

    srv = serve.InferenceServer(broken, max_batch_size=4, max_delay_us=200,
                                name="serve_t_rowviol")
    try:
        f = srv.submit(np.ones((3, 2), np.float32))
        with pytest.raises(Exception):
            f.result(timeout=30)
        assert srv._worker.is_alive(), "batcher thread died"
        # later traffic (now pinned to the eager path) still gets a
        # prompt per-request error, not a hang
        f2 = srv.submit(np.ones((3, 2), np.float32))
        with pytest.raises(Exception):
            f2.result(timeout=30)
        assert srv._worker.is_alive()
    finally:
        srv.close()


# ------------------------------------------------------------ kill switch

def test_kill_switch_concurrent_eager_is_serialized():
    """Review finding: with the kill switch off, eager forwards run in
    CALLER threads against a stateful model (Module adapter mutates its
    executor's arg_dict) — the server must serialize model calls or
    concurrent submits swap each other's inputs."""
    sym = _sym_net()
    mod = mx.mod.Module(sym, context=mx.cpu(0))
    mod.bind(data_shapes=[("data", (1, 12))], for_training=False)
    mod.init_params(mx.init.Xavier())
    rng = np.random.RandomState(12)
    xs = [rng.rand(12).astype(np.float32) for _ in range(40)]
    seq = []
    for x in xs:
        mod.forward(mx.io.DataBatch(data=[mx.nd.array(x[None])]),
                    is_train=False)
        seq.append(np.asarray(mod.get_outputs()[0].asnumpy())[0])
    cfg.set("MXNET_TPU_SERVE", False)
    try:
        with serve.InferenceServer(mod, name="serve_t_killconc") as srv:
            got = [None] * len(xs)
            errs = []

            def client(lo, hi):
                try:
                    for i in range(lo, hi):
                        got[i] = np.asarray(srv.submit(xs[i]).result(60))
                except Exception as exc:       # noqa: BLE001
                    errs.append(exc)

            ts = [threading.Thread(target=client, args=(t * 10,
                                                        (t + 1) * 10))
                  for t in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(120)
            assert not errs, errs
    finally:
        cfg.reset("MXNET_TPU_SERVE")
    for i in range(len(xs)):
        np.testing.assert_allclose(
            got[i], seq[i], rtol=1e-5, atol=1e-6,
            err_msg="eager request %d got another request's result" % i)


def test_gauges_survive_reset_counters():
    profiler.set_gauge("serve_test_gauge", 7.0)
    profiler.reset_counters()
    assert profiler.get_gauge("serve_test_gauge") == 7.0
    profiler.reset_gauges()
    assert profiler.get_gauge("serve_test_gauge") == 0.0


def test_kill_switch_eager_parity():
    net = _mlp()
    xs = _samples(5, seed=6)
    seq = [np.asarray(net(mx.nd.array(x[None])).asnumpy())[0] for x in xs]
    cfg.set("MXNET_TPU_SERVE", False)
    try:
        with serve.InferenceServer(net, max_batch_size=8,
                                   name="serve_t_kill") as srv:
            before = profiler.get_counter("serve_t_kill_batches")
            got = [np.asarray(srv.submit(x).result(timeout=60))
                   for x in xs]
            # no batches were formed — every submit ran eagerly inline
            assert profiler.get_counter("serve_t_kill_batches") == before
            assert profiler.get_counter("serve_t_kill_eager") >= len(xs)
    finally:
        cfg.reset("MXNET_TPU_SERVE")
    for a, b in zip(seq, got):
        assert np.array_equal(a, b)


# --------------------------------------------------------------- adapters

def _sym_net():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def test_predictor_adapter_parity():
    sym = _sym_net()
    rng = np.random.RandomState(7)
    params = {"fc1_weight": rng.randn(16, 12).astype(np.float32) * 0.1,
              "fc1_bias": np.zeros(16, np.float32),
              "fc2_weight": rng.randn(4, 16).astype(np.float32) * 0.1,
              "fc2_bias": np.zeros(4, np.float32)}
    pred = mx.Predictor(sym.tojson(), params, {"data": (1, 12)})
    xs = [rng.rand(12).astype(np.float32) for _ in range(12)]
    seq = []
    for x in xs:
        pred.forward(data=x[None])
        seq.append(np.asarray(pred.get_output(0).asnumpy())[0])
    with serve.InferenceServer(pred, max_batch_size=8, max_delay_us=500,
                               name="serve_t_pred") as srv:
        futs = [srv.submit(x) for x in xs]
        got = [np.asarray(f.result(timeout=60)) for f in futs]
    for a, b in zip(seq, got):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
    # review finding: serving must not corrupt the predictor's declared
    # geometry — a later DIRECT forward at the bound (1, 12) shape must
    # return ONE row, not a bucket-broadcast batch
    pred.forward(data=xs[0][None])
    direct = np.asarray(pred.get_output(0).asnumpy())
    assert direct.shape == (1, 4), direct.shape
    np.testing.assert_allclose(direct[0], seq[0], rtol=1e-6, atol=1e-7)


def test_abandoned_server_is_collected_and_thread_exits():
    """Review finding: a server dropped without close() must be
    garbage-collectable (the batcher holds it only weakly between
    iterations) and its thread must exit instead of polling forever."""
    import gc
    import weakref as _weakref
    net = _mlp()
    srv = serve.InferenceServer(net, max_batch_size=4, max_delay_us=200,
                                name="serve_t_gc")
    srv.submit(_samples(1)[0]).result(timeout=60)
    worker = srv._worker
    ref = _weakref.ref(srv)
    del srv
    for _ in range(100):        # worker may briefly hold its strong ref
        gc.collect()
        if ref() is None:
            break
        time.sleep(0.05)
    assert ref() is None, "dropped server was never collected"
    worker.join(5.0)
    assert not worker.is_alive(), "batcher thread outlived its server"


def test_module_adapter_parity():
    sym = _sym_net()
    mod = mx.mod.Module(sym, context=mx.cpu(0))
    mod.bind(data_shapes=[("data", (4, 12))], for_training=False)
    mod.init_params(mx.init.Xavier())
    rng = np.random.RandomState(8)
    xs = [rng.rand(12).astype(np.float32) for _ in range(8)]
    seq = []
    for x in xs:
        mod.forward(mx.io.DataBatch(data=[mx.nd.array(x[None])]),
                    is_train=False)
        seq.append(np.asarray(mod.get_outputs()[0].asnumpy())[0])
    with serve.InferenceServer(mod, max_batch_size=8, max_delay_us=500,
                               name="serve_t_mod") as srv:
        futs = [srv.submit(x) for x in xs]
        got = [np.asarray(f.result(timeout=60)) for f in futs]
    for a, b in zip(seq, got):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


# ------------------------------------------------------------------ stats

def test_stats_snapshot_schema():
    net = _mlp()
    with serve.InferenceServer(net, max_batch_size=8, max_delay_us=300,
                               name="serve_t_stats") as srv:
        for f in [srv.submit(x) for x in _samples(30, seed=9)]:
            f.result(timeout=60)
        s = srv.stats()
    assert s["requests"] == 30
    assert s["batches"] >= 1
    assert 0 < s["occupancy"] <= 1.0
    assert s["avg_batch_rows"] >= 1
    lat = s["latency"]
    for k in ("p50_ms", "p95_ms", "p99_ms", "mean_ms", "max_ms"):
        assert lat[k] >= 0
    assert lat["p50_ms"] <= lat["p95_ms"] <= lat["p99_ms"] <= lat["max_ms"]
    assert s["buckets"], "per-bucket table empty"
    assert profiler.get_gauge("serve_t_stats_queue_depth") == 0

"""serve generative decode — continuous batching + bucketed KV cache
(ISSUE 16 tentpole).

The contract under test: prefill logits match the Module forward
bit-for-bit-ish (f32 ~1e-6) at the last real position, greedy
generation is COMPOSITION-INVARIANT (a sequence decodes the same tokens
alone as co-resident with strangers — padding and slot reuse never
bleed), int8 KV tracks f32 within documented tolerance, the executable
universe stays |prompt buckets| + |decode buckets| with zero
steady-state recompiles (counter-asserted), streaming works (iterator /
result / callback), joins land mid-flight, and the fault matrix holds:
``serve.decode`` kills ONE sequence's future, never the co-resident
batch; ``serve.evict`` fails the handle but still frees the pages.
"""
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, profiler
from mxnet_tpu import io as io_mod
from mxnet_tpu.serve import (DeadlineExceeded, GenerativeServer, QueueFull,
                             ServeError, ServerClosed)

VOCAB, LAYERS, DMODEL, HEADS, SEQ = 128, 2, 32, 2, 16


def _module(seed=11):
    from mxnet_tpu.models import transformer
    net = transformer.get_symbol(vocab_size=VOCAB, num_layers=LAYERS,
                                 d_model=DMODEL, n_heads=HEADS,
                                 seq_len=SEQ)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (1, SEQ))],
             label_shapes=[("softmax_label", (1, SEQ))])
    mx.random.seed(seed)
    mod.init_params(mx.init.Uniform(0.05))
    return mod


@pytest.fixture(scope="module")
def module():
    return _module()


def _ref_probs(mod, seq):
    """Module forward softmax row at the last real position."""
    data = np.zeros((1, SEQ), np.float32)
    data[0, :len(seq)] = seq
    mod.forward(io_mod.DataBatch(data=[mx.nd.array(data)]), is_train=False)
    return mod.get_outputs()[0].asnumpy().reshape(SEQ, -1)[len(seq) - 1]


def _softmax(x):
    e = np.exp(x - x.max())
    return e / e.sum()


def _server(module, **kw):
    kw.setdefault("max_sequences", 4)
    kw.setdefault("page", 4)
    kw.setdefault("int8", False)
    return GenerativeServer(module, n_heads=HEADS, **kw)


# ------------------------------------------------------------- correctness

def test_prefill_logits_match_module_forward(module):
    """The decode engine's prefill IS the model: softmax at the last
    real prompt position matches the bucket-padded Module forward."""
    from mxnet_tpu._fused import CompileCache
    from mxnet_tpu.serve.decode import DecodeEngine, extract_params
    from mxnet_tpu.serve.kv_cache import KVCache
    params = extract_params(module)
    cache = KVCache(LAYERS, HEADS, DMODEL // HEADS, 2, SEQ, page=4,
                    int8=False, name="parity")
    eng = DecodeEngine(params, HEADS, cache, CompileCache("parity"),
                       name="parity")
    for prompt in ([3, 11, 7, 2, 9], [1], [5] * 15):
        slot = cache.acquire(len(prompt))
        logits = eng.prefill(np.array(prompt), slot)
        err = np.abs(_ref_probs(module, prompt)
                     - _softmax(logits)).max()
        assert err < 1e-4, "prompt %r: %g" % (prompt, err)
        cache.release(slot)


def test_decode_steps_match_full_forward(module):
    """Incremental KV decode == full re-forward at every step (greedy
    tokens identical, probabilities within f32 tolerance)."""
    from mxnet_tpu._fused import CompileCache
    from mxnet_tpu.serve.decode import DecodeEngine, extract_params
    from mxnet_tpu.serve.kv_cache import KVCache
    params = extract_params(module)
    cache = KVCache(LAYERS, HEADS, DMODEL // HEADS, 2, SEQ, page=4,
                    int8=False, name="steps")
    eng = DecodeEngine(params, HEADS, cache, CompileCache("steps"),
                       name="steps")
    prompt = [3, 11, 7, 2, 9]
    slot = cache.acquire(len(prompt))
    seq = list(prompt) + [int(np.argmax(eng.prefill(np.array(prompt),
                                                    slot)))]
    pos = len(prompt)
    for _ in range(6):
        t = np.zeros((2,), np.int32)
        p = np.zeros((2,), np.int32)
        a = np.zeros((2,), bool)
        t[slot], p[slot], a[slot] = seq[-1], pos, True
        logits = eng.decode_step(t, p, a)[slot]
        cache.grow(slot)
        pos += 1
        ref = _ref_probs(module, seq)
        assert np.abs(ref - _softmax(logits)).max() < 1e-4
        assert int(np.argmax(logits)) == int(np.argmax(ref))
        seq.append(int(np.argmax(logits)))
    cache.release(slot)


def test_greedy_generation_composition_invariant(module):
    """THE continuous-batching correctness property: a sequence decodes
    the SAME greedy tokens alone as co-resident with other sequences —
    slot packing, masking, and bucket padding never bleed across rows."""
    srv = _server(module, name="alone")
    try:
        solo = {p: srv.submit_generate(list(p), max_new_tokens=6)
                .result(timeout=120)
                for p in ((3, 1, 4), (1, 5), (9, 2, 6, 5))}
    finally:
        srv.close()
    srv = _server(module, name="together")
    try:
        handles = {p: srv.submit_generate(list(p), max_new_tokens=6)
                   for p in solo}
        together = {p: h.result(timeout=120) for p, h in handles.items()}
    finally:
        srv.close()
    assert solo == together


def test_int8_kv_matches_f32_within_tolerance(module):
    """int8 KV documented tolerance: greedy tokens identical on this
    model, decode softmax within 5e-2 of f32 (int8 round-trip is exact
    while a page's scale is unchanged; requantization adds bounded
    noise)."""
    out = {}
    for int8 in (False, True):
        srv = _server(module, int8=int8, name="q%d" % int8)
        try:
            out[int8] = srv.submit_generate([3, 11, 7], max_new_tokens=8)\
                .result(timeout=120)
        finally:
            srv.close()
    assert out[False] == out[True]


# ------------------------------------------------------- scheduler behavior

def test_streaming_iterator_and_callback(module):
    srv = _server(module, name="stream")
    try:
        got = []
        h = srv.submit_generate([2, 4], max_new_tokens=5,
                                on_token=got.append)
        streamed = list(h)
        assert len(streamed) == 5
        assert h.result(timeout=10) == streamed
        assert got == streamed            # callback saw every token
        assert h.done()
    finally:
        srv.close()


def test_eos_stops_generation(module):
    srv = _server(module, name="eos")
    try:
        free = srv.submit_generate([7, 3], max_new_tokens=10)\
            .result(timeout=120)
        eos = free[2]
        toks = srv.submit_generate([7, 3], max_new_tokens=10,
                                   eos_id=eos).result(timeout=120)
        assert toks == free[:3]           # eos token streamed, then stop
    finally:
        srv.close()


def test_join_mid_flight_and_zero_steady_state_recompiles(module):
    """Requests joining a RUNNING batch don't recompile: after every
    bucket is warm, a second wave of joins + evictions moves the
    compile counter by ZERO while serving real tokens."""
    srv = _server(module, name="joinflight")
    try:
        first = srv.submit_generate([1, 2, 3], max_new_tokens=12)
        while not first.tokens_so_far():
            time.sleep(0.01)
        # join mid-flight, different prompt bucket
        joiners = [srv.submit_generate([5 + i], max_new_tokens=12)
                   for i in range(2)]
        for h in [first] + joiners:
            assert len(h.result(timeout=120)) == 12
        warm_compiles = profiler.get_counter("joinflight_compile")
        assert warm_compiles <= srv.engine.executable_bound()
        # steady state: every bucket warm, so a full second wave is hits
        wave = [srv.submit_generate([i + 1, i + 2], max_new_tokens=9)
                for i in range(4)]
        for h in wave:
            assert len(h.result(timeout=120)) == 9
        assert profiler.get_counter("joinflight_compile") == warm_compiles
        st = srv.stats()
        assert st["compiles"] <= st["executable_bound"]
        assert st["kv"]["slots_in_use"] == 0      # all evicted and freed
        assert st["tokens"] >= 3 * 12 + 4 * 9
        assert st["ttft"] and st["tpot"]          # latency pair populated
    finally:
        srv.close()


def test_deadline_and_queue_full(module):
    srv = _server(module, max_sequences=1, queue_bound=1, name="shed")
    try:
        # soak the single slot so later submits queue
        long_run = srv.submit_generate([1, 2], max_new_tokens=12)
        while srv.stats()["active_sequences"] < 1:
            time.sleep(0.01)
        expired = srv.submit_generate([3], max_new_tokens=2,
                                      timeout=0.0)      # TTFT deadline
        with pytest.raises(QueueFull):
            for _ in range(50):
                srv.submit_generate([4], max_new_tokens=2)
        with pytest.raises(DeadlineExceeded):
            expired.result(timeout=120)
        assert profiler.get_counter("shed_shed") >= 1
        assert profiler.get_counter("shed_deadline_expired") >= 1
        assert len(long_run.result(timeout=120)) == 12
    finally:
        srv.close()


def test_submit_after_close_raises(module):
    srv = _server(module, name="closed")
    srv.close()
    with pytest.raises(ServerClosed):
        srv.submit_generate([1], max_new_tokens=1)


def test_close_drains_waiting_requests(module):
    srv = _server(module, max_sequences=1, queue_bound=8, name="drain")
    handles = [srv.submit_generate([i + 1], max_new_tokens=3)
               for i in range(3)]
    srv.close(drain=True)
    for h in handles:
        assert len(h.result(timeout=10)) == 3


def test_submit_mid_drain_rejected_promptly(module):
    """A submit issued WHILE close(drain=True) is still draining must
    raise ServerClosed immediately — not enqueue behind a scheduler
    that is about to exit (ISSUE 20 satellite)."""
    srv = _server(module, max_sequences=1, queue_bound=8, name="middrain")
    inflight = srv.submit_generate([1, 2], max_new_tokens=10)
    while not inflight.tokens_so_far():
        time.sleep(0.01)
    closer = threading.Thread(target=lambda: srv.close(drain=True))
    closer.start()
    deadline = time.time() + 10
    while not srv._closed and time.time() < deadline:
        time.sleep(0.001)
    assert srv._closed
    t0 = time.time()
    with pytest.raises(ServerClosed):
        srv.submit_generate([9], max_new_tokens=2)
    assert time.time() - t0 < 1.0         # rejected, not queued-then-failed
    # the drain promise still stands for work admitted before the close
    assert len(inflight.result(timeout=120)) == 10
    closer.join(timeout=120)
    assert not closer.is_alive()


def test_second_close_cannot_revoke_drain_promise(module):
    """close() is idempotent the way InferenceServer.close() documents:
    a second close(drain=False) during a first close(drain=True) only
    joins — it must not cancel sequences the first close promised to
    finish."""
    srv = _server(module, max_sequences=1, queue_bound=8, name="reclose")
    slow = srv.submit_generate([3, 5], max_new_tokens=10)
    queued = srv.submit_generate([4], max_new_tokens=3)
    while not slow.tokens_so_far():
        time.sleep(0.01)
    closer = threading.Thread(target=lambda: srv.close(drain=True))
    closer.start()
    while not srv._closed:
        time.sleep(0.001)
    srv.close(drain=False, timeout=120)   # must behave as drain=True
    assert len(slow.result(timeout=120)) == 10
    assert len(queued.result(timeout=120)) == 3
    closer.join(timeout=120)


# --------------------------------------------------------- tp-sharded KV

HEADS_TP = 4


@pytest.fixture(scope="module")
def module4():
    """4-head variant: the tp=4 island needs a head axis it can split
    (2 heads over tp=4 would leave idle shards)."""
    from mxnet_tpu.models import transformer
    net = transformer.get_symbol(vocab_size=VOCAB, num_layers=LAYERS,
                                 d_model=DMODEL, n_heads=HEADS_TP,
                                 seq_len=SEQ)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (1, SEQ))],
             label_shapes=[("softmax_label", (1, SEQ))])
    mx.random.seed(11)
    mod.init_params(mx.init.Uniform(0.05))
    return mod


def test_tp_sharded_kv_decode_parity(module4):
    """GenerativeServer with the KV cache head axis sharded tp=4 over
    the 8-device virtual mesh (``island_specs("serve")``): greedy
    tokens identical to the unsharded server, joins/evictions work, and
    steady-state decode stays at ZERO recompiles (ISSUE 20 satellite)."""
    from mxnet_tpu.parallel import SpecLayout
    lo = SpecLayout(tp=4).sized(8)
    mesh = lo.mesh()
    ref_srv = GenerativeServer(module4, n_heads=HEADS_TP, max_sequences=4,
                               page=4, int8=False, name="tpref")
    try:
        ref = {}
        for p in ([3, 11, 7], [5, 2]):
            ref[tuple(p)] = ref_srv.submit_generate(
                p, max_new_tokens=8).result(timeout=120)
    finally:
        ref_srv.close()
    srv = GenerativeServer(module4, n_heads=HEADS_TP, max_sequences=4,
                           page=4, int8=False, name="tpshard",
                           mesh=mesh, layout=lo)
    try:
        first = srv.submit_generate([3, 11, 7], max_new_tokens=8)
        while not first.tokens_so_far():
            time.sleep(0.01)
        joiner = srv.submit_generate([5, 2], max_new_tokens=8)   # mid-flight
        assert first.result(timeout=240) == ref[(3, 11, 7)]
        assert joiner.result(timeout=240) == ref[(5, 2)]
        warm = profiler.get_counter("tpshard_compile")
        wave = [srv.submit_generate([i + 1, i + 2], max_new_tokens=6)
                for i in range(4)]
        for h in wave:
            assert len(h.result(timeout=240)) == 6
        # every bucket warm: the second wave moved the counter by ZERO
        assert profiler.get_counter("tpshard_compile") == warm
        st = srv.stats()
        assert st["kv"]["slots_in_use"] == 0       # evictions freed pages
    finally:
        srv.close()


def test_tp_sharded_int8_parity(module4):
    """int8 KV under the tp=4 sharding: greedy tokens match the sharded
    f32 server (the quantized page layout shards the same head axis)."""
    from mxnet_tpu.parallel import SpecLayout
    lo = SpecLayout(tp=4).sized(8)
    mesh = lo.mesh()
    out = {}
    for int8 in (False, True):
        srv = GenerativeServer(module4, n_heads=HEADS_TP, max_sequences=4,
                               page=4, int8=int8, mesh=mesh, layout=lo,
                               name="tpq%d" % int8)
        try:
            out[int8] = srv.submit_generate(
                [3, 11, 7], max_new_tokens=8).result(timeout=240)
        finally:
            srv.close()
    assert out[False] == out[True]


def test_capacity_truncation(module):
    """A sequence hitting max_seq finishes (truncated) instead of
    wedging the batch."""
    srv = _server(module, name="trunc")
    try:
        toks = srv.submit_generate([1] * (SEQ - 2), max_new_tokens=50)\
            .result(timeout=120)
        assert 1 <= len(toks) <= SEQ      # bounded by cache capacity
        assert srv.stats()["kv"]["slots_in_use"] == 0
    finally:
        srv.close()


# ------------------------------------------------------------- fault drills

def test_fault_decode_kills_one_sequence_not_batch(module):
    """serve.decode@n kills ONE sequence's future with a legible error;
    co-resident sequences keep decoding to completion."""
    srv = _server(module, name="fdec")
    try:
        # b streaming its first token proves co-residency; steps are
        # ~1ms so the observer can miss a's whole lifetime under GIL
        # scheduling — retry until caught co-resident
        for _ in range(10):
            a = srv.submit_generate([1, 2, 3], max_new_tokens=30)
            while not a.tokens_so_far():
                time.sleep(0.001)
            b = srv.submit_generate([4, 5], max_new_tokens=10)
            while not b.tokens_so_far():
                time.sleep(0.0005)
            if not a.done():
                break
            b.result(timeout=120)      # drain the attempt and retry
        else:
            raise AssertionError("never caught a and b co-resident")
        faults.install("serve.decode@1")
        try:
            # exactly ONE dies (slot reuse is LIFO so which handle holds
            # the victim slot varies); the co-resident completes
            outcomes = []
            for h in (a, b):
                try:
                    outcomes.append(("ok", len(h.result(timeout=120))))
                except ServeError as exc:
                    assert "serve.decode" in str(exc)
                    outcomes.append(("killed", None))
        finally:
            faults.clear()
        assert [k for k, _ in outcomes].count("killed") == 1
        survivor = [n for k, n in outcomes if k == "ok"][0]
        assert survivor in (10, SEQ - 3)  # b's 10, or a truncated
        assert srv.stats()["kv"]["slots_in_use"] == 0
    finally:
        faults.clear()
        srv.close()


def test_fault_evict_fails_handle_but_frees_pages(module):
    """serve.evict@n fails the finishing handle legibly, but the pages
    are STILL freed — an eviction fault must never leak the slot."""
    srv = _server(module, name="fevt")
    try:
        faults.install("serve.evict@1")
        try:
            h = srv.submit_generate([1, 2], max_new_tokens=2)
            with pytest.raises(ServeError, match="serve.evict"):
                h.result(timeout=120)
            assert "pages were still freed" in str(h.exception)
        finally:
            faults.clear()
        st = srv.stats()
        assert st["kv"]["slots_in_use"] == 0      # NO leak
        assert st["kv"]["pages_in_use"] == 0
        # the server still serves after the drill
        assert len(srv.submit_generate([3], max_new_tokens=2)
                   .result(timeout=120)) == 2
    finally:
        faults.clear()
        srv.close()


# ------------------------------------------------------- stats + gate

def test_stats_schema_superset(module):
    """Regression: InferenceServer.stats() keys survive untouched, and
    the generative snapshot carries the documented new keys."""
    srv = _server(module, name="schema")
    try:
        srv.submit_generate([1, 2], max_new_tokens=3).result(timeout=120)
        st = srv.stats()
    finally:
        srv.close()
    for k in ("requests", "compiles", "cache_hits", "shed",
              "deadline_expired"):      # shared with InferenceServer
        assert k in st, k
    for k in ("tokens", "decode_steps", "active_sequences", "waiting",
              "evicted", "executable_bound", "kv", "buckets", "ttft",
              "tpot"):
        assert k in st, k
    for k in ("slots_in_use", "pages_in_use", "occupancy", "max_slots",
              "page", "int8", "hbm_bytes"):
        assert k in st["kv"], k
    assert st["buckets"]["decode"][-1] == SEQ
    for side in ("ttft", "tpot"):
        assert st[side] is not None
        for k in ("p50_ms", "p95_ms", "p99_ms", "window"):
            assert k in st[side], (side, k)


def test_batch_server_stats_schema_unchanged():
    """The pre-existing InferenceServer.stats() schema is pinned — the
    decode work must not have moved it."""
    from mxnet_tpu.gluon import nn
    net = nn.Sequential()
    net.add(nn.Dense(8))
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(np.zeros((1, 4), np.float32)))
    srv = mx.serve.InferenceServer(net, max_batch_size=4, name="pin")
    try:
        srv.submit(np.zeros((4,), np.float32)).result(timeout=120)
        st = srv.stats()
    finally:
        srv.close()
    for k in ("requests", "batches", "avg_batch_rows", "buckets",
              "compiles", "cache_hits"):
        assert k in st, k


def test_zero_cost_import_gate():
    """Importing mxnet_tpu.serve (or mxnet_tpu) must NOT import the
    decode path — kv_cache/decode load lazily on first use."""
    code = (
        "import sys\n"
        "import mxnet_tpu\n"
        "import mxnet_tpu.serve\n"
        "bad = [m for m in sys.modules\n"
        "       if m in ('mxnet_tpu.serve.decode',\n"
        "                'mxnet_tpu.serve.kv_cache')]\n"
        "assert not bad, bad\n"
        "from mxnet_tpu.serve import KVCache\n"
        "assert 'mxnet_tpu.serve.kv_cache' in sys.modules\n"
        "print('GATE-OK')\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=240)
    assert "GATE-OK" in out.stdout, out.stdout + out.stderr

"""Scan-over-layers (ISSUE 9): detection soundness, bit-identical
lowering, and the fused-fit integration.

The contract (mxnet_tpu/symbol/scan.py): chains of verified-isomorphic
repeated blocks lower through ONE ``jax.lax.scan``; anything that does
not verify falls back to the unrolled path silently. Forward is
bit-identical to unrolled execution; backward is allowed 2 float32 ulps
(XLA fuses the pointwise backward chains differently across the two
program shapes — the divergence is reassociation, not math).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym_mod
from mxnet_tpu.models import transformer
from mxnet_tpu.symbol.scan import build_scan_plan

sym = mx.sym

V, T, B = 64, 8, 2


def _tf(num_layers=4, d_model=32, seq_len=T):
    return transformer.get_symbol(vocab_size=V, num_layers=num_layers,
                                  d_model=d_model, n_heads=2,
                                  seq_len=seq_len)


def _bind_pair(net, data_shapes, label_shapes=None, seed=3):
    """Two executors over identical params/RNG: scan off and scan on."""
    executors = []
    for mode in ("off", "2"):
        mx.config.set("MXNET_TPU_SCAN_LAYERS", mode)
        try:
            kw = {n: s for n, s in data_shapes.items()}
            if label_shapes:
                kw.update(label_shapes)
            executors.append(net.simple_bind(mx.cpu(), **kw))
        finally:
            mx.config.set("MXNET_TPU_SCAN_LAYERS", "auto")
    ex0, ex1 = executors
    rs = np.random.RandomState(seed)
    for n, a in ex0.arg_dict.items():
        val = rs.uniform(-0.1, 0.1, a.shape).astype(np.float32)
        a[:] = val
        ex1.arg_dict[n][:] = val
    ex1._base_key = ex0._base_key
    return ex0, ex1


# ------------------------------------------------------------- detection

def test_detects_transformer_chain():
    plan = build_scan_plan(_tf(4), min_repeat=2)
    assert plan is not None
    assert plan.n_layers == 4
    assert len(plan.var_lists) == 12          # 12 params per block
    assert all(len(v) == 4 for v in plan.var_lists.values())


def test_min_repeat_threshold():
    net = _tf(3)
    assert build_scan_plan(net, min_repeat=4) is None
    assert build_scan_plan(net, min_repeat=2) is not None


def test_no_chain_in_mlp():
    net = sym.FullyConnected(sym.Variable("data"), num_hidden=16,
                             name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=16, name="fc2")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=16, name="fc3")
    # fc1/fc2/fc3 form a name family but fc3 feeds no next block — the
    # chain check must reject rather than mis-scan
    assert build_scan_plan(net, min_repeat=2) is None


def test_shared_weight_chain_falls_back():
    # RNN-style unroll: ONE weight variable used by every step — no
    # per-layer family, so no plan
    x = sym.Variable("data")
    w = sym.Variable("w")
    for i in range(4):
        x = sym.FullyConnected(x, weight=w, no_bias=True, num_hidden=16,
                               name="step%d" % i)
    assert build_scan_plan(x, min_repeat=2) is None


def test_heterogeneous_blocks_fall_back():
    # same names-by-index but different widths: attrs differ -> reject
    x = sym.Variable("data")
    for i, nh in enumerate((16, 16, 32, 16)):
        x = sym.FullyConnected(x, num_hidden=nh, name="layer%d_fc" % i)
        x = sym.Activation(x, act_type="relu")
    assert build_scan_plan(x, min_repeat=2) is None


def test_internal_output_consumed_outside_falls_back():
    # expose an interior block output as a second head (get_internals
    # use case): scanning would hide the value, so no plan
    net = _tf(4)
    internals = net.get_internals()
    probe = [name for name in internals.list_outputs()
             if name.startswith("layer1_att_proj")][0]
    grouped = sym_mod.Group([net, internals[probe]])
    assert build_scan_plan(grouped, min_repeat=2) is None


def test_executor_knob_off_and_auto_threshold():
    net = _tf(4)
    mx.config.set("MXNET_TPU_SCAN_LAYERS", "off")
    try:
        ex = net.simple_bind(mx.cpu(), data=(B, T),
                             softmax_label=(B, T))
        assert ex._scan_plan is None
    finally:
        mx.config.set("MXNET_TPU_SCAN_LAYERS", "auto")
    # auto default: min repeat 4 -> a 4-layer chain scans
    ex = net.simple_bind(mx.cpu(), data=(B, T), softmax_label=(B, T))
    assert ex._scan_plan is not None and ex._scan_plan.n_layers == 4


# ----------------------------------------------------------- bit parity

def test_forward_bit_identical():
    ex0, ex1 = _bind_pair(_tf(4), {"data": (B, T)},
                          {"softmax_label": (B, T)})
    assert ex1._scan_plan is not None
    for n in ("data", "softmax_label"):
        v = np.random.RandomState(0).randint(0, V, (B, T)).astype(
            np.float32)
        ex0.arg_dict[n][:] = v
        ex1.arg_dict[n][:] = v
    o0 = ex0.forward(is_train=False)[0].asnumpy()
    o1 = ex1.forward(is_train=False)[0].asnumpy()
    np.testing.assert_array_equal(o0, o1)


def test_backward_parity_within_ulps():
    ex0, ex1 = _bind_pair(_tf(4), {"data": (B, T)},
                          {"softmax_label": (B, T)})
    for n in ("data", "softmax_label"):
        v = np.random.RandomState(1).randint(0, V, (B, T)).astype(
            np.float32)
        ex0.arg_dict[n][:] = v
        ex1.arg_dict[n][:] = v
    for ex in (ex0, ex1):
        ex.forward(is_train=True)
        ex.backward()
    for n in ex0.grad_dict:
        g0 = ex0.grad_dict[n].asnumpy()
        g1 = ex1.grad_dict[n].asnumpy()
        # 2 f32 ulps of the observed grad scale (~1e-2): XLA pointwise
        # fusion reassociates differently across program shapes
        np.testing.assert_allclose(g0, g1, rtol=0, atol=5e-9,
                                   err_msg=n)


def test_rng_ops_fold_identically():
    # dropout inside the repeated block: the per-node topo indices ride
    # the scan xs, so masks must match the unrolled program bit-for-bit
    x = sym.Variable("data")
    for i in range(4):
        x = sym.FullyConnected(x, num_hidden=16, name="blk%d_fc" % i)
        x = sym.Activation(x, act_type="relu")
        x = sym.Dropout(x, p=0.5)
    ex0, ex1 = _bind_pair(x, {"data": (8, 16)})
    assert ex1._scan_plan is not None and ex1._scan_plan.n_layers == 4
    v = np.random.RandomState(2).rand(8, 16).astype(np.float32)
    ex0.arg_dict["data"][:] = v
    ex1.arg_dict["data"][:] = v
    o0 = ex0.forward(is_train=True)[0].asnumpy()
    o1 = ex1.forward(is_train=True)[0].asnumpy()
    np.testing.assert_array_equal(o0, o1)


# ------------------------------------------------------------- fused fit

def _fit(net, scan_mode, X, Y, init, epochs=2, accum=None):
    mx.config.set("MXNET_TPU_SCAN_LAYERS", scan_mode)
    try:
        it = mx.io.NDArrayIter(X, Y, batch_size=B,
                               label_name="softmax_label")
        mod = mx.mod.Module(net, context=mx.cpu(0))
        mod.fit(it, num_epoch=epochs,
                arg_params={k: v.copy() for k, v in init.items()},
                eval_metric=mx.metric.Loss(),
                optimizer_params={"learning_rate": 0.05},
                grad_accum=accum)
        return {n: v.asnumpy() for n, v in mod.get_params()[0].items()}
    finally:
        mx.config.set("MXNET_TPU_SCAN_LAYERS", "auto")


@pytest.fixture(scope="module")
def tf_fixture():
    net = _tf(4)
    m = mx.mod.Module(net, context=mx.cpu(0))
    m.bind(data_shapes=[("data", (B, T))],
           label_shapes=[("softmax_label", (B, T))])
    rs = np.random.RandomState(5)
    init = {n: mx.nd.array(rs.uniform(-0.05, 0.05, a.shape)
                           .astype(np.float32))
            for n, a in m._exec.arg_dict.items()
            if n not in ("data", "softmax_label")}
    X = np.random.RandomState(0).randint(0, V, (8, T)).astype(np.float32)
    Y = np.random.RandomState(1).randint(0, V, (8, T)).astype(np.float32)
    return net, X, Y, init


def test_fused_fit_parity_and_counters(tf_fixture):
    net, X, Y, init = tf_fixture
    from mxnet_tpu import profiler
    p_off = _fit(net, "off", X, Y, init)
    with profiler.counter_delta() as d:
        p_on = _fit(net, "2", X, Y, init)
    assert d.get("scan_applied") >= 1
    assert d.get("loop_recompile") == 0
    for n in p_off:
        np.testing.assert_allclose(p_off[n], p_on[n], rtol=0, atol=5e-8,
                                   err_msg=n)


def test_scan_grads_reach_every_layer(tf_fixture):
    # stacked-param vjp unstacks per layer: after a step, every layer's
    # params must have moved (a silently-dropped gradient path would
    # leave a layer frozen)
    net, X, Y, init = tf_fixture
    p_on = _fit(net, "2", X, Y, init, epochs=1)
    for n, v in init.items():
        assert np.abs(p_on[n] - v.asnumpy()).max() > 0, \
            "%s never updated under scan" % n

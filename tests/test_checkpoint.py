"""mx.checkpoint — crash-safety, fault-injection, and exact-resume suite
(docs/architecture/checkpoint.md).

Three contracts under test:

* **atomicity** — ``kill -9`` at ANY byte of a save never damages the
  previous checkpoint (deterministic SIGKILL points via the
  ``MXNET_TPU_CKPT_TEST_CRASH`` hook, in subprocesses);
* **verification** — bit-flips and truncation are detected at load
  (manifest crc32) and ``load_latest`` falls back to the newest VALID
  candidate; retention GC can never delete the only valid checkpoint;
* **exact resume** — ``fit(checkpoint=..., resume_from=...)`` reproduces
  the uninterrupted run's params, aux states, and optimizer states
  bit-identically, at epoch boundaries and mid-epoch, with the async
  window >= 2, on the MLP and the BN+dropout stem (aux + RNG chains).
"""
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import config as cfg
from mxnet_tpu import profiler
from mxnet_tpu.checkpoint import (CheckpointConfig, CheckpointCorrupt,
                                  CheckpointManager, CheckpointNotFound,
                                  atomic_open, collect_garbage,
                                  list_checkpoints, load_latest,
                                  probe_valid, read_checkpoint,
                                  write_checkpoint)

BATCH = 8
NSAMP = 64
FEAT = 16
NCLS = 8

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------ helpers

def _mlp():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=12, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=NCLS, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _stem():
    """Conv + BatchNorm (aux states) + Dropout (executor RNG chain)."""
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, num_filter=8, kernel=(3, 3), pad=(1, 1),
                           name="conv0")
    bn = mx.sym.BatchNorm(c, name="bn0")
    r = mx.sym.Activation(bn, act_type="relu", name="relu0")
    p = mx.sym.Pooling(r, kernel=(2, 2), stride=(2, 2), pool_type="max",
                       name="pool0")
    f = mx.sym.Flatten(p, name="flat")
    dp = mx.sym.Dropout(f, p=0.3, name="drop0")
    fc = mx.sym.FullyConnected(dp, num_hidden=NCLS, name="fc1")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def _mlp_data():
    rng = np.random.RandomState(0)
    return (rng.uniform(-1, 1, (NSAMP, FEAT)).astype(np.float32),
            rng.randint(0, NCLS, (NSAMP,)).astype(np.float32))


def _stem_data():
    rng = np.random.RandomState(1)
    return (rng.uniform(-1, 1, (NSAMP, 3, 8, 8)).astype(np.float32),
            rng.randint(0, NCLS, (NSAMP,)).astype(np.float32))


def _seed_init(symbol, shapes):
    rng = np.random.RandomState(42)
    args, _, _ = symbol.infer_shape(**shapes)
    init = {}
    for name, shape in zip(symbol.list_arguments(), args):
        if name in shapes:
            continue
        init[name] = mx.nd.array(
            rng.uniform(-0.1, 0.1, shape).astype(np.float32))
    return init


class _Stop(Exception):
    """Simulated crash: abandons fit() from a batch-end callback, exactly
    as abruptly as the loop can be abandoned in-process."""


def _fit(symbol, X, Y, epochs, ckpt=None, resume=None, seed=True,
         stop_after=None, optimizer="sgd", opt_params=None, window=None):
    """One deterministic fit under the checkpoint knobs; returns the
    module's full param+aux dict as numpy."""
    if window is not None:
        cfg.set("MXNET_TPU_ASYNC_WINDOW", window)
    try:
        mx.random.seed(7)
        shapes = {"data": (BATCH,) + X.shape[1:], "softmax_label": (BATCH,)}
        it = mx.io.NDArrayIter(X, Y, batch_size=BATCH)
        mod = mx.mod.Module(symbol, context=mx.cpu())
        kw = {}
        if seed:
            init = _seed_init(symbol, shapes)
            kw["arg_params"] = {k: v.copy() for k, v in init.items()}
        if stop_after is not None:
            calls = [0]

            def cb(_param):
                calls[0] += 1
                if calls[0] >= stop_after:
                    raise _Stop()

            kw["batch_end_callback"] = cb
        try:
            mod.fit(it, num_epoch=epochs, optimizer=optimizer,
                    optimizer_params=opt_params
                    or {"learning_rate": 0.1},
                    checkpoint=ckpt, resume_from=resume, **kw)
        except _Stop:
            pass
        arg, aux = mod.get_params()
        w = {k: v.asnumpy().copy() for k, v in arg.items()}
        w.update({k: v.asnumpy().copy() for k, v in aux.items()})
        return mod, w
    finally:
        if window is not None:
            cfg.reset("MXNET_TPU_ASYNC_WINDOW")


def _assert_equal(w0, w1):
    assert set(w0) == set(w1)
    for k in sorted(w0):
        np.testing.assert_array_equal(w0[k], w1[k], err_msg=k)


def _tensors(step=1):
    rng = np.random.RandomState(step)
    return {"w": rng.normal(size=(32, 16)).astype(np.float32),
            "b": rng.normal(size=(16,)).astype(np.float32)}


# ----------------------------------------------------------- atomic writes

def test_atomic_open_replaces_only_on_success(tmp_path):
    p = str(tmp_path / "f.bin")
    with atomic_open(p, "wb") as f:
        f.write(b"first")
    assert open(p, "rb").read() == b"first"

    with pytest.raises(RuntimeError):
        with atomic_open(p, "wb") as f:
            f.write(b"torn-half-")
            raise RuntimeError("crash mid-write")
    # previous contents intact, no temp residue
    assert open(p, "rb").read() == b"first"
    assert os.listdir(str(tmp_path)) == ["f.bin"]


def test_atomic_open_rejects_read_modes(tmp_path):
    with pytest.raises(ValueError):
        with atomic_open(str(tmp_path / "x"), "r+b"):
            pass


def test_nd_save_failure_preserves_previous_file(tmp_path, monkeypatch):
    p = str(tmp_path / "params.npz")
    mx.nd.save(p, {"a": mx.nd.ones((3,))})

    def boom(*_a, **_k):
        raise OSError("disk on fire")

    monkeypatch.setattr(np, "savez", boom)
    with pytest.raises(OSError):
        mx.nd.save(p, {"a": mx.nd.zeros((3,))})
    monkeypatch.undo()
    out = mx.nd.load(p)                       # old file still loads clean
    np.testing.assert_array_equal(out["a"].asnumpy(), np.ones((3,)))


def test_symbol_and_model_checkpoint_still_roundtrip(tmp_path):
    prefix = str(tmp_path / "model")
    sym = _mlp()
    arg = {"fc1_weight": mx.nd.ones((12, FEAT))}
    mx.model.save_checkpoint(prefix, 3, sym, arg, {})
    s2, a2, x2 = mx.model.load_checkpoint(prefix, 3)
    assert s2.list_arguments() == sym.list_arguments()
    np.testing.assert_array_equal(a2["fc1_weight"].asnumpy(),
                                  arg["fc1_weight"].asnumpy())
    assert x2 == {}


# --------------------------------------------------------- format + verify

def test_write_read_roundtrip_and_meta(tmp_path):
    base = str(tmp_path)
    t = _tensors()
    write_checkpoint(base, 7, t, meta={"loop": {"epoch": 2,
                                                "batches_done": 5}})
    path, tensors, manifest = load_latest(base)
    assert path.endswith("ckpt-0000000007")
    _assert_equal(tensors, {k: np.asarray(v) for k, v in t.items()})
    assert manifest["meta"]["loop"]["batches_done"] == 5


def test_corruption_detected_and_fallback_to_previous(tmp_path):
    base = str(tmp_path)
    write_checkpoint(base, 1, _tensors(1))
    p2 = write_checkpoint(base, 2, _tensors(2))
    # flip one payload byte deep inside the newest arrays container
    arrays = os.path.join(p2, "arrays.npz")
    blob = bytearray(open(arrays, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(arrays, "wb").write(bytes(blob))

    with pytest.raises(CheckpointCorrupt):
        read_checkpoint(p2)
    before = profiler.get_counter("ckpt_load_fallback")
    path, tensors, _ = load_latest(base)
    assert path.endswith("ckpt-0000000001")
    _assert_equal(tensors, {k: np.asarray(v)
                            for k, v in _tensors(1).items()})
    assert profiler.get_counter("ckpt_load_fallback") == before + 1


def test_manifest_tamper_and_truncation_rejected(tmp_path):
    base = str(tmp_path)
    p = write_checkpoint(base, 1, _tensors())
    man_path = os.path.join(p, "manifest.json")
    man = json.load(open(man_path))

    man["arrays"]["w"]["shape"] = [1, 1]          # shape drift
    json.dump(man, open(man_path, "w"))
    with pytest.raises(CheckpointCorrupt):
        read_checkpoint(p)

    open(man_path, "w").write("{half a manif")    # truncation
    with pytest.raises(CheckpointCorrupt):
        read_checkpoint(p)
    assert not probe_valid(p)
    with pytest.raises(CheckpointNotFound):
        load_latest(base)


def test_probe_valid_catches_truncated_arrays(tmp_path):
    base = str(tmp_path)
    p = write_checkpoint(base, 1, _tensors())
    assert probe_valid(p)
    arrays = os.path.join(p, "arrays.npz")
    blob = open(arrays, "rb").read()
    open(arrays, "wb").write(blob[:len(blob) // 2])
    assert not probe_valid(p)


def test_corrupt_tensor_table_stays_in_fallback_chain(tmp_path):
    """Bit rot inside the manifest's tensor TABLE (JSON parses, the
    arrays-set and crc checks still pass) must surface as
    CheckpointCorrupt — a raw KeyError would break load_latest's
    fallback chain."""
    base = str(tmp_path)
    write_checkpoint(base, 1, _tensors(1))
    p2 = write_checkpoint(base, 2, _tensors(2))
    man_path = os.path.join(p2, "manifest.json")
    man = json.load(open(man_path))
    man["tensors"]["w"]["key"] = "nonexistent"
    json.dump(man, open(man_path, "w"))
    with pytest.raises(CheckpointCorrupt):
        read_checkpoint(p2)
    path, _, _ = load_latest(base)
    assert path.endswith("ckpt-0000000001")


def test_rewrite_replaces_invalid_existing_step(tmp_path):
    """A valid ckpt-<step> makes a same-step re-save a no-op, but a
    corrupt one (the checkpoint resume just fell back PAST) must not
    block re-checkpointing the retraced step forever."""
    base = str(tmp_path)
    p = write_checkpoint(base, 1, _tensors(1))
    write_checkpoint(base, 1, _tensors(2))        # skipped: valid exists
    tensors, _ = read_checkpoint(p)
    _assert_equal(tensors, {k: np.asarray(v)
                            for k, v in _tensors(1).items()})
    open(os.path.join(p, "manifest.json"), "w").write("{")
    assert not probe_valid(p)
    write_checkpoint(base, 1, _tensors(3))        # replaces the corpse
    assert probe_valid(p)
    tensors, _ = read_checkpoint(p)
    _assert_equal(tensors, {k: np.asarray(v)
                            for k, v in _tensors(3).items()})


def test_resume_payload_preserves_dtype(tmp_path):
    """arg/aux payloads must round-trip at the SAVED precision —
    nd.array's default would silently cast everything to float32."""
    from mxnet_tpu.checkpoint import restore_latest
    base = str(tmp_path)
    t = {"arg:w64": np.arange(4, dtype=np.float64),
         "arg:w16": np.ones((3,), dtype=np.float16)}
    write_checkpoint(base, 1, t, meta={"param_names": ["w64", "w16"]})
    ck = restore_latest(base)
    nd_args = ck.arg_params_nd()
    assert nd_args["w16"].dtype == np.float16
    # f64 models only exist under x64 (jax stores f32 otherwise), so the
    # f64 leg of the round-trip is asserted there
    from jax.experimental import enable_x64
    with enable_x64():
        nd64 = ck.arg_params_nd()["w64"]
        assert nd64.dtype == np.float64
        np.testing.assert_array_equal(nd64.asnumpy(), t["arg:w64"])


def test_no_optimizer_saves_are_not_deduped(tmp_path):
    """A bound-but-no-optimizer snapshot reports step 0 every time; the
    one-state-per-step dedup must not silently drop later saves."""
    class _FakeMod:
        def __init__(self):
            self.v = 0

        def _checkpoint_snapshot(self):
            self.v += 1
            return {"w": np.full((2,), self.v, np.float32)}, {"step": 0}

    mgr = CheckpointManager(CheckpointConfig(str(tmp_path),
                                             async_save=False))
    fm = _FakeMod()
    s1 = mgr.save_module(fm, epoch=0)
    s2 = mgr.save_module(fm, epoch=1)
    assert s2 > s1
    assert len(list_checkpoints(str(tmp_path))) == 2
    _, tensors, _ = load_latest(str(tmp_path))
    assert tensors["w"][0] == 2                   # newest payload won
    mgr.close()


def test_atomic_open_reaps_dead_writer_temps(tmp_path):
    """kill -9 mid-save leaves a hidden temp next to the target; the
    next save of the SAME artifact must reap it (dead pid in the name)
    instead of letting full-size orphans accumulate forever."""
    target = str(tmp_path / "x.bin")
    stale = str(tmp_path / ".x.bin.tmp-999999999-abcd")
    open(stale, "wb").write(b"orphan")
    with atomic_open(target, "wb") as f:
        f.write(b"data")
    assert not os.path.exists(stale)
    assert open(target, "rb").read() == b"data"


def test_atomic_open_honors_umask_permissions(tmp_path):
    """mkstemp creates 0600; the rename must not silently demote
    artifacts from the umask-derived mode plain open() would give."""
    p = str(tmp_path / "artifact.bin")
    with atomic_open(p, "wb") as f:
        f.write(b"payload")
    umask = os.umask(0)
    os.umask(umask)
    assert (os.stat(p).st_mode & 0o777) == (0o666 & ~umask)


# ------------------------------------------------- SIGKILL fault injection

_CRASH_CHILD = r"""
import os, sys
sys.path.insert(0, %(repo)r)
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
from mxnet_tpu.checkpoint import write_checkpoint
base = %(base)r
rng = np.random.RandomState(0)
t = {"w": rng.normal(size=(64, 32)).astype(np.float32)}
write_checkpoint(base, 1, t)                      # clean previous ckpt
os.environ["MXNET_TPU_CKPT_TEST_CRASH"] = %(point)r
write_checkpoint(base, 2, t)                      # SIGKILLed mid-write
print("NOT-REACHED")
"""


@pytest.mark.parametrize("point", ["after_arrays", "after_manifest",
                                   "before_rename"])
def test_sigkill_mid_write_never_loses_previous(tmp_path, point):
    """kill -9 at every deterministic point of the write protocol: the
    previous checkpoint stays the newest loadable state and the residue
    is a .tmp-* directory readers never consider."""
    base = str(tmp_path)
    proc = subprocess.run(
        [sys.executable, "-c",
         _CRASH_CHILD % {"repo": REPO, "base": base, "point": point}],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": ""})
    assert proc.returncode == -signal.SIGKILL, proc.stdout + proc.stderr
    assert "NOT-REACHED" not in proc.stdout

    assert [s for s, _ in list_checkpoints(base)] == [1]
    path, tensors, _ = load_latest(base)
    assert path.endswith("ckpt-0000000001")
    assert tensors["w"].shape == (64, 32)
    # the dead writer left a .tmp residue; GC reaps it (pid is gone)
    residue = [n for n in os.listdir(base) if n.startswith(".tmp-")]
    assert residue
    collect_garbage(base, keep_last=5)
    assert not [n for n in os.listdir(base) if n.startswith(".tmp-")]


# ------------------------------------------------------------ retention GC

def test_gc_keep_last_and_keep_every(tmp_path):
    base = str(tmp_path)
    for s in range(1, 11):
        write_checkpoint(base, s, _tensors(s))
    removed = collect_garbage(base, keep_last=2, keep_every=4)
    steps = [s for s, _ in list_checkpoints(base)]
    assert steps == [4, 8, 9, 10]          # keep-every multiples + last 2
    assert removed == 6


def test_gc_never_deletes_only_valid_checkpoint(tmp_path):
    base = str(tmp_path)
    p1 = write_checkpoint(base, 1, _tensors(1))
    write_checkpoint(base, 2, _tensors(2))
    p3 = write_checkpoint(base, 3, _tensors(3))
    # corrupt the two newest: the single valid one must survive ANY quota
    for p in (p3,):
        open(os.path.join(p, "arrays.npz"), "wb").write(b"junk")
    open(os.path.join(p1, "manifest.json"), "w").write("{")
    collect_garbage(base, keep_last=1)
    steps = [s for s, _ in list_checkpoints(base)]
    assert 2 in steps                      # the only valid one survived
    path, _, _ = load_latest(base)
    assert path.endswith("ckpt-0000000002")
    # corrupt candidates are left for the operator, never auto-deleted
    assert set(steps) == {1, 2, 3}


def test_gc_disabled_and_knob_default(tmp_path):
    base = str(tmp_path)
    for s in range(1, 4):
        write_checkpoint(base, s, _tensors(s))
    assert collect_garbage(base, keep_last=0) == 0
    assert len(list_checkpoints(base)) == 3
    c = CheckpointConfig(base)
    assert c.resolved_keep_last() == cfg.get("MXNET_TPU_CKPT_KEEP")
    assert c.resolved_async() == cfg.get("MXNET_TPU_CKPT_ASYNC")


# --------------------------------------------------------- manager lifecycle

def test_async_write_error_surfaces_at_close(tmp_path):
    blocker = str(tmp_path / "blocker")
    open(blocker, "w").write("a file where the base dir must go")
    mgr = CheckpointManager(CheckpointConfig(
        os.path.join(blocker, "sub"), async_save=True))
    mgr.save({"w": np.ones((4,), np.float32)}, {}, step=1)
    with pytest.raises(mx.checkpoint.CheckpointError):
        mgr.close()
    assert profiler.get_counter("ckpt_write_failed") >= 1


def test_sync_save_blocks_and_writes(tmp_path):
    mgr = CheckpointManager(CheckpointConfig(str(tmp_path),
                                             async_save=False))
    mgr.save({"w": np.ones((4,), np.float32)}, {"k": 1}, step=5)
    assert [s for s, _ in list_checkpoints(str(tmp_path))] == [5]
    mgr.close()


def test_async_blocking_is_fraction_of_write_time(tmp_path):
    """The CheckFreq split, counter-asserted: an async save blocks the
    caller for well under 25%% of the background serialization time (the
    arrays are big enough that npz+crc+fsync dominates queue handoff).
    The writer is drained between saves — real checkpoint periods dwarf
    the write time; back-to-back saturation (bounded-queue backpressure)
    is exercised separately below."""
    mgr = CheckpointManager(CheckpointConfig(str(tmp_path),
                                             async_save=True,
                                             keep_last=0))
    rng = np.random.RandomState(0)
    tensors = {"w%d" % i: rng.normal(size=(256, 256)).astype(np.float32)
               for i in range(8)}          # ~2 MB per save
    with profiler.counter_delta() as d:
        for step in range(1, 6):
            mgr.save(dict(tensors), {}, step=step)
            mgr.wait()
    mgr.close()
    block, write = d.get("ckpt_block_us"), d.get("ckpt_write_us")
    assert write > 0 and d.get("ckpt_saved") == 5
    assert block < 0.25 * write, \
        "async save blocked %dus vs %dus write time" % (block, write)


def test_async_backpressure_bounds_queue(tmp_path):
    """Back-to-back saves past the queue depth must block (bounded
    memory: each queued snapshot pins a parameter generation) and be
    counted, not dropped — every save still reaches disk."""
    mgr = CheckpointManager(CheckpointConfig(str(tmp_path),
                                             async_save=True, keep_last=0,
                                             queue_depth=1))
    rng = np.random.RandomState(0)
    tensors = {"w": rng.normal(size=(512, 512)).astype(np.float32)}
    with profiler.counter_delta() as d:
        for step in range(1, 7):
            mgr.save(dict(tensors), {}, step=step)
        mgr.wait()
    mgr.close()
    assert d.get("ckpt_saved") == 6
    assert d.get("ckpt_backpressure_wait") >= 1
    assert [s for s, _ in list_checkpoints(str(tmp_path))] == \
        list(range(1, 7))


# ------------------------------------------------------------ exact resume

def test_resume_epoch_boundary_parity_mlp(tmp_path):
    X, Y = _mlp_data()
    _, w_ref = _fit(_mlp(), X, Y, epochs=4)
    ckpt = CheckpointConfig(str(tmp_path), period_epochs=1)
    _fit(_mlp(), X, Y, epochs=2, ckpt=ckpt)
    assert list_checkpoints(str(tmp_path))
    _, w_res = _fit(_mlp(), X, Y, epochs=4, ckpt=ckpt,
                    resume=str(tmp_path), seed=False)
    _assert_equal(w_ref, w_res)


def test_resume_mid_epoch_parity_mlp(tmp_path):
    """Killed mid-epoch-1 after a scheduled batch save: the resumed run
    restores loop position + RNG + optimizer state and replays the tail
    bit-identically (params AND optimizer states)."""
    X, Y = _mlp_data()
    ref_mod, w_ref = _fit(_mlp(), X, Y, epochs=2)
    ckpt = CheckpointConfig(str(tmp_path), every_n_batches=3,
                            period_epochs=1)
    _fit(_mlp(), X, Y, epochs=2, ckpt=ckpt, stop_after=11)
    res_mod, w_res = _fit(_mlp(), X, Y, epochs=2, ckpt=ckpt,
                          resume=str(tmp_path), seed=False)
    _assert_equal(w_ref, w_res)
    # optimizer-state parity, leaf by leaf
    ref_states = ref_mod._fused_states
    res_states = res_mod._fused_states
    assert set(ref_states) == set(res_states)
    import jax
    for n in ref_states:
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=n),
            ref_states[n], res_states[n])


def test_resume_parity_bn_stem_async_window2(tmp_path):
    """The hard case from the acceptance criteria: BatchNorm aux states +
    dropout (executor PRNG chain) + adam state tuples, with the async
    in-flight window at depth 2, killed mid-epoch."""
    X, Y = _stem_data()
    _, w_ref = _fit(_stem(), X, Y, epochs=3, optimizer="adam",
                    opt_params={"learning_rate": 0.01}, window=2)
    ckpt = CheckpointConfig(str(tmp_path), every_n_batches=5,
                            period_epochs=1)
    _fit(_stem(), X, Y, epochs=3, ckpt=ckpt, stop_after=13,
         optimizer="adam", opt_params={"learning_rate": 0.01}, window=2)
    _, w_res = _fit(_stem(), X, Y, epochs=3, ckpt=ckpt,
                    resume=str(tmp_path), seed=False, optimizer="adam",
                    opt_params={"learning_rate": 0.01}, window=2)
    _assert_equal(w_ref, w_res)


def test_resume_from_empty_directory_raises(tmp_path):
    X, Y = _mlp_data()
    with pytest.raises(CheckpointNotFound):
        _fit(_mlp(), X, Y, epochs=1, resume=str(tmp_path))


def test_checkpoint_config_accepts_pathlike(tmp_path):
    c = CheckpointConfig.coerce(tmp_path)          # a pathlib.Path
    assert c.directory == str(tmp_path)


def test_preempt_save_survives_stale_async_error(tmp_path):
    """A stale async-write failure from earlier in the run must not
    abort the SIGTERM exit-143 protocol once the final synchronous save
    has landed."""
    class _FakeMod:
        def _checkpoint_snapshot(self):
            return {"w": np.zeros((2,), np.float32)}, {"step": 1}

    mgr = CheckpointManager(CheckpointConfig(str(tmp_path)))
    mgr._last_error = RuntimeError("earlier async write failed")
    mgr.preempt_save(_FakeMod(), epoch=0)          # must NOT raise
    assert [s for s, _ in list_checkpoints(str(tmp_path))] == [1]


def test_metric_state_roundtrip():
    m = mx.metric.Accuracy()
    m.sum_metric, m.num_inst = 13.0, 42
    state = m._ckpt_state()
    m2 = mx.metric.Accuracy()
    assert m2._ckpt_restore(state)
    assert (m2.sum_metric, m2.num_inst) == (13.0, 42)

    comp = mx.metric.CompositeEvalMetric(
        metrics=[mx.metric.Accuracy(), mx.metric.MSE()])
    comp.metrics[0].sum_metric = 3.0
    comp.metrics[1].num_inst = 9
    state = comp._ckpt_state()
    comp2 = mx.metric.CompositeEvalMetric(
        metrics=[mx.metric.Accuracy(), mx.metric.MSE()])
    assert comp2._ckpt_restore(state)
    assert comp2.metrics[0].sum_metric == 3.0
    assert comp2.metrics[1].num_inst == 9
    assert not comp2._ckpt_restore({"kind": "scalar"})   # shape mismatch


def test_composite_metric_restore_is_all_or_nothing():
    """A child failing to restore must not leave its siblings holding the
    snapshot totals while it reports tail-only — on any child failure the
    WHOLE composite resets to the consistent tail-only state."""
    comp = mx.metric.CompositeEvalMetric(
        metrics=[mx.metric.Accuracy(), mx.metric.MSE()])
    comp.metrics[0].sum_metric, comp.metrics[0].num_inst = 3.0, 4
    state = comp._ckpt_state()
    state["children"][1] = {"kind": "bogus"}      # child 1 can't consume
    comp2 = mx.metric.CompositeEvalMetric(
        metrics=[mx.metric.Accuracy(), mx.metric.MSE()])
    assert not comp2._ckpt_restore(state)
    assert comp2.metrics[0].sum_metric == 0.0     # child 0 rolled back
    assert comp2.metrics[0].num_inst == 0


# --------------------------------------- updater round trip (fused trainer)

def test_updater_states_roundtrip_under_fused_trainer():
    """get_states/set_states mid-training under the FUSED eager-update
    path (Module.update -> FusedUpdater): the restored run must continue
    bit-identically, and the restored leaves must be NDArray-wrapped
    OWNED buffers (no aliasing into the pickled blob)."""
    X, Y = _mlp_data()
    shapes = {"data": (BATCH, FEAT), "softmax_label": (BATCH,)}
    init = _seed_init(_mlp(), shapes)

    def make_module():
        mod = mx.mod.Module(_mlp(), context=mx.cpu())
        mod.bind(data_shapes=[("data", (BATCH, FEAT))],
                 label_shapes=[("softmax_label", (BATCH,))])
        mod.init_params(arg_params={k: v.copy() for k, v in init.items()})
        mod.init_optimizer(optimizer="adam",
                           optimizer_params={"learning_rate": 0.01})
        return mod

    def step(mod, i):
        batch = mx.io.DataBatch(
            data=[mx.nd.array(X[i * BATCH:(i + 1) * BATCH])],
            label=[mx.nd.array(Y[i * BATCH:(i + 1) * BATCH])])
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()

    # uninterrupted: 6 eager-fused steps
    ref = make_module()
    for i in range(6):
        step(ref, i % 4)
    ref_w, _ = ref.get_params()

    # interrupted at step 3: round trip the updater blob, continue
    a = make_module()
    for i in range(3):
        step(a, i % 4)
    blob = a._updater.get_states()
    state_a, _ = a.get_params()

    b = make_module()
    for i in range(3):
        step(b, i % 4)
    b._updater.set_states(blob)
    for idx, st in b._updater.states.items():
        def check(leaf):
            if leaf is None:
                return
            assert isinstance(leaf, mx.nd.NDArray), \
                "restored leaf %r not rewrapped" % (idx,)
        if isinstance(st, tuple):
            for leaf in st:
                check(leaf)
        else:
            check(st)
    for i in range(3, 6):
        step(b, i % 4)
    b_w, _ = b.get_params()
    for k in ref_w:
        np.testing.assert_array_equal(ref_w[k].asnumpy(),
                                      b_w[k].asnumpy(), err_msg=k)


def test_fused_module_optimizer_states_file_roundtrip(tmp_path):
    """Module.save/load_optimizer_states on the fused-step pytree path,
    mid-training, continues bit-identically (and the file write is
    atomic)."""
    X, Y = _mlp_data()
    fname = str(tmp_path / "opt.states")
    _, w_ref = _fit(_mlp(), X, Y, epochs=2, optimizer="adam",
                    opt_params={"learning_rate": 0.01})

    mx.random.seed(7)
    shapes = {"data": (BATCH, FEAT), "softmax_label": (BATCH,)}
    init = _seed_init(_mlp(), shapes)
    it = mx.io.NDArrayIter(X, Y, batch_size=BATCH)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(it, num_epoch=1, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            arg_params={k: v.copy() for k, v in init.items()})
    mod.save_optimizer_states(fname)
    arg, aux = mod.get_params()

    mx.random.seed(7)
    it2 = mx.io.NDArrayIter(X, Y, batch_size=BATCH)
    mod2 = mx.mod.Module(_mlp(), context=mx.cpu())
    mod2.fit(it2, num_epoch=1, optimizer="adam",
             optimizer_params={"learning_rate": 0.01},
             arg_params={k: v.copy() for k, v in init.items()})
    mod2.load_optimizer_states(fname)
    # continue one epoch on each; they must stay in lockstep
    it.reset()
    it2.reset()
    for m, data in ((mod, it), (mod2, it2)):
        for batch in data:
            m._fit_step(batch)
    w1, _ = mod.get_params()
    w2, _ = mod2.get_params()
    for k in w1:
        np.testing.assert_array_equal(w1[k].asnumpy(), w2[k].asnumpy(),
                                      err_msg=k)


def test_dealias_states_copies_shared_buffers():
    """Donation safety: a state leaf sharing a weight's buffer (or
    another state's) must be copied before a donating fused call."""
    import jax.numpy as jnp
    from mxnet_tpu._fused import _dealias_states
    w = jnp.ones((4,))
    s_alias = w                     # the Test-optimizer aliasing shape
    s_own = jnp.zeros((4,))
    out = _dealias_states([w], [s_alias, (s_own, s_own), None])
    assert out[0] is not w and np.array_equal(np.asarray(out[0]),
                                              np.asarray(w))
    first, second = out[1]
    assert first is s_own and second is not s_own   # intra-state dedup
    assert out[2] is None


# ------------------------------------------------- mesh / sharded save-load

def test_sharded_checkpoint_roundtrip_tp_mesh(tmp_path):
    """A tensor-parallel module saves partitioned params per shard with
    index windows in the manifest; resume reassembles and re-shards them
    and the run continues bit-identically with the uninterrupted mesh
    run."""
    from mxnet_tpu.parallel import P
    X, Y = _mlp_data()
    shardings = {"fc1_weight": P("model", None), "fc1_bias": P("model")}

    def run(epochs, ckpt=None, resume=None, seed=True):
        mx.random.seed(7)
        shapes = {"data": (BATCH, FEAT), "softmax_label": (BATCH,)}
        it = mx.io.NDArrayIter(X, Y, batch_size=BATCH)
        mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(4)],
                            mesh_shape={"data": 2, "model": 2},
                            param_shardings=shardings)
        kw = {}
        if seed:
            init = _seed_init(_mlp(), shapes)
            kw["arg_params"] = {k: v.copy() for k, v in init.items()}
        mod.fit(it, num_epoch=epochs, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1},
                checkpoint=ckpt, resume_from=resume, **kw)
        arg, aux = mod.get_params()
        return {k: v.asnumpy().copy() for k, v in arg.items()}

    w_ref = run(3)
    ckpt = CheckpointConfig(str(tmp_path), period_epochs=1)
    run(2, ckpt=ckpt)
    # the manifest records fc1_weight as a sharded tensor with windows
    path, _, manifest = load_latest(str(tmp_path))
    entry = manifest["tensors"]["arg:fc1_weight"]
    assert entry["kind"] == "sharded"
    assert entry["mesh"] == {"data": 2, "model": 2}
    assert len(entry["shards"]) == 2       # 2-way model split, data-replicated
    w_res = run(3, ckpt=ckpt, resume=str(tmp_path), seed=False)
    _assert_equal(w_ref, w_res)


# ----------------------------------------------- preemption + kill -9 smoke

_SIGTERM_CHILD = r"""
import os, signal, sys
sys.path.insert(0, %(repo)r)
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import mxnet_tpu as mx

rng = np.random.RandomState(0)
X = rng.uniform(-1, 1, (64, 16)).astype(np.float32)
Y = rng.randint(0, 8, (64,)).astype(np.float32)

data = mx.sym.Variable("data")
fc1 = mx.sym.FullyConnected(data, num_hidden=12, name="fc1")
act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
fc2 = mx.sym.FullyConnected(act, num_hidden=8, name="fc2")
sym = mx.sym.SoftmaxOutput(fc2, name="softmax")

r42 = np.random.RandomState(42)
args, _, _ = sym.infer_shape(data=(8, 16), softmax_label=(8,))
init = {n: mx.nd.array(r42.uniform(-0.1, 0.1, s).astype(np.float32))
        for n, s in zip(sym.list_arguments(), args)
        if n not in ("data", "softmax_label")}

mx.random.seed(7)
it = mx.io.NDArrayIter(X, Y, batch_size=8)
mod = mx.mod.Module(sym, context=mx.cpu())
calls = [0]
def cb(param):
    calls[0] += 1
    if calls[0] == 10:        # "preemption notice" mid-epoch-1
        os.kill(os.getpid(), signal.SIGTERM)
cfg = mx.checkpoint.CheckpointConfig(%(base)r, period_epochs=1,
                                     save_on_sigterm=True)
mod.fit(it, num_epoch=50, optimizer="sgd",
        optimizer_params={"learning_rate": 0.1},
        arg_params={k: v.copy() for k, v in init.items()},
        checkpoint=cfg, batch_end_callback=cb)
print("FINISHED-WITHOUT-PREEMPT")
"""


def test_sigterm_preemption_saves_and_exits_143(tmp_path):
    """SIGTERM during fit: the loop finishes the batch, lands a
    synchronous checkpoint, and exits 143; the checkpoint resumes into a
    run bit-identical to an uninterrupted one."""
    base = str(tmp_path)
    proc = subprocess.run(
        [sys.executable, "-c",
         _SIGTERM_CHILD % {"repo": REPO, "base": base}],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "PYTHONPATH": ""})
    assert proc.returncode == 143, proc.stdout + proc.stderr
    assert "FINISHED-WITHOUT-PREEMPT" not in proc.stdout
    assert profiler is not None
    entries = list_checkpoints(base)
    assert entries, "preemption save did not land"
    ckpt = mx.checkpoint.restore_latest(base)
    # the SIGTERM landed mid-epoch-1 (batch 10 of 8-per-epoch)
    assert ckpt.mid_epoch and ckpt.epoch == 1

    X, Y = _mlp_data()
    _, w_ref = _fit(_mlp(), X, Y, epochs=3)
    _, w_res = _fit(_mlp(), X, Y, epochs=3, resume=base, seed=False)
    _assert_equal(w_ref, w_res)


@pytest.mark.slow
def test_kill9_resume_smoke_script():
    """The CI smoke end-to-end: SIGKILL lands DURING an async checkpoint
    write, the torn candidate is skipped, and the resumed run matches the
    uninterrupted one bit-identically (tools/ckpt_kill_resume_smoke.py)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "ckpt_kill_resume_smoke.py")],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "KILL-RESUME-PARITY-OK" in proc.stdout

"""Filesystem abstraction (mx.filesystem): URI-scheme dispatch, staging
semantics, and its wiring into nd.save/load and RecordIO.

Reference parity: dmlc-core's Stream layer, which lets checkpoints and
RecordIO datasets live on s3://... URIs (SURVEY.md §2.11). No egress in
this environment, so a custom test scheme plays the remote backend.
"""
import contextlib
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import filesystem as fs


@pytest.fixture
def memfs(tmp_path):
    """A fake remote scheme backed by a hidden directory: mem://name."""
    store = tmp_path / "remote_store"
    store.mkdir()
    log = {"reads": 0, "writes": 0}

    @contextlib.contextmanager
    def opener(path, mode):
        import shutil
        import tempfile
        local = tempfile.NamedTemporaryFile(delete=False).name
        try:
            if "r" in mode:
                log["reads"] += 1
                shutil.copyfile(str(store / path), local)
            yield local
            if "w" in mode:
                log["writes"] += 1
                shutil.copyfile(local, str(store / path))
        finally:
            os.unlink(local)

    fs.register_scheme("mem", opener)
    yield store, log
    fs._SCHEMES.pop("mem", None)


def test_local_passthrough(tmp_path):
    p = str(tmp_path / "a.txt")
    with fs.open_uri(p, "w") as local:
        assert local == p
    with fs.open_uri("file://" + p, "w") as local:
        assert local == p
    assert fs.scheme_of("s3://b/k") == "s3"
    assert fs.scheme_of("/plain/path") == ""


def test_unknown_scheme_raises():
    with pytest.raises(IOError):
        with fs.open_uri("gopher://x/y"):
            pass


def test_s3_without_boto_raises_clearly():
    with pytest.raises(IOError, match="boto3"):
        with fs.open_uri("s3://bucket/key", "r"):
            pass


def test_nd_save_load_through_scheme(memfs):
    store, log = memfs
    data = {"w": mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))}
    mx.nd.save("mem://ckpt.params", data)
    assert log["writes"] == 1
    out = mx.nd.load("mem://ckpt.params")
    assert log["reads"] == 1
    np.testing.assert_array_equal(out["w"].asnumpy(), data["w"].asnumpy())


def test_recordio_through_scheme(memfs):
    store, log = memfs
    rec = mx.recordio.MXRecordIO("mem://data.rec", "w")
    rec.write(b"alpha")
    rec.write(b"beta" * 100)
    rec.close()
    assert log["writes"] == 1 and (store / "data.rec").exists()
    rec = mx.recordio.MXRecordIO("mem://data.rec", "r")
    assert rec.read() == b"alpha"
    assert rec.read() == b"beta" * 100
    rec.close()


def test_exists_file_scheme_checks_filesystem(tmp_path):
    missing = "file://" + str(tmp_path / "nope.bin")
    assert not fs.exists(missing)
    p = tmp_path / "yes.bin"
    p.write_bytes(b"x")
    assert fs.exists("file://" + str(p))


def test_append_mode_rejected_for_remote():
    with pytest.raises(IOError, match="append"):
        with fs.open_uri("s3://bucket/key", "a"):
            pass


def test_recordio_invalid_flag_no_staging(memfs):
    store, log = memfs
    with pytest.raises(ValueError):
        mx.recordio.MXRecordIO("mem://x.rec", "a")
    assert log["writes"] == 0 and log["reads"] == 0


def test_predictor_checkpoint_through_scheme(memfs, tmp_path):
    store, log = memfs
    # train a tiny model, checkpoint locally, copy into the fake remote
    import shutil
    rng = np.random.RandomState(0)
    x = rng.rand(8, 4).astype(np.float32)
    data = mx.sym.Variable("data")
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(data, num_hidden=2, name="fc"),
        name="softmax")
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 4))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(mx.init.Xavier())
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 1)
    shutil.copyfile(prefix + "-symbol.json", str(store / "m-symbol.json"))
    shutil.copyfile(prefix + "-0001.params", str(store / "m-0001.params"))
    pred = mx.Predictor.from_checkpoint("mem://m", 1,
                                        input_shapes={"data": (8, 4)},
                                        ctx=mx.cpu())
    pred.forward(data=x)
    ref = mx.Predictor.from_checkpoint(prefix, 1,
                                       input_shapes={"data": (8, 4)},
                                       ctx=mx.cpu())
    ref.forward(data=x)
    np.testing.assert_allclose(pred.get_output(0).asnumpy(),
                               ref.get_output(0).asnumpy())

"""Compile-time control (ISSUE 9): applied remat, gradient
accumulation, AOT warm starts, and the persistent-cache fence.

Companions: tests/test_scan_layers.py (the scan transform itself) and
tools/compile_time_smoke.py (the CI job's cross-process gates).
"""
import os
import pickle

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import aot, profiler
from mxnet_tpu.base import MXNetError
from mxnet_tpu.models import transformer

sym = mx.sym


def _mlp(normalization="null"):
    net = sym.FullyConnected(sym.Variable("data"), num_hidden=16,
                             name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                             name="softmax", normalization=normalization)


def _data(n=64, d=8, classes=4, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.uniform(-1, 1, (n, d)).astype(np.float32)
    Y = rs.randint(0, classes, (n,)).astype(np.float32)
    return X, Y


def _init_for(net, data_shapes, label_shapes, seed=11):
    m = mx.mod.Module(net, context=mx.cpu(0))
    m.bind(data_shapes=data_shapes, label_shapes=label_shapes)
    rs = np.random.RandomState(seed)
    skip = {d[0] for d in data_shapes + label_shapes}
    return {n: mx.nd.array(rs.uniform(-0.1, 0.1, a.shape)
                           .astype(np.float32))
            for n, a in m._exec.arg_dict.items() if n not in skip}


def _fit(net, X, Y, init, batch=32, accum=None, epochs=2, opt_params=None,
         **fit_kw):
    it = mx.io.NDArrayIter(X, Y, batch_size=batch,
                           label_name="softmax_label")
    mod = mx.mod.Module(net, context=mx.cpu(0))
    mod.fit(it, num_epoch=epochs,
            arg_params={k: v.copy() for k, v in init.items()},
            optimizer_params=opt_params or {"learning_rate": 0.1},
            grad_accum=accum, **fit_kw)
    arg, aux = mod.get_params()
    return ({n: v.asnumpy() for n, v in arg.items()},
            {n: v.asnumpy() for n, v in aux.items()})


# ----------------------------------------------------- grad accumulation

class TestGradAccum:
    def test_mlp_parity_sum_normalized(self):
        # normalization='null': per-sample grads, accumulation sums —
        # exact up to float reassociation
        net = _mlp()
        X, Y = _data()
        init = _init_for(net, [("data", (32, 8))],
                         [("softmax_label", (32,))])
        p1, _ = _fit(net, X, Y, init)
        with profiler.counter_delta() as d:
            p4, _ = _fit(net, X, Y, init, accum=4)
        assert d.get("accum_steps") == 4 * 4  # 2 epochs x 2 batches x 4
        assert d.get("loop_recompile") == 0
        for n in p1:
            np.testing.assert_allclose(p1[n], p4[n], rtol=0, atol=1e-7,
                                       err_msg=n)

    def test_mlp_parity_batch_normalized(self):
        # normalization='batch': microbatch means averaged (1/N rescale)
        # must equal the full-batch mean exactly
        net = _mlp(normalization="batch")
        X, Y = _data(seed=3)
        init = _init_for(net, [("data", (32, 8))],
                         [("softmax_label", (32,))])
        p1, _ = _fit(net, X, Y, init)
        p4, _ = _fit(net, X, Y, init, accum=4)
        for n in p1:
            np.testing.assert_allclose(p1[n], p4[n], rtol=0, atol=1e-7,
                                       err_msg=n)

    def test_bn_stem_matches_sequential_microbatches(self):
        # BatchNorm: each microbatch normalizes with its own statistics
        # and advances the moving stats sequentially — the documented
        # semantics. Reference: run the two microbatches through a
        # plain executor, sum the grads, apply one SGD update by hand.
        B, C, H = 8, 3, 6
        net = sym.Convolution(sym.Variable("data"), num_filter=4,
                              kernel=(3, 3), pad=(1, 1), name="conv0")
        net = sym.BatchNorm(net, name="bn0")
        net = sym.Activation(net, act_type="relu")
        net = sym.FullyConnected(net, num_hidden=4, name="fc")
        net = sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                                name="softmax")
        rs = np.random.RandomState(0)
        X = rs.uniform(-1, 1, (B, C, H, H)).astype(np.float32)
        Y = rs.randint(0, 4, (B,)).astype(np.float32)
        init = _init_for(net, [("data", (B, C, H, H))],
                         [("softmax_label", (B,))])
        lr = 0.1

        # accumulated fused step, one batch, one epoch
        it = mx.io.NDArrayIter(X, Y, batch_size=B,
                               label_name="softmax_label")
        mod = mx.mod.Module(net, context=mx.cpu(0))
        mod.fit(it, num_epoch=1,
                arg_params={k: v.copy() for k, v in init.items()},
                optimizer_params={"learning_rate": lr, "wd": 0.0},
                grad_accum=2)
        got_arg, got_aux = mod.get_params()

        # reference: executor at microbatch size with sequential aux
        ex = net.simple_bind(mx.cpu(), grad_req="write",
                             data=(B // 2, C, H, H),
                             softmax_label=(B // 2,))
        for n, v in init.items():
            ex.arg_dict[n][:] = v.asnumpy()
        for n, a in ex.aux_dict.items():
            # fit's initializer seeds moving_var=1 / moving_mean=0;
            # simple_bind leaves zeros — align the starting aux state
            a[:] = np.ones(a.shape, np.float32) if "var" in n else \
                np.zeros(a.shape, np.float32)
        # the fused step folds the step key once more per microbatch;
        # this net is dropout-free so RNG does not matter
        grads = {n: 0.0 for n in init}
        for k in range(2):
            ex.arg_dict["data"][:] = X[k * B // 2:(k + 1) * B // 2]
            ex.arg_dict["softmax_label"][:] = Y[k * B // 2:(k + 1) * B // 2]
            ex.forward(is_train=True)
            ex.backward()
            for n in grads:
                grads[n] = grads[n] + ex.grad_dict[n].asnumpy()
        rescale = 1.0 / B   # init_optimizer's rescale_grad on the FULL batch
        for n, v in init.items():
            want = v.asnumpy() - lr * rescale * grads[n]
            np.testing.assert_allclose(got_arg[n].asnumpy(), want,
                                       rtol=0, atol=2e-6, err_msg=n)
        for n in ex.aux_dict:
            np.testing.assert_allclose(got_aux[n].asnumpy(),
                                       ex.aux_dict[n].asnumpy(),
                                       rtol=0, atol=1e-6, err_msg=n)

    def test_async_window_and_device_metrics_intact(self):
        net = _mlp()
        X, Y = _data(seed=5)
        init = _init_for(net, [("data", (32, 8))],
                         [("softmax_label", (32,))])
        mx.config.set("MXNET_TPU_ASYNC_WINDOW", 2)
        try:
            with profiler.counter_delta() as d:
                p_async, _ = _fit(net, X, Y, init, accum=4, epochs=3)
            assert d.get("loop_recompile") == 0
            assert d.get("loop_host_sync") == 0
        finally:
            mx.config.reset("MXNET_TPU_ASYNC_WINDOW")
        mx.config.set("MXNET_TPU_ASYNC_WINDOW", 0)
        try:
            p_sync, _ = _fit(net, X, Y, init, accum=4, epochs=3)
        finally:
            mx.config.reset("MXNET_TPU_ASYNC_WINDOW")
        for n in p_async:
            np.testing.assert_array_equal(p_async[n], p_sync[n],
                                          err_msg=n)

    def test_indivisible_batch_rejected(self):
        net = _mlp()
        X, Y = _data()
        init = _init_for(net, [("data", (32, 8))],
                         [("softmax_label", (32,))])
        with pytest.raises(MXNetError, match="does not divide"):
            _fit(net, X, Y, init, accum=3)

    def test_valid_normalization_rejected(self):
        net = _mlp(normalization="valid")
        X, Y = _data()
        init = _init_for(net, [("data", (32, 8))],
                         [("softmax_label", (32,))])
        with pytest.raises(MXNetError, match="valid"):
            _fit(net, X, Y, init, accum=4)

    def test_accum_one_is_the_plain_step(self):
        net = _mlp()
        X, Y = _data(seed=9)
        init = _init_for(net, [("data", (32, 8))],
                         [("softmax_label", (32,))])
        p_none, _ = _fit(net, X, Y, init)
        p_one, _ = _fit(net, X, Y, init, accum=1)
        for n in p_none:
            np.testing.assert_array_equal(p_none[n], p_one[n])

    def test_trainer_grad_req_add_accumulation(self):
        # the gluon-side idiom: grad_req='add', N backwards, one step
        from mxnet_tpu.gluon import nn, Trainer
        from mxnet_tpu import autograd

        def build(grad_req):
            net = nn.Dense(4, in_units=8)
            net.initialize(mx.init.Constant(0.05))
            for p in net.collect_params().values():
                p.grad_req = grad_req
            return net

        rs = np.random.RandomState(2)
        xs = [mx.nd.array(rs.uniform(-1, 1, (8, 8)).astype(np.float32))
              for _ in range(2)]
        full = mx.nd.concatenate(xs)

        ref = build("write")
        tr = Trainer(ref.collect_params(), "sgd",
                     {"learning_rate": 0.1, "wd": 0.0})
        with autograd.record():
            loss = ref(full).sum()
        loss.backward()
        tr.step(16)

        acc = build("add")
        tr2 = Trainer(acc.collect_params(), "sgd",
                      {"learning_rate": 0.1, "wd": 0.0})
        for x in xs:
            with autograd.record():
                loss = acc(x).sum()
            loss.backward()
        tr2.step(16)
        for (n0, p0), (n1, p1) in zip(
                sorted(ref.collect_params().items()),
                sorted(acc.collect_params().items())):
            np.testing.assert_allclose(p0.data().asnumpy(),
                                       p1.data().asnumpy(),
                                       rtol=0, atol=1e-7, err_msg=n0)


# ------------------------------------------------------------ remat

class TestRemat:
    def test_named_policy_applies_and_preserves_training(self):
        net = _mlp()
        X, Y = _data(seed=13)
        init = _init_for(net, [("data", (32, 8))],
                         [("softmax_label", (32,))])
        p_plain, _ = _fit(net, X, Y, init)
        mx.config.set("MXNET_TPU_REMAT", "dots_with_no_batch_dims_saveable")
        try:
            with profiler.counter_delta() as d:
                p_remat, _ = _fit(net, X, Y, init)
            assert d.get("remat_applied") >= 1
        finally:
            mx.config.set("MXNET_TPU_REMAT", "off")
        for n in p_plain:
            np.testing.assert_allclose(p_plain[n], p_remat[n], rtol=0,
                                       atol=1e-7, err_msg=n)

    def test_bad_policy_name_raises_naming_valid_ones(self):
        net = _mlp()
        X, Y = _data()
        init = _init_for(net, [("data", (32, 8))],
                         [("softmax_label", (32,))])
        mx.config.set("MXNET_TPU_REMAT", "no_such_policy")
        try:
            with pytest.raises(MXNetError, match="nothing_saveable"):
                _fit(net, X, Y, init)
        finally:
            mx.config.set("MXNET_TPU_REMAT", "off")

    def test_auto_round_trip_prediction_within_25pct(self):
        # THE ISSUE 9 satellite: the remat-opportunity suggestion,
        # applied via MXNET_TPU_REMAT=auto (per block, through the scan
        # plan), must move analyze_program_memory's activation
        # high-water by the pass's predicted amount +-25%
        import jax
        import jax.numpy as jnp
        from mxnet_tpu.analysis import (analyze_program_memory,
                                        analyze_symbol)

        net = transformer.get_symbol(vocab_size=128, num_layers=2,
                                     d_model=32, n_heads=2, seq_len=16)
        shapes = {"data": (2, 16), "softmax_label": (2, 16)}
        sug = analyze_symbol(net, input_shapes=shapes,
                             calibrate_remat=True) \
            .extras["remat"]["suggestion"]
        predicted = sug["est_peak_saving"]
        assert predicted > 0
        # a plain bind analysis stays execution-free: no calibration
        plain = analyze_symbol(net, input_shapes=shapes) \
            .extras["remat"]["suggestion"]
        assert "est_peak_saving" not in plain

        def build(remat_mode):
            mx.config.set("MXNET_TPU_SCAN_LAYERS", "2")
            mx.config.set("MXNET_TPU_REMAT", remat_mode)
            try:
                m = mx.mod.Module(net, context=mx.cpu(0))
                m.bind(data_shapes=[("data", (2, 16))],
                       label_shapes=[("softmax_label", (2, 16))])
                m.init_params(mx.init.Xavier())
                return m._exec
            finally:
                mx.config.set("MXNET_TPU_REMAT", "off")
                mx.config.set("MXNET_TPU_SCAN_LAYERS", "auto")

        def peak(ex):
            fn = ex._fn
            params = {n: a.data for n, a in ex.arg_dict.items()
                      if n not in ("data", "softmax_label")}
            inputs = {n: ex.arg_dict[n].data
                      for n in ("data", "softmax_label")}
            key = jax.random.PRNGKey(0)

            def g(p):
                def loss_fn(p_):
                    return fn({**p_, **inputs}, {}, key, True)
                (outs, new_aux), vjp = jax.vjp(loss_fn, p)
                cts = [jnp.ones_like(o) for o in outs]
                return vjp((cts, {k: jnp.zeros_like(v)
                                  for k, v in new_aux.items()}))[0]

            return analyze_program_memory(g, params).extras[
                "program_memory"]["activation_peak_bytes"]

        ex_plain = build("off")
        assert ex_plain._scan_plan is not None
        ex_remat = build("auto")
        assert ex_remat._scan_plan.body_wrapper is not None
        measured = peak(ex_plain) - peak(ex_remat)
        assert measured > 0
        assert abs(measured - predicted) <= 0.25 * predicted, \
            "predicted %d vs measured %d (%.0f%% off)" % (
                predicted, measured,
                100.0 * abs(measured - predicted) / predicted)

    def test_legacy_knob_still_remats(self):
        net = _mlp()
        X, Y = _data()
        init = _init_for(net, [("data", (32, 8))],
                         [("softmax_label", (32,))])
        mx.config.set("MXNET_EXEC_ENABLE_REMAT", "1")
        try:
            with profiler.counter_delta() as d:
                _fit(net, X, Y, init, epochs=1)
            assert d.get("remat_applied") >= 1
        finally:
            mx.config.reset("MXNET_EXEC_ENABLE_REMAT")


# --------------------------------------------------------------- AOT

class TestAot:
    def test_capability_probe(self):
        assert aot.supported() is True

    def test_in_process_store_then_hit(self, tmp_path):
        net = _mlp()
        X, Y = _data(seed=21)
        init = _init_for(net, [("data", (32, 8))],
                         [("softmax_label", (32,))])
        mx.config.set("MXNET_TPU_COMPILE_CACHE", str(tmp_path))
        try:
            with profiler.counter_delta() as d:
                p_cold, _ = _fit(net, X, Y, init, epochs=1)
            assert d.get("aot_store") == 1
            assert d.get("aot_hit") == 0
            files = [f for f in os.listdir(tmp_path)
                     if f.startswith("fused_step-")]
            assert len(files) == 1
            with profiler.counter_delta() as d:
                p_warm, _ = _fit(net, X, Y, init, epochs=1)
            assert d.get("aot_hit") == 1
            assert d.get("aot_store") == 0
            assert d.get("aot_error") == 0
        finally:
            mx.config.reset("MXNET_TPU_COMPILE_CACHE")
        for n in p_cold:
            np.testing.assert_array_equal(p_cold[n], p_warm[n],
                                          err_msg=n)

    def test_corrupt_entry_is_a_miss_not_an_error(self, tmp_path):
        net = _mlp()
        X, Y = _data(seed=22)
        init = _init_for(net, [("data", (32, 8))],
                         [("softmax_label", (32,))])
        mx.config.set("MXNET_TPU_COMPILE_CACHE", str(tmp_path))
        try:
            p_cold, _ = _fit(net, X, Y, init, epochs=1)
            (entry,) = [f for f in os.listdir(tmp_path)
                        if f.startswith("fused_step-")]
            with open(os.path.join(tmp_path, entry), "wb") as f:
                f.write(b"not a pickle")
            with profiler.counter_delta() as d:
                p_again, _ = _fit(net, X, Y, init, epochs=1)
            assert d.get("aot_miss") >= 1
            assert d.get("aot_store") == 1   # re-serialized cleanly
        finally:
            mx.config.reset("MXNET_TPU_COMPILE_CACHE")
        for n in p_cold:
            np.testing.assert_array_equal(p_cold[n], p_again[n])

    def test_stale_fingerprint_is_a_miss(self, tmp_path):
        net = _mlp()
        X, Y = _data(seed=23)
        init = _init_for(net, [("data", (32, 8))],
                         [("softmax_label", (32,))])
        mx.config.set("MXNET_TPU_COMPILE_CACHE", str(tmp_path))
        try:
            _fit(net, X, Y, init, epochs=1)
            (name,) = [f for f in os.listdir(tmp_path)
                       if f.startswith("fused_step-")]
            path = os.path.join(tmp_path, name)
            with open(path, "rb") as f:
                entry = pickle.load(f)
            entry["fingerprint"] = "elsewhere"
            with open(path, "wb") as f:
                pickle.dump(entry, f)
            with profiler.counter_delta() as d:
                _fit(net, X, Y, init, epochs=1)
            assert d.get("aot_miss") >= 1
            assert d.get("aot_hit") == 0
        finally:
            mx.config.reset("MXNET_TPU_COMPILE_CACHE")

    def test_executor_forward_aot_per_bucket_shape(self, tmp_path):
        # the serve path: one executor re-entered with different padded
        # batch geometries — each bucket shape gets its own serialized
        # executable, and a fresh process (executor) loads them all
        net = sym.FullyConnected(sym.Variable("data"), num_hidden=4,
                                 name="fc1")
        mx.config.set("MXNET_TPU_COMPILE_CACHE", str(tmp_path))
        try:
            x4 = np.random.RandomState(0).rand(4, 8).astype(np.float32)
            ex = net.simple_bind(mx.cpu(), data=(4, 8))
            with profiler.counter_delta() as d:
                o4 = ex.forward(is_train=False,
                                data=mx.nd.array(x4))[0].asnumpy()
                ex.forward(is_train=False,
                           data=mx.nd.array(np.ones((2, 8), np.float32)))
            assert d.get("aot_store") == 2      # one per bucket shape
            assert d.get("aot_error") == 0
            ex2 = net.simple_bind(mx.cpu(), data=(4, 8))
            ex2.copy_params_from({"fc1_weight": ex.arg_dict["fc1_weight"],
                                  "fc1_bias": ex.arg_dict["fc1_bias"]},
                                 allow_extra_params=True)
            with profiler.counter_delta() as d:
                o4b = ex2.forward(is_train=False,
                                  data=mx.nd.array(x4))[0].asnumpy()
            assert d.get("aot_hit") == 1
            assert d.get("aot_error") == 0
            np.testing.assert_array_equal(o4, o4b)
        finally:
            mx.config.reset("MXNET_TPU_COMPILE_CACHE")

    def test_multidevice_module_never_serializes(self, tmp_path):
        # THE regression the ISSUE names: multi-device executables must
        # never reach the serialized-executable path
        net = _mlp()
        X, Y = _data(seed=24)
        mx.config.set("MXNET_TPU_COMPILE_CACHE", str(tmp_path))
        try:
            it = mx.io.NDArrayIter(X, Y, batch_size=32,
                                   label_name="softmax_label")
            mod = mx.mod.Module(net,
                                context=[mx.cpu(i) for i in range(8)])
            with profiler.counter_delta() as d:
                mod.fit(it, num_epoch=1,
                        optimizer_params={"learning_rate": 0.1})
            assert d.get("aot_skip_multidevice") >= 1
            assert d.get("aot_store") == 0
            assert d.get("aot_hit") == 0
            assert os.listdir(tmp_path) == []
        finally:
            mx.config.reset("MXNET_TPU_COMPILE_CACHE")


# ----------------------------------------------- persistent-cache fence

class TestPersistentCacheFence:
    def test_fence_installed_by_conftest(self):
        # idempotent re-install must report success
        assert aot.install_persistent_cache_fence() is True

    def test_multidevice_compile_skips_cache(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("d",))
        sh = NamedSharding(mesh, P("d"))
        x = jax.device_put(jnp.ones((8, 4)), sh)
        salt = float(np.random.RandomState().rand())  # fresh program
        with profiler.counter_delta() as d:
            jax.jit(lambda v: (v * salt).sum(), in_shardings=(sh,))(x)
        assert d.get("compile_cache_fence_skip") >= 1

    def test_single_device_compile_uses_cache(self):
        import jax
        import jax.numpy as jnp
        with profiler.counter_delta() as d:
            jax.jit(lambda v: v * 17.113)(jnp.ones(3))
        assert d.get("compile_cache_fence_skip") == 0

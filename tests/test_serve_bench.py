"""Tier-1 smoke for tools/perf/serve_bench.py (not slow).

Runs the quick variant end-to-end (real closed-loop clients against a
real InferenceServer on the doc-evidence MLP) and asserts the mechanics
the acceptance criteria care about: the batcher engages (avg batch rows
> 1), throughput is finite, zero steady-state recompiles, and the JSON
artifact schema matches what BENCH_serving.json records. Wall-clock
speedup is recorded by the full bench, not asserted here — shared CI
hosts are too noisy for a hard ratio gate (same policy as
test_trainer_step_bench).
"""
import importlib
import json
import os
import sys

import numpy as np


def _load_bench():
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "perf"))
    try:
        return importlib.import_module("serve_bench")
    finally:
        sys.path.pop(0)


def test_serve_bench_quick(tmp_path):
    bench = _load_bench()
    results = bench.run(quick=True)
    assert "mlp" in results
    r = results["mlp"]
    for k in ("sequential_rps", "served_rps", "speedup", "p50_ms",
              "p95_ms", "p99_ms", "avg_batch_rows", "occupancy",
              "bucket_compiles", "steady_state_recompiles"):
        assert k in r, "missing %s" % k
    assert np.isfinite(r["sequential_rps"]) and r["sequential_rps"] > 0
    assert np.isfinite(r["served_rps"]) and r["served_rps"] > 0
    assert r["avg_batch_rows"] > 1, "the dynamic batcher never coalesced"
    assert r["steady_state_recompiles"] == 0, \
        "bucketed serving recompiled after warmup"
    assert r["p50_ms"] > 0 and r["p99_ms"] >= r["p50_ms"]

    # artifact schema: what the driver's BENCH_serving.json consumers read
    path = str(tmp_path / "BENCH_serving.json")
    with open(path, "w") as f:
        json.dump({"bench": "serving", "results": results}, f)
    with open(path) as f:
        loaded = json.load(f)
    assert loaded["bench"] == "serving"
    assert loaded["results"]["mlp"]["served_rps"] == r["served_rps"]


def test_serve_bench_decode_quick(tmp_path):
    """Decode-section smoke: continuous batching engages against the
    matched-deployment sequential baseline, the recompile counter stays
    zero through the timed window, and the BENCH_decode.json schema
    holds. The >=3x@32-clients acceptance ratio is recorded by the full
    bench (BENCH_decode.json), not asserted on noisy CI hosts."""
    bench = _load_bench()
    r = bench._bench_decode(quick=True)
    assert r["sequential_tps"] > 0
    c8 = r["clients_8"]
    assert np.isfinite(c8["continuous_tps"]) and c8["continuous_tps"] > 0
    assert c8["steady_state_recompiles"] == 0, \
        "bucketed decode recompiled after warmup"
    # executable universe: <= |prompt buckets| + |decode buckets|
    assert c8["executable_bound"] >= 2
    for side in ("ttft", "tpot"):
        assert c8[side] is not None
        for k in ("p50_ms", "p95_ms", "p99_ms", "window"):
            assert k in c8[side], (side, k)
    path = str(tmp_path / "BENCH_decode.json")
    payload = dict(r)
    payload["bench"] = "serve_decode"
    with open(path, "w") as f:
        json.dump(payload, f)
    with open(path) as f:
        loaded = json.load(f)
    assert loaded["bench"] == "serve_decode"
    assert loaded["clients_8"]["steady_state_recompiles"] == 0

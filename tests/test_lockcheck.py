"""Runtime lock witness (mxnet_tpu.lockcheck / MXNET_TPU_LOCKCHECK).

The dynamic twin of the static lock-order pass: a real two-thread ABBA
inversion is provoked and must be flagged online (warn counts + logs,
abort raises BEFORE the blocking acquire), held-lock device syncs are
caught at the NDArray sync points, and the off path is subprocess-proven
to never construct the wrapper nor move a ``lockcheck_*`` counter.
"""
import os
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx                                     # noqa: E402
from mxnet_tpu import config, lockcheck, profiler          # noqa: E402
from mxnet_tpu.base import MXNetError                      # noqa: E402

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture()
def witness_mode(request):
    mode = getattr(request, "param", "warn")
    config.set("MXNET_TPU_LOCKCHECK", mode)
    lockcheck.reset_order_graph()
    yield mode
    config.reset("MXNET_TPU_LOCKCHECK")
    lockcheck.reset_order_graph()


def run_in_thread(fn):
    exc = []

    def body():
        try:
            fn()
        except BaseException as e:                         # noqa: BLE001
            exc.append(e)

    t = threading.Thread(target=body)
    t.start()
    t.join(timeout=30)
    assert not t.is_alive(), "witness thread hung"
    return exc


# ============================================================ inversion


def test_abba_inversion_warn_counts(witness_mode):
    """Two threads take A->B then B->A sequentially (a REAL inversion
    shape, observable without actually deadlocking): warn mode counts
    lockcheck_inversion exactly once for the pair."""
    a = lockcheck.Lock(name="A")
    b = lockcheck.Lock(name="B")
    with profiler.counter_delta() as d:
        run_in_thread(lambda: _nest(a, b))
        run_in_thread(lambda: _nest(b, a))
        assert d.get("lockcheck_inversion") == 1, d.all()
        # the pair is flagged once, not once per re-observation
        run_in_thread(lambda: _nest(b, a))
        assert d.get("lockcheck_inversion") == 1, d.all()


def _nest(outer, inner):
    with outer:
        with inner:
            pass


@pytest.mark.parametrize("witness_mode", ["abort"], indirect=True)
def test_abba_inversion_abort_raises(witness_mode):
    """Abort mode raises MXNetError in the inverting thread BEFORE its
    blocking acquire — the thread stops at the inversion, not inside
    the deadlock it would have caused."""
    a = lockcheck.Lock(name="A")
    b = lockcheck.Lock(name="B")
    with profiler.counter_delta() as d:
        assert run_in_thread(lambda: _nest(a, b)) == []
        exc = run_in_thread(lambda: _nest(b, a))
        assert len(exc) == 1 and isinstance(exc[0], MXNetError), exc
        assert "inversion" in str(exc[0])
        # both chains with sites are in the message
        assert "while holding lock[B]" in str(exc[0])
        assert "while holding lock[A]" in str(exc[0])
        assert d.get("lockcheck_inversion") == 1, d.all()


def test_consistent_order_is_silent(witness_mode):
    a = lockcheck.Lock(name="A")
    b = lockcheck.Lock(name="B")
    with profiler.counter_delta() as d:
        for _ in range(3):
            run_in_thread(lambda: _nest(a, b))
        assert d.get("lockcheck_inversion") == 0, d.all()


def test_rlock_reentry_is_not_an_inversion(witness_mode):
    r = lockcheck.RLock(name="R")
    other = lockcheck.Lock(name="O")
    with profiler.counter_delta() as d:
        with r:
            with other:
                with r:          # reentrant re-acquire while holding O
                    pass
        # ...even though O->R now exists alongside R->O
        assert d.get("lockcheck_inversion") == 0, d.all()


def test_trylock_records_no_edges(witness_mode):
    """A non-blocking acquire cannot complete a deadlock cycle — an ABBA
    via try-acquires must not flag."""
    a = lockcheck.Lock(name="A")
    b = lockcheck.Lock(name="B")

    def t1():
        with a:
            assert b.acquire(False)
            b.release()

    def t2():
        with b:
            assert a.acquire(False)
            a.release()

    with profiler.counter_delta() as d:
        run_in_thread(t1)
        run_in_thread(t2)
        assert d.get("lockcheck_inversion") == 0, d.all()


def test_condition_wait_notify_through_funnel(witness_mode):
    """Condition round-trip: wait() releases ALL recursion levels and
    the re-acquire is witnessed — held-state stays exact (no phantom
    held locks after the with-block)."""
    cond = lockcheck.Condition(name="C")
    done = []

    def waiter():
        with cond:
            cond.wait(timeout=10)
            done.append(True)

    t = threading.Thread(target=waiter)
    t.start()
    deadline = 50
    while deadline and not t.is_alive():
        deadline -= 1
    import time
    time.sleep(0.2)
    with cond:
        cond.notify_all()
    t.join(timeout=10)
    assert done == [True]
    # the waiter's thread-local held list fully drained
    with profiler.counter_delta() as d:
        with cond:
            pass
        assert d.get("lockcheck_inversion") == 0, d.all()


def test_condition_sharing_witnessed_lock(witness_mode):
    lock = lockcheck.Lock(name="shared")
    cond = lockcheck.Condition(lock)
    with cond:
        cond.notify_all()
    assert not lock.locked()


# ============================================================ held sync


def test_held_sync_counts_and_warns(witness_mode):
    x = mx.nd.array(np.zeros(3))
    guard = lockcheck.Lock(name="guard")
    with profiler.counter_delta() as d:
        with guard:
            x.asnumpy()
        assert d.get("lockcheck_held_sync") == 1, d.all()
        with guard:
            x.asnumpy()          # same (site, sync) pair: once
        assert d.get("lockcheck_held_sync") == 1, d.all()


def test_allow_sync_lock_is_exempt(witness_mode):
    """allow_sync=True is the runtime twin of the static
    allow(lock-host-sync) justification (serve's _model_lock)."""
    x = mx.nd.array(np.zeros(3))
    ok = lockcheck.Lock(name="justified", allow_sync=True)
    with profiler.counter_delta() as d:
        with ok:
            x.asnumpy()
            x.wait_to_read()
        assert d.get("lockcheck_held_sync") == 0, d.all()


@pytest.mark.parametrize("witness_mode", ["abort"], indirect=True)
def test_held_sync_abort_raises(witness_mode):
    x = mx.nd.array(np.zeros(3))
    guard = lockcheck.Lock(name="guard2")
    with pytest.raises(MXNetError, match="host sync"):
        with guard:
            x.asnumpy()


def test_unlocked_sync_is_silent(witness_mode):
    x = mx.nd.array(np.zeros(3))
    with profiler.counter_delta() as d:
        x.asnumpy()
        x.wait_to_read()
        assert d.get("lockcheck_held_sync") == 0, d.all()


# ============================================================= zero cost


def test_lockcheck_off_is_zero_cost():
    """Knob off (default): the funnels return PLAIN threading
    primitives (no wrapper object anywhere), serve traffic moves no
    lockcheck_* counter, and exercising sync points records nothing —
    subprocess-proven like every other knob (satellite + CI gate)."""
    prog = textwrap.dedent("""
        import sys, threading
        sys.path.insert(0, %r)
        import numpy as np
        import mxnet_tpu as mx
        from mxnet_tpu import lockcheck, profiler

        l = lockcheck.Lock(name="x")
        r = lockcheck.RLock()
        c = lockcheck.Condition()
        assert type(l) is type(threading.Lock()), type(l)
        assert type(r) is type(threading.RLock()), type(r)
        assert type(c) is threading.Condition, type(c)

        x = mx.nd.array(np.arange(8.0))
        with l:
            x.asnumpy()
            x.wait_to_read()
        bad = [k for k in profiler.counters() if k.startswith("lockcheck")]
        assert not bad, bad
        print("LOCKCHECK_ZERO_COST_OK")
    """) % (REPO,)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="")
    env.pop("MXNET_TPU_LOCKCHECK", None)
    res = subprocess.run([sys.executable, "-c", prog], env=env,
                         capture_output=True, text=True, timeout=240)
    assert res.returncode == 0, res.stderr
    assert "LOCKCHECK_ZERO_COST_OK" in res.stdout


def test_mode_flip_affects_new_locks(witness_mode):
    """The knob is read at lock creation: flipping it off leaves already
    -witnessed locks witnessed but new locks plain."""
    assert lockcheck.mode() == "warn"
    config.set("MXNET_TPU_LOCKCHECK", "off")
    try:
        plain = lockcheck.Lock()
        assert type(plain) is type(threading.Lock())
    finally:
        config.set("MXNET_TPU_LOCKCHECK", "warn")

"""Non-finite step guard (``MXNET_TPU_NANCHECK``, ISSUE 12 satellite):
a device-side isfinite reduction chained onto the fused step — zero
host syncs during batches, one flag fetch at the epoch log boundary.

The fires/stays-silent pair: a poisoned input must count
``loop_nonfinite`` (warn) or raise naming the first non-finite output
(abort); a clean run must move nothing; off must build nothing.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import profiler


def _fit(mode, poison, num_epoch=2):
    mx.config.set("MXNET_TPU_NANCHECK", mode)
    try:
        mx.random.seed(7)
        X = np.random.RandomState(0).uniform(
            -1, 1, (32, 8)).astype(np.float32)
        if poison:
            X[5, 3] = np.nan
        Y = np.random.RandomState(1).uniform(
            -1, 1, (32, 2)).astype(np.float32)
        it = mx.io.NDArrayIter({"data": X}, {"label": Y}, batch_size=8)
        sym = mx.sym.LinearRegressionOutput(
            mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                                  name="fc"),
            mx.sym.Variable("label"), name="reg")
        mod = mx.mod.Module(sym, context=mx.cpu(),
                            data_names=("data",), label_names=("label",))
        mod.fit(it, num_epoch=num_epoch, eval_metric="mse",
                optimizer="sgd",
                optimizer_params={"learning_rate": 0.1})
        return mod
    finally:
        mx.config.reset("MXNET_TPU_NANCHECK")


def test_clean_run_stays_silent():
    base = profiler.get_counter("loop_nonfinite")
    mod = _fit("warn", poison=False)
    assert profiler.get_counter("loop_nonfinite") == base
    # the guard existed (the chained reduction was built)...
    assert mod._nancheck_fn is not None
    # ...and left no pending flags after the final poll
    assert mod._nan_flags is None


def test_warn_counts_and_continues():
    base = profiler.get_counter("loop_nonfinite")
    _fit("warn", poison=True, num_epoch=2)     # completes despite NaNs
    # flagged once per epoch boundary (the accumulator resets per epoch)
    assert profiler.get_counter("loop_nonfinite") == base + 2


def test_abort_raises_naming_the_output():
    with pytest.raises(mx.MXNetError, match=r"reg_output.*NANCHECK"):
        _fit("abort", poison=True)


def test_off_builds_nothing():
    base = profiler.get_counter("loop_nonfinite")
    mod = _fit("off", poison=True)
    assert profiler.get_counter("loop_nonfinite") == base
    assert mod._nancheck_mode == "off"
    assert mod._nancheck_fn is None
    assert mod._nan_flags is None

"""mx.obs — cross-thread trace timeline, metrics exposition, MFU/compile
accounting (ISSUE 6, docs/architecture/observability.md).

Covers: span gating + zero-allocation disabled mode, per-thread lanes,
flow-event linkage of one batch across the async fit's threads, the
bounded log-bucket histogram (quantile parity vs numpy.percentile), the
serve latency migration, Prometheus exposition + pure-Python grammar
check, the /metrics endpoint, always-on compile accounting (a fused-step
bind must populate obs_bind_ms/obs_compile_count), and the obs MFU gauge
against independently measured throughput.
"""
import json
import os
import tempfile
import threading
import time
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import profiler as _profiler


@pytest.fixture
def obs_on():
    mx.config.set("MXNET_TPU_OBS", 1)
    try:
        yield
    finally:
        mx.config.set("MXNET_TPU_OBS", 0)
        mx.config.reset("MXNET_TPU_OBS")


def _mlp(hidden=8):
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="relu1")
    h = mx.sym.FullyConnected(h, num_hidden=2, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _fit_data(n=160, batch=16, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.uniform(-1, 1, (n, 6)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    return mx.io.NDArrayIter(x, y, batch_size=batch,
                             label_name="softmax_label")


def _dump_trace(tmpdir):
    path = os.path.join(tmpdir, "trace.json")
    mx.profiler.set_config(filename=path)
    mx.profiler.dump()
    with open(path) as f:
        return json.load(f)


# ------------------------------------------------------------ span gating


def test_disabled_span_is_shared_noop_and_allocates_nothing():
    assert not mx.obs.spans_enabled()
    s1 = mx.obs.span("a")
    s2 = mx.obs.span("b", flow=123, lane="x")
    assert s1 is s2, "disabled span() must return the shared singleton"
    with _profiler.counter_delta() as d:
        with mx.obs.span("region"):
            pass
        s1.mark_flow(7)
    assert d.get("obs_spans") == 0


def test_disabled_fit_records_zero_spans():
    """The disabled-mode overhead discipline: a full async fit with obs
    off and the profiler stopped must record NO span events (the CI obs
    job runs the same assertion in a subprocess)."""
    mx.profiler.set_state("stop")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    with _profiler.counter_delta() as d:
        mod.fit(_fit_data(), optimizer="sgd", initializer=mx.init.Xavier(),
                optimizer_params={"learning_rate": 0.1}, num_epoch=1)
    assert d.get("obs_spans") == 0


def test_span_records_under_obs_knob_without_profiler(obs_on, tmp_path):
    """MXNET_TPU_OBS enables spans while the profiler state stays
    'stop' — structured timeline without per-op sync tracing."""
    assert mx.profiler.state() == "stop"
    with mx.obs.span("outer", "t"):
        with mx.obs.span("inner", "t"):
            time.sleep(0.001)
    trace = _dump_trace(str(tmp_path))
    spans = {e["name"]: e for e in trace["traceEvents"] if e["ph"] == "X"}
    assert "outer" in spans and "inner" in spans
    o, i = spans["outer"], spans["inner"]
    # proper nesting: inner inside outer on the same lane
    assert o["tid"] == i["tid"]
    assert o["ts"] <= i["ts"]
    assert i["ts"] + i["dur"] <= o["ts"] + o["dur"] + 1.0  # 1us slack


def test_named_lanes_and_explicit_lane_override(obs_on, tmp_path):
    mx.obs.register_thread_lane("lane-test-main")
    done = threading.Event()

    def worker():
        mx.obs.register_thread_lane("lane-test-worker")
        with mx.obs.span("w"):
            pass
        done.set()

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert done.is_set()
    with mx.obs.span("m"):
        pass
    with mx.obs.span("staged", lane="lane-test-stage"):
        pass
    trace = _dump_trace(str(tmp_path))
    lanes = {e["args"]["name"]: e["tid"] for e in trace["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    spans = {e["name"]: e["tid"] for e in trace["traceEvents"]
             if e["ph"] == "X"}
    assert spans["w"] == lanes["lane-test-worker"]
    assert spans["m"] == lanes["lane-test-main"]
    assert spans["staged"] == lanes["lane-test-stage"]
    # lane ids are small registered ints, not tid % 100000 hashes
    assert all(0 < tid < 10000 for tid in lanes.values())


# ------------------------------------------------ cross-thread fit trace


def test_async_fit_trace_links_batches_across_lanes(obs_on, tmp_path):
    """The acceptance trace: an async fit produces a Perfetto-loadable
    {"traceEvents": [...]} with >=4 distinct named lanes, and flow
    events connect one batch across at least prefetch, training, and
    metric lanes."""
    ckpt_dir = os.path.join(str(tmp_path), "ck")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(_fit_data(), optimizer="sgd", initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.1}, num_epoch=2,
            checkpoint=mx.checkpoint.CheckpointConfig(
                ckpt_dir, every_n_batches=5))
    trace = _dump_trace(str(tmp_path))
    events = trace["traceEvents"]
    assert isinstance(events, list) and events

    lanes = {e["args"]["name"]: e["tid"] for e in events
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert len(lanes) >= 4, "expected >=4 named lanes, got %s" % lanes
    for expect in ("train", "metric", "place"):
        assert expect in lanes, lanes
    assert any(name.startswith("prefetch/") for name in lanes), lanes
    assert "ckpt-writer" in lanes, lanes

    names = {e["name"] for e in events if e["ph"] == "X"}
    for expect in ("prefetch_next", "device_place", "fused_step_dispatch",
                   "metric_update", "metric_sync", "ckpt_snapshot",
                   "ckpt_write"):
        assert expect in names, (expect, sorted(names))

    # flow linkage: at least one batch's flow id must appear on >=3
    # distinct lanes (prefetch -> place -> train/metric), starting with
    # exactly one "s"
    flow_lanes, flow_phases = {}, {}
    for e in events:
        if e.get("cat") == "flow":
            flow_lanes.setdefault(e["id"], set()).add(e["tid"])
            flow_phases.setdefault(e["id"], []).append(e["ph"])
    linked = [fid for fid, ls in flow_lanes.items() if len(ls) >= 3]
    assert linked, "no flow id crossed >=3 lanes: %s" % {
        k: len(v) for k, v in flow_lanes.items()}
    for fid in linked:
        assert flow_phases[fid].count("s") == 1, flow_phases[fid]


# ------------------------------------------------------------- histogram


def test_histogram_quantiles_within_one_bucket_of_numpy():
    rng = np.random.RandomState(7)
    samples = np.exp(rng.normal(-5.0, 1.5, size=5000))   # lognormal, sec
    h = mx.obs.Histogram()
    for v in samples:
        h.observe(float(v))
    bounds = list(h.bounds)

    def bucket_of(v):
        import bisect
        return bisect.bisect_left(bounds, v)

    for q in (50.0, 95.0, 99.0):
        exact = float(np.percentile(samples, q))
        est = h.quantile(q / 100.0)
        assert est is not None
        assert abs(bucket_of(est) - bucket_of(exact)) <= 1, \
            "q%.0f: est %.6g vs exact %.6g" % (q, est, exact)
    snap = h.snapshot()
    assert snap["count"] == len(samples)
    assert abs(snap["sum"] - samples.sum()) / samples.sum() < 1e-9
    assert snap["max"] == samples.max() and snap["min"] == samples.min()


def test_histogram_registry_shared_and_resettable():
    h1 = mx.obs.histogram("obs_test_shared")
    h2 = mx.obs.histogram("obs_test_shared")
    assert h1 is h2
    mx.obs.observe("obs_test_shared", 0.5)
    assert h1.count >= 1
    h1.reset()
    assert h1.count == 0 and h1.quantile(0.5) is None


def test_serve_latency_stats_on_shared_histogram():
    from mxnet_tpu.serve.stats import LatencyStats
    st = LatencyStats(name="obs_test_latency_seconds")
    st.reset()
    assert st.snapshot() is None
    rng = np.random.RandomState(3)
    vals = np.abs(rng.normal(0.010, 0.004, size=500)) + 1e-4
    for v in vals:
        st.record(float(v))
    snap = st.snapshot()
    assert snap["window"] == 500
    assert 0 < snap["p50_ms"] <= snap["p95_ms"] <= snap["p99_ms"] \
        <= snap["max_ms"]
    # one-bucket accuracy against the exact percentile
    exact_p50 = float(np.percentile(vals, 50)) * 1e3
    assert abs(snap["p50_ms"] - exact_p50) / exact_p50 < 0.25
    # the registry histogram is what the exposition renders
    assert mx.obs.histogram("obs_test_latency_seconds").count == 500


# ------------------------------------------------------------ prometheus


def test_render_prometheus_parses_and_matches_registry():
    _profiler.incr_counter("obs_test_ctr", 5)
    _profiler.set_gauge("obs_test_gauge", 2.5)
    mx.obs.observe("obs_test_hist", 0.002)
    mx.obs.observe("obs_test_hist", 0.008)
    text = mx.obs.render_prometheus()
    samples = mx.obs.parse_prometheus(text)

    def get(name, **labels):
        return samples[(name, tuple(sorted(labels.items())))]

    assert get("mxnet_tpu_obs_test_ctr_total") >= 5
    # registry keys already ending in _total keep exactly one suffix
    assert "_total_total" not in text
    assert get("mxnet_tpu_obs_test_gauge") == 2.5
    assert get("mxnet_tpu_obs_test_hist_count") >= 2
    assert get("mxnet_tpu_obs_test_hist_bucket", le="+Inf") >= 2
    # cumulative bucket counts are non-decreasing in le
    buckets = sorted(
        ((float("inf") if lbl[0][1] == "+Inf" else float(lbl[0][1])), v)
        for (n, lbl) in samples
        if n == "mxnet_tpu_obs_test_hist_bucket"
        for v in [samples[(n, lbl)]])
    assert all(a[1] <= b[1] for a, b in zip(buckets, buckets[1:]))


def test_render_survives_nonfinite_gauges():
    _profiler.set_gauge("obs_test_inf_gauge", float("inf"))
    _profiler.set_gauge("obs_test_nan_gauge", float("nan"))
    try:
        samples = mx.obs.parse_prometheus(mx.obs.render_prometheus())
        import math
        assert samples[("mxnet_tpu_obs_test_inf_gauge", ())] == math.inf
        assert math.isnan(samples[("mxnet_tpu_obs_test_nan_gauge", ())])
    finally:
        # registries are process-global: a lingering inf gauge is fine
        # for other tests, but keep the table tidy
        _profiler.set_gauge("obs_test_inf_gauge", 0.0)
        _profiler.set_gauge("obs_test_nan_gauge", 0.0)


def test_labeled_histogram_round_trip_with_le():
    """PR 11 federation path, parser side: a histogram rendered under
    pod identity labels must round-trip with BOTH the identity labels
    and the per-bucket ``le`` on every bucket sample, cumulative counts
    intact — and two hosts' expositions of the SAME metric must
    coexist after a federated concatenation."""
    h = _profiler.histogram("obs_fed_hist")
    h.reset()
    for v in (0.001, 0.004, 0.4):
        h.observe(v)
    lab0 = {"process_index": "0", "world_size": "2"}
    lab1 = {"process_index": "1", "world_size": "2"}
    # a federated scrape body: both hosts' renders concatenated
    text = mx.obs.render_prometheus(labels=lab0) + \
        mx.obs.render_prometheus(labels=lab1)
    samples = mx.obs.parse_prometheus(text)

    def bucket(le, **labels):
        return samples[("mxnet_tpu_obs_fed_hist_bucket",
                        tuple(sorted(dict(labels, le=le).items())))]

    for lab in (lab0, lab1):
        assert bucket("+Inf", **lab) == 3
        assert samples[("mxnet_tpu_obs_fed_hist_count",
                        tuple(sorted(lab.items())))] == 3
        assert samples[("mxnet_tpu_obs_fed_hist_sum",
                        tuple(sorted(lab.items())))] == \
            pytest.approx(0.405)
        # cumulative in le within ONE label set
        series = sorted(
            ((float("inf") if lbl_d["le"] == "+Inf"
              else float(lbl_d["le"])), v)
            for (n, lbl), v in samples.items()
            if n == "mxnet_tpu_obs_fed_hist_bucket"
            for lbl_d in [dict(lbl)]
            if lbl_d.get("process_index") == lab["process_index"])
        assert [v for _le, v in series] == \
            sorted(v for _le, v in series)
        assert series[-1][1] == 3


def test_labeled_nonfinite_gauges_round_trip():
    import math
    _profiler.set_gauge("obs_fed_inf", float("inf"))
    _profiler.set_gauge("obs_fed_nan", float("nan"))
    try:
        lab = {"process_index": "3", "world_size": "4"}
        samples = mx.obs.parse_prometheus(
            mx.obs.render_prometheus(labels=lab))
        key = tuple(sorted(lab.items()))
        assert samples[("mxnet_tpu_obs_fed_inf", key)] == math.inf
        assert math.isnan(samples[("mxnet_tpu_obs_fed_nan", key)])
    finally:
        _profiler.set_gauge("obs_fed_inf", 0.0)
        _profiler.set_gauge("obs_fed_nan", 0.0)


def test_same_name_different_labels_coexist():
    """Rank 3's sample must never overwrite rank 0's — the exact
    collision pod_labels() exists to prevent."""
    _profiler.incr_counter("obs_fed_ctr", 2)
    text = mx.obs.render_prometheus(
        labels={"process_index": "0", "world_size": "2"}) + \
        mx.obs.render_prometheus(
            labels={"process_index": "1", "world_size": "2"})
    samples = mx.obs.parse_prometheus(text)
    keys = [lbl for (n, lbl) in samples
            if n == "mxnet_tpu_obs_fed_ctr_total"]
    assert len(keys) == 2 and keys[0] != keys[1]
    # and the bare (unlabeled) sample is a THIRD distinct series
    samples_bare = mx.obs.parse_prometheus(
        mx.obs.render_prometheus(labels={}))
    assert ("mxnet_tpu_obs_fed_ctr_total", ()) in samples_bare


def test_parse_prometheus_rejects_malformed():
    with pytest.raises(ValueError):
        mx.obs.parse_prometheus("not a metric line !!!\n")
    with pytest.raises(ValueError):
        mx.obs.parse_prometheus("metric_ok{le=unquoted} 1\n")
    with pytest.raises(ValueError):
        mx.obs.parse_prometheus("metric_ok notanumber\n")
    # well-formed corner cases parse
    ok = mx.obs.parse_prometheus(
        '# HELP m doc\n# TYPE m counter\nm{a="b",c="d"} 1e3\nn +Inf\n')
    assert ok[("m", (("a", "b"), ("c", "d")))] == 1000.0


def test_metrics_http_endpoint():
    _profiler.incr_counter("obs_test_http_ctr")
    with mx.obs.start_metrics_server(port=0) as srv:
        assert srv.port > 0
        body = urllib.request.urlopen(srv.url, timeout=10).read().decode()
        samples = mx.obs.parse_prometheus(body)
        assert ("mxnet_tpu_obs_test_http_ctr_total", ()) in samples
        # non-/metrics paths 404
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                "http://%s:%d/other" % (srv.host, srv.port), timeout=10)


def test_serve_server_metrics_port():
    def model(x):
        return x * 2.0

    srv = mx.serve.InferenceServer(model, max_batch_size=4, metrics_port=0,
                                   name="obs_msrv")
    try:
        assert srv.metrics_port and srv.metrics_port > 0
        srv.submit(np.ones((3,), np.float32)).result(timeout=30)
        body = urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % srv.metrics_port,
            timeout=10).read().decode()
        samples = mx.obs.parse_prometheus(body)
        assert ("mxnet_tpu_obs_msrv_latency_seconds_count", ()) in samples
    finally:
        srv.close()
    # default knob (-1): no endpoint
    srv2 = mx.serve.InferenceServer(model, max_batch_size=4)
    try:
        assert srv2.metrics_port is None
    finally:
        srv2.close()


def test_serve_metrics_port_conflict_degrades_not_raises():
    """An observability port conflict must not take down the serving
    path: the second server comes up WITHOUT an endpoint, counted."""
    def model(x):
        return x

    srv1 = mx.serve.InferenceServer(model, max_batch_size=4, metrics_port=0,
                                    name="obs_conflict")
    try:
        with _profiler.counter_delta() as d:
            srv2 = mx.serve.InferenceServer(
                model, max_batch_size=4, metrics_port=srv1.metrics_port,
                name="obs_conflict")
            try:
                assert srv2.metrics_port is None
                assert d.get("obs_conflict_metrics_bind_failed") == 1
                # serving still works
                srv2.submit(np.ones((2,), np.float32)).result(timeout=30)
            finally:
                srv2.close()
    finally:
        srv1.close()


# ----------------------------------------------------- compile accounting


def test_fused_step_bind_populates_compile_telemetry():
    """Satellite guard: a small fused-step bind must land in the
    obs_bind_ms histogram, the obs_compile_count counter, AND the ring
    with its scope — silent loss of compile telemetry fails here."""
    hist = mx.obs.histogram("obs_bind_ms")
    count_before = hist.count
    mod = mx.mod.Module(_mlp(hidden=5), context=mx.cpu())
    mod.bind(data_shapes=[("data", (9, 6))],
             label_shapes=[("softmax_label", (9,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    x = np.random.RandomState(0).rand(9, 6).astype(np.float32)
    y = np.zeros((9,), np.float32)
    with _profiler.counter_delta() as d:
        mod._fit_step(mx.io.DataBatch(data=[mx.nd.array(x)],
                                      label=[mx.nd.array(y)]))
    assert d.get("obs_compile_count") >= 1
    assert d.get("obs_bind_ms_total") >= 0
    assert hist.count > count_before
    recs = [r for r in mx.obs.compiles.snapshot()
            if r["scope"] == "fused_step"]
    assert recs, "no fused_step compile record in the ring"
    r = recs[-1]
    assert r["bind_ms"] >= r["compile_ms"] >= 0
    assert r["trace_ms"] >= 0
    assert r["signature"] and "fused_step" in r["signature"]
    # the trace histogram fills alongside
    assert mx.obs.histogram("obs_trace_ms").count > 0


def test_compile_scope_attributes_unscoped_as_none():
    import jax
    import jax.numpy as jnp
    jax.jit(lambda x: x * 31.7 - 2)(jnp.ones((3, 2))).block_until_ready()
    recs = mx.obs.compiles.snapshot()
    assert recs        # ring bounded but non-empty after any compile
    assert len(recs) <= mx.obs.compiles.RING_CAPACITY


# ------------------------------------------------------------------- MFU


def test_obs_mfu_matches_independent_throughput_math():
    """The acceptance cross-check, CPU-sized: obs_flops_per_sec (analysis
    cost model x measured steps/s between report() calls) must agree
    with an independently timed rate over the same region; obs_mfu is
    exactly flops_per_sec / the overridden peak."""
    import jax
    mx.config.set("MXNET_TPU_OBS_PEAK_FLOPS", 1e9)
    try:
        mod = mx.mod.Module(_mlp(hidden=64), context=mx.cpu())
        mod.bind(data_shapes=[("data", (32, 6))],
                 label_shapes=[("softmax_label", (32,))])
        mod.init_params(mx.init.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        rng = np.random.RandomState(0)
        db = mx.io.DataBatch(
            data=[mx.nd.array(rng.rand(32, 6).astype(np.float32))],
            label=[mx.nd.array(np.zeros((32,), np.float32))])
        for _ in range(2):     # warmup/compile: EXACTLY the bench.py
            mod._fit_step(db)  # pattern — the window-open report below
        jax.block_until_ready(mod._step_token())
        mx.obs.report()        # must set the baseline at steps==warmup
        n = 100
        t0 = time.perf_counter()
        for _ in range(n):
            mod._fit_step(db)
        jax.block_until_ready(mod._step_token())
        dt = time.perf_counter() - t0
        rep = mx.obs.report()                  # close the rate window

        execs = [e for e in rep["executors"] if e["steps_per_sec"]]
        assert execs, rep["executors"]
        e = max(execs, key=lambda r: r["steps_per_sec"])
        assert e["flops_per_step"] and e["flops_per_step"] > 0
        independent_rate = n / dt
        rel = abs(e["steps_per_sec"] - independent_rate) / independent_rate
        assert rel < 0.10, \
            "obs %.1f vs independent %.1f steps/s (rel %.3f)" % (
                e["steps_per_sec"], independent_rate, rel)
        assert e["mfu"] == pytest.approx(e["flops_per_sec"] / 1e9)
        assert rep["gauges"]["obs_mfu"] > 0
        assert rep["gauges"]["obs_flops_per_sec"] > 0
    finally:
        mx.config.reset("MXNET_TPU_OBS_PEAK_FLOPS")


def test_mfu_flops_model_matches_mlp_closed_form():
    """The analysis-cost-model FLOPs the MFU gauge uses equal the MLP
    closed form (train = 3x forward)."""
    from mxnet_tpu.obs import mfu as _mfu
    mod = mx.mod.Module(_mlp(hidden=16), context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 6))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    mod._obs_flops_per_step = None          # force recompute
    fps = _mfu._flops_per_step(mod)
    # forward: fc1 2*8*16*6 + bias-add 8*16 + relu 8*16 + fc2 2*8*2*16 +
    # bias 8*2 + softmax 5*8*2
    fwd = 2 * 8 * 16 * 6 + 8 * 16 + 8 * 16 + 2 * 8 * 2 * 16 + 8 * 2 \
        + 5 * 8 * 2
    assert fps == pytest.approx(3 * fwd, rel=0.15)


def test_transformer_flops_model_matches_palm_accounting():
    """The obs MFU FLOP source (analysis cost model, fwd x3) must agree
    with bench.py's independent PaLM accounting on the transformer —
    including the flash-attention variant (a default per-element rule
    undercounted attention and ate most of the 10% acceptance budget)."""
    from mxnet_tpu.models import transformer
    from mxnet_tpu.analysis import analyze_symbol
    L, D, H, T, V, B = 2, 256, 4, 128, 1000, 4
    n_params = transformer.param_count(V, L, D, H, seq_len=T)
    palm = 6 * (n_params - (V * D + T * D)) + 12 * L * D * T
    for attn in ("dense", "flash"):
        sym = transformer.get_symbol(vocab_size=V, num_layers=L,
                                     d_model=D, n_heads=H, seq_len=T,
                                     attention=attn)
        rep = analyze_symbol(sym, input_shapes={"data": (B, T),
                                                "softmax_label": (B, T)})
        per_tok = 3.0 * rep.extras["cost"]["flops"] / (B * T)
        assert abs(per_tok / palm - 1.0) < 0.05, \
            "%s: obs %.3e vs palm %.3e" % (attn, per_tok, palm)


def test_peak_flops_table_and_override():
    from mxnet_tpu.obs import mfu as _mfu
    assert _mfu.peak_flops("TPU v4") == 275e12
    assert _mfu.peak_flops("TPU v5 lite") == 197e12
    assert _mfu.peak_flops("weird accelerator") is None
    mx.config.set("MXNET_TPU_OBS_PEAK_FLOPS", 123.0)
    try:
        assert _mfu.peak_flops("TPU v4") == 123.0
    finally:
        mx.config.reset("MXNET_TPU_OBS_PEAK_FLOPS")


# ---------------------------------------------- profiler thread-safety


def test_profiler_concurrent_state_config_dump_hammer(tmp_path):
    """The satellite races: set_state/set_config vs record_event vs
    dump() from many threads — every dumped file must be valid JSON and
    nothing may raise."""
    errors = []
    stop = threading.Event()
    paths = [os.path.join(str(tmp_path), "h%d.json" % i) for i in range(2)]

    def flipper():
        i = 0
        while not stop.is_set():
            mx.profiler.set_state("run" if i % 2 else "stop")
            mx.profiler.set_config(filename=paths[i % 2])
            i += 1

    def recorder():
        while not stop.is_set():
            t = time.perf_counter()
            mx.profiler.record_event("evt", t, t + 1e-6)
            with mx.obs.span("sp"):
                pass

    def dumper():
        while not stop.is_set():
            try:
                p = mx.profiler.dump()
                with open(p) as f:
                    json.load(f)
            except Exception as exc:                       # noqa: BLE001
                errors.append(exc)

    threads = [threading.Thread(target=f)
               for f in (flipper, recorder, recorder, dumper)]
    for t in threads:
        t.start()
    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    mx.profiler.set_state("stop")
    assert not errors, errors[0]


def test_record_event_lane_is_stable_per_thread(tmp_path, obs_on):
    mx.profiler.set_state("run")
    try:
        t0 = time.perf_counter()
        mx.profiler.record_event("a1", t0, t0 + 1e-6)
        mx.profiler.record_event("a2", t0, t0 + 1e-6)

        def other():
            t = time.perf_counter()
            mx.profiler.record_event("b1", t, t + 1e-6)

        th = threading.Thread(target=other, name="obs-other-thread")
        th.start()
        th.join()
    finally:
        mx.profiler.set_state("stop")
    trace = _dump_trace(str(tmp_path))
    by_name = {e["name"]: e["tid"] for e in trace["traceEvents"]
               if e["ph"] == "X"}
    assert by_name["a1"] == by_name["a2"]
    assert by_name["b1"] != by_name["a1"]
    lanes = {e["args"]["name"] for e in trace["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "obs-other-thread" in lanes


# ------------------------------------------------------------- bench glue


def test_bench_merge_carries_per_section_bind_and_obs():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    merged = bench._merge({
        "resnet": {"section": "resnet", "value": 100.0, "mfu": 0.3,
                   "bind_secs": 12.5, "obs_mfu": 0.29,
                   "obs_bind_ms_total": 12500},
        "transformer": {"section": "transformer", "transformer_mfu": 0.62,
                        "bind_secs": 30.1, "obs_mfu": 0.60,
                        "obs_bind_ms_total": 30100},
    })
    assert merged["bind_secs"] == {"resnet": 12.5, "transformer": 30.1}
    assert merged["obs_mfu"] == {"resnet": 0.29, "transformer": 0.60}
    assert merged["obs_bind_ms_total"]["transformer"] == 30100
    assert merged["mfu"] == 0.3 and merged["transformer_mfu"] == 0.62
    # a wedged section surfaces as an error, not silence
    merged2 = bench._merge({"resnet": {"error": "timeout after 600s"}})
    assert merged2["errors"]["resnet"].startswith("timeout")

"""mxnet_tpu.fleet — gateway routing, replica supervision, fail-over
(ISSUE 20 tentpole).

The contract under test: the wire round-trips the serve API (streaming
tokens + the exception taxonomy) over real sockets; the gateway routes
least-loaded and keeps sequences sticky; a replica death mid-stream
fails over with an EXACT at-most-once continuation (the scripted
decoder's pure-autoregressive token function makes bit-equality
checkable without a model); shed/deadline/closed propagate as the same
exception classes a local ``GenerativeServer`` raises; the
``gateway.route`` fault site kills one request legibly; ``/metrics``
federates replica-labeled expositions into one parseable text; and the
package stays zero-cost: a plain ``import mxnet_tpu`` never loads it.

In-process tests front :class:`ScriptedDecodeServer` instances with
real ``ServeWire`` sockets and run the gateway in ``addresses=`` mode
(no subprocesses — the supervised-spawn path is exercised by
``tools/fleet_smoke.py`` with real model replicas). Replica "death"
here is wire-stop + drain=False close, which exercises both fail-over
triggers: transport death AND the clean-early-END a gracefully
shutting-down replica produces.
"""
import subprocess
import sys
import threading
import time

import pytest

import mxnet_tpu as mx
from mxnet_tpu import config as _config
from mxnet_tpu import faults
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serve import DeadlineExceeded, QueueFull, ServerClosed
from mxnet_tpu.serve.server import ServeError


@pytest.fixture(autouse=True)
def _fleet_knob():
    snap = _config.snapshot_overrides(["MXNET_TPU_FLEET"])
    _config.set("MXNET_TPU_FLEET", True)
    yield
    _config.restore_overrides(snap)


def _scripted_pair(n=2, step_s=0.005, **kw):
    from mxnet_tpu.fleet import ScriptedDecodeServer, ServeWire
    srvs, wires = [], []
    for r in range(n):
        s = ScriptedDecodeServer(step_s=step_s,
                                 name="t%d_%s" % (r, _uniq()), **kw)
        wires.append(ServeWire(s, rank=r))
        srvs.append(s)
    return srvs, wires


_SEQ = [0]


def _uniq():
    _SEQ[0] += 1
    return "u%d" % _SEQ[0]


def _ref_stream(prompt, n):
    from mxnet_tpu.fleet import scripted_token
    seq, out = list(prompt), []
    for _ in range(n):
        t = scripted_token(seq)
        out.append(t)
        seq.append(t)
    return out


def _teardown(gw, srvs, wires):
    gw.close(drain=False, timeout=10.0)
    for w in wires:
        w.stop()
    for s in srvs:
        try:
            s.close(drain=False, timeout=2.0)
        except Exception:                                   # noqa: BLE001
            pass


# ---------------------------------------------------------------- wire

def test_wire_streams_and_roundtrips_stats():
    from mxnet_tpu.fleet import FleetClient
    srvs, wires = _scripted_pair(n=1)
    try:
        cli = FleetClient(wires[0].address)
        assert cli.ping()
        toks = cli.generate([1, 2, 3], max_new_tokens=8,
                            result_timeout=30.0)
        assert toks == _ref_stream([1, 2, 3], 8)
        snap = cli.stats()
        assert snap["tokens"] >= 8
        assert snap["kv"]["max_slots"] == 4
        text = cli.metrics_text()
        assert 'replica="0"' in text
    finally:
        for w in wires:
            w.stop()
        for s in srvs:
            s.close(drain=False, timeout=2.0)


def test_wire_rehydrates_serve_exceptions():
    from mxnet_tpu.fleet import FleetClient, ScriptedDecodeServer, ServeWire
    s = ScriptedDecodeServer(slots=1, step_s=0.05, queue_bound=1,
                             name="shed_" + _uniq())
    w = ServeWire(s, rank=0)
    try:
        cli = FleetClient(w.address)
        # the client submit is async (a daemon thread drives the wire),
        # so sequence the fill deterministically: [1] resident FIRST,
        # then [2] into the one queue slot — submitting both at once
        # races [2] against [1]'s admission and the shed lands on the
        # wrong request

        def _wait(pred, what):
            deadline = time.time() + 5.0
            while time.time() < deadline:
                if pred(s.stats()):
                    return
                time.sleep(0.01)
            pytest.fail("server never reached " + what)

        h1 = cli.submit_generate([1], max_new_tokens=50)
        _wait(lambda st: st["active_sequences"] >= 1, "slot-full")
        h2 = cli.submit_generate([2], max_new_tokens=50)
        _wait(lambda st: st["waiting"] >= 1, "queue-full")
        with pytest.raises(QueueFull):
            cli.generate([3], max_new_tokens=4, result_timeout=10.0)
        h1.cancel()
        h2.cancel()
    finally:
        w.stop()
        s.close(drain=False, timeout=2.0)


def test_wire_end_reason_distinguishes_done_from_released():
    from mxnet_tpu.fleet import wire as fwire
    srvs, wires = _scripted_pair(n=1, step_s=0.005)
    s, w = srvs[0], wires[0]
    try:
        # finished on the server's own terms -> reason "done"
        got = []
        end = fwire.stream_generate(
            w.address,
            {"prompt": [1], "prefix": [], "start": 0,
             "max_new_tokens": 4, "eos_id": None, "temperature": 0.0,
             "seed": None, "timeout": None},
            lambda i, t: got.append(t))
        assert end["n"] == 4 and end["reason"] == "done"
        assert got == _ref_stream([1], 4)
        # a draining shutdown cancels the sequence -> reason "released"
        box = {}

        def run():
            try:
                box["end"] = fwire.stream_generate(
                    w.address,
                    {"prompt": [2], "prefix": [], "start": 0,
                     "max_new_tokens": 10000, "eos_id": None,
                     "temperature": 0.0, "seed": None, "timeout": None},
                    lambda i, t: None)
            except BaseException as exc:                    # noqa: BLE001
                box["exc"] = exc

        t = threading.Thread(target=run, daemon=True)
        t.start()
        time.sleep(0.1)             # a few tokens in
        s.close(drain=False, timeout=5.0)
        t.join(10.0)
        assert box.get("end", {}).get("reason") == "released"
    finally:
        w.stop()
        s.close(drain=False, timeout=2.0)


def test_probe_adjudicates_alive_dead_ambiguous():
    import socket
    from mxnet_tpu.fleet import probe
    from mxnet_tpu.parallel.dist import free_port
    srvs, wires = _scripted_pair(n=1)
    try:
        assert probe(wires[0].address, timeout=2.0) == "alive"
    finally:
        wires[0].stop()
        srvs[0].close(drain=False, timeout=2.0)
    # connection refused = the probe-confirmed death signal
    assert probe(("127.0.0.1", free_port()), timeout=1.0) == "dead"
    # a peer answering garbage is never grounds for a kill verdict
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)

    def answer():
        conn, _ = srv.accept()
        conn.recv(64)
        conn.sendall(b"WAT\n")
        conn.close()

    t = threading.Thread(target=answer, daemon=True)
    t.start()
    try:
        assert probe(srv.getsockname(), timeout=2.0) == "ambiguous"
    finally:
        srv.close()


# ------------------------------------------------------------- gateway

def test_gateway_requires_opt_in_knob():
    from mxnet_tpu.fleet import Gateway
    _config.set("MXNET_TPU_FLEET", False)
    with pytest.raises(MXNetError):
        Gateway(addresses=[("127.0.0.1", 1)], port=None)


def test_gateway_streams_through_client_wire():
    from mxnet_tpu.fleet import FleetClient, Gateway
    srvs, wires = _scripted_pair(n=2)
    gw = Gateway(addresses=[w.address for w in wires],
                 name="gwt_" + _uniq(), stats_period=0.1)
    try:
        assert gw.wait_ready(timeout=10.0) == 2
        cli = FleetClient(("127.0.0.1", gw.port))
        toks = cli.generate([4, 5], max_new_tokens=10,
                            result_timeout=30.0)
        assert toks == _ref_stream([4, 5], 10)
        snap = cli.stats()          # gateway stats through the same wire
        assert snap["live"] == 2 and snap["tokens"] >= 10
    finally:
        _teardown(gw, srvs, wires)


def test_routing_spreads_load_least_loaded():
    from mxnet_tpu.fleet import Gateway
    srvs, wires = _scripted_pair(n=2, slots=2, step_s=0.01)
    gw = Gateway(addresses=[w.address for w in wires],
                 name="lb_" + _uniq(), stats_period=0.05)
    try:
        assert gw.wait_ready(timeout=10.0) == 2
        handles = [gw.submit_generate([i + 1], max_new_tokens=20)
                   for i in range(4)]
        for h in handles:
            assert len(h.result(timeout=60.0)) == 20
        # with 4 concurrent 2-slot replica loads, least-loaded MUST
        # have spread: both replicas decoded something
        per = [s.stats()["tokens"] for s in srvs]
        assert all(t > 0 for t in per), per
    finally:
        _teardown(gw, srvs, wires)


def test_sticky_one_replica_per_stream():
    from mxnet_tpu.fleet import Gateway
    srvs, wires = _scripted_pair(n=2)
    gw = Gateway(addresses=[w.address for w in wires],
                 name="stick_" + _uniq(), stats_period=0.05)
    try:
        assert gw.wait_ready(timeout=10.0) == 2
        h = gw.submit_generate([7], max_new_tokens=30)
        assert len(h.result(timeout=60.0)) == 30
        # no fail-over happened, so exactly ONE replica carried the
        # whole stream (stickiness is by construction; this pins it)
        per = [s.stats()["tokens"] for s in srvs]
        assert sorted(per) == [0, 30], per
    finally:
        _teardown(gw, srvs, wires)


def test_gateway_sheds_at_admission_bound():
    from mxnet_tpu.fleet import Gateway
    srvs, wires = _scripted_pair(n=1, step_s=0.05)
    gw = Gateway(addresses=[w.address for w in wires],
                 name="bound_" + _uniq(), queue_bound=1,
                 stats_period=0.1)
    try:
        assert gw.wait_ready(timeout=10.0) == 1
        h = gw.submit_generate([1], max_new_tokens=40)
        with pytest.raises(QueueFull):
            gw.submit_generate([2], max_new_tokens=4)
        h.cancel()
    finally:
        _teardown(gw, srvs, wires)


def test_ttft_deadline_propagates():
    from mxnet_tpu.fleet import Gateway
    # one slot, long resident sequence: the queued request's TTFT
    # deadline expires inside the REPLICA queue and comes back as
    # DeadlineExceeded through the wire
    srvs, wires = _scripted_pair(n=1, slots=1, step_s=0.05)
    gw = Gateway(addresses=[w.address for w in wires],
                 name="dl_" + _uniq(), stats_period=0.1)
    try:
        assert gw.wait_ready(timeout=10.0) == 1
        h1 = gw.submit_generate([1], max_new_tokens=60)
        time.sleep(0.1)
        h2 = gw.submit_generate([2], max_new_tokens=4, timeout=0.2)
        with pytest.raises(DeadlineExceeded):
            h2.result(timeout=30.0)
        h1.cancel()
    finally:
        _teardown(gw, srvs, wires)


def test_close_rejects_new_submits():
    from mxnet_tpu.fleet import Gateway
    srvs, wires = _scripted_pair(n=1)
    gw = Gateway(addresses=[w.address for w in wires],
                 name="cl_" + _uniq(), stats_period=0.1)
    gw.wait_ready(timeout=10.0)
    gw.close(drain=True, timeout=10.0)
    with pytest.raises(ServerClosed):
        gw.submit_generate([1], max_new_tokens=4)
    for w in wires:
        w.stop()
    for s in srvs:
        s.close(drain=False, timeout=2.0)


# ------------------------------------------------------------ fail-over

def test_failover_midstream_exact_continuation():
    from mxnet_tpu.fleet import Gateway
    srvs, wires = _scripted_pair(n=2, step_s=0.01)
    gw = Gateway(addresses=[w.address for w in wires],
                 name="fo_" + _uniq(), stats_period=0.05)
    try:
        assert gw.wait_ready(timeout=10.0) == 2
        witness = gw.submit_generate([9], max_new_tokens=40)
        time.sleep(0.08)            # a few tokens in
        st = gw.stats()
        victim = next(r["rank"] for r in st["replicas"]
                      if r["assigned"] > 0)
        survivor = 1 - victim
        # a co-resident sequence on the SURVIVOR must ride through the
        # victim's death untouched
        bystander = gw.submit_generate([3, 3], max_new_tokens=40)
        time.sleep(0.05)
        wires[victim].stop()
        srvs[victim].close(drain=False, timeout=2.0)
        out = witness.result(timeout=60.0)
        assert out == _ref_stream([9], 40)      # exact, no dup, no gap
        assert bystander.result(timeout=60.0) == _ref_stream([3, 3], 40)
        st = gw.stats()
        assert st["failover"] >= 1
        assert st["dup_dropped"] == 0
        # every token the survivor decoded for the witness re-prefilled
        # from prompt + delivered prefix — delivered exactly once
        assert st["replicas"][survivor]["state"] == "live"
    finally:
        _teardown(gw, srvs, wires)


def test_failover_redispatch_drops_ttft_and_derives_seed(monkeypatch):
    # the TTFT deadline constrains only the FIRST token: a fail-over
    # re-dispatch after delivery must not carry the (long-expired)
    # deadline into the survivor's admission, and a seeded request's
    # continuation seed derives from the fail-over point instead of
    # replaying the original seed's draws at the wrong positions
    from mxnet_tpu.fleet import Gateway
    from mxnet_tpu.fleet import wire as fwire
    srvs, wires = _scripted_pair(n=1, step_s=0.005)
    gw = Gateway(addresses=[w.address for w in wires],
                 name="rdp_" + _uniq(), stats_period=0.1)
    payloads = []
    real = fwire.stream_generate

    def fake(addr, payload, on_frame, **kw):
        payloads.append(dict(payload))
        if len(payloads) == 1:
            for i, t in enumerate(_ref_stream([7], 2)):
                on_frame(i, t)      # two tokens out, then die
            raise ConnectionResetError("mid-stream death")
        return real(addr, payload, on_frame, **kw)

    monkeypatch.setattr(fwire, "stream_generate", fake)
    try:
        assert gw.wait_ready(timeout=10.0) == 1
        h = gw.submit_generate([7], max_new_tokens=8, timeout=5.0,
                               seed=123)
        assert h.result(timeout=30.0) == _ref_stream([7], 8)
        assert len(payloads) == 2
        assert payloads[0]["timeout"] is not None
        assert payloads[0]["seed"] == 123
        assert payloads[1]["start"] == 2
        assert payloads[1]["prefix"] == _ref_stream([7], 2)
        assert payloads[1]["timeout"] is None
        assert payloads[1]["seed"] not in (None, 123)
    finally:
        _teardown(gw, srvs, wires)


def test_short_done_end_is_a_complete_result(monkeypatch):
    # a replica's KV-capacity truncation ENDs the stream cleanly SHORT
    # with reason "done": the gateway must finish the request as a bare
    # server would — not burn fail-over budget re-prefilling a prompt
    # that already outgrew max_seq
    from mxnet_tpu.fleet import Gateway
    from mxnet_tpu.fleet import wire as fwire

    def fake(addr, payload, on_frame, **kw):
        for i, t in enumerate(_ref_stream([5], 3)):
            on_frame(i, t)
        return {"n": 3, "reason": "done"}

    monkeypatch.setattr(fwire, "stream_generate", fake)
    srvs, wires = _scripted_pair(n=1)
    gw = Gateway(addresses=[w.address for w in wires],
                 name="trunc_" + _uniq(), stats_period=0.1)
    try:
        assert gw.wait_ready(timeout=10.0) == 1
        h = gw.submit_generate([5], max_new_tokens=64)
        assert h.result(timeout=30.0) == _ref_stream([5], 3)
        assert gw.stats()["failover"] == 0
    finally:
        _teardown(gw, srvs, wires)


def test_all_replicas_dead_fails_legibly():
    from mxnet_tpu.fleet import Gateway
    srvs, wires = _scripted_pair(n=1, step_s=0.01)
    gw = Gateway(addresses=[w.address for w in wires],
                 name="dead_" + _uniq(), stats_period=0.05)
    try:
        assert gw.wait_ready(timeout=10.0) == 1
        h = gw.submit_generate([5], max_new_tokens=60)
        time.sleep(0.05)
        wires[0].stop()
        srvs[0].close(drain=False, timeout=2.0)
        with pytest.raises(ServeError):
            h.result(timeout=120.0)
    finally:
        _teardown(gw, srvs, wires)


def test_gateway_route_fault_kills_one_request():
    from mxnet_tpu.fleet import Gateway
    srvs, wires = _scripted_pair(n=1)
    gw = Gateway(addresses=[w.address for w in wires],
                 name="fr_" + _uniq(), stats_period=0.1)
    try:
        assert gw.wait_ready(timeout=10.0) == 1
        faults.install("gateway.route@1:raise")
        try:
            h1 = gw.submit_generate([1], max_new_tokens=4)
            with pytest.raises(ServeError):
                h1.result(timeout=30.0)
            # the site fired once; the next request routes normally
            h2 = gw.submit_generate([2], max_new_tokens=4)
            assert len(h2.result(timeout=30.0)) == 4
        finally:
            faults.clear()
    finally:
        _teardown(gw, srvs, wires)


# ------------------------------------------------------------- metrics

def test_metrics_federation_parses_with_replica_labels():
    from mxnet_tpu.fleet import Gateway
    from mxnet_tpu.obs.prometheus import parse_prometheus
    srvs, wires = _scripted_pair(n=2)
    gw = Gateway(addresses=[w.address for w in wires],
                 name="met_" + _uniq(), stats_period=0.05)
    try:
        assert gw.wait_ready(timeout=10.0) == 2
        gw.submit_generate([1], max_new_tokens=4).result(timeout=30.0)
        text = gw.metrics_text()
        samples = parse_prometheus(text)    # strict: raises on bad text
        assert samples, "federated exposition empty"
        replicas = {dict(lbls).get("replica")
                    for (_name, lbls) in samples}
        assert "0" in replicas and "1" in replicas
    finally:
        _teardown(gw, srvs, wires)


def test_merge_prometheus_dedupes_metadata():
    from mxnet_tpu.fleet import merge_prometheus
    a = ("# HELP m a counter\n# TYPE m counter\n"
         'm{replica="0"} 1\n')
    b = ("# HELP m a counter\n# TYPE m counter\n"
         'm{replica="1"} 2\n')
    merged = merge_prometheus([a, b])
    assert merged.count("# HELP m") == 1
    assert merged.count("# TYPE m") == 1
    assert 'm{replica="0"} 1' in merged and 'm{replica="1"} 2' in merged


# ------------------------------------------------------------ zero cost

def test_zero_cost_import_gate():
    """A plain import must not load the fleet (lazy PEP 562 hook)."""
    code = ("import sys; import mxnet_tpu; "
            "assert 'mxnet_tpu.fleet' not in sys.modules, 'fleet loaded'; "
            "import mxnet_tpu.serve; "
            "assert 'mxnet_tpu.fleet' not in sys.modules, 'serve pulls fleet'; "
            "print('OK')")
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=240, env=_child_env())
    assert out.returncode == 0, out.stderr
    assert "OK" in out.stdout


def _child_env():
    import os
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ""
    return env


def test_client_accepts_host_port_string():
    """A 'host:port' string must parse, not be indexed char-by-char
    into the silently-wrong address ('1', 2)."""
    from mxnet_tpu.fleet import FleetClient
    assert FleetClient("127.0.0.1:4242").address == ("127.0.0.1", 4242)
    assert FleetClient(("10.0.0.1", 7)).address == ("10.0.0.1", 7)
    with pytest.raises(ValueError):
        FleetClient("localhost")            # no port
    with pytest.raises(ValueError):
        FleetClient("host:notaport")

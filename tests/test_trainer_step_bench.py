"""Tier-1 smoke for tools/perf/trainer_step_bench.py (not slow).

Runs the quick variant end-to-end (real forward/backward + timed step
loops on the doc-evidence MLP) and asserts the mechanics the acceptance
criteria care about: the fused path engages, produces finite throughput,
and dispatches one executable per step. Wall-clock speedup is recorded by
the full bench (BENCH_trainer_step.json), not asserted here — shared CI
hosts are too noisy for a hard ratio gate.
"""
import importlib
import os
import sys

import numpy as np


def test_trainer_step_bench_quick():
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "perf"))
    try:
        bench = importlib.import_module("trainer_step_bench")
    finally:
        sys.path.pop(0)
    results = bench.run(quick=True)
    assert "mlp_sgd" in results and "mlp_adam" in results
    for key, r in results.items():
        assert r["n_params"] >= 4
        assert np.isfinite(r["eager_steps_per_s"]) and \
            r["eager_steps_per_s"] > 0
        assert np.isfinite(r["fused_steps_per_s"]) and \
            r["fused_steps_per_s"] > 0

"""Custom-op escape hatch (reference: python/mxnet/operator.py CustomOp/
CustomOpProp/register; canonical example example/numpy-ops/custom_softmax.py).
"""
import numpy as np
import pytest

import mxnet_tpu as mx


@mx.operator.register("test_softmax")
class SoftmaxProp(mx.operator.CustomOpProp):
    """The reference's custom softmax-loss example: forward softmax,
    backward (p - onehot), no head gradient."""

    def __init__(self):
        super(SoftmaxProp, self).__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = (in_shape[0][0],)
        output_shape = in_shape[0]
        return [data_shape, label_shape], [output_shape], []

    def create_operator(self, ctx, shapes, dtypes):
        return Softmax()


class Softmax(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        y = np.exp(x - x.max(axis=1, keepdims=True))
        y /= y.sum(axis=1, keepdims=True)
        self.assign(out_data[0], req[0], mx.nd.array(y))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        l = in_data[1].asnumpy().ravel().astype(np.int64)
        y = out_data[0].asnumpy()
        y[np.arange(l.shape[0]), l] -= 1.0
        self.assign(in_grad[0], req[0], mx.nd.array(y))
        self.assign(in_grad[1], req[1], mx.nd.zeros(in_data[1].shape))


@mx.operator.register("test_scale2")
class Scale2Prop(mx.operator.CustomOpProp):
    def __init__(self, factor="2.0"):
        super(Scale2Prop, self).__init__(need_top_grad=True)
        self.factor = float(factor)

    def create_operator(self, ctx, shapes, dtypes):
        factor = self.factor

        class Scale(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0], in_data[0] * factor)

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                self.assign(in_grad[0], req[0], out_grad[0] * factor)

        return Scale()


def test_unregistered_op_type_raises():
    with pytest.raises(KeyError, match="no_such_custom"):
        mx.nd.Custom(mx.nd.ones((2, 2)), op_type="no_such_custom")


def test_custom_eager_forward():
    x = mx.nd.array(np.array([[1.0, 2.0], [3.0, 1.0]], np.float32))
    lbl = mx.nd.array(np.zeros((2,), np.float32))
    out = mx.nd.Custom(x, lbl, op_type="test_softmax")
    p = out.asnumpy()
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)
    assert p[0, 1] > p[0, 0]


def test_custom_eager_autograd_top_grad():
    x = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.Custom(x, op_type="test_scale2", factor="3.0")
        z = mx.nd.sum(y)
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 3.0)


def test_custom_symbol_module_fit():
    """The VERDICT gate: a CustomOp softmax head trains through Module.fit."""
    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (200, 2)).astype(np.float32)
    y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.float32)

    data = mx.sym.Variable("data")
    f1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    a1 = mx.sym.Activation(f1, act_type="tanh")
    f2 = mx.sym.FullyConnected(a1, num_hidden=2, name="fc2")
    sym = mx.sym.Custom(data=f2, name="softmax", op_type="test_softmax")
    # the missing 'label' input is auto-created as softmax_label, exactly
    # like the reference's Custom symbol glue
    assert "softmax_label" in sym.list_arguments()

    it = mx.io.NDArrayIter(x, y, batch_size=50, shuffle=True,
                           label_name="softmax_label")
    mod = mx.mod.Module(sym, context=mx.cpu(),
                        label_names=("softmax_label",))
    mod.fit(it, optimizer="sgd", initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            num_epoch=20)
    it.reset()
    score = mod.score(it, "acc")
    assert dict(score)["accuracy"] > 0.9, score


def test_custom_infer_shape_through_symbol():
    data = mx.sym.Variable("data")
    out = mx.sym.Custom(data=data, op_type="test_softmax", name="cs")
    arg_shapes, out_shapes, _ = out.infer_shape(data=(8, 5))
    args = out.list_arguments()
    assert arg_shapes[args.index("cs_label")] == (8,)
    assert out_shapes == [(8, 5)]


def test_legacy_numpy_op_softmax():
    """NumpyOp shim (reference operator.py:143): the classic softmax
    example from the reference's example/numpy-ops, run through Module."""
    class NumpySoftmax(mx.operator.NumpyOp):
        def __init__(self):
            super().__init__(need_top_grad=False)

        def list_arguments(self):
            return ["data", "label"]

        def list_outputs(self):
            return ["output"]

        def infer_shape(self, in_shape):
            data_shape = in_shape[0]
            label_shape = (in_shape[0][0],)
            return [data_shape, label_shape], [data_shape]

        def forward(self, in_data, out_data):
            x = in_data[0]
            y = out_data[0]
            y[:] = np.exp(x - x.max(axis=1, keepdims=True))
            y /= y.sum(axis=1, keepdims=True)

        def backward(self, out_grad, in_data, out_data, in_grad):
            l = in_data[1].astype(int)
            y = out_data[0]
            dx = in_grad[0]
            dx[:] = y
            dx[np.arange(l.shape[0]), l] -= 1.0

    op = NumpySoftmax()
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = op.get_symbol(fc, mx.sym.Variable("softmax_label"),
                        name="softmax")
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    yl = (x[:, 0] > 0).astype(np.float32)
    it = mx.io.NDArrayIter(x, yl, batch_size=8)
    mod = mx.mod.Module(net, context=mx.cpu(0))
    mod.fit(it, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.init.Xavier(), num_epoch=12)
    it.reset()
    mod.forward(next(it), is_train=False)
    p = mod.get_outputs()[0].asnumpy()
    acc = (p.argmax(1) == yl[:8]).mean()
    assert acc >= 0.75, acc


def test_legacy_ndarray_op_scale():
    """NDArrayOp shim (reference operator.py:243): forward/backward get
    NDArrays and assign via slicing; gradient must flow."""
    class Scale(mx.operator.NDArrayOp):
        def forward(self, in_data, out_data):
            out_data[0][:] = in_data[0] * 3.0

        def backward(self, out_grad, in_data, out_data, in_grad):
            in_grad[0][:] = out_grad[0] * 3.0

    op = Scale()
    x = mx.sym.Variable("data")
    net = op.get_symbol(x, name="scale3")
    ex = net.simple_bind(ctx=mx.cpu(0), data=(2, 3))
    ex.arg_dict["data"][:] = np.ones((2, 3), np.float32)
    out = ex.forward(is_train=True)[0].asnumpy()
    np.testing.assert_allclose(out, 3.0)
    ex.backward(mx.nd.array(np.full((2, 3), 2.0, np.float32)))
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(), 6.0)


# ---------------------------------------------------------------- traced


@mx.operator.register("traced_gelu")
class TracedGeluProp(mx.operator.CustomOpProp):
    """Device-resident custom op: jax-traceable forward, autodiff grads —
    compiles into the program, no host callback (docs/new_op.md)."""

    def forward_traced(self, in_data, is_train):
        import jax
        (x,) = in_data
        return (jax.nn.gelu(x),)


@mx.operator.register("traced_softmax_loss")
class TracedSoftmaxLossProp(mx.operator.CustomOpProp):
    """Traced forward + traced custom backward with loss-op semantics
    (ignores the incoming cotangent, like SoftmaxOutput)."""

    def __init__(self):
        super(TracedSoftmaxLossProp, self).__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def infer_shape(self, in_shape):
        return [in_shape[0], [in_shape[0][0]]], [in_shape[0]], []

    def forward_traced(self, in_data, is_train):
        import jax
        x, _ = in_data
        return (jax.nn.softmax(x, axis=1),)

    def backward_traced(self, out_grad, in_data, out_data):
        import jax
        import jax.numpy as jnp
        x, label = in_data
        p = out_data[0]
        oh = jax.nn.one_hot(label.astype(jnp.int32), x.shape[1],
                            dtype=p.dtype)
        return (p - oh, jnp.zeros_like(label))


def test_traced_custom_forward_and_autodiff():
    x = mx.nd.array(np.array([[-2.0, 0.0, 3.0]], np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.Custom(x, op_type="traced_gelu")
        loss = mx.nd.sum(y)
    loss.backward()
    import jax
    import jax.numpy as jnp
    want = np.asarray(jax.nn.gelu(jnp.asarray(x.asnumpy())))
    np.testing.assert_allclose(y.asnumpy(), want, rtol=1e-5, atol=1e-6)
    gref = np.asarray(jax.grad(
        lambda v: jnp.sum(jax.nn.gelu(v)))(jnp.asarray(x.asnumpy())))
    np.testing.assert_allclose(x.grad.asnumpy(), gref, rtol=1e-5,
                               atol=1e-6)


def test_traced_custom_loss_module_fit():
    """The traced custom loss trains through the fused Module step —
    the path that must work on callback-less backends."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    h = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="tanh")
    h = mx.sym.FullyConnected(h, num_hidden=3, name="fc2")
    out = mx.sym.Custom(h, label, op_type="traced_softmax_loss",
                        name="loss")
    mod = mx.mod.Module(out, context=mx.cpu(0), data_names=["data"],
                        label_names=["label"])
    mod.bind(data_shapes=[("data", (12, 6))],
             label_shapes=[("label", (12,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    rng = np.random.RandomState(0)
    X = rng.randn(12, 6).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.float32) + (X[:, 1] > 0).astype(
        np.float32)
    db = mx.io.DataBatch(data=[mx.nd.array(X)], label=[mx.nd.array(Y)])
    for _ in range(200):
        mod.forward_backward(db)
        mod.update()
    mod.forward(db, is_train=False)
    p = mod.get_outputs()[0].asnumpy()
    assert (p.argmax(1) == Y).mean() >= 0.9


def test_traced_custom_loss_int_labels():
    """Integer-dtype inputs need float0 cotangents in the traced custom
    backward (review r5 finding)."""
    import jax.numpy as jnp
    x = mx.nd.array(np.random.RandomState(0).randn(4, 3).astype(np.float32))
    lab = mx.nd.array(np.array([0, 1, 2, 1], np.int32))
    x.attach_grad()
    with mx.autograd.record():
        p = mx.nd.Custom(x, lab, op_type="traced_softmax_loss")
        loss = mx.nd.sum(p)
    loss.backward()
    g = x.grad.asnumpy()
    pn = np.asarray(p.asnumpy())
    oh = np.eye(3, dtype=np.float32)[[0, 1, 2, 1]]
    np.testing.assert_allclose(g, pn - oh, rtol=1e-5, atol=1e-6)


_EVAL_DRAIN_SCRIPT = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, %(root)r)
import numpy as np
import mxnet_tpu as mx


@mx.operator.register("evaltime_identity")
class IdProp(mx.operator.CustomOpProp):
    def create_operator(self, ctx, shapes, dtypes):
        class Id(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                # eager NDArray dispatch INSIDE the host callback — the
                # re-entrancy that wedged train_rcnn's eval
                self.assign(out_data[0], req[0], in_data[0] * 1.0)

            def backward(self, req, out_grad, in_data, out_data,
                         in_grad, aux):
                self.assign(in_grad[0], req[0], out_grad[0])
        return Id()


import jax
import jax.numpy as jnp

# fill the async dispatch queue with heavy jitted steps, then run the
# callback-path custom op while they drain (the rcnn eval pattern:
# queued train steps + an eval-time proposal op)
f = jax.jit(lambda x: (x @ x.T).sum())
h = jnp.ones((512, 512))
pending = [f(h) for _ in range(64)]
out = mx.nd.Custom(mx.nd.array(np.ones((4, 5), np.float32)),
                   op_type="evaltime_identity")
assert float(out.asnumpy().sum()) == 20.0
jax.block_until_ready(pending)
print("DRAIN_OK")
"""


def test_callback_custom_op_while_async_queue_drains():
    """Regression (train_rcnn eval deadlock): a callback-path custom op
    issued while async-queued jitted work drains must complete — its
    user Python runs on the dedicated custom-op thread, never on the
    runtime callback thread. Hard subprocess timeout turns a regression
    into a fast failure instead of a suite wedge."""
    import os
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = _EVAL_DRAIN_SCRIPT % {"root": root}
    proc = subprocess.run(
        [sys.executable, "-c", script], timeout=120,
        capture_output=True, text=True,
        env={**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DRAIN_OK" in proc.stdout

"""Reshard-on-load property battery (ISSUE 10 tentpole + satellite).

The elastic contract: a checkpoint saved from ANY mesh/spec reassembles
from its recorded per-shard index windows and re-lays out onto ANY other
mesh/spec with bit-identical host values — N-chip save to M-chip
restore across {1,2,4,8} world sizes and dp/tp/fsdp-style/replicated
layouts, params and optimizer state together; incompatible layouts fail
with a divisibility error NAMING the offending array.
"""
import os

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding

import mxnet_tpu as mx
from mxnet_tpu import profiler
from mxnet_tpu.checkpoint import (CheckpointCorrupt, CheckpointError,
                                  read_checkpoint, reshard_tensors,
                                  write_checkpoint)
from mxnet_tpu.parallel import P
from mxnet_tpu.parallel.mesh import make_mesh, validate_spec

# dims chosen divisible by every mesh size in the battery
ROWS, COLS = 16, 8


def _tensors():
    """A params + optimizer-state shaped tensor dict (what a Module
    snapshot stages): weight, bias, and a momentum buffer per param."""
    rng = np.random.RandomState(7)
    return {
        "arg:fc1_weight": rng.normal(size=(ROWS, COLS)).astype(np.float32),
        "arg:fc1_bias": rng.normal(size=(COLS,)).astype(np.float32),
        "opt:fc1_weight.0": rng.normal(size=(ROWS, COLS)
                                       ).astype(np.float32),
        "opt:fc1_bias.0": rng.normal(size=(COLS,)).astype(np.float32),
    }


# (label, mesh_shape, layout) — layout maps the weight-shaped arrays;
# bias-shaped arrays stay replicated except under dp-bias/fsdp entries
def _mesh_cases(n):
    weight_regex = r"(arg|opt):fc1_weight(\.\d+)?"
    bias_regex = r"(arg|opt):fc1_bias(\.\d+)?"
    cases = [
        ("replicated", {"data": n}, None),
        ("dp", {"data": n}, {weight_regex: P(None, None)}),
        ("fsdp", {"data": n},
         {weight_regex: P("data", None), bias_regex: P("data")}),
    ]
    if n >= 2:
        cases.append(
            ("tp", {"data": n // 2, "model": 2},
             {weight_regex: P("model", None)}))
        cases.append(
            ("tp-col", {"data": n // 2, "model": 2},
             {weight_regex: P(None, "model")}))
    return [(("%s@%d" % (label, n)), shape, layout)
            for label, shape, layout in cases]


ALL_CASES = [c for n in (1, 2, 4, 8) for c in _mesh_cases(n)]
# the full save x load cross-product is |ALL_CASES|^2 (~300) cheap cases;
# keep the battery dense where it matters — every save case restores
# onto four representative targets incl. 1-device and the biggest tp
LOAD_TARGETS = [ALL_CASES[0],                       # replicated@1
                ("fsdp@8", {"data": 8},
                 {r"(arg|opt):fc1_weight(\.\d+)?": P("data", None),
                  r"(arg|opt):fc1_bias(\.\d+)?": P("data")}),
                ("tp@8", {"data": 4, "model": 2},
                 {r"(arg|opt):fc1_weight(\.\d+)?": P("model", None)}),
                ("dp@2", {"data": 2}, None)]


def _place(tensors, mesh, layout):
    from mxnet_tpu.checkpoint.format import resolve_layout_spec
    out = {}
    for name, arr in tensors.items():
        spec = resolve_layout_spec(layout, name)
        out[name] = jax.device_put(
            arr, NamedSharding(mesh, spec if spec is not None else P()))
    return out


@pytest.mark.parametrize("save_case", ALL_CASES,
                         ids=[c[0] for c in ALL_CASES])
def test_roundtrip_across_meshes(save_case, tmp_path):
    """Save under one mesh/spec, restore under four different ones:
    host values bit-identical every time, for params AND optimizer
    state."""
    _label, save_shape, save_layout = save_case
    ref = _tensors()
    save_mesh = make_mesh(save_shape)
    placed = _place(ref, save_mesh, save_layout)
    write_checkpoint(str(tmp_path), 1, placed)
    path = os.path.join(str(tmp_path), "ckpt-0000000001")
    for _tgt_label, load_shape, load_layout in LOAD_TARGETS:
        load_mesh = make_mesh(load_shape)
        tensors, _m = read_checkpoint(path, mesh=load_mesh,
                                      layout=load_layout)
        for k in ref:
            got = np.asarray(tensors[k])
            np.testing.assert_array_equal(got, ref[k], err_msg=k)
            from mxnet_tpu.checkpoint.format import resolve_layout_spec
            spec = resolve_layout_spec(load_layout, k)
            want = NamedSharding(load_mesh,
                                 spec if spec is not None else P())
            assert tensors[k].sharding.is_equivalent_to(
                want, np.ndim(ref[k])), k


def test_roundtrip_to_host_without_mesh(tmp_path):
    """mesh=None keeps the PR 5 behavior: plain host numpy arrays."""
    ref = _tensors()
    mesh = make_mesh({"data": 2, "model": 2})
    placed = _place(ref, mesh,
                    {r"(arg|opt):fc1_weight(\.\d+)?": P("model", None)})
    write_checkpoint(str(tmp_path), 1, placed)
    tensors, _m = read_checkpoint(
        os.path.join(str(tmp_path), "ckpt-0000000001"))
    for k in ref:
        assert isinstance(tensors[k], np.ndarray)
        np.testing.assert_array_equal(tensors[k], ref[k], err_msg=k)


def test_divisibility_error_names_the_array(tmp_path):
    write_checkpoint(str(tmp_path), 1, _tensors())
    path = os.path.join(str(tmp_path), "ckpt-0000000001")
    with pytest.raises(CheckpointError) as ei:
        read_checkpoint(path, mesh=make_mesh({"data": 3}),
                        layout={"arg:fc1_bias": P("data")})
    msg = str(ei.value)
    assert "arg:fc1_bias" in msg and "divisible" in msg


def test_unknown_axis_error_names_the_array(tmp_path):
    write_checkpoint(str(tmp_path), 1, _tensors())
    path = os.path.join(str(tmp_path), "ckpt-0000000001")
    with pytest.raises(CheckpointError) as ei:
        read_checkpoint(path, mesh=make_mesh({"data": 2}),
                        layout={"arg:fc1_weight": P("model", None)})
    msg = str(ei.value)
    assert "arg:fc1_weight" in msg and "model" in msg


def test_validate_spec_accepts_multi_axis_tuples():
    mesh = make_mesh({"data": 2, "model": 2})
    validate_spec(mesh, P(("data", "model"), None), (16, 8), name="w")
    with pytest.raises(ValueError) as ei:
        validate_spec(mesh, P(("data", "model"), None), (6, 8), name="w")
    assert "w" in str(ei.value)


def test_reshard_counter_counts_cross_mesh_arrays(tmp_path):
    ref = _tensors()
    mesh4 = make_mesh({"data": 4})
    placed = _place(ref, mesh4,
                    {r"(arg|opt):fc1_weight(\.\d+)?": P("data", None)})
    write_checkpoint(str(tmp_path), 1, placed)
    path = os.path.join(str(tmp_path), "ckpt-0000000001")
    before = profiler.get_counter("ckpt_reshard")
    read_checkpoint(path, mesh=make_mesh({"data": 2}))
    # the two weight-shaped arrays were sharded on the 4-dev mesh and
    # landed on a different one; the replicated biases don't count
    assert profiler.get_counter("ckpt_reshard") - before == 2


# ------------------------------------------------ compose-level hardening

def _manifest_edit(path, fn):
    import json
    mpath = os.path.join(path, "manifest.json")
    with open(mpath) as f:
        manifest = json.load(f)
    fn(manifest)
    with open(mpath, "w") as f:
        json.dump(manifest, f)


def test_overlapping_windows_dedup_by_last_writer(tmp_path):
    """Overlapping index windows are legal (replicated-over-one-axis
    layouts; hand-merged generations): coverage is mask-tracked and
    overlapping writes agree because each shard is crc-verified."""
    ref = _tensors()
    mesh = make_mesh({"data": 4})
    placed = _place(ref, mesh,
                    {r"arg:fc1_weight": P("data", None)})
    write_checkpoint(str(tmp_path), 1, placed)
    path = os.path.join(str(tmp_path), "ckpt-0000000001")

    def dup_first_shard(manifest):
        entry = manifest["tensors"]["arg:fc1_weight"]
        assert entry["kind"] == "sharded"
        entry["shards"].append(dict(entry["shards"][0]))

    _manifest_edit(path, dup_first_shard)
    tensors, _m = read_checkpoint(path)
    np.testing.assert_array_equal(tensors["arg:fc1_weight"],
                                  ref["arg:fc1_weight"])


def test_underfilling_shard_is_corruption_not_broadcast(tmp_path):
    """A bit-rotted window LARGER than its (crc-valid) shard must be
    corruption — numpy broadcasting would otherwise replicate the shard
    into the window and mark it covered."""
    ref = _tensors()
    mesh = make_mesh({"data": 4})
    placed = _place(ref, mesh, {r"arg:fc1_weight": P("data", None)})
    write_checkpoint(str(tmp_path), 1, placed)
    path = os.path.join(str(tmp_path), "ckpt-0000000001")

    def widen_first_window(manifest):
        entry = manifest["tensors"]["arg:fc1_weight"]
        entry["shards"][0]["index"][0] = [0, ROWS // 2]   # 2x the piece

    _manifest_edit(path, widen_first_window)
    with pytest.raises(CheckpointCorrupt) as ei:
        read_checkpoint(path)
    assert "arg:fc1_weight" in str(ei.value)


def test_uncovered_window_is_corruption(tmp_path):
    ref = _tensors()
    mesh = make_mesh({"data": 4})
    placed = _place(ref, mesh, {r"arg:fc1_weight": P("data", None)})
    write_checkpoint(str(tmp_path), 1, placed)
    path = os.path.join(str(tmp_path), "ckpt-0000000001")

    def drop_last_shard(manifest):
        entry = manifest["tensors"]["arg:fc1_weight"]
        dropped = entry["shards"].pop()
        # keep the arrays table consistent so the failure is COVERAGE,
        # not array-set mismatch
        del manifest["arrays"][dropped["key"]]

    _manifest_edit(path, drop_last_shard)
    # the npz still holds the dropped key: tolerate set mismatch by
    # checking either corruption flavor mentions the tensor state
    with pytest.raises(CheckpointCorrupt):
        read_checkpoint(path)


def test_module_fit_resumes_onto_a_different_mesh(tmp_path):
    """End-to-end: a tp-mesh module checkpoints, and fit(resume_from=)
    on a module bound to a DIFFERENT mesh shape restores and continues
    (elastic_reshard counted); the restored params match the saved host
    values bit-identically before further training."""
    rng = np.random.RandomState(0)
    X = rng.uniform(-1, 1, (32, 16)).astype(np.float32)
    Y = rng.randint(0, 8, (32,)).astype(np.float32)
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=8,
                              name="fc1"), name="softmax")

    def fit(mesh_shape, ncpu, resume=None, epochs=1, shardings=None):
        mx.random.seed(3)
        it = mx.io.NDArrayIter(X, Y, batch_size=8)
        mod = mx.mod.Module(sym, context=[mx.cpu(i) for i in range(ncpu)],
                            mesh_shape=mesh_shape,
                            param_shardings=shardings)
        mod.fit(it, num_epoch=epochs, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1},
                checkpoint=mx.checkpoint.CheckpointConfig(
                    str(tmp_path), period_epochs=1),
                resume_from=resume)
        arg, _aux = mod.get_params()
        return {k: v.asnumpy().copy() for k, v in arg.items()}

    w_saved = fit({"data": 2, "model": 2}, 4,
                  shardings={"fc1_weight": P("model", None)})
    before = profiler.get_counter("elastic_reshard")
    ckpt = mx.checkpoint.restore_latest(str(tmp_path))
    w_resumed = fit({"data": 2}, 2, resume=str(tmp_path), epochs=2)
    assert profiler.get_counter("elastic_reshard") - before >= 1
    # the restore itself was exact: checkpoint bytes == the saved params
    for k, v in ckpt.arg_params().items():
        np.testing.assert_array_equal(v, w_saved[k], err_msg=k)
    assert set(w_resumed) == set(w_saved)
    for v in w_resumed.values():
        assert np.isfinite(v).all()

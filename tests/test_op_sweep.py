"""Auto-generated registry sweep: every op gets a forward smoke check and
every differentiable op gets a central-difference gradient check.

This is the TPU counterpart of the reference's per-op forward+backward
coverage (tests/python/unittest/test_operator.py, ~9.1k LoC of manual
cases, all driven by python/mxnet/test_utils.py:439 check_numeric_gradient):
instead of hand-writing a case per op, the registry itself is the test
manifest — a guard test asserts no op can be added without either a spec,
a sensible default, or an explicit exclusion with a reason.
"""
import functools
import itertools

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops import OP_REGISTRY
from mxnet_tpu.test_utils import check_numeric_gradient

# one entry per canonical op (aliases collapse)
CANONICAL = {}
for _n, _op in OP_REGISTRY.items():
    CANONICAL.setdefault(_op.name, _op)


_rand_seq = itertools.count()


def _rand(*shape, low=-1.0, high=1.0, seed=None):
    # distinct values per call: repeated same-shape inputs must differ
    # (x==y would make specs like `where`/`elemwise_sub` vacuous). SPECS
    # entries draw from a counter at import (fixed order = deterministic);
    # the runtime default paths in _spec_for pass an op-derived seed so a
    # test reproduces identically whether run alone or in the full suite.
    if seed is None:
        seed = 1000 + next(_rand_seq)
    rng = np.random.RandomState(seed)
    return (rng.uniform(low, high, size=shape)).astype(np.float32)


def _op_seed(name, i=0):
    import zlib
    return (zlib.crc32(name.encode()) + 7919 * i) % (2 ** 31)


def _pos(*shape):
    return _rand(*shape, low=0.3, high=2.0)


_SPD = (lambda a: (a @ a.T + 3 * np.eye(3)).astype(np.float32))(
    np.random.RandomState(3).rand(3, 3))

# ops whose full behavior is covered by a dedicated test file — excluded
# from the sweep with the covering file as the reason
COVERED_ELSEWHERE = {
    "Custom": "test_custom_op.py",
    "MoE": "test_moe.py + test_gluon.py (routing exactness, bf16, grads)",
    "RNN": "test_rnn.py",
    "FlashAttention": "test_rtc.py",
    "MultiBoxPrior": "test_vision_ops.py",
    "MultiBoxTarget": "test_vision_ops.py",
    "MultiBoxDetection": "test_vision_ops.py",
    "Proposal": "test_vision_ops.py",
    "ROIPooling": "test_vision_ops.py",
    "PSROIPooling": "test_vision_ops.py",
    "BilinearSampler": "test_vision_ops.py",
    "GridGenerator": "test_vision_ops.py",
    "SpatialTransformer": "test_vision_ops.py",
    "Correlation": "test_vision_ops.py",
    "DeformableConvolution": "test_vision_ops.py",
    "CTCLoss": "test_vision_ops.py",
    "sgd_update": "test_optimizer.py",
    "sgd_mom_update": "test_optimizer.py",
    "adam_update": "test_optimizer.py",
    "adamax_update": "test_optimizer.py",
    "adagrad_update": "test_optimizer.py",
    "adadelta_update": "test_optimizer.py",
    "rmsprop_update": "test_optimizer.py",
    "rmspropalex_update": "test_optimizer.py",
    "ftrl_update": "test_optimizer.py",
    "nag_mom_update": "test_optimizer.py",
    "sgld_update": "test_optimizer.py",
}

# inputs/kwargs per op that the unary default can't serve.
# value: (list_of_input_arrays, kwargs) with optional third element
# "nograd" for float ops whose gradient is not finite-difference checkable
SPECS = {
    "Activation": ([_rand(2, 3)], {"act_type": "tanh"}),
    # gradient needs train_mode + fix_gamma handling — numeric-checked in
    # test_autograd_semantics.py::test_numeric_gradient_batchnorm_train
    "BatchNorm": ([_rand(2, 3, 4, 4), _pos(3), _rand(3), _rand(3),
                   _pos(3)], {}, "nograd"),
    "BlockGrad": ([_rand(2, 3)], {}, "nograd"),   # grad is defined as zero
    "Cast": ([_rand(2, 3)], {"dtype": "float32"}),
    "Concat": ([_rand(2, 3), _rand(2, 3)], {"dim": 1}),
    "Convolution": ([_rand(1, 2, 5, 5), _rand(4, 2, 3, 3), _rand(4)],
                    {"kernel": (3, 3), "num_filter": 4}),
    "Deconvolution": ([_rand(1, 2, 5, 5), _rand(2, 4, 3, 3), _rand(4)],
                      {"kernel": (3, 3), "num_filter": 4}),
    "Dropout": ([_rand(2, 3)], {"p": 0.0}),
    # indices are not differentiable — gradient checked wrt weight only
    "Embedding": ([np.array([[0, 2], [1, 0]], np.float32), _rand(4, 3)],
                  {"input_dim": 4, "output_dim": 3}, ["arg1"]),
    "Flatten": ([_rand(2, 3, 4)], {}),
    "FullyConnected": ([_rand(2, 3), _rand(4, 3), _rand(4)],
                       {"num_hidden": 4}),
    # backward intentionally attaches a KL penalty (not the forward's
    # gradient), so finite differences can't validate it
    "IdentityAttachKLSparseReg": ([_pos(2, 3), _pos(3)], {}, "nograd"),
    "Crop": ([_rand(1, 2, 6, 6), _rand(1, 2, 4, 4)],
             {"offset": (1, 1)}),
    "InstanceNorm": ([_rand(2, 3, 4, 4), _pos(3), _rand(3)], {}),
    "LayerNorm": ([_rand(2, 3, 8), _pos(8), _rand(8)], {}),
    "L2Normalization": ([_rand(2, 3)], {}),
    "LRN": ([_rand(1, 4, 5, 5)], {"nsize": 3}),
    "LeakyReLU": ([_rand(2, 3)], {"act_type": "leaky"}),
    # *Output loss layers: the backward is the LOSS gradient (out - label
    # etc.), not the vjp of the forward output — finite differences of the
    # forward cannot validate it by design (reference *Output semantics;
    # covered by test_autograd_semantics.py loss-gradient oracles)
    "LinearRegressionOutput": ([_rand(2, 3), _rand(2, 3)], {}, "nograd"),
    "LogisticRegressionOutput": ([_rand(2, 3), _rand(2, 3)], {}, "nograd"),
    "MAERegressionOutput": ([_rand(2, 3), _rand(2, 3)], {}, "nograd"),
    "MakeLoss": ([_pos(2, 3)], {}, "nograd"),
    "Pad": ([_rand(1, 2, 3, 3)], {"mode": "constant",
                                  "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)}),
    "Pooling": ([_rand(1, 2, 4, 4)], {"kernel": (2, 2), "pool_type": "max",
                                      "stride": (2, 2)}),
    "Reshape": ([_rand(2, 6)], {"shape": (3, 4)}),
    "SVMOutput": ([_rand(2, 3), np.array([0, 2], np.float32)], {},
                  "nograd"),
    "SequenceLast": ([_rand(3, 2, 4)], {}),
    "SequenceMask": ([_rand(3, 2, 4)], {}),
    "SequenceReverse": ([_rand(3, 2, 4)], {}),
    "SliceChannel": ([_rand(2, 4)], {"num_outputs": 2}),
    "SoftmaxActivation": ([_rand(2, 3)], {}),
    "SoftmaxOutput": ([_rand(2, 3), np.array([0, 2], np.float32)], {},
                      "nograd"),
    "SwapAxis": ([_rand(2, 3)], {"dim1": 0, "dim2": 1}),
    "UpSampling": ([_rand(1, 2, 3, 3)], {"scale": 2,
                                         "sample_type": "nearest"}),
    "_arange": ([], {"start": 0, "stop": 6}),
    "_eye": ([], {"N": 3}),
    "_full": ([], {"shape": (2, 3), "value": 1.5}),
    "_ones": ([], {"shape": (2, 3)}),
    "_zeros": ([], {"shape": (2, 3)}),
    "add_n": ([_rand(2, 3), _rand(2, 3), _rand(2, 3)], {}),
    "argmax": ([_rand(2, 3)], {}),
    "argmax_channel": ([_rand(2, 3)], {}),
    "argmin": ([_rand(2, 3)], {}),
    "argsort": ([_rand(2, 3)], {}),
    "batch_dot": ([_rand(2, 3, 4), _rand(2, 4, 3)], {}),
    "batch_take": ([_rand(2, 3), np.array([0, 2], np.float32)], {},
                   "nograd"),
    "broadcast_axis": ([_rand(1, 3)], {"axis": 0, "size": 2}),
    "broadcast_to": ([_rand(1, 3)], {"shape": (2, 3)}),
    "clip": ([_rand(2, 3)], {"a_min": -0.5, "a_max": 0.5}),
    "count_sketch": ([_rand(2, 8),
                      np.abs(_rand(8)) * 3.9,
                      np.sign(_rand(8)) + (np.sign(_rand(8)) == 0)],
                     {"out_dim": 4}, "nograd"),
    "dequantize": ([(_rand(2, 3) * 100).astype(np.uint8).astype(np.float32),
                    np.float32([0.0]), np.float32([255.0])],
                   {"out_type": "float32"}, "nograd"),
    "dot": ([_rand(2, 3), _rand(3, 2)], {}),
    "elemwise_add": ([_rand(2, 3), _rand(2, 3)], {}),
    "elemwise_div": ([_rand(2, 3), _pos(2, 3)], {}),
    "elemwise_mul": ([_rand(2, 3), _rand(2, 3)], {}),
    "elemwise_sub": ([_rand(2, 3), _rand(2, 3)], {}),
    "expand_dims": ([_rand(2, 3)], {"axis": 1}),
    "fft": ([_rand(2, 8)], {}, "nograd"),
    "ifft": ([_rand(2, 16)], {}, "nograd"),
    "gather_nd": ([_rand(3, 4), np.array([[0, 2], [1, 3]], np.float32)],
                  {}, "nograd"),
    "khatri_rao": ([_rand(2, 3), _rand(4, 3)], {}),
    "linalg_gemm": ([_rand(2, 3), _rand(3, 2), _rand(2, 2)], {}),
    "linalg_gemm2": ([_rand(2, 3), _rand(3, 2)], {}),
    "linalg_potrf": ([_SPD], {}),
    "linalg_potri": ([_SPD], {}),
    "linalg_sumlogdiag": ([_SPD], {}),
    "linalg_trmm": ([np.tril(_pos(3, 3)) + np.eye(3, dtype=np.float32),
                     _rand(3, 3)], {}),
    "linalg_trsm": ([np.tril(_pos(3, 3)) + np.eye(3, dtype=np.float32),
                     _rand(3, 3)], {}),
    "one_hot": ([np.array([0, 2, 1], np.float32)], {"depth": 3}, "nograd"),
    "pick": ([_rand(2, 3), np.array([0, 2], np.float32)], {}, "nograd"),
    "quantize": ([_rand(2, 3), np.float32([-1.0]), np.float32([1.0])],
                 {"out_type": "uint8"}, "nograd"),
    "repeat": ([_rand(2, 3)], {"repeats": 2}),
    "reverse": ([_rand(2, 3)], {"axis": 1}),
    "slice": ([_rand(3, 4)], {"begin": (0, 1), "end": (2, 3)}),
    "slice_axis": ([_rand(3, 4)], {"axis": 1, "begin": 1, "end": 3}),
    "smooth_l1": ([_rand(2, 3)], {"scalar": 1.0}),
    "stack": ([_rand(2, 3), _rand(2, 3)], {"axis": 0}),
    "take": ([_rand(4, 3), np.array([0, 2], np.float32)], {}, "nograd"),
    "tile": ([_rand(2, 3)], {"reps": (2, 1)}),
    "topk": ([_rand(2, 6)], {"k": 2}),
    "where": ([(np.array([[1, 0, 1], [0, 1, 0]], np.float32)),
               _rand(2, 3), _rand(2, 3)], {}, "nograd"),
}

# unary ops with restricted domains: name -> (low, high)
DOMAIN = {
    "arccos": (-0.8, 0.8), "arcsin": (-0.8, 0.8), "arctanh": (-0.8, 0.8),
    "erfinv": (-0.8, 0.8),
    "arccosh": (1.2, 3.0),
    "log": (0.3, 3.0), "log10": (0.3, 3.0), "log2": (0.3, 3.0),
    "log1p": (-0.5, 3.0), "expm1": (-1.0, 1.0),
    "sqrt": (0.3, 3.0), "rsqrt": (0.3, 3.0), "cbrt": (0.3, 3.0),
    "rcbrt": (0.3, 3.0),
    "gamma": (0.5, 3.0), "gammaln": (0.5, 3.0),
    "reciprocal": (0.3, 3.0),
    "norm": (0.3, 3.0),
    # step functions: sample away from the jumps so the numeric gradient
    # (zero) is well-defined at the probe points
    "ceil": (0.1, 0.4), "floor": (0.1, 0.4), "round": (0.1, 0.4),
    "rint": (0.1, 0.4), "fix": (0.1, 0.4), "trunc": (0.1, 0.4),
    "sign": (0.3, 0.9),
}

_SCALAR_KW = {"_power_scalar": {"scalar": 2.0},
              "_rpower_scalar": {"scalar": 2.0},
              "_mod_scalar": {"scalar": 2.0}}


def _spec_for(name):
    """Resolve (inputs, kwargs, grad_ok, grad_nodes) for an op, falling
    back to the generic unary/binary/scalar defaults. A spec's optional
    third element is "nograd" (skip the gradient check) or a list of
    positional arg names (check those gradients only)."""
    op = CANONICAL[name]
    if name in SPECS:
        s = SPECS[name]
        if len(s) < 3:
            return s[0], s[1], True, None
        if isinstance(s[2], list):
            return s[0], s[1], True, s[2]
        return s[0], s[1], False, None
    if name.endswith("_scalar"):
        lo, hi = (0.3, 2.0) if name in ("_mod_scalar", "_rdiv_scalar",
                                        "_rpower_scalar") else (-1.0, 1.0)
        return [_rand(2, 3, low=lo, high=hi, seed=_op_seed(name))], \
            _SCALAR_KW.get(name, {"scalar": 1.5}), True, None
    if name.startswith("broadcast_"):
        return [_rand(2, 3, low=0.3, high=2.0, seed=_op_seed(name)),
                _rand(1, 3, low=0.3, high=2.0, seed=_op_seed(name, 1))], \
            {}, True, None
    if op.is_random or op.needs_rng:
        shape_kw = {} if op.num_inputs else {"shape": (2, 3)}
        ins = [np.abs(_rand(2, 3, seed=_op_seed(name, i))) + 0.5
               for i in range(op.num_inputs or 0)]
        return ins, shape_kw, False, None
    if op.num_inputs == 1:
        lo, hi = DOMAIN.get(name, (-1.0, 1.0))
        return [_rand(2, 3, low=lo, high=hi, seed=_op_seed(name))], \
            {}, True, None
    if op.num_inputs == 2:
        return [_rand(2, 3, seed=_op_seed(name)),
                _rand(2, 3, low=0.3, high=2.0, seed=_op_seed(name, 1))], \
            {}, True, None
    raise NotImplementedError(
        "op %r (num_inputs=%r) has no sweep spec — add one to SPECS or "
        "COVERED_ELSEWHERE in tests/test_op_sweep.py" % (name, op.num_inputs))


SWEEP = sorted(n for n in CANONICAL if n not in COVERED_ELSEWHERE)


def test_every_registry_op_is_swept_or_justified():
    """Guard: adding an op without sweep coverage fails the suite."""
    for name in SWEEP:
        _spec_for(name)        # raises NotImplementedError if unspecced


@pytest.mark.parametrize("name", SWEEP)
def test_forward(name):
    inputs, kwargs, _, _ = _spec_for(name)
    fn = getattr(mx.nd, name)
    out = fn(*[mx.nd.array(a) for a in inputs], **kwargs)
    outs = out if isinstance(out, (list, tuple)) else [out]
    for o in outs:
        v = o.asnumpy()
        assert np.isfinite(v.astype(np.float64)).all(), \
            "%s produced non-finite output" % name


def _grad_names():
    names = []
    for name in SWEEP:
        inputs, kwargs, grad_ok, _ = _spec_for(name)
        if not grad_ok or not inputs:
            continue
        names.append(name)
    return names


@pytest.mark.parametrize("name", _grad_names())
def test_gradient(name):
    inputs, kwargs, _, grad_nodes = _spec_for(name)
    fn = getattr(mx.nd, name)
    out = fn(*[mx.nd.array(a) for a in inputs], **kwargs)
    first = (out[0] if isinstance(out, (list, tuple)) else out)
    if first.dtype not in (np.float32, np.float64):
        pytest.skip("integer-valued output")
    wrapped = functools.partial(fn, **kwargs) if kwargs else fn
    check_numeric_gradient(wrapped, list(inputs), grad_nodes=grad_nodes,
                           numeric_eps=1e-3, rtol=3e-2, atol=3e-3)

"""Autograd semantics probes — the round-1 VERDICT/ADVICE failure cases.

Reference semantics being matched: the reference tracks autograd nodes on the
NDArray itself (src/ndarray/autograd.cc:129-227), so gradients are computed at
the values the forward consumed, and chains through in-place updates stay
correct.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient


def test_post_record_mutation_uses_recorded_value():
    # VERDICT "What's weak" #1 probe: grad must be 4 (recorded p=2), not 200.
    p = mx.nd.array([2.0])
    p.attach_grad()
    with autograd.record():
        q = p * p
    p[:] = 100.0
    q.backward()
    assert_almost_equal(p.grad, np.array([4.0]))


def test_inplace_mul_chains_gradient():
    # ADVICE high probe: w=2, x=w*3; x*=2; sum(x).backward() -> w.grad == 6.
    w = mx.nd.array([2.0])
    w.attach_grad()
    with autograd.record():
        x = w * 3.0
        x *= 2.0
        y = x.sum()
    y.backward()
    assert_almost_equal(w.grad, np.array([6.0]))


def test_inplace_add_ndarray_chains_gradient():
    w = mx.nd.array([1.0, 2.0])
    w.attach_grad()
    with autograd.record():
        x = w * 2.0
        x += w          # x = 3w
        y = (x * x).sum()
    y.backward()
    # d/dw sum((3w)^2) = 18w
    assert_almost_equal(w.grad, np.array([18.0, 36.0]))


def test_setitem_outside_tape_does_not_corrupt():
    # mutation via __setitem__ during recording is not a recorded op: the
    # recorded uses keep their recorded values.
    p = mx.nd.array([3.0])
    p.attach_grad()
    with autograd.record():
        q = p * p          # uses p@v0 = 3
        p[:] = 7.0          # unrecorded mutation -> new version
        r = p * p          # uses p@v1 = 7
        y = q + r
    y.backward()
    # dq/dp@v0 = 6, dr/dp@v1 = 14; both accumulate into p.grad
    assert_almost_equal(p.grad, np.array([20.0]))


def test_backward_only_consumes_own_subgraph():
    # retain_graph=False must not clear tape entries of unrelated heads.
    a = mx.nd.array([2.0])
    b = mx.nd.array([3.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        x = a * a
        y = b * b * b
    x.backward()
    assert_almost_equal(a.grad, np.array([4.0]))
    y.backward()   # must still work
    assert_almost_equal(b.grad, np.array([27.0]))


def test_retain_graph_allows_double_backward():
    a = mx.nd.array([2.0])
    a.attach_grad()
    with autograd.record():
        x = a * a
    x.backward(retain_graph=True)
    assert_almost_equal(a.grad, np.array([4.0]))
    x.backward()
    assert_almost_equal(a.grad, np.array([4.0]))


def test_grad_req_add_accumulates():
    a = mx.nd.array([2.0])
    grad = mx.nd.zeros((1,))
    autograd.mark_variables([a], [grad], "add")
    for _ in range(3):
        with autograd.record():
            x = a * a
        x.backward()
    assert_almost_equal(a.grad, np.array([12.0]))


def test_aux_state_recorded_before_commit():
    # BatchNorm: replay must consume the pre-update moving stats (ADVICE low).
    data = mx.nd.array(np.random.randn(4, 3).astype(np.float32))
    gamma = mx.nd.ones((3,))
    beta = mx.nd.zeros((3,))
    mmean = mx.nd.zeros((3,))
    mvar = mx.nd.ones((3,))
    data.attach_grad()
    with autograd.record(train_mode=False):
        out = mx.nd.BatchNorm(data, gamma, beta, mmean, mvar,
                              use_global_stats=True, fix_gamma=False)
        loss = (out * out).sum()
    # mutate aux after recording: replay must still use recorded stats
    mmean[:] = 5.0
    mvar[:] = 9.0
    loss.backward()
    # use_global_stats with mean=0, var=1, eps=1e-3: out ≈ data/sqrt(1+eps)
    expected = 2 * data.asnumpy() / (1 + 1e-3)
    assert_almost_equal(data.grad, expected, rtol=1e-4, atol=1e-5)


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = 1.0 / (1.0 + mx.nd.exp(-x))
            self.saved = y
            return y

        def backward(self, dy):
            y = self.saved
            return dy * y * (1.0 - y)

    x = mx.nd.array([0.0, 1.0, -2.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
        z = y.sum()
    z.backward()
    s = 1.0 / (1.0 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad, s * (1 - s), rtol=1e-5, atol=1e-6)


def test_tape_pruned_on_new_record_scope():
    from mxnet_tpu.autograd import _st
    # isolate from entries other tests' live arrays legitimately keep on
    # the process-global tape (this asserts pruning, not global cleanliness)
    _st().tape.clear()
    a = mx.nd.array([1.0])
    a.attach_grad()
    for _ in range(5):
        with autograd.record():
            _tmp = a * 2.0      # head dropped, never backward'd
        del _tmp
    with autograd.record():
        pass
    assert len(_st().tape) == 0


# ------------------------------------------------------ numeric gradients


def test_numeric_gradient_elemwise_chain():
    check_numeric_gradient(
        lambda x, y: (x * y + mx.nd.tanh(x)).sum(),
        {"x": np.random.randn(3, 4), "y": np.random.randn(3, 4)})


def test_numeric_gradient_fully_connected():
    check_numeric_gradient(
        lambda data, w, b: mx.nd.FullyConnected(data, w, b, num_hidden=4),
        {"data": np.random.randn(2, 5), "w": np.random.randn(4, 5),
         "b": np.random.randn(4)})


def test_numeric_gradient_convolution():
    check_numeric_gradient(
        lambda data, w, b: mx.nd.Convolution(
            data, w, b, kernel=(3, 3), num_filter=2, pad=(1, 1)),
        {"data": np.random.randn(1, 2, 5, 5), "w": np.random.randn(2, 2, 3, 3),
         "b": np.random.randn(2)},
        rtol=2e-2, atol=2e-3)


def test_numeric_gradient_batchnorm_train():
    def fn(data, gamma, beta):
        mm = mx.nd.zeros((3,))
        mv = mx.nd.ones((3,))
        with autograd.train_mode():
            return mx.nd.BatchNorm(data, gamma, beta, mm, mv,
                                   fix_gamma=False, momentum=0.9)
    check_numeric_gradient(
        fn, {"data": np.random.randn(8, 3), "gamma": np.random.rand(3) + 0.5,
             "beta": np.random.randn(3)}, rtol=2e-2, atol=2e-3)


def test_softmax_output_matches_ce_gradient_3d():
    # ADVICE medium probe: default mode flattens trailing axes; backward must
    # match the gradient of CE over the flattened distribution.
    np.random.seed(0)
    data = np.random.randn(2, 3, 4).astype(np.float32)
    label = np.random.randint(0, 12, size=(2,)).astype(np.float32)

    d = mx.nd.array(data)
    l = mx.nd.array(label)
    d.attach_grad()
    with autograd.record():
        out = mx.nd.SoftmaxOutput(d, l)
    out.backward()

    # explicit CE gradient: p - onehot over flattened classes
    flat = data.reshape(2, -1)
    p = np.exp(flat - flat.max(1, keepdims=True))
    p /= p.sum(1, keepdims=True)
    oh = np.zeros_like(p)
    oh[np.arange(2), label.astype(int)] = 1.0
    assert_almost_equal(d.grad, (p - oh).reshape(data.shape),
                        rtol=1e-4, atol=1e-5)


def test_kl_sparse_reg_gradient():
    np.random.seed(1)
    rho, penalty, mom = 0.1, 0.01, 0.9
    act = np.random.rand(4, 3).astype(np.float32) * 0.8 + 0.1
    ma0 = np.full((3,), 0.2, dtype=np.float32)
    d = mx.nd.array(act)
    ma = mx.nd.array(ma0)
    d.attach_grad()
    with autograd.record():
        out = mx.nd.IdentityAttachKLSparseReg(
            d, ma, sparseness_target=rho, penalty=penalty, momentum=mom)
        loss = out.sum()
    loss.backward()
    # aux committed: moving_avg updated with batch mean
    new_ma = mom * ma0 + (1 - mom) * act.mean(0)
    assert_almost_equal(ma, new_ma, rtol=1e-5, atol=1e-6)
    expected = 1.0 + penalty * (-rho / new_ma + (1 - rho) / (1 - new_ma))
    assert_almost_equal(d.grad, np.broadcast_to(expected, act.shape),
                        rtol=1e-4, atol=1e-5)


def test_save_load_no_pickle(tmp_path):
    f = str(tmp_path / "ck.npz")
    arrs = {"w": mx.nd.array([[1.0, 2.0]]), "b": mx.nd.array([3.0])}
    mx.nd.save(f, arrs)
    loaded = mx.nd.load(f)
    assert set(loaded) == {"w", "b"}
    assert_almost_equal(loaded["w"], arrs["w"].asnumpy())

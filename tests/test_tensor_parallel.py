"""Tensor parallelism through the Module surface (SURVEY §2.21): a 2D
data x model mesh, parameters partitioned over the model axis, XLA
inserting the TP collectives from operand shardings."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.parallel import P


def _mlp():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    h = mx.sym.Activation(h, act_type="tanh")
    h = mx.sym.FullyConnected(h, num_hidden=2, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.uniform(-1, 1, (n, 6)).astype(np.float32)
    y = ((x[:, 0] * x[:, 1]) > 0).astype(np.float32)
    return x, y


# Megatron-style split: fc1 column-parallel (output dim over model),
# fc2 row-parallel (input dim over model) -> one psum at fc2's output
TP_SHARDINGS = {
    "fc1_weight": P("model", None),
    "fc1_bias": P("model"),
    "fc2_weight": P(None, "model"),
}


def _train(mesh_shape, param_shardings, steps=4):
    x, y = _data()
    np.random.seed(0)
    mx.random.seed(0)
    ctxs = [mx.cpu(i) for i in range(8)] if mesh_shape else [mx.cpu(0)]
    mod = mx.mod.Module(_mlp(), context=ctxs, mesh_shape=mesh_shape,
                        param_shardings=param_shardings)
    mod.bind(data_shapes=[("data", (64, 6))],
             label_shapes=[("softmax_label", (64,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    batch = mx.io.DataBatch(data=[mx.nd.array(x)],
                            label=[mx.nd.array(y)])
    for _ in range(steps):
        mod._fit_step(batch)
    return mod


def test_tp_params_actually_partitioned():
    mod = _train({"data": 2, "model": 4}, TP_SHARDINGS, steps=1)
    w1 = mod._exec.arg_dict["fc1_weight"].data
    assert len(w1.devices()) == 8
    spec = w1.sharding.spec
    assert "model" in str(spec), spec
    # a shard holds 1/4 of the rows (32/4 = 8)
    shard_shape = w1.sharding.shard_shape(w1.shape)
    assert shard_shape == (8, 6)


def test_tp_matches_single_device_training():
    """dp x tp fused training must be numerically identical to the
    single-device run (same init, same data)."""
    single = _train(None, None)
    tp = _train({"data": 2, "model": 4}, TP_SHARDINGS)
    p1 = {k: v.asnumpy() for k, v in single.get_params()[0].items()}
    p2 = {k: v.asnumpy() for k, v in tp.get_params()[0].items()}
    for k in p1:
        np.testing.assert_allclose(p1[k], p2[k], rtol=2e-4, atol=2e-5,
                                   err_msg=k)


def test_tp_regex_shardings():
    mod = _train({"data": 2, "model": 4},
                 {r"fc1_w.*": P("model", None)}, steps=1)
    w1 = mod._exec.arg_dict["fc1_weight"].data
    assert "model" in str(w1.sharding.spec)
    # non-matching params stay replicated
    w2 = mod._exec.arg_dict["fc2_weight"].data
    assert "model" not in str(w2.sharding.spec)


def test_pure_tp_mesh_without_data_axis():
    """A model-only mesh replicates the batch instead of crashing."""
    np.random.seed(0)
    mx.random.seed(0)
    ctxs = [mx.cpu(i) for i in range(4)]
    mod = mx.mod.Module(_mlp(), context=ctxs, mesh_shape={"model": 4},
                        param_shardings={"fc1_weight": P("model", None)})
    mod.bind(data_shapes=[("data", (16, 6))],
             label_shapes=[("softmax_label", (16,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    x, y = _data(16, seed=2)
    mod._fit_step(mx.io.DataBatch(data=[mx.nd.array(x)],
                                  label=[mx.nd.array(y)]))
    w = mod._exec.arg_dict["fc1_weight"]
    assert np.isfinite(w.asnumpy()).all()


def test_mesh_shape_context_mismatch_raises():
    ctxs = [mx.cpu(i) for i in range(8)]
    mod = mx.mod.Module(_mlp(), context=ctxs,
                        mesh_shape={"data": 2, "model": 2})
    with pytest.raises(ValueError, match="must match"):
        mod.bind(data_shapes=[("data", (16, 6))],
                 label_shapes=[("softmax_label", (16,))])


def test_tp_forward_predict_path():
    mod = _train({"data": 2, "model": 4}, TP_SHARDINGS, steps=2)
    x, y = _data(32, seed=5)
    it = mx.io.NDArrayIter(x, y, batch_size=32,
                           label_name="softmax_label")
    out = mod.predict(it).asnumpy()
    assert out.shape == (32, 2)
    assert np.allclose(out.sum(axis=1), 1.0, atol=1e-4)

"""Profiler, Monitor, visualization (reference tests:
tests/python/unittest/test_profiler.py + monitor usage in test_monitor.py)."""
import json
import os
import tempfile

import numpy as np

import mxnet_tpu as mx


def _mlp():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="relu1")
    h = mx.sym.FullyConnected(h, num_hidden=2, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def test_profiler_records_ops_and_dumps_chrome_trace():
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "prof.json")
        mx.profiler.set_config(filename=path)
        mx.profiler.set_state("run")
        a = mx.nd.uniform(shape=(8, 8))
        b = mx.nd.dot(a, a)
        (b + 1).asnumpy()
        with mx.profiler.record("my_region"):
            mx.nd.sum(b).asnumpy()
        mx.profiler.set_state("stop")
        out = mx.profiler.dump()
        assert out == path
        trace = json.load(open(path))
        names = [e["name"] for e in trace["traceEvents"]]
        assert "dot" in names
        assert "my_region" in names
        # complete events carry real durations; lane-name metadata ("M")
        # and flow events ("s"/"t") are part of the format since mx.obs
        for e in trace["traceEvents"]:
            assert e["ph"] in ("X", "M", "s", "t", "f")
            if e["ph"] == "X":
                assert e["dur"] >= 0


def test_profiler_off_records_nothing():
    mx.profiler.set_state("stop")
    mx.nd.uniform(shape=(4, 4)).asnumpy()
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "p.json")
        mx.profiler.set_config(filename=path)
        mx.profiler.dump()
        assert json.load(open(path))["traceEvents"] == []


def test_monitor_collects_per_op_stats():
    sym = _mlp()
    ex = sym.simple_bind(ctx=mx.cpu(), data=(4, 6),
                         softmax_label=(4,))
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "softmax_label"):
            arr[:] = np.random.RandomState(0).rand(*arr.shape)
    ex.arg_dict["data"][:] = np.random.RandomState(1).rand(4, 6)
    ex.arg_dict["softmax_label"][:] = np.array([0, 1, 0, 1], np.float32)

    mon = mx.mon.Monitor(interval=1, pattern=".*fc.*")
    mon.install(ex)
    mon.tic()
    ex.forward(is_train=True)
    stats = mon.toc()
    names = [k for _, k, _ in stats]
    assert any("fc1" in n for n in names)
    assert any("fc2" in n for n in names)
    assert not any("relu" in n for n in names)   # pattern filtered
    for _, _, v in stats:
        assert float(v) >= 0


def test_monitor_through_module_fit():
    """install_monitor has a real Monitor to receive now (VERDICT 5.1)."""
    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (40, 6)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=20, label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mon = mx.mon.Monitor(interval=2)
    mod.fit(it, optimizer="sgd", initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.1}, num_epoch=1,
            monitor=mon)
    assert mon.step > 0


def test_print_summary_counts_params(capsys):
    sym = _mlp()
    total = mx.viz.print_summary(sym, shape={"data": (4, 6)})
    # fc1: 6*8+8, fc2: 8*2+2
    assert total == 6 * 8 + 8 + 8 * 2 + 2
    out = capsys.readouterr().out
    assert "fc1" in out and "Total params" in out


def test_plot_network_builds_digraph():
    try:
        import graphviz  # noqa: F401
    except ImportError:
        import pytest
        pytest.skip("graphviz not installed")
    dot = mx.viz.plot_network(_mlp(), shape={"data": (4, 6)})
    src = dot.source
    assert "fc1" in src and "softmax" in src

"""Pipeline parallelism (parallel/pipeline.py): GPipe microbatch schedule
over a mesh axis, forward and gradients checked against the sequential
oracle on the 8-device virtual CPU mesh.

Reference parity target: the reference's inter-layer model parallelism
(group2ctx + PlaceDevice, src/executor/graph_executor.cc:279-393) — here
as an explicit SPMD schedule with ppermute stage hops.
"""
import numpy as np
import pytest

import jax
from jax import experimental as jax_experimental
import jax.numpy as jnp

from mxnet_tpu.parallel import make_mesh, pipeline_apply, stack_stage_params

N_STAGES = 4


def _setup(dtype=np.float32, n_micro=8, mb=4, dim=16):
    rng = np.random.RandomState(0)
    stages = [{"w": rng.normal(0, 0.3, (dim, dim)).astype(dtype),
               "b": rng.normal(0, 0.1, (dim,)).astype(dtype)}
              for _ in range(N_STAGES)]
    x = rng.normal(0, 1, (n_micro, mb, dim)).astype(dtype)
    return stages, x


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _seq(stages, x):
    y = x
    for p in stages:
        y = jnp.tanh(y @ p["w"] + p["b"])
    return y


def test_pipeline_forward_matches_sequential():
    mesh = make_mesh({"pipe": N_STAGES})
    stages, x = _setup()
    out = pipeline_apply(_stage_fn, stack_stage_params(stages), x,
                         mesh=mesh, axis="pipe")
    np.testing.assert_allclose(np.asarray(out), np.asarray(_seq(stages, x)),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_gradients_match_sequential_f64():
    # float64 removes scan-order rounding: forward AND backward must be
    # bit-tight vs the sequential program
    mesh = make_mesh({"pipe": N_STAGES})
    with jax_experimental.enable_x64():
        stages, x = _setup(dtype=np.float64, n_micro=6, mb=2, dim=8)
        stacked = stack_stage_params(stages)

        def loss_pipe(params, xx):
            return jnp.sum(pipeline_apply(_stage_fn, params, xx, mesh=mesh,
                                          axis="pipe") ** 2)

        def loss_seq(ps, xx):
            return jnp.sum(_seq(ps, xx) ** 2)

        g = jax.grad(loss_pipe)(stacked, x)
        g_ref = jax.grad(loss_seq)(stages, x)
        for i in range(N_STAGES):
            np.testing.assert_allclose(np.asarray(g["w"][i]),
                                       np.asarray(g_ref[i]["w"]),
                                       rtol=1e-12, atol=1e-12)
        gx = jax.grad(lambda xx: loss_pipe(stacked, xx))(x)
        gx_ref = jax.grad(lambda xx: loss_seq(stages, xx))(x)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                                   rtol=1e-12, atol=1e-12)


def test_pipeline_trains_f32():
    # one SGD step through the pipelined loss moves params and tracks the
    # sequential update within f32 schedule-rounding tolerance
    mesh = make_mesh({"pipe": N_STAGES})
    stages, x = _setup(n_micro=4, mb=2, dim=8)
    stacked = stack_stage_params(stages)

    def loss(params, xx):
        return jnp.mean(pipeline_apply(_stage_fn, params, xx, mesh=mesh,
                                       axis="pipe") ** 2)

    g = jax.grad(loss)(stacked, x)
    g_ref = jax.grad(
        lambda ps, xx: jnp.mean(_seq(ps, xx) ** 2))(stages, x)
    for i in range(N_STAGES):
        np.testing.assert_allclose(np.asarray(g["w"][i]),
                                   np.asarray(g_ref[i]["w"]),
                                   rtol=5e-2, atol=5e-4)
    new_w = stacked["w"] - 0.1 * g["w"]
    assert not np.allclose(np.asarray(new_w), np.asarray(stacked["w"]))


def test_pipeline_rejects_empty_microbatches():
    mesh = make_mesh({"pipe": N_STAGES})
    stages, x = _setup()
    with pytest.raises(ValueError):
        pipeline_apply(_stage_fn, stack_stage_params(stages), x[:0],
                       mesh=mesh, axis="pipe")


def test_pipeline_heterogeneous_embed_to_loss():
    # first_fn embeds int ids -> wire, stage_fn maps wire -> wire,
    # last_fn projects wire -> per-token logits; checks the full
    # embed -> blocks -> head shape change against the sequential oracle
    mesh = make_mesh({"pipe": N_STAGES})
    rng = np.random.RandomState(1)
    V, D, O, n_micro, mb, T = 11, 8, 5, 6, 2, 3
    stages = [{"w": rng.normal(0, 0.3, (D, D)).astype(np.float32),
               "b": rng.normal(0, 0.1, (D,)).astype(np.float32)}
              for _ in range(N_STAGES)]
    fparams = {"emb": rng.normal(0, 1, (V, D)).astype(np.float32)}
    lparams = {"head": rng.normal(0, 0.3, (D, O)).astype(np.float32)}
    ids = rng.randint(0, V, (n_micro, mb, T)).astype(np.int32)

    def first(p, raw):
        return p["emb"][raw]                     # (mb, T, D)

    def last(p, h):
        return h @ p["head"]                     # (mb, T, O)

    out = pipeline_apply(_stage_fn, stack_stage_params(stages),
                         jnp.asarray(ids), mesh=mesh, axis="pipe",
                         first_fn=first, first_params=fparams,
                         last_fn=last, last_params=lparams)
    assert out.shape == (n_micro, mb, T, O)
    ref = last(lparams, _seq(stages, first(fparams, jnp.asarray(ids))))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    # gradients flow into the replicated first/last params too
    def loss(fp, sp, lp):
        o = pipeline_apply(_stage_fn, sp, jnp.asarray(ids), mesh=mesh,
                           axis="pipe", first_fn=first, first_params=fp,
                           last_fn=last, last_params=lp)
        return jnp.mean(o ** 2)

    gf, gs, gl = jax.grad(loss, argnums=(0, 1, 2))(fparams,
                                                   stack_stage_params(stages),
                                                   lparams)
    def ref_loss(fp, sp_list, lp):
        return jnp.mean(last(lp, _seq(sp_list, first(fp, jnp.asarray(ids)))) ** 2)
    rf, rs, rl = jax.grad(ref_loss, argnums=(0, 1, 2))(fparams, stages, lparams)
    np.testing.assert_allclose(np.asarray(gf["emb"]), np.asarray(rf["emb"]),
                               rtol=5e-4, atol=5e-6)
    np.testing.assert_allclose(np.asarray(gl["head"]), np.asarray(rl["head"]),
                               rtol=5e-4, atol=5e-6)
    for i in range(N_STAGES):
        np.testing.assert_allclose(np.asarray(gs["w"][i]),
                                   np.asarray(rs[i]["w"]),
                                   rtol=5e-4, atol=5e-6)


def test_pipeline_remat_matches_plain():
    mesh = make_mesh({"pipe": N_STAGES})
    stages, x = _setup(n_micro=4, mb=2, dim=8)
    stacked = stack_stage_params(stages)

    def loss(params, xx, remat):
        return jnp.mean(pipeline_apply(_stage_fn, params, xx, mesh=mesh,
                                       axis="pipe", remat=remat) ** 2)

    g_plain = jax.grad(lambda p: loss(p, x, False))(stacked)
    g_remat = jax.grad(lambda p: loss(p, x, True))(stacked)
    np.testing.assert_allclose(np.asarray(g_remat["w"]),
                               np.asarray(g_plain["w"]),
                               rtol=1e-6, atol=1e-7)


def test_1f1b_stacked_and_tuple_match_sequential():
    """pipeline_1f1b's two parameter layouts (stacked/P(axis)-sharded
    for homogeneous stages, per-stage tuple for heterogeneous) must both
    reproduce the sequential model's gradients exactly."""
    from mxnet_tpu.parallel.pipeline import pipeline_1f1b

    D = 8
    rng = np.random.RandomState(0)
    Ws = [jnp.asarray(rng.randn(D, D).astype(np.float32) * 0.3)
          for _ in range(N_STAGES)]
    We = jnp.asarray(rng.randn(6, D).astype(np.float32) * 0.3)
    Wh = jnp.asarray(rng.randn(D, 4).astype(np.float32) * 0.3)
    X = jnp.asarray(rng.randn(16, 6).astype(np.float32))
    L = jnp.asarray(rng.randn(16, 4).astype(np.float32))
    mesh = make_mesh({"pipe": N_STAGES})
    inputs = {"data": X.reshape(8, 2, 6), "label": L.reshape(8, 2, 4)}
    first = lambda p, raw, k: raw["data"] @ p["we"]
    last = lambda p, y, raw, k: jnp.sum((y @ p["wh"] - raw["label"]) ** 2,
                                        axis=-1)
    fp, lp = {"we": We}, {"wh": Wh}
    sfn = lambda p, x, k: jnp.tanh(x @ p["w"])

    o1, g1 = pipeline_1f1b(sfn, stack_stage_params([{"w": w} for w in Ws]),
                           inputs, mesh=mesh, axis="pipe", first_fn=first,
                           first_params=fp, last_fn=last, last_params=lp)
    o2, g2 = pipeline_1f1b([sfn] * N_STAGES, tuple({"w": w} for w in Ws),
                           inputs, mesh=mesh, axis="pipe", first_fn=first,
                           first_params=fp, last_fn=last, last_params=lp)

    def ref_loss(ps):
        fp_, ws, lp_ = ps
        h = X @ fp_["we"]
        for w in ws:
            h = jnp.tanh(h @ w)
        return jnp.sum(jnp.sum((h @ lp_["wh"] - L) ** 2, axis=-1))

    gr = jax.grad(ref_loss)((fp, tuple(Ws), lp))
    for k in range(N_STAGES):
        np.testing.assert_allclose(np.asarray(g1["stages"]["w"][k]),
                                   np.asarray(gr[1][k]), rtol=5e-3,
                                   atol=5e-4)
        np.testing.assert_allclose(np.asarray(g2["stages"][k]["w"]),
                                   np.asarray(gr[1][k]), rtol=5e-3,
                                   atol=5e-4)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                               rtol=1e-5, atol=1e-5)

"""Async training loop (docs/architecture/async_loop.md): parity, counter,
and lifecycle regression suite.

The acceptance contract: async ``fit()`` (bounded in-flight dispatch +
device-resident metrics + device prefetch) must produce *identical* metric
values and final weights to the synchronous loop, steady state must do
ZERO per-batch host syncs and ZERO recompiles (counter-asserted, same
trick as the serve suite), and ``MXNET_TPU_ASYNC_WINDOW=0`` must exactly
reproduce the pre-async behavior (the kill switch). Host-callback
(CustomOp) programs must stay synchronous — the PR 2 deadlock rule.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import config as cfg
from mxnet_tpu import metric as mmetric
from mxnet_tpu import profiler

BATCH = 8
NSAMP = 64
FEAT = 16
NCLS = 8
EPOCHS = 3


def _mlp_symbol():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=12, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=NCLS, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _stem_symbol():
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, num_filter=8, kernel=(3, 3), pad=(1, 1),
                           name="conv0")
    bn = mx.sym.BatchNorm(c, name="bn0")
    r = mx.sym.Activation(bn, act_type="relu", name="relu0")
    p = mx.sym.Pooling(r, kernel=(2, 2), stride=(2, 2), pool_type="max",
                       name="pool0")
    f = mx.sym.Flatten(p, name="flat")
    fc = mx.sym.FullyConnected(f, num_hidden=NCLS, name="fc1")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def _mlp_data():
    rng = np.random.RandomState(0)
    return (rng.uniform(-1, 1, (NSAMP, FEAT)).astype(np.float32),
            rng.randint(0, NCLS, (NSAMP,)).astype(np.float32))


def _stem_data():
    rng = np.random.RandomState(1)
    return (rng.uniform(-1, 1, (NSAMP, 3, 8, 8)).astype(np.float32),
            rng.randint(0, NCLS, (NSAMP,)).astype(np.float32))


def _seed_init(symbol, shapes):
    """Deterministic init params so independent fit() runs are comparable
    (fit's default initializer draws from the unseeded global RNG)."""
    rng = np.random.RandomState(42)
    args, _, _ = symbol.infer_shape(**shapes)
    init = {}
    for name, shape in zip(symbol.list_arguments(), args):
        if name in shapes:
            continue
        init[name] = mx.nd.array(
            rng.uniform(-0.1, 0.1, shape).astype(np.float32))
    return init


def _fit(symbol, X, Y, window, metric=None, epochs=EPOCHS, dev_metrics=True,
         prefetch=None, lr=0.1):
    """One deterministic fit() under the given knobs; returns (metric
    name/value pairs of the last epoch, {param: np.ndarray}, counter
    deltas)."""
    shapes = {"data": (BATCH,) + X.shape[1:], "softmax_label": (BATCH,)}
    init = _seed_init(symbol, shapes)
    cfg.set("MXNET_TPU_ASYNC_WINDOW", window)
    cfg.set("MXNET_TPU_DEVICE_METRICS", dev_metrics)
    if prefetch is not None:
        cfg.set("MXNET_TPU_DEVICE_PREFETCH", prefetch)
    try:
        it = mx.io.NDArrayIter(X, Y, batch_size=BATCH)
        mod = mx.mod.Module(symbol, context=mx.cpu())
        m = metric if metric is not None else mx.metric.Accuracy()
        with profiler.counter_delta() as d:
            mod.fit(it, eval_metric=m, num_epoch=epochs, optimizer="sgd",
                    optimizer_params={"learning_rate": lr},
                    arg_params={k: v.copy() for k, v in init.items()})
        arg, aux = mod.get_params()
        weights = {k: v.asnumpy().copy() for k, v in arg.items()}
        weights.update({k: v.asnumpy().copy() for k, v in aux.items()})
        return m.get_name_value(), weights, d.all()
    finally:
        for k in ("MXNET_TPU_ASYNC_WINDOW", "MXNET_TPU_DEVICE_METRICS",
                  "MXNET_TPU_DEVICE_PREFETCH"):
            cfg.reset(k)


def _assert_weights_equal(w0, w1):
    assert set(w0) == set(w1)
    for k in w0:
        np.testing.assert_array_equal(w0[k], w1[k], err_msg=k)


# ---------------------------------------------------------------- parity
def test_async_sync_parity_mlp():
    """Bit-identical metric values and final weights, MLP, 3 epochs."""
    X, Y = _mlp_data()
    m0, w0, _ = _fit(_mlp_symbol(), X, Y, window=0)
    m2, w2, _ = _fit(_mlp_symbol(), X, Y, window=2)
    assert m0 == m2, (m0, m2)
    _assert_weights_equal(w0, w2)


def test_async_sync_parity_resnet_stem():
    """Conv/BN/pool stem: parity must also cover aux (BN running stats)."""
    X, Y = _stem_data()
    m0, w0, _ = _fit(_stem_symbol(), X, Y, window=0)
    m2, w2, _ = _fit(_stem_symbol(), X, Y, window=2)
    assert m0 == m2, (m0, m2)
    _assert_weights_equal(w0, w2)


def test_kill_switch_window_zero_is_fully_synchronous():
    """MXNET_TPU_ASYNC_WINDOW=0 exactly reproduces the pre-async loop: no
    async machinery runs at all — no window waits, no prefetch placement,
    no deferred metric sync."""
    X, Y = _mlp_data()
    _, _, counters = _fit(_mlp_symbol(), X, Y, window=0)
    for k in ("loop_window_wait", "loop_window_drain",
              "loop_prefetch_placed", "loop_metric_sync",
              "loop_host_sync", "loop_recompile"):
        assert counters.get(k, 0) == 0, (k, counters)


# --------------------------------------------------------------- counters
def test_steady_state_zero_per_batch_syncs():
    """THE tentpole assertion: async fit does 0 per-batch host syncs and 0
    steady-state recompiles; every batch is device-placed by the prefetch
    stage; the metric syncs once per epoch boundary, not per batch."""
    X, Y = _mlp_data()
    nbatches = (NSAMP // BATCH) * EPOCHS
    _, _, counters = _fit(_mlp_symbol(), X, Y, window=2)
    assert counters.get("loop_host_sync", 0) == 0, counters
    assert counters.get("loop_recompile", 0) == 0, counters
    assert counters.get("loop_prefetch_placed", 0) == nbatches, counters
    # one deferred metric fetch per epoch log boundary (get_name_value)
    assert counters.get("loop_metric_sync", 0) == EPOCHS, counters
    # the sliding window engaged: waits happen once the fifo passes depth
    assert counters.get("loop_window_wait", 0) > 0, counters


def test_custom_metric_falls_back_per_batch():
    """A numpy CustomMetric cannot accumulate on device: the loop must run
    the host path each batch and count the sync (the visible pipeline
    break), while still producing correct values."""
    X, Y = _mlp_data()

    def top1(label, pred):
        return float((pred.argmax(axis=1) == label).mean())

    m = mx.metric.CustomMetric(top1, name="np_top1")
    nv, _, counters = _fit(_mlp_symbol(), X, Y, window=2, metric=m)
    nbatches = (NSAMP // BATCH) * EPOCHS
    assert counters.get("loop_host_sync", 0) == nbatches, counters
    assert counters.get("loop_metric_sync", 0) == 0, counters
    assert 0.0 <= dict(nv)["np_top1"] <= 1.0


def test_device_metrics_knob_disables_device_path():
    X, Y = _mlp_data()
    m0, w0, _ = _fit(_mlp_symbol(), X, Y, window=0)
    m2, w2, counters = _fit(_mlp_symbol(), X, Y, window=2,
                            dev_metrics=False)
    assert counters.get("loop_metric_sync", 0) == 0
    assert counters.get("loop_host_sync", 0) > 0
    assert m0 == m2
    _assert_weights_equal(w0, w2)


def test_async_capable_false_for_host_callback_program():
    """CustomOp (host-callback) programs must stay synchronous with the
    frontend — the PR 2 deadlock rule: the async window never engages and
    every step is a forced sync."""

    @mx.operator.register("async_fit_scale")
    class ScaleProp(mx.operator.CustomOpProp):  # noqa: F841
        def __init__(self):
            super().__init__(need_top_grad=True)

        def create_operator(self, ctx, shapes, dtypes):
            class Scale(mx.operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    self.assign(out_data[0], req[0], in_data[0] * 2.0)

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    self.assign(in_grad[0], req[0], out_grad[0] * 2.0)

            return Scale()

    X, Y = _mlp_data()
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=12, name="fc1")
    sc = mx.sym.Custom(data=fc1, op_type="async_fit_scale", name="sc")
    fc2 = mx.sym.FullyConnected(sc, num_hidden=NCLS, name="fc2")
    sym = mx.sym.SoftmaxOutput(fc2, name="softmax")

    cfg.set("MXNET_TPU_ASYNC_WINDOW", 2)
    try:
        it = mx.io.NDArrayIter(X, Y, batch_size=BATCH)
        mod = mx.mod.Module(sym, context=mx.cpu())
        with profiler.counter_delta() as d:
            mod.fit(it, eval_metric="acc", num_epoch=1, optimizer="sgd",
                    initializer=mx.init.Xavier(),
                    optimizer_params={"learning_rate": 0.1})
        counters = d.all()
    finally:
        cfg.reset("MXNET_TPU_ASYNC_WINDOW")
    assert counters.get("loop_window_wait", 0) == 0, counters
    assert counters.get("loop_prefetch_placed", 0) == 0, counters
    assert counters.get("loop_forced_sync", 0) >= NSAMP // BATCH, counters


# ------------------------------------------------- device metric parity
_DEV_METRIC_CASES = [
    ("acc", lambda: mmetric.Accuracy(),
     lambda rng: (rng.randint(0, 4, (16,)).astype(np.float32),
                  rng.uniform(0, 1, (16, 4)).astype(np.float32))),
    ("topk", lambda: mmetric.TopKAccuracy(top_k=3),
     lambda rng: (rng.randint(0, 6, (16,)).astype(np.float32),
                  rng.uniform(0, 1, (16, 6)).astype(np.float32))),
    ("mse", lambda: mmetric.MSE(),
     lambda rng: (rng.uniform(-1, 1, (16, 4)).astype(np.float32),
                  rng.uniform(-1, 1, (16, 4)).astype(np.float32))),
    ("mae", lambda: mmetric.MAE(),
     lambda rng: (rng.uniform(-1, 1, (16, 4)).astype(np.float32),
                  rng.uniform(-1, 1, (16, 4)).astype(np.float32))),
    ("rmse", lambda: mmetric.RMSE(),
     lambda rng: (rng.uniform(-1, 1, (16, 4)).astype(np.float32),
                  rng.uniform(-1, 1, (16, 4)).astype(np.float32))),
    ("ce", lambda: mmetric.CrossEntropy(),
     lambda rng: (rng.randint(0, 4, (16,)).astype(np.float32),
                  rng.dirichlet(np.ones(4), 16).astype(np.float32))),
    ("ppl", lambda: mmetric.Perplexity(ignore_label=0),
     lambda rng: (rng.randint(0, 4, (16,)).astype(np.float32),
                  rng.dirichlet(np.ones(4), 16).astype(np.float32))),
    ("loss", lambda: mmetric.Loss(),
     lambda rng: (rng.uniform(0, 1, (16,)).astype(np.float32),
                  rng.uniform(0, 2, (16,)).astype(np.float32))),
]


@pytest.mark.parametrize("name,make,gen",
                         _DEV_METRIC_CASES, ids=[c[0] for c in
                                                 _DEV_METRIC_CASES])
def test_update_device_matches_host_update(name, make, gen):
    """Every device-capable metric: N batches through update_device give
    the same get() as the per-batch host path (f32 device accumulate vs
    float64 host accumulate → tolerance, exact for the count metrics)."""
    rng = np.random.RandomState(7)
    batches = [gen(rng) for _ in range(4)]
    host, dev = make(), make()
    assert dev.device_capable()
    for label, pred in batches:
        host.update([mx.nd.array(label)], [mx.nd.array(pred)])
        assert dev.update_device([mx.nd.array(label)], [mx.nd.array(pred)])
    (hn, hv), (dn, dv) = host.get(), dev.get()
    assert hn == dn
    np.testing.assert_allclose(dv, hv, rtol=2e-6, atol=2e-7)
    # get() drained the device accumulator: num_inst now lives on host
    assert dev.num_inst == host.num_inst


def test_update_device_interleaves_with_host_update():
    """Mixing update() and update_device() on one instance must total
    correctly — get() folds the device accumulator into the host sums."""
    rng = np.random.RandomState(3)
    label = rng.randint(0, 4, (8,)).astype(np.float32)
    pred = rng.uniform(0, 1, (8, 4)).astype(np.float32)
    m, ref = mmetric.Accuracy(), mmetric.Accuracy()
    m.update([mx.nd.array(label)], [mx.nd.array(pred)])
    assert m.update_device([mx.nd.array(label)], [mx.nd.array(pred)])
    for _ in range(2):
        ref.update([mx.nd.array(label)], [mx.nd.array(pred)])
    assert m.get() == ref.get()


def test_composite_device_capability():
    """All-capable composite accumulates on device as a unit; a composite
    with one host-only child falls back atomically (no child sees a batch
    twice)."""
    both = mmetric.CompositeEvalMetric(
        [mmetric.Accuracy(), mmetric.TopKAccuracy(top_k=2)])
    assert both.device_capable()
    mixed = mmetric.CompositeEvalMetric(
        [mmetric.Accuracy(), mmetric.F1()])
    assert not mixed.device_capable()
    assert not mixed.update_device([mx.nd.zeros((4,))],
                                   [mx.nd.zeros((4, 2))])
    assert mixed.metrics[0].num_inst == 0  # nothing committed on refusal

    rng = np.random.RandomState(5)
    label = rng.randint(0, 3, (12,)).astype(np.float32)
    pred = rng.uniform(0, 1, (12, 3)).astype(np.float32)
    ref = mmetric.CompositeEvalMetric(
        [mmetric.Accuracy(), mmetric.TopKAccuracy(top_k=2)])
    ref.update([mx.nd.array(label)], [mx.nd.array(pred)])
    assert both.update_device([mx.nd.array(label)], [mx.nd.array(pred)])
    assert both.get_name_value() == ref.get_name_value()


def test_reset_discards_device_accumulator():
    rng = np.random.RandomState(9)
    label = rng.randint(0, 4, (8,)).astype(np.float32)
    pred = rng.uniform(0, 1, (8, 4)).astype(np.float32)
    m = mmetric.Accuracy()
    assert m.update_device([mx.nd.array(label)], [mx.nd.array(pred)])
    m.reset()
    assert m.num_inst == 0
    name, val = m.get()
    assert np.isnan(val)


# ----------------------------------------- vectorized host-path parity
def _topk_loop_reference(label, pred, top_k):
    """The pre-vectorization per-column loop (reference metric.py:404)."""
    order = np.argsort(pred.astype(np.float32), axis=1)
    label = label.astype(np.int32)
    num_samples, num_classes = order.shape
    k = min(num_classes, top_k)
    hits = 0
    for j in range(k):
        hits += (order[:, num_classes - 1 - j].flatten()
                 == label.flatten()).sum()
    return hits, num_samples


def _f1_loop_reference(label, pred):
    """Per-sample tp/fp/fn counting (reference metric.py:478)."""
    pred_label = np.argmax(pred, axis=1)
    label = label.astype(np.int32).flatten()
    tp = fp = fn = 0
    for y_hat, y in zip(pred_label, label):
        if y_hat == 1 and y == 1:
            tp += 1
        elif y_hat == 1 and y == 0:
            fp += 1
        elif y_hat == 0 and y == 1:
            fn += 1
    precision = tp / (tp + fp) if tp + fp > 0 else 0.0
    recall = tp / (tp + fn) if tp + fn > 0 else 0.0
    return 2 * precision * recall / (precision + recall) \
        if precision + recall > 0 else 0.0


def _pearson_loop_reference(label, pred):
    """Explicit sum-form Pearson r over samples (reference metric.py:923)."""
    x, y = pred.ravel(), label.ravel()
    n = len(x)
    mx_, my = sum(x) / n, sum(y) / n
    num = sum((a - mx_) * (b - my) for a, b in zip(x, y))
    den = (sum((a - mx_) ** 2 for a in x)
           * sum((b - my) ** 2 for b in y)) ** 0.5
    return num / den


def test_topk_vectorized_matches_loop():
    rng = np.random.RandomState(11)
    for top_k in (2, 3, 5):
        label = rng.randint(0, 5, (32,)).astype(np.float32)
        pred = rng.uniform(0, 1, (32, 5)).astype(np.float32)
        m = mmetric.TopKAccuracy(top_k=top_k)
        m.update([mx.nd.array(label)], [mx.nd.array(pred)])
        hits, n = _topk_loop_reference(label, pred, top_k)
        assert m.sum_metric == hits and m.num_inst == n


def test_f1_vectorized_matches_loop():
    rng = np.random.RandomState(13)
    for _ in range(3):
        label = rng.randint(0, 2, (32,)).astype(np.float32)
        pred = rng.uniform(0, 1, (32, 2)).astype(np.float32)
        m = mmetric.F1()
        m.update([mx.nd.array(label)], [mx.nd.array(pred)])
        np.testing.assert_allclose(m.get()[1],
                                   _f1_loop_reference(label, pred),
                                   rtol=1e-12)


def test_pearson_vectorized_matches_loop():
    rng = np.random.RandomState(17)
    label = rng.uniform(-1, 1, (32, 3)).astype(np.float32)
    pred = (0.5 * label + 0.1 * rng.uniform(-1, 1, (32, 3))) \
        .astype(np.float32)
    m = mmetric.PearsonCorrelation()
    m.update([mx.nd.array(label)], [mx.nd.array(pred)])
    np.testing.assert_allclose(m.get()[1],
                               _pearson_loop_reference(label, pred),
                               rtol=1e-6)


# --------------------------------------------------- PrefetchingIter
def test_user_prefetching_iter_not_double_wrapped():
    """fit() must use an iterator the user already wrapped as-is instead
    of stacking a second PrefetchingIter (extra worker thread + queue hop
    just for the placement stage): no device-prefetch stage is attached
    (batches are placed in _load_batch), and training parity holds."""
    X, Y = _mlp_data()
    ref_m, ref_w, _ = _fit(_mlp_symbol(), X, Y, window=2)
    shapes = {"data": (BATCH, FEAT), "softmax_label": (BATCH,)}
    init = _seed_init(_mlp_symbol(), shapes)
    cfg.set("MXNET_TPU_ASYNC_WINDOW", 2)
    try:
        it = mx.io.PrefetchingIter(
            mx.io.NDArrayIter(X, Y, batch_size=BATCH))
        mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
        m = mx.metric.Accuracy()
        with profiler.counter_delta() as d:
            mod.fit(it, eval_metric=m, num_epoch=EPOCHS, optimizer="sgd",
                    optimizer_params={"learning_rate": 0.1},
                    arg_params={k: v.copy() for k, v in init.items()})
        assert it._device_placer is None
        # a stacked wrapper would run the device stage: placed > 0
        assert d.all().get("loop_prefetch_placed", 0) == 0, d.all()
        assert m.get_name_value() == ref_m, (m.get_name_value(), ref_m)
        arg, aux = mod.get_params()
        weights = {k: v.asnumpy().copy() for k, v in arg.items()}
        weights.update({k: v.asnumpy().copy() for k, v in aux.items()})
        _assert_weights_equal(ref_w, weights)
        assert it.close()
    finally:
        cfg.reset("MXNET_TPU_ASYNC_WINDOW")


def test_prefetching_iter_close_joins_workers():
    data = np.arange(24, dtype=np.float32).reshape(12, 2)
    base = mx.io.NDArrayIter(data, np.arange(12), batch_size=4)
    before = threading.active_count()
    it = mx.io.PrefetchingIter(base)
    assert threading.active_count() > before
    it.next()
    it.close()
    deadline = time.monotonic() + 5.0
    while any(t.is_alive() for t in it._threads):
        assert time.monotonic() < deadline, "prefetch worker leaked"
        time.sleep(0.01)
    it.close()  # idempotent


def test_prefetching_iter_reset_race():
    """Regression for the reset race: a worker holding a pre-reset batch
    (blocked on a full queue) must not leak it into the next epoch — every
    post-reset epoch starts at batch 0 and yields exactly n batches."""
    data = np.arange(40, dtype=np.float32).reshape(20, 2)
    base = mx.io.NDArrayIter(data, np.arange(20), batch_size=4)
    it = mx.io.PrefetchingIter(base, prefetch_depth=1)
    try:
        for trial in range(6):
            # pull a partial epoch so workers are mid-stream, then reset
            # at a varying depth to scan interleavings
            for _ in range(trial % 4):
                it.next()
            time.sleep(0.01)   # let the worker block on the full queue
            it.reset()
            batches = []
            try:
                while True:
                    batches.append(it.next())
            except StopIteration:
                pass
            assert len(batches) == 5, "epoch leaked/lost batches"
            np.testing.assert_array_equal(batches[0].data[0].asnumpy(),
                                          data[:4])
            it.reset()
    finally:
        it.close()


def test_prefetching_iter_device_stage():
    """The device-prefetch stage runs the placer in the worker thread and
    hands the consumer already-placed batches; placement failures re-raise
    in the consumer."""
    data = np.arange(24, dtype=np.float32).reshape(12, 2)
    placed_in = []

    def placer(batch):
        placed_in.append(threading.current_thread().name)
        batch._mx_placed = {"data": batch.data[0]}
        return batch

    base = mx.io.NDArrayIter(data, np.arange(12), batch_size=4)
    it = mx.io.PrefetchingIter(base, device_placer=placer)
    try:
        batches = []
        try:
            while True:
                batches.append(it.next())
        except StopIteration:
            pass
        assert len(batches) == 3
        assert all(hasattr(b, "_mx_placed") for b in batches)
        main = threading.current_thread().name
        assert all(name != main for name in placed_in), \
            "placement ran on the consumer thread (critical path)"
    finally:
        it.close()

    def bad_placer(batch):
        raise RuntimeError("H2D exploded")

    base2 = mx.io.NDArrayIter(data, np.arange(12), batch_size=4)
    it2 = mx.io.PrefetchingIter(base2, device_placer=bad_placer)
    try:
        with pytest.raises(RuntimeError, match="H2D exploded"):
            for _ in range(4):
                it2.next()
    finally:
        it2.close()


def test_prefetching_iter_inner_error_reraises():
    """A raising inner iterator must surface in the consumer, not kill the
    worker silently and hang next() on an empty queue forever."""
    class _Exploding(mx.io.DataIter):
        def __init__(self):
            super().__init__()
            self._inner = mx.io.NDArrayIter(
                np.zeros((12, 2), np.float32), np.arange(12), batch_size=4)
            self.provide_data = self._inner.provide_data
            self.provide_label = self._inner.provide_label
            self.batch_size = 4
            self._n = 0

        def next(self):
            self._n += 1
            if self._n > 1:
                raise IOError("corrupt record")
            return self._inner.next()

        def reset(self):
            self._n = 0
            self._inner.reset()

    for placer in (None, lambda b: b):
        it = mx.io.PrefetchingIter(_Exploding(), device_placer=placer)
        try:
            it.next()
            with pytest.raises(IOError, match="corrupt record"):
                it.next()
        finally:
            it.close()


def test_fit_closes_its_prefetcher():
    """fit() must tear down the PrefetchingIter it wraps around the user's
    iterator (satellite: no daemon-thread leak across fits)."""
    X, Y = _mlp_data()
    before = threading.active_count()
    _fit(_mlp_symbol(), X, Y, window=2, epochs=1)
    deadline = time.monotonic() + 5.0
    while threading.active_count() > before:
        assert time.monotonic() < deadline, "fit leaked prefetch threads"
        time.sleep(0.01)


# ------------------------------------------------------------ slow tier
@pytest.mark.slow
def test_window_depth_sweep_parity():
    """Every window depth (including deeper-than-epoch) reproduces the
    synchronous result exactly — the sliding window is flow control, not
    numerics."""
    X, Y = _mlp_data()
    m0, w0, _ = _fit(_mlp_symbol(), X, Y, window=0)
    for depth in (1, 2, 4, 16):
        m, w, _ = _fit(_mlp_symbol(), X, Y, window=depth)
        assert m == m0, (depth, m, m0)
        _assert_weights_equal(w0, w)


@pytest.mark.slow
def test_donation_stress_many_epochs():
    """Donation safety under a deep window across many epochs: params swap
    through arg_dict every step, so no buffer is ever re-donated while an
    in-flight step still references it (jax would raise on a donated
    buffer reuse — surviving 10 epochs IS the assertion), and training
    still matches the synchronous loop bit-for-bit."""
    X, Y = _stem_data()
    m0, w0, _ = _fit(_stem_symbol(), X, Y, window=0, epochs=10)
    m4, w4, counters = _fit(_stem_symbol(), X, Y, window=4, epochs=10)
    assert m0 == m4
    _assert_weights_equal(w0, w4)
    assert counters.get("loop_host_sync", 0) == 0
    assert counters.get("loop_recompile", 0) == 0

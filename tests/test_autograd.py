"""Autograd tests (modeled on reference tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag


def test_simple_grad():
    x = mx.nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with ag.record():
        y = x * x + 2 * x
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy() + 2, rtol=1e-5)


def test_chain_rule_through_ops():
    x = mx.nd.array(np.random.rand(3, 4).astype("f"))
    x.attach_grad()
    with ag.record():
        y = mx.nd.exp(mx.nd.sum(x * x))
    y.backward()
    xe = x.asnumpy()
    expected = 2 * xe * np.exp((xe * xe).sum())
    np.testing.assert_allclose(x.grad.asnumpy(), expected, rtol=1e-3)


def test_head_grads():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = 3 * x
    y.backward(mx.nd.array([10.0, 100.0]))
    np.testing.assert_allclose(x.grad.asnumpy(), [30.0, 300.0], rtol=1e-5)


def test_grad_req_add_and_null():
    x = mx.nd.array([1.0, 2.0])
    gx = mx.nd.zeros((2,))
    ag.mark_variables([x], [gx], grad_reqs="add")
    with ag.record():
        y = x * 2
    y.backward()
    with ag.record():
        y = x * 3
    y.backward()
    np.testing.assert_allclose(gx.asnumpy(), [5.0, 5.0], rtol=1e-5)

    z = mx.nd.array([1.0])
    gz = mx.nd.zeros((1,))
    ag.mark_variables([z], [gz], grad_reqs="null")
    with ag.record():
        w = z * 5
    w.backward()
    np.testing.assert_allclose(gz.asnumpy(), [0.0])


def test_multiple_variables():
    a = mx.nd.array([2.0])
    b = mx.nd.array([3.0])
    a.attach_grad()
    b.attach_grad()
    with ag.record():
        c = a * b + a
    c.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), [4.0], rtol=1e-5)  # b + 1
    np.testing.assert_allclose(b.grad.asnumpy(), [2.0], rtol=1e-5)  # a


def test_training_mode_flags():
    assert not ag.is_training()
    assert not ag.is_recording()
    with ag.record():
        assert ag.is_training()
        assert ag.is_recording()
        with ag.pause():
            assert not ag.is_recording()
            assert not ag.is_training()
    with ag.record(train_mode=False):
        assert ag.is_recording()
        assert not ag.is_training()
    with ag.train_mode():
        assert ag.is_training()


def test_retain_graph():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * x
    y.backward(retain_graph=True)
    g1 = x.grad.asnumpy().copy()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), g1)


def test_dropout_replay_consistency():
    # The vjp replay must reuse the recorded dropout mask (captured rng key)
    x = mx.nd.array(np.ones((50, 50), dtype="f"))
    x.attach_grad()
    with ag.record():
        y = mx.nd.Dropout(x, p=0.5)
    y.backward()
    g = x.grad.asnumpy()
    ynp = y.asnumpy()
    # gradient nonzero exactly where the forward kept the unit
    np.testing.assert_allclose((g != 0), (ynp != 0))


def test_softmax_output_backward_semantics():
    # SoftmaxOutput backward = (p - onehot) regardless of head grads
    x = mx.nd.array(np.random.randn(4, 5).astype("f"))
    label = mx.nd.array([0, 1, 2, 3])
    x.attach_grad()
    with ag.record():
        out = mx.nd.SoftmaxOutput(x, label)
    out.backward()
    p = out.asnumpy()
    oh = np.zeros((4, 5), dtype="f")
    oh[np.arange(4), [0, 1, 2, 3]] = 1
    np.testing.assert_allclose(x.grad.asnumpy(), p - oh, rtol=1e-4, atol=1e-6)


def test_stochastic_activation_pruning_backward():
    # reference backward: d_act = grad * mask, d_prob = 0
    # (stochastic_activation_pruning-inl.h:139-178)
    act = mx.nd.array(np.random.rand(4, 20).astype("f") + 1)
    prob = mx.nd.array(np.full((4, 20), 0.05, dtype="f"))
    act.attach_grad()
    prob.attach_grad()
    with ag.record():
        out = mx.nd.stochastic_activation_pruning(act, prob, frac=0.5)
    out.backward()
    mask = out.asnumpy() / act.asnumpy()  # recovers mask since out = act*mask
    np.testing.assert_allclose(act.grad.asnumpy(), mask, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(prob.grad.asnumpy(), 0.0, atol=1e-6)


def test_attach_grad_detach():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = (x * 2).detach()  # detach cuts the graph
        z = x * 3
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [3.0, 3.0], rtol=1e-5)


def test_positional_const_args_replay():
    """Non-NDArray positionals (e.g. a positional reshape shape) must be
    replayed as constants in backward — they are not tape inputs.
    Regression: they were dropped, so backward re-ran the op with default
    attrs (reshape got shape=None and crashed)."""
    import mxnet_tpu as mx
    x = mx.nd.array(np.arange(12, dtype=np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.reshape(x, (3, 4))      # shape passed positionally
        loss = (y * y).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(),
                               2 * np.arange(12, dtype=np.float32))

"""Storage layer (mx.storage): memory spaces, host staging, stats.

Reference parity: include/mxnet/storage.h + PinnedMemoryStorage
(SURVEY.md §2.2) — on TPU the allocator is PJRT's; what remains is the
memory-space surface, which these tests exercise on the CPU backend
(same kinds: device / pinned_host / unpinned_host).
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import storage


def test_memory_kinds_listed():
    kinds = storage.memory_kinds(mx.cpu())
    assert storage.DEVICE in kinds
    assert storage.PINNED_HOST in kinds


def test_roundtrip_through_pinned_host():
    x = mx.nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert storage.memory_kind_of(x) == storage.DEVICE
    h = storage.as_in_memory(x, storage.PINNED_HOST)
    assert storage.memory_kind_of(h) == storage.PINNED_HOST
    back = storage.as_in_memory(h, storage.DEVICE)
    assert storage.memory_kind_of(back) == storage.DEVICE
    np.testing.assert_array_equal(back.asnumpy(), x.asnumpy())


def test_offload_restore_dict():
    params = {"w": mx.nd.array(np.ones((4, 4), np.float32)),
              "b": mx.nd.array(np.zeros((4,), np.float32))}
    off = storage.offload(params)
    assert all(storage.memory_kind_of(v) == storage.PINNED_HOST
               for v in off.values())
    # offloaded arrays are still usable as values
    np.testing.assert_array_equal(off["w"].asnumpy(), params["w"].asnumpy())
    on = storage.restore(off)
    assert all(storage.memory_kind_of(v) == storage.DEVICE
               for v in on.values())


def test_memory_stats_shape():
    stats = storage.memory_stats(mx.cpu())
    assert isinstance(stats, dict)   # CPU backend may expose none

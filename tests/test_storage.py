"""Storage layer (mx.storage): memory spaces, host staging, stats.

Reference parity: include/mxnet/storage.h + PinnedMemoryStorage
(SURVEY.md §2.2) — on TPU the allocator is PJRT's; what remains is the
memory-space surface. The kinds a backend advertises drift across
jax/PJRT versions (this build's CPU backend exposes only
``unpinned_host``), so the exact-placement tests run behind the
``supports_memory_kind`` capability probe and the value-roundtrip
behavior is asserted unconditionally.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import storage

_HAS_PINNED = storage.supports_memory_kind(storage.PINNED_HOST, mx.cpu())
pinned_only = pytest.mark.skipif(
    not _HAS_PINNED, reason="backend does not advertise a pinned_host "
    "memory space (capability-gated; CPU PJRT on this jax version "
    "exposes only unpinned_host)")


def test_memory_kinds_listed():
    kinds = storage.memory_kinds(mx.cpu())
    assert isinstance(kinds, list)
    # whatever the backend calls its default space, the portable DEVICE
    # capability must hold — even on runtimes predating the memories API
    assert storage.supports_memory_kind(storage.DEVICE, mx.cpu())
    if not kinds:
        pytest.skip("runtime predates the memories API (empty kinds is "
                    "the documented graceful path)")
    assert all(isinstance(k, str) for k in kinds)
    assert storage.default_memory_kind(mx.cpu()) in kinds


@pinned_only
def test_roundtrip_through_pinned_host():
    x = mx.nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert storage.memory_kind_of(x) == storage.DEVICE
    h = storage.as_in_memory(x, storage.PINNED_HOST)
    assert storage.memory_kind_of(h) == storage.PINNED_HOST
    back = storage.as_in_memory(h, storage.DEVICE)
    assert storage.memory_kind_of(back) == storage.DEVICE
    np.testing.assert_array_equal(back.asnumpy(), x.asnumpy())


@pinned_only
def test_offload_restore_dict():
    params = {"w": mx.nd.array(np.ones((4, 4), np.float32)),
              "b": mx.nd.array(np.zeros((4,), np.float32))}
    off = storage.offload(params)
    assert all(storage.memory_kind_of(v) == storage.PINNED_HOST
               for v in off.values())
    # offloaded arrays are still usable as values
    np.testing.assert_array_equal(off["w"].asnumpy(), params["w"].asnumpy())
    on = storage.restore(off)
    assert all(storage.memory_kind_of(v) == storage.DEVICE
               for v in on.values())


def test_offload_restore_values_survive_fallback():
    """Without a pinned pool the staging falls back to the nearest host
    space — placement differs but offload/restore must stay a correct
    value roundtrip on EVERY backend."""
    params = {"w": mx.nd.array(np.arange(16, dtype=np.float32)
                               .reshape(4, 4)),
              "b": mx.nd.array(np.zeros((4,), np.float32))}
    off = storage.offload(params)
    on = storage.restore(off)
    for k in params:
        np.testing.assert_array_equal(on[k].asnumpy(), params[k].asnumpy())
        assert storage.memory_kind_of(on[k]) == storage.DEVICE


def test_default_kind_reports_as_device():
    x = mx.nd.array(np.ones((2, 2), np.float32))
    assert storage.memory_kind_of(x) == storage.DEVICE


def test_memory_stats_shape():
    stats = storage.memory_stats(mx.cpu())
    assert isinstance(stats, dict)   # CPU backend may expose none

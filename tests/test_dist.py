"""Fake-cluster distributed tests (reference:
tests/nightly/dist_sync_kvstore.py launched by tools/launch.py -n N
--launcher local).

Spawns real worker processes through the launcher — the same code path a
user runs on a multi-host cluster — and checks the dist_sync contract:
identical replicas after rank-dependent training.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dist_kvstore_requires_cluster():
    """No launcher env, single process: the silent-stub path must be gone."""
    assert "DMLC_NUM_WORKER" not in os.environ
    with pytest.raises(mx.base.MXNetError, match="launch"):
        mx.kv.create("dist_sync")


@pytest.mark.slow
def test_dist_sync_fake_cluster(tmp_path):
    # reference nightly runs 7 workers (tests/nightly/dist_sync_kvstore.py);
    # 4 keeps the 1-core CI rig honest while exercising n > 2 reduction
    n = 4
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # workers must not inherit the parent's 8-device virtual rig: one CPU
    # device per process keeps the cross-process mesh unambiguous
    env["XLA_FLAGS"] = ""
    cmd = [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
           "-n", str(n), "--launcher", "local",
           sys.executable, os.path.join(ROOT, "tests", "_dist_worker.py"),
           str(tmp_path)]
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=600)
    assert proc.returncode == 0, \
        "launcher failed:\n%s\n%s" % (proc.stdout[-4000:], proc.stderr[-4000:])

    ranks = [np.load(tmp_path / ("params_rank%d.npz" % r)) for r in range(n)]
    for key in ranks[0].files:
        for r in range(1, n):
            np.testing.assert_array_equal(
                ranks[0][key], ranks[r][key],
                err_msg="weight %r diverged between ranks" % key)


@pytest.mark.slow
def test_dist_dead_worker_detected(tmp_path):
    """Kill-a-worker: rank N-1 os._exit()s mid-run; survivors must see
    get_num_dead_node() > 0 via heartbeat staleness (VERDICT r3 weak #2;
    reference: ps-lite heartbeats, kvstore.h:287)."""
    n = 3
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    cmd = [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
           "-n", str(n), "--launcher", "local",
           sys.executable, os.path.join(ROOT, "tests",
                                        "_dist_dead_worker.py"),
           str(tmp_path)]
    # one retry: the injected death races jax's own coordination-service
    # liveness tracking, which (rarely) aborts a survivor before it can
    # report success — an artifact of killing tasks under the shared
    # coordinator, not of the heartbeat detector under test
    for attempt in range(2):
        for r in range(n - 1):
            marker = tmp_path / ("dead_seen_rank%d" % r)
            if marker.exists():
                marker.unlink()
        proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                              timeout=600)
        if proc.returncode == 0:
            break
    assert proc.returncode == 0, \
        "launcher failed:\n%s\n%s" % (proc.stdout[-4000:],
                                        proc.stderr[-4000:])
    for r in range(n - 1):
        marker = tmp_path / ("dead_seen_rank%d" % r)
        assert marker.exists(), "rank %d never observed the dead node" % r
        assert int(marker.read_text()) >= 1

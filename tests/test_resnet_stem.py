"""Space-to-depth stem transform (models/resnet.py _stem_s2d): the
TPU ResNet stem restructuring must be mathematically identical to the
reference's 7x7/2 conv, on the same (F, 3, 7, 7) parameter."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.models import resnet


def test_s2d_stem_matches_7x7_stem():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 64, 64).astype(np.float32)
    w = (rng.randn(8, 3, 7, 7) * 0.05).astype(np.float32)

    data = mx.sym.Variable("data")
    direct = mx.sym.Convolution(data=data, num_filter=8, kernel=(7, 7),
                                stride=(2, 2), pad=(3, 3), no_bias=True,
                                name="conv0")
    s2d = resnet._stem_s2d(data, 8, 64)
    feed = {"data": mx.nd.array(x), "conv0_weight": mx.nd.array(w)}
    a = direct.bind(mx.cpu(0), dict(feed)).forward()[0].asnumpy()
    b = s2d.bind(mx.cpu(0), dict(feed)).forward()[0].asnumpy()
    assert a.shape == b.shape == (2, 8, 32, 32)
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_s2d_resnet_trains_and_shares_checkpoint_shape():
    sym = resnet.get_symbol(num_classes=10, num_layers=18,
                            image_shape="3,64,64", stem="s2d")
    shapes, _, _ = sym.infer_shape(data=(2, 3, 64, 64), softmax_label=(2,))
    by_name = dict(zip(sym.list_arguments(), shapes))
    # the stem parameter keeps the reference's 7x7 shape
    assert by_name["conv0_weight"] == (64, 3, 7, 7)

    mod = mx.mod.Module(sym, context=mx.cpu(0))
    mod.bind(data_shapes=[("data", (2, 3, 64, 64))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    rng = np.random.RandomState(1)
    db = mx.io.DataBatch(
        data=[mx.nd.array(rng.randn(2, 3, 64, 64).astype(np.float32))],
        label=[mx.nd.array(np.array([1.0, 3.0], np.float32))])
    mod.forward_backward(db)
    mod.update()
    out = mod.get_outputs()[0].asnumpy()
    assert out.shape == (2, 10) and np.isfinite(out).all()

"""Spatial + detection op family vs numpy oracles (reference tests:
tests/python/unittest/test_operator.py test_roipooling/test_bilinear_sampler
etc., tests/python/unittest/test_contrib_operator.py multibox tests)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def test_roi_pooling_oracle():
    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 8, 8).astype(np.float32)
    rois = np.array([[0, 0, 0, 7, 7],
                     [1, 2, 2, 6, 6],
                     [0, 4, 4, 4, 4]], np.float32)   # single-pixel roi
    out = mx.nd.ROIPooling(mx.nd.array(x), mx.nd.array(rois),
                           pooled_size=(2, 2), spatial_scale=1.0).asnumpy()
    assert out.shape == (3, 3, 2, 2)
    # full-image roi, 2x2 pooling = max over quadrants
    expect = x[0].reshape(3, 2, 4, 2, 4).max(axis=(2, 4))
    np.testing.assert_allclose(out[0], expect, rtol=1e-6)
    # single-pixel roi: every bin containing the pixel reports it
    np.testing.assert_allclose(out[2, :, 1, 1], x[0, :, 4, 4], rtol=1e-6)


def test_roi_pooling_grad_flows():
    x = mx.nd.array(np.arange(32, dtype=np.float32).reshape(1, 2, 4, 4))
    x.attach_grad()
    rois = mx.nd.array(np.array([[0, 0, 0, 3, 3]], np.float32))
    with mx.autograd.record():
        y = mx.nd.ROIPooling(x, rois, pooled_size=(1, 1), spatial_scale=1.0)
        s = mx.nd.sum(y)
    s.backward()
    g = x.grad.asnumpy()
    assert g.sum() == 2.0           # one max location per channel
    assert g[0, 0, 3, 3] == 1.0 and g[0, 1, 3, 3] == 1.0


def test_bilinear_sampler_identity_and_shift():
    rng = np.random.RandomState(1)
    x = rng.rand(1, 2, 5, 5).astype(np.float32)
    ys, xs = np.meshgrid(np.linspace(-1, 1, 5), np.linspace(-1, 1, 5),
                         indexing="ij")
    grid = np.stack([xs, ys])[None].astype(np.float32)
    out = mx.nd.BilinearSampler(mx.nd.array(x), mx.nd.array(grid)).asnumpy()
    np.testing.assert_allclose(out, x, rtol=1e-5, atol=1e-6)
    # everything sampled far outside -> zeros
    far = np.full_like(grid, 5.0)
    out = mx.nd.BilinearSampler(mx.nd.array(x), mx.nd.array(far)).asnumpy()
    np.testing.assert_allclose(out, 0.0)


def test_spatial_transformer_identity():
    rng = np.random.RandomState(2)
    x = rng.rand(2, 1, 6, 6).astype(np.float32)
    theta = np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32), (2, 1))
    out = mx.nd.SpatialTransformer(mx.nd.array(x), mx.nd.array(theta),
                                   target_shape=(6, 6)).asnumpy()
    np.testing.assert_allclose(out, x, rtol=1e-5, atol=1e-6)


def test_grid_generator_warp_zero_flow_is_identity():
    flow = np.zeros((1, 2, 4, 4), np.float32)
    g = mx.nd.GridGenerator(mx.nd.array(flow),
                            transform_type="warp").asnumpy()
    assert g.min() >= -1.0 - 1e-6 and g.max() <= 1.0 + 1e-6
    x = np.random.RandomState(3).rand(1, 3, 4, 4).astype(np.float32)
    out = mx.nd.BilinearSampler(mx.nd.array(x), mx.nd.array(g)).asnumpy()
    np.testing.assert_allclose(out, x, rtol=1e-5, atol=1e-6)


def test_multibox_prior_reference_enumeration():
    data = mx.nd.zeros((1, 3, 2, 2))
    out = mx.nd.MultiBoxPrior(data, sizes=(0.5, 0.25), ratios=(1.0, 2.0),
                              steps=(-1.0, -1.0)).asnumpy()
    # A = sizes + ratios - 1 = 3 anchors per cell
    assert out.shape == (1, 2 * 2 * 3, 4)
    # first cell center (0.25, 0.25); first anchor: size .5 ratio 1
    np.testing.assert_allclose(out[0, 0], [0.0, 0.0, 0.5, 0.5], atol=1e-6)
    np.testing.assert_allclose(out[0, 1], [0.125, 0.125, 0.375, 0.375],
                               atol=1e-6)
    # ratio-2 anchor of size .5? no: extra ratios use sizes[0]
    w = 0.5 * np.sqrt(2.0) / 2
    h = 0.5 / np.sqrt(2.0) / 2
    np.testing.assert_allclose(out[0, 2],
                               [0.25 - w, 0.25 - h, 0.25 + w, 0.25 + h],
                               atol=1e-6)


def test_multibox_target_matching_and_encoding():
    anchors = np.array([[[0.0, 0.0, 0.5, 0.5],
                         [0.5, 0.5, 1.0, 1.0],
                         [0.0, 0.5, 0.5, 1.0]]], np.float32)
    # one gt overlapping anchor 0 well, class 2
    label = np.array([[[2, 0.05, 0.05, 0.45, 0.45],
                       [-1, 0, 0, 0, 0]]], np.float32)
    cls_pred = np.zeros((1, 3, 3), np.float32)
    bt, bm, ct = mx.nd.MultiBoxTarget(mx.nd.array(anchors),
                                      mx.nd.array(label),
                                      mx.nd.array(cls_pred))
    ct = ct.asnumpy()
    bm = bm.asnumpy().reshape(1, 3, 4)
    bt = bt.asnumpy().reshape(1, 3, 4)
    assert ct[0, 0] == 3.0          # class 2 -> target 3 (bg is 0)
    assert ct[0, 1] == 0.0 and ct[0, 2] == 0.0
    assert bm[0, 0].all() and not bm[0, 1].any()
    # encoding: gt center == anchor center shifted by -0.0 -> dx = 0
    aw = 0.5
    gx, ax = 0.25, 0.25
    np.testing.assert_allclose(bt[0, 0, 0], (gx - ax) / aw / 0.1, atol=1e-5)
    np.testing.assert_allclose(bt[0, 0, 2],
                               np.log(0.4 / 0.5) / 0.2, atol=1e-5)


def test_multibox_target_two_gts_share_best_anchor():
    # both gts' IoU-argmax is anchor 0; greedy bipartite must give the
    # loser a distinct forced anchor instead of dropping it
    anchors = np.array([[[0.0, 0.0, 1.0, 1.0],
                         [0.0, 0.0, 0.4, 0.4],
                         [2.0, 2.0, 3.0, 3.0]]], np.float32)
    label = np.array([[[1, 0.0, 0.0, 0.9, 1.0],
                       [2, 0.0, 0.0, 1.0, 0.9]]], np.float32)
    cls_pred = np.zeros((1, 4, 3), np.float32)
    _, _, ct = mx.nd.MultiBoxTarget(mx.nd.array(anchors),
                                    mx.nd.array(label),
                                    mx.nd.array(cls_pred))
    ct = ct.asnumpy()[0]
    assert sorted(c for c in ct if c > 0) == [2.0, 3.0]


def test_multibox_detection_nonzero_background_id():
    # 3 classes with background at id 2: real classes keep ids 0 and 1
    anchors = np.array([[[0.1, 0.1, 0.3, 0.3],
                         [0.6, 0.6, 0.9, 0.9]]], np.float32)
    cls_prob = np.array([[[0.9, 0.1],
                          [0.05, 0.8],
                          [0.05, 0.1]]], np.float32)
    loc = np.zeros((1, 8), np.float32)
    out = mx.nd.MultiBoxDetection(mx.nd.array(cls_prob), mx.nd.array(loc),
                                  mx.nd.array(anchors), background_id=2,
                                  nms_threshold=0.5).asnumpy()
    kept = out[0][out[0, :, 0] >= 0]
    assert sorted(kept[:, 0].tolist()) == [0.0, 1.0]


def test_multibox_detection_decode_and_nms():
    anchors = np.array([[[0.1, 0.1, 0.3, 0.3],
                         [0.11, 0.11, 0.31, 0.31],
                         [0.6, 0.6, 0.9, 0.9]]], np.float32)
    # class 1 strong on anchors 0,1 (overlapping); class 2 on anchor 2
    cls_prob = np.array([[[0.1, 0.2, 0.1],
                          [0.8, 0.7, 0.05],
                          [0.1, 0.1, 0.85]]], np.float32)
    loc = np.zeros((1, 12), np.float32)
    out = mx.nd.MultiBoxDetection(mx.nd.array(cls_prob), mx.nd.array(loc),
                                  mx.nd.array(anchors),
                                  nms_threshold=0.5).asnumpy()
    assert out.shape == (1, 3, 6)
    kept = out[0][out[0, :, 0] >= 0]
    # anchor 1 suppressed by anchor 0 (same class, IoU > .5)
    assert len(kept) == 2
    classes = sorted(kept[:, 0].tolist())
    assert classes == [0.0, 1.0]    # class ids shift down by 1 (bg removed)
    cls0 = kept[kept[:, 0] == 0.0][0]
    assert abs(cls0[1] - 0.8) < 1e-5      # anchor 0 won over anchor 1
    np.testing.assert_allclose(cls0[2:], [0.1, 0.1, 0.3, 0.3], atol=1e-5)


def test_multibox_detection_nms_topk_drops_tail():
    # nms_topk caps the number of surviving detections, not just the
    # suppressor set (reference multibox_detection.cc)
    anchors = np.array([[[0.0, 0.0, 0.2, 0.2],
                         [0.4, 0.4, 0.6, 0.6],
                         [0.8, 0.8, 1.0, 1.0]]], np.float32)
    cls_prob = np.array([[[0.1, 0.2, 0.3],
                          [0.9, 0.8, 0.7]]], np.float32)
    loc = np.zeros((1, 12), np.float32)
    out = mx.nd.MultiBoxDetection(mx.nd.array(cls_prob), mx.nd.array(loc),
                                  mx.nd.array(anchors), nms_topk=1,
                                  nms_threshold=0.5).asnumpy()
    kept = out[0][out[0, :, 0] >= 0]
    assert len(kept) == 1
    assert abs(kept[0, 1] - 0.9) < 1e-5


def test_proposal_shapes_and_clip():
    rng = np.random.RandomState(4)
    N, A, H, W = 1, 3, 4, 4
    cls = rng.rand(N, 2 * A, H, W).astype(np.float32)
    bbox = (rng.rand(N, 4 * A, H, W).astype(np.float32) - 0.5) * 0.2
    im_info = np.array([[64, 64, 1.0]], np.float32)
    rois = mx.nd.Proposal(mx.nd.array(cls), mx.nd.array(bbox),
                          mx.nd.array(im_info), feature_stride=16,
                          scales=(2.0,), ratios=(0.5, 1.0, 2.0),
                          rpn_pre_nms_top_n=30, rpn_post_nms_top_n=8,
                          rpn_min_size=4).asnumpy()
    assert rois.shape == (8, 5)
    assert np.all(rois[:, 0] == 0)
    assert rois[:, 1:].min() >= 0 and rois[:, 1:].max() <= 63


def test_proposal_more_survivors_than_post_nms():
    # NMS keeps more boxes than rpn_post_nms_top_n: every output slot must
    # hold a real proposal (regression: unkept entries once scatter-wrote
    # 0.0 into the last slot)
    rng = np.random.RandomState(9)
    N, A, H, W = 1, 3, 6, 6
    cls = rng.rand(N, 2 * A, H, W).astype(np.float32) + 0.5
    bbox = np.zeros((N, 4 * A, H, W), np.float32)
    im_info = np.array([[96, 96, 1.0]], np.float32)
    rois = mx.nd.Proposal(mx.nd.array(cls), mx.nd.array(bbox),
                          mx.nd.array(im_info), feature_stride=16,
                          scales=(2.0,), ratios=(0.5, 1.0, 2.0),
                          rpn_pre_nms_top_n=100, rpn_post_nms_top_n=4,
                          threshold=0.95, rpn_min_size=4).asnumpy()
    assert rois.shape == (4, 5)
    w = rois[:, 3] - rois[:, 1]
    h = rois[:, 4] - rois[:, 2]
    assert (w > 0).all() and (h > 0).all()


def _np_ctc_loss(logits, labels):
    """Brute-force CTC by enumerating alignments (tiny T only)."""
    from itertools import product
    T, C = logits.shape
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    total = 0.0
    for path in product(range(C), repeat=T):
        # collapse repeats then drop blanks (0)
        seq = []
        prev = None
        for s in path:
            if s != prev:
                seq.append(s)
            prev = s
        seq = [s for s in seq if s != 0]
        if seq == list(labels):
            total += np.prod([p[t, path[t]] for t in range(T)])
    return -np.log(total)


def test_ctc_loss_matches_bruteforce():
    rng = np.random.RandomState(5)
    T, N, C = 4, 2, 3
    data = rng.randn(T, N, C).astype(np.float32)
    label = np.array([[1, 2], [2, 0]], np.float32)   # 0 = padding
    loss = mx.nd.CTCLoss(mx.nd.array(data), mx.nd.array(label)).asnumpy()
    np.testing.assert_allclose(loss[0], _np_ctc_loss(data[:, 0], [1, 2]),
                               rtol=1e-4)
    np.testing.assert_allclose(loss[1], _np_ctc_loss(data[:, 1], [2]),
                               rtol=1e-4)


def test_ctc_loss_grad_flows():
    rng = np.random.RandomState(6)
    x = mx.nd.array(rng.randn(5, 1, 4).astype(np.float32))
    x.attach_grad()
    lbl = mx.nd.array(np.array([[1, 3]], np.float32))
    with mx.autograd.record():
        loss = mx.nd.CTCLoss(x, lbl)
        s = mx.nd.sum(loss)
    s.backward()
    g = x.grad.asnumpy()
    assert np.isfinite(g).all() and np.abs(g).max() > 0


def test_correlation_self_zero_displacement():
    rng = np.random.RandomState(7)
    x = rng.rand(1, 4, 6, 6).astype(np.float32)
    out = mx.nd.Correlation(mx.nd.array(x), mx.nd.array(x), kernel_size=1,
                            max_displacement=1, stride1=1, stride2=1,
                            pad_size=1).asnumpy()
    assert out.shape[1] == 9
    # center displacement channel (index 4) is mean of x*x over channels
    center = out[0, 4]
    expect = (x[0] ** 2).mean(axis=0)
    np.testing.assert_allclose(center, expect, rtol=1e-5, atol=1e-6)


def test_deformable_conv_zero_offset_matches_conv():
    rng = np.random.RandomState(8)
    x = rng.rand(1, 3, 7, 7).astype(np.float32)
    w = rng.rand(4, 3, 3, 3).astype(np.float32)
    off = np.zeros((1, 2 * 9, 5, 5), np.float32)
    out_d = mx.nd.DeformableConvolution(
        mx.nd.array(x), mx.nd.array(off), mx.nd.array(w),
        kernel=(3, 3), num_filter=4, no_bias=True).asnumpy()
    out_c = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w),
                              kernel=(3, 3), num_filter=4,
                              no_bias=True).asnumpy()
    np.testing.assert_allclose(out_d, out_c, rtol=1e-4, atol=1e-5)


def test_psroi_pooling_uniform_plane():
    # each channel plane constant = its own index; output bin (i,j) of
    # channel c must read plane c*g*g + i*g + j
    od, g = 2, 2
    x = np.zeros((1, od * g * g, 6, 6), np.float32)
    for c in range(od * g * g):
        x[0, c] = c
    rois = np.array([[0, 0, 0, 5, 5]], np.float32)
    out = mx.nd.PSROIPooling(mx.nd.array(x), mx.nd.array(rois),
                             spatial_scale=1.0, output_dim=od,
                             pooled_size=g, group_size=g).asnumpy()
    assert out.shape == (1, od, g, g)
    for c in range(od):
        for i in range(g):
            for j in range(g):
                assert out[0, c, i, j] == c * g * g + i * g + j


def test_ssd_head_trains_one_step():
    """A minimal SSD head (the §2.15 capability gate): conv features ->
    cls/loc heads -> MultiBoxTarget -> losses; one fused train step."""
    num_cls, A = 3, 4       # 2 sizes + 3 ratios - 1 = 4 anchors/cell

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    feat = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1),
                              num_filter=8, name="feat")
    feat = mx.sym.Activation(feat, act_type="relu")
    cls_pred = mx.sym.Convolution(feat, kernel=(3, 3), pad=(1, 1),
                                  num_filter=(num_cls + 1) * A, name="cls")
    loc_pred = mx.sym.Convolution(feat, kernel=(3, 3), pad=(1, 1),
                                  num_filter=4 * A, name="loc")
    anchors = mx.sym.MultiBoxPrior(feat, sizes=(0.3, 0.6),
                                   ratios=(1.0, 0.5, 2.0))
    # (N, C+1, A*cells) / (N, A*cells*4)
    cls_pred = mx.sym.reshape(mx.sym.transpose(cls_pred, axes=(0, 2, 3, 1)),
                              shape=(0, -1, num_cls + 1))
    cls_pred = mx.sym.transpose(cls_pred, axes=(0, 2, 1))
    loc_pred = mx.sym.reshape(mx.sym.transpose(loc_pred, axes=(0, 2, 3, 1)),
                              shape=(0, -1))
    box_t, box_m, cls_t = mx.sym.MultiBoxTarget(anchors, label, cls_pred,
                                                name="target")
    cls_loss = mx.sym.SoftmaxOutput(cls_pred, cls_t, multi_output=True,
                                    use_ignore=True, ignore_label=-1,
                                    normalization="valid", name="cls_prob")
    loc_diff = (loc_pred - box_t) * box_m
    loc_loss = mx.sym.MakeLoss(mx.sym.smooth_l1(loc_diff, scalar=1.0),
                               grad_scale=1.0, name="loc_loss")
    sym = mx.sym.Group([cls_loss, loc_loss])

    N, H = 2, 8
    mod = mx.mod.Module(sym, context=mx.cpu(), data_names=("data",),
                        label_names=("label",))
    mod.bind(data_shapes=[("data", (N, 3, H, H))],
             label_shapes=[("label", (N, 2, 5))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.01})
    rng = np.random.RandomState(0)
    x = rng.rand(N, 3, H, H).astype(np.float32)
    y = np.array([[[1, 0.1, 0.1, 0.5, 0.5], [-1, 0, 0, 0, 0]],
                  [[2, 0.4, 0.4, 0.9, 0.9], [0, 0.0, 0.0, 0.3, 0.3]]],
                 np.float32)
    batch = mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])
    before = mod.get_params()[0]["cls_weight"].asnumpy().copy()
    mod._fit_step(batch)
    after = mod.get_params()[0]["cls_weight"].asnumpy()
    assert np.isfinite(after).all()
    assert not np.allclose(before, after)

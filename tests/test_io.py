"""Data IO tests (reference: tests/python/unittest/test_io.py,
test_recordio.py)."""
import gzip
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def test_ndarray_iter_basic():
    data = np.arange(40, dtype=np.float32).reshape(10, 4)
    label = np.arange(10, dtype=np.float32)
    it = mx.io.NDArrayIter(data, label, batch_size=4, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 4)
    assert batches[-1].pad == 2
    # padded batch wraps around
    assert_almost_equal(batches[-1].data[0].asnumpy()[2:], data[:2])
    it.reset()
    assert len(list(it)) == 3


def test_ndarray_iter_discard_and_shuffle():
    data = np.arange(10, dtype=np.float32).reshape(10, 1)
    it = mx.io.NDArrayIter(data, None, batch_size=3,
                           last_batch_handle="discard", shuffle=True)
    batches = list(it)
    assert len(batches) == 3
    seen = np.concatenate([b.data[0].asnumpy().ravel() for b in batches])
    assert len(set(seen.astype(int))) == 9


def test_ndarray_iter_dict_input():
    it = mx.io.NDArrayIter({"a": np.zeros((6, 2)), "b": np.ones((6, 3))},
                           np.arange(6), batch_size=2)
    names = [d.name for d in it.provide_data]
    assert sorted(names) == ["a", "b"]
    b = next(it)
    assert len(b.data) == 2


def test_csv_iter(tmp_path):
    data = np.random.rand(8, 3).astype(np.float32)
    label = np.arange(8, dtype=np.float32)
    np.savetxt(tmp_path / "d.csv", data, delimiter=",")
    np.savetxt(tmp_path / "l.csv", label, delimiter=",")
    it = mx.io.CSVIter(data_csv=str(tmp_path / "d.csv"), data_shape=(3,),
                       label_csv=str(tmp_path / "l.csv"), batch_size=4)
    b = next(it)
    assert b.data[0].shape == (4, 3)
    assert_almost_equal(b.data[0], data[:4], rtol=1e-5, atol=1e-6)


def _write_idx(path, arr):
    ndim = arr.ndim
    magic = 0x800 + ndim if arr.dtype == np.uint8 else 0x800 + ndim
    with open(path, "wb") as f:
        f.write(struct.pack(">I", (0x08 << 8) | ndim))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.astype(np.uint8).tobytes())


def test_mnist_iter(tmp_path):
    images = (np.random.rand(20, 28, 28) * 255).astype(np.uint8)
    labels = (np.arange(20) % 10).astype(np.uint8)
    _write_idx(tmp_path / "img", images)
    _write_idx(tmp_path / "lbl", labels)
    it = mx.io.MNISTIter(image=str(tmp_path / "img"),
                         label=str(tmp_path / "lbl"),
                         batch_size=5, shuffle=False, flat=False)
    b = next(it)
    assert b.data[0].shape == (5, 1, 28, 28)
    assert b.label[0].shape == (5,)
    assert_almost_equal(b.data[0].asnumpy()[0, 0], images[0] / 255.0,
                        rtol=1e-5, atol=1e-6)
    flat_it = mx.io.MNISTIter(image=str(tmp_path / "img"),
                              label=str(tmp_path / "lbl"),
                              batch_size=5, shuffle=False, flat=True)
    b = next(flat_it)
    assert b.data[0].shape == (5, 784)


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "t.rec")
    rec = mx.recordio.MXRecordIO(path, "w")
    payloads = [b"hello", b"x" * 1000, b"", b"abc\x00def"]
    for p in payloads:
        rec.write(p)
    rec.close()
    rec = mx.recordio.MXRecordIO(path, "r")
    got = []
    while True:
        r = rec.read()
        if r is None:
            break
        got.append(r)
    # empty payload reads back as empty bytes
    assert got == [b"hello", b"x" * 1000, b"", b"abc\x00def"]


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "t.rec")
    idx_path = str(tmp_path / "t.idx")
    rec = mx.recordio.MXIndexedRecordIO(idx_path, path, "w")
    for i in range(5):
        rec.write_idx(i, b"rec%d" % i)
    rec.close()
    rec = mx.recordio.MXIndexedRecordIO(idx_path, path, "r")
    assert rec.read_idx(3) == b"rec3"
    assert rec.read_idx(0) == b"rec0"
    assert rec.keys == [0, 1, 2, 3, 4]


def test_pack_unpack_header():
    h = mx.recordio.IRHeader(0, 3.0, 7, 0)
    s = mx.recordio.pack(h, b"payload")
    h2, payload = mx.recordio.unpack(s)
    assert payload == b"payload"
    assert h2.label == 3.0 and h2.id == 7
    # multi-label
    h = mx.recordio.IRHeader(4, np.array([1, 2, 3, 4], np.float32), 9, 0)
    h2, payload = mx.recordio.unpack(mx.recordio.pack(h, b"z"))
    assert_almost_equal(h2.label, np.array([1, 2, 3, 4], np.float32))
    assert payload == b"z"


def test_image_record_iter(tmp_path):
    cv2 = pytest.importorskip("cv2")
    path = str(tmp_path / "img.rec")
    rec = mx.recordio.MXRecordIO(path, "w")
    rng = np.random.RandomState(0)
    for i in range(10):
        img = (rng.rand(12, 12, 3) * 255).astype(np.uint8)
        rec.write(mx.recordio.pack_img(
            mx.recordio.IRHeader(0, float(i % 3), i, 0), img, img_fmt=".png"))
    rec.close()
    it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                               batch_size=4, shuffle=False,
                               preprocess_threads=2)
    b = next(it)
    assert b.data[0].shape == (4, 3, 8, 8)
    assert b.label[0].shape == (4,)
    assert_almost_equal(b.label[0], np.array([0.0, 1.0, 2.0, 0.0]))
    n = 1
    try:
        while True:
            b = next(it)
            n += 1
    except StopIteration:
        pass
    assert n == 3  # 10 imgs / bs 4 -> 2 full + 1 padded
    it.reset()
    b = next(it)
    assert b.data[0].shape == (4, 3, 8, 8)


def test_prefetching_iter():
    data = np.arange(24, dtype=np.float32).reshape(12, 2)
    base = mx.io.NDArrayIter(data, np.arange(12), batch_size=4)
    it = mx.io.PrefetchingIter(base)
    batches = []
    try:
        while True:
            batches.append(it.next())
    except StopIteration:
        pass
    assert len(batches) == 3
    assert_almost_equal(batches[0].data[0], data[:4])
    it.reset()
    b2 = it.next()
    assert_almost_equal(b2.data[0], data[:4])


def test_resize_iter():
    data = np.zeros((8, 2), np.float32)
    base = mx.io.NDArrayIter(data, None, batch_size=4)
    it = mx.io.ResizeIter(base, 5)
    assert len(list(it)) == 5


def test_recordio_multipart_write_roundtrip(tmp_path):
    # payloads >= 2**29 bytes are split into a cflag 1/2/3 chain
    # (dmlc-core writer behavior); small payloads stay single-part, and
    # the reader must reassemble a hand-forged chain.
    path = str(tmp_path / "big.rec")
    rec = mx.recordio.MXRecordIO(path, "w")
    payload = bytes(range(256)) * 40                      # 10240 bytes
    rec.write(payload)
    rec.close()
    rec = mx.recordio.MXRecordIO(path, "r")
    assert rec.read() == payload
    rec.close()
    # now forge a 3-part chain on disk and check the reader reassembles it
    kmagic = 0xced7230a
    with open(str(tmp_path / "chain.rec"), "wb") as f:
        parts = [payload[:4000], payload[4000:8000], payload[8000:]]
        for i, chunk in enumerate(parts):
            cflag = 1 if i == 0 else (3 if i == len(parts) - 1 else 2)
            f.write(struct.pack("<II", kmagic, (cflag << 29) | len(chunk)))
            f.write(chunk)
            f.write(b"\x00" * ((-len(chunk)) % 4))
    rec = mx.recordio.MXRecordIO(str(tmp_path / "chain.rec"), "r")
    assert rec.read() == payload
    assert rec.read() is None
    rec.close()


def test_image_record_iter_sharding(tmp_path):
    """num_parts/part_index must partition the records disjointly
    (distributed data parallelism; reference ImageRecParserParam)."""
    import cv2
    from mxnet_tpu import recordio
    rng = np.random.RandomState(0)
    path = str(tmp_path / "s.rec")
    rec = recordio.MXRecordIO(path, "w")
    n = 20
    for i in range(n):
        img = (rng.rand(8, 8, 3) * 255).astype(np.uint8)
        ok, enc = cv2.imencode(".png", img)
        rec.write(recordio.pack(recordio.IRHeader(0, float(i), i, 0),
                                enc.tobytes()))
    rec.close()

    seen = []
    for part in (0, 1):
        it = mx.io.ImageRecordIter(
            path_imgrec=path, data_shape=(3, 8, 8), batch_size=5,
            num_parts=2, part_index=part, round_batch=False)
        assert it.num_data == n // 2
        labels = []
        for b in it:
            lab = np.asarray(b.label[0].asnumpy()).ravel()
            if b.pad:
                lab = lab[: len(lab) - b.pad]
            labels.extend(lab.tolist())
        seen.append(set(int(v) for v in labels))
    assert seen[0].isdisjoint(seen[1])
    assert seen[0] | seen[1] == set(range(n))
    with pytest.raises(ValueError):
        mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                              batch_size=5, num_parts=2, part_index=2)


# ----------------------------------------------- recordio index validation

def _tamper_dataset(tmp_path, n=6):
    """A healthy indexed record file the tamper tests then corrupt."""
    from mxnet_tpu import recordio
    rec_path = str(tmp_path / "t.rec")
    idx_path = str(tmp_path / "t.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(n):
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), b"payload-%d" % i))
    w.close()
    return rec_path, idx_path


def test_indexed_recordio_rejects_offset_past_eof(tmp_path):
    """A stale/corrupt .idx whose offset cannot hold a record header is
    rejected AT OPEN with the index key named — not later as an opaque
    struct error from whatever read_idx happens to hit it."""
    from mxnet_tpu import recordio
    rec_path, idx_path = _tamper_dataset(tmp_path)
    size = os.path.getsize(rec_path)
    with open(idx_path) as fin:
        lines = fin.read().splitlines()
    lines[3] = "3\t%d" % (size + 100)          # key 3 -> past EOF
    with open(idx_path, "w") as fout:
        fout.write("\n".join(lines) + "\n")
    with pytest.raises(IOError) as err:
        recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    msg = str(err.value)
    assert "3" in msg and idx_path in msg and "stale or corrupt" in msg


def test_indexed_recordio_rejects_malformed_index_line(tmp_path):
    from mxnet_tpu import recordio
    rec_path, idx_path = _tamper_dataset(tmp_path)
    with open(idx_path, "a") as fout:
        fout.write("not-a-key\n")
    with pytest.raises(IOError) as err:
        recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    assert "malformed index entry" in str(err.value)
    assert idx_path in str(err.value)


def test_indexed_recordio_names_key_on_bad_magic(tmp_path):
    """An in-bounds offset that lands mid-record: the magic check fires
    and read_idx names the index key, offset, and file."""
    from mxnet_tpu import recordio
    rec_path, idx_path = _tamper_dataset(tmp_path)
    good = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    off = good.idx[2]
    good.close()
    with open(idx_path) as fin:
        lines = fin.read().splitlines()
    lines[2] = "2\t%d" % (off + 2)             # mid-record: valid bound,
    with open(idx_path, "w") as fout:          # garbage magic
        fout.write("\n".join(lines) + "\n")
    bad = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    assert bad.read_idx(1)                     # neighbors still fine
    with pytest.raises(IOError) as err:
        bad.read_idx(2)
    msg = str(err.value)
    assert "key 2" in msg and "magic" in msg.lower()
    bad.close()


def test_indexed_recordio_names_key_on_truncated_payload(tmp_path):
    """The record file ends mid-payload: the error names the promised
    vs available bytes and the index key being read."""
    from mxnet_tpu import recordio
    rec_path, idx_path = _tamper_dataset(tmp_path)
    good = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    last = good.idx[5]
    good.close()
    with open(rec_path, "r+b") as f:
        f.truncate(last + 10)                  # header intact, payload cut
    bad = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    with pytest.raises(IOError) as err:
        bad.read_idx(5)
    msg = str(err.value)
    assert "key 5" in msg and "truncated" in msg
    bad.close()


def test_indexed_recordio_missing_key_is_legible(tmp_path):
    from mxnet_tpu import recordio
    rec_path, idx_path = _tamper_dataset(tmp_path)
    r = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    with pytest.raises(KeyError) as err:
        r.read_idx(99)
    assert "99" in str(err.value) and idx_path in str(err.value)
    r.close()

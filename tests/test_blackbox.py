"""Flight recorder (ISSUE 13): bounded ring + crash-surviving flushes,
the post-mortem merge CLI, and the lint discipline over the flush paths.

The recorder's whole contract is "the telemetry survives the process",
so most coverage here is subprocess drills: SIGTERM/143 preemption,
an uncaught crash, a SIGKILL with only the periodic heartbeat flush to
save the window, and the zero-import gate for plain fits.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, profiler as _profiler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def recorder(tmp_path):
    """Arm the recorder at tmp_path (no periodic thread); tear it down
    fully so the span listener never leaks into other tests."""
    from mxnet_tpu.obs import blackbox
    mx.config.set("MXNET_TPU_OBS_BLACKBOX", str(tmp_path))
    mx.config.set("MXNET_TPU_OBS_BLACKBOX_FLUSH_SECS", "0")
    try:
        yield blackbox
    finally:
        blackbox.reset()
        mx.config.reset("MXNET_TPU_OBS_BLACKBOX")
        mx.config.reset("MXNET_TPU_OBS_BLACKBOX_FLUSH_SECS")
        faults.clear()


def _read(path):
    lines = [ln for ln in open(path).read().splitlines() if ln.strip()]
    header = json.loads(lines[0])
    events = [json.loads(ln) for ln in lines[1:]]
    return header, events


def test_ring_is_bounded_and_flush_is_complete(recorder, tmp_path):
    mx.config.set("MXNET_TPU_OBS_BLACKBOX_RING", 64)
    try:
        for i in range(200):
            recorder.record("test", "ev%d" % i, i=i)
        with _profiler.span("bb_span", "test"):
            pass
        _profiler.incr_counter("bb_unit_counter", 3)
        path = recorder.flush("unit")
        header, events = _read(path)
        assert header["blackbox"] == 1
        assert header["flush_reason"] == "unit"
        assert header["rank"] == 0 and header["role"] == "proc"
        assert "wall_base" in header and "clock_offset_s" in header
        assert len(events) <= 64
        names = [e["name"] for e in events if e["kind"] == "test"]
        assert "ev199" in names and "ev0" not in names
        # span closes land in the ring even with MXNET_TPU_OBS off
        assert any(e["kind"] == "span" and e["name"] == "bb_span"
                   for e in events)
        # counter deltas ride each flush
        delta = [e for e in events if e["kind"] == "counters"][-1]
        assert delta["data"].get("bb_unit_counter") == 3
        # events carry monotone wall timestamps
        ts = [e["t"] for e in events]
        assert ts == sorted(ts)
    finally:
        mx.config.reset("MXNET_TPU_OBS_BLACKBOX_RING")


def test_fault_fire_records_and_flushes(recorder, tmp_path):
    faults.install("bb.site@1:raise")
    with pytest.raises(faults.FaultInjected):
        faults.fire("bb.site")
    path = recorder.path()
    assert path is not None and os.path.exists(path)
    header, events = _read(path)
    assert header["flush_reason"] == "fault:bb.site@1:raise"
    fault_evs = [e for e in events if e["kind"] == "fault"]
    assert fault_evs and fault_evs[-1]["name"] == "bb.site"
    assert fault_evs[-1]["data"] == {"arrival": 1, "kind": "raise"}
    assert "bb.site@1:raise" in header["faults_armed"]


def test_slow_fault_records_without_flushing(recorder, tmp_path,
                                             monkeypatch):
    monkeypatch.setenv("MXNET_TPU_FAULTS_SLOW_SECS", "0.01")
    faults.install("bb.site:slow")
    t0 = time.perf_counter()
    faults.fire("bb.site")
    assert time.perf_counter() - t0 >= 0.01
    # recorded in the ring but no per-arrival disk flush
    assert not os.path.exists(recorder.path())
    path = recorder.flush("check")
    _h, events = _read(path)
    assert any(e["kind"] == "fault" and e["data"]["kind"] == "slow"
               for e in events)


def test_knob_off_is_zero_import_and_zero_cost():
    """A plain fit must never import the recorder or the straggler
    stack, and the flush counter must stay 0 (subprocess so this test
    is immune to other tests having imported the modules)."""
    code = """
import sys
import numpy as np
import mxnet_tpu as mx
X = np.random.RandomState(0).uniform(-1, 1, (32, 8)).astype("float32")
Y = np.zeros((32, 1), "float32")
it = mx.io.NDArrayIter({"data": X}, {"label": Y}, batch_size=8)
net = mx.sym.LinearRegressionOutput(
    mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=1),
    mx.sym.Variable("label"))
mod = mx.mod.Module(net, context=mx.cpu(), data_names=("data",),
                    label_names=("label",))
mod.fit(it, num_epoch=1, eval_metric="mse", optimizer="sgd")
assert "mxnet_tpu.obs.blackbox" not in sys.modules
assert "mxnet_tpu.obs.straggler" not in sys.modules
from mxnet_tpu import profiler
assert profiler.get_counter("obs_blackbox_flush") == 0
assert profiler.get_counter("obs_straggler") == 0
print("ZERO-IMPORT-OK")
"""
    env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
    for k in ("MXNET_TPU_OBS_BLACKBOX", "MXNET_TPU_FAULTS",
              "MXNET_TPU_POD_KV"):
        env.pop(k, None)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          cwd=REPO, capture_output=True, text=True,
                          timeout=300)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    assert "ZERO-IMPORT-OK" in proc.stdout


def test_sigterm_preemption_leaves_window(tmp_path):
    """The SIGTERM/143 protocol flushes the window from the training
    thread (observed-flag discipline): the file must carry the preempt
    event, the ckpt preempt-save phase, and the armed fault spec."""
    bbdir = str(tmp_path / "bb")
    code = """
import numpy as np
import mxnet_tpu as mx
X = np.random.RandomState(0).uniform(-1, 1, (64, 8)).astype("float32")
Y = np.zeros((64, 1), "float32")
it = mx.io.NDArrayIter({"data": X}, {"label": Y}, batch_size=8)
net = mx.sym.LinearRegressionOutput(
    mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=1),
    mx.sym.Variable("label"))
mod = mx.mod.Module(net, context=mx.cpu(), data_names=("data",),
                    label_names=("label",))
mod.fit(it, num_epoch=4, eval_metric="mse", optimizer="sgd",
        checkpoint=mx.checkpoint.CheckpointConfig(%r, every_n_batches=2))
"""
    env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu",
           "MXNET_TPU_OBS_BLACKBOX": bbdir,
           "MXNET_TPU_OBS_BLACKBOX_FLUSH_SECS": "0",
           "MXNET_TPU_FAULTS": "fit.batch@5:sigterm"}
    proc = subprocess.run(
        [sys.executable, "-c", code % str(tmp_path / "ckpts")],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 143, (proc.returncode,
                                    proc.stderr[-3000:])
    header, events = _read(os.path.join(bbdir, "blackbox-p0.jsonl"))
    kinds = {(e["kind"], e["name"]) for e in events}
    assert ("preempt", "sigterm") in kinds, sorted(kinds)
    assert ("ckpt", "preempt-save") in kinds, sorted(kinds)
    assert ("ckpt", "save") in kinds
    assert ("fault", "fit.batch") in kinds
    assert "fit.batch@5:sigterm" in header["faults_armed"]


def test_crash_excepthook_flushes(tmp_path):
    bbdir = str(tmp_path)
    code = """
import mxnet_tpu as mx
from mxnet_tpu.obs import blackbox
blackbox.record("unit", "before-crash")
raise RuntimeError("boom for the recorder")
"""
    env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu",
           "MXNET_TPU_OBS_BLACKBOX": bbdir,
           "MXNET_TPU_OBS_BLACKBOX_FLUSH_SECS": "0"}
    env.pop("MXNET_TPU_FAULTS", None)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          cwd=REPO, capture_output=True, text=True,
                          timeout=300)
    assert proc.returncode == 1
    assert "boom for the recorder" in proc.stderr   # hook chains through
    _header, events = _read(os.path.join(bbdir, "blackbox-p0.jsonl"))
    crash = [e for e in events if e["kind"] == "crash"]
    assert crash and "boom for the recorder" in crash[-1]["data"]["message"]
    assert any(e["kind"] == "unit" for e in events)


def test_periodic_heartbeat_survives_sigkill(tmp_path):
    """The SIGKILL guarantee: no flush call ever runs, yet the last
    periodic window must be on disk."""
    bbdir = str(tmp_path)
    code = """
import time
import mxnet_tpu as mx
from mxnet_tpu.obs import blackbox
blackbox.record("unit", "pre-kill", n=1)
print("ARMED", flush=True)
time.sleep(60)
"""
    env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu",
           "MXNET_TPU_OBS_BLACKBOX": bbdir,
           "MXNET_TPU_OBS_BLACKBOX_FLUSH_SECS": "0.2"}
    env.pop("MXNET_TPU_FAULTS", None)
    proc = subprocess.Popen([sys.executable, "-c", code], env=env,
                            cwd=REPO, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    try:
        deadline = time.monotonic() + 120
        path = os.path.join(bbdir, "blackbox-p0.jsonl")
        while time.monotonic() < deadline and not os.path.exists(path):
            time.sleep(0.1)
        time.sleep(0.5)      # let at least one periodic flush land
        assert os.path.exists(path), proc.communicate(timeout=30)
    finally:
        proc.kill()
        proc.communicate()
    header, events = _read(path)
    assert header["flush_reason"] == "periodic"
    assert any(e["kind"] == "unit" and e["name"] == "pre-kill"
               for e in events)


# ----------------------------------------------------- merge CLI


def _write_rank_file(path, rank, role, reason, events, offset=0.0,
                     armed=()):
    header = {"blackbox": 1, "rank": rank, "role": role,
              "flush_reason": reason, "clock_offset_s": offset,
              "faults_armed": list(armed), "gen": 0,
              "wall_base": 100.0, "perf_base": 0.0}
    with open(path, "w") as f:
        f.write(json.dumps(header) + "\n")
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def _synthetic_pod(tmp_path):
    d = str(tmp_path)
    # rank 1 died at aligned t=110 (its wall runs +5s fast)
    _write_rank_file(
        os.path.join(d, "blackbox-p1.jsonl"), 1, "child",
        "fault:host.die@12:hostkill",
        [{"s": 1, "t": 114.0, "kind": "span", "name": "step",
          "cat": "step", "dur_ms": 4.0},
         {"s": 2, "t": 115.0, "kind": "fault", "name": "host.die",
          "data": {"arrival": 12, "kind": "hostkill"}}],
        offset=5.0, armed=["host.die@12:hostkill"])
    # rank 0 survived: saw the death at 120, failed over at 125
    _write_rank_file(
        os.path.join(d, "blackbox-p0.jsonl"), 0, "child", "exit",
        [{"s": 1, "t": 100.0, "kind": "epoch", "name": "end"}])
    _write_rank_file(
        os.path.join(d, "blackbox-p0-coord.jsonl"), 0, "coord", "exit",
        [{"s": 1, "t": 120.0, "kind": "pod", "name": "dead-hosts",
          "data": {"ranks": [1]}},
         {"s": 2, "t": 125.0, "kind": "pod", "name": "failover",
          "data": {"leader": 0, "addr": "127.0.0.1:1"}}])
    return d


def test_cli_verdict_names_first_dead_and_aligns_clocks(tmp_path,
                                                        capsys):
    from mxnet_tpu.obs.__main__ import main as obs_main
    d = _synthetic_pod(tmp_path)
    assert obs_main(["blackbox", d]) == 0
    out = capsys.readouterr().out
    line = [ln for ln in out.splitlines()
            if ln.startswith("POD-BLACKBOX-VERDICT ")][0]
    verdict = json.loads(line.split(" ", 1)[1])
    assert verdict["first_dead"] == 1
    assert verdict["dead"] == [1] and verdict["survivors"] == [0]
    # clock alignment: the skewed rank's wall 115 lands at 110 — BEFORE
    # the survivor's detection at 120
    assert verdict["last_event"]["t"] == pytest.approx(110.0)
    assert verdict["last_fault"]["site"] == "host.die"
    assert verdict["armed_faults"] == ["host.die@12:hostkill"]
    view = verdict["survivor_views"]["0"]
    assert [e["name"] for e in view] == ["dead-hosts", "failover"]
    assert verdict["failovers"][0]["t"] > verdict["last_event"]["t"]


def test_cli_merged_timeline_is_valid_chrome_trace(tmp_path):
    from mxnet_tpu.obs.__main__ import main as obs_main
    d = _synthetic_pod(tmp_path)
    # a per-rank chrome trace merges in, shifted onto the aligned clock
    with open(os.path.join(d, "profile-p0.json"), "w") as f:
        json.dump({"traceEvents": [
            {"name": "op", "ph": "X", "ts": 0.0, "dur": 5.0,
             "pid": 0, "tid": 1}]}, f)
    # give rank 0's header the trace anchor
    path = os.path.join(d, "blackbox-p0.jsonl")
    lines = open(path).read().splitlines()
    header = json.loads(lines[0])
    header["trace0_wall"] = 118.0
    with open(path, "w") as f:
        f.write("\n".join([json.dumps(header)] + lines[1:]) + "\n")
    assert obs_main(["blackbox", d]) == 0
    with open(os.path.join(d, "pod-timeline.json")) as f:
        merged = json.load(f)
    events = merged["traceEvents"]
    assert events and isinstance(events, list)
    # rank lanes: pid == pod rank, with process_name metadata
    pids = {e.get("pid") for e in events if e.get("ph") != "M"}
    assert pids >= {0, 1}
    names = {e["args"]["name"] for e in events
             if e.get("name") == "process_name"}
    assert names == {"rank 0", "rank 1"}
    # the shifted chrome-trace op landed under rank 0's pid at
    # (118 - 100) * 1e6 us on the merged clock (aligned_min = 100)
    ops = [e for e in events if e.get("name") == "op"]
    assert ops and ops[0]["pid"] == 0
    assert ops[0]["ts"] == pytest.approx(18e6)
    # span events render as complete slices with durations
    spans = [e for e in events if e.get("name") == "span:step"]
    assert spans and spans[0]["ph"] == "X" and spans[0]["dur"] > 0


def test_cli_all_clean_pod(tmp_path, capsys):
    from mxnet_tpu.obs.__main__ import main as obs_main
    _write_rank_file(os.path.join(str(tmp_path), "blackbox-p0.jsonl"),
                     0, "child", "exit",
                     [{"s": 1, "t": 10.0, "kind": "epoch",
                       "name": "end"}])
    assert obs_main(["blackbox", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    verdict = json.loads([ln for ln in out.splitlines()
                          if ln.startswith("POD-BLACKBOX-VERDICT ")][0]
                         .split(" ", 1)[1])
    assert verdict["first_dead"] is None and verdict["dead"] == []


def test_cli_empty_dir_fails_legibly(tmp_path, capsys):
    from mxnet_tpu.obs.__main__ import main as obs_main
    assert obs_main(["blackbox", str(tmp_path)]) == 2
    assert "no blackbox" in capsys.readouterr().out


# ------------------------------------------------------ lint wiring


def test_lint_rules_hold_over_recorder_and_flush_paths():
    """The satellite wiring: the signal-unsafe and wall-clock lint
    rules run over the recorder and every module that flushes it — the
    recorder's SIGTERM flush is exactly the hazard class the lint
    exists for. The recorder's single wall-clock anchor and the PodKV
    clock exchange carry explicit, justified inline allows; nothing
    may register a signal handler that touches the recorder."""
    from mxnet_tpu.analysis.lint import lint_paths
    paths = [os.path.join(REPO, "mxnet_tpu", "obs", "blackbox.py"),
             os.path.join(REPO, "mxnet_tpu", "obs", "straggler.py"),
             os.path.join(REPO, "mxnet_tpu", "obs", "__main__.py"),
             os.path.join(REPO, "mxnet_tpu", "faults.py"),
             os.path.join(REPO, "mxnet_tpu", "elastic.py"),
             os.path.join(REPO, "mxnet_tpu", "parallel", "dist.py")]
    report = lint_paths(paths)
    bad = [f for f in report.findings
           if f.code in ("signal-unsafe", "wall-clock")]
    assert not bad, ["%s:%s %s" % (f.path, f.line, f.message)
                     for f in bad]

"""Worker body for the pod observability tests (straggler detection +
pod-suffixed profiler dumps). Run by tests/test_obs_pod.py in a 2-rank
DMLC fake cluster; NOT collected by pytest.

argv: <mode> <outdir>   mode in {"slow", "balanced", "slowloader"}

Both ranks train the same tiny regression over the dist kvstore with a
``fit.batch:slow`` fault armed on EVERY batch — ``balanced`` gives both
ranks the same per-batch sleep (work rates equal, detection must stay
silent), ``slow`` gives rank 1 a much larger one (rank 0's aggregation
must flag it). Using the fault's sleep as the work floor makes the
ratio deterministic instead of riding microsecond-scale fwd/bwd noise.

``slowloader`` (ISSUE 17 satellite) keeps the compute balanced but
feeds rank 0 through a ``mx.data.DataLoader`` whose transform stalls
far longer per batch than the work floor: a slow DATA PLANE. The
inter-step window re-mark in fit (base_module) must keep that stall
out of the straggler rate — detection stays silent and the slowness
surfaces as ``data_stall``/``loop_prefetch_stall`` instead.
"""
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

BATCH, NSAMP, FEAT, OUT = 8, 64, 16, 4
EPOCHS = 3


def main():
    mode, outdir = sys.argv[1], sys.argv[2]
    os.chdir(outdir)
    import mxnet_tpu as mx
    from mxnet_tpu import faults, profiler

    rank = int(os.environ["DMLC_WORKER_ID"])
    sleep = {"balanced": ("0.05", "0.05"),
             "slowloader": ("0.05", "0.05"),
             "slow": ("0.05", "0.30")}[mode][min(rank, 1)]
    os.environ["MXNET_TPU_FAULTS_SLOW_SECS"] = sleep
    faults.install("fit.batch:slow")

    kv = mx.kv.create("dist_sync")
    assert kv.num_workers == 2
    mx.random.seed(7)
    rng = np.random.RandomState(11)
    X = rng.uniform(-1, 1, (NSAMP, FEAT)).astype(np.float32)
    Y = rng.uniform(-1, 1, (NSAMP, OUT)).astype(np.float32)
    if mode == "slowloader":
        # rank 0 streams through the data plane with a per-record stall
        # that dwarfs the 0.05s work floor (~0.4s/batch of loader
        # latency): without the off-thread fetch re-mark in fit, rank
        # 0's work rate would read ~8x slow and trip the ratio=3 flag
        from mxnet_tpu import recordio
        rec = os.path.join(outdir, "d-r%d.rec" % rank)
        idx = os.path.join(outdir, "d-r%d.idx" % rank)
        w = recordio.MXIndexedRecordIO(idx, rec, "w")
        for i in range(NSAMP):
            w.write_idx(i, recordio.pack(
                recordio.IRHeader(OUT, Y[i], i, 0), X[i].tobytes()))
        w.close()
        transform = mx.data.RawTransform((FEAT,), label_width=OUT)
        if rank == 0:
            transform = mx.data.StallTransform(transform, 0.05)
        it = mx.data.DataLoader(
            rec, idx_path=idx, batch_size=BATCH, transform=transform,
            shuffle=False, num_workers=1, part=(0, 1),
            label_name="label")
    else:
        it = mx.io.NDArrayIter({"data": X}, {"label": Y},
                               batch_size=BATCH)
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=OUT, name="fc")
    net = mx.sym.LinearRegressionOutput(fc, mx.sym.Variable("label"))
    mod = mx.mod.Module(net, context=mx.cpu(), data_names=("data",),
                        label_names=("label",))
    mod.fit(it, num_epoch=EPOCHS, eval_metric="mse", optimizer="sgd",
            optimizer_params={"learning_rate": 0.05}, kvstore=kv)

    # pod-suffixed profiler dump: the DEFAULT filename must not collide
    # across ranks on a shared filesystem
    profiler.set_state("run")
    (mx.nd.ones((4, 4)) + 1).asnumpy()
    profiler.set_state("stop")
    dump_path = profiler.dump()

    result = {
        "rank": rank,
        "mode": mode,
        "dump": os.path.basename(dump_path),
        "obs_straggler": profiler.get_counter("obs_straggler"),
        "publish_failed": profiler.get_counter(
            "obs_straggler_publish_failed"),
        "data_stall": profiler.get_counter("data_stall"),
        "loop_prefetch_stall": profiler.get_counter(
            "loop_prefetch_stall"),
        "gauges": {k: v for k, v in profiler.gauges().items()
                   if k.startswith("obs_pod_")},
    }
    if rank == 0:
        from mxnet_tpu.obs import straggler
        result["block"] = straggler.pod_block()
        result["report_pod"] = mx.obs.report().get("pod")
    with open(os.path.join(outdir, "result-r%d.json" % rank), "w") as f:
        json.dump(result, f)
    kv.barrier()
    print("OBS-POD-WORKER-DONE rank=%d" % rank, flush=True)


if __name__ == "__main__":
    main()

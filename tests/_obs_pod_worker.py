"""Worker body for the pod observability tests (straggler detection +
pod-suffixed profiler dumps). Run by tests/test_obs_pod.py in a 2-rank
DMLC fake cluster; NOT collected by pytest.

argv: <mode> <outdir>   mode in {"slow", "balanced"}

Both ranks train the same tiny regression over the dist kvstore with a
``fit.batch:slow`` fault armed on EVERY batch — ``balanced`` gives both
ranks the same per-batch sleep (work rates equal, detection must stay
silent), ``slow`` gives rank 1 a much larger one (rank 0's aggregation
must flag it). Using the fault's sleep as the work floor makes the
ratio deterministic instead of riding microsecond-scale fwd/bwd noise.
"""
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

BATCH, NSAMP, FEAT, OUT = 8, 64, 16, 4
EPOCHS = 3


def main():
    mode, outdir = sys.argv[1], sys.argv[2]
    os.chdir(outdir)
    import mxnet_tpu as mx
    from mxnet_tpu import faults, profiler

    rank = int(os.environ["DMLC_WORKER_ID"])
    sleep = {"balanced": ("0.05", "0.05"),
             "slow": ("0.05", "0.30")}[mode][min(rank, 1)]
    os.environ["MXNET_TPU_FAULTS_SLOW_SECS"] = sleep
    faults.install("fit.batch:slow")

    kv = mx.kv.create("dist_sync")
    assert kv.num_workers == 2
    mx.random.seed(7)
    rng = np.random.RandomState(11)
    X = rng.uniform(-1, 1, (NSAMP, FEAT)).astype(np.float32)
    Y = rng.uniform(-1, 1, (NSAMP, OUT)).astype(np.float32)
    it = mx.io.NDArrayIter({"data": X}, {"label": Y}, batch_size=BATCH)
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=OUT, name="fc")
    net = mx.sym.LinearRegressionOutput(fc, mx.sym.Variable("label"))
    mod = mx.mod.Module(net, context=mx.cpu(), data_names=("data",),
                        label_names=("label",))
    mod.fit(it, num_epoch=EPOCHS, eval_metric="mse", optimizer="sgd",
            optimizer_params={"learning_rate": 0.05}, kvstore=kv)

    # pod-suffixed profiler dump: the DEFAULT filename must not collide
    # across ranks on a shared filesystem
    profiler.set_state("run")
    (mx.nd.ones((4, 4)) + 1).asnumpy()
    profiler.set_state("stop")
    dump_path = profiler.dump()

    result = {
        "rank": rank,
        "mode": mode,
        "dump": os.path.basename(dump_path),
        "obs_straggler": profiler.get_counter("obs_straggler"),
        "publish_failed": profiler.get_counter(
            "obs_straggler_publish_failed"),
        "gauges": {k: v for k, v in profiler.gauges().items()
                   if k.startswith("obs_pod_")},
    }
    if rank == 0:
        from mxnet_tpu.obs import straggler
        result["block"] = straggler.pod_block()
        result["report_pod"] = mx.obs.report().get("pod")
    with open(os.path.join(outdir, "result-r%d.json" % rank), "w") as f:
        json.dump(result, f)
    kv.barrier()
    print("OBS-POD-WORKER-DONE rank=%d" % rank, flush=True)


if __name__ == "__main__":
    main()

"""Worker body for the tools/launch.py round-trip smoke (ISSUE 11
satellite): every rank must see the SAME cluster_env() the launcher
wired, the distributed bootstrap must complete (bounded — never a
hang), and a dist.barrier() must release all ranks.

Run via tools/launch.py by tests/test_pod.py; NOT collected by pytest.
"""
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# the accelerator plugin can rewrite JAX_PLATFORMS at startup; pin CPU
# (same guard as tests/_dist_worker.py)
jax.config.update("jax_platforms", "cpu")


def main():
    outdir = sys.argv[1]
    from mxnet_tpu.parallel import dist

    env = dist.cluster_env()
    assert env is not None, "launcher did not set the DMLC_* protocol"
    assert env["num_workers"] == int(os.environ["DMLC_NUM_WORKER"])
    assert env["rank"] == int(os.environ["DMLC_WORKER_ID"])

    dist.initialize()
    assert dist.is_initialized()
    assert dist.rank() == env["rank"]
    assert dist.num_workers() == env["num_workers"]

    dist.barrier()          # every rank must pass, or nothing returns

    with open(os.path.join(outdir, "env_rank%d.json" % env["rank"]),
              "w") as f:
        json.dump(env, f)

    dist.barrier()          # all records durable before anyone exits
    print("launch worker rank %d/%d OK"
          % (env["rank"], env["num_workers"]), flush=True)


if __name__ == "__main__":
    main()

"""Gluon API tests (reference model: tests/python/unittest/test_gluon*.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def _toy_data(n=200, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.uniform(-1, 1, (n, 2)).astype(np.float32)
    y = ((x[:, 0] * x[:, 1]) > 0).astype(np.float32)
    return x, y


def _mlp():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
    return net


def test_parameter_basic():
    p = gluon.Parameter("weight", shape=(3, 4))
    p.initialize(init=mx.init.One())
    np.testing.assert_allclose(p.data().asnumpy(), np.ones((3, 4)))
    assert p.grad().shape == (3, 4)
    assert p.list_ctx()[0].device_type in ("cpu", "tpu")


def test_parameter_deferred_init():
    dense = nn.Dense(4)
    dense.initialize()
    with pytest.raises(gluon.DeferredInitializationError):
        dense.weight.data()
    out = dense(mx.nd.ones((2, 3)))
    assert out.shape == (2, 4)
    assert dense.weight.shape == (4, 3)


def _named_mlp():
    net = nn.HybridSequential(prefix="net_")
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", prefix="fc1_"),
                nn.Dense(2, prefix="fc2_"))
    return net


def test_block_collect_and_save_load(tmp_path):
    net = _named_mlp()
    net.initialize(mx.init.Xavier())
    net(mx.nd.ones((1, 2)))
    params = net.collect_params()
    assert len(params.keys()) == 4
    fname = str(tmp_path / "net.params")
    net.save_params(fname)
    net2 = _named_mlp()
    net2.load_params(fname)
    out1 = net(mx.nd.ones((3, 2))).asnumpy()
    out2 = net2(mx.nd.ones((3, 2))).asnumpy()
    np.testing.assert_allclose(out1, out2, rtol=1e-6)


def test_gluon_trainer_converges():
    x, y = _toy_data()
    X, Y = mx.nd.array(x), mx.nd.array(y)
    net = _mlp()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(60):
        with mx.autograd.record():
            loss = loss_fn(net(X), Y)
        loss.backward()
        trainer.step(x.shape[0])
    acc = (net(X).asnumpy().argmax(1) == y).mean()
    assert acc > 0.95, acc


def test_hybridize_matches_eager_forward_and_grad():
    """hybridize() (CachedOp jit) must match the imperative path for both
    outputs and parameter gradients."""
    x, y = _toy_data(32, seed=4)
    X, Y = mx.nd.array(x), mx.nd.array(y)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def run(hybrid):
        np.random.seed(7)
        net = _named_mlp()
        net.initialize(mx.init.Xavier(rnd_type="gaussian", magnitude=2),
                       force_reinit=True)
        if hybrid:
            net.hybridize()
        with mx.autograd.record():
            loss = loss_fn(net(X), Y)
        loss.backward()
        grads = {k: p.grad().asnumpy()
                 for k, p in net.collect_params().items()
                 if p.grad_req != "null"}
        return loss.asnumpy(), grads

    l_e, g_e = run(False)
    l_h, g_h = run(True)
    np.testing.assert_allclose(l_e, l_h, rtol=1e-5)
    assert set(g_e) == set(g_h)
    for k in g_e:
        np.testing.assert_allclose(
            g_e[k], g_h[k], rtol=1e-4, atol=1e-6,
            err_msg="hybrid grad mismatch at %s" % k)


def test_hybridize_batchnorm_updates_running_stats():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1), nn.BatchNorm(),
            nn.Activation("relu"), nn.Flatten(), nn.Dense(2))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = mx.nd.array(np.random.RandomState(0).rand(4, 3, 6, 6)
                    .astype(np.float32))
    with mx.autograd.record():
        net(x)
    rm = [p for n, p in net.collect_params().items()
          if n.endswith("running_mean")][0]
    assert float(np.abs(rm.data().asnumpy()).sum()) > 0


def test_losses_against_numpy():
    rng = np.random.RandomState(0)
    pred = rng.randn(8, 5).astype(np.float32)
    label = rng.randint(0, 5, (8,)).astype(np.float32)
    l = gluon.loss.SoftmaxCrossEntropyLoss()(
        mx.nd.array(pred), mx.nd.array(label)).asnumpy()
    # numpy reference
    e = np.exp(pred - pred.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    expected = -np.log(p[np.arange(8), label.astype(int)])
    np.testing.assert_allclose(l, expected, rtol=1e-5)

    pred2 = rng.randn(8, 3).astype(np.float32)
    lab2 = rng.randn(8, 3).astype(np.float32)
    l2 = gluon.loss.L2Loss()(mx.nd.array(pred2), mx.nd.array(lab2)).asnumpy()
    np.testing.assert_allclose(l2, ((pred2 - lab2) ** 2).mean(1) / 2,
                               rtol=1e-5)

    l1 = gluon.loss.L1Loss()(mx.nd.array(pred2), mx.nd.array(lab2)).asnumpy()
    np.testing.assert_allclose(l1, np.abs(pred2 - lab2).mean(1), rtol=1e-5)


def test_fused_lstm_matches_cell_unroll():
    """gluon.rnn.LSTM (fused lax.scan op) == LSTMCell unrolled with the
    same weights (reference: FusedRNNCell.unfuse equivalence tests in
    test_rnn.py)."""
    T, N, I, H = 4, 3, 5, 6
    rng = np.random.RandomState(0)
    x = rng.randn(T, N, I).astype(np.float32)

    layer = gluon.rnn.LSTM(hidden_size=H, num_layers=1, input_size=I)
    layer.initialize(mx.init.Xavier())
    out = layer(mx.nd.array(x)).asnumpy()

    cell = gluon.rnn.LSTMCell(H, input_size=I)
    cell.initialize(mx.init.Xavier())
    # copy fused layer weights into the cell
    lp = {k.split("_", 1)[1]: v for k, v in layer.collect_params().items()
          if "_l0_" in "_" + k or k.split("_")[-3:-1]}
    layer_params = dict(layer.collect_params().items())
    get = lambda suffix: [v for k, v in layer_params.items()  # noqa: E731
                          if k.endswith(suffix)][0]
    cell.i2h_weight.set_data(get("l0_i2h_weight").data())
    cell.h2h_weight.set_data(get("l0_h2h_weight").data())
    cell.i2h_bias.set_data(get("l0_i2h_bias").data())
    cell.h2h_bias.set_data(get("l0_h2h_bias").data())
    outs, _ = cell.unroll(T, mx.nd.array(x), layout="TNC",
                          merge_outputs=True)
    np.testing.assert_allclose(out, outs.asnumpy(), rtol=1e-5, atol=1e-6)


def test_gru_and_rnn_layers_run():
    for layer in (gluon.rnn.GRU(5, num_layers=2, bidirectional=True),
                  gluon.rnn.RNN(5, activation="tanh")):
        layer.initialize(mx.init.Xavier())
        out = layer(mx.nd.array(np.random.rand(3, 2, 4)
                                .astype(np.float32)))
        assert out.shape[0] == 3 and out.shape[1] == 2


def test_sequential_rnn_cell_and_modifiers():
    cell = gluon.rnn.SequentialRNNCell()
    cell.add(gluon.rnn.LSTMCell(4, input_size=3))
    cell.add(gluon.rnn.ResidualCell(gluon.rnn.GRUCell(4, input_size=4)))
    cell.initialize(mx.init.Xavier())
    x = mx.nd.array(np.random.rand(2, 5, 3).astype(np.float32))
    outs, states = cell.unroll(5, x, layout="NTC", merge_outputs=True)
    assert outs.shape == (2, 5, 4)
    assert len(states) == 3  # lstm h,c + gru h


def test_dataset_dataloader():
    x = np.arange(40).reshape(20, 2).astype(np.float32)
    y = np.arange(20).astype(np.float32)
    ds = gluon.data.ArrayDataset(x, y)
    assert len(ds) == 20
    loader = gluon.data.DataLoader(ds, batch_size=6, last_batch="keep")
    batches = list(loader)
    assert len(batches) == 4
    assert batches[0][0].shape == (6, 2)
    assert batches[-1][0].shape == (2, 2)
    # shuffle covers all samples
    loader2 = gluon.data.DataLoader(ds, batch_size=5, shuffle=True)
    seen = np.sort(np.concatenate([b[1].asnumpy() for b in loader2]))
    np.testing.assert_array_equal(seen, np.arange(20))
    # threaded prefetch path
    loader3 = gluon.data.DataLoader(ds, batch_size=5, num_workers=2)
    assert sum(b[0].shape[0] for b in loader3) == 20


def test_model_zoo_constructors():
    vision = gluon.model_zoo.vision
    net = vision.get_model("resnet18_v2", classes=10)
    net.initialize(mx.init.Xavier())
    out = net(mx.nd.array(np.random.rand(2, 3, 32, 32).astype(np.float32)))
    assert out.shape == (2, 10)
    with pytest.raises(ValueError):
        vision.get_model("not_a_model")
    # all names constructible (no forward — just graph building)
    for name in ("alexnet", "vgg11", "squeezenet1_0", "densenet121",
                 "inception_v3", "mobilenet0_25", "resnet50_v1"):
        vision.get_model(name)


def test_split_and_load_and_clip_global_norm():
    data = mx.nd.array(np.arange(24).reshape(8, 3).astype(np.float32))
    parts = gluon.utils.split_data(data, 4)
    assert [p.shape for p in parts] == [(2, 3)] * 4
    arrays = [mx.nd.array(np.ones(4).astype(np.float32)),
              mx.nd.array(np.ones(4).astype(np.float32) * 2)]
    norm = gluon.utils.clip_global_norm(arrays, 1.0)
    total = np.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays))
    assert abs(total - 1.0) < 1e-5
    assert norm > 1.0


def test_hybridized_dropout_no_tracer_leak():
    """Dropout inside a hybridized block must not leak the traced PRNG key
    into the global chain (regression: UnexpectedTracerError on the next
    eager op), and training-mode masks must differ across calls."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"))
    net.add(nn.Dropout(0.5))
    net.add(nn.Dense(4))
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    net.hybridize()
    x = mx.nd.array(np.random.RandomState(0).rand(8, 8).astype(np.float32))
    with autograd.record():
        o1 = net(x)
        loss = (o1 * o1).sum()
    loss.backward()                       # exact-mask replay path
    with autograd.record():
        o2 = net(x)
    # fresh key per call: masks (hence outputs) differ while training
    assert not np.allclose(o1.asnumpy(), o2.asnumpy())
    # eager op after the hybridized call must not hit a leaked tracer
    z = (mx.nd.random.uniform(shape=(2,)) + 1).asnumpy()
    assert np.all(np.isfinite(z))
    # inference mode: dropout off, deterministic
    a = net(x).asnumpy()
    b = net(x).asnumpy()
    np.testing.assert_allclose(a, b)


def test_gluon_moe_block_trains_and_aux_flows():
    # nn.MoE: expert FFN block; router aux loss collected via
    # collect_aux_losses participates in the gradient
    from mxnet_tpu.gluon import nn as gnn, Trainer, loss as gloss
    net = gnn.HybridSequential()
    net.add(gnn.Dense(16, flatten=False))
    net.add(gnn.MoE(16, 32, 4))
    net.add(gnn.Dense(4, flatten=False))
    net.initialize(mx.init.Xavier())
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(8, 16).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 4, (8,)).astype(np.float32))
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    lf = gloss.SoftmaxCrossEntropyLoss()
    losses = []
    for _ in range(10):
        with mx.autograd.record():
            out = net(x)
            aux = gnn.collect_aux_losses(net)
            l = lf(out, y).mean() + 0.01 * aux
        l.backward()
        tr.step(8)
        losses.append(float(l.asnumpy()))
    assert losses[-1] < losses[0]
    assert float(aux.asnumpy()) >= 1.0 - 1e-3   # GShard aux lower bound
    # router must have received gradient through the aux term + gating
    moe = net[1]
    g = moe.router.grad()
    assert float(np.abs(g.asnumpy()).sum()) > 0


def test_gluon_moe_hybridize_aux_raises_clearly():
    # aux-loss training is eager-only: under hybridize() the stashed aux
    # is a stale tracer and collect_aux_losses must say so loudly
    from mxnet_tpu.gluon import nn as gnn
    import pytest as _pytest
    net = gnn.HybridSequential()
    net.add(gnn.MoE(8, 16, 2))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = mx.nd.array(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    net(x)
    with _pytest.raises(RuntimeError, match="hybridize"):
        gnn.collect_aux_losses(net)


def test_pipeline_module_get_params_reflects_training():
    from tests.test_pipeline_module import _stages
    mod = mx.mod.PipelineModule(_stages(), n_microbatches=2)
    mod.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.init.Xavier())
    before = {i: {k: v.copy() for k, v in p.items()}
              for i, p in mod.get_params().items()}
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    rng = np.random.RandomState(0)
    db = mx.io.DataBatch(
        data=[mx.nd.array(rng.randn(4, 6).astype(np.float32))],
        label=[mx.nd.array(rng.randint(0, 4, (4,)).astype(np.float32))])
    for _ in range(3):
        mod.fit_step(db)
    after = mod.get_params()
    moved = any(not np.allclose(before[i][k], after[i][k])
                for i in before for k in before[i])
    assert moved, "get_params returned untrained copies"

"""Mixed-precision (mx.amp) policy tests.

Reference parity: the reference's fp16 story is cast-to-fp16 +
SGD(multi_precision=True) (tests/python/train/test_dtype.py,
python/mxnet/optimizer.py SGD). Here the policy is trace-time: bf16 MXU
compute, fp32 master weights.
"""
import numpy as np
import pytest

import mxnet_tpu as mx


@pytest.fixture(autouse=True)
def _amp_off_after():
    yield
    mx.amp.off()


def test_cast_compute_policy():
    import jax.numpy as jnp
    a = jnp.ones((4, 4), jnp.float32)
    i = jnp.ones((4,), jnp.int32)
    assert mx.amp.cast_compute(a).dtype == jnp.float32   # off: no-op
    mx.amp.init("bfloat16")
    assert mx.amp.active()
    out_a, out_i = mx.amp.cast_compute(a, i)
    assert out_a.dtype == jnp.bfloat16
    assert out_i.dtype == jnp.int32                      # non-f32 untouched
    mx.amp.off()
    assert not mx.amp.active()


def test_mxu_operands_accumulation_request():
    import jax.numpy as jnp
    a32 = jnp.ones((2, 2), jnp.float32)
    b16 = jnp.ones((2, 2), jnp.bfloat16)
    # fp32 matmul and conv both request fp32 accumulation
    _, _, acc = mx.amp.mxu_operands(a32, a32)
    assert acc == {"preferred_element_type": jnp.float32}
    _, _, acc = mx.amp.mxu_operands(a32, a32, conv=True)
    assert acc == {"preferred_element_type": jnp.float32}
    # bf16 dot: explicit fp32 accumulation; bf16 conv: operand dtype
    # (conv transpose rule forbids mixed dtypes; MXU accumulates fp32 anyway)
    _, _, acc = mx.amp.mxu_operands(b16, b16)
    assert acc == {"preferred_element_type": jnp.float32}
    _, _, acc = mx.amp.mxu_operands(b16, b16, conv=True)
    assert acc == {}


def test_amp_dense_conv_compute_dtype():
    mx.amp.init("bfloat16")
    x = mx.nd.array(np.random.RandomState(0).rand(2, 3, 8, 8).astype(np.float32))
    w = mx.nd.array(np.random.RandomState(1).rand(4, 3, 3, 3).astype(np.float32))
    out = mx.nd.Convolution(x, w, kernel=(3, 3), num_filter=4, no_bias=True)
    assert str(out.dtype) == "bfloat16"
    xf = mx.nd.array(np.random.RandomState(2).rand(2, 8).astype(np.float32))
    wf = mx.nd.array(np.random.RandomState(3).rand(5, 8).astype(np.float32))
    out = mx.nd.FullyConnected(xf, wf, num_hidden=5, no_bias=True)
    assert str(out.dtype) == "bfloat16"


def test_amp_fused_rnn_compute_dtype():
    from mxnet_tpu.ops.rnn_op import rnn_param_size
    T, N, I, H = 3, 2, 4, 5
    n = rnn_param_size(1, I, H, "lstm")
    rng = np.random.RandomState(0)
    data = mx.nd.array(rng.rand(T, N, I).astype(np.float32))
    params = mx.nd.array(rng.rand(n).astype(np.float32) * 0.1)
    state = mx.nd.zeros((1, N, H))
    cell = mx.nd.zeros((1, N, H))
    out32 = mx.nd.RNN(data, params, state, cell, state_size=H,
                      num_layers=1, mode="lstm")
    assert str(out32.dtype) == "float32"
    mx.amp.init("bfloat16")
    out16 = mx.nd.RNN(data, params, state, cell, state_size=H,
                      num_layers=1, mode="lstm")
    assert str(out16.dtype) == "bfloat16"
    np.testing.assert_allclose(out16.asnumpy().astype(np.float32),
                               out32.asnumpy(), rtol=0.1, atol=0.05)


def test_amp_module_fit_master_weights_fp32():
    rng = np.random.RandomState(7)
    x = rng.uniform(-1, 1, (200, 2)).astype(np.float32)
    y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.float32)
    d = mx.sym.Variable("data")
    f1 = mx.sym.FullyConnected(d, num_hidden=16, name="fc1")
    a1 = mx.sym.Activation(f1, act_type="tanh")
    f2 = mx.sym.FullyConnected(a1, num_hidden=2, name="fc2")
    sym = mx.sym.SoftmaxOutput(f2, name="softmax")

    mx.amp.init("bfloat16")
    it = mx.io.NDArrayIter(x, y, batch_size=50, shuffle=True)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.fit(it, optimizer="sgd", initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            num_epoch=15)
    params = mod.get_params()[0]
    for name, arr in params.items():
        assert str(arr.dtype) == "float32", (name, arr.dtype)
    it.reset()
    acc = dict(mod.score(it, "acc"))["accuracy"]
    assert acc > 0.9, acc

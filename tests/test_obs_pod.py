"""Pod observability (ISSUE 13): straggler detection over federated
step telemetry, pod-suffixed profiler dumps, and the coordinator's
opt-in /metrics endpoint.

The 2-process drills use the same localhost DMLC fake-cluster pattern
as tests/test_dist.py; the aggregation math itself is unit-tested
against fake windows (fires on a slow rank / stays silent balanced,
counter-asserted both ways — the ISSUE 13 acceptance pair).
"""
import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

import mxnet_tpu as mx
from mxnet_tpu import profiler as _profiler
from mxnet_tpu.obs import straggler as _straggler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_obs_pod_worker.py")


def _free_port():
    from mxnet_tpu.parallel.dist import free_port
    return free_port()


# ------------------------------------------------- aggregation units


def _fake_reader(windows):
    """reader(key, timeout_ms) over {rank: payload} fake windows."""
    def read(key, _timeout_ms):
        rank = int(key.rsplit("/", 1)[1])
        payload = windows.get(rank)
        return None if payload is None else json.dumps(payload)
    return read


def _window(rank, count, work_s, wall_s=None):
    return {"rank": rank, "epoch": 0, "gen": 0, "count": count,
            "wall_s": wall_s if wall_s is not None else work_s,
            "work_s": work_s}


def test_aggregate_flags_slow_rank():
    mx.config.set("MXNET_TPU_OBS_STRAGGLER_RATIO", 2.0)
    try:
        before = _profiler.get_counter("obs_straggler")
        block = _straggler.aggregate(2, _fake_reader({
            0: _window(0, 20, 2.0),      # 10 steps/s of local work
            1: _window(1, 20, 10.0),     # 2 steps/s — 5x slower
        }), gen=0)
        assert block is not None
        assert block["stragglers"] == [1], block
        assert block["slow_fast_ratio"] == pytest.approx(5.0), block
        assert _profiler.get_counter("obs_straggler") == before + 1
        assert _profiler.get_gauge("obs_pod_straggler_r1") == 1.0
        assert _profiler.get_gauge("obs_pod_straggler_r0") == 0.0
        assert _profiler.get_gauge("obs_pod_work_per_sec_r1") == \
            pytest.approx(2.0)
    finally:
        mx.config.reset("MXNET_TPU_OBS_STRAGGLER_RATIO")


def test_aggregate_silent_on_balanced_pod():
    mx.config.set("MXNET_TPU_OBS_STRAGGLER_RATIO", 2.0)
    try:
        before = _profiler.get_counter("obs_straggler")
        block = _straggler.aggregate(2, _fake_reader({
            0: _window(0, 20, 2.0),
            1: _window(1, 20, 2.4),      # 1.2x: inside the ratio
        }), gen=0)
        assert block["stragglers"] == [], block
        assert _profiler.get_counter("obs_straggler") == before
        assert _profiler.get_gauge("obs_pod_straggler_r1") == 0.0
    finally:
        mx.config.reset("MXNET_TPU_OBS_STRAGGLER_RATIO")


def test_aggregate_reports_under_stable_pod_rank():
    """After a fail-over the DMLC slots are generation-renumbered:
    windows carrying pod_rank must be flagged/gauged under the ORIGINAL
    rank (the identity the flight-recorder files use), never the
    slot."""
    mx.config.set("MXNET_TPU_OBS_STRAGGLER_RATIO", 2.0)
    try:
        # survivors of a dead rank 0: slots 0,1 are original ranks 1,2
        block = _straggler.aggregate(2, _fake_reader({
            0: dict(_window(0, 20, 2.0), pod_rank=1),
            1: dict(_window(1, 20, 10.0), pod_rank=2),
        }), gen=1)
        assert set(block["ranks"]) == {"1", "2"}, block
        assert block["stragglers"] == [2], block
        assert _profiler.get_gauge("obs_pod_straggler_r2") == 1.0
    finally:
        mx.config.reset("MXNET_TPU_OBS_STRAGGLER_RATIO")
        # leave no flagged gauge behind for other tests
        _straggler.aggregate(2, _fake_reader({
            0: dict(_window(0, 20, 2.0), pod_rank=1),
            1: dict(_window(1, 20, 2.0), pod_rank=2)}), gen=1)


def test_aggregate_zeroes_gauges_of_departed_ranks():
    """A flagged rank whose windows stop arriving (host death, reshard
    to a smaller world) must not keep serving straggler=1.0 forever."""
    mx.config.set("MXNET_TPU_OBS_STRAGGLER_RATIO", 2.0)
    try:
        _straggler.aggregate(2, _fake_reader({
            0: _window(0, 20, 2.0),
            1: _window(1, 20, 10.0),
        }), gen=0)
        assert _profiler.get_gauge("obs_pod_straggler_r1") == 1.0
        # rank 1 is gone: the next aggregation only sees rank 0
        _straggler.aggregate(1, _fake_reader({
            0: _window(0, 20, 2.0),
        }), gen=0)
        assert _profiler.get_gauge("obs_pod_straggler_r1") == 0.0
        assert _profiler.get_gauge("obs_pod_steps_per_sec_r1") == 0.0
        assert _profiler.get_gauge("obs_pod_work_per_sec_r1") == 0.0
    finally:
        mx.config.reset("MXNET_TPU_OBS_STRAGGLER_RATIO")


def test_aggregate_handles_missing_and_garbage_windows():
    mx.config.set("MXNET_TPU_OBS_STRAGGLER_RATIO", 2.0)
    try:
        # only one usable window: no ratio, no stragglers, no crash
        def read(key, _t):
            rank = int(key.rsplit("/", 1)[1])
            return json.dumps(_window(0, 10, 1.0)) if rank == 0 \
                else "not json"
        block = _straggler.aggregate(2, read, gen=0)
        assert block["stragglers"] == []
        assert block["slow_fast_ratio"] is None
        assert _straggler.aggregate(2, lambda k, t: None, gen=0) is None
    finally:
        mx.config.reset("MXNET_TPU_OBS_STRAGGLER_RATIO")


# --------------------------------------------------- 2-process drills


def _run_pod(mode, tmp_path, timeout=420.0):
    port = _free_port()
    outdir = str(tmp_path)
    env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "",
           "MXNET_TPU_OBS_STRAGGLER_RATIO": "3",
           "MXNET_TPU_DIST_TIMEOUT": "60",
           "DMLC_ROLE": "worker", "DMLC_PS_ROOT_URI": "127.0.0.1",
           "DMLC_PS_ROOT_PORT": str(port), "DMLC_NUM_WORKER": "2",
           "DMLC_NUM_SERVER": "0"}
    for k in ("MXNET_TPU_FAULTS", "MXNET_TPU_OBS_BLACKBOX",
              "MXNET_TPU_POD_KV"):
        env.pop(k, None)
    procs = [subprocess.Popen(
        [sys.executable, WORKER, mode, outdir],
        env={**env, "DMLC_WORKER_ID": str(r)},
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for r in range(2)]
    outs = [p.communicate(timeout=timeout) for p in procs]
    dump = "\n".join("--- rank %d rc=%s\n%s\n%s"
                     % (i, p.returncode, o[-3000:], e[-3000:])
                     for i, (p, (o, e)) in enumerate(zip(procs, outs)))
    assert all(p.returncode == 0 for p in procs), dump
    results = {}
    for r in range(2):
        with open(os.path.join(outdir, "result-r%d.json" % r)) as f:
            results[r] = json.load(f)
    return results, dump


@pytest.mark.slow
def test_straggler_fires_on_injected_slow_rank(tmp_path):
    """ISSUE 13 acceptance: a rank slow-faulted every batch must be
    flagged by the leader's log-boundary aggregation, and the profiler
    dump default path must come out rank-suffixed on BOTH ranks."""
    results, dump = _run_pod("slow", tmp_path)
    r0 = results[0]
    assert r0["obs_straggler"] > 0, (r0, dump)
    assert r0["block"] is not None and r0["block"]["stragglers"] == [1], \
        (r0, dump)
    assert r0["gauges"].get("obs_pod_straggler_r1") == 1.0, r0
    assert r0["gauges"].get("obs_pod_straggler_r0") == 0.0, r0
    # the pod block rides mx.obs.report()
    assert r0["report_pod"] is not None and \
        r0["report_pod"]["stragglers"] == [1], r0
    # per-rank rates present for both ranks
    assert set(r0["block"]["ranks"]) == {"0", "1"}, r0
    # the slow rank itself never aggregates (leader-only)
    assert results[1]["obs_straggler"] == 0, results[1]
    # satellite: default profiler dump is rank-suffixed under a pod
    assert results[0]["dump"] == "profile-p0.json", results[0]
    assert results[1]["dump"] == "profile-p1.json", results[1]
    for r in range(2):
        with open(os.path.join(str(tmp_path),
                               "profile-p%d.json" % r)) as f:
            trace = json.load(f)
        assert isinstance(trace["traceEvents"], list)


@pytest.mark.slow
def test_straggler_silent_on_balanced_pod(tmp_path):
    """The other half of the acceptance pair: identical per-batch work
    on both ranks must not fire (counter stays 0, no flagged ranks)."""
    results, dump = _run_pod("balanced", tmp_path)
    r0 = results[0]
    assert r0["obs_straggler"] == 0, (r0, dump)
    assert r0["block"] is None or r0["block"]["stragglers"] == [], r0
    assert r0["publish_failed"] == 0, r0


@pytest.mark.slow
def test_straggler_silent_on_slow_loader(tmp_path):
    """ISSUE 17 satellite (re-derived inter-step window): rank 0 feeds
    through a DataLoader stalled ~8x past the balanced work floor — a
    slow DATA PLANE. It must surface as data_stall/loop_prefetch_stall
    on that rank, never as a straggler flag (the off-thread fetch
    re-mark in base_module.fit keeps loader waits out of the
    local-work window)."""
    results, dump = _run_pod("slowloader", tmp_path)
    r0 = results[0]
    assert r0["obs_straggler"] == 0, (r0, dump)
    assert r0["block"] is None or r0["block"]["stragglers"] == [], \
        (r0, dump)
    # the slowness is visible where it belongs: the data plane
    assert r0["data_stall"] + r0["loop_prefetch_stall"] > 0, (r0, dump)
    # the unstalled rank sees no data-plane bubbles worth flagging
    assert results[1]["obs_straggler"] == 0, results[1]


def test_single_process_dump_keeps_default_name(tmp_path, monkeypatch):
    """No pod -> no suffix: the default filename stays profile.json and
    an explicit set_config() filename is always respected."""
    monkeypatch.chdir(tmp_path)
    _profiler.set_config(filename="profile.json")
    _profiler.set_state("run")
    (mx.nd.ones((2, 2)) + 1).asnumpy()
    _profiler.set_state("stop")
    path = _profiler.dump()
    assert os.path.basename(path) == "profile.json"
    assert os.path.exists(path)


# ------------------------------------------- coordinator /metrics


@pytest.mark.slow
def test_coordinator_metrics_endpoint_no_backend(tmp_path):
    """Satellite: the pod coordinator exposes the opt-in /metrics
    endpoint (elastic_* counters render) WITHOUT initializing any jax
    backend — proven by running it under an unresolvable JAX_PLATFORMS
    (any backend init would die loudly, the PR 11 trick)."""
    from mxnet_tpu.obs.prometheus import parse_prometheus
    port = _free_port()
    mport = _free_port()
    env = {**os.environ, "PYTHONPATH": "",
           "JAX_PLATFORMS": "no_such_platform",
           "MXNET_TPU_OBS_METRICS_PORT": str(mport),
           "MXNET_TPU_DIST_TIMEOUT": "30",
           "MXNET_TPU_HEARTBEAT_PERIOD": "0.5",
           "DMLC_ROLE": "worker", "DMLC_PS_ROOT_URI": "127.0.0.1",
           "DMLC_PS_ROOT_PORT": str(port), "DMLC_NUM_WORKER": "1",
           "DMLC_NUM_SERVER": "0", "DMLC_WORKER_ID": "0"}
    for k in ("MXNET_TPU_FAULTS", "MXNET_TPU_OBS_BLACKBOX"):
        env.pop(k, None)
    child = ("import time; time.sleep(8)")
    proc = subprocess.Popen(
        [sys.executable, "-m", "mxnet_tpu.elastic", "--coordinated",
         "--", sys.executable, "-c", child],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        body = None
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and body is None:
            if proc.poll() is not None:
                break
            try:
                with urllib.request.urlopen(
                        "http://127.0.0.1:%d/metrics" % mport,
                        timeout=2.0) as resp:
                    body = resp.read().decode("utf-8")
            except OSError:
                time.sleep(0.3)
        assert body is not None, \
            "never scraped the coordinator /metrics\n%s" % str(
                proc.communicate(timeout=30))
        samples = parse_prometheus(body)       # strict grammar check
        names = {n for n, _labels in samples}
        assert "mxnet_tpu_elastic_world" in names, sorted(names)[:40]
        out, err = proc.communicate(timeout=60)
        assert proc.returncode == 0, (proc.returncode, out[-3000:],
                                      err[-3000:])
        assert "POD-COORDINATOR-EXIT" in out
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()

"""Config/env knob layer + log parsing tools (reference: the MXNET_* env
vars of docs/how_to/env_var.md and tools/parse_log.py)."""
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_config_env_override_precedence(monkeypatch):
    assert mx.config.get("MXNET_PREFETCH_BUFFER") == 4
    monkeypatch.setenv("MXNET_PREFETCH_BUFFER", "9")
    assert mx.config.get("MXNET_PREFETCH_BUFFER") == 9
    mx.config.set("MXNET_PREFETCH_BUFFER", 3)
    try:
        assert mx.config.get("MXNET_PREFETCH_BUFFER") == 3
    finally:
        mx.config.reset("MXNET_PREFETCH_BUFFER")
    assert mx.config.get("MXNET_PREFETCH_BUFFER") == 9   # env again


def test_config_describe_lists_all_knobs():
    txt = mx.config.describe()
    for name in mx.config.KNOBS:
        assert name in txt


def test_config_unknown_knob_raises():
    with pytest.raises(KeyError):
        mx.config.get("MXNET_NO_SUCH_KNOB")


def test_naive_engine_sync_dispatch():
    from mxnet_tpu.ndarray import ndarray as nd_mod
    mx.config.set("MXNET_ENGINE_TYPE", "NaiveEngine")
    try:
        assert nd_mod._SYNC_DISPATCH        # hot-path cache refreshed
        out = mx.nd.dot(mx.nd.ones((8, 8)), mx.nd.ones((8, 8)))
        np.testing.assert_allclose(out.asnumpy(), 8.0)
    finally:
        mx.config.reset("MXNET_ENGINE_TYPE")
    assert not nd_mod._SYNC_DISPATCH


def test_remat_knob_matches_baseline():
    """MXNET_EXEC_ENABLE_REMAT must change memory strategy, not results."""
    def run():
        mx.random.seed(0)
        np.random.seed(0)
        d = mx.sym.Variable("data")
        h = mx.sym.FullyConnected(d, num_hidden=8, name="fc1")
        h = mx.sym.Activation(h, act_type="tanh")
        h = mx.sym.FullyConnected(h, num_hidden=2, name="fc2")
        sym = mx.sym.SoftmaxOutput(h, name="softmax")
        x = np.random.RandomState(0).rand(32, 4).astype(np.float32)
        y = (x[:, 0] > 0.5).astype(np.float32)
        it = mx.io.NDArrayIter(x, y, batch_size=16,
                               label_name="softmax_label")
        mod = mx.mod.Module(sym, context=mx.cpu())
        mod.fit(it, optimizer="sgd", initializer=mx.init.Xavier(),
                optimizer_params={"learning_rate": 0.1}, num_epoch=2)
        return {k: v.asnumpy() for k, v in mod.get_params()[0].items()}

    base = run()
    mx.config.set("MXNET_EXEC_ENABLE_REMAT", True)
    try:
        remat = run()
    finally:
        mx.config.reset("MXNET_EXEC_ENABLE_REMAT")
    for k in base:
        np.testing.assert_allclose(base[k], remat[k], rtol=1e-5,
                                   atol=1e-6, err_msg=k)


def test_worker_nthreads_knob_flows_to_record_iter(tmp_path):
    import cv2
    from mxnet_tpu import recordio
    path = str(tmp_path / "x.rec")
    rec = recordio.MXRecordIO(path, "w")
    ok, enc = cv2.imencode(
        ".png", np.zeros((10, 10, 3), np.uint8))
    rec.write(recordio.pack(recordio.IRHeader(0, 0.0, 0, 0), enc.tobytes()))
    rec.close()
    mx.config.set("MXNET_CPU_WORKER_NTHREADS", 2)
    try:
        it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                                   batch_size=1)
        assert it._n_threads == 2
    finally:
        mx.config.reset("MXNET_CPU_WORKER_NTHREADS")


def test_parse_log(tmp_path):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    import parse_log
    log = """
2026-01-01 Epoch[0] Batch [10]\tSpeed: 500.00 samples/sec\taccuracy=0.5
2026-01-01 Epoch[0] Batch [20]\tSpeed: 700.00 samples/sec\taccuracy=0.6
2026-01-01 Epoch[0] Train-accuracy=0.650000
2026-01-01 Epoch[0] Time cost=3.500
2026-01-01 Epoch[0] Validation-accuracy=0.700000
2026-01-01 Epoch[1] Train-accuracy=0.900000
2026-01-01 Epoch[1] Time cost=3.100
2026-01-01 Epoch[1] Validation-accuracy=0.950000
"""
    rows = parse_log.parse(log.splitlines())
    assert rows[0]["train-accuracy"] == 0.65
    assert rows[0]["val-accuracy"] == 0.7
    assert rows[0]["speed"] == 600.0
    assert rows[1]["val-accuracy"] == 0.95
    f = tmp_path / "t.log"
    f.write_text(log)
    assert parse_log.main([str(f), "--format", "csv"]) == 0

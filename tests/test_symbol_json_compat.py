"""Reference symbol-JSON interop (reference format:
MXSymbolCreateFromJSON / MXSymbolSaveToJSON, src/c_api/c_api_symbolic.cc;
oracle file: the reference's own checkpoint fixture
tests/python/unittest/save_000800.json)."""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx

# a verbatim 0.8-era reference-schema MLP (same structure as the reference's
# save_000800.json fixture: param/attr split, 2-element inputs/heads)
REFERENCE_MLP_JSON = json.dumps({
    "nodes": [
        {"op": "null", "param": {}, "name": "data", "inputs": [],
         "backward_source_id": -1,
         "attr": {"ctx_group": "stage1", "lr_mult": "0.2"}},
        {"op": "null", "param": {}, "name": "fc1_weight", "inputs": [],
         "backward_source_id": -1, "attr": {"wd_mult": "0.3"}},
        {"op": "null", "param": {}, "name": "fc1_bias", "inputs": [],
         "backward_source_id": -1},
        {"op": "FullyConnected",
         "param": {"no_bias": "False", "num_hidden": "16",
                   "workspace": "1024"},
         "name": "fc1", "inputs": [[0, 0], [1, 0], [2, 0]],
         "backward_source_id": -1},
        {"op": "Activation", "param": {"act_type": "relu"}, "name": "relu1",
         "inputs": [[3, 0]], "backward_source_id": -1},
        {"op": "null", "param": {}, "name": "fc2_weight", "inputs": [],
         "backward_source_id": -1},
        {"op": "null", "param": {}, "name": "fc2_bias", "inputs": [],
         "backward_source_id": -1},
        {"op": "FullyConnected",
         "param": {"no_bias": "False", "num_hidden": "4"},
         "name": "fc2", "inputs": [[4, 0], [5, 0], [6, 0]],
         "backward_source_id": -1},
        {"op": "null", "param": {}, "name": "softmax_label", "inputs": [],
         "backward_source_id": -1},
        {"op": "SoftmaxOutput", "param": {"grad_scale": "1"},
         "name": "softmax", "inputs": [[7, 0], [8, 0]],
         "backward_source_id": -1},
    ],
    "arg_nodes": [0, 1, 2, 5, 6, 8],
    "heads": [[9, 0]],
})


def test_load_reference_schema_and_bind():
    sym = mx.sym.load_json(REFERENCE_MLP_JSON)
    assert sym.list_arguments() == ["data", "fc1_weight", "fc1_bias",
                                    "fc2_weight", "fc2_bias",
                                    "softmax_label"]
    # user attrs survive
    assert sym.attr_dict()["data"]["ctx_group"] == "stage1"
    ex = sym.simple_bind(ctx=mx.cpu(), data=(8, 10), softmax_label=(8,))
    out = ex.forward()[0]
    assert out.shape == (8, 4)


def test_reference_fixture_loads():
    """The reference repo's own checkpoint fixture parses and binds."""
    path = "/root/reference/tests/python/unittest/save_000800.json"
    if not os.path.exists(path):
        pytest.skip("reference fixture not available")
    sym = mx.sym.load(path)
    args = sym.list_arguments()
    assert args[0] == "data" and "fc1_weight" in args
    ex = sym.simple_bind(ctx=mx.cpu(), data=(4, 32), softmax_label=(4,))
    assert ex.forward()[0].shape[0] == 4


def test_roundtrip_preserves_semantics():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    js = net.tojson()
    # exported container is the reference schema
    g = json.loads(js)
    assert set(g) == {"nodes", "arg_nodes", "heads"}
    assert all("param" in n for n in g["nodes"])
    assert all(len(e) == 2 for n in g["nodes"] for e in n["inputs"])

    back = mx.sym.load_json(js)
    assert back.list_arguments() == net.list_arguments()
    rng = np.random.RandomState(0)
    vals = {}
    for name, shp in zip(net.list_arguments(),
                         net.infer_shape(data=(4, 6),
                                         softmax_label=(4,))[0]):
        vals[name] = mx.nd.array(rng.rand(*shp).astype(np.float32))
    o1 = net.eval(**vals)[0].asnumpy()
    o2 = back.eval(**vals)[0].asnumpy()
    np.testing.assert_allclose(o1, o2, rtol=1e-6)


def test_roundtrip_batchnorm_aux_rederived():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4, name="c1")
    net = mx.sym.BatchNorm(net, name="bn1")
    net = mx.sym.Activation(net, act_type="relu")
    js = net.tojson()
    g = json.loads(js)
    bn = [n for n in g["nodes"] if n["op"] == "BatchNorm"][0]
    assert len(bn["inputs"]) == 3          # data, gamma, beta — no aux
    back = mx.sym.load_json(js)
    assert back.list_auxiliary_states() == net.list_auxiliary_states()
    ex = back.simple_bind(ctx=mx.cpu(), data=(2, 3, 8, 8))
    assert ex.forward()[0].shape == (2, 4, 6, 6)


def test_load_1x_style_batchnorm_with_serialized_aux():
    """Reference 1.x files serialize moving stats as graph nodes — they
    must be adopted as aux, not duplicated (regression)."""
    js = json.dumps({
        "nodes": [
            {"op": "null", "param": {}, "name": "data", "inputs": []},
            {"op": "null", "param": {}, "name": "bn_gamma", "inputs": []},
            {"op": "null", "param": {}, "name": "bn_beta", "inputs": []},
            {"op": "null", "param": {}, "name": "bn_moving_mean",
             "inputs": []},
            {"op": "null", "param": {}, "name": "bn_moving_var",
             "inputs": []},
            {"op": "BatchNorm", "param": {"eps": "0.001"}, "name": "bn",
             "inputs": [[0, 0], [1, 0], [2, 0], [3, 0], [4, 0]]},
        ],
        "arg_nodes": [0, 1, 2, 3, 4],
        "heads": [[5, 0]],
    })
    sym = mx.sym.load_json(js)
    assert sym.list_arguments() == ["data", "bn_gamma", "bn_beta"]
    assert sym.list_auxiliary_states() == ["bn_moving_mean",
                                           "bn_moving_var"]
    ex = sym.simple_bind(ctx=mx.cpu(), data=(2, 3, 4, 4))
    assert ex.forward()[0].shape == (2, 3, 4, 4)


def test_get_internals_with_aux_head_serializes():
    """get_internals() exposes aux variables as heads; tojson must not
    KeyError on them (regression)."""
    data = mx.sym.Variable("data")
    net = mx.sym.BatchNorm(data, name="bn")
    internals = net.get_internals()
    js = internals.tojson()
    back = mx.sym.load_json(js)
    assert "bn_moving_mean" in (back.list_auxiliary_states()
                                + back.list_arguments() + back.list_outputs())


def test_explicit_aux_binding_survives_roundtrip():
    """A user-bound aux symbol must keep its edge through save/load
    (regression: it was silently dropped and re-created under a new
    name)."""
    data = mx.sym.Variable("data")
    custom = mx.sym.Variable("custom_mean")
    net = mx.sym.BatchNorm(data, moving_mean=custom, name="bn")
    back = mx.sym.load_json(net.tojson())
    assert "custom_mean" in back.list_auxiliary_states()


def test_module_checkpoint_roundtrips_through_reference_schema(tmp_path):
    """save_checkpoint -> load_checkpoint through the new format."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 6))], label_shapes=[("softmax_label",
                                                            (4,))])
    mod.init_params(mx.init.Xavier())
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 1)
    sym2, arg2, aux2 = mx.model.load_checkpoint(prefix, 1)
    assert sym2.list_arguments() == net.list_arguments()
    np.testing.assert_allclose(
        arg2["fc_weight"].asnumpy(),
        mod.get_params()[0]["fc_weight"].asnumpy())

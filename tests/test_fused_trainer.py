"""Fused whole-model optimizer step (mxnet_tpu/_fused.py).

Covers:

* parity: fused ``Trainer.step`` == eager per-param path, for every
  built-in optimizer x {plain, clip_gradient, wd, lr_mult/wd_mult,
  null-grad param riding along}, over >= 3 steps;
* cache behavior: LR-schedule / wd / batch-size changes do NOT recompile,
  shape changes do; exactly one compiled executable dispatched per step
  after warmup (profiler compile/hit counters);
* fallback matrix: SGLD (fresh per-step noise) keeps the eager path;
* the shared-cache bugfixes: closure-backed OpDef signature collision
  (Scale(2.0)/Scale(3.0) repro) and bounded-retry negative caching;
* the MXNET_TPU_LAYERNORM_TWO_PASS escape hatch;
* Module.update() riding the same fused layer.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import _fused, autograd, gluon, profiler
from mxnet_tpu import config as cfg
from mxnet_tpu import optimizer as opt


def _make_params(shapes, seed=0, mults=False, with_null=False):
    rng = np.random.RandomState(seed)
    params = []
    for i, shp in enumerate(shapes):
        p = gluon.Parameter("p%d_weight" % i, shape=shp)
        if mults and i == 0:
            p.lr_mult, p.wd_mult = 0.5, 2.0
        p.initialize()
        p.set_data(mx.nd.array(rng.randn(*shp).astype(np.float32)))
        params.append(p)
    if with_null:
        p = gluon.Parameter("frozen_weight", shape=(3,), grad_req="null")
        p.initialize()
        p.set_data(mx.nd.array(np.ones(3, np.float32)))
        params.append(p)
    return params


def _run_steps(opt_name, opt_kwargs, fused, steps=3, mults=False,
               with_null=True, shapes=((4, 5), (7,), (2, 3, 2))):
    cfg.set("MXNET_TPU_FUSED_TRAINER", fused)
    try:
        params = _make_params(shapes, mults=mults, with_null=with_null)
        live = [p for p in params if p.grad_req != "null"]
        kw = dict(opt_kwargs)
        kw.setdefault("learning_rate", 0.1)
        trainer = gluon.Trainer(params, opt_name, kw)
        rng = np.random.RandomState(99)
        for _ in range(steps):
            for p in live:
                p.grad()[:] = mx.nd.array(
                    rng.randn(*p.shape).astype(np.float32))
            trainer.step(batch_size=2)
        return [p.data().asnumpy() for p in params], trainer
    finally:
        cfg.reset("MXNET_TPU_FUSED_TRAINER")


OPTIMIZERS = [
    ("sgd", {}),
    ("sgd", {"momentum": 0.9}),
    ("nag", {"momentum": 0.9}),
    ("adam", {}),
    ("adagrad", {}),
    ("rmsprop", {}),
    ("rmsprop", {"centered": True}),
    ("adadelta", {}),
    ("ftrl", {}),
    ("adamax", {}),
    ("nadam", {}),
    ("dcasgd", {"momentum": 0.9}),
    ("test", {}),
]

VARIANTS = [
    {},
    {"clip_gradient": 0.05},
    # non-positive threshold means "clipping disabled" in the eager ops;
    # the fused path must not lift it to an always-on traced threshold
    {"clip_gradient": -1.0},
    {"wd": 0.01},
]


@pytest.mark.parametrize("opt_name,opt_kwargs",
                         OPTIMIZERS, ids=lambda v: str(v))
def test_fused_parity(opt_name, opt_kwargs):
    for variant in VARIANTS:
        kw = dict(opt_kwargs, **variant)
        c0 = profiler.get_counter("trainer_step_compile")
        h0 = profiler.get_counter("trainer_step_cache_hit")
        got, _ = _run_steps(opt_name, kw, fused=True)
        # engaged every step: one compile OR hit per step (the wd variant
        # legitimately HITS the plain variant's program — wd is dynamic)
        fused_calls = (profiler.get_counter("trainer_step_compile") - c0 +
                       profiler.get_counter("trainer_step_cache_hit") - h0)
        assert fused_calls == 3, \
            "fused path did not engage for %s %s" % (opt_name, kw)
        want, _ = _run_steps(opt_name, kw, fused=False)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-6)


def test_fused_parity_lr_scheduler_boundary():
    """The eager loop reads the scheduler BEFORE advancing num_update, so
    at a boundary the step's first param still sees the old lr; the fused
    per-param lr vector must reproduce that sequence exactly."""
    from mxnet_tpu import lr_scheduler

    def run(fused):
        cfg.set("MXNET_TPU_FUSED_TRAINER", fused)
        try:
            params = _make_params([(4, 3), (6,)], seed=11)
            sched = lr_scheduler.MultiFactorScheduler(step=[2, 4],
                                                      factor=0.5)
            trainer = gluon.Trainer(
                params, "sgd", {"learning_rate": 0.2, "momentum": 0.9,
                                "lr_scheduler": sched})
            rng = np.random.RandomState(7)
            for _ in range(6):
                for p in params:
                    p.grad()[:] = mx.nd.array(
                        rng.randn(*p.shape).astype(np.float32))
                trainer.step(batch_size=2)
            return [p.data().asnumpy() for p in params]
        finally:
            cfg.reset("MXNET_TPU_FUSED_TRAINER")

    for g, w in zip(run(True), run(False)):
        np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-6)


def test_fused_parity_lr_wd_mult():
    got, _ = _run_steps("sgd", {"momentum": 0.9, "wd": 0.01}, fused=True,
                        mults=True)
    want, _ = _run_steps("sgd", {"momentum": 0.9, "wd": 0.01}, fused=False,
                         mults=True)
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-6)


def test_fused_parity_multi_precision_sgd():
    def run(fused):
        cfg.set("MXNET_TPU_FUSED_TRAINER", fused)
        try:
            rng = np.random.RandomState(0)
            p = gluon.Parameter("w_weight", shape=(8, 4), dtype=np.float16)
            p.initialize()
            p.set_data(mx.nd.array(rng.randn(8, 4).astype(np.float16)))
            trainer = gluon.Trainer(
                [p], "sgd", {"learning_rate": 0.1, "momentum": 0.9,
                             "multi_precision": True})
            for _ in range(3):
                p.grad()[:] = mx.nd.array(
                    rng.randn(8, 4).astype(np.float16))
                trainer.step(2)
            return p.data().asnumpy()
        finally:
            cfg.reset("MXNET_TPU_FUSED_TRAINER")

    np.testing.assert_allclose(run(True), run(False), rtol=1e-3, atol=1e-3)


def test_fused_keeps_f16_dtype_without_multi_precision():
    """Hypers enter as weak-typed python scalars: f16 weights/states must
    stay f16 through the fused step (a strong f32 lr array would promote
    them and recompile every step)."""
    cfg.set("MXNET_TPU_FUSED_TRAINER", True)
    try:
        p = gluon.Parameter("w_weight", shape=(4, 4), dtype=np.float16)
        p.initialize()
        trainer = gluon.Trainer([p], "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9})
        c0 = profiler.get_counter("trainer_step_compile")
        for _ in range(3):
            p.grad()[:] = mx.nd.array(np.ones((4, 4), np.float16))
            trainer.step(2)
        assert p.data().dtype == np.float16
        mom = trainer._updaters.states[0]
        assert mom.dtype == np.float16
        assert profiler.get_counter("trainer_step_compile") == c0 + 1
    finally:
        cfg.reset("MXNET_TPU_FUSED_TRAINER")


def test_fused_matches_update_counts_and_states():
    _, tr_f = _run_steps("adam", {}, fused=True)
    _, tr_e = _run_steps("adam", {}, fused=False)
    assert tr_f._optimizer.num_update == tr_e._optimizer.num_update == 3
    assert tr_f._optimizer._index_update_count == \
        tr_e._optimizer._index_update_count
    sf, se = tr_f._updaters.states, tr_e._updaters.states
    assert set(sf) == set(se)
    for k in sf:
        for a, b in zip(sf[k], se[k]):
            np.testing.assert_allclose(a.asnumpy(), b.asnumpy(),
                                       rtol=1e-4, atol=1e-6)


def test_sgld_falls_back_to_eager():
    c0 = profiler.get_counter("trainer_step_compile")
    got, _ = _run_steps("sgld", {}, fused=True)
    assert profiler.get_counter("trainer_step_compile") == c0
    assert all(np.isfinite(g).all() for g in got)


def test_one_executable_per_step_after_warmup():
    params = _make_params([(8, 8), (8,)], seed=3)
    trainer = gluon.Trainer(params, "adam", {"learning_rate": 0.01})
    for p in params:
        p.grad()[:] = mx.nd.array(
            np.random.RandomState(5).randn(*p.shape).astype(np.float32))
    trainer.step(2)   # warmup: the one compile
    c0 = profiler.get_counter("trainer_step_compile")
    h0 = profiler.get_counter("trainer_step_cache_hit")
    for _ in range(5):
        trainer.step(2)
    assert profiler.get_counter("trainer_step_compile") == c0
    assert profiler.get_counter("trainer_step_cache_hit") == h0 + 5


def test_lr_schedule_change_does_not_recompile():
    params = _make_params([(6, 4)], seed=4)
    trainer = gluon.Trainer(params, "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9,
                             "clip_gradient": 1.0, "wd": 0.001})
    params[0].grad()[:] = mx.nd.array(np.ones((6, 4), np.float32))
    trainer.step(2)
    c0 = profiler.get_counter("trainer_step_compile")
    h0 = profiler.get_counter("trainer_step_cache_hit")
    # every per-step dynamic hyper: lr, wd, clip value, rescale (batch)
    for lr in (0.05, 0.01, 0.002):
        trainer.set_learning_rate(lr)
        trainer.step(2)
    trainer._optimizer.wd = 0.01
    trainer._optimizer.clip_gradient = 0.5
    trainer.step(2)
    trainer.step(batch_size=7)
    assert profiler.get_counter("trainer_step_compile") == c0
    assert profiler.get_counter("trainer_step_cache_hit") == h0 + 5
    # structural changes DO recompile: clip presence flips the program
    trainer._optimizer.clip_gradient = None
    trainer.step(2)
    assert profiler.get_counter("trainer_step_compile") == c0 + 1


def test_shape_change_recompiles():
    c0 = profiler.get_counter("trainer_step_compile")
    _run_steps("sgd", {}, fused=True, steps=1, with_null=False,
               shapes=((5, 5),))
    _run_steps("sgd", {}, fused=True, steps=1, with_null=False,
               shapes=((6, 5),))
    assert profiler.get_counter("trainer_step_compile") == c0 + 2


def test_trainer_save_load_states_roundtrip_with_fused(tmp_path):
    _, trainer = _run_steps("adam", {}, fused=True)
    fname = str(tmp_path / "opt.states")
    trainer.save_states(fname)
    _, trainer2 = _run_steps("adam", {}, fused=True, steps=1)
    trainer2.load_states(fname)
    def as_np(v):
        return v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)

    s1, s2 = trainer._updaters.states, trainer2._updaters.states
    assert set(s1) == set(s2)
    for k in s1:
        for a, b in zip(s1[k], s2[k]):
            np.testing.assert_allclose(as_np(a), as_np(b))
    # training must continue after a load (states rewrapped as NDArray)
    for p in trainer2._params:
        if p.grad_req != "null":
            p.grad()[:] = mx.nd.array(np.ones(p.shape, np.float32))
    trainer2.step(batch_size=2)


def test_custom_optimizer_uses_generic_fused_path():
    @opt.register
    class MyPlainSGD(opt.Optimizer):
        def create_state(self, index, weight):
            return None

        def update(self, index, weight, grad, state):
            lr = self._get_lr(index)
            self._update_count(index)
            weight -= lr * grad * self.rescale_grad

    c0 = profiler.get_counter("trainer_step_compile")
    got, _ = _run_steps("myplainsgd", {}, fused=True)
    assert profiler.get_counter("trainer_step_compile") == c0 + 1
    want, _ = _run_steps("myplainsgd", {}, fused=False)
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-6)


def test_stateful_custom_optimizer_falls_back_to_eager():
    """A custom optimizer keeping per-step state on the instance (warmup
    counter) cannot be replayed functionally — the fused layer must
    detect the impure update() and pin it to the eager path instead of
    silently training with a frozen value."""
    @opt.register
    class WarmupSGD(opt.Optimizer):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            self.t = 0

        def create_state(self, index, weight):
            return None

        def update(self, index, weight, grad, state):
            self.t += 1
            lr = self._get_lr(index) * min(1.0, self.t / 3.0)
            self._update_count(index)
            weight -= lr * grad * self.rescale_grad

    c0 = profiler.get_counter("trainer_step_compile")
    f0 = profiler.get_counter("trainer_step_compile_failed")
    got, _ = _run_steps("warmupsgd", {}, fused=True, steps=5)
    # must NOT have produced a cached fused program, and must pay the
    # failed trace exactly ONCE (instance pinned to eager afterwards —
    # the evolving warmup counter lands in the sig, so a per-sig
    # negative cache alone would re-trace every step)
    assert profiler.get_counter("trainer_step_compile") == c0
    assert profiler.get_counter("trainer_step_compile_failed") == f0 + 1
    want, _ = _run_steps("warmupsgd", {}, fused=False, steps=5)
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=1e-5, atol=1e-7)


def test_module_update_uses_fused_step():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu(0))
    mod.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    batch = mx.io.DataBatch(data=[mx.nd.array(np.random.rand(4, 6))],
                            label=[mx.nd.array(np.zeros(4))])
    c0 = profiler.get_counter("trainer_step_compile")
    mod.forward(batch, is_train=True)
    mod.backward()
    mod.update()
    mod.forward(batch, is_train=True)
    mod.backward()
    mod.update()
    assert profiler.get_counter("trainer_step_compile") == c0 + 1
    out = mod.get_outputs()[0].asnumpy()
    assert np.isfinite(out).all()


# ------------------------------------------------------- shared cache fixes


def test_function_closure_no_collision():
    """advisor HIGH: two same-shaped closure-backed Functions must not
    replay each other's compiled backward (Scale(2.0)/Scale(3.0))."""

    class Scale(autograd.Function):
        def __init__(self, s):
            self.s = s

        def forward(self, x):
            return x * self.s

        def backward(self, dy):
            return dy * self.s

    x = mx.nd.array([1.0, 1.0])
    x.attach_grad()
    with autograd.record():
        y = Scale(2.0)(x)
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2.0, 2.0])
    with autograd.record():
        y = Scale(3.0)(x)
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [3.0, 3.0])


def test_compile_cache_bounded_retry():
    """advisor low: a transient first failure must not pin a structure to
    eager forever; structural untraceability must."""
    c = _fused.CompileCache("unit_retry")
    sig = ("some", "structure")
    assert not c.should_skip(sig)
    c.mark_failed(sig)                      # transient #1
    assert not c.should_skip(sig)           # retried
    c.mark_failed(sig)                      # transient #2
    assert not c.should_skip(sig)
    c.mark_failed(sig)                      # transient #3 -> give up
    assert c.should_skip(sig)
    # success on another sig clears its failure history
    sig2 = ("other",)
    c.mark_failed(sig2)
    c.put(sig2, lambda: None)
    assert not c.should_skip(sig2)
    # structural failures skip immediately
    sig3 = ("structural",)
    c.mark_failed(sig3, permanent=True)
    assert c.should_skip(sig3)


def test_structural_failure_classification():
    import jax
    assert _fused.structural_failure(_fused.Uncacheable("x"))
    assert _fused.structural_failure(
        jax.errors.TracerBoolConversionError.__new__(
            jax.errors.TracerBoolConversionError))
    assert not _fused.structural_failure(RuntimeError("RESOURCE_EXHAUSTED"))


def test_fn_token_stable_and_distinct():
    f = lambda x: x          # noqa: E731
    g = lambda x: x          # noqa: E731
    assert _fused.fn_token(f) == _fused.fn_token(f)
    assert _fused.fn_token(f) != _fused.fn_token(g)


# ------------------------------------------------------- layernorm knob


def test_layernorm_two_pass_flag():
    rng = np.random.RandomState(0)
    # large common offset: one-pass E[x^2]-E[x]^2 cancels catastrophically
    # in f32, the two-pass form stays accurate
    x = (1e4 + rng.randn(8, 256)).astype(np.float32)
    gamma = np.ones(256, np.float32)
    beta = np.zeros(256, np.float32)

    x64 = x.astype(np.float64)
    mean = x64.mean(axis=-1, keepdims=True)
    var = ((x64 - mean) ** 2).mean(axis=-1, keepdims=True)
    ref = ((x64 - mean) / np.sqrt(var + 1e-5)).astype(np.float64)

    def run():
        out = mx.nd.LayerNorm(mx.nd.array(x), mx.nd.array(gamma),
                              mx.nd.array(beta))
        return out.asnumpy().astype(np.float64)

    err_one_pass = np.abs(run() - ref).max()
    cfg.set("MXNET_TPU_LAYERNORM_TWO_PASS", True)
    try:
        err_two_pass = np.abs(run() - ref).max()
    finally:
        cfg.reset("MXNET_TPU_LAYERNORM_TWO_PASS")
    assert err_two_pass < 0.01, err_two_pass
    assert err_two_pass < err_one_pass

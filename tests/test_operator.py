"""Per-op forward correctness vs numpy oracle (modeled on reference
tests/python/unittest/test_operator.py — SURVEY.md §4)."""
import numpy as np
import pytest

import mxnet_tpu as mx

RTOL, ATOL = 1e-5, 1e-6


def _nd(x):
    return mx.nd.array(np.asarray(x, dtype="float32"))


def test_unary_ops():
    x = np.random.rand(3, 4).astype("f") + 0.5
    nd = _nd(x)
    cases = {
        "exp": np.exp, "log": np.log, "sqrt": np.sqrt, "square": np.square,
        "abs": np.abs, "sign": np.sign, "floor": np.floor, "ceil": np.ceil,
        "sin": np.sin, "cos": np.cos, "tanh": np.tanh,
        "log1p": np.log1p, "expm1": np.expm1, "rsqrt": lambda v: 1 / np.sqrt(v),
        "reciprocal": lambda v: 1 / v, "negative": lambda v: -v,
    }
    for name, fn in cases.items():
        out = getattr(mx.nd, name)(nd).asnumpy()
        np.testing.assert_allclose(out, fn(x), rtol=1e-4, atol=1e-5, err_msg=name)


def test_activation_types():
    x = np.random.randn(4, 5).astype("f")
    nd = _nd(x)
    np.testing.assert_allclose(
        mx.nd.Activation(nd, act_type="relu").asnumpy(), np.maximum(x, 0), rtol=RTOL)
    np.testing.assert_allclose(
        mx.nd.Activation(nd, act_type="sigmoid").asnumpy(), 1 / (1 + np.exp(-x)),
        rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(
        mx.nd.Activation(nd, act_type="tanh").asnumpy(), np.tanh(x), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(
        mx.nd.Activation(nd, act_type="softrelu").asnumpy(),
        np.log1p(np.exp(-np.abs(x))) + np.maximum(x, 0), rtol=1e-4, atol=1e-6)


def test_leaky_relu():
    x = np.random.randn(3, 4).astype("f")
    out = mx.nd.LeakyReLU(_nd(x), act_type="leaky", slope=0.1).asnumpy()
    np.testing.assert_allclose(out, np.where(x >= 0, x, 0.1 * x), rtol=RTOL)
    out = mx.nd.LeakyReLU(_nd(x), act_type="elu", slope=1.0).asnumpy()
    np.testing.assert_allclose(out, np.where(x >= 0, x, np.expm1(x)), rtol=1e-4, atol=1e-6)


def test_softmax_ops():
    x = np.random.randn(4, 10).astype("f")
    def np_softmax(v, axis=-1):
        e = np.exp(v - v.max(axis=axis, keepdims=True))
        return e / e.sum(axis=axis, keepdims=True)
    np.testing.assert_allclose(
        mx.nd.softmax(_nd(x)).asnumpy(), np_softmax(x), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(
        mx.nd.log_softmax(_nd(x)).asnumpy(), np.log(np_softmax(x)), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        mx.nd.SoftmaxActivation(_nd(x)).asnumpy(), np_softmax(x), rtol=1e-4, atol=1e-6)


def test_fully_connected():
    x = np.random.rand(5, 8).astype("f")
    w = np.random.rand(3, 8).astype("f")
    b = np.random.rand(3).astype("f")
    out = mx.nd.FullyConnected(_nd(x), _nd(w), _nd(b), num_hidden=3).asnumpy()
    np.testing.assert_allclose(out, x @ w.T + b, rtol=1e-4, atol=1e-5)
    out = mx.nd.FullyConnected(_nd(x), _nd(w), no_bias=True, num_hidden=3).asnumpy()
    np.testing.assert_allclose(out, x @ w.T, rtol=1e-4, atol=1e-5)
    # 4-d input flattens
    x4 = np.random.rand(5, 2, 2, 2).astype("f")
    out = mx.nd.FullyConnected(_nd(x4), _nd(w), _nd(b), num_hidden=3).asnumpy()
    np.testing.assert_allclose(out, x4.reshape(5, 8) @ w.T + b, rtol=1e-4, atol=1e-5)


def _np_conv2d(x, w, b, stride, pad):
    n, c, h, ww = x.shape
    f, _, kh, kw = w.shape
    sh, sw = stride
    xp = np.pad(x, ((0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1])))
    oh = (h + 2 * pad[0] - kh) // sh + 1
    ow = (ww + 2 * pad[1] - kw) // sw + 1
    out = np.zeros((n, f, oh, ow), dtype=x.dtype)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw]
            out[:, :, i, j] = np.einsum("nchw,fchw->nf", patch, w)
    if b is not None:
        out += b.reshape(1, -1, 1, 1)
    return out


def test_convolution():
    x = np.random.rand(2, 3, 8, 8).astype("f")
    w = np.random.rand(4, 3, 3, 3).astype("f")
    b = np.random.rand(4).astype("f")
    out = mx.nd.Convolution(_nd(x), _nd(w), _nd(b), kernel=(3, 3), num_filter=4,
                            stride=(1, 1), pad=(1, 1)).asnumpy()
    exp = _np_conv2d(x, w, b, (1, 1), (1, 1))
    np.testing.assert_allclose(out, exp, rtol=1e-3, atol=1e-4)
    out = mx.nd.Convolution(_nd(x), _nd(w), kernel=(3, 3), num_filter=4,
                            stride=(2, 2), no_bias=True).asnumpy()
    exp = _np_conv2d(x, w, None, (2, 2), (0, 0))
    np.testing.assert_allclose(out, exp, rtol=1e-3, atol=1e-4)


def test_pooling():
    x = np.random.rand(2, 3, 6, 6).astype("f")
    out = mx.nd.Pooling(_nd(x), kernel=(2, 2), stride=(2, 2), pool_type="max").asnumpy()
    exp = x.reshape(2, 3, 3, 2, 3, 2).max(axis=(3, 5))
    np.testing.assert_allclose(out, exp, rtol=RTOL)
    out = mx.nd.Pooling(_nd(x), kernel=(2, 2), stride=(2, 2), pool_type="avg").asnumpy()
    exp = x.reshape(2, 3, 3, 2, 3, 2).mean(axis=(3, 5))
    np.testing.assert_allclose(out, exp, rtol=1e-4, atol=1e-6)
    out = mx.nd.Pooling(_nd(x), global_pool=True, pool_type="max").asnumpy()
    np.testing.assert_allclose(out, x.max(axis=(2, 3), keepdims=True), rtol=RTOL)


def test_batchnorm_train_and_eval():
    np.random.seed(0)
    x = np.random.rand(4, 3, 2, 2).astype("f")
    gamma = np.ones(3, dtype="f")
    beta = np.zeros(3, dtype="f")
    mm = mx.nd.zeros((3,))
    mv = mx.nd.ones((3,))
    with mx.autograd.record(train_mode=True):
        out = mx.nd.BatchNorm(_nd(x), _nd(gamma), _nd(beta), mm, mv,
                              fix_gamma=False, momentum=0.9)
    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    exp = (x - mean.reshape(1, 3, 1, 1)) / np.sqrt(var.reshape(1, 3, 1, 1) + 1e-3)
    np.testing.assert_allclose(out.asnumpy(), exp, rtol=1e-3, atol=1e-4)
    # aux states updated
    np.testing.assert_allclose(mm.asnumpy(), 0.1 * mean, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(mv.asnumpy(), 0.9 + 0.1 * var, rtol=1e-4, atol=1e-5)
    # eval mode uses moving stats
    out_eval = mx.nd.BatchNorm(_nd(x), _nd(gamma), _nd(beta), mm, mv,
                               fix_gamma=False)
    exp_eval = (x - mm.asnumpy().reshape(1, 3, 1, 1)) / np.sqrt(
        mv.asnumpy().reshape(1, 3, 1, 1) + 1e-3)
    np.testing.assert_allclose(out_eval.asnumpy(), exp_eval, rtol=1e-3, atol=1e-4)


def test_dropout_modes():
    x = np.ones((100, 100), dtype="f")
    # eval = identity
    out = mx.nd.Dropout(_nd(x), p=0.5).asnumpy()
    np.testing.assert_allclose(out, x)
    with mx.autograd.record(train_mode=True):
        out = mx.nd.Dropout(_nd(x), p=0.5).asnumpy()
    frac = (out == 0).mean()
    assert 0.4 < frac < 0.6
    kept = out[out != 0]
    np.testing.assert_allclose(kept, 2.0, rtol=1e-5)


def test_reductions():
    x = np.random.rand(3, 4, 5).astype("f")
    nd = _nd(x)
    np.testing.assert_allclose(mx.nd.sum(nd).asnumpy(), x.sum(), rtol=1e-4)
    np.testing.assert_allclose(
        mx.nd.sum(nd, axis=1).asnumpy(), x.sum(axis=1), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        mx.nd.sum(nd, axis=(0, 2), keepdims=True).asnumpy(),
        x.sum(axis=(0, 2), keepdims=True), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        mx.nd.sum(nd, axis=1, exclude=True).asnumpy(), x.sum(axis=(0, 2)),
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(mx.nd.mean(nd, axis=0).asnumpy(), x.mean(axis=0),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(mx.nd.max(nd, axis=2).asnumpy(), x.max(axis=2), rtol=RTOL)
    np.testing.assert_allclose(mx.nd.argmax(nd, axis=1).asnumpy(),
                               x.argmax(axis=1), rtol=RTOL)
    np.testing.assert_allclose(mx.nd.norm(nd).asnumpy(),
                               np.sqrt((x ** 2).sum()), rtol=1e-4)


def test_matrix_ops():
    x = np.random.rand(2, 3, 4).astype("f")
    nd = _nd(x)
    np.testing.assert_allclose(mx.nd.transpose(nd).asnumpy(), x.T, rtol=RTOL)
    np.testing.assert_allclose(
        mx.nd.transpose(nd, axes=(1, 0, 2)).asnumpy(), x.transpose(1, 0, 2), rtol=RTOL)
    np.testing.assert_allclose(mx.nd.swapaxes(nd, dim1=0, dim2=2).asnumpy(),
                               x.swapaxes(0, 2), rtol=RTOL)
    np.testing.assert_allclose(mx.nd.expand_dims(nd, axis=1).asnumpy(),
                               x[:, None], rtol=RTOL)
    np.testing.assert_allclose(mx.nd.flip(nd, axis=2).asnumpy(),
                               x[:, :, ::-1], rtol=RTOL)
    np.testing.assert_allclose(
        mx.nd.slice(nd, begin=(0, 1, None), end=(2, 3, None)).asnumpy(),
        x[0:2, 1:3, :], rtol=RTOL)
    np.testing.assert_allclose(
        mx.nd.slice_axis(nd, axis=2, begin=1, end=3).asnumpy(), x[:, :, 1:3], rtol=RTOL)
    np.testing.assert_allclose(mx.nd.tile(nd, reps=(1, 2, 1)).asnumpy(),
                               np.tile(x, (1, 2, 1)), rtol=RTOL)
    np.testing.assert_allclose(mx.nd.repeat(nd, repeats=2, axis=1).asnumpy(),
                               np.repeat(x, 2, axis=1), rtol=RTOL)
    np.testing.assert_allclose(mx.nd.clip(nd, a_min=0.2, a_max=0.8).asnumpy(),
                               np.clip(x, 0.2, 0.8), rtol=RTOL)


def test_batch_dot():
    a = np.random.rand(4, 3, 5).astype("f")
    b = np.random.rand(4, 5, 2).astype("f")
    out = mx.nd.batch_dot(_nd(a), _nd(b)).asnumpy()
    np.testing.assert_allclose(out, np.einsum("bij,bjk->bik", a, b), rtol=1e-4, atol=1e-5)
    out = mx.nd.batch_dot(_nd(a.transpose(0, 2, 1)), _nd(b), transpose_a=True).asnumpy()
    np.testing.assert_allclose(out, np.einsum("bij,bjk->bik", a, b), rtol=1e-4, atol=1e-5)


def test_embedding_take_pick_onehot():
    w = np.random.rand(10, 4).astype("f")
    idx = np.array([1, 3, 5], dtype="f")
    out = mx.nd.Embedding(_nd(idx), _nd(w), input_dim=10, output_dim=4).asnumpy()
    np.testing.assert_allclose(out, w[[1, 3, 5]], rtol=RTOL)
    out = mx.nd.take(_nd(w), _nd(idx)).asnumpy()
    np.testing.assert_allclose(out, w[[1, 3, 5]], rtol=RTOL)
    data = np.random.rand(3, 5).astype("f")
    pidx = np.array([0, 2, 4], dtype="f")
    out = mx.nd.pick(_nd(data), _nd(pidx)).asnumpy()
    np.testing.assert_allclose(out, data[np.arange(3), [0, 2, 4]], rtol=RTOL)
    out = mx.nd.one_hot(_nd(idx), depth=10).asnumpy()
    exp = np.zeros((3, 10), dtype="f")
    exp[np.arange(3), [1, 3, 5]] = 1
    np.testing.assert_allclose(out, exp, rtol=RTOL)


def test_ordering_ops():
    x = np.random.rand(4, 6).astype("f")
    np.testing.assert_allclose(mx.nd.sort(_nd(x), axis=1).asnumpy(),
                               np.sort(x, axis=1), rtol=RTOL)
    np.testing.assert_allclose(
        mx.nd.sort(_nd(x), axis=1, is_ascend=False).asnumpy(),
        -np.sort(-x, axis=1), rtol=RTOL)
    np.testing.assert_allclose(mx.nd.argsort(_nd(x), axis=1).asnumpy(),
                               np.argsort(x, axis=1), rtol=RTOL)
    vals = mx.nd.topk(_nd(x), k=2, ret_typ="value").asnumpy()
    np.testing.assert_allclose(vals, -np.sort(-x, axis=1)[:, :2], rtol=RTOL)
    idxs = mx.nd.topk(_nd(x), k=1).asnumpy()
    np.testing.assert_allclose(idxs.ravel(), x.argmax(axis=1), rtol=RTOL)


def test_where():
    cond = np.array([[1, 0], [0, 1]], dtype="f")
    x = np.ones((2, 2), dtype="f")
    y = np.zeros((2, 2), dtype="f")
    out = mx.nd.where(_nd(cond), _nd(x), _nd(y)).asnumpy()
    np.testing.assert_allclose(out, cond)


def test_sequence_ops():
    # TNC layout: T=4, N=2, C=3
    x = np.random.rand(4, 2, 3).astype("f")
    lengths = np.array([2, 4], dtype="f")
    out = mx.nd.SequenceLast(_nd(x), _nd(lengths), use_sequence_length=True).asnumpy()
    np.testing.assert_allclose(out[0], x[1, 0], rtol=RTOL)
    np.testing.assert_allclose(out[1], x[3, 1], rtol=RTOL)
    out = mx.nd.SequenceMask(_nd(x), _nd(lengths), use_sequence_length=True,
                             value=-1.0).asnumpy()
    np.testing.assert_allclose(out[:2, 0], x[:2, 0], rtol=RTOL)
    assert (out[2:, 0] == -1).all()
    np.testing.assert_allclose(out[:, 1], x[:, 1], rtol=RTOL)
    out = mx.nd.SequenceReverse(_nd(x), _nd(lengths), use_sequence_length=True).asnumpy()
    np.testing.assert_allclose(out[0, 0], x[1, 0], rtol=RTOL)
    np.testing.assert_allclose(out[1, 0], x[0, 0], rtol=RTOL)
    np.testing.assert_allclose(out[2:, 0], x[2:, 0], rtol=RTOL)
    np.testing.assert_allclose(out[:, 1], x[::-1, 1], rtol=RTOL)


def test_random_ops():
    mx.random.seed(42)
    a = mx.nd.random_uniform(low=0, high=1, shape=(1000,)).asnumpy()
    assert 0 <= a.min() and a.max() <= 1
    assert abs(a.mean() - 0.5) < 0.05
    mx.random.seed(42)
    b = mx.nd.random_uniform(low=0, high=1, shape=(1000,)).asnumpy()
    np.testing.assert_allclose(a, b)  # reproducible
    n = mx.nd.random_normal(loc=2.0, scale=0.5, shape=(2000,)).asnumpy()
    assert abs(n.mean() - 2.0) < 0.1
    assert abs(n.std() - 0.5) < 0.1


def test_sample_multinomial():
    mx.random.seed(0)
    p = mx.nd.array([[0.0, 0.0, 1.0], [1.0, 0.0, 0.0]])
    s = mx.nd.sample_multinomial(p).asnumpy()
    np.testing.assert_allclose(s, [2, 0])


def test_stochastic_activation_pruning():
    mx.random.seed(0)
    act = np.random.rand(8, 100).astype("f") + 1.0
    prob = np.abs(act) / np.abs(act).sum(axis=1, keepdims=True)
    out = mx.nd.stochastic_activation_pruning(_nd(act), _nd(prob), frac=0.5).asnumpy()
    # zeros where pruned; kept values rescaled upward
    assert (out == 0).any()
    kept = out != 0
    assert kept.sum() > 0
    # kept entries equal act * weight, weight >= 1
    ratio = out[kept] / act[kept]
    assert (ratio >= 1.0 - 1e-5).all()
    # frac=1.0 keeps expectation approximately unbiased
    out_full = mx.nd.stochastic_activation_pruning(_nd(act), _nd(prob), frac=1.0).asnumpy()
    assert (out_full != 0).mean() > 0.3


def test_loss_head_forwards():
    x = np.random.randn(4, 5).astype("f")
    label = np.array([0, 1, 2, 3], dtype="f")
    out = mx.nd.SoftmaxOutput(_nd(x), _nd(label)).asnumpy()
    e = np.exp(x - x.max(axis=1, keepdims=True))
    np.testing.assert_allclose(out, e / e.sum(axis=1, keepdims=True), rtol=1e-4, atol=1e-6)
    out = mx.nd.LinearRegressionOutput(_nd(x), _nd(x)).asnumpy()
    np.testing.assert_allclose(out, x, rtol=RTOL)
    out = mx.nd.LogisticRegressionOutput(_nd(x), _nd(x)).asnumpy()
    np.testing.assert_allclose(out, 1 / (1 + np.exp(-x)), rtol=1e-4, atol=1e-6)
    out = mx.nd.MakeLoss(_nd(x)).asnumpy()
    np.testing.assert_allclose(out, x, rtol=RTOL)


def test_lrn():
    x = np.random.rand(2, 5, 3, 3).astype("f")
    out = mx.nd.LRN(_nd(x), nsize=3, alpha=1e-4, beta=0.75, knorm=2.0).asnumpy()
    # oracle
    sq = x ** 2
    exp = np.zeros_like(x)
    for c in range(5):
        lo, hi = max(0, c - 1), min(5, c + 2)
        ssum = sq[:, lo:hi].sum(axis=1)
        exp[:, c] = x[:, c] / ((2.0 + 1e-4 * ssum / 3) ** 0.75)
    np.testing.assert_allclose(out, exp, rtol=1e-4, atol=1e-5)


def test_l2_normalization():
    x = np.random.rand(3, 4).astype("f")
    out = mx.nd.L2Normalization(_nd(x), mode="instance").asnumpy()
    np.testing.assert_allclose(
        out, x / np.sqrt((x ** 2).sum(axis=1, keepdims=True) + 1e-10), rtol=1e-4)


def test_cast_and_bf16():
    x = np.random.rand(4, 4).astype("f")
    out = mx.nd.Cast(_nd(x), dtype="bfloat16")
    assert str(out.dtype) == "bfloat16"
    back = out.astype("float32").asnumpy()
    np.testing.assert_allclose(back, x, rtol=1e-2)


def test_deconvolution_shape():
    x = np.random.rand(1, 4, 5, 5).astype("f")
    w = np.random.rand(4, 6, 3, 3).astype("f")  # (C_in, F, kh, kw)
    out = mx.nd.Deconvolution(_nd(x), _nd(w), kernel=(3, 3), stride=(2, 2),
                              num_filter=6, no_bias=True)
    assert out.shape == (1, 6, 11, 11)  # (5-1)*2 + 3 = 11
    # adjoint check: deconv(x) dot y == x dot conv(y)
    y = np.random.rand(1, 6, 11, 11).astype("f")
    conv_y = mx.nd.Convolution(_nd(y), _nd(w), kernel=(3, 3), stride=(2, 2),
                               num_filter=4, no_bias=True).asnumpy()
    lhs = (out.asnumpy() * y).sum()
    rhs = (x * conv_y).sum()
    np.testing.assert_allclose(lhs, rhs, rtol=1e-3)

"""Elastic training + deterministic fault injection (ISSUE 10).

Three contracts under test:

* **supervisor** (``mxnet_tpu.elastic``) — exit 143 and crashes restart
  the child bounded times with backoff; exit 0 ends the run; the world
  schedule rewrites the child's device count per attempt and counts
  reshards; a clean child never restarts.
* **fault harness** (``mxnet_tpu.faults``) — the
  ``MXNET_TPU_FAULTS=<site>@<nth>[:kind]`` grammar, arrival counting,
  the legacy ``MXNET_TPU_CKPT_TEST_CRASH`` alias, and zero-cost when
  disarmed.
* **fault matrix** — every recovery path driven under an injected
  fault: transient writer IO errors are retried and the save still
  lands (``ckpt_write_retry``), persistent errors surface at close,
  read-side bit-rot/truncation falls back to the previous checkpoint,
  a SIGTERM/SIGKILL'd fit resumes to the SAME trained params as an
  uninterrupted run, and an injected serve.submit failure hurts one
  request only.
"""
import errno
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import elastic, faults, profiler
from mxnet_tpu.checkpoint import (CheckpointConfig, CheckpointManager,
                                  CheckpointNotFound, list_checkpoints,
                                  load_latest, write_checkpoint)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm():
    faults.clear()
    yield
    faults.clear()


# ------------------------------------------------------------ the grammar

def test_faults_grammar_and_arrival_counting():
    faults.install("x.site@2:raise")
    faults.fire("x.site")                       # arrival 1: silent
    with pytest.raises(faults.FaultInjected):
        faults.fire("x.site")                   # arrival 2: fires
    faults.fire("x.site")                       # arrival 3: silent again


def test_faults_every_arrival_without_nth():
    faults.install("y.site:eio")
    for _ in range(3):
        with pytest.raises(OSError) as ei:
            faults.fire("y.site")
        assert ei.value.errno == errno.EIO


def test_faults_default_kind_comes_from_site():
    faults.install("z.site@1")                  # no kind in the spec
    with pytest.raises(OSError) as ei:
        faults.fire("z.site", default_kind="enospc")
    assert ei.value.errno == errno.ENOSPC


def test_faults_reject_unknown_kind_and_bad_nth():
    with pytest.raises(ValueError):
        faults.install("a.b@1:frobnicate")
    with pytest.raises(ValueError):
        faults.install("a.b@0:eio")
    assert not faults.ARMED                     # bad install arms nothing


def test_faults_disarmed_is_silent_and_counterless():
    assert not faults.ARMED
    before = profiler.get_counter("fault_injected")
    faults.fire("ckpt.arrays_write")            # no spec installed
    assert profiler.get_counter("fault_injected") == before


def test_clear_is_final_against_env_rearming(monkeypatch):
    """A one-shot @nth env fault must not resurrect with fresh arrival
    counts after an explicit clear() (it would fire a second time)."""
    monkeypatch.setenv(faults.ENV, "ckpt.read_manifest@1:bitflip")
    faults.clear()
    assert not faults.armed_or_env()
    assert not faults.ARMED


def test_config_set_routes_through_install():
    from mxnet_tpu import config as cfg
    cfg.set("MXNET_TPU_FAULTS", "q.site@1:raise")
    try:
        assert faults.ARMED
        with pytest.raises(faults.FaultInjected):
            faults.fire("q.site")
        cfg.set("MXNET_TPU_FAULTS", "")
        assert not faults.ARMED
    finally:
        cfg.reset("MXNET_TPU_FAULTS")


def test_install_empty_disarms_against_env_too(monkeypatch):
    """mx.config.set('MXNET_TPU_FAULTS','') must disarm FOR GOOD even
    when the env var is still set — the programmatic override wins and
    armed_or_env() must not resurrect the env spec with fresh counts."""
    monkeypatch.setenv(faults.ENV, "ckpt.read_manifest@1:bitflip")
    faults.install("")
    assert not faults.armed_or_env()
    assert not faults.ARMED


def test_legacy_ckpt_crash_env_maps_to_sigkill_site(tmp_path):
    """MXNET_TPU_CKPT_TEST_CRASH=<point>@<n> still SIGKILLs the writer at
    the n-th arrival (the PR 5 drills keep working unchanged)."""
    child = (
        "import os, sys; sys.path.insert(0, %r); "
        "os.environ['JAX_PLATFORMS']='cpu'; "
        "import numpy as np; "
        "from mxnet_tpu.checkpoint import write_checkpoint; "
        "write_checkpoint(%r, 1, {'x': np.ones(4, np.float32)}); "
        "write_checkpoint(%r, 2, {'x': np.ones(4, np.float32)}); "
        "print('SECOND-SAVE-LANDED')"
        % (REPO, str(tmp_path), str(tmp_path)))
    proc = subprocess.run(
        [sys.executable, "-c", child], capture_output=True, text=True,
        timeout=240,
        env={**os.environ, "PYTHONPATH": "",
             "MXNET_TPU_CKPT_TEST_CRASH": "after_arrays@2"})
    assert proc.returncode == -signal.SIGKILL, \
        proc.stdout + proc.stderr
    assert "SECOND-SAVE-LANDED" not in proc.stdout
    steps = [s for s, _ in list_checkpoints(str(tmp_path))]
    assert steps == [1]


# --------------------------------------------------- writer retry (matrix)

def _mgr(tmp_path, **kw):
    kw.setdefault("retry_backoff", 0.01)
    kw.setdefault("async_save", False)
    return CheckpointManager(CheckpointConfig(str(tmp_path), **kw))


def test_two_transient_failures_still_land_the_save(tmp_path):
    """The satellite contract: EIO then ENOSPC on consecutive attempts,
    and the bounded retry still lands a fully valid checkpoint."""
    faults.install("ckpt.arrays_write@1:eio,ckpt.arrays_write@2:enospc")
    mgr = _mgr(tmp_path, write_retries=3)
    before = profiler.get_counter("ckpt_write_retry")
    mgr.save({"w": np.arange(8, dtype=np.float32)}, {}, step=1)
    mgr.close()
    assert profiler.get_counter("ckpt_write_retry") - before == 2
    path, tensors, _m = load_latest(str(tmp_path))
    assert np.array_equal(tensors["w"], np.arange(8, dtype=np.float32))
    # no torn residue survives the failed attempts
    assert not [n for n in os.listdir(str(tmp_path))
                if n.startswith(".tmp-")]


def test_eintr_is_retried_too(tmp_path):
    faults.install("ckpt.arrays_write@1:eintr")
    mgr = _mgr(tmp_path, write_retries=1)
    mgr.save({"w": np.ones(4, np.float32)}, {}, step=1)
    mgr.close()
    assert list_checkpoints(str(tmp_path))


def test_persistent_failure_exhausts_retries_sync(tmp_path):
    faults.install("ckpt.arrays_write:enospc")       # every arrival
    mgr = _mgr(tmp_path, write_retries=2)
    with pytest.raises(OSError) as ei:
        mgr.save({"w": np.ones(4, np.float32)}, {}, step=1)
    assert ei.value.errno == errno.ENOSPC
    mgr.close()
    assert not list_checkpoints(str(tmp_path))


def test_persistent_failure_surfaces_at_close_async(tmp_path):
    from mxnet_tpu.checkpoint import CheckpointError
    faults.install("ckpt.arrays_write:eio")
    mgr = _mgr(tmp_path, write_retries=1, async_save=True)
    mgr.save({"w": np.ones(4, np.float32)}, {}, step=1)
    mgr.wait()
    with pytest.raises(CheckpointError):
        mgr.close()


def test_non_transient_oserror_is_not_retried(tmp_path, monkeypatch):
    from mxnet_tpu.checkpoint import format as fmt
    calls = [0]
    real = fmt.write_checkpoint

    def boom(*a, **kw):
        calls[0] += 1
        raise OSError(errno.EACCES, "permission denied")

    monkeypatch.setattr(fmt, "write_checkpoint", boom)
    mgr = _mgr(tmp_path, write_retries=3)
    with pytest.raises(OSError):
        mgr.save({"w": np.ones(4, np.float32)}, {}, step=1)
    assert calls[0] == 1
    monkeypatch.setattr(fmt, "write_checkpoint", real)
    mgr.close()


# ------------------------------------------------- read-side bit-rot drill

def test_manifest_bitflip_falls_back_to_previous(tmp_path):
    write_checkpoint(str(tmp_path), 1, {"w": np.full(8, 1.0, np.float32)})
    write_checkpoint(str(tmp_path), 2, {"w": np.full(8, 2.0, np.float32)})
    before = profiler.get_counter("ckpt_load_fallback")
    faults.install("ckpt.read_manifest@1:bitflip")
    path, tensors, _m = load_latest(str(tmp_path))
    assert path.endswith("ckpt-0000000001")
    assert tensors["w"][0] == 1.0
    assert profiler.get_counter("ckpt_load_fallback") - before == 1


def test_arrays_truncation_falls_back_to_previous(tmp_path):
    write_checkpoint(str(tmp_path), 1, {"w": np.full(64, 1.0, np.float32)})
    write_checkpoint(str(tmp_path), 2, {"w": np.full(64, 2.0, np.float32)})
    faults.install("ckpt.read_arrays@1:truncate")
    path, tensors, _m = load_latest(str(tmp_path))
    assert path.endswith("ckpt-0000000001")
    assert tensors["w"][0] == 1.0


def test_all_candidates_rotted_raises_not_found(tmp_path):
    write_checkpoint(str(tmp_path), 1, {"w": np.ones(64, np.float32)})
    faults.install("ckpt.read_arrays:bitflip")       # every arrival
    with pytest.raises(CheckpointNotFound):
        load_latest(str(tmp_path))


# -------------------------------------------- kill-kind fit drills (matrix)

_KILL_CHILD = r"""
import os, sys
sys.path.insert(0, %(repo)r)
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import mxnet_tpu as mx

rng = np.random.RandomState(0)
X = rng.uniform(-1, 1, (64, 16)).astype(np.float32)
Y = rng.randint(0, 8, (64,)).astype(np.float32)
mx.random.seed(7)
sym = mx.sym.SoftmaxOutput(
    mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=8,
                          name="fc1"), name="softmax")
it = mx.io.NDArrayIter(X, Y, batch_size=8)
mod = mx.mod.Module(sym, context=mx.cpu())
cfg = mx.checkpoint.CheckpointConfig(%(base)r, every_n_batches=2,
                                     period_epochs=1)
mod.fit(it, num_epoch=4, optimizer="sgd",
        optimizer_params={"learning_rate": 0.1}, checkpoint=cfg)
print("FINISHED-WITHOUT-FAULT")
"""


def _run_kill_child(base, fault):
    return subprocess.run(
        [sys.executable, "-c",
         _KILL_CHILD % {"repo": REPO, "base": base}],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "PYTHONPATH": "", "MXNET_TPU_FAULTS": fault})


def _resume_and_reference(base):
    """Finish the interrupted run from ``base`` and run the uninterrupted
    twin; returns (resumed, reference) param dicts."""
    rng = np.random.RandomState(0)
    X = rng.uniform(-1, 1, (64, 16)).astype(np.float32)
    Y = rng.randint(0, 8, (64,)).astype(np.float32)

    def fit(resume):
        mx.random.seed(7)
        sym = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=8,
                                  name="fc1"), name="softmax")
        it = mx.io.NDArrayIter(X, Y, batch_size=8)
        mod = mx.mod.Module(sym, context=mx.cpu())
        mod.fit(it, num_epoch=4, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1},
                resume_from=resume)
        arg, aux = mod.get_params()
        w = {k: v.asnumpy().copy() for k, v in arg.items()}
        w.update({k: v.asnumpy().copy() for k, v in aux.items()})
        return w

    return fit(base), fit(None)


@pytest.mark.parametrize("kind,expect_rc", [
    ("sigterm", 143),                 # preemption notice: clean save+143
    ("sigkill", -signal.SIGKILL),     # hard kill between batches
])
def test_fit_batch_kill_then_resume_matches_uninterrupted(
        tmp_path, kind, expect_rc):
    """The matrix acceptance: a fit killed at batch K by either signal
    kind resumes from its checkpoints to the SAME trained params as a
    never-interrupted run (default initializer included — it draws from
    the seeded mx.random chain, so the reference run and the killed run
    start identically)."""
    base = str(tmp_path)
    proc = _run_kill_child(base, "fit.batch@13:%s" % kind)
    assert proc.returncode == expect_rc, proc.stdout + proc.stderr
    assert "FINISHED-WITHOUT-FAULT" not in proc.stdout
    assert list_checkpoints(base), "no checkpoint survived the kill"
    resumed, reference = _resume_and_reference(base)
    assert set(resumed) == set(reference)
    for k in sorted(reference):
        np.testing.assert_array_equal(resumed[k], reference[k], err_msg=k)


# --------------------------------------------------------- serve (matrix)

def test_serve_submit_fault_hurts_one_request_only():
    from mxnet_tpu import serve
    from mxnet_tpu.gluon import nn
    net = nn.Sequential()
    net.add(nn.Dense(8))
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(np.zeros((1, 16), np.float32)))
    srv = serve.InferenceServer(net, max_batch_size=8,
                                name="serve_t_fault")
    try:
        x = np.ones(16, np.float32)
        ok1 = srv.submit(x).result(timeout=60)
        faults.install("serve.submit@1:raise")
        with pytest.raises(faults.FaultInjected):
            srv.submit(x)
        ok2 = srv.submit(x).result(timeout=60)   # server still serves
        assert np.array_equal(np.asarray(ok1), np.asarray(ok2))
    finally:
        srv.close()


# ----------------------------------------------------------- the supervisor

_OK_AFTER = r"""
import json, os, sys
state = %(state)r
n = 0
if os.path.exists(state):
    n = json.load(open(state))["runs"]
json.dump({"runs": n + 1,
           "attempt": os.environ.get("MXNET_TPU_ELASTIC_ATTEMPT"),
           "resumed": os.environ.get("MXNET_TPU_ELASTIC_RESUMED"),
           "xla": os.environ.get("XLA_FLAGS", "")},
          open(state, "w"))
sys.exit(0 if n + 1 >= %(succeed_on)d else %(rc)d)
"""


def _script(tmp_path, body):
    p = tmp_path / "child.py"
    p.write_text(body)
    return str(p)


def test_supervisor_restarts_until_success(tmp_path):
    state = str(tmp_path / "state.json")
    child = _script(tmp_path, _OK_AFTER
                    % {"state": state, "succeed_on": 3, "rc": 143})
    sup = elastic.Supervisor([child], max_restarts=5, backoff=0.01,
                             backoff_max=0.02, jitter_seed=0,
                             world_schedule=[8, 4, 2])
    assert sup.run() == 0
    assert sup.restarts == 2
    assert sup.reshards == 2
    import json
    rec = json.load(open(state))
    assert rec["runs"] == 3
    assert rec["attempt"] == "2"
    assert rec["resumed"] == "1"
    assert "--xla_force_host_platform_device_count=2" in rec["xla"]


def test_supervisor_crash_rc_also_restarts(tmp_path):
    state = str(tmp_path / "state.json")
    child = _script(tmp_path, _OK_AFTER
                    % {"state": state, "succeed_on": 2, "rc": 17})
    sup = elastic.Supervisor([child], max_restarts=3, backoff=0.01,
                             jitter_seed=0)
    assert sup.run() == 0
    assert sup.restarts == 1


def test_supervisor_budget_exhausted_returns_child_rc(tmp_path):
    child = _script(tmp_path, "import sys; sys.exit(9)\n")
    sup = elastic.Supervisor([child], max_restarts=2, backoff=0.01,
                             backoff_max=0.02, jitter_seed=0)
    assert sup.run() == 9
    assert sup.restarts == 2


def test_supervisor_clean_child_never_restarts(tmp_path):
    child = _script(tmp_path, "import sys; sys.exit(0)\n")
    sup = elastic.Supervisor([child], max_restarts=3, backoff=0.01)
    assert sup.run() == 0
    assert sup.restarts == 0


def test_backoff_sleep_interruptible_by_termination(tmp_path):
    """A SIGTERM mid-backoff must cut the sleep short (PEP 475 would
    resume one long sleep after the flag-only handler returns)."""
    import threading
    import time as _time
    child = _script(tmp_path, "import sys; sys.exit(0)\n")
    sup = elastic.Supervisor([child], backoff=0.01)
    threading.Timer(0.1, lambda: setattr(sup, "_terminated", True)).start()
    t0 = _time.monotonic()
    sup._backoff_sleep(30.0)
    assert _time.monotonic() - t0 < 5.0


def test_supervisor_sigterm_between_attempts_stops_before_spawn(tmp_path):
    """A preemption notice that lands while no child is alive (backoff
    sleep, world probe) must not spawn a fresh child doomed to a hard
    kill — the supervisor exits 143 without another attempt."""
    marker = tmp_path / "ran"
    child = _script(tmp_path,
                    "import pathlib, sys\n"
                    "pathlib.Path(%r).touch()\n"
                    "sys.exit(0)\n" % str(marker))
    sup = elastic.Supervisor([child], max_restarts=3, backoff=0.01)
    sup._terminated = True           # SIGTERM arrived between attempts
    assert sup.run() == 143
    assert not marker.exists()


def test_supervisor_schedule_repeats_last_entry(tmp_path):
    state = str(tmp_path / "state.json")
    child = _script(tmp_path, _OK_AFTER
                    % {"state": state, "succeed_on": 4, "rc": 143})
    sup = elastic.Supervisor([child], max_restarts=5, backoff=0.01,
                             backoff_max=0.02, jitter_seed=0,
                             world_schedule=[4, 2])
    assert sup.run() == 0
    assert sup.restarts == 3
    assert sup.reshards == 1          # 4 -> 2, then 2 repeats
    import json
    assert "device_count=2" in json.load(open(state))["xla"]


def test_resume_dir_requires_a_valid_checkpoint(tmp_path):
    assert elastic.resume_dir(str(tmp_path)) is None
    write_checkpoint(str(tmp_path), 1, {"w": np.ones(4, np.float32)})
    assert elastic.resume_dir(str(tmp_path)) == str(tmp_path)
    # corrupt the only candidate: no longer resumable
    arrays = os.path.join(str(tmp_path), "ckpt-0000000001", "arrays.npz")
    with open(arrays, "ab") as f:
        f.write(b"x")                  # size mismatch fails probe_valid
    assert elastic.resume_dir(str(tmp_path)) is None


def test_elastic_cli_entrypoint(tmp_path):
    child = _script(tmp_path, "import sys; sys.exit(0)\n")
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.elastic", "--max-restarts", "1",
         "--backoff", "0.01", "--", child],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_supervisor_never_initializes_a_jax_backend(tmp_path):
    """The supervisor's device view must come from throwaway probe
    subprocesses, never an in-process backend (a backend pins its device
    set for the process lifetime — fatal for elasticity). Run the whole
    supervisor + one restart under an unresolvable JAX_PLATFORMS: any
    in-process backend initialization raises; the child overrides the
    platform itself and must succeed."""
    child = _script(tmp_path, (
        "import json, os, sys\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'   # override, not setdefault\n"
        "state = %r\n"
        "n = 0\n"
        "if os.path.exists(state):\n"
        "    n = json.load(open(state))['runs']\n"
        "json.dump({'runs': n + 1}, open(state, 'w'))\n"
        "sys.exit(0 if n + 1 >= 2 else 143)\n"
        % str(tmp_path / "state.json")))
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.elastic", "--max-restarts", "2",
         "--backoff", "0.01", "--", child],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "PYTHONPATH": REPO,
             "JAX_PLATFORMS": "no_such_platform"})
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ------------------------------------------------------------- end-to-end

@pytest.mark.slow
def test_elastic_smoke_script():
    """The CI drill end-to-end: 8-device fit preempted mid-epoch,
    auto-resumed on 4 then 2 devices, final params bit-identical to the
    uninterrupted 8-device baseline (tools/elastic_smoke.py)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "elastic_smoke.py")],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ELASTIC-DRILL-OK" in proc.stdout

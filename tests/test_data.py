"""mx.data — the sharded multi-worker streaming data plane.

What must hold (docs/architecture/data_plane.md):

* **order is a pure function** of (seed, epoch, world, rank, batch
  size) — NEVER of worker count: identical streams across
  num_workers in {0, 1, 2, 4}, and across epochs at a fixed seed.
* **exact cursor resume** — a mid-epoch checkpoint cursor fast-forwards
  the stream bit-identically, including with a DIFFERENT worker count
  (the elastic reshard path); mismatched stream identity fails loudly.
* **fault containment** — a dead worker (``data.worker``) is respawned
  over exactly its undelivered range (the stream stays identical); a
  decode fault (``data.decode``) poisons ONE batch, never the epoch.
* **zero cost when unused** — a fit fed by any other iterator never
  imports ``mxnet_tpu.data`` (subprocess-proven).
* **straggler telemetry stays honest** — an off-thread loader stall is
  a data-plane wait (``data_stall``/``loop_prefetch_stall``), excluded
  from the PR 13 inter-step local-work window (regression for the
  re-derivation in base_module.fit).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, profiler, recordio
from mxnet_tpu import config as cfg
from mxnet_tpu.checkpoint import CheckpointConfig, restore_latest
from mxnet_tpu.data import (DataLoader, PartitionPlan, RawTransform,
                            StallTransform, epoch_order)

BATCH = 4
FEAT = 6
NCLS = 3
NREC = 48

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------ helpers

@pytest.fixture()
def dataset(tmp_path):
    """An indexed RecordIO file whose record i carries data full of
    distinctive values and label i % NCLS."""
    rec = str(tmp_path / "t.rec")
    idx = str(tmp_path / "t.idx")
    rng = np.random.RandomState(0)
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(NREC):
        hdr = recordio.IRHeader(0, float(i % NCLS), i, 0)
        payload = np.concatenate(
            [[np.float32(i)], rng.uniform(-1, 1, FEAT - 1)]
        ).astype(np.float32)
        w.write_idx(i, recordio.pack(hdr, payload.tobytes()))
    w.close()
    return rec, idx


def _loader(dataset, **kw):
    rec, idx = dataset
    kw.setdefault("batch_size", BATCH)
    kw.setdefault("transform", RawTransform((FEAT,)))
    kw.setdefault("shuffle", True)
    kw.setdefault("seed", 11)
    kw.setdefault("part", (0, 1))
    return DataLoader(rec, idx_path=idx, **kw)


def _stream(dl, close=True):
    """Record ids of every delivered batch (data[:, 0] is the id)."""
    out = [b.data[0][:, 0].astype(int).tolist() for b in dl]
    if close:
        dl.close()
    return out


# ------------------------------------------------------------ the partition

def test_partition_is_pure_and_workers_cover_disjointly():
    plan = PartitionPlan(100, 8, seed=5, epoch=2, num_workers=3)
    again = PartitionPlan(100, 8, seed=5, epoch=2, num_workers=3)
    assert list(plan.local_order) == list(again.local_order)
    owned = [plan.owned_batches(w) for w in range(3)]
    flat = sorted(b for lst in owned for b in lst)
    assert flat == list(range(plan.num_batches))      # disjoint cover
    for w, lst in enumerate(owned):
        assert all(k % 3 == w for k in lst)           # k % W ownership
    # a different epoch draws a different permutation...
    other = PartitionPlan(100, 8, seed=5, epoch=3, num_workers=3)
    assert list(other.local_order) != list(plan.local_order)
    # ...and shuffle=False is file order
    ident = PartitionPlan(100, 8, seed=5, epoch=2, shuffle=False)
    assert list(ident.local_order) == list(range(100))
    assert list(epoch_order(10, 0, 0, shuffle=False)) == list(range(10))


def test_partition_world_strides_are_disjoint():
    order = epoch_order(NREC, 11, 0, shuffle=True)
    plans = [PartitionPlan(NREC, BATCH, seed=11, epoch=0, rank=r,
                           world_size=2) for r in range(2)]
    seen = [i for p in plans for i in p.local_order]
    assert sorted(seen) == list(range(NREC))
    # each host's sequence is the global permutation strided by rank
    for r, p in enumerate(plans):
        assert list(p.local_order) == list(order[r::2])


# ----------------------------------------------------------- stream identity

def test_stream_identical_across_worker_counts(dataset):
    streams = {w: _stream(_loader(dataset, num_workers=w))
               for w in (0, 1, 2, 4)}
    for w in (1, 2, 4):
        assert streams[w] == streams[0], "num_workers=%d diverged" % w
    # shuffled: not file order
    assert streams[0] != [list(range(i, i + BATCH))
                          for i in range(0, NREC, BATCH)]


def test_epochs_are_deterministic_and_distinct(dataset):
    def epochs(workers):
        dl = _loader(dataset, num_workers=workers)
        e0 = _stream(dl, close=False)
        dl.reset()
        e1 = _stream(dl)
        return e0, e1

    a0, a1 = epochs(2)
    b0, b1 = epochs(0)
    assert (a0, a1) == (b0, b1)       # replayable across worker counts
    assert a0 != a1                   # fresh permutation per epoch
    flat0 = sorted(i for b in a0 for i in b)
    assert flat0 == list(range(NREC))  # every record exactly once


def test_world_partition_feeds_disjoint_hosts(dataset):
    per_host = [_stream(_loader(dataset, num_workers=2, part=(r, 2)))
                for r in range(2)]
    flat = sorted(i for s in per_host for b in s for i in b)
    assert flat == list(range(NREC))
    assert not (set(i for b in per_host[0] for i in b)
                & set(i for b in per_host[1] for i in b))


def test_too_few_records_fails_loudly(dataset):
    rec, idx = dataset
    with pytest.raises(mx.MXNetError, match="cannot fill"):
        DataLoader(rec, idx_path=idx, batch_size=NREC // 2,
                   transform=RawTransform((FEAT,)), part=(0, 4))


def test_transform_is_required(dataset):
    rec, idx = dataset
    with pytest.raises(ValueError, match="transform"):
        DataLoader(rec, idx_path=idx, batch_size=BATCH)


# --------------------------------------------------------------- the cursor

def test_fast_forward_matches_uninterrupted_across_worker_counts(dataset):
    base = _stream(_loader(dataset, num_workers=2))
    for workers in (0, 1, 4):
        dl = _loader(dataset, num_workers=workers)
        cur = dl._mx_cursor(epoch=0, batches_done=5)
        dl._mx_fast_forward(0, 5, cursor=cur)
        assert _stream(dl) == base[5:], \
            "resume at batch 5 with %d workers diverged" % workers


def test_cursor_mismatch_names_the_field(dataset):
    dl = _loader(dataset, num_workers=0, seed=11)
    cur = dl._mx_cursor(epoch=0, batches_done=3)
    dl.close()
    other = _loader(dataset, num_workers=0, seed=99)
    with pytest.raises(mx.MXNetError, match="seed"):
        other._mx_fast_forward(0, 3, cursor=cur)
    other.close()
    smaller = _loader(dataset, num_workers=0, batch_size=BATCH * 2)
    with pytest.raises(mx.MXNetError, match="batch_size"):
        smaller._mx_fast_forward(0, 3, cursor=cur)
    smaller.close()
    future = dict(cur, version=cur["version"] + 1)
    last = _loader(dataset, num_workers=0)
    with pytest.raises(mx.MXNetError, match="version"):
        last._mx_fast_forward(0, 3, cursor=future)
    last.close()


# ------------------------------------------------------------------- faults

def test_worker_death_replays_exactly(dataset):
    base = _stream(_loader(dataset, num_workers=2))
    before = profiler.get_counter("data_worker_respawn")
    faults.install("data.worker@1:sigkill")
    try:
        survived = _stream(_loader(dataset, num_workers=2))
    finally:
        faults.clear()
    assert survived == base
    assert profiler.get_counter("data_worker_respawn") > before


def test_decode_fault_poisons_one_batch_not_the_epoch(dataset):
    base = _stream(_loader(dataset, num_workers=1))
    before = profiler.get_counter("data_batch_poisoned")
    faults.install("data.decode@3:raise")
    try:
        poisoned = _stream(_loader(dataset, num_workers=1))
    finally:
        faults.clear()
    assert len(poisoned) == len(base) - 1
    assert profiler.get_counter("data_batch_poisoned") == before + 1
    # the surviving batches are the base stream minus exactly one batch
    it = iter(base)
    dropped = 0
    for b in poisoned:
        while next(it) != b:
            dropped += 1
    assert dropped <= 1


def test_decode_fault_inline_path(dataset):
    base = _stream(_loader(dataset, num_workers=0))
    faults.install("data.decode@2:raise")
    try:
        poisoned = _stream(_loader(dataset, num_workers=0))
    finally:
        faults.clear()
    assert len(poisoned) == len(base) - 1


def test_steady_state_has_zero_stalls(dataset):
    """A decode pool that keeps up must never stall the consumer — the
    counter-assert the ISSUE pins for the steady state (the bench and
    tools/data_smoke.py assert the same through a real fit)."""
    import time
    before = profiler.get_counter("data_stall")
    dl = _loader(dataset, num_workers=2, queue_depth=8)
    batches = 0
    for _ in dl:
        batches += 1
        time.sleep(0.01)             # the "step": consume slower than
        # decode so the queues stay warm — zero bubbles expected
    dl.close()
    assert batches == NREC // BATCH
    assert profiler.get_counter("data_stall") == before


# ----------------------------------------------------------- fit integration

def _mlp():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=NCLS, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _seed_init():
    rng = np.random.RandomState(42)
    shapes = {"data": (BATCH, FEAT), "softmax_label": (BATCH,)}
    sym = _mlp()
    args, _, _ = sym.infer_shape(**shapes)
    return {n: mx.nd.array(rng.uniform(-0.1, 0.1, s).astype(np.float32))
            for n, s in zip(sym.list_arguments(), args) if n not in shapes}


class _Stop(Exception):
    """In-process crash: abandons fit() from a batch-end callback."""


def _fit(dataset, epochs, workers, ckpt=None, resume=None, seed=True,
         stop_after=None, stall_s=0.0):
    mx.random.seed(7)
    transform = RawTransform((FEAT,))
    if stall_s:
        transform = StallTransform(transform, stall_s)
    it = _loader(dataset, num_workers=workers, transform=transform,
                 label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    kw = {}
    if seed:
        kw["arg_params"] = {k: v.copy() for k, v in _seed_init().items()}
    if stop_after is not None:
        calls = [0]

        def cb(_param):
            calls[0] += 1
            if calls[0] >= stop_after:
                raise _Stop()

        kw["batch_end_callback"] = cb
    try:
        mod.fit(it, num_epoch=epochs, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1},
                checkpoint=ckpt, resume_from=resume, **kw)
    except _Stop:
        pass
    finally:
        it.close()
    arg, aux = mod.get_params()
    w = {k: v.asnumpy().copy() for k, v in arg.items()}
    w.update({k: v.asnumpy().copy() for k, v in aux.items()})
    return w


def _assert_equal(w0, w1):
    assert set(w0) == set(w1)
    for k in sorted(w0):
        np.testing.assert_array_equal(w0[k], w1[k], err_msg=k)


def test_fit_trains_from_the_loader(dataset):
    w = _fit(dataset, epochs=1, workers=2)
    assert all(np.isfinite(v).all() for v in w.values())


def test_checkpoint_manifest_carries_the_cursor(dataset, tmp_path):
    base = str(tmp_path / "ckpt")
    ck = CheckpointConfig(base, every_n_batches=3, period_epochs=1)
    _fit(dataset, epochs=1, workers=2, ckpt=ck, stop_after=7)
    cur = restore_latest(base).data_cursor
    assert cur is not None
    assert cur["version"] == 1
    assert cur["epoch"] == 0
    assert cur["batches_done"] == 6      # last every-3 save before stop
    assert cur["seed"] == 11 and cur["batch_size"] == BATCH
    assert cur["num_records"] == NREC and cur["num_workers"] == 2


def test_mid_epoch_resume_with_different_workers_is_bit_identical(
        dataset, tmp_path):
    """The headline drill, in-process: crash mid-epoch-1, resume with a
    DIFFERENT worker count, land bit-identical to uninterrupted."""
    w_ref = _fit(dataset, epochs=2, workers=2)
    base = str(tmp_path / "ckpt")
    ck = CheckpointConfig(base, every_n_batches=3, period_epochs=1)
    _fit(dataset, epochs=2, workers=2, ckpt=ck, stop_after=15)
    assert restore_latest(base).mid_epoch
    w_res = _fit(dataset, epochs=2, workers=4, resume=base, seed=False)
    _assert_equal(w_ref, w_res)
    # and with the multiprocessing pool disabled entirely
    w_res0 = _fit(dataset, epochs=2, workers=0, resume=base, seed=False)
    _assert_equal(w_ref, w_res0)


def test_epoch_boundary_resume_is_bit_identical(dataset, tmp_path):
    w_ref = _fit(dataset, epochs=2, workers=2)
    base = str(tmp_path / "ckpt")
    ck = CheckpointConfig(base, period_epochs=1)
    _fit(dataset, epochs=1, workers=2, ckpt=ck)
    w_res = _fit(dataset, epochs=2, workers=1, resume=base, seed=False)
    _assert_equal(w_ref, w_res)


# -------------------------------------------------- straggler window honesty

class _RecordingPublisher(object):
    """FitPublisher stand-in: records the work_s stream fit feeds it."""

    instances = []

    def __init__(self):
        self.windows = []
        self.published = []
        _RecordingPublisher.instances.append(self)

    @classmethod
    def create(cls):
        return cls()

    def step(self, work_s):
        self.windows.append(float(work_s))

    def publish(self, epoch):
        self.published.append(int(epoch))


def test_straggler_window_excludes_offthread_loader_stall(
        dataset, monkeypatch):
    """PR 13 regression, re-derived for the streaming loader: a SLOW
    LOADER shows up as loop_prefetch_stall/data_stall, never as
    inter-step local work that would flag this rank a straggler."""
    from mxnet_tpu.obs import straggler as straggler_mod
    monkeypatch.setenv("DMLC_NUM_WORKER", "2")
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    monkeypatch.setattr(straggler_mod, "FitPublisher",
                        _RecordingPublisher)
    _RecordingPublisher.instances = []
    stall_before = (profiler.get_counter("data_stall")
                    + profiler.get_counter("loop_prefetch_stall"))
    _fit(dataset, epochs=1, workers=1, stall_s=0.02)
    [pub] = _RecordingPublisher.instances
    assert pub.published == [0]
    assert pub.windows, "fit never fed the straggler publisher"
    # 12 batches x 4 records x 20ms decode stall ≈ 1s of loader latency;
    # NONE of it may land in the local-work window
    assert max(pub.windows) < 0.05, (
        "loader stall leaked into the straggler local-work window: %r"
        % (pub.windows,))
    stalled = (profiler.get_counter("data_stall")
               + profiler.get_counter("loop_prefetch_stall"))
    assert stalled > stall_before, \
        "a slow loader must surface as a data-plane stall counter"


def test_inline_iterator_decode_still_counts_as_local_work(
        dataset, monkeypatch):
    """The flip side: num_workers=0 decodes ON the consumer thread —
    that IS rank-local work and stays inside the window (an actually
    slow host must not be able to hide behind the loader). The
    device-prefetch wrap is disabled: wrapped, the fetch moves to the
    prefetch thread and is legitimately off-thread."""
    from mxnet_tpu.obs import straggler as straggler_mod
    monkeypatch.setenv("DMLC_NUM_WORKER", "2")
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    monkeypatch.setattr(straggler_mod, "FitPublisher",
                        _RecordingPublisher)
    _RecordingPublisher.instances = []
    cfg.set("MXNET_TPU_DEVICE_PREFETCH", 0)
    try:
        _fit(dataset, epochs=1, workers=0, stall_s=0.02)
    finally:
        cfg.reset("MXNET_TPU_DEVICE_PREFETCH")
    [pub] = _RecordingPublisher.instances
    assert pub.windows
    # each inline fetch decodes BATCH records x 20ms inside the window
    assert max(pub.windows) > 0.05, (
        "inline decode time vanished from the local-work window: %r"
        % (pub.windows,))


# ------------------------------------------------------------ zero-cost gate

def test_unused_loader_is_never_imported(tmp_path):
    """A fit fed by NDArrayIter must not import mxnet_tpu.data (lazy
    module) nor touch any data_* counter — subprocess-proven."""
    prog = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import profiler
X = np.random.RandomState(0).uniform(-1, 1, (32, 6)).astype(np.float32)
Y = (np.arange(32) % 3).astype(np.float32)
it = mx.io.NDArrayIter(X, Y, batch_size=4, label_name="softmax_label")
data = mx.sym.Variable("data")
fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
mod = mx.mod.Module(mx.sym.SoftmaxOutput(fc, name="softmax"),
                    context=mx.cpu())
mod.fit(it, num_epoch=1, optimizer="sgd")
assert "mxnet_tpu.data" not in sys.modules, "loader imported unused"
bad = [n for n in ("data_batches", "data_records", "data_stall",
                   "data_worker_respawn", "data_batch_poisoned")
       if profiler.get_counter(n)]
assert not bad, "counters touched without the loader: %r" % bad
print("ZERO_COST_OK")
"""
    proc = subprocess.run(
        [sys.executable, "-c", prog], cwd=REPO, capture_output=True,
        text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stderr
    assert "ZERO_COST_OK" in proc.stdout

"""Module API tests.

Reference model (SURVEY.md §4): tests/python/unittest/test_module.py +
tests/python/train/test_mlp.py (train MNIST-like data to an accuracy bar).
"""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx


def _xor_like_data(n=800, seed=0):
    """Small separable 2-class problem an MLP must crack."""
    rng = np.random.RandomState(seed)
    x = rng.uniform(-1, 1, (n, 2)).astype(np.float32)
    y = ((x[:, 0] * x[:, 1]) > 0).astype(np.float32)
    return x, y


def _mlp_symbol(num_hidden=16, num_classes=2):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=num_hidden, name="fc1")
    act1 = mx.sym.Activation(fc1, act_type="tanh", name="tanh1")
    fc2 = mx.sym.FullyConnected(act1, num_hidden=num_classes, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def test_module_bind_and_shapes():
    sym = _mlp_symbol()
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 2))],
             label_shapes=[("softmax_label", (8,))])
    assert mod.binded
    assert mod.data_shapes[0].shape == (8, 2)
    mod.init_params(mx.init.Xavier())
    arg_params, aux_params = mod.get_params()
    assert set(arg_params) == {"fc1_weight", "fc1_bias",
                               "fc2_weight", "fc2_bias"}
    assert aux_params == {}


def test_module_forward_backward_update():
    sym = _mlp_symbol()
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 2))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    x, y = _xor_like_data(8)
    batch = mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])
    w0 = mod.get_params()[0]["fc1_weight"].asnumpy().copy()
    mod.forward(batch, is_train=True)
    out = mod.get_outputs()[0]
    assert out.shape == (8, 2)
    np.testing.assert_allclose(out.asnumpy().sum(axis=1), np.ones(8),
                               rtol=1e-5)
    mod.backward()
    mod.update()
    w1 = mod.get_params()[0]["fc1_weight"].asnumpy()
    assert not np.allclose(w0, w1), "update did not change weights"


def test_module_fit_converges():
    x, y = _xor_like_data(800)
    train = mx.io.NDArrayIter(x, y, batch_size=50, shuffle=True,
                              label_name="softmax_label")
    val = mx.io.NDArrayIter(x, y, batch_size=50,
                            label_name="softmax_label")
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
    mod.fit(train, eval_data=val, optimizer="sgd",
            initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            num_epoch=30)
    score = mod.score(val, "acc")
    assert score[0][1] > 0.95, "fit failed to converge: %s" % score


def test_module_fused_matches_eager():
    """Fused jitted step must produce the same updates as
    forward/backward/update (the reference's engine-ops path)."""
    x, y = _xor_like_data(32, seed=3)
    batch = mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])

    def make():
        mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
        mod.bind(data_shapes=[("data", (32, 2))],
                 label_shapes=[("softmax_label", (32,))])
        mod.init_params(mx.init.Xavier(rnd_type="gaussian",
                                       factor_type="in", magnitude=2))
        np_params = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
        return mod, np_params

    mod_a, params_a = make()
    mod_b, _ = make()
    mod_b.set_params({k: mx.nd.array(v) for k, v in params_a.items()}, {})

    opt_kw = {"learning_rate": 0.1, "momentum": 0.9}
    mod_a.init_optimizer(optimizer="sgd", optimizer_params=opt_kw)
    mod_b.init_optimizer(optimizer="sgd", optimizer_params=opt_kw)

    for _ in range(3):
        mod_a.forward(batch, is_train=True)
        mod_a.backward()
        mod_a.update()
        mod_b._fit_step(batch)

    pa = mod_a.get_params()[0]
    pb = mod_b.get_params()[0]
    for k in pa:
        np.testing.assert_allclose(pa[k].asnumpy(), pb[k].asnumpy(),
                                   rtol=2e-5, atol=2e-6,
                                   err_msg="fused/eager diverged at %s" % k)


def test_module_data_parallel_matches_single():
    """8-device data-parallel step == single-device step (the reference's
    kvstore-summed gradients, SURVEY §2.21; here GSPMD psum)."""
    x, y = _xor_like_data(64, seed=5)
    batch = mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])

    def run(ctxs):
        mod = mx.mod.Module(_mlp_symbol(), context=ctxs)
        mod.bind(data_shapes=[("data", (64, 2))],
                 label_shapes=[("softmax_label", (64,))])
        mod.init_params(mx.init.Uniform(0.07))
        return mod

    mod_1 = run(mx.cpu(0))
    mod_8 = run([mx.cpu(i) for i in range(8)])
    mod_8.set_params({k: v.copyto(mx.cpu(0)) for k, v in
                      mod_1.get_params()[0].items()}, {})

    kw = {"learning_rate": 0.2}
    mod_1.init_optimizer(optimizer="sgd", optimizer_params=kw)
    mod_8.init_optimizer(kvstore="device", optimizer="sgd",
                         optimizer_params=kw)

    for _ in range(2):
        mod_1._fit_step(batch)
        mod_8._fit_step(batch)

    p1 = mod_1.get_params()[0]
    p8 = mod_8.get_params()[0]
    for k in p1:
        np.testing.assert_allclose(p1[k].asnumpy(), p8[k].asnumpy(),
                                   rtol=1e-4, atol=1e-5,
                                   err_msg="data-parallel diverged at %s" % k)


def test_module_save_load_checkpoint(tmp_path):
    sym = _mlp_symbol()
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 2))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer()
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 3, save_optimizer_states=True)
    assert os.path.exists(prefix + "-symbol.json")
    assert os.path.exists(prefix + "-0003.params")
    assert os.path.exists(prefix + "-0003.states")

    mod2 = mx.mod.Module.load(prefix, 3, context=mx.cpu())
    mod2.bind(data_shapes=[("data", (4, 2))],
              label_shapes=[("softmax_label", (4,))])
    p1 = mod.get_params()[0]
    p2 = mod2.get_params()[0]
    for k in p1:
        np.testing.assert_allclose(p1[k].asnumpy(), p2[k].asnumpy())
    # loaded params must actually drive the executor, not just get_params
    x, y = _xor_like_data(4)
    batch = mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])
    mod.forward(batch, is_train=False)
    mod2.forward(batch, is_train=False)
    np.testing.assert_allclose(mod.get_outputs()[0].asnumpy(),
                               mod2.get_outputs()[0].asnumpy(), rtol=1e-6)


def test_module_fixed_params_not_trained():
    """fixed_param_names must be frozen on both eager and fused paths
    (reference: module.py fixed_param_names → grad_req null)."""
    x, y = _xor_like_data(16)
    batch = mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu(),
                        fixed_param_names=["fc1_weight"])
    mod.bind(data_shapes=[("data", (16, 2))],
             label_shapes=[("softmax_label", (16,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    w_fixed = mod.get_params()[0]["fc1_weight"].asnumpy().copy()
    w_free = mod.get_params()[0]["fc2_weight"].asnumpy().copy()
    mod._fit_step(batch)                      # fused
    mod.forward_backward(batch)
    mod.update()                              # eager
    p = mod.get_params()[0]
    np.testing.assert_allclose(p["fc1_weight"].asnumpy(), w_fixed)
    assert not np.allclose(p["fc2_weight"].asnumpy(), w_free)


def test_module_predict_and_score():
    x, y = _xor_like_data(100)
    it = mx.io.NDArrayIter(x, y, batch_size=25, label_name="softmax_label")
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    out = mod.predict(it)
    assert out.shape == (100, 2)
    res = mod.score(it, "acc")
    assert 0.0 <= res[0][1] <= 1.0


@pytest.mark.parametrize("opt_name", ["adam", "nadam", "rmsprop", "adagrad"])
def test_module_fused_matches_eager_stateful_optimizers(opt_name):
    """Stateful optimizers must produce identical updates on the fused
    (traced raw_update) and eager (engine-op) paths."""
    x, y = _xor_like_data(16, seed=11)
    batch = mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])

    def make():
        mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
        mod.bind(data_shapes=[("data", (16, 2))],
                 label_shapes=[("softmax_label", (16,))])
        mod.init_params(mx.init.Uniform(0.1))
        return mod

    mod_a, mod_b = make(), make()
    mod_b.set_params({k: mx.nd.array(v.asnumpy())
                      for k, v in mod_a.get_params()[0].items()}, {})
    for m in (mod_a, mod_b):
        m.init_optimizer(optimizer=opt_name,
                         optimizer_params={"learning_rate": 0.01})
    for _ in range(3):
        mod_a.forward_backward(batch)
        mod_a.update()
        mod_b._fit_step(batch)
    pa, pb = mod_a.get_params()[0], mod_b.get_params()[0]
    for k in pa:
        np.testing.assert_allclose(pa[k].asnumpy(), pb[k].asnumpy(),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg="%s diverged at %s" % (opt_name, k))


def test_module_lr_scheduler_no_retrace():
    """LR schedule changes must not retrigger compilation (traced lr)."""
    x, y = _xor_like_data(32)
    batch = mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (32, 2))],
             label_shapes=[("softmax_label", (32,))])
    mod.init_params(mx.init.Xavier())
    sched = mx.lr_scheduler.FactorScheduler(step=1, factor=0.5)
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.4,
                                         "lr_scheduler": sched})
    for _ in range(3):
        mod._fit_step(batch)
    n_compiles = mod._fused_jit._cache_size()
    assert n_compiles == 1, "lr schedule caused %d recompiles" % n_compiles

"""mxnet_tpu.analysis — static graph/program analyzer + AST lint (ISSUE 3).

Coverage contract (acceptance criteria):

* every hazard class has a negative test proving its pass FIRES (the test
  fails without the pass) and the clean-graph tests prove it stays silent;
* model-zoo nets (resnet, transformer, transformer+MoE) analyze with zero
  ERROR-level findings;
* the baked-constant pass catches the PR 1 closure-captured-constant
  pattern, and CompileCache signatures for two programs differing only in
  a captured constant never collide;
* ``MXNET_TPU_ANALYZE=strict`` turns ERROR findings into bind-time
  exceptions; ``warn`` logs and proceeds;
* with the knob unset the bind path never imports the analyzer
  (zero-cost guard, asserted in a subprocess).
"""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.analysis import (Severity, analyze_program, analyze_symbol,
                                diff_baseline, lint_source, load_baseline,
                                write_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def codes(report, code=None):
    if code is None:
        return [f.code for f in report]
    return [f for f in report if f.code == code]


# ===================================================== graph passes


def test_cycle_detected():
    a = sym.Variable("a")
    s1 = a + 1.0
    s2 = s1 + 2.0
    # close a loop by hand (the API can't build one, but composed/mutated
    # graphs and future passes can)
    s1._entries[0][0].inputs.append((s2._entries[0][0], 0))
    report = analyze_symbol(s2)
    hits = codes(report, "cycle")
    assert hits and hits[0].severity == Severity.ERROR
    assert "cycle" in hits[0].message


def test_no_cycle_on_diamond():
    a = sym.Variable("a")
    left = a + 1.0
    right = a * 2.0
    report = analyze_symbol(left + right)
    assert not codes(report, "cycle")


def test_duplicate_variable_names():
    report = analyze_symbol(sym.Variable("x") + sym.Variable("x"))
    hits = codes(report, "dup-name")
    assert hits and hits[0].severity == Severity.ERROR
    assert "'x'" in hits[0].message


def test_duplicate_op_names():
    d = sym.Variable("data")
    f1 = sym.FullyConnected(d, num_hidden=4, name="fc")
    f2 = sym.FullyConnected(f1, num_hidden=4, name="fc")
    report = analyze_symbol(f2)
    assert codes(report, "dup-name")


def test_unique_names_clean():
    d = sym.Variable("data")
    net = sym.FullyConnected(d, num_hidden=4, name="fc1")
    net = sym.FullyConnected(net, num_hidden=2, name="fc2")
    report = analyze_symbol(net, input_shapes={"data": (2, 8)})
    assert not codes(report, "dup-name")
    assert not codes(report, "dead-node")
    assert not report.errors


def test_dead_output_detected():
    x = sym.Variable("data")
    parts = sym.SliceChannel(x, num_outputs=3, axis=1, name="split")
    report = analyze_symbol(parts[0], input_shapes={"data": (2, 6)})
    hits = codes(report, "dead-node")
    assert hits and hits[0].node == "split"
    assert "[1, 2]" in hits[0].message


def test_all_outputs_used_clean():
    x = sym.Variable("data")
    parts = sym.SliceChannel(x, num_outputs=2, axis=1, name="split")
    report = analyze_symbol(parts[0] + parts[1],
                            input_shapes={"data": (2, 6)})
    assert not codes(report, "dead-node")


def test_unused_input_binding():
    d = sym.Variable("data")
    net = sym.FullyConnected(d, num_hidden=4)
    report = analyze_symbol(net, input_shapes={"data": (2, 8),
                                               "weihgt": (4, 8)})
    hits = codes(report, "unused-input")
    assert hits and "weihgt" in hits[0].message


def test_shape_conflict_names_node_and_shapes():
    d = sym.Variable("data")
    w = sym.Variable("w", shape=(7, 5))          # wrong: data is (4, 11)
    fc = sym.FullyConnected(d, w, num_hidden=7, no_bias=True, name="fc_bad")
    report = analyze_symbol(fc, input_shapes={"data": (4, 11)})
    hits = [f for f in codes(report, "shape-error")
            if f.severity == Severity.ERROR]
    assert hits
    f = hits[0]
    assert f.node == "fc_bad" and f.op == "FullyConnected"
    assert "4x11" in f.message and "7x5" in f.message


def test_shape_clean_net_no_errors():
    d = sym.Variable("data")
    net = sym.FullyConnected(d, num_hidden=4)
    report = analyze_symbol(net, input_shapes={"data": (2, 8)})
    assert not report.errors


def test_cost_model_mlp_flops():
    from mxnet_tpu.models import mlp
    net = mlp.get_symbol(num_classes=10, hidden=(128, 64))
    report = analyze_symbol(net, input_shapes={"data": (32, 784),
                                               "softmax_label": (32,)})
    cost = report.extras["cost"]
    # three matmuls dominate: 2*B*(784*128 + 128*64 + 64*10)
    matmul = 2 * 32 * (784 * 128 + 128 * 64 + 64 * 10)
    assert matmul <= cost["flops"] <= int(matmul * 1.2)
    # bound_bytes counts every bound variable buffer: weights/biases AND
    # the data/label inputs (what bind actually allocates)
    n_params = (784 * 128 + 128 + 128 * 64 + 64 + 64 * 10 + 10)
    assert cost["bound_bytes"] == 4 * (n_params + 32 * 784 + 32)
    assert cost["peak_bytes"] > cost["bound_bytes"] > 0
    assert cost["nodes_skipped"] == 0
    assert codes(report, "cost-model")


# ============================================== symbol-level ergonomics


def test_cost_model_liveness_self_consuming_op():
    """An op consuming the same entry through two edges (b*b) must free
    that entry ONCE — double-freeing deflates `live` and hides any LATER
    peak: here the true peak is the 3 simultaneous buffers at e."""
    a = sym.Variable("a")
    b = a + 0.0
    c = b * b          # b's last use: two edges, one buffer
    d = c + 0.0
    e = c + d          # c, d and e live together: the true 3-buffer peak
    report = analyze_symbol(e, input_shapes={"a": (256, 256)})
    buf = 256 * 256 * 4
    cost = report.extras["cost"]
    assert cost["activation_peak_bytes"] == 3 * buf
    assert cost["peak_bytes"] == cost["bound_bytes"] + \
        cost["activation_peak_bytes"]


def test_symbol_analyze_kwargs_form():
    d = sym.Variable("data")
    net = sym.FullyConnected(d, num_hidden=4)
    report = net.analyze(data=(2, 8))
    assert "cost" in report.extras


def test_mx_analysis_lazy_attribute():
    assert mx.analysis.Severity is Severity
    with pytest.raises(AttributeError):
        mx.no_such_subsystem


def test_module_analyze_bound_shapes():
    from mxnet_tpu.models import mlp
    net = mlp.get_symbol(num_classes=10)
    mod = mx.mod.Module(net, context=mx.cpu(),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (4, 784))],
             label_shapes=[("softmax_label", (4,))])
    report = mod.analyze()
    assert not report.errors
    assert report.extras["cost"]["flops"] > 0


def test_infer_shape_failure_names_offending_op():
    d = sym.Variable("data")
    w = sym.Variable("w", shape=(7, 5))
    fc = sym.FullyConnected(d, w, num_hidden=7, no_bias=True,
                            name="fc_ctx")
    with pytest.raises(mx.MXNetError) as exc_info:
        fc.infer_shape(data=(4, 11))
    msg = str(exc_info.value)
    assert "FullyConnected" in msg and "fc_ctx" in msg
    assert "(4,11)" in msg and "(7,5)" in msg
    # and not the raw eval_shape traceback of the whole graph
    assert "eval_shape" not in msg


def test_infer_type_honors_dtype_attr():
    d = sym.Variable("data", dtype=np.float16)
    net = sym.FullyConnected(d, num_hidden=4)
    arg_types, _, _ = net.infer_type()
    by_name = dict(zip(net.list_arguments(), arg_types))
    assert by_name["data"] == np.dtype(np.float16)
    weight = next(n for n in by_name if n.endswith("_weight"))
    assert by_name[weight] == np.dtype(np.float32)


def test_infer_type_invalid_dtype_names_variable():
    d = sym.Variable("data")
    net = sym.FullyConnected(d, num_hidden=4)
    with pytest.raises(mx.MXNetError, match="data"):
        net.infer_type(data="not-a-dtype")


# ===================================================== program passes


def test_baked_const_pattern_pr1():
    """The PR 1 shape: an op closure captures a constant; the program
    bakes it. The pass must fire on the closure-captured version and stay
    silent when the same array is passed as an argument."""
    big = np.ones((256, 256), np.float32)

    def closure_version(x):
        return x @ big                       # baked

    def arg_version(x, w):
        return x @ w                         # passed

    r = analyze_program(jax.jit(closure_version), jnp.ones((8, 256)))
    hits = codes(r, "baked-const")
    assert hits and hits[0].detail["nbytes"] == 256 * 256 * 4
    r = analyze_program(jax.jit(arg_version), jnp.ones((8, 256)),
                        jnp.asarray(big))
    assert not codes(r, "baked-const")


def test_baked_const_threshold():
    small = np.ones((4,), np.float32)
    r = analyze_program(lambda x: x + small, jnp.ones((4,)))
    assert not codes(r, "baked-const")       # tiny consts are fine
    r = analyze_program(lambda x: x + small, jnp.ones((4,)),
                        const_bytes_warn=1)
    assert codes(r, "baked-const")


def test_compile_cache_sigs_differ_for_closure_constants():
    """Two OpDefs wrapping different closure constants must never share a
    compiled-program signature (the PR 1 Scale(2.0)/Scale(3.0) collision):
    registry-external ops sign as (name, per-fn token), and per-call
    ``_Function_*`` ops refuse caching outright."""
    from mxnet_tpu._fused import Uncacheable, op_identity
    from mxnet_tpu.ops.registry import OpDef

    def make(scale):
        def fn(x):
            return x * scale
        return OpDef("Scale", fn)

    a, b = make(2.0), make(3.0)
    assert op_identity(a) != op_identity(b)
    # same object -> stable identity (cache hits still work)
    assert op_identity(a) == op_identity(a)
    with pytest.raises(Uncacheable):
        op_identity(OpDef("_Function_Scale", lambda x: x * 2.0))


def test_f64_promotion_detected_under_x64():
    from jax.experimental import enable_x64
    with enable_x64():
        r = analyze_program(lambda x: x * np.float64(3.0),
                            jnp.ones((4,), jnp.float32))
    assert codes(r, "f64-promotion")


def test_f64_all_f64_is_intentional():
    from jax.experimental import enable_x64
    with enable_x64():
        r = analyze_program(lambda x: x * np.float64(3.0),
                            jnp.ones((4,), jnp.float64))
    assert not codes(r, "f64-promotion")


def test_f64_silent_without_x64():
    r = analyze_program(lambda x: x * np.float64(3.0),
                        jnp.ones((4,), jnp.float32))
    assert not codes(r, "f64-promotion")


def test_host_callback_detected():
    def fn(x):
        return jax.pure_callback(
            lambda a: np.asarray(a),
            jax.ShapeDtypeStruct((4,), np.float32), x)

    r = analyze_program(fn, jnp.ones((4,)))
    hits = codes(r, "host-callback")
    assert hits and hits[0].detail["primitive"] == "pure_callback"
    # callback inside a jitted program is still found (sub-jaxpr walk)
    r = analyze_program(jax.jit(fn), jnp.ones((4,)))
    assert codes(r, "host-callback")
    r = analyze_program(lambda x: x + 1.0, jnp.ones((4,)))
    assert not codes(r, "host-callback")


def test_donation_passthrough_and_unused():
    r = analyze_program(lambda x, y: (x, x + y),
                        jnp.ones((4,)), jnp.ones((4,)),
                        donate_argnums=(0,))
    hits = codes(r, "donation")
    assert hits and hits[0].severity == Severity.ERROR
    assert "returned unchanged" in hits[0].message

    r = analyze_program(lambda x, y: y * 2.0,
                        jnp.ones((4,)), jnp.ones((4,)),
                        donate_argnums=(0,))
    hits = codes(r, "donation")
    assert hits and hits[0].severity == Severity.WARNING
    assert "never consumed" in hits[0].message

    r = analyze_program(lambda x, y: x + y,
                        jnp.ones((4,)), jnp.ones((4,)),
                        donate_argnums=(0,))
    assert not codes(r, "donation")


def test_analyze_executor_program():
    """The executor's fused graph function audits clean through the same
    API (analyze_program over the bound trace)."""
    d = sym.Variable("data")
    net = sym.FullyConnected(d, num_hidden=4)
    ex = net.simple_bind(mx.cpu(), data=(2, 8))
    args = {n: a.data for n, a in ex.arg_dict.items()}
    key = jax.random.PRNGKey(0)
    r = analyze_program(lambda a: ex._fn(a, {}, key, False), args)
    assert not codes(r, "host-callback")
    assert not [f for f in codes(r, "baked-const")
                if f.severity == Severity.ERROR]


# ========================================================= model zoo


def test_zoo_resnet_zero_errors():
    from mxnet_tpu import models
    net = models.get_resnet(num_classes=10, num_layers=8,
                            image_shape="3,32,32")
    report = analyze_symbol(net, input_shapes={"data": (2, 3, 32, 32),
                                               "softmax_label": (2,)})
    assert not report.errors, report.format(Severity.ERROR)
    assert report.extras["cost"]["flops"] > 1e7


def test_zoo_transformer_zero_errors():
    from mxnet_tpu.models import transformer
    net = transformer.get_symbol(vocab_size=128, num_layers=2,
                                 d_model=32, n_heads=2, seq_len=16)
    report = analyze_symbol(net, input_shapes={"data": (2, 16),
                                               "softmax_label": (2, 16)})
    assert not report.errors, report.format(Severity.ERROR)


def test_zoo_moe_transformer_zero_errors():
    from mxnet_tpu.models import transformer
    stages = transformer.get_pipeline_stages(
        vocab_size=64, n_stages=2, layers_per_stage=1, d_model=32,
        n_heads=2, seq_len=8, moe_experts=4)
    shapes = {"data": (2, 8)}
    for i, stage in enumerate(stages):
        report = analyze_symbol(stage, input_shapes=shapes
                                if i == 0 else None)
        assert not report.errors, \
            "stage %d: %s" % (i, report.format(Severity.ERROR))


# ============================================== bind hook / strictness


def test_strict_mode_raises_at_bind():
    mx.config.set("MXNET_TPU_ANALYZE", "strict")
    try:
        net = sym.Variable("x") + sym.Variable("x")   # dup-name ERROR
        with pytest.raises(mx.MXNetError, match="dup-name"):
            net.bind(mx.cpu(), {"x": mx.nd.ones((2,))})
    finally:
        mx.config.reset("MXNET_TPU_ANALYZE")


def test_strict_mode_clean_net_binds():
    mx.config.set("MXNET_TPU_ANALYZE", "strict")
    try:
        d = sym.Variable("data")
        net = sym.FullyConnected(d, num_hidden=4)
        ex = net.simple_bind(mx.cpu(), data=(2, 8))
        out = ex.forward()[0]
        assert out.shape == (2, 4)
    finally:
        mx.config.reset("MXNET_TPU_ANALYZE")


def test_warn_mode_logs_but_binds(caplog):
    import logging
    mx.config.set("MXNET_TPU_ANALYZE", "warn")
    try:
        net = sym.Variable("x") + sym.Variable("x")
        with caplog.at_level(logging.WARNING, "mxnet_tpu.analysis"):
            net.bind(mx.cpu(), {"x": mx.nd.ones((2,))})
        assert any("dup-name" in r.message for r in caplog.records)
    finally:
        mx.config.reset("MXNET_TPU_ANALYZE")


def test_finding_counters_increment():
    from mxnet_tpu import profiler
    before = profiler.get_counter("analysis_dup_name")
    analyze_symbol(sym.Variable("x") + sym.Variable("x"))
    assert profiler.get_counter("analysis_dup_name") == before + 1


def test_analyze_off_is_zero_cost():
    """With MXNET_TPU_ANALYZE unset, binding must never import the
    analyzer package (satellite: the bind path stays exactly as cheap as
    before this subsystem existed)."""
    prog = textwrap.dedent("""
        import sys
        sys.path.insert(0, %r)
        import mxnet_tpu as mx
        from mxnet_tpu import sym
        d = sym.Variable("data")
        net = sym.FullyConnected(d, num_hidden=4)
        ex = net.simple_bind(mx.cpu(), data=(2, 8))
        ex.forward()
        mod = mx.mod.Module(net, context=mx.cpu(), label_names=())
        mod.bind(data_shapes=[("data", (2, 8))])
        assert not any(m.startswith("mxnet_tpu.analysis")
                       for m in sys.modules), "analysis imported while off"
        print("ZERO_COST_OK")
    """) % (REPO,)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="")
    env.pop("MXNET_TPU_ANALYZE", None)
    res = subprocess.run([sys.executable, "-c", prog], env=env,
                         capture_output=True, text=True, timeout=240)
    assert res.returncode == 0, res.stderr
    assert "ZERO_COST_OK" in res.stdout


# ============================================================= lint


LOCKED_SYNC = """
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()

    def fetch(self, arr):
        with self._lock:
            return arr.asnumpy()
"""


def test_lint_host_sync_under_lock():
    report = lint_source(LOCKED_SYNC, path="s.py")
    hits = codes(report, "lock-host-sync")
    assert hits and hits[0].severity == Severity.ERROR
    assert hits[0].func == "S.fetch"
    # the same sync WITHOUT the lock is fine
    clean = LOCKED_SYNC.replace("with self._lock:\n            return",
                                "if True:\n            return")
    assert not codes(lint_source(clean, path="s.py"), "lock-host-sync")


def test_lint_dispatch_under_lock():
    src = """
import threading, jax
lock = threading.Lock()

def go(xs):
    with lock:
        return jax.jit(sum)(xs)
"""
    assert codes(lint_source(src, path="d.py"), "lock-dispatch")


def test_lint_wall_clock():
    src = """
import time

def latency():
    t0 = time.time()
    return time.time() - t0
"""
    assert len(codes(lint_source(src, path="t.py"), "wall-clock")) == 2
    ok = src.replace("time.time()", "time.monotonic()")
    assert not codes(lint_source(ok, path="t.py"), "wall-clock")


def test_lint_eager_loop_sync():
    """A per-batch host sync inside a fit/score/*_loop batch loop fires;
    the same sync in a non-loop function (the deferred get()-boundary
    fetch) stays silent."""
    src = """
def fit(batches, metric):
    for batch in batches:
        metric.log(batch.out.asnumpy())   # per-batch pipeline break
"""
    hits = codes(lint_source(src, path="f.py"), "eager-loop-sync")
    assert hits and hits[0].severity == Severity.WARNING
    assert hits[0].func == "fit"
    # the deferred-sync pattern: same call, but in a get()-style boundary
    ok = src.replace("def fit(", "def get(").replace(
        "for batch in batches:\n        ", "")
    assert not codes(lint_source(ok, path="f.py"), "eager-loop-sync")
    # and a loop in a non-loop-owning function is not flagged either
    other = src.replace("def fit(", "def collect(")
    assert not codes(lint_source(other, path="f.py"), "eager-loop-sync")


def test_lint_nested_function_resets_lock_context():
    src = """
import threading
lock = threading.Lock()

def outer(arr):
    with lock:
        def callback():
            return arr.asnumpy()   # runs later, NOT under the lock
        return callback
"""
    assert not codes(lint_source(src, path="n.py"), "lock-host-sync")


def test_lint_lambda_resets_lock_context():
    src = """
import threading
lock = threading.Lock()

def outer(arr, sink):
    with lock:
        sink.cb = lambda: arr.asnumpy()   # deferred, runs without the lock
"""
    assert not codes(lint_source(src, path="l.py"), "lock-host-sync")


def test_lint_inline_suppression():
    src = LOCKED_SYNC.replace(
        "with self._lock:",
        "with self._lock:  # mx-lint: allow(lock-host-sync)")
    assert not codes(lint_source(src, path="s.py"), "lock-host-sync")


def test_lint_repo_is_clean_against_baseline():
    """The CI gate, in-process: the checked-in baseline covers every
    current finding in mxnet_tpu/ + tools/ — new hazards fail."""
    from mxnet_tpu.analysis import lint_paths
    report = lint_paths([os.path.join(REPO, "mxnet_tpu"),
                         os.path.join(REPO, "tools")])
    baseline = load_baseline(os.path.join(REPO, "tools",
                                          "analysis_baseline.json"))
    fresh = diff_baseline(report, baseline, REPO)
    assert not fresh, "\n".join(f.format() for f in fresh)


def test_baseline_roundtrip_and_new_finding(tmp_path):
    report = lint_source(LOCKED_SYNC, path=str(tmp_path / "s.py"))
    assert len(report) == 1
    bl_path = str(tmp_path / "bl.json")
    write_baseline(report, bl_path, str(tmp_path))
    baseline = load_baseline(bl_path)
    assert sum(baseline.values()) == 1
    # same findings -> clean
    assert not diff_baseline(report, baseline, str(tmp_path))
    # a second finding of the same key overflows the baselined count
    doubled = lint_source(LOCKED_SYNC.replace(
        "return arr.asnumpy()",
        "arr.asnumpy()\n            return arr.asnumpy()"),
        path=str(tmp_path / "s.py"))
    assert len(diff_baseline(doubled, baseline, str(tmp_path))) == 1


# ============================================================== CLI


def test_cli_graph_zoo_and_fail_on():
    from mxnet_tpu.analysis.__main__ import main
    assert main(["graph", "zoo:mlp"]) == 0


def test_cli_lint_baseline_gate(tmp_path):
    from mxnet_tpu.analysis.__main__ import main
    bad = tmp_path / "bad.py"
    bad.write_text(LOCKED_SYNC)
    assert main(["lint", str(bad), "--root", str(tmp_path)]) == 1
    bl = tmp_path / "bl.json"
    assert main(["lint", str(bad), "--root", str(tmp_path),
                 "--write-baseline", str(bl)]) == 0
    assert main(["lint", str(bad), "--root", str(tmp_path),
                 "--baseline", str(bl)]) == 0


def test_cli_self_check():
    from mxnet_tpu.analysis.__main__ import main
    assert main(["self-check"]) == 0

"""ISSUE 19: the configuration autotuner (``mxnet_tpu.tune``).

* **grad_accum cost model** (satellite): the static activation
  high-water prices the microbatch peak inside the ``lax.scan`` carry —
  parity-tested against ``analyze_program_memory`` on the zoo
  transformer at N in {1, 4}.
* **search determinism**: the same (module, budget, seed) yields an
  identical ``TunedConfig`` in static mode — byte-equal dicts.
* **probe isolation**: probes run in subprocesses and leak no counters,
  gauges or executables into the searching process.
* **store**: fingerprint-keyed persistence round-trips; any program
  delta changes the key.
* **fit(tune=)**: the winner is applied (counter-asserted), explicit
  user arguments keep precedence, and the knob overrides are
  fit-scoped (restored when fit returns).
* **zero-cost gate**: with ``MXNET_TPU_TUNE`` unset, a full fit never
  imports ``mxnet_tpu.tune`` (subprocess-asserted).

The CI-scale end-to-end pass (bounded search + warm-restart
zero-compile) lives in ``tools/tune_smoke.py``; the tuner-vs-hand-tuned
MFU evidence in ``tools/perf/tune_bench.py`` -> ``BENCH_tune.json``.
"""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import profiler, sym
from mxnet_tpu.models import transformer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mlp():
    d = sym.Variable("data")
    h = sym.FullyConnected(d, num_hidden=16, name="fc1")
    h = sym.Activation(h, act_type="relu")
    h = sym.FullyConnected(h, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(h, name="softmax")


def _tfm():
    return transformer.get_symbol(vocab_size=64, num_layers=2,
                                  d_model=32, n_heads=2, seq_len=16)


# ===================================================== grad_accum model


class TestGradAccumCostModel:
    def test_act_peak_prices_microbatch(self):
        from mxnet_tpu.analysis import tuning
        shapes = {"data": (8, 16), "softmax_label": (8, 16)}
        batch_inputs = ["data", "softmax_label"]
        r1 = tuning.cost_report(_tfm(), shapes,
                                batch_inputs=batch_inputs)
        r4 = tuning.cost_report(_tfm(), shapes, grad_accum=4,
                                batch_inputs=batch_inputs)
        c1, c4 = r1.extras["cost"], r4.extras["cost"]
        assert c1["grad_accum"] == 1 and c4["grad_accum"] == 4
        # no scan at N=1: no gradient carry priced
        assert c1["grad_carry_bytes"] == 0
        assert c4["grad_carry_bytes"] > 0
        # microbatch activations (carry excluded) must shrink
        act1 = c1["activation_peak_bytes"] - c1["grad_carry_bytes"]
        act4 = c4["activation_peak_bytes"] - c4["grad_carry_bytes"]
        assert act4 < act1
        # FLOPs stay full-batch: the scan still runs all N microbatches
        assert c4["flops"] == c1["flops"]

    def test_accum_must_divide_batch(self):
        from mxnet_tpu.analysis import tuning
        shapes = {"data": (6, 16), "softmax_label": (6, 16)}
        r = tuning.cost_report(_tfm(), shapes, grad_accum=4,
                               batch_inputs=["data", "softmax_label"])
        c = r.extras["cost"]
        # 4 does not divide 6: no scaling, no carry — full-batch pricing
        assert c["grad_carry_bytes"] == 0

    @pytest.mark.slow
    def test_parity_program_memory_transformer(self):
        """The model's N=1 -> N=4 activation scaling must match the
        measured program twin: the real grad program at the full batch
        vs at the microbatch slice (what one ``lax.scan`` iteration
        materializes), both via ``analyze_program_memory``."""
        import jax
        import jax.numpy as jnp
        from mxnet_tpu.analysis import analyze_program_memory, tuning

        net = _tfm()
        B, N = 8, 4
        shapes = {"data": (B, 16), "softmax_label": (B, 16)}
        bi = ["data", "softmax_label"]
        c1 = tuning.cost_report(net, shapes,
                                batch_inputs=bi).extras["cost"]
        c4 = tuning.cost_report(net, shapes, grad_accum=N,
                                batch_inputs=bi).extras["cost"]
        model_ratio = (
            (c1["activation_peak_bytes"] - c1["grad_carry_bytes"])
            / (c4["activation_peak_bytes"] - c4["grad_carry_bytes"]))

        def measured_peak(b):
            m = mx.mod.Module(net, context=mx.cpu(0))
            m.bind(data_shapes=[("data", (b, 16))],
                   label_shapes=[("softmax_label", (b, 16))])
            m.init_params(mx.init.Xavier())
            ex = m._exec
            fn = ex._fn
            params = {n: a.data for n, a in ex.arg_dict.items()
                      if n not in ("data", "softmax_label")}
            inputs = {n: ex.arg_dict[n].data
                      for n in ("data", "softmax_label")}
            key = jax.random.PRNGKey(0)

            def g(p):
                def loss_fn(p_):
                    return fn({**p_, **inputs}, {}, key, True)
                (outs, new_aux), vjp = jax.vjp(loss_fn, p)
                cts = [jnp.ones_like(o) for o in outs]
                return vjp((cts, {k: jnp.zeros_like(v)
                                  for k, v in new_aux.items()}))[0]

            return analyze_program_memory(g, params).extras[
                "program_memory"]["activation_peak_bytes"]

        measured_ratio = measured_peak(B) / measured_peak(B // N)
        # both ratios sit between 1 (all weight-side) and N (all
        # batch-side); the model must land within 35% of the program
        assert 1.0 < model_ratio <= N + 0.01
        assert 1.0 < measured_ratio <= N + 0.01
        assert abs(model_ratio - measured_ratio) <= 0.35 * measured_ratio, \
            "model %.2fx vs program %.2fx" % (model_ratio, measured_ratio)


# ====================================================== search statics


class TestSpaceAndPrune:
    def test_space_deterministic_default_first(self):
        from mxnet_tpu.tune.space import DEFAULT, enumerate_space
        s1 = enumerate_space(32)
        s2 = enumerate_space(32)
        assert s1 == s2
        assert s1[0] == DEFAULT
        assert len(set(s1)) == len(s1)
        # grad_accum rungs must divide the batch
        assert {c.grad_accum for c in enumerate_space(6)} == {1, 2}

    def test_budget_prunes_and_audits(self):
        from mxnet_tpu.tune.prune import static_rank
        from mxnet_tpu.tune.space import enumerate_space
        shapes = {"data": (8, 16), "softmax_label": (8, 16)}
        cands = enumerate_space(8)
        with profiler.counter_delta() as d:
            kept, audit = static_rank(
                _tfm(), shapes, ["data", "softmax_label"], cands,
                budget_bytes=1)   # nothing fits in 1 byte
        assert kept == []
        assert d.get("tune_pruned") == len(cands)
        assert all(a["fate"] == "pruned" for a in audit)
        assert all("budget" in a["why"] for a in audit)
        # unbudgeted: everything survives, rank is deterministic
        kept2, _ = static_rank(_tfm(), shapes,
                               ["data", "softmax_label"], cands)
        kept3, _ = static_rank(_tfm(), shapes,
                               ["data", "softmax_label"], cands)
        assert kept2 == kept3 and len(kept2) == len(cands)

    def test_static_rank_multi_device_layout_ties(self):
        """Regression: DEFAULT (layout=None) ties the top-ranked layout
        candidate with default knobs on the whole score prefix, so the
        final tie-break must be total-orderable — the old raw-Candidate
        tail raised TypeError comparing a None layout against a tuple,
        crashing every multi-device search."""
        from mxnet_tpu.analysis.tuning import rank_layouts
        from mxnet_tpu.tune.prune import static_rank
        from mxnet_tpu.tune.space import DEFAULT, enumerate_space
        shapes = {"data": (8, 16), "softmax_label": (8, 16)}
        layout_rank = rank_layouts(8, param_bytes=1 << 20,
                                   activation_bytes=1 << 18)
        layouts = [(r["data"], r["fsdp"], r["tp"]) for r in layout_rank]
        cands = enumerate_space(8, n_devices=8, layouts=layouts)
        assert DEFAULT in cands
        kept, audit = static_rank(_tfm(), shapes,
                                  ["data", "softmax_label"], cands,
                                  layout_rank=layout_rank)
        assert len(kept) == len(cands)
        # the rank is a pure total order: input order cannot change it
        kept2, _ = static_rank(_tfm(), shapes, ["data", "softmax_label"],
                               list(reversed(cands)),
                               layout_rank=layout_rank)
        assert kept == kept2

    def test_rank_layouts_comm_model(self):
        from mxnet_tpu.analysis.tuning import rank_layouts
        recs = rank_layouts(8, param_bytes=1 << 20,
                            activation_bytes=1 << 18)
        assert all(r["data"] * r["fsdp"] * r["tp"] == 8 for r in recs)
        # pure data-parallel ranks ahead of pure TP for a param-dominated
        # net (TP all-reduces activations per layer but FSDP/TP shard
        # memory; comm model orders, mem breaks ties)
        assert recs == sorted(recs, key=lambda r: (r["comm_bytes"],
                                                   r["mem_bytes"],
                                                   -r["data"]))


class TestSearchDeterminism:
    def test_static_search_identical(self, tmp_path):
        from mxnet_tpu.tune import search
        net = _mlp()
        kw = dict(optimizer="sgd", budget="1G", mode="static",
                  use_store=False, seed=3)
        a = search(net, [("data", (16, 8))], [("softmax_label", (16,))],
                   **kw)
        b = search(net, [("data", (16, 8))], [("softmax_label", (16,))],
                   **kw)
        # identical up to wall-clock (searched_s is timing, not decision)
        da = {k: v for k, v in a.to_dict().items() if k != "searched_s"}
        db = {k: v for k, v in b.to_dict().items() if k != "searched_s"}
        assert da == db
        assert a.source == "static"
        assert a.key == b.key

    def test_program_key_sensitivity(self):
        from mxnet_tpu.tune.store import program_key
        j = _mlp().tojson()
        base = program_key(j, [("data", (16, 8))], [], "sgd", {}, "1G", 1)
        assert base == program_key(j, [("data", (16, 8))], [], "sgd",
                                   {}, "1G", 1)
        assert base != program_key(j, [("data", (32, 8))], [], "sgd",
                                   {}, "1G", 1)
        assert base != program_key(j, [("data", (16, 8))], [], "adam",
                                   {}, "1G", 1)
        assert base != program_key(j, [("data", (16, 8))], [], "sgd",
                                   {}, "2G", 1)
        assert base != program_key(j, [("data", (16, 8))], [], "sgd",
                                   {}, "1G", 8)
        assert base != program_key(_tfm().tojson(), [("data", (16, 8))],
                                   [], "sgd", {}, "1G", 1)


# ========================================================== the store


class TestStore:
    def test_round_trip(self, tmp_path, monkeypatch):
        from mxnet_tpu.tune.space import Candidate
        from mxnet_tpu.tune.store import (TunedConfig, load_config,
                                          store_config)
        monkeypatch.setenv("MXNET_TPU_TUNE_STORE", str(tmp_path))
        cfg = TunedConfig(candidate=Candidate(grad_accum=4,
                                              async_window=0),
                          key="k" * 64, source="probe",
                          score={"mfu": 0.5}, searched_s=1.25,
                          n_probed=3, n_pruned=7)
        with profiler.counter_delta() as d:
            path = store_config(cfg)
            got = load_config("k" * 64)
        assert path and os.path.exists(path)
        assert d.get("tune_store_write") == 1
        assert d.get("tune_store_hit") == 1
        assert got.to_dict() == cfg.to_dict()
        assert got.candidate.grad_accum == 4

    def test_miss_and_future_version(self, tmp_path, monkeypatch):
        from mxnet_tpu.tune.store import load_config
        monkeypatch.setenv("MXNET_TPU_TUNE_STORE", str(tmp_path))
        with profiler.counter_delta() as d:
            assert load_config("absent" * 10) is None
        assert d.get("tune_store_miss") == 1
        with open(os.path.join(str(tmp_path),
                               "tune-%s.json" % ("v" * 64)), "w") as f:
            json.dump({"version": 99, "candidate": {}}, f)
        assert load_config("v" * 64) is None

    def test_no_store_dir_is_none(self, monkeypatch):
        from mxnet_tpu.tune.space import Candidate
        from mxnet_tpu.tune.store import TunedConfig, store_config
        monkeypatch.delenv("MXNET_TPU_TUNE_STORE", raising=False)
        monkeypatch.delenv("MXNET_TPU_COMPILE_CACHE", raising=False)
        assert store_config(TunedConfig(candidate=Candidate(),
                                        key="x" * 64)) is None


# =================================================== probes + fit(tune=)


def _fit_data(nbatch=4, batch=8):
    X = np.zeros((nbatch * batch, 8), np.float32)
    Y = np.zeros((nbatch * batch,), np.float32)
    return mx.io.NDArrayIter(X, Y, batch_size=batch)


@pytest.mark.slow
class TestProbeIsolation:
    def test_probes_leak_nothing_into_parent(self, tmp_path):
        from mxnet_tpu.tune import search
        before_counters = dict(profiler.counters())
        before_execs = {e.get("label")
                        for e in mx.obs.report()["executors"]}
        cfg = search(_mlp(), [("data", (8, 8))],
                     [("softmax_label", (8,))], optimizer="sgd",
                     mode="auto", probe_steps=2, max_probes=1,
                     probe_deadline_s=240, use_store=False)
        # max_probes budgets the RANKED candidates; the default is
        # always probed in addition (the MAX_PROBES help-text contract)
        assert cfg.n_probed == 2
        after = profiler.counters()
        # the probe's own loop/aot/obs counters must NOT appear here;
        # only the tuner's bookkeeping may move
        moved = {k for k in after
                 if after[k] != before_counters.get(k, 0)}
        # the static phase legitimately moves analysis_* hazard counters
        assert all(k.startswith(("tune", "analysis")) for k in moved), \
            moved
        # no executable registered in the parent's obs accounting
        after_execs = {e.get("label")
                       for e in mx.obs.report()["executors"]}
        assert after_execs == before_execs
        # probe subprocesses must not leave knob overrides behind
        assert mx.config.get("MXNET_TPU_ASYNC_WINDOW") == 2

    def test_failed_probe_keeps_partials(self):
        from mxnet_tpu.tune.probe import run_probe
        # an unparseable spec: the child dies, the parent scores it
        # failed and moves on — no exception, counters tell the story
        with profiler.counter_delta() as d:
            score = run_probe({"candidate": {}, "symbol": "not json",
                               "data_shapes": [], "label_shapes": [],
                               "steps": 1, "optimizer": "sgd"},
                              deadline_s=240)
        assert score["ok"] is False and score["why"]
        assert d.get("tune_probe") == 1
        assert d.get("tune_probe_fail") == 1


@pytest.mark.slow
class TestFitTune:
    def test_fit_applies_static_winner(self):
        with profiler.counter_delta() as d:
            mod = mx.mod.Module(_mlp(), context=mx.cpu(0))
            mod.fit(_fit_data(), num_epoch=1, tune="static",
                    optimizer_params={"learning_rate": 0.01})
        assert d.get("tune_applied") == 1
        assert not d.get("tune_probe")   # static mode: no probes
        assert not d.get("loop_recompile")

    def test_explicit_args_beat_tuned(self):
        # caller's grad_accum wins over whatever the tuner picked
        mod = mx.mod.Module(_mlp(), context=mx.cpu(0))
        mod.fit(_fit_data(), num_epoch=1, tune="static", grad_accum=2,
                optimizer_params={"learning_rate": 0.01})
        assert mod._grad_accum == 2

    def test_tuned_knobs_do_not_outlive_fit(self):
        # the winner's config overrides are fit-scoped: a later fit
        # with tune off must not inherit them, and a pre-existing user
        # override must survive the tuned fit untouched
        from mxnet_tpu import config as _cfg
        knobs = ("MXNET_TPU_REMAT", "MXNET_TPU_SCAN_LAYERS",
                 "MXNET_TPU_GROUP_UPDATE", "MXNET_TPU_ASYNC_WINDOW")
        _cfg.set("MXNET_TPU_REMAT", "off")
        try:
            before = _cfg.snapshot_overrides(knobs)
            mod = mx.mod.Module(_mlp(), context=mx.cpu(0))
            mod.fit(_fit_data(), num_epoch=1, tune="static",
                    optimizer_params={"learning_rate": 0.01})
            assert _cfg.snapshot_overrides(knobs) == before
        finally:
            for k in knobs:
                _cfg.reset(k)


def test_config_snapshot_restore_overrides():
    """The scoped-set primitive fit(tune=) rides: restore re-instates
    old overrides and DROPS ones that did not exist (back to
    environment/default, not a frozen copy of the computed value)."""
    from mxnet_tpu import config as _cfg
    names = ("MXNET_TPU_REMAT", "MXNET_TPU_ASYNC_WINDOW")
    _cfg.set("MXNET_TPU_ASYNC_WINDOW", 3)
    try:
        snap = _cfg.snapshot_overrides(names)
        _cfg.set("MXNET_TPU_REMAT", "auto")
        _cfg.set("MXNET_TPU_ASYNC_WINDOW", 0)
        _cfg.restore_overrides(snap)
        assert _cfg.get("MXNET_TPU_ASYNC_WINDOW") == 3
        # REMAT had no override: restore drops it entirely (back to
        # environment/default) instead of pinning the computed value
        assert _cfg.snapshot_overrides(names) == snap
        assert snap["MXNET_TPU_REMAT"] is _cfg._NO_OVERRIDE
    finally:
        for k in names:
            _cfg.reset(k)


# ======================================================= zero-cost gate


def test_tune_off_is_zero_cost():
    """With MXNET_TPU_TUNE unset, a full fit must never import the
    tuner package nor touch a tune_* counter."""
    prog = textwrap.dedent("""
        import sys
        sys.path.insert(0, %r)
        import numpy as np
        import mxnet_tpu as mx
        from mxnet_tpu import sym
        d = sym.Variable("data")
        net = sym.SoftmaxOutput(
            sym.FullyConnected(d, num_hidden=4), name="softmax")
        X = np.zeros((16, 8), np.float32)
        Y = np.zeros((16,), np.float32)
        it = mx.io.NDArrayIter(X, Y, batch_size=8)
        mod = mx.mod.Module(net, context=mx.cpu(0))
        mod.fit(it, num_epoch=1,
                optimizer_params={"learning_rate": 0.01})
        bad_mods = [m for m in sys.modules
                    if m.startswith("mxnet_tpu.tune")]
        assert not bad_mods, bad_mods
        bad_counters = [k for k in mx.profiler.counters()
                        if k.startswith("tune")]
        assert not bad_counters, bad_counters
        print("TUNE_ZERO_COST_OK")
    """) % (REPO,)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="")
    for k in list(env):
        if k.startswith("MXNET_TPU_TUNE"):
            env.pop(k)
    res = subprocess.run([sys.executable, "-c", prog], env=env,
                         capture_output=True, text=True, timeout=240)
    assert res.returncode == 0, res.stderr
    assert "TUNE_ZERO_COST_OK" in res.stdout

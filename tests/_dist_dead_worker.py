"""Worker body for the dead-node detection test: rank N-1 dies abruptly
(os._exit, no clean coordinator leave), survivors assert
``get_num_dead_node() > 0`` via heartbeat staleness (reference:
ps-lite heartbeats feeding kvstore.h:287; SURVEY §5.3).

Run via tools/launch.py by tests/test_dist.py; NOT collected by pytest.
No collectives happen after the death point — gloo would hang on a
missing member; liveness flows through the coordinator KV store only.
"""
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main():
    outdir = sys.argv[1]
    import mxnet_tpu as mx
    from mxnet_tpu import config as _config
    from mxnet_tpu.parallel import dist

    # fast staleness for the test: dead after 3s without a new beat
    _config.set("MXNET_KVSTORE_HEARTBEAT_STALE_SECS", 3.0)

    kv = mx.kv.create("dist_sync")
    rank, n = kv.rank, kv.num_workers
    assert n >= 3, "dead-node test wants >= 3 workers"

    # everyone synchronizes once while all are alive; all heartbeats seen
    kv.init(0, mx.nd.ones((2, 2)))
    kv.push(0, mx.nd.ones((2, 2)))
    out = mx.nd.zeros((2, 2))
    kv.pull(0, out=out)
    assert kv.get_num_dead_node(0, timeout=2) == 0

    if rank == n - 1:
        # die without cleanup: heartbeat freezes at its last counter
        os._exit(0)

    # survivors: poll until the victim's beat goes stale (needs two
    # observations of the same counter separated by the stale window)
    deadline = time.monotonic() + 60
    dead = 0
    while time.monotonic() < deadline:
        dead = kv.get_num_dead_node(0, timeout=2)
        if dead > 0:
            break
        time.sleep(1.0)
    assert dead > 0, "dead worker was never detected"
    with open(os.path.join(outdir, "dead_seen_rank%d" % rank), "w") as f:
        f.write(str(dead))
    print("rank %d saw %d dead node(s) OK" % (rank, dead), flush=True)
    sys.stdout.flush()
    # exit order matters: rank 0 hosts the coordination service, so any
    # survivor still holding a client when it vanishes gets a fatal
    # "leader died" abort. Non-leaders publish done and hard-exit at
    # once; the leader waits for their keys and leaves last. Hard exits
    # everywhere skip jax's clean shutdown, whose barrier would wait on
    # the dead member and flag the whole job fatal.
    client = dist._client()
    if rank != 0:
        client.key_value_set("mxnet_dead_test_done/%d" % rank, "1")
        os._exit(0)
    for r in range(1, n - 1):
        client.blocking_key_value_get("mxnet_dead_test_done/%d" % r, 30000)
    os._exit(0)


if __name__ == "__main__":
    main()

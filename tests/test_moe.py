"""Expert parallelism (parallel/moe.py): routing, capacity, the sharded
all_to_all lowering, and exactness against a dense oracle.

The reference has no MoE (SURVEY.md §2.21) — this is the TPU build's
modern-capability extension; tests follow the repo's numpy-oracle style.
f64 is used for tight comparisons because this backend's f32 matmuls run
at DEFAULT (bf16-accumulate) precision on CPU.
"""
import numpy as np
import pytest

import jax
from jax import experimental as jax_experimental
import jax.numpy as jnp

from mxnet_tpu.parallel import make_mesh
from mxnet_tpu.parallel.moe import moe_init, moe_apply


def _dense_oracle(params, x, k=2):
    """Apply every expert to every token; gather top-k with renormalized
    gates (no capacity drops)."""
    logits = x @ params["router"]
    probs = jax.nn.softmax(logits)
    gate, idx = jax.lax.top_k(probs, k)
    gate = gate / gate.sum(-1, keepdims=True)
    h = jax.nn.gelu(jnp.einsum("td,edh->teh", x, params["wi"]))
    y = jnp.einsum("teh,ehd->ted", h, params["wo"])
    sel = jnp.take_along_axis(y, idx[:, :, None], axis=1)
    return jnp.einsum("tk,tkd->td", gate, sel)


def test_moe_matches_dense_oracle_f64():
    with jax_experimental.enable_x64():
        rng = np.random.RandomState(0)
        T, D, H, E = 64, 16, 32, 8
        params = moe_init(rng, D, H, E, dtype=np.float64)
        x = rng.normal(0, 1, (T, D))
        out, aux = moe_apply(params, x, top_k=2, capacity_factor=8.0)
        ref = _dense_oracle(params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-10, atol=1e-12)
        assert float(aux) > 0


def test_moe_capacity_drops_tokens():
    with jax_experimental.enable_x64():
        rng = np.random.RandomState(1)
        T, D, H, E = 32, 8, 16, 4
        params = moe_init(rng, D, H, E, dtype=np.float64)
        # route everything to one expert: tokens over capacity must differ
        # from the ample-capacity result
        params["router"][:, 0] = 5.0
        x = rng.normal(0, 1, (T, D))
        full, _ = moe_apply(params, x, top_k=1, capacity_factor=E * 1.0)
        tight, _ = moe_apply(params, x, top_k=1, capacity_factor=0.25)
        assert not np.allclose(np.asarray(full), np.asarray(tight))
        # dropped tokens produce zero output rows (gate renorm denom -> 0)
        n_zero = int(np.sum(np.all(np.asarray(tight) == 0, axis=1)))
        assert n_zero > 0


def test_moe_sharded_matches_unsharded():
    mesh = make_mesh({"expert": 8})
    with jax_experimental.enable_x64():
        rng = np.random.RandomState(2)
        T, D, H, E = 64, 16, 32, 8
        params = moe_init(rng, D, H, E, dtype=np.float64)
        x = rng.normal(0, 1, (T, D))
        out, _ = moe_apply(params, x, capacity_factor=8.0)
        out_sh, _ = jax.jit(
            lambda p, xx: moe_apply(p, xx, capacity_factor=8.0,
                                    mesh=mesh))(params, x)
        np.testing.assert_allclose(np.asarray(out_sh), np.asarray(out),
                                   rtol=1e-10, atol=1e-12)


def test_moe_sharded_lowering_redistributes_tokens():
    # dp x ep: tokens sharded over "data", experts over "expert" — the
    # dispatch einsum must move tokens across devices (GSPMD picks
    # all-to-all or all-gather depending on shapes)
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = make_mesh({"data": 2, "expert": 4})
    rng = np.random.RandomState(3)
    params = moe_init(rng, 16, 32, 8)
    x = rng.normal(0, 1, (64, 16)).astype(np.float32)
    x_sh = jax.device_put(x, NamedSharding(mesh, P("data", None)))
    txt = jax.jit(
        lambda p, xx: moe_apply(p, xx, mesh=mesh)[0]
    ).lower(params, x_sh).compile().as_text()
    assert ("all-to-all" in txt) or ("all-gather" in txt)


def test_moe_gradients_flow_and_aux_balances():
    rng = np.random.RandomState(4)
    T, D, H, E = 64, 8, 16, 4
    params = moe_init(rng, D, H, E)
    x = rng.normal(0, 1, (T, D)).astype(np.float32)

    def loss(p):
        out, aux = moe_apply(p, x)
        return jnp.mean(out ** 2) + 0.01 * aux

    g = jax.grad(loss)(params)
    for k in ("router", "wi", "wo"):
        assert float(jnp.linalg.norm(g[k])) > 0, k
    # perfectly uniform routing minimizes the GShard aux loss at 1.0
    _, aux = moe_apply(params, x)
    assert float(aux) >= 1.0 - 1e-3


def test_moe_bf16_routing_exact_beyond_256_assignments():
    # Routing bookkeeping must be exact in int32: with bf16 activations the
    # cumsum position counters saturate at 256 (bf16 has 8 mantissa bits),
    # so tokens past the 256th collide in one capacity slot and their
    # dispatched activations get summed together. Force every token to one
    # expert with ample capacity; each token's output must then equal the
    # dense bf16 FFN of that token alone.
    rng = np.random.RandomState(7)
    T, D, H, E = 1024, 16, 32, 4          # 1024 assignments to expert 0
    params = moe_init(rng, D, H, E)
    params["router"] = np.zeros((D, E), np.float32)
    x = rng.normal(0, 1, (T, D)).astype(np.float32)
    x[:, 0] = 5.0                          # all tokens prefer expert 0
    params["router"][0, 0] = 10.0
    p16 = {k: jnp.asarray(v, jnp.bfloat16) for k, v in params.items()}
    x16 = jnp.asarray(x, jnp.bfloat16)

    out, _ = moe_apply(p16, x16, top_k=1, capacity_factor=float(E))
    dense = jax.nn.gelu(x16 @ p16["wi"][0]) @ p16["wo"][0]
    err = jnp.max(jnp.abs((out - dense).astype(jnp.float32)))
    scale = float(jnp.max(jnp.abs(dense.astype(jnp.float32)))) + 1e-6
    assert float(err) / scale < 0.05, float(err) / scale

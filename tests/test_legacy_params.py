"""Reference-binary .params compatibility (ndarray/legacy_format.py).

The fixtures are hand-packed with struct against the reference layout
(src/ndarray/ndarray.cc:666-770: NDARRAY_V1_MAGIC records inside the
kMXAPINDArrayListMagic list container), so compatibility is pinned at
the byte level rather than through our own writer alone.
"""
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray import legacy_format as lf


def _pack_v1(arr, dev=(1, 0)):
    out = [struct.pack("<I", 0xF993FAC8),
           struct.pack("<I", arr.ndim),
           struct.pack("<%dq" % arr.ndim, *arr.shape)]
    out.append(struct.pack("<ii", *dev))
    flag = {np.dtype(np.float32): 0, np.dtype(np.float64): 1,
            np.dtype(np.float16): 2, np.dtype(np.uint8): 3,
            np.dtype(np.int32): 4, np.dtype(np.int8): 5,
            np.dtype(np.int64): 6}[arr.dtype]
    out.append(struct.pack("<i", flag))
    out.append(arr.tobytes())
    return b"".join(out)


def _pack_v0(arr):
    # pre-V1: the magic slot IS ndim, dims are uint32
    out = [struct.pack("<I", arr.ndim),
           struct.pack("<%dI" % arr.ndim, *arr.shape),
           struct.pack("<ii", 1, 0), struct.pack("<i", 0),
           arr.tobytes()]
    return b"".join(out)


def _container(blobs, names):
    parts = [struct.pack("<QQ", 0x112, 0), struct.pack("<Q", len(blobs))]
    parts += blobs
    parts.append(struct.pack("<Q", len(names)))
    for nm in names:
        b = nm.encode()
        parts.append(struct.pack("<Q", len(b)))
        parts.append(b)
    return b"".join(parts)


def test_parse_handpacked_v1_named():
    rng = np.random.RandomState(0)
    w = rng.randn(3, 4).astype(np.float32)
    b = rng.randn(4).astype(np.float64)
    i = rng.randint(0, 100, (2, 2, 2)).astype(np.int32)
    buf = _container([_pack_v1(w), _pack_v1(b, dev=(2, 0)), _pack_v1(i)],
                     ["arg:w", "arg:b", "aux:i"])
    out = lf.load_bytes(buf)
    np.testing.assert_array_equal(out["arg:w"], w)
    np.testing.assert_array_equal(out["arg:b"], b)
    np.testing.assert_array_equal(out["aux:i"], i)


def test_parse_handpacked_v0_legacy_and_anonymous_list():
    rng = np.random.RandomState(1)
    a = rng.randn(2, 3).astype(np.float32)
    b = rng.randn(5).astype(np.float32)
    buf = _container([_pack_v0(a), _pack_v0(b)], [])
    out = lf.load_bytes(buf)
    assert isinstance(out, list) and len(out) == 2
    np.testing.assert_array_equal(out[0], a)
    np.testing.assert_array_equal(out[1], b)


def test_save_bytes_roundtrip_and_magic():
    rng = np.random.RandomState(2)
    d = {"w": rng.randn(4, 2).astype(np.float32),
         "idx": rng.randint(0, 9, (3,)).astype(np.int64),
         "h": rng.randn(2).astype(np.float16)}
    buf = lf.save_bytes(d)
    assert lf.is_legacy_params(buf[:8])
    out = lf.load_bytes(buf)
    for k in d:
        np.testing.assert_array_equal(out[k], d[k])
        assert out[k].dtype == d[k].dtype


def test_nd_save_load_mxnet_format(tmp_path):
    p = str(tmp_path / "c.params")
    d = {"a": mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3)),
         "b": mx.nd.array(np.ones((4,), np.float32))}
    mx.nd.save(p, d, format="mxnet")
    # the on-disk head must carry the reference magic, not npz
    with open(p, "rb") as f:
        assert lf.is_legacy_params(f.read(8))
    out = mx.nd.load(p)
    np.testing.assert_array_equal(out["a"].asnumpy(),
                                  d["a"].asnumpy())
    np.testing.assert_array_equal(out["b"].asnumpy(),
                                  d["b"].asnumpy())


def test_zoo_pretrained_path_roundtrip(tmp_path):
    from mxnet_tpu.gluon.model_zoo import vision
    rng = np.random.RandomState(3)
    x = mx.nd.array(rng.rand(2, 3, 32, 32).astype(np.float32))

    net = vision.get_model("squeezenet1_0", classes=10)
    net.initialize(mx.init.Xavier())
    ref = net(x).asnumpy()
    p = str(tmp_path / "sq.params")
    mx.nd.save(p, {k: v.data() for k, v in net.collect_params().items()},
               format="mxnet")

    net2 = vision.get_model("squeezenet1_0", classes=10, pretrained=p)
    np.testing.assert_allclose(net2(x).asnumpy(), ref, rtol=1e-5,
                               atol=1e-6)

    with pytest.raises(ValueError, match="download"):
        vision.get_model("squeezenet1_0", pretrained=True)


def test_predictor_reference_era_checkpoint(tmp_path):
    """A checkpoint in the reference's on-disk formats end to end —
    symbol JSON (0.8-era schema) + binary .params with arg:/aux:
    prefixes — must produce identical logits through Predictor."""
    from mxnet_tpu.models import lenet
    rng = np.random.RandomState(4)
    sym = lenet.get_symbol(num_classes=10)
    mod = mx.mod.Module(sym, context=mx.cpu(0))
    mod.bind(data_shapes=[("data", (2, 1, 28, 28))],
             label_shapes=[("softmax_label", (2,))], for_training=False)
    mod.init_params(mx.init.Xavier())
    x = rng.rand(2, 1, 28, 28).astype(np.float32)
    mod.forward(mx.io.DataBatch(data=[mx.nd.array(x)]), is_train=False)
    ref = mod.get_outputs()[0].asnumpy()

    jpath = str(tmp_path / "net-symbol.json")
    ppath = str(tmp_path / "net-0000.params")
    sym.save(jpath)
    args, auxs = mod.get_params()
    blob = {"arg:%s" % k: v for k, v in args.items()}
    blob.update({"aux:%s" % k: v for k, v in auxs.items()})
    mx.nd.save(ppath, blob, format="mxnet")

    pred = mx.predictor.Predictor(jpath, ppath,
                                  {"data": (2, 1, 28, 28)})
    out = pred.forward(data=x)[0].asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_convert_params_cli(tmp_path):
    import subprocess
    import sys as _sys
    import os as _os
    root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    src = str(tmp_path / "m.params")
    d = {"arg:w": mx.nd.array(np.arange(4, dtype=np.float32)),
         "aux:m": mx.nd.array(np.ones((2,), np.float32))}
    mx.nd.save(src, d, format="mxnet")
    out = str(tmp_path / "flat.params")
    env = dict(_os.environ); env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([_sys.executable,
                        _os.path.join(root, "tools", "convert_params.py"),
                        src, out, "--strip-prefix"],
                       capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    got = mx.nd.load(out)
    assert sorted(got) == ["m", "w"]
    np.testing.assert_array_equal(got["w"].asnumpy(),
                                  d["arg:w"].asnumpy())


def test_mixed_prefix_checkpoint_and_unsupported_dtype():
    # mixed prefixed/unprefixed keys must strip cleanly (regression:
    # an unguarded split crashed), and save_bytes must refuse dtypes the
    # reference format cannot represent instead of silently casting
    from mxnet_tpu.ndarray.legacy_format import strip_arg_aux
    d = {"arg:w": 1, "aux:m": 2, "extra_stat": 3}
    assert strip_arg_aux(d) == {"w": 1, "m": 2, "extra_stat": 3}
    with pytest.raises(ValueError, match="type flag"):
        lf.save_bytes({"ids": np.arange(3, dtype=np.uint64)})

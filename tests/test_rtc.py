"""Custom-kernel escape hatch: mx.rtc.PallasKernel + the flash-attention
showcase kernel (reference surface: python/mxnet/rtc.py / mxrtc.h §2.22 —
NVRTC there, Pallas here). Runs in Pallas interpreter mode on the CPU rig;
numerics are identical to the compiled TPU path."""
import numpy as np
import pytest

import mxnet_tpu as mx


def test_pallas_kernel_elementwise():
    def scale_add(x_ref, y_ref, o_ref):
        o_ref[:] = x_ref[:] * 2.0 + y_ref[:]

    kern = mx.rtc.PallasKernel(scale_add, ((8, 128), np.float32),
                               interpret=True)
    rng = np.random.RandomState(0)
    x = rng.rand(8, 128).astype(np.float32)
    y = rng.rand(8, 128).astype(np.float32)
    out = kern(mx.nd.array(x), mx.nd.array(y))
    np.testing.assert_allclose(out.asnumpy(), x * 2 + y, rtol=1e-6)


def test_pallas_kernel_register_as_op():
    def relu_k(x_ref, o_ref):
        import jax.numpy as jnp
        o_ref[:] = jnp.maximum(x_ref[:], 0.0)

    kern = mx.rtc.PallasKernel(relu_k, ((4, 128), np.float32),
                               interpret=True)
    kern.register("my_pallas_relu")
    x = np.random.RandomState(1).randn(4, 128).astype(np.float32)
    out = mx.nd.my_pallas_relu(mx.nd.array(x))
    np.testing.assert_allclose(out.asnumpy(), np.maximum(x, 0), rtol=1e-6)
    # symbol path too
    s = mx.sym.my_pallas_relu(mx.sym.Variable("data"))
    ex = s.simple_bind(ctx=mx.cpu(), data=(4, 128))
    ex.arg_dict["data"][:] = x
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), np.maximum(x, 0),
                               rtol=1e-6)


def test_cuda_module_points_to_pallas():
    with pytest.raises(NotImplementedError, match="Pallas"):
        mx.rtc.CudaModule("__global__ void k(){}")


def _ref_attention(q, k, v, causal=False):
    B, H, S, D = q.shape
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    if causal:
        mask = np.tril(np.ones((S, S), bool))
        s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


def test_flash_attention_matches_reference():
    import jax
    rng = np.random.RandomState(2)
    B, H, S, D = 2, 2, 256, 32
    q = rng.randn(B, H, S, D).astype(np.float32)
    k = rng.randn(B, H, S, D).astype(np.float32)
    v = rng.randn(B, H, S, D).astype(np.float32)
    from mxnet_tpu.ops.pallas import flash_attention
    # pin to CPU: on this rig raw numpy lands on the axon TPU, whose f32
    # matmuls round differently than the f64 oracle demands
    cpu = jax.local_devices(backend="cpu")[0]
    qj, kj, vj = (jax.device_put(a, cpu) for a in (q, k, v))
    out = np.asarray(flash_attention(qj, kj, vj, block_q=128, block_k=128,
                                     interpret=True))
    np.testing.assert_allclose(out, _ref_attention(q, k, v),
                               rtol=2e-4, atol=2e-5)


def test_flash_attention_causal_and_op():
    rng = np.random.RandomState(3)
    B, H, S, D = 1, 2, 128, 16
    q = rng.randn(B, H, S, D).astype(np.float32)
    k = rng.randn(B, H, S, D).astype(np.float32)
    v = rng.randn(B, H, S, D).astype(np.float32)
    out = mx.nd.FlashAttention(mx.nd.array(q), mx.nd.array(k),
                               mx.nd.array(v), causal=True,
                               block_q=64, block_k=64).asnumpy()
    np.testing.assert_allclose(out, _ref_attention(q, k, v, causal=True),
                               rtol=2e-4, atol=2e-5)


def test_flash_attention_grad_matches_xla():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas import flash_attention
    rng = np.random.RandomState(4)
    B, H, S, D = 1, 1, 128, 16
    cpu = jax.local_devices(backend="cpu")[0]
    q = jax.device_put(rng.randn(B, H, S, D).astype(np.float32), cpu)
    k = jax.device_put(rng.randn(B, H, S, D).astype(np.float32), cpu)
    v = jax.device_put(rng.randn(B, H, S, D).astype(np.float32), cpu)

    def f_flash(q, k, v):
        return flash_attention(q, k, v, interpret=True).sum()

    def f_ref(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        p = jax.nn.softmax(s, -1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_flash_attention_causal_grad_with_padded_q():
    # S not a multiple of block_q: the recompute backward must use the same
    # top-aligned causal mask as the kernel (regression: a bottom-aligned
    # tril offset corrupted every real row's gradient)
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas import flash_attention
    rng = np.random.RandomState(7)
    B, H, S, D = 1, 1, 96, 16
    cpu = jax.local_devices(backend="cpu")[0]
    q = jax.device_put(rng.randn(B, H, S, D).astype(np.float32), cpu)
    k = jax.device_put(rng.randn(B, H, S, D).astype(np.float32), cpu)
    v = jax.device_put(rng.randn(B, H, S, D).astype(np.float32), cpu)

    def f_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=64, block_k=32,
                               interpret=True).sum()

    def f_ref(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        s = jnp.where(jnp.tril(jnp.ones((S, S), bool)), s, -1e30)
        p = jax.nn.softmax(s, -1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_pallas_kernel_multi_output_symbol_visible():
    def split_k(x_ref, a_ref, b_ref):
        a_ref[:] = x_ref[:] * 2.0
        b_ref[:] = x_ref[:] + 1.0

    kern = mx.rtc.PallasKernel(
        split_k, [((4, 128), np.float32), ((4, 128), np.float32)],
        interpret=True)
    kern.register("my_pallas_split")
    x = np.random.RandomState(8).rand(4, 128).astype(np.float32)
    a, b = mx.nd.my_pallas_split(mx.nd.array(x))
    np.testing.assert_allclose(a.asnumpy(), x * 2, rtol=1e-6)
    np.testing.assert_allclose(b.asnumpy(), x + 1, rtol=1e-6)
    s = mx.sym.my_pallas_split(mx.sym.Variable("data"))
    assert len(s.list_outputs()) == 2
    ex = s.simple_bind(ctx=mx.cpu(), data=(4, 128))
    ex.arg_dict["data"][:] = x
    outs = ex.forward()
    assert len(outs) == 2
    np.testing.assert_allclose(outs[1].asnumpy(), x + 1, rtol=1e-6)


def test_flash_attention_rejects_unaligned_keys():
    q = np.zeros((1, 1, 64, 16), np.float32)
    k = np.zeros((1, 1, 100, 16), np.float32)
    with pytest.raises(ValueError, match="multiple of block_k"):
        from mxnet_tpu.ops.pallas import flash_attention
        flash_attention(q, k, k, block_k=64, interpret=True)


def test_flash_attention_fused_bwd_cross_and_bf16():
    # fused Pallas backward: rectangular (Sk != S) grads match XLA, and the
    # bf16 path stays within bf16 tolerance of the f32 oracle
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas import flash_attention
    rng = np.random.RandomState(11)
    B, H, S, Sk, D = 1, 2, 64, 128, 16
    cpu = jax.local_devices(backend="cpu")[0]
    q = jax.device_put(rng.randn(B, H, S, D).astype(np.float32), cpu)
    k = jax.device_put(rng.randn(B, H, Sk, D).astype(np.float32), cpu)
    v = jax.device_put(rng.randn(B, H, Sk, D).astype(np.float32), cpu)

    def f_flash(q, k, v):
        return flash_attention(q, k, v, block_q=32, block_k=32,
                               interpret=True).sum()

    def f_ref(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        p = jax.nn.softmax(s, -1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)

    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    g16 = jax.grad(lambda *a: f_flash(*a).astype(jnp.float32),
                   argnums=(0, 1, 2))(qb, kb, vb)
    for a, b in zip(g16, g_ref):
        err = np.max(np.abs(np.asarray(a, np.float32) - np.asarray(b)))
        scale = np.max(np.abs(np.asarray(b))) + 1e-6
        assert err / scale < 0.06, err / scale

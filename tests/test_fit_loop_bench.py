"""Tier-1 smoke for tools/perf/fit_loop_bench.py (not slow).

Runs the quick variant end-to-end (real fit() epochs, sync vs async, on
the input-bound MLP and the compute-bound stem) and asserts the
mechanics the acceptance criteria care about: zero per-batch host syncs,
zero steady-state recompiles, the prefetch stage placed every batch, and
the JSON artifact schema matches what BENCH_fit_loop.json records.
Wall-clock speedup is recorded by the full bench, not asserted here —
shared CI hosts are too noisy for a hard ratio gate (same policy as
test_trainer_step_bench / test_serve_bench).
"""
import importlib
import json
import os
import sys

import numpy as np


def _load_bench():
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "perf"))
    try:
        return importlib.import_module("fit_loop_bench")
    finally:
        sys.path.pop(0)


def test_fit_loop_bench_quick(tmp_path):
    bench = _load_bench()
    results = bench.run(quick=True)
    assert set(results) == {"mlp", "resnet_stem"}
    for name, r in results.items():
        for k in ("sync_steps_s", "async_steps_s", "speedup",
                  "batches_per_epoch", "host_syncs_per_batch",
                  "steady_state_recompiles", "prefetch_placed",
                  "window_waits", "metric_syncs"):
            assert k in r, "missing %s in %s" % (k, name)
        assert np.isfinite(r["sync_steps_s"]) and r["sync_steps_s"] > 0
        assert np.isfinite(r["async_steps_s"]) and r["async_steps_s"] > 0
        # the tentpole's counter gate: async fit never syncs per batch,
        # never recompiles after warmup, and prefetch feeds every batch
        assert r["host_syncs_per_batch"] == 0, (name, r)
        assert r["steady_state_recompiles"] == 0, (name, r)
        assert r["prefetch_placed"] == r["batches_per_epoch"], (name, r)
        assert r["metric_syncs"] == 1, (name, r)

    # artifact schema: what the driver's BENCH_fit_loop.json consumers read
    path = str(tmp_path / "BENCH_fit_loop.json")
    with open(path, "w") as f:
        json.dump({"bench": "fit_loop", "results": results}, f)
    with open(path) as f:
        loaded = json.load(f)
    assert loaded["bench"] == "fit_loop"
    assert loaded["results"]["mlp"]["async_steps_s"] == \
        results["mlp"]["async_steps_s"]

"""Image augmentation library + detection pipeline (reference tests:
tests/python/unittest/test_image.py — augmenter semantics, ImageIter
batching, detection iterator label handling)."""
import os
import random as pyrandom
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image as img_mod


def _toy_image(h=32, w=40, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, 255, (h, w, 3)).astype(np.uint8)


def test_resize_short_and_scale_down():
    img = _toy_image(32, 64)
    out = img_mod.resize_short(img, 16).asnumpy()
    assert out.shape == (16, 32, 3)
    assert img_mod.scale_down((10, 10), (20, 40)) == (5, 10)


def test_fixed_center_random_crop():
    img = _toy_image(32, 40)
    out = img_mod.fixed_crop(img, 4, 2, 8, 8).asnumpy()
    np.testing.assert_array_equal(out, img[2:10, 4:12])
    out, (x0, y0, w, h) = img_mod.center_crop(img, (20, 20))
    assert out.shape == (20, 20, 3) and (w, h) == (20, 20)
    out, (x0, y0, w, h) = img_mod.random_crop(img, (16, 16))
    assert out.shape == (16, 16, 3)
    assert 0 <= x0 <= 40 - 16 and 0 <= y0 <= 32 - 16


def test_random_size_crop_respects_bounds():
    pyrandom.seed(3)
    img = _toy_image(48, 48)
    for _ in range(5):
        out, (x0, y0, w, h) = img_mod.random_size_crop(
            img, (24, 24), 0.3, (0.75, 1.333))
        assert out.shape == (24, 24, 3)
        assert x0 + w <= 48 and y0 + h <= 48


def test_color_normalize_and_cast():
    img = _toy_image()
    mean = np.array([1.0, 2.0, 3.0], np.float32)
    std = np.array([2.0, 2.0, 2.0], np.float32)
    out = img_mod.color_normalize(img, mean, std).asnumpy()
    np.testing.assert_allclose(out, (img - mean) / std, rtol=1e-6)
    assert img_mod.CastAug()(img).dtype == np.float32


def test_horizontal_flip_p1():
    img = _toy_image()
    pyrandom.seed(0)
    out = img_mod.HorizontalFlipAug(1.0)(img).asnumpy()
    np.testing.assert_array_equal(out, img[:, ::-1])


def test_brightness_contrast_saturation_bounds():
    pyrandom.seed(1)
    img = _toy_image().astype(np.float32)
    out = img_mod.BrightnessJitterAug(0.5)(img).asnumpy()
    ratio = out.sum() / img.sum()
    assert 0.5 - 1e-5 <= ratio <= 1.5 + 1e-5
    out = img_mod.SaturationJitterAug(1.0)(img).asnumpy()
    assert out.shape == img.shape and np.isfinite(out).all()
    out = img_mod.ContrastJitterAug(1.0)(img).asnumpy()
    assert np.isfinite(out).all()


def test_hue_zero_is_identity():
    # the truncated YIQ matrix constants round-trip to ~0.3% of the uint8
    # range, not exactly
    img = _toy_image().astype(np.float32)
    out = img_mod.HueJitterAug(0.0)(img).asnumpy()
    np.testing.assert_allclose(out, img, atol=1.0)


def test_create_augmenter_end_to_end():
    pyrandom.seed(0)
    augs = img_mod.CreateAugmenter((3, 24, 24), resize=28, rand_crop=True,
                                   rand_mirror=True, brightness=0.1,
                                   contrast=0.1, saturation=0.1, hue=0.1,
                                   pca_noise=0.05, mean=True, std=True)
    img = _toy_image(50, 60)
    out = img
    for a in augs:
        out = a(out)
    arr = out.asnumpy()
    assert arr.shape == (24, 24, 3)
    assert arr.dtype == np.float32
    assert abs(arr.mean()) < 3.0      # roughly normalized


def _write_imglist_pngs(tmpdir, n=6):
    import cv2
    entries = []
    for i in range(n):
        path = os.path.join(tmpdir, "img%d.png" % i)
        cv2.imwrite(path, _toy_image(40, 40, seed=i))
        entries.append([float(i % 3), path])
    return entries


def test_image_iter_from_imglist():
    with tempfile.TemporaryDirectory() as td:
        entries = _write_imglist_pngs(td)
        it = img_mod.ImageIter(batch_size=4, data_shape=(3, 24, 24),
                               imglist=entries, shuffle=False,
                               rand_crop=True, rand_mirror=True)
        batch = it.next()
        assert batch.data[0].shape == (4, 3, 24, 24)
        assert batch.label[0].shape == (4,)
        batch2 = it.next()           # 2 real + 2 pad
        assert batch2.pad == 2
        with pytest.raises(StopIteration):
            it.next()
        it.reset()
        assert it.next().data[0].shape == (4, 3, 24, 24)


def test_image_record_iter_aug_list():
    import cv2
    from mxnet_tpu import recordio
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "data.rec")
        rec = recordio.MXRecordIO(path, "w")
        for i in range(4):
            ok, enc = cv2.imencode(".png", _toy_image(36, 36, seed=i))
            header = recordio.IRHeader(0, float(i), i, 0)
            rec.write(recordio.pack(header, enc.tobytes()))
        rec.close()
        augs = [img_mod.CenterCropAug((20, 20)), img_mod.CastAug(),
                img_mod.ColorNormalizeAug(np.zeros(3), np.full(3, 255.0))]
        it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 20, 20),
                                   batch_size=2, aug_list=augs)
        batch = next(iter(it))
        assert batch.data[0].shape == (2, 3, 20, 20)
        assert float(batch.data[0].asnumpy().max()) <= 1.0


# ------------------------------------------------------------- detection


def _det_label(rows):
    return np.asarray(rows, np.float32)


def test_det_horizontal_flip_flips_boxes():
    pyrandom.seed(0)
    img = _toy_image()
    label = _det_label([[1, 0.1, 0.2, 0.4, 0.6], [-1, 0, 0, 0, 0]])
    aug = img_mod.DetHorizontalFlipAug(1.0)
    out, lbl = aug(img, label)
    np.testing.assert_array_equal(out.asnumpy(), img[:, ::-1])
    np.testing.assert_allclose(lbl[0, 1:5], [0.6, 0.2, 0.9, 0.6],
                               rtol=1e-6)
    assert lbl[1, 0] == -1


def test_det_random_crop_keeps_valid_boxes():
    pyrandom.seed(5)
    img = _toy_image(64, 64)
    label = _det_label([[0, 0.3, 0.3, 0.7, 0.7]])
    aug = img_mod.DetRandomCropAug(min_object_covered=0.3,
                                   area_range=(0.3, 1.0))
    for _ in range(5):
        out, lbl = aug(img, label)
        kept = lbl[lbl[:, 0] >= 0]
        assert len(kept) >= 1
        assert (kept[:, 1:5] >= -1e-6).all() and (kept[:, 1:5] <= 1 + 1e-6).all()


def test_det_random_pad_shrinks_boxes():
    pyrandom.seed(2)
    img = _toy_image(32, 32)
    label = _det_label([[0, 0.0, 0.0, 1.0, 1.0]])
    aug = img_mod.DetRandomPadAug(area_range=(2.0, 2.0))
    out, lbl = aug(img, label)
    w = lbl[0, 3] - lbl[0, 1]
    h = lbl[0, 4] - lbl[0, 2]
    assert w < 1.0 and h < 1.0


def test_image_det_iter_and_ssd_target_flow():
    """An ImageDetIter batch must flow into MultiBoxTarget — the §2.15 SSD
    data-path capability gate."""
    import cv2
    with tempfile.TemporaryDirectory() as td:
        entries = []
        for i in range(4):
            path = os.path.join(td, "d%d.png" % i)
            cv2.imwrite(path, _toy_image(48, 48, seed=i))
            # one box per image, flat [cls x1 y1 x2 y2]
            entries.append([float(i % 2), 0.2, 0.2, 0.8, 0.8, path])
        it = img_mod.ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                                  imglist=entries, rand_mirror=True,
                                  mean=True, std=True)
        batch = it.next()
        assert batch.data[0].shape == (2, 3, 32, 32)
        assert batch.label[0].shape[0] == 2 and batch.label[0].shape[2] == 5
        anchors = mx.nd.MultiBoxPrior(mx.nd.zeros((1, 3, 8, 8)),
                                      sizes=(0.4, 0.8), ratios=(1.0,))
        cls_pred = mx.nd.zeros((2, 3, anchors.shape[1]))
        bt, bm, ct = mx.nd.MultiBoxTarget(anchors, batch.label[0], cls_pred)
        assert np.isfinite(bt.asnumpy()).all()
        assert (ct.asnumpy() >= 0).any()


def test_image_record_iter_aug_error_surfaces():
    # a broken aug pipeline must raise in next(), not hang the loader
    import cv2
    from mxnet_tpu import recordio
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "data.rec")
        rec = recordio.MXRecordIO(path, "w")
        for i in range(2):
            ok, enc = cv2.imencode(".png", _toy_image(30 + i, 30, seed=i))
            rec.write(recordio.pack(recordio.IRHeader(0, float(i), i, 0),
                                    enc.tobytes()))
        rec.close()
        it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 20, 20),
                                   batch_size=2,
                                   aug_list=[img_mod.CastAug()])  # no crop
        with pytest.raises(ValueError, match="crop/resize"):
            it.next()


def test_det_parse_label_header_width_2():
    entries = [[2, 5, 1.0, 0.1, 0.1, 0.5, 0.5, "unused.png"]]
    # construct without reading the file: use _parse_label directly
    flat = np.asarray(entries[0][:-1], np.float32)
    it = img_mod.ImageDetIter.__new__(img_mod.ImageDetIter)
    it._ow = 5
    lbl = it._parse_label(flat)
    assert lbl.shape == (1, 5)
    np.testing.assert_allclose(lbl[0], [1.0, 0.1, 0.1, 0.5, 0.5])


def test_det_random_crop_covers_small_object():
    # a crop fully containing a small box has coverage 1.0 and must be
    # accepted (regression: IoU semantics rejected every attempt)
    pyrandom.seed(0)
    img = _toy_image(64, 64)
    label = _det_label([[0, 0.45, 0.45, 0.55, 0.55]])   # tiny box
    aug = img_mod.DetRandomCropAug(min_object_covered=0.9,
                                   area_range=(0.5, 0.9))
    hit = False
    for _ in range(10):
        out, lbl = aug(img, label)
        if _to_np(out).shape != img.shape:
            hit = True
            kept = lbl[lbl[:, 0] >= 0]
            assert len(kept) == 1
    assert hit, "crop never fired on a fully-contained small object"


def _to_np(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else np.asarray(x)


def test_image_det_record_iter():
    import cv2
    from mxnet_tpu import recordio
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "det.rec")
        rec = recordio.MXRecordIO(path, "w")
        for i in range(3):
            ok, enc = cv2.imencode(".png", _toy_image(40, 40, seed=i))
            # det header form: [4, 5, pad, pad, cls x1 y1 x2 y2]
            label = np.array([4, 5, 0, 0, 1.0, 0.1, 0.1, 0.5, 0.5],
                             np.float32)
            header = recordio.IRHeader(0, label, i, 0)
            rec.write(recordio.pack(header, enc.tobytes()))
        rec.close()
        it = mx.io.ImageDetRecordIter(path_imgrec=path,
                                      data_shape=(3, 24, 24), batch_size=3)
        batch = it.next()
        assert batch.data[0].shape == (3, 3, 24, 24)
        lbl = batch.label[0].asnumpy()
        assert lbl.shape == (3, 1, 5)
        np.testing.assert_allclose(lbl[0, 0], [1.0, 0.1, 0.1, 0.5, 0.5],
                                   atol=1e-6)

"""Model parallelism (group2ctx) and multi-device Gluon, executed on the
virtual CPU mesh (reference: tests/python/unittest/test_model_parallel.py +
test_multi_device_exec.py run the same on cpu(0)/cpu(1) pairs)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon


def _split_mlp():
    """Two FC stages pinned to different ctx groups (the reference
    test_model_parallel.py net shape)."""
    with mx.AttrScope(ctx_group="dev1"):
        data = mx.sym.Variable("data")
        fc1 = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
        act1 = mx.sym.Activation(fc1, act_type="tanh")
    with mx.AttrScope(ctx_group="dev2"):
        fc2 = mx.sym.FullyConnected(act1, num_hidden=4, name="fc2")
        out = mx.sym.LinearRegressionOutput(fc2, mx.sym.Variable("label"),
                                            name="out")
    return out


def _bind_and_run(sym, group2ctx, ctx):
    rng = np.random.RandomState(0)
    shapes = {"data": (6, 5), "label": (6, 4)}
    args = {}
    arg_shapes, _, _ = sym.infer_shape(**shapes)
    for name, shp in zip(sym.list_arguments(), arg_shapes):
        args[name] = mx.nd.array(rng.randn(*shp).astype(np.float32))
    grads = {name: mx.nd.zeros(a.shape) for name, a in args.items()}
    ex = sym.bind(ctx, args, args_grad=grads, group2ctx=group2ctx)
    out = ex.forward(is_train=True)[0].asnumpy()
    ex.backward()
    return out, {k: g.asnumpy() for k, g in grads.items()}


def test_model_parallel_matches_single_device():
    """group2ctx placement on 2 devices must be numerically identical to
    the single-device run (reference: test_model_parallel.py compares the
    summed outputs/grads across placements)."""
    sym = _split_mlp()
    out1, grads1 = _bind_and_run(sym, None, mx.cpu(0))
    out2, grads2 = _bind_and_run(
        sym, {"dev1": mx.cpu(0), "dev2": mx.cpu(1)}, mx.cpu(0))
    np.testing.assert_allclose(out1, out2, rtol=1e-5, atol=1e-6)
    for k in grads1:
        np.testing.assert_allclose(grads1[k], grads2[k], rtol=1e-5,
                                   atol=1e-6, err_msg=k)


def test_model_parallel_args_actually_placed():
    """The bound args must live on the device their ctx group names."""
    sym = _split_mlp()
    shapes = {"data": (6, 5), "label": (6, 4)}
    ex = sym.simple_bind(ctx=mx.cpu(0),
                         group2ctx={"dev1": mx.cpu(0), "dev2": mx.cpu(1)},
                         **shapes)
    assert ex.arg_dict["fc1_weight"].context == mx.cpu(0)
    assert ex.arg_dict["fc2_weight"].context == mx.cpu(1)


def _toy(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.uniform(-1, 1, (n, 4)).astype(np.float32)
    y = ((x[:, 0] * x[:, 1]) > 0).astype(np.float32)
    return x, y


def _mlp_net():
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(16, activation="tanh"))
        net.add(gluon.nn.Dense(2))
    return net


def _train(net, ctx_list, x, y, steps=5):
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(steps):
        for xs, ys in zip(gluon.utils.split_and_load(x, ctx_list),
                          gluon.utils.split_and_load(y, ctx_list)):
            with mx.autograd.record():
                loss = loss_fn(net(xs), ys)
            loss.backward()
        trainer.step(x.shape[0])
    return {k: v.data().asnumpy()
            for k, v in net.collect_params().items()}


def test_gluon_multi_device_matches_single():
    """Mesh data-parallel Gluon training (params replicated, batch sharded)
    must match the single-device run bit-for-bit in math (reference
    pattern: gluon trainer.py:116 multi-ctx grads sum)."""
    x, y = _toy()
    mx.random.seed(0)
    net1 = _mlp_net()
    net1.initialize(mx.init.Xavier(), ctx=mx.cpu(0))
    net1(mx.nd.array(x[:2]))          # materialize shapes
    start = [v.data().asnumpy()
             for _, v in sorted(net1.collect_params().items())]

    mx.random.seed(0)
    ctxs = [mx.cpu(i) for i in range(4)]
    net2 = _mlp_net()
    net2.initialize(mx.init.Xavier(), ctx=ctxs)
    for xs in gluon.utils.split_and_load(x[:4], ctxs):
        net2(xs)                      # materialize shapes
    # same starting point (auto-generated param names differ between nets —
    # match by position)
    for (_, v), s in zip(sorted(net2.collect_params().items()), start):
        v.set_data(mx.nd.array(s))

    p1 = _train(net1, [mx.cpu(0)], x, y)
    p2 = _train(net2, ctxs, x, y)
    for (k1, a), (k2, b) in zip(sorted(p1.items()), sorted(p2.items())):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5,
                                   err_msg="%s vs %s" % (k1, k2))


def test_gluon_multi_device_param_surface():
    ctxs = [mx.cpu(i) for i in range(2)]
    net = _mlp_net()
    net.initialize(mx.init.Xavier(), ctx=ctxs)
    xs = gluon.utils.split_and_load(np.zeros((4, 3), np.float32), ctxs)
    assert len(xs) == 1 and len(xs[0].data.devices()) == 2
    net(xs[0])
    p = list(net.collect_params().values())[0]
    assert p.list_ctx() == ctxs
    assert len(p.data().data.devices()) == 2


def test_split_and_load_uneven_raises():
    ctxs = [mx.cpu(i) for i in range(4)]
    with pytest.raises(ValueError, match="divisible"):
        gluon.utils.split_and_load(np.zeros((6, 3), np.float32), ctxs)


def test_param_stays_replicated_after_load_and_reset():
    import tempfile
    ctxs = [mx.cpu(i) for i in range(2)]
    net = _mlp_net()
    net.initialize(mx.init.Xavier(), ctx=ctxs)
    net(gluon.utils.split_and_load(np.zeros((4, 3), np.float32), ctxs)[0])
    with tempfile.NamedTemporaryFile(suffix=".params") as f:
        net.collect_params().save(f.name)
        net.collect_params().load(f.name, ctx=ctxs)
    p = list(net.collect_params().values())[0]
    assert len(p.data().data.devices()) == 2, "load dropped replication"
    p.reset_ctx(mx.cpu(0))
    assert p.list_ctx() == [mx.cpu(0)]
    assert len(p.data().data.devices()) == 1

"""Transformer LM (models/transformer.py): shape inference through the
Symbol layer, causality, learning, and the symbolic positional-attr fix
that enables it (sym.reshape(x, shape_tuple)).
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.models import transformer


def _build(T=16, V=50):
    sym = transformer.get_symbol(vocab_size=V, num_layers=2, d_model=32,
                                 n_heads=4, seq_len=T)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, T))],
             label_shapes=[("softmax_label", (4, T))])
    mod.init_params(mx.init.Xavier())
    return mod


def test_transformer_shapes_infer_from_data_alone():
    sym = transformer.get_symbol(vocab_size=50, num_layers=1, d_model=32,
                                 n_heads=4, seq_len=8)
    arg_shapes, out_shapes, _ = sym.infer_shape(data=(2, 8),
                                                softmax_label=(2, 8))
    shapes = dict(zip(sym.list_arguments(), arg_shapes))
    assert shapes["tok_embed_weight"] == (50, 32)
    assert shapes["layer0_att_qkv_weight"] == (96, 32)
    assert shapes["layer0_ln1_gamma"] == (32,)
    assert out_shapes[0] == (2 * 8, 50)


def test_transformer_is_causal():
    mod = _build()
    rng = np.random.RandomState(0)
    x = rng.randint(0, 50, (4, 16)).astype(np.float32)
    y = np.zeros_like(x)
    db = mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])
    mod.forward(db, is_train=False)
    out1 = mod.get_outputs()[0].asnumpy().reshape(4, 16, 50)
    x2 = x.copy()
    x2[:, -1] = (x2[:, -1] + 7) % 50
    mod.forward(mx.io.DataBatch(data=[mx.nd.array(x2)],
                                label=[mx.nd.array(y)]), is_train=False)
    out2 = mod.get_outputs()[0].asnumpy().reshape(4, 16, 50)
    # perturbing the last token must not change logits at positions < T-1
    np.testing.assert_allclose(out1[:, :-1], out2[:, :-1], atol=1e-5)
    assert np.abs(out1[:, -1] - out2[:, -1]).max() > 1e-4


def test_transformer_learns_next_token():
    mod = _build()
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 3e-3})
    rng = np.random.RandomState(0)
    x = rng.randint(0, 50, (4, 16)).astype(np.float32)
    y = (x + 1) % 50
    db = mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])
    for _ in range(150):
        mod._fit_step(db)
    mod.forward(db, is_train=False)
    pred = mod.get_outputs()[0].asnumpy().argmax(1).reshape(4, 16)
    assert (pred == y).mean() > 0.95


def test_symbol_positional_attrs():
    """sym.reshape(x, shape) / sym.transpose(x, axes) positional attrs map
    onto the op's parameters (regression: silently dropped)."""
    x = mx.sym.Variable("x")
    r = mx.sym.reshape(x, (2, 6))
    t = mx.sym.transpose(r, (1, 0))
    _, outs, _ = t.infer_shape(x=(3, 4))
    assert outs[0] == (6, 2)


def test_transformer_flash_attention_matches_dense():
    """attention='flash' (Pallas kernel path) must produce the same
    logits as the dense composition under shared parameters."""
    mods = {}
    for att in ("dense", "flash"):
        sym = transformer.get_symbol(vocab_size=50, num_layers=1,
                                     d_model=32, n_heads=2, seq_len=128,
                                     attention=att)
        mod = mx.mod.Module(sym, context=mx.cpu())
        mod.bind(data_shapes=[("data", (2, 128))],
                 label_shapes=[("softmax_label", (2, 128))])
        mod.init_params(mx.init.Xavier())
        mods[att] = mod
    args, auxs = mods["dense"].get_params()
    mods["flash"].set_params(args, auxs)
    rng = np.random.RandomState(0)
    x = rng.randint(0, 50, (2, 128)).astype(np.float32)
    db = mx.io.DataBatch(data=[mx.nd.array(x)],
                         label=[mx.nd.array(np.zeros_like(x))])
    outs = {}
    for att, mod in mods.items():
        mod.forward(db, is_train=False)
        outs[att] = mod.get_outputs()[0].asnumpy()
    np.testing.assert_allclose(outs["flash"], outs["dense"],
                               rtol=1e-4, atol=1e-5)

"""Optimizer tests — numpy reference updates vs the registered update ops
(reference test model: tests/python/unittest/test_optimizer.py compares the
python Updater against the C++ update ops)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def _run(opt, w0, g, steps=3):
    w = mx.nd.array(w0.copy())
    state = opt.create_state(0, w)
    for _ in range(steps):
        opt.update(0, w, mx.nd.array(g), state)
    return w.asnumpy()


def test_sgd_plain_matches_numpy():
    w0 = np.array([1.0, -2.0, 3.0], np.float32)
    g = np.array([0.1, 0.2, -0.3], np.float32)
    opt = mx.optimizer.SGD(learning_rate=0.1, wd=0.01, rescale_grad=0.5)
    got = _run(opt, w0, g)
    w = w0.copy()
    for _ in range(3):
        w -= 0.1 * (0.5 * g + 0.01 * w)
    assert_almost_equal(got, w, rtol=1e-5, atol=1e-6)


def test_sgd_momentum_matches_numpy():
    w0 = np.array([1.0, -2.0], np.float32)
    g = np.array([0.5, -0.5], np.float32)
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9)
    got = _run(opt, w0, g, steps=4)
    w, mom = w0.copy(), np.zeros_like(w0)
    for _ in range(4):
        mom = 0.9 * mom - 0.1 * g
        w += mom
    assert_almost_equal(got, w, rtol=1e-5, atol=1e-6)


def test_adam_matches_numpy():
    np.random.seed(0)
    w0 = np.random.randn(4).astype(np.float32)
    g = np.random.randn(4).astype(np.float32)
    opt = mx.optimizer.Adam(learning_rate=0.01)
    got = _run(opt, w0, g, steps=5)
    w = w0.copy().astype(np.float64)
    m = np.zeros(4)
    v = np.zeros(4)
    b1, b2, eps = 0.9, 0.999, 1e-8
    for t in range(1, 6):
        lr = 0.01 * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        w -= lr * m / (np.sqrt(v) + eps)
    assert_almost_equal(got, w.astype(np.float32), rtol=1e-4, atol=1e-6)


def test_rmsprop_matches_numpy():
    w0 = np.array([0.5, 1.5], np.float32)
    g = np.array([0.3, -0.2], np.float32)
    opt = mx.optimizer.RMSProp(learning_rate=0.01, gamma1=0.9)
    got = _run(opt, w0, g, steps=3)
    w = w0.astype(np.float64)
    n = np.zeros(2)
    for _ in range(3):
        n = 0.1 * g * g + 0.9 * n
        w -= 0.01 * g / np.sqrt(n + 1e-8)
    assert_almost_equal(got, w.astype(np.float32), rtol=1e-4, atol=1e-6)


def test_adagrad_matches_numpy():
    w0 = np.array([1.0, 2.0], np.float32)
    g = np.array([0.5, 0.1], np.float32)
    opt = mx.optimizer.AdaGrad(learning_rate=0.1)
    got = _run(opt, w0, g, steps=3)
    w = w0.astype(np.float64)
    h = np.zeros(2)
    for _ in range(3):
        h += g * g
        w -= 0.1 * g / np.sqrt(h + 1e-7)
    assert_almost_equal(got, w.astype(np.float32), rtol=1e-4, atol=1e-6)


def test_clip_gradient():
    w0 = np.array([0.0], np.float32)
    g = np.array([100.0], np.float32)
    opt = mx.optimizer.SGD(learning_rate=1.0, clip_gradient=1.0)
    got = _run(opt, w0, g, steps=1)
    assert_almost_equal(got, np.array([-1.0], np.float32))


def test_lr_scheduler_factor():
    sched = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5)
    opt = mx.optimizer.SGD(learning_rate=1.0, lr_scheduler=sched)
    assert opt._get_lr(0) == 1.0
    opt.num_update = 25
    lr = sched(25)
    assert lr == 0.25


def test_multifactor_scheduler():
    sched = mx.lr_scheduler.MultiFactorScheduler(step=[5, 15], factor=0.1)
    sched.base_lr = 1.0
    assert sched(3) == 1.0
    assert abs(sched(10) - 0.1) < 1e-9
    assert abs(sched(20) - 0.01) < 1e-9


def test_updater_and_registry():
    opt = mx.optimizer.create("sgd", learning_rate=0.5)
    upd = mx.optimizer.get_updater(opt)
    w = mx.nd.array([1.0])
    upd(0, mx.nd.array([1.0]), w)
    assert_almost_equal(w, np.array([0.5], np.float32))
    states = upd.get_states()
    assert isinstance(states, bytes)


def test_lr_wd_mult():
    opt = mx.optimizer.SGD(learning_rate=1.0,
                           param_idx2name={0: "w_weight", 1: "b_bias"})
    opt.set_lr_mult({"w_weight": 0.1})
    opt.set_wd_mult({})
    assert abs(opt._get_lr(0) - 0.1) < 1e-9
    assert opt._get_wd(1) == 0.0   # bias wd_mult defaults to 0


def test_multi_precision_sgd():
    w = mx.nd.array(np.array([1.0, 2.0]), dtype=np.float16)
    g = mx.nd.array(np.array([0.5, 0.5]), dtype=np.float16)
    opt = mx.optimizer.SGD(learning_rate=0.1, multi_precision=True)
    state = opt.create_state(0, w)
    assert isinstance(state, tuple)
    assert state[1].dtype == np.float32
    opt.update(0, w, g, state)
    assert w.dtype == np.float16
    assert_almost_equal(w, np.array([0.95, 1.95], np.float16), rtol=1e-2,
                        atol=1e-3)

"""Symbol + Executor tests (reference test model: tests/python/unittest/
test_symbol.py, test_executor.py, test_infer_shape.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient


def _mlp():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def test_list_arguments_order():
    out = _mlp()
    assert out.list_arguments() == [
        "data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
        "softmax_label"]
    assert out.list_outputs() == ["softmax_output"]


def test_infer_shape_mlp():
    out = _mlp()
    arg_shapes, out_shapes, aux_shapes = out.infer_shape(
        data=(4, 8), softmax_label=(4,))
    d = dict(zip(out.list_arguments(), arg_shapes))
    assert d["fc1_weight"] == (16, 8)
    assert d["fc1_bias"] == (16,)
    assert d["fc2_weight"] == (10, 16)
    assert out_shapes == [(4, 10)]


def test_infer_shape_conv_bn():
    d = mx.sym.Variable("data")
    c = mx.sym.Convolution(d, kernel=(3, 3), num_filter=8, pad=(1, 1),
                           stride=(2, 2), name="c1")
    b = mx.sym.BatchNorm(c, name="bn1")
    f = mx.sym.FullyConnected(b, num_hidden=10, name="fc")
    arg_shapes, out_shapes, aux_shapes = f.infer_shape(data=(2, 3, 8, 8))
    args = dict(zip(f.list_arguments(), arg_shapes))
    assert args["c1_weight"] == (8, 3, 3, 3)
    assert args["bn1_gamma"] == (8,)
    assert dict(zip(f.list_auxiliary_states(), aux_shapes))[
        "bn1_moving_mean"] == (8,)
    assert out_shapes == [(2, 10)]


def test_json_round_trip():
    out = _mlp()
    out2 = mx.sym.load_json(out.tojson())
    assert out2.list_arguments() == out.list_arguments()
    assert out2.list_outputs() == out.list_outputs()
    a1, o1, _ = out.infer_shape(data=(4, 8), softmax_label=(4,))
    a2, o2, _ = out2.infer_shape(data=(4, 8), softmax_label=(4,))
    assert a1 == a2 and o1 == o2


def test_symbol_arithmetic_eval():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = (a + b) * 2.0 - a / 2.0
    outs = c.eval(a=mx.nd.array([2.0]), b=mx.nd.array([3.0]))
    assert_almost_equal(outs[0], np.array([9.0]))


def test_symbol_compose():
    a = mx.sym.Variable("a")
    net = mx.sym.FullyConnected(a, num_hidden=4, name="fc")
    data2 = mx.sym.Variable("d2")
    net2 = net(a=data2)
    assert "d2" in net2.list_arguments()
    assert "a" not in net2.list_arguments()


def test_get_internals():
    out = _mlp()
    internals = out.get_internals()
    names = internals.list_outputs()
    assert "fc1_output" in names
    feat = internals["fc1_output"]
    assert feat.list_arguments() == ["data", "fc1_weight", "fc1_bias"]


def test_executor_forward_matches_numpy():
    np.random.seed(0)
    out = _mlp()
    ex = out.simple_bind(mx.cpu(0), data=(4, 8), softmax_label=(4,))
    params = {n: np.random.randn(*a.shape).astype(np.float32) * 0.1
              for n, a in ex.arg_dict.items() if n.endswith(("weight", "bias"))}
    for n, v in params.items():
        ex.arg_dict[n][:] = mx.nd.array(v)
    x = np.random.randn(4, 8).astype(np.float32)
    ex.forward(is_train=False, data=mx.nd.array(x),
               softmax_label=mx.nd.array([0, 1, 2, 3]))
    h = np.maximum(x.dot(params["fc1_weight"].T) + params["fc1_bias"], 0)
    logits = h.dot(params["fc2_weight"].T) + params["fc2_bias"]
    p = np.exp(logits - logits.max(1, keepdims=True))
    p /= p.sum(1, keepdims=True)
    assert_almost_equal(ex.outputs[0], p, rtol=1e-4, atol=1e-5)


def test_executor_backward_softmax_ce():
    np.random.seed(1)
    out = _mlp()
    ex = out.simple_bind(mx.cpu(0), data=(4, 8), softmax_label=(4,))
    for n, a in ex.arg_dict.items():
        if n.endswith(("weight", "bias")):
            a[:] = mx.nd.array(np.random.randn(*a.shape).astype(np.float32) * 0.1)
    label = np.array([0, 1, 2, 3], dtype=np.float32)
    ex.forward(is_train=True, data=mx.nd.array(np.random.randn(4, 8)),
               softmax_label=mx.nd.array(label))
    ex.backward()
    p = ex.outputs[0].asnumpy()
    # data-grad of fc2 output head = p - onehot; check via fc2_bias grad
    oh = np.zeros_like(p)
    oh[np.arange(4), label.astype(int)] = 1
    assert_almost_equal(ex.grad_dict["fc2_bias"], (p - oh).sum(0),
                        rtol=1e-4, atol=1e-5)


def test_executor_grad_req_add_and_null():
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    loss = mx.sym.MakeLoss(out.sum())
    ex = loss.simple_bind(mx.cpu(0), data=(2, 4),
                          grad_req={"data": "null", "fc_weight": "add",
                                    "fc_bias": "write"})
    ex.arg_dict["fc_weight"][:] = 1.0
    x = mx.nd.array(np.ones((2, 4), np.float32))
    for _ in range(2):
        ex.forward(is_train=True, data=x)
        ex.backward()
    # weight grad accumulated twice: d(sum(xW^T+b))/dW = sum over batch of x
    assert_almost_equal(ex.grad_dict["fc_weight"],
                        np.full((3, 4), 4.0), rtol=1e-5, atol=1e-6)
    assert_almost_equal(ex.grad_dict["fc_bias"], np.full((3,), 2.0),
                        rtol=1e-5, atol=1e-6)


def test_executor_aux_update():
    d = mx.sym.Variable("data")
    b = mx.sym.BatchNorm(d, name="bn", momentum=0.5, fix_gamma=False)
    loss = mx.sym.MakeLoss(b)
    ex = loss.simple_bind(mx.cpu(0), data=(8, 3))
    ex.arg_dict["bn_gamma"][:] = 1.0
    ex.aux_dict["bn_moving_var"][:] = 1.0
    x = np.random.randn(8, 3).astype(np.float32) + 2.0
    ex.forward(is_train=True, data=mx.nd.array(x))
    ex.backward()
    expected_mm = 0.5 * 0.0 + 0.5 * x.mean(0)
    assert_almost_equal(ex.aux_dict["bn_moving_mean"], expected_mm,
                        rtol=1e-4, atol=1e-5)


def test_symbol_numeric_gradient():
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("w")
    out = mx.sym.FullyConnected(data, w, num_hidden=3, no_bias=True,
                                name="fc")
    out = mx.sym.Activation(out, act_type="tanh")
    check_numeric_gradient(out, {"data": np.random.randn(2, 4),
                                 "w": np.random.randn(3, 4)})


def test_group_and_multi_output():
    a = mx.sym.Variable("a")
    g = mx.sym.Group([a * 2.0, a + 1.0])
    assert len(g.list_outputs()) == 2
    outs = g.eval(a=mx.nd.array([1.0, 2.0]))
    assert_almost_equal(outs[0], np.array([2.0, 4.0]))
    assert_almost_equal(outs[1], np.array([2.0, 3.0]))


def test_attr_scope_ctx_group():
    with mx.AttrScope(ctx_group="dev1"):
        a = mx.sym.Variable("a")
        b = a * 2.0
    assert a.attr("ctx_group") == "dev1"


def test_variable_shape_attr():
    data = mx.sym.Variable("data", shape=(4, 8))
    fc = mx.sym.FullyConnected(data, num_hidden=2, name="fc")
    arg_shapes, out_shapes, _ = fc.infer_shape()
    assert out_shapes == [(4, 2)]

"""Tier-1 smoke for tools/perf/checkpoint_bench.py (not slow).

Runs the quick variant end-to-end (real Module, real fused steps, real
atomic writes) and asserts the mechanics: every save landed, none
failed, and the async submit blocked the training thread for a small
fraction of the background serialization time — the CheckFreq split the
tentpole exists for. The threshold here is looser than the full bench's
25% gate (shared CI hosts are noisy; the full bench enforces 25% and
records the honest number into BENCH_checkpoint.json)."""
import importlib
import json
import os
import sys


def _load_bench():
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "perf"))
    try:
        return importlib.import_module("checkpoint_bench")
    finally:
        sys.path.pop(0)


def test_checkpoint_bench_quick(tmp_path):
    bench = _load_bench()
    results = bench.run(quick=True)
    for k in ("saves", "ckpt_mbytes", "async_block_ms_per_save",
              "async_write_ms_per_save", "block_fraction_of_write",
              "sync_block_ms_per_save", "async_vs_sync_block_speedup",
              "saved", "write_failed"):
        assert k in results, "missing %s" % k
    assert results["saved"] == results["saves"]
    assert results["write_failed"] == 0
    assert results["ckpt_mbytes"] > 0
    assert results["async_write_ms_per_save"] > 0
    # the split itself: blocking well under serialization time even on a
    # loaded box (full bench gates the honest <0.25)
    assert results["block_fraction_of_write"] < 0.6, results

    # artifact schema BENCH_checkpoint.json consumers read
    path = str(tmp_path / "BENCH_checkpoint.json")
    with open(path, "w") as f:
        json.dump({"bench": "checkpoint", "results": results}, f)
    with open(path) as f:
        loaded = json.load(f)
    assert loaded["results"]["saved"] == results["saves"]

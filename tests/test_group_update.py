"""Grouped fused optimizer update over scan var-lists + remat on the
non-fused forward_backward path (the two PR 9 close-out levers, landed
in ISSUE 14).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.models import transformer

import jax
import jax.numpy as jnp


L, D, H, T, V, B = 4, 16, 2, 8, 32, 4


def _sym(layers=L):
    return transformer.get_symbol(vocab_size=V, num_layers=layers,
                                  d_model=D, n_heads=H, seq_len=T)


def _batch():
    rng = np.random.RandomState(0)
    x = rng.randint(0, V, (B, T)).astype(np.float32)
    y = rng.randint(0, V, (B, T)).astype(np.float32)
    return mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])


def _seed_params(sym):
    np.random.seed(42)
    m = mx.mod.Module(sym, context=mx.cpu(0))
    m.bind(data_shapes=[("data", (B, T))],
           label_shapes=[("softmax_label", (B, T))])
    m.init_params(mx.init.Xavier())
    return {n: mx.nd.array(np.asarray(a.data))
            for n, a in m._exec.arg_dict.items()}


def _train(sym, arg0, group, optimizer="adam", steps=3, lr_mult=None,
           scan="auto"):
    mx.config.set("MXNET_TPU_GROUP_UPDATE", group)
    mx.config.set("MXNET_TPU_SCAN_LAYERS", scan)
    try:
        mx.random.seed(11)
        mod = mx.mod.Module(sym, context=mx.cpu(0))
        mod.bind(data_shapes=[("data", (B, T))],
                 label_shapes=[("softmax_label", (B, T))])
        mod.init_params(arg_params=arg0, aux_params={})
        opt = mx.optimizer.create(
            optimizer, learning_rate=0.01, rescale_grad=1.0,
            param_idx2name={i: n for i, n in
                            enumerate(mod._param_names)})
        if lr_mult:
            opt.set_lr_mult(lr_mult)
        mod.init_optimizer(optimizer=opt)
        db = _batch()
        for _ in range(steps):
            mod._fit_step(db)
        jax.block_until_ready(mod._exec.arg_dict["lm_head_weight"].data)
        return mod
    finally:
        mx.config.reset("MXNET_TPU_GROUP_UPDATE")
        mx.config.reset("MXNET_TPU_SCAN_LAYERS")


def _weights(mod):
    return {n: np.asarray(a.data) for n, a in mod._exec.arg_dict.items()}


def test_grouped_update_bit_identical():
    """The vmapped per-family update is the SAME math elementwise —
    grouped and per-param runs end bit-identical."""
    sym = _sym()
    arg0 = _seed_params(sym)
    w_on = _weights(_train(sym, arg0, True))
    w_off = _weights(_train(sym, arg0, False))
    assert set(w_on) == set(w_off)
    for k in w_on:
        np.testing.assert_array_equal(w_on[k], w_off[k], err_msg=k)


def test_grouped_update_applies_and_counts():
    sym = _sym()
    arg0 = _seed_params(sym)
    with mx.profiler.counter_delta() as d:
        mod = _train(sym, arg0, True, steps=1)
    assert d.all().get("fused_update_grouped", 0) >= 1
    assert mx.profiler.gauges().get("fused_update_groups", 0) >= 1
    # the scan plan's families were actually consumed
    assert mod._exec._scan_plan is not None


def test_grouped_update_off_without_scan_plan():
    """No scan plan (scan off) -> no grouping, knob irrelevant."""
    sym = _sym()
    arg0 = _seed_params(sym)
    with mx.profiler.counter_delta() as d:
        _train(sym, arg0, True, steps=1, scan="off")
    assert d.all().get("fused_update_grouped", 0) == 0


def test_grouped_update_knob_off_counts_nothing():
    sym = _sym()
    arg0 = _seed_params(sym)
    with mx.profiler.counter_delta() as d:
        _train(sym, arg0, False, steps=1)
    assert d.all().get("fused_update_grouped", 0) == 0


def test_nonuniform_lr_mult_family_falls_back():
    """A family whose members resolve different lr multipliers cannot
    share one vmapped body — it must fall back per-param (and stay
    correct)."""
    sym = _sym()
    arg0 = _seed_params(sym)
    mult = {"layer1_att_qkv_weight": 0.5}
    mod = _train(sym, arg0, True, lr_mult=mult, steps=2)
    w_grp = _weights(mod)
    w_ref = _weights(_train(sym, arg0, False, lr_mult=mult, steps=2))
    for k in w_grp:
        np.testing.assert_array_equal(w_grp[k], w_ref[k], err_msg=k)
    # the qkv family must NOT have been grouped (one member differs);
    # other families still group
    assert mx.profiler.gauges().get("fused_update_groups", 0) >= 1


def test_grouped_update_shrinks_the_program():
    """The deterministic form of the O(L) claim: the fused step's jaxpr
    carries materially fewer equations with grouping on (the per-layer
    update chains collapse to one vmapped body per family)."""
    sym = _sym(layers=6)
    arg0 = _seed_params(sym)

    def eqns(group):
        mod = _train(sym, arg0, group, steps=1)
        params = {n: mod._exec.arg_dict[n].data
                  for n in mod._param_names}
        aux = {n: a.data for n, a in mod._exec.aux_dict.items()}
        inputs = {n: mod._exec.arg_dict[n].data
                  for n in ("data", "softmax_label")}
        jaxpr = jax.make_jaxpr(mod._fused_jit.__wrapped__)(
            params, mod._fused_states, aux, inputs, {},
            jax.random.PRNGKey(0), jnp.float32(0.01), jnp.int32(1))
        return len(jaxpr.jaxpr.eqns)

    n_on, n_off = eqns(True), eqns(False)
    assert n_on < n_off, (n_on, n_off)


def test_grouped_update_with_momentum_states():
    """Stacked state trees (sgd momentum) thread through the vmapped
    body and come back per-param."""
    sym = _sym()
    arg0 = _seed_params(sym)
    m_on = _train(sym, arg0, True, optimizer="sgd", steps=2)
    m_off = _train(sym, arg0, False, optimizer="sgd", steps=2)
    w_on, w_off = _weights(m_on), _weights(m_off)
    for k in w_on:
        np.testing.assert_array_equal(w_on[k], w_off[k], err_msg=k)
    for n, s in m_on._fused_states.items():
        ref = m_off._fused_states[n]
        for a, b in zip(jax.tree_util.tree_leaves(s),
                        jax.tree_util.tree_leaves(ref)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=n)


# ------------------------------- remat on the non-fused fwd_bwd path


def _mlp():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _fwd_bwd_grads(remat):
    mx.config.set("MXNET_TPU_REMAT", remat)
    try:
        np.random.seed(5)
        seed = mx.mod.Module(_mlp(), context=mx.cpu())
        seed.bind(data_shapes=[("data", (8, 32))],
                  label_shapes=[("softmax_label", (8,))])
        seed.init_params(mx.init.Uniform(0.07))
        arg0 = {n: mx.nd.array(np.asarray(a.data))
                for n, a in seed._exec.arg_dict.items()}

        rng = np.random.RandomState(0)
        x = rng.uniform(-1, 1, (8, 32)).astype(np.float32)
        y = rng.randint(0, 10, (8,)).astype(np.float32)
        mod = mx.mod.Module(_mlp(), context=mx.cpu())
        mod.bind(data_shapes=[("data", (8, 32))],
                 label_shapes=[("softmax_label", (8,))])
        mod.init_params(arg_params=arg0, aux_params={})
        db = mx.io.DataBatch(data=[mx.nd.array(x)],
                             label=[mx.nd.array(y)])
        mod.forward_backward(db)
        applied = mod._exec._fwd_bwd_remat is not None
        return ({n: np.asarray(g.data)
                 for n, g in mod._exec.grad_dict.items()}, applied)
    finally:
        mx.config.reset("MXNET_TPU_REMAT")


def test_fwd_bwd_remat_parity():
    g_off, a_off = _fwd_bwd_grads("off")
    g_on, a_on = _fwd_bwd_grads("dots_with_no_batch_dims_saveable")
    assert not a_off and a_on
    for k in g_off:
        np.testing.assert_array_equal(g_on[k], g_off[k], err_msg=k)
    assert mx.profiler.counters().get("remat_applied", 0) >= 1


def test_fwd_bwd_remat_zero_cost_when_off():
    """MXNET_TPU_REMAT=off builds nothing on the fwd_bwd path."""
    _g, applied = _fwd_bwd_grads("off")
    assert not applied


def test_fwd_bwd_remat_parity_vs_fused_step():
    """The rematted non-fused path trains the same step the fused path
    does (one sgd step, same seed params)."""
    mx.config.set("MXNET_TPU_REMAT", "dots_with_no_batch_dims_saveable")
    try:
        np.random.seed(6)
        seed = mx.mod.Module(_mlp(), context=mx.cpu())
        seed.bind(data_shapes=[("data", (8, 32))],
                  label_shapes=[("softmax_label", (8,))])
        seed.init_params(mx.init.Uniform(0.07))
        arg0 = {n: mx.nd.array(np.asarray(a.data))
                for n, a in seed._exec.arg_dict.items()}
        rng = np.random.RandomState(1)
        x = rng.uniform(-1, 1, (8, 32)).astype(np.float32)
        y = rng.randint(0, 10, (8,)).astype(np.float32)
        db = mx.io.DataBatch(data=[mx.nd.array(x)],
                             label=[mx.nd.array(y)])

        def one_step(fused):
            mod = mx.mod.Module(_mlp(), context=mx.cpu())
            mod.bind(data_shapes=[("data", (8, 32))],
                     label_shapes=[("softmax_label", (8,))])
            mod.init_params(arg_params=arg0, aux_params={})
            mod.init_optimizer(optimizer="sgd", optimizer_params={
                "learning_rate": 0.1, "rescale_grad": 1.0 / 8})
            if fused:
                mod._fit_step(db)
            else:
                mod.forward_backward(db)
                mod.update()
            return {n: np.asarray(a.data)
                    for n, a in mod._exec.arg_dict.items()}

        w_fused = one_step(True)
        w_eager = one_step(False)
        for k in w_fused:
            np.testing.assert_allclose(w_fused[k], w_eager[k],
                                       rtol=1e-6, atol=1e-7,
                                       err_msg=k)
    finally:
        mx.config.reset("MXNET_TPU_REMAT")

"""Metric + initializer tests (reference: tests/python/unittest/
test_metric.py, test_init.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def test_accuracy():
    m = mx.metric.Accuracy()
    pred = mx.nd.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
    label = mx.nd.array([1, 0, 0])
    m.update([label], [pred])
    name, acc = m.get()
    assert name == "accuracy"
    assert abs(acc - 2.0 / 3) < 1e-6


def test_topk_accuracy():
    m = mx.metric.TopKAccuracy(top_k=2)
    pred = mx.nd.array([[0.1, 0.5, 0.4], [0.6, 0.3, 0.1]])
    label = mx.nd.array([2, 1])
    m.update([label], [pred])
    _, acc = m.get()
    assert abs(acc - 1.0) < 1e-6


def test_f1():
    m = mx.metric.F1()
    pred = mx.nd.array([[0.2, 0.8], [0.9, 0.1], [0.4, 0.6]])
    label = mx.nd.array([1, 0, 1])
    m.update([label], [pred])
    _, f1 = m.get()
    assert abs(f1 - 1.0) < 1e-6


def test_mse_mae_rmse():
    pred = mx.nd.array([[1.0], [2.0]])
    label = mx.nd.array([0.0, 4.0])
    for name, expected in [("mse", (1 + 4) / 2.0), ("mae", (1 + 2) / 2.0),
                           ("rmse", np.sqrt(2.5))]:
        m = mx.metric.create(name)
        m.update([label], [pred])
        _, v = m.get()
        assert abs(v - expected) < 1e-6, name


def test_perplexity():
    m = mx.metric.Perplexity(ignore_label=None)
    pred = mx.nd.array([[0.5, 0.5], [0.9, 0.1]])
    label = mx.nd.array([0, 0])
    m.update([label], [pred])
    _, v = m.get()
    expected = np.exp(-(np.log(0.5) + np.log(0.9)) / 2)
    assert abs(v - expected) < 1e-5


def test_composite_and_create():
    m = mx.metric.create(["acc", "mse"])
    assert isinstance(m, mx.metric.CompositeEvalMetric)
    names, values = m.get()
    assert names == ["accuracy", "mse"]


def test_custom_metric():
    m = mx.metric.np(lambda label, pred: float(np.sum(label == pred.argmax(1))))
    pred = mx.nd.array([[0.1, 0.9]])
    label = mx.nd.array([1])
    m.update([label], [pred])
    _, v = m.get()
    assert v == 1.0


# ------------------------------------------------------------- initializers


def test_xavier_scale():
    np.random.seed(0)
    arr = mx.nd.zeros((128, 64))
    init = mx.init.Xavier(rnd_type="uniform", factor_type="avg", magnitude=3)
    init("fc_weight", arr)
    bound = np.sqrt(3.0 / ((128 + 64) / 2))
    a = arr.asnumpy()
    assert np.abs(a).max() <= bound + 1e-6
    assert a.std() > bound / 4


def test_initializer_dispatch():
    init = mx.init.Uniform(0.1)
    bias = mx.nd.ones((4,))
    init("fc_bias", bias)
    assert_almost_equal(bias, np.zeros(4, np.float32))
    gamma = mx.nd.zeros((4,))
    init("bn_gamma", gamma)
    assert_almost_equal(gamma, np.ones(4, np.float32))
    mvar = mx.nd.zeros((4,))
    init("bn_moving_var", mvar)
    assert_almost_equal(mvar, np.ones(4, np.float32))


def test_orthogonal():
    np.random.seed(0)
    arr = mx.nd.zeros((16, 16))
    mx.init.Orthogonal(scale=1.0)("w_weight", arr)
    a = arr.asnumpy()
    assert_almost_equal(a.dot(a.T), np.eye(16), rtol=1e-4, atol=1e-4)


def test_mixed_and_constant():
    init = mx.init.Mixed([".*bias", ".*"],
                         [mx.init.Constant(7.0), mx.init.Uniform(0.1)])
    b = mx.nd.zeros((3,))
    init("fc_bias", b)
    assert_almost_equal(b, np.full(3, 7.0, np.float32))


def test_lstmbias():
    arr = mx.nd.ones((8,))
    mx.init.LSTMBias(forget_bias=1.0)("lstm_bias", arr)
    expected = np.zeros(8, np.float32)
    expected[2:4] = 1.0
    assert_almost_equal(arr, expected)


def test_load_initializer(tmp_path):
    f = str(tmp_path / "p.params")
    mx.nd.save(f, {"arg:fc_weight": mx.nd.array([[1.0, 2.0]])})
    init = mx.init.Load(f, default_init=mx.init.Zero())
    w = mx.nd.zeros((1, 2))
    init("fc_weight", w)
    assert_almost_equal(w, np.array([[1.0, 2.0]], np.float32))
    other = mx.nd.ones((2,))
    init("other_weight", other)
    assert_almost_equal(other, np.zeros(2, np.float32))

"""Test configuration.

Mirrors the reference's test strategy (SURVEY.md §4): the suite runs on CPU
with a *virtual 8-device mesh* so all multi-device/sharding machinery is
exercised without TPU hardware — the TPU analogue of the reference running
multi-device tests on cpu(0)/cpu(1) (tests/python/unittest/
test_multi_device_exec.py) and its localhost "fake cluster" pattern.

Must set XLA flags before jax initializes.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# The remote-TPU plugin rides PYTHONPATH (a sitecustomize that dials its
# relay at interpreter start) — when the tunnel wedges, every subprocess
# the suite spawns hangs before main() runs. The whole suite is
# CPU-targeted and every spawned script sys.path-inserts the repo root
# itself, so drop the plugin path from the inherited environment.
os.environ["PYTHONPATH"] = ""
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The accelerator plugin on this machine rewrites JAX_PLATFORMS at interpreter
# startup, so the env var alone does NOT keep jax off the real chip: without
# the config override the *default* device stays the TPU and every
# host->device transfer in the suite crosses the tunnel (~100ms each, plus
# remote compiles — a 20x suite slowdown). Force the config directly.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# persistent compile cache: jit programs survive across pytest runs
_cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
# cache HLO only — the AOT kernel cache embeds exact host CPU features and
# spews loader errors when they drift (e.g. cache written under a different
# XLA host-feature fingerprint)
jax.config.update("jax_persistent_cache_enable_xla_caches", "none")

import numpy as _np  # noqa: E402
import pytest  # noqa: E402

# On this jax/XLA version a collective-bearing CPU executable loaded
# from the persistent compile cache intermittently computes WRONG
# results (root-caused in PR 2: test_1f1b_matches_gpipe_one_step diffs
# of ~2.0 with a warm cache, 0 failures in 10+ runs with a cold cache,
# both schedules individually deterministic). Earlier conftests excluded
# whole multi-device test MODULES from the cache by name; the root-cause
# fence (mxnet_tpu/aot.py) instead skips the cache at its get/put entry
# points for any executable with num_replicas*num_partitions > 1, so
# multi-device programs always compile fresh while single-device
# programs keep warm starts in EVERY module. If the fence cannot install
# (jax internals drifted), the persistent cache is disabled wholesale —
# a slow suite is better than a wrong one. Re-verified for PR 10: the
# historical test_pipeline_module.py under-load flake stayed green 10/10
# with the fence alone while a full tier-1 run churned concurrently.
from mxnet_tpu import aot as _aot  # noqa: E402

if not _aot.install_persistent_cache_fence():
    jax.config.update("jax_compilation_cache_dir", None)


@pytest.fixture(autouse=True)
def _seed_rngs():
    """Deterministic RNG per test regardless of execution order (the
    reference seeds per-module; a shared global key made
    test_module_fit_converges order-dependent)."""
    _np.random.seed(0)
    import mxnet_tpu as mx
    mx.random.seed(0)
    yield

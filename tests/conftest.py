"""Test configuration.

Mirrors the reference's test strategy (SURVEY.md §4): the suite runs on CPU
with a *virtual 8-device mesh* so all multi-device/sharding machinery is
exercised without TPU hardware — the TPU analogue of the reference running
multi-device tests on cpu(0)/cpu(1) (tests/python/unittest/
test_multi_device_exec.py) and its localhost "fake cluster" pattern.

Must set XLA flags before jax initializes.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

"""Native C++ data path (mxnet_tpu/native): RecordIO codec, image decode,
and the threaded batch pipeline, each checked against a Python oracle.

Reference parity: dmlc-core RecordIO framing + src/io/iter_image_recordio_2.cc
(SURVEY.md §2.8, §2.11).
"""
import ctypes
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import native
from mxnet_tpu import recordio as rio

L = native.lib()
pytestmark = pytest.mark.skipif(
    L is None, reason="native library unavailable (no toolchain)")

u8p = ctypes.POINTER(ctypes.c_uint8)


def _write_rec(tmp_path, payloads):
    path = str(tmp_path / "t.rec")
    rec = rio.MXRecordIO(path, "w")
    for b in payloads:
        rec.write(b)
    rec.close()
    return path


def test_native_reader_matches_python_codec(tmp_path):
    payloads = [b"hello", b"x" * 1037, b"", os.urandom(4096), b"abcd"]
    path = _write_rec(tmp_path, payloads)
    r = L.mxrio_open(path.encode())
    assert r
    assert L.mxrio_count(r) == len(payloads)
    for i, b in enumerate(payloads):
        ptr = u8p()
        n = L.mxrio_get(r, i, ctypes.byref(ptr))
        got = bytes(bytearray(ptr[:n])) if n else b""
        assert got == b
        off = L.mxrio_offset(r, i)
        assert L.mxrio_index_of(r, off) == i
    L.mxrio_close(r)


def test_native_writer_matches_python_reader(tmp_path):
    path = str(tmp_path / "w.rec")
    payloads = [b"alpha", b"b" * 999, b"gamma"]
    w = L.mxrio_writer_open(path.encode())
    offs = [L.mxrio_writer_write(w, b, len(b)) for b in payloads]
    assert L.mxrio_writer_close(w) == 0
    assert offs[0] == 0 and all(o >= 0 for o in offs)
    rec = rio.MXRecordIO(path, "r")
    for b in payloads:
        assert rec.read() == b
    assert rec.read() is None
    rec.close()


def test_native_jpeg_png_decode_vs_cv2():
    cv2 = pytest.importorskip("cv2")
    img = (np.random.RandomState(0).rand(37, 53, 3) * 255).astype(np.uint8)
    out, h, w, c = u8p(), ctypes.c_int(), ctypes.c_int(), ctypes.c_int()
    for fmt, exact in ((".jpg", True), (".png", True)):
        ok, enc = cv2.imencode(fmt, img)
        buf = enc.tobytes()
        rc = L.mximg_decode(buf, len(buf), 3, ctypes.byref(out),
                            ctypes.byref(h), ctypes.byref(w),
                            ctypes.byref(c))
        assert rc == 0
        arr = np.ctypeslib.as_array(out, shape=(h.value, w.value,
                                                c.value)).copy()
        L.mximg_free(out)
        ref = cv2.cvtColor(cv2.imdecode(enc, cv2.IMREAD_COLOR),
                           cv2.COLOR_BGR2RGB)
        # same libjpeg/libpng underneath: decodes are bit-identical
        np.testing.assert_array_equal(arr, ref)


def test_native_resize_close_to_cv2():
    cv2 = pytest.importorskip("cv2")
    img = (np.random.RandomState(3).rand(41, 67, 3) * 255).astype(np.uint8)
    dst = np.zeros((23, 31, 3), np.uint8)
    L.mximg_resize(img.ctypes.data_as(u8p), 41, 67, 3,
                   dst.ctypes.data_as(u8p), 23, 31)
    ref = cv2.resize(img, (31, 23), interpolation=cv2.INTER_LINEAR)
    assert np.abs(dst.astype(int) - ref.astype(int)).max() <= 1


def _make_image_rec(tmp_path, n=11):
    cv2 = pytest.importorskip("cv2")
    rng = np.random.RandomState(1)
    path = str(tmp_path / "imgs.rec")
    rec = rio.MXRecordIO(path, "w")
    imgs = []
    for i in range(n):
        img = (rng.rand(40 + i, 48, 3) * 255).astype(np.uint8)  # HWC RGB
        imgs.append(img)
        ok, enc = cv2.imencode(".png", img[:, :, ::-1])
        rec.write(rio.pack(rio.IRHeader(0, float(i), i, 0), enc.tobytes()))
    rec.close()
    return path, imgs


def test_native_pipeline_vs_numpy_oracle(tmp_path):
    path, imgs = _make_image_rec(tmp_path)
    mean = np.array([123., 117., 104.], np.float32)
    std = np.array([58., 57., 57.], np.float32)
    it = mx.io.ImageRecordIter(
        path_imgrec=path, data_shape=(3, 32, 32), batch_size=4,
        mean_r=123, mean_g=117, mean_b=104, std_r=58, std_g=57, std_b=57)
    assert it._native is not None, "native pipeline should engage here"
    i = 0
    for batch in it:
        n = batch.data[0].shape[0] - batch.pad
        dat = batch.data[0].asnumpy()
        lab = batch.label[0].asnumpy()
        for k in range(n):
            img = imgs[i]
            h, w = img.shape[:2]
            y0, x0 = (h - 32) // 2, (w - 32) // 2
            ref = img[y0:y0 + 32, x0:x0 + 32].astype(np.float32)
            ref = ((ref - mean) / std).transpose(2, 0, 1)
            np.testing.assert_allclose(dat[k], ref, atol=1e-4)
            assert lab[k] == float(i)
            i += 1
    assert i == len(imgs)


def test_native_pipeline_shuffle_epochs_deterministic(tmp_path):
    path, _ = _make_image_rec(tmp_path)

    def labels_of(it):
        out = []
        for batch in it:
            n = batch.data[0].shape[0] - batch.pad
            out.extend(batch.label[0].asnumpy()[:n].astype(int).tolist())
        return out

    it1 = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                                batch_size=4, shuffle=True, seed=7)
    it2 = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                                batch_size=4, shuffle=True, seed=7)
    e1a = labels_of(it1)
    it1.reset()
    e1b = labels_of(it1)
    assert sorted(e1a) == list(range(11))
    assert e1a != list(range(11))          # actually shuffled
    assert e1b != e1a                      # reshuffled across epochs
    assert labels_of(it2) == e1a           # same seed → same stream

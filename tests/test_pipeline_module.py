"""PipelineModule (module/pipeline_module.py): the Module-style user
surface for GPipe pipeline parallelism, on the 8-device virtual mesh."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _stages(D=8, n_body=2):
    data = mx.sym.Variable("data")
    s0 = mx.sym.FullyConnected(data, num_hidden=D, name="adapt",
                               flatten=False)
    body = []
    for i in range(n_body):
        x = mx.sym.Variable("x")
        h = mx.sym.FullyConnected(x, num_hidden=D, name="b%d" % i,
                                  flatten=False)
        body.append(mx.sym.Activation(h, act_type="tanh"))
    x = mx.sym.Variable("x")
    head = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(x, num_hidden=4, name="head"),
        mx.sym.Variable("softmax_label"), name="softmax")
    return [s0] + body + [head]


def test_pipeline_module_trains_to_separable_task():
    mod = mx.mod.PipelineModule(_stages(), n_microbatches=4)
    mod.bind(data_shapes=[("data", (8, 6))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    rng = np.random.RandomState(0)
    X = rng.randn(8, 6).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.float32) + 2 * (X[:, 1] > 0).astype(
        np.float32)
    db = mx.io.DataBatch(data=[mx.nd.array(X)], label=[mx.nd.array(Y)])
    accs = []
    for _ in range(250):
        outs = mod.fit_step(db)
        p = np.asarray(outs).reshape(8, 4)
        accs.append(float((p.argmax(1) == Y).mean()))
    assert accs[-1] >= 0.85, accs[-1]


def test_pipeline_module_validations():
    with pytest.raises(ValueError, match="3 stages"):
        mx.mod.PipelineModule(_stages()[:2], n_microbatches=2)
    mod = mx.mod.PipelineModule(_stages(), n_microbatches=3)
    with pytest.raises(ValueError, match="divisible"):
        mod.bind(data_shapes=[("data", (8, 6))],
                 label_shapes=[("softmax_label", (8,))])


def test_pipeline_module_rejects_aux_stages():
    data = mx.sym.Variable("data")
    s0 = mx.sym.FullyConnected(data, num_hidden=8, name="adapt")
    x = mx.sym.Variable("x")
    bnb = mx.sym.BatchNorm(mx.sym.FullyConnected(x, num_hidden=8,
                                                 name="b0"), name="bn0")
    head = mx.sym.SoftmaxOutput(mx.sym.Variable("x"), name="softmax")
    mod = mx.mod.PipelineModule([s0, bnb, bnb, head], n_microbatches=2)
    with pytest.raises(mx.base.MXNetError, match="auxiliary"):
        mod.bind(data_shapes=[("data", (4, 6))])


def _stages_norm(normalization):
    data = mx.sym.Variable("data")
    s0 = mx.sym.FullyConnected(data, num_hidden=8, name="adapt",
                               flatten=False)
    body = []
    for i in range(2):
        x = mx.sym.Variable("x")
        h = mx.sym.FullyConnected(x, num_hidden=8, name="b%d" % i,
                                  flatten=False)
        body.append(mx.sym.Activation(h, act_type="tanh"))
    x = mx.sym.Variable("x")
    head = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(x, num_hidden=4, name="head"),
        mx.sym.Variable("softmax_label"), name="softmax",
        normalization=normalization)
    return [s0] + body + [head]


@pytest.mark.parametrize("normalization", ["null", "batch"])
def test_pipeline_grads_invariant_to_microbatch_count(normalization):
    """advisor r4 (medium): --microbatches at fixed batch must not change
    the effective learning rate (GPipe accumulation invariance)."""
    rng = np.random.RandomState(1)
    X = rng.randn(8, 6).astype(np.float32)
    Y = rng.randint(0, 4, size=(8,)).astype(np.float32)
    db = mx.io.DataBatch(data=[mx.nd.array(X)], label=[mx.nd.array(Y)])

    init_params = {}

    def params_after_step(n_micro):
        mod = mx.mod.PipelineModule(_stages_norm(normalization),
                                    n_microbatches=n_micro)
        mod.bind(data_shapes=[("data", (8, 6))],
                 label_shapes=[("softmax_label", (8,))])
        mod.init_params(mx.init.Uniform(0.07))
        if not init_params:  # share one init across both runs
            init_params.update({i: {k: v.copy() for k, v in p.items()}
                                for i, p in mod._params.items()})
        mod._params = {i: {k: v.copy() for k, v in p.items()}
                       for i, p in init_params.items()}
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 1.0})
        mod.fit_step(db)
        return mod.get_params()

    p2, p8 = params_after_step(2), params_after_step(8)
    for stage in p2:
        for name in p2[stage]:
            np.testing.assert_allclose(
                p2[stage][name], p8[stage][name], rtol=2e-4, atol=2e-5,
                err_msg="stage %s param %s" % (stage, name))

    # and against the equivalent non-pipelined Module run (the parity the
    # module docstring promises): same network as ONE composed symbol,
    # same init, same rescale_grad convention
    stages = _stages_norm(normalization)
    net = stages[0]
    for s in stages[1:]:
        net = s(x=net)
    mod = mx.mod.Module(net, context=mx.cpu(0))
    mod.bind(data_shapes=[("data", (8, 6))],
             label_shapes=[("softmax_label", (8,))])
    flat_init = {k: v for p in init_params.values() for k, v in p.items()}
    mod.init_params(mx.init.Uniform(0.07))
    mod.set_params(
        {k: mx.nd.array(v) for k, v in flat_init.items()}, {})
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 1.0})
    mod.forward_backward(db)
    mod.update()
    ref_args, _ = mod.get_params()
    for stage in p2:
        for name in p2[stage]:
            np.testing.assert_allclose(
                p2[stage][name], ref_args[name].asnumpy(),
                rtol=2e-3, atol=2e-4,
                err_msg="vs Module: stage %s param %s" % (stage, name))


def _hetero_stages(D=8):
    """Body stages with UNEQUAL parameter structure: stage 1 is one FC,
    stage 2 is a two-FC bottleneck (wire shape stays D)."""
    data = mx.sym.Variable("data")
    s0 = mx.sym.FullyConnected(data, num_hidden=D, name="adapt",
                               flatten=False)
    x = mx.sym.Variable("x")
    b0 = mx.sym.Activation(
        mx.sym.FullyConnected(x, num_hidden=D, name="b0", flatten=False),
        act_type="tanh")
    x = mx.sym.Variable("x")
    h = mx.sym.FullyConnected(x, num_hidden=2 * D, name="b1a",
                              flatten=False)
    h = mx.sym.Activation(h, act_type="tanh")
    b1 = mx.sym.Activation(
        mx.sym.FullyConnected(h, num_hidden=D, name="b1b", flatten=False),
        act_type="tanh")
    x = mx.sym.Variable("x")
    head = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(x, num_hidden=4, name="head"),
        mx.sym.Variable("softmax_label"), name="softmax")
    return [s0, b0, b1, head]


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_pipeline_heterogeneous_stages_train(schedule):
    """VERDICT r4 item 3: body stages with unequal parameter structure."""
    mod = mx.mod.PipelineModule(_hetero_stages(), n_microbatches=4,
                                schedule=schedule)
    mod.bind(data_shapes=[("data", (8, 6))],
             label_shapes=[("softmax_label", (8,))])
    assert mod._hetero
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer("sgd", {"learning_rate": 0.5})
    rng = np.random.RandomState(0)
    X = rng.randn(8, 6).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.float32) + 2 * (X[:, 1] > 0).astype(
        np.float32)
    db = mx.io.DataBatch(data=[mx.nd.array(X)], label=[mx.nd.array(Y)])
    for _ in range(250):
        outs = mod.fit_step(db)
    p = np.asarray(outs).reshape(8, 4)
    acc = float((p.argmax(1) == Y).mean())
    assert acc >= 0.85, acc
    # per-stage param dicts keep their own (unequal) structures
    params = mod.get_params()
    assert set(params[1]) == {"b0_weight", "b0_bias"}
    assert set(params[2]) == {"b1a_weight", "b1a_bias",
                              "b1b_weight", "b1b_bias"}


def test_1f1b_matches_gpipe_one_step():
    """The hand-scheduled 1F1B backward must produce the same update as
    GPipe autodiff (same math, different schedule)."""
    rng = np.random.RandomState(3)
    X = rng.randn(8, 6).astype(np.float32)
    Y = rng.randint(0, 4, size=(8,)).astype(np.float32)
    db = mx.io.DataBatch(data=[mx.nd.array(X)], label=[mx.nd.array(Y)])
    init_params = {}

    def one_step(schedule):
        mod = mx.mod.PipelineModule(_stages_norm("batch"),
                                    n_microbatches=4, schedule=schedule)
        mod.bind(data_shapes=[("data", (8, 6))],
                 label_shapes=[("softmax_label", (8,))])
        mod.init_params(mx.init.Uniform(0.07))
        if not init_params:
            init_params.update(
                {i: {k: v.copy() for k, v in p.items()}
                 for i, p in mod._params.items()})
        mod._params = {i: {k: v.copy() for k, v in p.items()}
                       for i, p in init_params.items()}
        mod.init_optimizer("sgd", {"learning_rate": 1.0})
        mod.fit_step(db)
        return mod.get_params()

    pg, p1 = one_step("gpipe"), one_step("1f1b")
    for stage in pg:
        for name in pg[stage]:
            np.testing.assert_allclose(
                pg[stage][name], p1[stage][name], rtol=2e-4, atol=2e-5,
                err_msg="stage %s param %s" % (stage, name))


def test_1f1b_batchnorm_stage_aux_updates():
    """1f1b supports BatchNorm (auxiliary states) inside body stages;
    running stats must advance."""
    data = mx.sym.Variable("data")
    s0 = mx.sym.FullyConnected(data, num_hidden=8, name="adapt",
                               flatten=False)
    x = mx.sym.Variable("x")
    b0 = mx.sym.Activation(
        mx.sym.BatchNorm(
            mx.sym.FullyConnected(x, num_hidden=8, name="b0",
                                  flatten=False), name="bn0"),
        act_type="tanh")
    x = mx.sym.Variable("x")
    b1 = mx.sym.Activation(
        mx.sym.FullyConnected(x, num_hidden=8, name="b1", flatten=False),
        act_type="tanh")
    x = mx.sym.Variable("x")
    head = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(x, num_hidden=4, name="head"),
        mx.sym.Variable("softmax_label"), name="softmax")

    mod = mx.mod.PipelineModule([s0, b0, b1, head], n_microbatches=4,
                                schedule="1f1b")
    mod.bind(data_shapes=[("data", (8, 6))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer("sgd", {"learning_rate": 0.5})
    aux0 = {k: v.copy() for k, v in mod.get_aux()[1].items()}
    rng = np.random.RandomState(0)
    X = rng.randn(8, 6).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.float32) + 2 * (X[:, 1] > 0).astype(
        np.float32)
    db = mx.io.DataBatch(data=[mx.nd.array(X)], label=[mx.nd.array(Y)])
    for _ in range(150):
        outs = mod.fit_step(db)
    p = np.asarray(outs).reshape(8, 4)
    assert float((p.argmax(1) == Y).mean()) >= 0.85
    aux1 = mod.get_aux()[1]
    assert any(np.abs(aux1[k] - aux0[k]).max() > 1e-6 for k in aux1)


def test_gpipe_rejects_batchnorm_stage():
    data = mx.sym.Variable("data")
    s0 = mx.sym.FullyConnected(data, num_hidden=8, name="adapt")
    x = mx.sym.Variable("x")
    bnb = mx.sym.BatchNorm(mx.sym.FullyConnected(x, num_hidden=8,
                                                 name="b0"), name="bn0")
    head = mx.sym.SoftmaxOutput(mx.sym.Variable("x"), name="softmax")
    mod = mx.mod.PipelineModule([s0, bnb, bnb, head], n_microbatches=2)
    with pytest.raises(mx.base.MXNetError, match="1f1b"):
        mod.bind(data_shapes=[("data", (4, 6))])

"""PipelineModule (module/pipeline_module.py): the Module-style user
surface for GPipe pipeline parallelism, on the 8-device virtual mesh."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _stages(D=8, n_body=2):
    data = mx.sym.Variable("data")
    s0 = mx.sym.FullyConnected(data, num_hidden=D, name="adapt",
                               flatten=False)
    body = []
    for i in range(n_body):
        x = mx.sym.Variable("x")
        h = mx.sym.FullyConnected(x, num_hidden=D, name="b%d" % i,
                                  flatten=False)
        body.append(mx.sym.Activation(h, act_type="tanh"))
    x = mx.sym.Variable("x")
    head = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(x, num_hidden=4, name="head"),
        mx.sym.Variable("softmax_label"), name="softmax")
    return [s0] + body + [head]


def test_pipeline_module_trains_to_separable_task():
    mod = mx.mod.PipelineModule(_stages(), n_microbatches=4)
    mod.bind(data_shapes=[("data", (8, 6))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    rng = np.random.RandomState(0)
    X = rng.randn(8, 6).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.float32) + 2 * (X[:, 1] > 0).astype(
        np.float32)
    db = mx.io.DataBatch(data=[mx.nd.array(X)], label=[mx.nd.array(Y)])
    accs = []
    for _ in range(250):
        outs = mod.fit_step(db)
        p = np.asarray(outs).reshape(8, 4)
        accs.append(float((p.argmax(1) == Y).mean()))
    assert accs[-1] >= 0.85, accs[-1]


def test_pipeline_module_validations():
    with pytest.raises(ValueError, match="3 stages"):
        mx.mod.PipelineModule(_stages()[:2], n_microbatches=2)
    mod = mx.mod.PipelineModule(_stages(), n_microbatches=3)
    with pytest.raises(ValueError, match="divisible"):
        mod.bind(data_shapes=[("data", (8, 6))],
                 label_shapes=[("softmax_label", (8,))])


def test_pipeline_module_rejects_aux_stages():
    data = mx.sym.Variable("data")
    s0 = mx.sym.FullyConnected(data, num_hidden=8, name="adapt")
    x = mx.sym.Variable("x")
    bnb = mx.sym.BatchNorm(mx.sym.FullyConnected(x, num_hidden=8,
                                                 name="b0"), name="bn0")
    head = mx.sym.SoftmaxOutput(mx.sym.Variable("x"), name="softmax")
    mod = mx.mod.PipelineModule([s0, bnb, bnb, head], n_microbatches=2)
    with pytest.raises(mx.base.MXNetError, match="auxiliary"):
        mod.bind(data_shapes=[("data", (4, 6))])

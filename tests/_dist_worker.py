"""Worker body for the fake-cluster test (reference pattern:
tests/nightly/dist_sync_kvstore.py run via `tools/launch.py -n N`).

Run by tests/test_dist.py through tools/launch.py; NOT collected by pytest.
Asserts push/pull allreduce semantics, then trains a tiny MLP with
rank-dependent data for a few steps and dumps the weights; the parent
asserts replicas are bit-identical across ranks (sync data-parallel SGD).
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# the accelerator plugin can rewrite JAX_PLATFORMS at startup; without the
# config override both workers intermittently grab the one real TPU over
# its tunnel and deadlock the coordinator handshake
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main():
    outdir = sys.argv[1]
    import mxnet_tpu as mx

    kv = mx.kv.create("dist_sync")
    rank, n = kv.rank, kv.num_workers
    assert n == int(os.environ["DMLC_NUM_WORKER"]), (n, os.environ)
    assert rank == int(os.environ["DMLC_WORKER_ID"]), rank

    # --- push/pull semantics: store = init + sum_r (rank+1) applied once
    kv.init(300, mx.nd.ones((4, 2)))
    kv.push(300, mx.nd.array(np.full((4, 2), rank + 1, np.float32)))
    out = mx.nd.zeros((4, 2))
    kv.pull(300, out=out)
    expect = 1.0 + n * (n + 1) / 2.0
    np.testing.assert_allclose(out.asnumpy(), expect)

    # --- big-array path: with a tiny MXNET_KVSTORE_BIGARRAY_BOUND the
    # fused flush must chunk the flattened buffer (reference: big-array
    # server sharding, tests/nightly/dist_sync_kvstore.py:30-40) and the
    # sum must still be exact; several keys staged before one pull also
    # exercises the single-fused-allreduce path
    from mxnet_tpu import config as _config
    _config.set("MXNET_KVSTORE_BIGARRAY_BOUND", 1000)
    big = np.arange(4096, dtype=np.float32).reshape(64, 64)
    kv.init("big", mx.nd.zeros((64, 64)))
    kv.init("small", mx.nd.zeros((3,)))
    kv.push("big", mx.nd.array(big * (rank + 1)))
    kv.push("small", mx.nd.array(np.full((3,), rank + 1, np.float32)))
    bout = mx.nd.zeros((64, 64))
    sout = mx.nd.zeros((3,))
    kv.pull("big", out=bout)
    kv.pull("small", out=sout)
    scale = n * (n + 1) / 2.0
    np.testing.assert_allclose(bout.asnumpy(), big * scale, rtol=1e-6)
    np.testing.assert_allclose(sout.asnumpy(), scale)
    _config.set("MXNET_KVSTORE_BIGARRAY_BOUND", 1000000)

    # --- rank-dependent init must be overridden by rank 0's broadcast
    kv.init("w0", mx.nd.array(np.full((3,), float(rank), np.float32)))
    got = mx.nd.zeros((3,))
    kv.pull("w0", out=got)
    np.testing.assert_allclose(got.asnumpy(), 0.0)

    # --- sync data-parallel training: different data per rank, identical
    # weights after every update (the dist_sync contract)
    rng = np.random.RandomState(100 + rank)
    x = rng.uniform(-1, 1, (64, 2)).astype(np.float32)
    y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.float32)

    d = mx.sym.Variable("data")
    f1 = mx.sym.FullyConnected(d, num_hidden=8, name="fc1")
    a1 = mx.sym.Activation(f1, act_type="tanh")
    f2 = mx.sym.FullyConnected(a1, num_hidden=2, name="fc2")
    sym = mx.sym.SoftmaxOutput(f2, name="softmax")

    it = mx.io.NDArrayIter(x, y, batch_size=16)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.fit(it, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.init.Xavier(),
            kvstore=kv, num_epoch=2)

    params = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    np.savez(os.path.join(outdir, "params_rank%d.npz" % rank), **params)

    # --- failure detection (§5.3): heartbeats published via the
    # coordinator KV store; everyone alive -> zero dead nodes
    dead = kv.get_num_dead_node(0, timeout=2)
    assert dead == 0, "expected no dead nodes, got %d" % dead

    kv.barrier()
    print("dist worker rank %d/%d OK" % (rank, n), flush=True)


if __name__ == "__main__":
    main()

"""NDArray unit tests (modeled on reference tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def test_creation():
    a = mx.nd.array([[1, 2, 3], [4, 5, 6]])
    assert a.shape == (2, 3)
    assert a.dtype == np.float32
    assert a.size == 6
    np.testing.assert_allclose(a.asnumpy(), [[1, 2, 3], [4, 5, 6]])


def test_zeros_ones_full_arange():
    assert mx.nd.zeros((2, 3)).asnumpy().sum() == 0
    assert mx.nd.ones((2, 3)).asnumpy().sum() == 6
    np.testing.assert_allclose(mx.nd.full((2,), 3.5).asnumpy(), [3.5, 3.5])
    np.testing.assert_allclose(mx.nd.arange(0, 5).asnumpy(), np.arange(5, dtype="f"))
    np.testing.assert_allclose(
        mx.nd.arange(0, 3, repeat=2).asnumpy(), [0, 0, 1, 1, 2, 2])


def test_elementwise():
    a = mx.nd.array([1.0, 2.0, 3.0])
    b = mx.nd.array([4.0, 5.0, 6.0])
    np.testing.assert_allclose((a + b).asnumpy(), [5, 7, 9])
    np.testing.assert_allclose((a - b).asnumpy(), [-3, -3, -3])
    np.testing.assert_allclose((a * b).asnumpy(), [4, 10, 18])
    np.testing.assert_allclose((b / a).asnumpy(), [4, 2.5, 2])
    np.testing.assert_allclose((a + 1).asnumpy(), [2, 3, 4])
    np.testing.assert_allclose((1 - a).asnumpy(), [0, -1, -2])
    np.testing.assert_allclose((2 ** a).asnumpy(), [2, 4, 8])
    np.testing.assert_allclose((-a).asnumpy(), [-1, -2, -3])
    np.testing.assert_allclose((a ** 2).asnumpy(), [1, 4, 9])


def test_inplace_arith():
    a = mx.nd.array([1.0, 2.0])
    aid = id(a)
    a += 1
    a *= 2
    assert id(a) == aid
    np.testing.assert_allclose(a.asnumpy(), [4, 6])


def test_comparisons():
    a = mx.nd.array([1.0, 2.0, 3.0])
    b = mx.nd.array([3.0, 2.0, 1.0])
    np.testing.assert_allclose((a == b).asnumpy(), [0, 1, 0])
    np.testing.assert_allclose((a > b).asnumpy(), [0, 0, 1])
    np.testing.assert_allclose((a <= 2).asnumpy(), [1, 1, 0])


def test_indexing():
    a = mx.nd.array(np.arange(12).reshape(3, 4))
    np.testing.assert_allclose(a[1].asnumpy(), [4, 5, 6, 7])
    np.testing.assert_allclose(a[1:3].asnumpy(), np.arange(12).reshape(3, 4)[1:3])
    a[1] = 0.0
    assert a.asnumpy()[1].sum() == 0
    a[:] = 7.0
    assert (a.asnumpy() == 7).all()


def test_reshape_special_codes():
    a = mx.nd.zeros((2, 3, 4))
    assert a.reshape((24,)).shape == (24,)
    assert a.reshape((-1, 4)).shape == (6, 4)
    assert mx.nd.reshape(a, shape=(0, -1)).shape == (2, 12)
    assert mx.nd.reshape(a, shape=(-2,)).shape == (2, 3, 4)
    assert mx.nd.reshape(a, shape=(-3, 4)).shape == (6, 4)
    assert mx.nd.reshape(a, shape=(-4, 1, 2, -2)).shape == (1, 2, 3, 4)


def test_copy_and_context():
    a = mx.nd.ones((2, 2), ctx=mx.cpu(0))
    assert a.context.device_type in ("cpu", "tpu")
    b = a.copyto(mx.cpu(0))
    np.testing.assert_allclose(b.asnumpy(), a.asnumpy())
    c = mx.nd.zeros((2, 2))
    a.copyto(c)
    np.testing.assert_allclose(c.asnumpy(), a.asnumpy())
    d = a.as_in_context(mx.cpu(0))
    assert d.shape == (2, 2)


def test_astype():
    a = mx.nd.array([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = a.astype(np.float16)
    assert c.dtype == np.float16


def test_wait_and_waitall():
    a = mx.nd.ones((4, 4))
    b = a * 2
    b.wait_to_read()
    mx.nd.waitall()
    assert b.asnumpy().sum() == 32


def test_dot():
    a = mx.nd.array(np.random.rand(3, 4).astype("f"))
    b = mx.nd.array(np.random.rand(4, 5).astype("f"))
    out = mx.nd.dot(a, b)
    np.testing.assert_allclose(out.asnumpy(), a.asnumpy() @ b.asnumpy(), rtol=1e-5)
    out_t = mx.nd.dot(a, mx.nd.array(b.asnumpy().T), transpose_b=True)
    np.testing.assert_allclose(out_t.asnumpy(), a.asnumpy() @ b.asnumpy(), rtol=1e-5)


def test_save_load_list_and_dict(tmp_path):
    fname = str(tmp_path / "test.params")
    a = mx.nd.array([1.0, 2.0])
    b = mx.nd.array([[3.0]])
    mx.nd.save(fname, [a, b])
    la, lb = mx.nd.load(fname)
    np.testing.assert_allclose(la.asnumpy(), a.asnumpy())
    np.testing.assert_allclose(lb.asnumpy(), b.asnumpy())
    mx.nd.save(fname, {"w": a, "b": b})
    d = mx.nd.load(fname)
    assert set(d.keys()) == {"w", "b"}
    np.testing.assert_allclose(d["w"].asnumpy(), a.asnumpy())


def test_concatenate_and_split():
    a = mx.nd.ones((2, 3))
    b = mx.nd.zeros((2, 3))
    c = mx.nd.concatenate([a, b], axis=0)
    assert c.shape == (4, 3)
    parts = mx.nd.split(mx.nd.array(np.arange(12).reshape(2, 6)), num_outputs=3, axis=1)
    assert len(parts) == 3
    assert parts[0].shape == (2, 2)


def test_broadcast_ops():
    a = mx.nd.array(np.ones((2, 1)))
    b = mx.nd.array(np.arange(3).reshape(1, 3))
    out = mx.nd.broadcast_add(a, b)
    assert out.shape == (2, 3)
    np.testing.assert_allclose(out.asnumpy(), 1 + np.arange(3) * np.ones((2, 1)))


def test_ndarray_onehot_encode():
    idx = mx.nd.array([0, 2])
    out = mx.nd.zeros((2, 3))
    mx.nd.onehot_encode(idx, out)
    np.testing.assert_allclose(out.asnumpy(), [[1, 0, 0], [0, 0, 1]])


def test_scalar_fill_keeps_placement():
    """Full-slice scalar assignment must stay on the array's device:
    jnp.full_like places fresh constants on the DEFAULT backend, which
    silently migrated bias/gamma/beta initializations on rigs whose
    default device differs from the context (round-5 dqn example bug)."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import ndarray as nd
    # a NON-default device, or the test is vacuous (full_like's default
    # placement would equal `before` anyway)
    ctx = mx.cpu(1) if mx.num_devices("cpu") > 1 else mx.cpu(0)
    z = nd.zeros((4,), dtype=np.float32, ctx=ctx)
    before = z.data.devices()
    assert before == {ctx.jax_device}
    z[:] = 0.0
    assert z.data.devices() == before
    z[:] = 3.5
    assert z.data.devices() == before
    np.testing.assert_allclose(z.asnumpy(), 3.5)

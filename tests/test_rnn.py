"""Symbolic RNN cells + bucketed iterator (reference test pattern:
tests/python/unittest/test_rnn.py — fused/unfused consistency,
pack/unpack round-trip, unroll shapes)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.rnn import (BucketSentenceIter, BidirectionalCell,
                           FusedRNNCell, GRUCell, LSTMCell, RNNCell,
                           SequentialRNNCell, ResidualCell, encode_sentences)


def _run_sym(sym, shapes, seed=0):
    ex = sym.simple_bind(ctx=mx.cpu(), **shapes)
    rng = np.random.RandomState(seed)
    for name, arr in ex.arg_dict.items():
        if "begin_state" in name:
            arr[:] = 0.0
        else:
            arr[:] = rng.uniform(-0.1, 0.1, arr.shape).astype(np.float32)
    return ex, {k: v.asnumpy() for k, v in ex.arg_dict.items()}


def test_rnn_cell_unroll_shapes():
    for cell, n_states in ((RNNCell(8, prefix="r_"), 1),
                           (LSTMCell(8, prefix="l_"), 2),
                           (GRUCell(8, prefix="g_"), 1)):
        outputs, states = cell.unroll(3, input_prefix="x_")
        assert len(outputs) == 3
        assert len(states) == n_states
        g = mx.sym.Group(outputs)
        shapes = {"x_t%d_data" % t: (4, 5) for t in range(3)}
        _, out_shapes, _ = g.infer_shape(__batch_size__=4, **shapes)
        assert all(s == (4, 8) for s in out_shapes)


def test_fused_matches_unfused_lstm():
    """The fused (lax.scan) path and the unrolled graph must agree."""
    T, N, I, H = 4, 2, 3, 5
    fused = FusedRNNCell(H, num_layers=1, mode="lstm", prefix="lstm_",
                         get_next_state=True)
    f_out, f_states = fused.unroll(T, inputs=mx.sym.Variable("data"),
                                   layout="NTC", merge_outputs=True)
    ex_f, args_f = _run_sym(mx.sym.Group([f_out] + f_states),
                            {"data": (N, T, I)})
    outs_f = ex_f.forward(is_train=False)

    unfused = fused.unfuse()
    u_out, u_states = unfused.unroll(T, inputs=mx.sym.Variable("data"),
                                     layout="NTC", merge_outputs=True)
    ex_u = mx.sym.Group([u_out]).simple_bind(ctx=mx.cpu(), data=(N, T, I),
                                             __batch_size__=N)
    # fused packed vector -> per-gate entries -> per-cell fused i2h/h2h
    cell_args = unfused.pack_weights(fused.unpack_weights(
        {"lstm_parameters": mx.nd.array(args_f["lstm_parameters"])}))
    for name, arr in ex_u.arg_dict.items():
        if name == "data":
            arr[:] = args_f["data"]
        elif name in cell_args:
            arr[:] = cell_args[name].asnumpy()
        else:
            arr[:] = 0.0
    outs_u = ex_u.forward(is_train=False)
    np.testing.assert_allclose(outs_u[0].asnumpy(), outs_f[0].asnumpy(),
                               rtol=2e-5, atol=2e-6)


def test_pack_unpack_roundtrip():
    for mode, bidir in (("lstm", False), ("gru", True), ("rnn_tanh", False)):
        cell = FusedRNNCell(6, num_layers=2, mode=mode, bidirectional=bidir,
                            prefix="f_")
        from mxnet_tpu.ops.rnn_op import rnn_param_size
        n = rnn_param_size(2, 4, 6, mode, bidir)
        packed = mx.nd.array(
            np.random.RandomState(0).uniform(-1, 1, (n,)).astype(np.float32))
        unpacked = cell.unpack_weights({"f_parameters": packed})
        assert "f_parameters" not in unpacked
        repacked = cell.pack_weights(unpacked)
        np.testing.assert_array_equal(repacked["f_parameters"].asnumpy(),
                                      packed.asnumpy())


def test_bidirectional_residual_stack():
    stack = SequentialRNNCell()
    stack.add(BidirectionalCell(LSTMCell(4, prefix="fl_"),
                                LSTMCell(4, prefix="fr_"),
                                output_prefix="bi_"))
    outputs, _ = stack._cells[0].unroll(3, input_prefix="x_",
                                        merge_outputs=True)
    shapes = {"x_t%d_data" % t: (2, 5) for t in range(3)}
    _, out_shapes, _ = outputs.infer_shape(__batch_size__=2, **shapes)
    assert out_shapes == [(2, 3, 8)]    # fwd+bwd concat on channel

    res = ResidualCell(RNNCell(5, prefix="rr_"))
    outputs, _ = res.unroll(2, input_prefix="y_")
    shapes = {"y_t%d_data" % t: (2, 5) for t in range(2)}
    _, out_shapes, _ = mx.sym.Group(outputs).infer_shape(__batch_size__=2,
                                                         **shapes)
    assert all(s == (2, 5) for s in out_shapes)


def test_encode_sentences_and_bucket_iter():
    sents = [["a", "b", "c"], ["a", "b"], ["b", "c"], ["a", "b", "c", "d"],
             ["a", "c"], ["b", "a"], ["c", "b", "a"]]
    encoded, vocab = encode_sentences(sents, start_label=1)
    assert all(isinstance(i, int) for s in encoded for i in s)
    assert len(set(vocab.values())) == len(vocab)

    it = BucketSentenceIter(encoded, batch_size=2, buckets=[2, 3],
                            invalid_label=-1, seed=7)
    assert it.default_bucket_key == 3
    seen = 0
    for batch in it:
        seen += 1
        d = batch.data[0].asnumpy()
        l = batch.label[0].asnumpy()
        assert d.shape == (2, batch.bucket_key)
        # label is the next-token shift with invalid tail
        np.testing.assert_array_equal(l[:, :-1], d[:, 1:])
        assert np.all(l[:, -1] == -1)
    assert seen >= 2
    it.reset()
    assert sum(1 for _ in it) == seen


def test_bucket_iter_time_major():
    sents = [[1, 2], [3, 4], [5, 6], [7, 8]]
    it = BucketSentenceIter(sents, batch_size=2, buckets=[2], layout="TN")
    batch = next(iter(it))
    assert batch.data[0].shape == (2, 2)
    assert batch.provide_data[0].layout == "TN"

"""Whole-program lock-order analysis (mxnet_tpu.analysis.concurrency).

Fires / stays-silent pairs for every finding the pass emits —
``lock-order-cycle``, interprocedural ``lock-host-sync`` (the PR 2
train_rcnn deadlock shape: helper-hidden sync under a caller's lock),
``unlocked-shared-state`` — plus the bare ``acquire()``/``release()``
lock_stack fix in the lexical linter.
"""
import os
import sys
import textwrap

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxnet_tpu.analysis import lint_paths, lint_source  # noqa: E402


def codes(report, code=None):
    if code is None:
        return [f.code for f in report]
    return [f for f in report if f.code == code]


def lint_tree(tmp_path, **files):
    for name, src in files.items():
        (tmp_path / (name + ".py")).write_text(textwrap.dedent(src))
    return lint_paths([str(tmp_path)])


# ===================================================== lock-order-cycle


ABBA_A = """
    import threading
    import mod_b

    LA = threading.Lock()

    def fa():
        with LA:
            with mod_b.LB:
                pass
"""

ABBA_B = """
    import threading
    import mod_a

    LB = threading.Lock()

    def fb():
        with LB:
            with mod_a.LA:
                pass
"""


def test_two_module_abba_cycle_fires(tmp_path):
    """The synthetic two-module ABBA cycle reports an ERROR naming BOTH
    acquisition chains with file:line (acceptance criterion)."""
    report = lint_tree(tmp_path, mod_a=ABBA_A, mod_b=ABBA_B)
    found = codes(report, "lock-order-cycle")
    assert len(found) == 1, [str(f) for f in report]
    f = found[0]
    assert f.severity.name == "ERROR"
    assert "mod_a.LA" in f.message and "mod_b.LB" in f.message
    assert "mod_a.py:" in f.message and "mod_b.py:" in f.message
    # both chains, not just the closing edge
    assert f.message.count("while holding") >= 2 or \
        f.message.count("while the caller holds") >= 1


def test_consistent_order_stays_silent(tmp_path):
    """Same two locks, both paths take them in the SAME order: no cycle."""
    report = lint_tree(
        tmp_path,
        mod_a=ABBA_A,
        mod_b="""
            import threading
            import mod_a

            LB = threading.Lock()

            def fb():
                with mod_a.LA:
                    with LB:
                        pass
        """)
    assert not codes(report, "lock-order-cycle"), \
        [str(f) for f in codes(report, "lock-order-cycle")]


def test_cycle_through_helper_call_fires(tmp_path):
    """The interprocedural edge: fa holds LA and CALLS a helper that
    acquires LB; fb nests them the other way lexically."""
    report = lint_tree(
        tmp_path,
        mod="""
            import threading

            LA = threading.Lock()
            LB = threading.Lock()

            def helper():
                with LB:
                    pass

            def fa():
                with LA:
                    helper()

            def fb():
                with LB:
                    with LA:
                        pass
        """)
    assert len(codes(report, "lock-order-cycle")) == 1, \
        [str(f) for f in report]


def test_cycle_allow_annotation_suppresses(tmp_path):
    report = lint_tree(
        tmp_path,
        mod="""
            import threading

            LA = threading.Lock()
            LB = threading.Lock()

            def fa():
                with LA:
                    with LB:  # mx-lint: allow(lock-order-cycle)
                        pass

            def fb():
                with LB:
                    with LA:
                        pass
        """)
    assert not codes(report, "lock-order-cycle")


def test_instance_attr_locks_cycle_fires(tmp_path):
    """self._*lock* attrs are named nodes too — an ABBA between two
    methods of one class is a cycle."""
    report = lint_tree(
        tmp_path,
        mod="""
            import threading

            class Srv:
                def __init__(self):
                    self._queue_lock = threading.Lock()
                    self._model_lock = threading.Lock()

                def submit(self):
                    with self._queue_lock:
                        with self._model_lock:
                            pass

                def shutdown(self):
                    with self._model_lock:
                        with self._queue_lock:
                            pass
        """)
    found = codes(report, "lock-order-cycle")
    assert len(found) == 1, [str(f) for f in report]
    assert "Srv._queue_lock" in found[0].message
    assert "Srv._model_lock" in found[0].message


# ==================================== interprocedural lock-host-sync


RCNN_SHAPE = """
    import threading

    class Trainer:
        def __init__(self):
            self._lock = threading.Lock()

        def _fetch(self, x):
            return x.asnumpy()

        def step(self, x):
            with self._lock:
                return self._fetch(x)
"""


def test_helper_hidden_sync_under_lock_fires(tmp_path):
    """The PR 2 train_rcnn deadlock shape (acceptance criterion): the
    sync is one call deep, invisible to the lexical linter — the
    interprocedural pass names caller lock, helper and sync site."""
    report = lint_tree(tmp_path, trainer=RCNN_SHAPE)
    found = codes(report, "lock-host-sync")
    assert len(found) == 1, [str(f) for f in report]
    f = found[0]
    assert f.severity.name == "ERROR"
    assert "_fetch" in f.message and "asnumpy" in f.message
    assert "Trainer._lock" in f.message
    assert "trainer.py:" in f.message        # the callee sync site


def test_helper_sync_outside_lock_stays_silent(tmp_path):
    """Same helper called OUTSIDE the lock: nothing to report — and the
    depth-0 lexical finding is not duplicated by this pass."""
    report = lint_tree(
        tmp_path,
        trainer="""
            import threading

            class Trainer:
                def __init__(self):
                    self._lock = threading.Lock()

                def _fetch(self, x):
                    return x.asnumpy()

                def step(self, x):
                    with self._lock:
                        n = 1
                    return self._fetch(x)
        """)
    assert not codes(report, "lock-host-sync"), \
        [str(f) for f in codes(report, "lock-host-sync")]


def test_lexical_sync_not_double_reported(tmp_path):
    """A depth-0 sync under a lock is the LEXICAL linter's finding;
    the interprocedural pass must not report it a second time."""
    report = lint_tree(
        tmp_path,
        mod="""
            import threading

            class T:
                def __init__(self):
                    self._lock = threading.Lock()

                def step(self, x):
                    with self._lock:
                        return x.asnumpy()
        """)
    assert len(codes(report, "lock-host-sync")) == 1, \
        [str(f) for f in codes(report, "lock-host-sync")]


def test_interprocedural_sync_allow_on_callee_suppresses(tmp_path):
    report = lint_tree(
        tmp_path,
        trainer="""
            import threading

            class Trainer:
                def __init__(self):
                    self._lock = threading.Lock()

                def _fetch(self, x):
                    return x.asnumpy()  # mx-lint: allow(lock-host-sync)

                def step(self, x):
                    with self._lock:
                        return self._fetch(x)
        """)
    assert not codes(report, "lock-host-sync")


def test_cross_module_helper_sync_fires(tmp_path):
    """The helper lives in ANOTHER module, reached via the import
    alias — still one level, still found."""
    report = lint_tree(
        tmp_path,
        helpers="""
            def fetch(x):
                return x.asnumpy()
        """,
        caller="""
            import threading
            import helpers

            L = threading.Lock()

            def step(x):
                with L:
                    return helpers.fetch(x)
        """)
    assert len(codes(report, "lock-host-sync")) == 1, \
        [str(f) for f in report]


# ==================================================== unlocked-shared-state


def test_unlocked_shared_state_fires(tmp_path):
    """An attr written under the lock in one method but bare on the
    Thread-entry path: the discipline has a hole."""
    report = lint_tree(
        tmp_path,
        mod="""
            import threading

            class Srv:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._served = 0
                    self._worker = threading.Thread(target=self._loop)

                def submit(self):
                    with self._lock:
                        self._served += 1

                def _loop(self):
                    while True:
                        self._served += 1
        """)
    found = codes(report, "unlocked-shared-state")
    assert len(found) == 1, [str(f) for f in report]
    f = found[0]
    assert f.severity.name == "WARNING"
    assert "_served" in f.message and "_loop" in f.message


def test_locked_everywhere_stays_silent(tmp_path):
    report = lint_tree(
        tmp_path,
        mod="""
            import threading

            class Srv:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._served = 0
                    self._worker = threading.Thread(target=self._loop)

                def submit(self):
                    with self._lock:
                        self._served += 1

                def _loop(self):
                    while True:
                        with self._lock:
                            self._served += 1
        """)
    assert not codes(report, "unlocked-shared-state")


def test_init_writes_are_exempt(tmp_path):
    """__init__ runs before Thread.start() — that edge is the
    happens-before, not a hole."""
    report = lint_tree(
        tmp_path,
        mod="""
            import threading

            class Srv:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._served = 0
                    self._worker = threading.Thread(target=self._loop)

                def _loop(self):
                    with self._lock:
                        self._served += 1
        """)
    assert not codes(report, "unlocked-shared-state")


# =========================================== bare acquire()/release()


def test_bare_acquire_sync_fires():
    """Satellite: the try/finally acquire()/release() idiom must feed
    lock_stack — a sync between the pair is exactly as deadlock-prone
    as under `with`."""
    report = lint_source(textwrap.dedent("""
        class T:
            def fetch(self, x):
                self._lock.acquire()
                try:
                    return x.asnumpy()
                finally:
                    self._lock.release()
    """))
    found = codes(report, "lock-host-sync")
    assert len(found) == 1, codes(report)
    assert "_lock" in found[0].message


def test_bare_release_ends_tracking():
    """After release() the lock is no longer held — the sync below the
    pair stays silent."""
    report = lint_source(textwrap.dedent("""
        class T:
            def fetch(self, x):
                self._lock.acquire()
                n = self._n
                self._lock.release()
                return x.asnumpy()
    """))
    assert not codes(report, "lock-host-sync"), codes(report)


def test_bare_acquire_dispatch_warns():
    report = lint_source(textwrap.dedent("""
        import jax.numpy as jnp

        def f(lock, x):
            lock.acquire()
            try:
                return jnp.sum(x)
            finally:
                lock.release()
    """))
    assert len(codes(report, "lock-dispatch")) == 1, codes(report)


def test_bare_acquire_feeds_concurrency_graph(tmp_path):
    """acquire()/release() pairs build the SAME order edges as `with` —
    an ABBA between the two idioms is still a cycle."""
    report = lint_tree(
        tmp_path,
        mod="""
            import threading

            LA = threading.Lock()
            LB = threading.Lock()

            def fa():
                LA.acquire()
                try:
                    with LB:
                        pass
                finally:
                    LA.release()

            def fb():
                with LB:
                    with LA:
                        pass
        """)
    assert len(codes(report, "lock-order-cycle")) == 1, \
        [str(f) for f in report]


# ================================================== shipped-tree shapes


def test_condition_aliasing_no_false_cycle(tmp_path):
    """Condition(self._lock) shares the lock's node — nesting the cond
    and its own lock must never read as a two-node cycle."""
    report = lint_tree(
        tmp_path,
        mod="""
            import threading

            class Srv:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cond = threading.Condition(self._lock)

                def wake(self):
                    with self._lock:
                        self._cond.notify_all()

                def wait_done(self):
                    with self._cond:
                        with self._lock:
                            pass
        """)
    assert not codes(report, "lock-order-cycle"), \
        [str(f) for f in codes(report, "lock-order-cycle")]


def test_lockcheck_funnel_locks_are_named(tmp_path):
    """Locks created through the mxnet_tpu.lockcheck funnels are
    first-class nodes, same as raw threading ones."""
    report = lint_tree(
        tmp_path,
        mod="""
            from mxnet_tpu import lockcheck

            LA = lockcheck.Lock(name="A")
            LB = lockcheck.Lock(name="B")

            def fa():
                with LA:
                    with LB:
                        pass

            def fb():
                with LB:
                    with LA:
                        pass
        """)
    assert len(codes(report, "lock-order-cycle")) == 1, \
        [str(f) for f in report]


def test_findings_flow_through_baseline_keys(tmp_path):
    """Concurrency findings carry path/func, so the ordinary baseline
    keying (path::code::func) covers them."""
    from mxnet_tpu.analysis import baseline_key
    report = lint_tree(tmp_path, trainer=RCNN_SHAPE)
    f = codes(report, "lock-host-sync")[0]
    key = baseline_key(f, str(tmp_path))
    assert key == "trainer.py::lock-host-sync::Trainer.step", key

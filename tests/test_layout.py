"""SpecLayout — the unified ``data x fsdp x tp`` layout (ISSUE 14).

Covers: the dataclass + name-heuristic resolver, the island
unification pin (check_islands must report ZERO disagreements on the
canonical mesh — the standing expert/pipe/sp-axis and batch-layout
findings are gone), Module FSDP end-to-end (params AND optimizer
states sharded, resident bytes shrink, zero steady-state recompiles),
fit(layout=), elastic-style bit-identical parity across layouts, and
checkpoint reshard-on-load through the same layout funnel.
"""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel import SpecLayout, parameter_spec_from_name
from mxnet_tpu.parallel.layout import (island_specs, resolve_model_axis,
                                       strip_ckpt_key)
from mxnet_tpu.parallel.mesh import resolve_layout_spec

import jax
from jax.sharding import PartitionSpec as P

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh")


# --------------------------------------------------------- the dataclass


def test_speclayout_validation():
    with pytest.raises(ValueError):
        SpecLayout(fsdp=0)
    with pytest.raises(ValueError):
        SpecLayout(fsdp=-1)
    with pytest.raises(ValueError):
        SpecLayout(data=0)
    with pytest.raises(ValueError):
        SpecLayout(data=-2)


def test_speclayout_axes_sized_world():
    lo = SpecLayout(data=2, fsdp=2, tp=2)
    assert lo.axes() == {"data": 2, "fsdp": 2, "tp": 2}
    assert lo.world_size() == 8
    ab = SpecLayout(fsdp=2)
    assert ab.world_size() is None
    assert ab.sized(8).data == 4
    with pytest.raises(ValueError):
        SpecLayout(fsdp=3).sized(8)


def test_speclayout_mesh_carries_all_axes():
    mesh = SpecLayout(data=4, fsdp=2).mesh()
    assert tuple(mesh.axis_names) == ("data", "fsdp", "tp")
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == \
        {"data": 4, "fsdp": 2, "tp": 1}


# ----------------------------------------------------- the name heuristic


def test_param_spec_fsdp_largest_divisible_dim():
    lo = SpecLayout(data=2, fsdp=4, min_shard_bytes=0)
    # dim 1 is largest and divisible -> fsdp there
    assert lo.spec_for("lut_weight", (4, 64)) == P(None, "fsdp")
    # dim 0 largest
    assert lo.spec_for("fc1_weight", (2048, 1024)) == P("fsdp")
    # nothing divisible -> replicated, NEVER an invalid spec
    assert lo.spec_for("odd_weight", (7, 9)) == P()


def test_param_spec_min_shard_bytes_keeps_small_replicated():
    lo = SpecLayout(data=2, fsdp=4)          # default 1 MiB threshold
    assert lo.spec_for("small_weight", (64, 64)) == P()
    assert lo.spec_for("big_weight", (1024, 1024)) != P()


def test_param_spec_tp_rules_col_and_row():
    lo = SpecLayout(data=2, fsdp=2, tp=2, min_shard_bytes=0)
    # col-parallel names: tp on dim 0 (mxnet FC weight is (out, in))
    assert lo.spec_for("layer0_att_qkv_weight", (96, 32)) == \
        P("tp", "fsdp")
    assert lo.spec_for("fc1_weight", (128, 64)) == P("tp", "fsdp")
    # row-parallel names: tp on dim 1 (fsdp takes the free dim 0)
    assert lo.spec_for("fc2_weight", (64, 128)) == P("fsdp", "tp")
    assert lo.spec_for("layer0_att_out_proj_weight", (32, 32)) == \
        P("fsdp", "tp")


def test_param_spec_unknown_shape_replicates():
    lo = SpecLayout(data=2, fsdp=4)
    assert lo.spec_for("anything_weight") == P()
    assert parameter_spec_from_name("x_weight", None, layout=lo) == P()


def test_overrides_win_exact_and_regex():
    lo = SpecLayout(data=2, fsdp=4, min_shard_bytes=0,
                    overrides={"special_weight": P("tp"),
                               r".*_gamma": P("fsdp")})
    assert lo.spec_for("special_weight", (64, 64)) == P("tp")
    assert lo.spec_for("bn1_gamma", (64,)) == P("fsdp")
    # non-matching falls through to the heuristic
    assert lo.spec_for("fc9_weight", (64, 64)) == P(None, "fsdp") or \
        lo.spec_for("fc9_weight", (64, 64)) == P("fsdp")


def test_resolve_layout_spec_strips_ckpt_keys():
    lo = SpecLayout(data=2, fsdp=4, min_shard_bytes=0)
    want = lo.spec_for("fc1_weight", (256, 64))
    assert resolve_layout_spec(lo, "arg:fc1_weight", (256, 64)) == want
    assert resolve_layout_spec(lo, "opt:fc1_weight.0", (256, 64)) == want
    # rng/upd bookkeeping stays replicated
    assert resolve_layout_spec(lo, "rng:global_key", (4,)) is None
    assert strip_ckpt_key("rng:global_key") is None
    assert strip_ckpt_key("opt:fc1_weight.0.1") == "fc1_weight"


def test_callable_protocol_shape_blind():
    lo = SpecLayout(data=2, fsdp=4, overrides={"x_weight": P("fsdp")})
    assert lo("x_weight") == P("fsdp")       # override, no shape needed
    assert lo("y_weight") == P()             # heuristic without shape


# ------------------------------------------------- the island unification


def test_islands_unified_zero_disagreements():
    """THE ISSUE 14 pin: the standing expert/pipe/sp-axis and
    batch-layout findings are GONE — every island draws from one
    SpecLayout, audited against the canonical mesh."""
    from mxnet_tpu.analysis import check_islands
    from mxnet_tpu.parallel import sharding_islands
    islands = sharding_islands()
    assert set(islands) == {"mesh", "dist", "moe", "pipeline",
                            "ring_attention"}
    report = check_islands(islands,
                           mesh=SpecLayout(data=2, fsdp=2, tp=2).mesh())
    assert len(report.findings) == 0, \
        [f.format() for f in report.findings]


def test_islands_share_one_batch_layout():
    from mxnet_tpu.parallel import sharding_islands
    islands = sharding_islands()
    batch_specs = {str(specs["batch"]) for specs in islands.values()}
    assert len(batch_specs) == 1, batch_specs


def test_island_specs_unknown_island():
    with pytest.raises(ValueError):
        island_specs("nope")


def test_resolve_model_axis():
    canonical = SpecLayout(data=2, tp=4).mesh()
    legacy = mx.parallel.make_mesh({"data": 2, "expert": 4})
    assert resolve_model_axis(canonical, "expert") == "tp"
    assert resolve_model_axis(legacy, "expert") == "expert"


def test_moe_default_axis_on_canonical_mesh():
    """moe_apply with no axis arg runs on a canonical mesh (the old
    default hard-coded 'expert', which no canonical mesh carries)."""
    import jax.numpy as jnp
    from mxnet_tpu.parallel.moe import moe_apply, moe_init
    mesh = SpecLayout(data=2, tp=4).mesh()
    rng = np.random.RandomState(3)
    params = moe_init(rng, 16, 32, 8)
    x = rng.normal(0, 1, (64, 16)).astype(np.float32)
    out_plain, _ = moe_apply(params, x, capacity_factor=8.0)
    out_mesh, _ = jax.jit(
        lambda p, xx: moe_apply(p, xx, capacity_factor=8.0,
                                mesh=mesh))(params, x)
    np.testing.assert_allclose(np.asarray(out_mesh),
                               np.asarray(out_plain), rtol=2e-5,
                               atol=1e-5)


# --------------------------------------------------- Module FSDP binding


def _mlp():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=512, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=8, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _iter(n=64, d=784, classes=8, batch=16):
    rng = np.random.RandomState(0)
    X = rng.uniform(-1, 1, (n, d)).astype(np.float32)
    Y = rng.randint(0, classes, (n,)).astype(np.float32)
    return mx.io.NDArrayIter(X, Y, batch_size=batch)


def test_fsdp_fit_shards_params_and_states():
    lo = SpecLayout(data=2, fsdp=4, min_shard_bytes=1 << 16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu(), layout=lo)
    with mx.profiler.counter_delta() as d:
        mod.fit(_iter(), num_epoch=2, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
                initializer=mx.init.Uniform(0.05))
    w = mod._exec.arg_dict["fc1_weight"].data
    assert "fsdp" in str(w.sharding.spec)
    # ZeRO: per-device resident = full/4
    shard = max(s.data.nbytes for s in w.addressable_shards)
    assert shard * 4 == w.nbytes
    # optimizer state follows the parameter layout
    for leaf in jax.tree_util.tree_leaves(mod._fused_states["fc1_weight"]):
        assert leaf.sharding.spec == w.sharding.spec
    # the batch shards over BOTH dp axes
    assert mod._batch_sharding is not None
    assert d.all().get("loop_recompile", 0) == 0


def test_fit_layout_kwarg_routes_set_layout():
    lo = SpecLayout(data=4, fsdp=2, min_shard_bytes=1 << 16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(_iter(), num_epoch=1, optimizer="sgd", layout=lo,
            initializer=mx.init.Uniform(0.05))
    assert mod._layout == lo
    assert dict(zip(mod._mesh.axis_names, mod._mesh.devices.shape)) == \
        {"data": 4, "fsdp": 2, "tp": 1}


def test_explicit_param_shardings_beat_the_layout():
    lo = SpecLayout(data=2, fsdp=4, min_shard_bytes=0)
    mod = mx.mod.Module(_mlp(), context=mx.cpu(), layout=lo,
                        param_shardings={"fc1_weight": P(None, None)})
    mod.bind(data_shapes=[("data", (16, 784))],
             label_shapes=[("softmax_label", (16,))])
    mod.init_params(mx.init.Uniform(0.05))
    assert str(mod._exec.arg_dict["fc1_weight"].data.sharding.spec) == \
        str(P(None, None))
    # un-overridden params still follow the layout
    assert "fsdp" in str(mod._sharding_for("fc2_weight").spec) or \
        mod._sharding_for("fc2_weight").spec == P()


def test_set_layout_errors():
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    with pytest.raises(MXNetError):
        mod.set_layout(object())
    with pytest.raises(MXNetError):
        mx.mod.Module(_mlp(), context=mx.cpu(),
                      mesh_shape={"data": 8},
                      layout=SpecLayout(data=8))
    lo = SpecLayout(data=8)
    mod = mx.mod.Module(_mlp(), context=mx.cpu(), layout=lo)
    mod.bind(data_shapes=[("data", (16, 784))],
             label_shapes=[("softmax_label", (16,))])
    mod.set_layout(lo)                       # same layout: idempotent
    with pytest.raises(MXNetError):
        mod.set_layout(SpecLayout(data=2, fsdp=4))


def test_indivisible_batch_fails_naming_the_input():
    lo = SpecLayout(data=2, fsdp=4)
    mod = mx.mod.Module(_mlp(), context=mx.cpu(), layout=lo)
    with pytest.raises(MXNetError, match="data"):
        mod.bind(data_shapes=[("data", (12, 784))],
                 label_shapes=[("softmax_label", (12,))])


# ------------------------------------- parity + checkpoint reshard drill


def _lookup_net():
    """One-hot lookup regression (the elastic drill's exact model):
    every reduction has exactly one nonzero contributor, so params are
    bit-identical across ANY layout."""
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, no_bias=True,
                               name="lut")
    return mx.sym.LinearRegressionOutput(fc, mx.sym.Variable("label"),
                                         name="reg")


def _lookup_iter():
    x = np.eye(64, dtype=np.float32)[np.arange(64) % 64]
    rng = np.random.RandomState(3)
    y = rng.uniform(-1, 1, (64, 4)).astype(np.float32)
    return mx.io.NDArrayIter({"data": x}, {"label": y}, batch_size=8)


def _train_lookup(layout):
    mx.random.seed(5)
    mod = mx.mod.Module(_lookup_net(), context=mx.cpu(),
                        data_names=("data",), label_names=("label",),
                        layout=layout)
    mod.fit(_lookup_iter(), num_epoch=2, eval_metric="mse",
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.3, "momentum": 0.9})
    arg, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in arg.items()}


def test_dp_vs_fsdp_bit_identical_on_exact_model():
    w_dp = _train_lookup(SpecLayout(data=8))
    w_fsdp = _train_lookup(SpecLayout(data=2, fsdp=4,
                                      min_shard_bytes=0))
    for k in w_dp:
        np.testing.assert_array_equal(w_dp[k], w_fsdp[k], err_msg=k)


def test_checkpoint_reshards_through_the_layout():
    """Save under dp2 x fsdp4, reshard-on-load through a DIFFERENT
    SpecLayout onto 4 devices — same resolver funnel as the bind, param
    and optimizer-state bytes intact."""
    lo8 = SpecLayout(data=2, fsdp=4, min_shard_bytes=1 << 16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu(), layout=lo8)
    d = tempfile.mkdtemp(prefix="layout_ck")
    mx.random.seed(9)
    mod.fit(_iter(), num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.init.Uniform(0.05),
            checkpoint=mx.checkpoint.CheckpointConfig(d, period_epochs=1))
    lo4 = SpecLayout(data=2, fsdp=2, min_shard_bytes=1 << 16)
    mesh4 = lo4.mesh(devices=jax.devices()[:4])
    _path, tensors, _mf = mx.checkpoint.load_latest(d, mesh=mesh4,
                                                    layout=lo4)
    w = tensors["arg:fc1_weight"]
    assert "fsdp" in str(w.sharding.spec)
    assert len(w.sharding.device_set) == 4
    np.testing.assert_array_equal(
        np.asarray(w), mod._exec.arg_dict["fc1_weight"].asnumpy())
    st = tensors.get("opt:fc1_weight")
    if st is not None:
        assert "fsdp" in str(st.sharding.spec)


def test_obs_report_carries_mesh_shape():
    lo = SpecLayout(data=2, fsdp=4, min_shard_bytes=1 << 16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu(), layout=lo)
    mod.fit(_iter(), num_epoch=1, optimizer="sgd",
            initializer=mx.init.Uniform(0.05))
    rep = mx.obs.report()
    ours = [e for e in rep["executors"]
            if e.get("mesh") == {"data": 2, "fsdp": 4, "tp": 1}]
    assert ours, rep["executors"]

"""serve.kv_cache — page ledger properties + budget audit (ISSUE 16).

The allocator contract: randomized join/finish interleavings never leak
or double-free pages (the ledger's ``check()`` invariant audit runs
after EVERY step), the occupancy gauges the server exports match the
host-side model exactly, ``max_slots_for`` is the consistent inverse of
``hbm_bytes`` (and int8 roughly doubles the slots a fixed budget
admits), and the hbm-budget audit rejects an over-budget reservation at
server start NAMING it — while ``MXNET_TPU_ANALYZE=off`` keeps the
analysis package unimported (the zero-cost gate).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import config as cfg
from mxnet_tpu import profiler
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serve.kv_cache import (KVCache, PageLedger, max_slots_for)


# ------------------------------------------------------------ ledger unit

def test_ledger_basic_lifecycle():
    led = PageLedger(max_slots=4, max_seq=16, page=4)
    assert led.total_pages == 16
    s = led.acquire(5)
    assert s is not None
    assert led.slots_in_use == 1
    assert led.pages_in_use == 2          # ceil(5/4)
    assert led.length(s) == 5
    for _ in range(3):
        led.grow(s)
    assert led.pages_in_use == 2          # 8 tokens still 2 pages
    led.grow(s)
    assert led.pages_in_use == 3          # 9th token opens page 3
    assert led.release(s) == 3
    assert led.slots_in_use == 0 and led.pages_in_use == 0
    led.check()


def test_ledger_double_free_raises():
    led = PageLedger(max_slots=2, max_seq=8, page=4)
    s = led.acquire(3)
    led.release(s)
    with pytest.raises(MXNetError, match="double-free"):
        led.release(s)


def test_ledger_bounds():
    led = PageLedger(max_slots=1, max_seq=8, page=4)
    with pytest.raises(ValueError):
        led.acquire(0)
    with pytest.raises(ValueError):
        led.acquire(9)
    s = led.acquire(8)
    assert led.acquire(1) is None         # full -> None, not an error
    with pytest.raises(MXNetError, match="max_seq"):
        led.grow(s)
    with pytest.raises(MXNetError, match="non-resident"):
        led.grow(s + 1)
    with pytest.raises(ValueError):
        PageLedger(max_slots=2, max_seq=10, page=4)   # 4 does not divide 10


def test_ledger_property_randomized_interleavings():
    """THE allocator property: thousands of random acquire/grow/release
    steps against a parallel host model — the ledger never leaks, never
    double-frees, and its page accounting matches ceil(len/page) exactly
    after every single step."""
    rng = np.random.RandomState(7)
    for trial in range(20):
        max_slots = int(rng.randint(1, 9))
        page = int(rng.choice([2, 4, 8]))
        max_seq = page * int(rng.randint(1, 9))
        led = PageLedger(max_slots, max_seq, page)
        model = {}                        # slot -> length (the oracle)
        for _ in range(200):
            op = rng.randint(3)
            if op == 0:                   # join
                n = int(rng.randint(1, max_seq + 1))
                slot = led.acquire(n)
                if len(model) == max_slots:
                    assert slot is None
                else:
                    assert slot is not None and slot not in model
                    model[slot] = n
            elif op == 1 and model:       # decode one token somewhere
                slot = int(rng.choice(sorted(model)))
                if model[slot] >= max_seq:
                    with pytest.raises(MXNetError):
                        led.grow(slot)
                else:
                    model[slot] += 1
                    assert led.grow(slot) == model[slot]
            elif op == 2 and model:       # finish
                slot = int(rng.choice(sorted(model)))
                expect = -(-model.pop(slot) // page)
                assert led.release(slot) == max(1, expect)
            led.check()
            assert led.slots_in_use == len(model)
            assert led.pages_in_use == sum(
                max(1, -(-n // page)) for n in model.values())
        for slot in sorted(model):
            led.release(slot)
        led.check()
        assert led.pages_in_use == 0


# ------------------------------------------------- cache gauges + geometry

def test_cache_gauges_match_ledger_exactly():
    """The occupancy gauges the server exports ARE the host model —
    asserted equal after every mutation."""
    cache = KVCache(num_layers=1, n_heads=2, d_head=4, max_slots=3,
                    max_seq=8, page=4, int8=False, name="gaugetest")
    rng = np.random.RandomState(3)
    live = []
    for _ in range(60):
        if live and rng.rand() < 0.4:
            cache.release(live.pop(rng.randint(len(live))))
        else:
            s = cache.acquire(int(rng.randint(1, 9)))
            if s is None:
                if live:
                    cache.release(live.pop())
            else:
                live.append(s)
        assert profiler.get_gauge("gaugetest_kv_slots_in_use") == \
            cache.ledger.slots_in_use
        assert profiler.get_gauge("gaugetest_kv_pages_in_use") == \
            cache.ledger.pages_in_use
        assert abs(profiler.get_gauge("gaugetest_kv_occupancy")
                   - cache.ledger.occupancy()) < 1e-12
    for s in live:
        cache.release(s)


def test_max_slots_for_inverts_hbm_bytes():
    """Capacity planning consistency: a cache built with the slots
    max_slots_for admits must fit the budget, and one more slot must
    not."""
    for int8 in (False, True):
        geo = dict(num_layers=2, n_heads=2, d_head=8, max_seq=32, page=8)
        budget = 600_000
        slots = max_slots_for(budget, int8=int8, **geo)
        assert slots >= 1
        cache = KVCache(max_slots=slots, int8=int8, name="cap", **geo)
        assert cache.hbm_bytes() <= budget
        bigger = KVCache(max_slots=slots + 1, int8=int8, name="cap2", **geo)
        assert bigger.hbm_bytes() > budget


def test_int8_doubles_resident_sequences():
    """THE int8 acceptance: same budget, quantized KV admits at least
    2x the resident sequences (int8 payload is 4x smaller; the scale
    planes claw a little back)."""
    geo = dict(num_layers=2, n_heads=4, d_head=16, max_seq=64, page=16)
    budget = 4 * 1024 * 1024
    f32_slots = max_slots_for(budget, int8=False, **geo)
    i8_slots = max_slots_for(budget, int8=True, **geo)
    assert f32_slots >= 1
    assert i8_slots >= 2 * f32_slots


# ------------------------------------------------------------ budget audit

def test_audit_zero_cost_when_analyze_off(monkeypatch):
    import subprocess, sys
    code = (
        "import sys\n"
        "import mxnet_tpu  # noqa: F401\n"
        "from mxnet_tpu.serve.kv_cache import KVCache\n"
        "c = KVCache(1, 2, 4, 2, 8, page=4, int8=False, name='zc')\n"
        "out = c.audit()\n"
        "assert out['fits'] is True\n"
        "assert not any(m.startswith('mxnet_tpu.analysis')\n"
        "               for m in sys.modules), 'analysis imported'\n"
        "print('ZC-OK')\n")
    env = {"MXNET_TPU_ANALYZE": "off", "JAX_PLATFORMS": "cpu"}
    import os
    full = dict(os.environ); full.update(env)
    out = subprocess.run([sys.executable, "-c", code], env=full,
                         capture_output=True, text=True, timeout=240)
    assert "ZC-OK" in out.stdout, out.stdout + out.stderr


def test_audit_strict_rejects_naming_reservation(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_ANALYZE", "strict")
    monkeypatch.setenv("MXNET_TPU_ANALYZE_HBM_BUDGET", "1K")
    cfg.reset("MXNET_TPU_ANALYZE")
    cfg.reset("MXNET_TPU_ANALYZE_HBM_BUDGET")
    try:
        cache = KVCache(num_layers=2, n_heads=2, d_head=8, max_slots=4,
                        max_seq=32, page=8, int8=False, name="rej")
        with pytest.raises(MXNetError) as err:
            cache.audit()
        msg = str(err.value)
        assert "hbm-budget" in msg
        assert "rej_kv_cache" in msg          # the reservation is NAMED
        assert "MXNET_TPU_SERVE_KV_INT8" in msg   # and the remedy offered
    finally:
        monkeypatch.delenv("MXNET_TPU_ANALYZE")
        monkeypatch.delenv("MXNET_TPU_ANALYZE_HBM_BUDGET")
        cfg.reset("MXNET_TPU_ANALYZE")
        cfg.reset("MXNET_TPU_ANALYZE_HBM_BUDGET")


def test_audit_warn_fits_under_big_budget(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_ANALYZE", "warn")
    monkeypatch.setenv("MXNET_TPU_ANALYZE_HBM_BUDGET", "1G")
    cfg.reset("MXNET_TPU_ANALYZE")
    cfg.reset("MXNET_TPU_ANALYZE_HBM_BUDGET")
    try:
        cache = KVCache(num_layers=1, n_heads=2, d_head=4, max_slots=2,
                        max_seq=8, page=4, int8=False, name="fits")
        out = cache.audit()
        assert out["fits"] is True
        assert out["reserved_bytes"] == cache.hbm_bytes()
    finally:
        monkeypatch.delenv("MXNET_TPU_ANALYZE")
        monkeypatch.delenv("MXNET_TPU_ANALYZE_HBM_BUDGET")
        cfg.reset("MXNET_TPU_ANALYZE")
        cfg.reset("MXNET_TPU_ANALYZE_HBM_BUDGET")

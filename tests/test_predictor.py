"""Predictor — the predict-only deployment path (reference:
include/mxnet/c_predict_api.h MXPredCreate/SetInput/Forward/GetOutput,
SURVEY.md §2.19): a trained checkpoint must round-trip through the
minimal forward-only runtime and reproduce Module.predict outputs.
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def _train_small(tmp_path):
    rng = np.random.RandomState(0)
    x = rng.rand(64, 1, 8, 8).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) > x.mean()).astype(np.float32)
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4, name="cv")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc")
    sym = mx.sym.SoftmaxOutput(net, name="softmax")
    it = mx.io.NDArrayIter(x, y, batch_size=16, label_name="softmax_label")
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.init.Xavier())
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 2)
    it.reset()
    ref = mod.predict(it).asnumpy()
    return prefix, x, ref


def test_predictor_from_checkpoint_matches_module(tmp_path):
    prefix, x, ref = _train_small(tmp_path)
    pred = mx.Predictor.from_checkpoint(
        prefix, 2, input_shapes={"data": (16, 1, 8, 8)}, ctx=mx.cpu())
    outs = []
    for s in range(0, 64, 16):
        pred.forward(data=x[s:s + 16])
        outs.append(pred.get_output(0).asnumpy())
    np.testing.assert_allclose(np.concatenate(outs), ref,
                               rtol=1e-5, atol=1e-6)


def test_predictor_reshape_and_validation(tmp_path):
    prefix, x, ref = _train_small(tmp_path)
    pred = mx.Predictor.from_checkpoint(
        prefix, 2, input_shapes={"data": (16, 1, 8, 8)}, ctx=mx.cpu())
    with pytest.raises(ValueError):
        pred.set_input("data", x[:4])          # wrong batch for the bind
    with pytest.raises(KeyError):
        pred.set_input("nope", x[:16])
    pred.reshape({"data": (4, 1, 8, 8)})       # MXPredReshape parity
    pred.forward(data=x[:4])
    np.testing.assert_allclose(pred.get_output(0).asnumpy(), ref[:4],
                               rtol=1e-5, atol=1e-6)


def test_predictor_from_param_dict_and_json_string(tmp_path):
    prefix, x, ref = _train_small(tmp_path)
    with open(prefix + "-symbol.json") as f:
        sym_json = f.read()
    sym, arg_params, aux_params = mx.model.load_checkpoint(prefix, 2)
    params = {"arg:" + k: v for k, v in arg_params.items()}
    params.update({"aux:" + k: v for k, v in aux_params.items()})
    pred = mx.Predictor(sym_json, params,
                        input_shapes={"data": (16, 1, 8, 8)}, ctx=mx.cpu())
    pred.forward(data=x[:16])
    np.testing.assert_allclose(pred.get_output(0).asnumpy(), ref[:16],
                               rtol=1e-5, atol=1e-6)


def test_predictor_missing_param_raises(tmp_path):
    prefix, x, _ = _train_small(tmp_path)
    with open(prefix + "-symbol.json") as f:
        sym_json = f.read()
    with pytest.raises(ValueError):
        mx.Predictor(sym_json, {}, input_shapes={"data": (16, 1, 8, 8)})


def test_predictor_export_runs_without_framework(tmp_path):
    """Predictor.export -> StableHLO artifact executed by the standalone
    loader (tools/predict_exported.py, no mxnet_tpu import) with
    identical outputs — the amalgamation-deployment equivalent
    (reference: amalgamation/Makefile, c_predict_api.h:77-178)."""
    import subprocess
    import sys as _sys
    import os as _os
    from mxnet_tpu.models import lenet

    rng = np.random.RandomState(5)
    sym = lenet.get_symbol(num_classes=10)
    mod = mx.mod.Module(sym, context=mx.cpu(0))
    mod.bind(data_shapes=[("data", (2, 1, 28, 28))],
             label_shapes=[("softmax_label", (2,))], for_training=False)
    mod.init_params(mx.init.Xavier())
    args, auxs = mod.get_params()
    blob = {"arg:%s" % k: v for k, v in args.items()}
    blob.update({"aux:%s" % k: v for k, v in auxs.items()})
    pred = mx.predictor.Predictor(sym.tojson(), blob,
                                  {"data": (2, 1, 28, 28)}, ctx=mx.cpu(0))
    x = rng.rand(2, 1, 28, 28).astype(np.float32)
    ref = pred.forward(data=x)[0].asnumpy()

    art = str(tmp_path / "lenet.mxprog")
    pred.export(art)

    # in-process loader check (imports only jax + numpy)
    root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    _sys.path.insert(0, _os.path.join(root, "tools"))
    try:
        from predict_exported import load_artifact
    finally:
        _sys.path.pop(0)
    call, manifest = load_artifact(art)
    assert manifest["inputs"] == ["data"]
    out = call(data=x)[0]
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    # subprocess proof: the CLI runs from a neutral cwd with no repo on
    # sys.path — the artifact needs jax only, not the framework
    xp = str(tmp_path / "x.npy")
    np.save(xp, x)
    env = dict(_os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONPATH", None)
    r = subprocess.run(
        [_sys.executable, _os.path.join(root, "tools",
                                        "predict_exported.py"),
         art, "--input", "data=%s" % xp],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
        timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "output[0] shape=(2, 10)" in r.stdout

"""The example CLI trainers must run end-to-end (reference: the example/
scripts double as integration tests in the reference's CI)."""
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args, timeout=560):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the remote-TPU plugin rides PYTHONPATH (sitecustomize) and dials
    # its relay at interpreter start — a wedged tunnel then hangs every
    # subprocess before main() runs. The example tier is CPU-targeted,
    # so drop the plugin path entirely (scripts sys.path.insert the
    # repo root themselves).
    env["PYTHONPATH"] = ""
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    # share the suite's persistent compile cache (the config knob) so the
    # subprocess doesn't recompile everything under load
    env.setdefault("MXNET_COMPILATION_CACHE_DIR",
                   os.path.join(ROOT, "tests", ".jax_cache"))
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "examples", script)] + list(args),
        env=env, capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, "%s failed:\n%s\n%s" % (
        script, proc.stdout[-3000:], proc.stderr[-3000:])
    return proc.stdout


@pytest.mark.slow
def test_train_mnist_cli():
    out = _run("train_mnist.py", "--num-epochs", "2",
               "--num-examples", "600", "--batch-size", "50")
    assert "final validation accuracy" in out


@pytest.mark.slow
def test_train_mnist_record_pipeline():
    """fit convergence gated through the real RecordIO image pipeline
    (VERDICT weak #10)."""
    out = _run("train_mnist.py", "--num-epochs", "2",
               "--num-examples", "600", "--batch-size", "50", "--use-rec")
    assert "final validation accuracy" in out


@pytest.mark.slow
def test_lstm_bucketing_cli():
    out = _run("lstm_bucketing.py")
    assert "final validation perplexity" in out


@pytest.mark.slow
def test_model_parallel_lstm_cli():
    out = _run("model_parallel_lstm.py")
    assert "ok: nll" in out


@pytest.mark.slow
def test_gluon_mnist_cli():
    out = _run("gluon_mnist.py", "--num-epochs", "2",
               "--num-examples", "800", "--hybridize")
    assert "final validation accuracy" in out


@pytest.mark.nightly
def test_gluon_image_classification_cli():
    """Model-zoo net + Trainer + hybridize (reference
    example/gluon/image_classification.py parity)."""
    out = _run("gluon_image_classification.py", "--num-epochs", "10")
    assert "final train accuracy" in out


@pytest.mark.nightly
def test_word_language_model_cli():
    out = _run("word_language_model.py", "--num-epochs", "6")
    assert "final validation perplexity" in out


@pytest.mark.nightly
def test_train_ssd_cli():
    """SSD detection convergence gate (SURVEY §2.15 example/ssd parity):
    multi-scale heads + MultiBox ops must learn to localize."""
    out = _run("train_ssd.py", "--num-epochs", "35",
               "--num-examples", "256", "--batch-size", "32")
    assert "mean IoU" in out


@pytest.mark.nightly
def test_train_rcnn_cli():
    """Fast R-CNN-style ROI pipeline (reference example/rcnn parity):
    ROIPooling + an in-graph CustomOp proposal-target must learn."""
    out = _run("train_rcnn.py", "--num-epochs", "25",
               "--num-examples", "128")
    assert "final ROI classification accuracy" in out


@pytest.mark.slow
def test_benchmark_score_cli():
    """Inference perf-table script (reference benchmark_score.py parity)."""
    out = _run("benchmark_score.py", "--network", "lenet",
               "--batch-sizes", "4", "--iters", "3")
    assert "img/s" in out


@pytest.mark.slow
def test_fine_tune_cli():
    """Checkpoint -> new head -> frozen-backbone fine-tune (reference
    fine-tune.py parity: set_params(allow_missing) + fixed_param_names)."""
    out = _run("fine_tune.py")
    assert "fine-tuned" in out


@pytest.mark.nightly
def test_dcgan_cli():
    """Adversarial two-Trainer training (reference example/gluon/dcgan.py
    parity): D margin must grow, G statistics must move toward the data."""
    out = _run("dcgan.py", "--num-epochs", "4")
    assert "generated mean" in out


@pytest.mark.nightly
def test_train_cifar10_cli():
    """Color RecordIO + crop/mirror augmentation through the fit harness
    (reference train_cifar10.py parity, small-image resnet)."""
    out = _run("train_cifar10.py", "--num-epochs", "6",
               "--num-examples", "1200")
    assert "final validation accuracy" in out


@pytest.mark.slow
def test_pipeline_moe_transformer_cli():
    """Pipeline stages + MoE through the PipelineModule user surface
    (VERDICT r3 #4): perplexity must fall on the cyclic corpus."""
    out = _run("pipeline_moe_transformer.py", "--stages", "2",
               "--experts", "4", "--num-epochs", "2", "--num-batches",
               "10", "--d-model", "32", "--seq-len", "16")
    assert "final-ppl=" in out


@pytest.mark.slow
def test_pipeline_transformer_1f1b_hetero_cli():
    """1F1B schedule + unequal per-stage FFN widths (heterogeneous
    pipeline, VERDICT r4 #3) through the same CLI."""
    out = _run("pipeline_moe_transformer.py", "--stages", "2",
               "--experts", "0", "--schedule", "1f1b",
               "--ffn-widths", "128,64", "--num-epochs", "2",
               "--num-batches", "10", "--d-model", "32",
               "--seq-len", "16")
    assert "final-ppl=" in out


@pytest.mark.slow
def test_super_resolution_cli():
    """ESPCN-style sub-pixel upscaling (reference
    example/gluon/super_resolution.py parity): PSNR must beat nearest."""
    out = _run("super_resolution.py", "--num-epochs", "14",
               "--num-examples", "60")
    assert "PSNR" in out


@pytest.mark.nightly
def test_actor_critic_cli():
    """Actor-critic RL (reference example/gluon/actor_critic.py parity):
    mean episode length must grow 1.5x over training."""
    out = _run("actor_critic.py", "--num-episodes", "120")
    assert "mean episode length" in out


@pytest.mark.slow
def test_cnn_text_classification_cli():
    """Kim-CNN over parallel conv widths + max-over-time pooling
    (reference example/cnn_text_classification parity)."""
    out = _run("cnn_text_classification.py", "--num-epochs", "5",
               "--num-examples", "900")
    assert "final validation accuracy" in out


@pytest.mark.slow
def test_autoencoder_cli():
    """Greedy layer-wise pretrain + fine-tune stacked AE (reference
    example/autoencoder parity)."""
    out = _run("autoencoder.py", "--num-epochs", "8",
               "--pretrain-epochs", "3", "--num-examples", "1000")
    assert "val mse" in out


@pytest.mark.slow
def test_bi_lstm_sort_cli():
    """BidirectionalCell LSTM learns to sort (reference
    example/bi-lstm-sort parity)."""
    out = _run("bi_lstm_sort.py", "--num-epochs", "6",
               "--num-examples", "900")
    assert "per-position sort accuracy" in out


@pytest.mark.slow
def test_lstm_crf_cli():
    """BiLSTM-CRF: dynamic-programming loss (forward algorithm) +
    Viterbi decode; the transition matrix must learn the tag grammar."""
    out = _run("lstm_crf.py", "--num-epochs", "6", "--num-examples",
               "200")
    assert "tag accuracy" in out


@pytest.mark.slow
def test_neural_style_cli():
    """Gradient-wrt-input optimization (Gatys-style): Gram statistics
    must move to the style target while content survives."""
    out = _run("neural_style.py", "--num-steps", "120")
    assert "style loss" in out


@pytest.mark.nightly
@pytest.mark.slow
def test_dqn_cli():
    """DQN: replay buffer + frozen target network + epsilon decay on
    cart-pole; greedy eval must beat random by >2.5x."""
    out = _run("dqn.py", "--num-episodes", "80")
    assert "greedy eval" in out


@pytest.mark.nightly
@pytest.mark.slow
def test_tree_lstm_cli():
    """Child-sum Tree-LSTM: recursive composition over expression trees
    with topology-bucketed batching; must beat the bag-of-leaves
    baseline decisively."""
    out = _run("tree_lstm.py")
    assert "eval accuracy" in out


@pytest.mark.slow
def test_train_imagenet_benchmark_cli():
    """The BASELINE north-star CLI (reference train_imagenet.py flag
    surface) in synthetic --benchmark mode: must train to memorization
    on the fixed synthetic batch."""
    out = _run("train_imagenet.py", "--network", "resnet",
               "--num-layers", "18", "--benchmark", "1",
               "--num-classes", "10", "--image-shape", "3,64,64",
               "--num-epochs", "3", "--batch-size", "32",
               "--num-examples", "256", "--lr", "0.05",
               "--lr-step-epochs", "")
    assert "final validation accuracy" in out


@pytest.mark.slow
def test_train_imagenet_recordio_cli(tmp_path):
    """The same CLI over a real RecordIO file (the reference's data
    path): pack synthetic images with the recordio codec, train, and
    assert the accuracy line prints."""
    import numpy as np
    import cv2
    sys.path.insert(0, os.path.join(ROOT))
    from mxnet_tpu import recordio

    rng = np.random.RandomState(0)
    n, size = 192, 64
    y = rng.randint(0, 4, n)
    x = rng.rand(n, size, size, 3).astype(np.float32) * 0.2
    for c in range(4):
        x[y == c, :, :, c % 3] += 0.6
    for split, idx in (("train", slice(0, 160)), ("val", slice(160, n))):
        rec = recordio.MXRecordIO(str(tmp_path / (split + ".rec")), "w")
        xs, ys = x[idx], y[idx]
        for i in range(xs.shape[0]):
            ok, enc = cv2.imencode(
                ".png", (xs[i][:, :, ::-1] * 255).astype(np.uint8))
            rec.write(recordio.pack(
                recordio.IRHeader(0, float(ys[i]), i, 0), enc.tobytes()))
        rec.close()
    out = _run("train_imagenet.py", "--network", "resnet",
               "--num-layers", "18",
               "--data-train", str(tmp_path / "train.rec"),
               "--data-val", str(tmp_path / "val.rec"),
               "--image-shape", "3,56,56", "--num-classes", "4",
               "--num-epochs", "2", "--batch-size", "32",
               "--num-examples", "160", "--lr", "0.05",
               "--lr-step-epochs", "", "--rgb-mean", "0,0,0")
    assert "final validation accuracy" in out


@pytest.mark.slow
def test_adversary_fgsm_cli():
    """FGSM attack (reference example/adversary): gradient wrt input of
    a TRAINED model collapses its accuracy within an Linf budget."""
    out = _run("adversary_fgsm.py")
    assert "FGSM" in out


@pytest.mark.slow
def test_ctc_ocr_cli():
    """CTC over unsegmented digit strips (reference example/ctc +
    warpctc): alignment-free sequence learning + greedy decode."""
    out = _run("ctc_ocr.py")
    assert "sequence accuracy" in out


@pytest.mark.slow
def test_svm_mnist_cli():
    """SVMOutput margin heads (reference example/svm_mnist): both SVM
    variants and softmax clear the bar on the same features."""
    out = _run("svm_mnist.py")
    assert "l2-svm" in out


@pytest.mark.slow
def test_multi_task_cli():
    """Two loss heads on one backbone with two bound labels (reference
    example/multi-task); must beat split-budget single-task models."""
    out = _run("multi_task.py")
    assert "multi-task" in out

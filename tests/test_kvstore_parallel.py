"""KVStore semantics + mesh parallelism tests.

Reference test model: tests/python/unittest/test_kvstore.py (local
aggregation math) and tests/nightly/dist_sync_kvstore.py (pushed value *
num_devices); multi-device on the virtual 8-CPU mesh per SURVEY.md §4.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal

shape = (4, 4)


def test_kvstore_init_pull():
    kv = mx.kv.create("local")
    kv.init(3, mx.nd.ones(shape))
    out = mx.nd.zeros(shape)
    kv.pull(3, out=out)
    assert_almost_equal(out, np.ones(shape))


def test_kvstore_push_aggregation():
    # reference semantics: push of N device-values aggregates their sum
    # (tests/python/unittest/test_kvstore.py test_single_kv_pair/list)
    kv = mx.kv.create("local")
    kv.init(3, mx.nd.zeros(shape))
    devs = [mx.cpu(i) for i in range(4)]
    vals = [mx.nd.ones(shape, ctx=d) for d in devs]
    kv.push(3, vals)
    out = mx.nd.zeros(shape)
    kv.pull(3, out=out)
    assert_almost_equal(out, 4 * np.ones(shape))


def test_kvstore_updater():
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.ones(shape))

    def updater(key, grad, weight):
        weight -= 0.1 * grad

    kv.set_updater(updater)
    kv.push("w", [mx.nd.ones(shape)] * 2)   # merged grad = 2
    out = mx.nd.zeros(shape)
    kv.pull("w", out=out)
    assert_almost_equal(out, np.ones(shape) - 0.2, rtol=1e-5, atol=1e-6)


def test_kvstore_list_keys():
    kv = mx.kv.create("device")
    keys = [5, 7, 9]
    kv.init(keys, [mx.nd.ones(shape)] * 3)
    kv.push(keys, [[mx.nd.ones(shape)] * 2] * 3)
    outs = [mx.nd.zeros(shape) for _ in keys]
    kv.pull(keys, out=outs)
    for o in outs:
        assert_almost_equal(o, 3 * np.ones(shape))


def test_kvstore_optimizer_states(tmp_path):
    kv = mx.kv.create("local")
    kv.init(0, mx.nd.ones((2,)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9))
    kv.push(0, mx.nd.ones((2,)))
    f = str(tmp_path / "states")
    kv.save_optimizer_states(f)
    kv.load_optimizer_states(f)
    assert kv.rank == 0
    assert kv.num_workers == 1


# ---------------------------------------------------------------- mesh


def test_make_mesh_shapes():
    mesh = mx.parallel.make_mesh({"data": 4, "model": 2})
    assert mesh.shape == {"data": 4, "model": 2}
    mesh2 = mx.parallel.make_mesh({"data": -1})
    assert mesh2.shape["data"] == len(mx.parallel.mesh_devices())


def test_data_parallel_grad_matches_single_device():
    """8-way data-parallel gradient == single-device gradient (SPMD psum
    inserted by XLA; the capability the reference gets from
    DataParallelExecutorGroup + KVStore)."""
    np.random.seed(0)
    w = np.random.randn(6, 3).astype(np.float32)
    x = np.random.randn(16, 6).astype(np.float32)
    y = np.random.randn(16, 3).astype(np.float32)

    def loss(w, x, y):
        return jnp.mean((x @ w - y) ** 2)

    g_single = jax.grad(loss)(w, x, y)

    mesh = mx.parallel.make_mesh({"data": 8})
    xs = mx.parallel.shard_batch(mesh, x)
    ys = mx.parallel.shard_batch(mesh, y)
    wr = mx.parallel.replicate(mesh, w)
    g_sharded = jax.jit(jax.grad(loss))(wr, xs, ys)
    # fp32 reduction order differs between one-device sum and 8-way psum
    assert_almost_equal(np.asarray(g_sharded), np.asarray(g_single),
                        rtol=1e-2, atol=1e-4)


def test_ring_attention_matches_full():
    np.random.seed(1)
    B, H, S, D = 2, 2, 16, 8
    q = np.random.randn(B, H, S, D).astype(np.float32)
    k = np.random.randn(B, H, S, D).astype(np.float32)
    v = np.random.randn(B, H, S, D).astype(np.float32)

    def full_attn(q, k, v, causal):
        s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        if causal:
            mask = np.tril(np.ones((S, S), bool))
            s = np.where(mask, s, -np.inf)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        return np.einsum("bhqk,bhkd->bhqd", p, v)

    mesh = mx.parallel.make_mesh({"sp": 8})
    for causal in (False, True):
        out = mx.parallel.ring_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh,
            axis_name="sp", causal=causal)
        assert_almost_equal(np.asarray(out), full_attn(q, k, v, causal),
                            rtol=1e-4, atol=1e-5)


def test_ring_attention_gradient_flows():
    B, H, S, D = 1, 1, 8, 4
    mesh = mx.parallel.make_mesh({"sp": 4})
    q = jnp.asarray(np.random.randn(B, H, S, D).astype(np.float32))

    def f(q):
        return jnp.sum(mx.parallel.ring_attention(q, q, q, mesh,
                                                  axis_name="sp"))

    g = jax.grad(f)(q)
    assert np.isfinite(np.asarray(g)).all()
    assert np.abs(np.asarray(g)).sum() > 0

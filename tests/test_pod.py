"""Multi-host pod runtime (ISSUE 11): bounded bootstrap, heartbeat
liveness, pod rendezvous, process-local checkpoints, and the obs
process_index labels.

Five contracts under test:

* **bootstrap** — ``dist.initialize`` can never hang: the roll-call
  fails with :class:`BootstrapTimeout` NAMING the absent rank (both on
  the coordinator and on a peer that cannot reach it), and the full
  subprocess bootstrap with a missing peer exits nonzero within a hard
  deadline.
* **liveness** — ``heartbeat_start``/``dead_ranks``/``num_dead_nodes``:
  deadline expiry on a frozen counter, recovery after the counter
  advances again (rejoin), and the progress-coupled publisher.
* **rendezvous** — the PodCoordinator membership protocol over a fake
  control plane: generation 0 requires every rank, later generations
  exclude dead ranks, an evicted rank learns it.
* **process-local checkpoints** — per-rank file tagging, legible
  mixed-world rejection (the stale host is NAMED), partial-save
  fallback, pod tmp reaping.
* **kvstore-resume** — a fit whose optimizer state lives on the
  kvstore (update_on_kvstore) checkpoints and resumes bit-identically
  — the path every pod child uses.

The end-to-end drills (2-host host.die sigkill + wedge + child-kill,
3-host leader-kill / cascade / coordsvc fail-over, the mid-save
leader-death matrix — all bit-identical params) are tools/pod_smoke.py,
run by the slow test at the bottom and the CI ``multihost`` job; the
fail-over unit contracts (probe ring, adjudication, election,
successor finalize) are tests/test_failover.py.
"""
import json
import os
import subprocess
import sys
import zlib

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, profiler
from mxnet_tpu.parallel import dist
from mxnet_tpu.checkpoint import (CheckpointCorrupt, list_checkpoints,
                                  load_latest, pod_info, probe_valid,
                                  read_checkpoint, write_checkpoint)
from mxnet_tpu.checkpoint import format as ckpt_format

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_liveness():
    dist.reset_liveness()
    yield
    dist.reset_liveness()
    faults.clear()


def _free_port():
    return dist.free_port()


# ------------------------------------------------------------- bootstrap

def test_rollcall_coordinator_names_absent_rank():
    """Rank 0 of a 2-world whose peer never shows: the error must name
    rank 1 — never a hang, never N-1 opaque deadline errors."""
    with pytest.raises(dist.BootstrapTimeout, match=r"rank\(s\) 1"):
        dist._rollcall("127.0.0.1:%d" % _free_port(), 2, 0, deadline=1.5)


def test_rollcall_peer_names_unreachable_coordinator():
    with pytest.raises(dist.BootstrapTimeout, match="rank 0"):
        dist._rollcall("127.0.0.1:%d" % _free_port(), 2, 1, deadline=1.0)


def test_rollcall_completes_when_all_ranks_present():
    import threading
    port = _free_port()
    addr = "127.0.0.1:%d" % port
    errs = []

    def peer():
        try:
            dist._rollcall(addr, 2, 1, deadline=10.0)
        except Exception as exc:                           # noqa: BLE001
            errs.append(exc)

    t = threading.Thread(target=peer)
    t.start()
    dist._rollcall(addr, 2, 0, deadline=10.0)
    t.join(10.0)
    assert not errs, errs


def test_bootstrap_missing_peer_times_out_legibly(tmp_path):
    """The acceptance regression: a 3-world pod bootstrap with rank 2
    absent must FAIL (named, nonzero) well inside the subprocess
    timeout on every present rank — never hang the pod."""
    port = _free_port()
    child = (
        "import os, sys; sys.path.insert(0, %r); "
        "os.environ['JAX_PLATFORMS'] = 'cpu'; "
        "from mxnet_tpu.parallel import dist; "
        "dist.initialize('127.0.0.1:%d', 3, int(sys.argv[1]), "
        "timeout=6, retries=0)" % (REPO, port))
    procs = [subprocess.Popen(
        [sys.executable, "-c", child, str(r)],
        env={**os.environ, "PYTHONPATH": ""},
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for r in range(2)]                    # rank 2 never launches
    outs = [p.communicate(timeout=120) for p in procs]
    assert procs[0].returncode != 0
    assert procs[1].returncode != 0
    assert "rank(s) 2" in outs[0][1], outs[0][1][-2000:]


def test_bootstrap_retries_cover_rollcall(monkeypatch):
    """Regression (review finding): MXNET_TPU_DIST_RETRIES promises a
    slow-starting peer one more window — and the stage a slow peer
    actually fails at is the roll-call, so the roll-call must sit
    INSIDE the retried window. The final error still names the rank."""
    calls = []

    def fake_rollcall(addr, n, pid, deadline):
        calls.append(1)
        raise dist.BootstrapTimeout(
            "pod bootstrap timed out: rank(s) 1 of world 2 never "
            "connected")

    monkeypatch.setattr(dist, "_rollcall", fake_rollcall)
    import jax._src.xla_bridge as xb
    monkeypatch.setattr(xb, "backends_are_initialized", lambda: False)
    with pytest.raises(dist.BootstrapTimeout,
                       match=r"2 attempt\(s\).*rank\(s\) 1"):
        dist.initialize("127.0.0.1:1", 2, 0, timeout=1, retries=1)
    assert len(calls) == 2
    assert not dist.is_initialized()


# -------------------------------------------------------------- liveness

class _FakeClient(object):
    """Coordination-service KV double for liveness tests."""

    def __init__(self):
        self.store = {}

    def key_value_set(self, key, value, allow_overwrite=False):
        self.store[key] = value

    def key_value_delete(self, key):
        self.store.pop(key, None)

    def blocking_key_value_get(self, key, timeout_ms):
        if key not in self.store:
            raise KeyError(key)
        return self.store[key]


@pytest.fixture()
def fake_pod(monkeypatch):
    """A fake 2-worker coordination client wired into dist."""
    client = _FakeClient()
    monkeypatch.setattr(dist, "_client", lambda: client)
    monkeypatch.setattr(dist, "num_workers", lambda: 2)
    monkeypatch.setattr(dist, "rank", lambda: 0)
    return client


def test_dead_ranks_missing_heartbeat_counts_dead(fake_pod):
    fake_pod.store["mxnet_hb/0"] = "5"
    assert dist.dead_ranks(stale_after=1.0, timeout_ms=10) == [1]
    assert dist.num_dead_nodes(stale_after=1.0, timeout_ms=10) == 1


def test_dead_ranks_deadline_expiry_and_recovery(fake_pod, monkeypatch):
    """The satellite contract: a frozen beat counter is dead only after
    the staleness deadline (two observations), and a rank whose counter
    advances again — a rejoin — is live immediately."""
    now = [100.0]
    monkeypatch.setattr("time.monotonic", lambda: now[0])
    fake_pod.store["mxnet_hb/0"] = "7"
    fake_pod.store["mxnet_hb/1"] = "3"
    # first observation never declares staleness
    assert dist.dead_ranks(stale_after=5.0, timeout_ms=10) == []
    now[0] += 4.0            # within the deadline: still live
    assert dist.dead_ranks(stale_after=5.0, timeout_ms=10) == []
    now[0] += 2.0            # rank 1 frozen past 5s: dead
    fake_pod.store["mxnet_hb/0"] = "8"
    assert dist.dead_ranks(stale_after=5.0, timeout_ms=10) == [1]
    # rejoin: the counter advances -> recovered at once
    fake_pod.store["mxnet_hb/1"] = "4"
    assert dist.dead_ranks(stale_after=5.0, timeout_ms=10) == []
    # and freezes again -> dead again after another full window (rank
    # 0 keeps beating, or it would be judged dead right along)
    now[0] += 6.0
    fake_pod.store["mxnet_hb/0"] = "9"
    assert dist.dead_ranks(stale_after=5.0, timeout_ms=10) == [1]


def test_heartbeat_publisher_and_progress_coupling(fake_pod):
    import time as _time
    token = ["a"]
    assert dist.heartbeat_start(period=0.02,
                                progress_fn=lambda: token[0])
    try:
        deadline = _time.monotonic() + 5.0
        while "mxnet_hb/0" not in fake_pod.store:
            assert _time.monotonic() < deadline
            _time.sleep(0.01)
        first = int(fake_pod.store["mxnet_hb/0"])
        _time.sleep(0.2)     # no progress: the counter must not advance
        assert int(fake_pod.store["mxnet_hb/0"]) == first
        token[0] = "b"       # progress: the counter advances
        deadline = _time.monotonic() + 5.0
        while int(fake_pod.store["mxnet_hb/0"]) == first:
            assert _time.monotonic() < deadline
            _time.sleep(0.01)
    finally:
        dist.heartbeat_stop()


def test_heartbeat_plain_beat_advances(fake_pod):
    import time as _time
    assert dist.heartbeat_start(period=0.02)
    try:
        deadline = _time.monotonic() + 5.0
        while int(fake_pod.store.get("mxnet_hb/0", 0)) < 3:
            assert _time.monotonic() < deadline
            _time.sleep(0.01)
    finally:
        dist.heartbeat_stop()


# ------------------------------------------------------------ rendezvous

@pytest.fixture()
def fake_control(monkeypatch):
    """Fake control plane for PodCoordinator._rendezvous: an in-memory
    KV plus an injectable dead set."""
    store = {}
    dead = []
    monkeypatch.setattr(dist, "kv_set",
                        lambda k, v: store.__setitem__(k, v))
    monkeypatch.setattr(dist, "kv_get",
                        lambda k, timeout_ms: store.get(k))
    monkeypatch.setattr(dist, "dead_ranks",
                        lambda **kw: list(dead))
    return store, dead


def _coordinator(monkeypatch, rank, world):
    from mxnet_tpu.elastic import PodCoordinator
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", "9999")
    monkeypatch.setenv("DMLC_NUM_WORKER", str(world))
    monkeypatch.setenv("DMLC_WORKER_ID", str(rank))
    return PodCoordinator(["true"], stale_after=0.5,
                          rendezvous_window=0.5)


def test_rendezvous_gen0_collects_every_rank(monkeypatch, fake_control):
    store, _dead = fake_control
    coord = _coordinator(monkeypatch, 0, 3)
    store["mxpod/g0/join/1"] = json.dumps({"host": "h1"})
    store["mxpod/g0/join/2"] = json.dumps({"host": "h2"})
    rec = coord._rendezvous(0)
    assert rec["ranks"] == [0, 1, 2]
    assert rec["leader"] == 0
    assert rec["coordinator"].startswith("127.0.0.1:")
    assert json.loads(store["mxpod/g0/members"]) == rec


def test_rendezvous_gen0_missing_rank_raises_legibly(monkeypatch,
                                                     fake_control):
    store, _dead = fake_control
    coord = _coordinator(monkeypatch, 0, 3)
    coord.bootstrap_timeout = 0.5
    store["mxpod/g0/join/1"] = json.dumps({"host": "h1"})
    with pytest.raises(RuntimeError, match="rank 2"):
        coord._rendezvous(0)


def test_rendezvous_later_gen_excludes_dead_ranks(monkeypatch,
                                                  fake_control):
    store, dead = fake_control
    coord = _coordinator(monkeypatch, 0, 3)
    dead.append(2)
    store["mxpod/g1/join/1"] = json.dumps({"host": "h1"})
    rec = coord._rendezvous(1)
    assert rec["ranks"] == [0, 1]


def test_rendezvous_follower_reads_membership_and_eviction(monkeypatch,
                                                           fake_control):
    store, _dead = fake_control
    coord = _coordinator(monkeypatch, 2, 3)
    store["mxpod/g1/members"] = json.dumps(
        {"gen": 1, "ranks": [0, 2], "leader": 0,
         "coordinator": "127.0.0.1:1234"})
    rec = coord._rendezvous(1)
    assert rec["ranks"] == [0, 2]
    env = coord._child_env(1, rec)
    assert env["DMLC_NUM_WORKER"] == "2"
    assert env["DMLC_WORKER_ID"] == "1"     # rank 2 is member index 1
    assert env["MXNET_TPU_POD_GEN"] == "1"
    assert env["MXNET_TPU_ELASTIC_COORDINATED"] == "1"
    # evicted: the membership omits us
    store["mxpod/g2/members"] = json.dumps(
        {"gen": 2, "ranks": [0], "leader": 0,
         "coordinator": "127.0.0.1:1235"})
    assert coord._rendezvous(2) is None


# ----------------------------------------- process-local checkpoint files

def _crc(arr):
    return zlib.crc32(memoryview(np.ascontiguousarray(arr)).cast("B")) \
        & 0xFFFFFFFF


def _write_pod_style(base, step, world, writers_arrays, meta=None):
    """Hand-build a pod-format checkpoint dir (the unit-level twin of
    what _write_checkpoint_pod commits)."""
    d = os.path.join(base, "ckpt-%010d" % step)
    os.makedirs(d)
    arrays = {}
    files = {}
    writers = {}
    tensors = {}
    for rank, tensor_map in writers_arrays.items():
        fname = "arrays-p%d.npz" % rank
        payload = {}
        for name, (val, window, shape) in tensor_map.items():
            key = "%s@p%d.s0" % (name, rank)
            payload[key] = val
            arrays[key] = {"shape": list(val.shape),
                           "dtype": str(val.dtype), "crc32": _crc(val),
                           "nbytes": int(val.nbytes), "file": fname,
                           "process_index": rank}
            entry = tensors.setdefault(
                name, {"kind": "sharded", "shape": list(shape),
                       "dtype": str(val.dtype), "mesh": {"data": world},
                       "spec": "('data',)", "shards": []})
            entry["shards"].append({"key": key, "index": window,
                                    "process_index": rank})
        with open(os.path.join(d, fname), "wb") as f:
            np.savez(f, **payload)
        files[fname] = os.path.getsize(os.path.join(d, fname))
        writers[str(rank)] = fname
    manifest = {"format": ckpt_format.FORMAT_VERSION, "step": step,
                "world_size": world, "writers": writers,
                "arrays": arrays, "tensors": tensors, "files": files,
                "meta": meta or {}}
    with open(os.path.join(d, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    return d, manifest


def test_pod_checkpoint_reassembles_across_files(tmp_path):
    a = np.arange(8, dtype=np.float32).reshape(2, 4)
    d, _m = _write_pod_style(
        str(tmp_path), 1, 2,
        {0: {"w": (a[:1], [[0, 1], None], (2, 4))},
         1: {"w": (a[1:], [[1, 2], None], (2, 4))}})
    assert probe_valid(d)
    tensors, man = read_checkpoint(d)
    np.testing.assert_array_equal(tensors["w"], a)
    assert man["world_size"] == 2


def test_mixed_world_save_rejected_naming_stale_host(tmp_path):
    """The satellite contract: a manifest committing world 1 that still
    carries a process-2 shard file is rejected AS A UNIT with the stale
    host named — not a crc-by-crc failure hunt — and load_latest falls
    back to the previous complete checkpoint."""
    good = np.full((2, 4), 7.0, np.float32)
    _write_pod_style(str(tmp_path), 1, 2,
                     {0: {"w": (good[:1], [[0, 1], None], (2, 4))},
                      1: {"w": (good[1:], [[1, 2], None], (2, 4))}})
    d2, man = _write_pod_style(
        str(tmp_path), 2, 2,
        {0: {"w": (good[:1], [[0, 1], None], (2, 4))},
         2: {"w": (good[1:], [[1, 2], None], (2, 4))}})
    man["world_size"] = 2            # commit says world 2, writer is p2
    with open(os.path.join(d2, "manifest.json"), "w") as f:
        json.dump(man, f)
    with pytest.raises(CheckpointCorrupt,
                       match=r"process 2.*world_size=2.*stale host"):
        read_checkpoint(d2)
    path, tensors, _m = load_latest(str(tmp_path))
    assert path.endswith("ckpt-0000000001")
    np.testing.assert_array_equal(tensors["w"], good)


def test_pod_checkpoint_missing_host_file_fails_probe(tmp_path):
    """A partial pod save (one host's file missing) never validates:
    probe_valid is False and read_checkpoint rejects it, so load_latest
    falls back."""
    a = np.ones((2, 4), np.float32)
    _write_pod_style(str(tmp_path), 1, 2,
                     {0: {"w": (a[:1], [[0, 1], None], (2, 4))},
                      1: {"w": (a[1:], [[1, 2], None], (2, 4))}})
    d2, _m = _write_pod_style(
        str(tmp_path), 2, 2,
        {0: {"w": (a[:1], [[0, 1], None], (2, 4))},
         1: {"w": (a[1:], [[1, 2], None], (2, 4))}})
    os.unlink(os.path.join(d2, "arrays-p1.npz"))
    assert not probe_valid(d2)
    with pytest.raises(CheckpointCorrupt):
        read_checkpoint(d2)
    path, _t, _m2 = load_latest(str(tmp_path))
    assert path.endswith("ckpt-0000000001")


def _fake_ckpt_kv(monkeypatch):
    store = {}
    monkeypatch.setattr(dist, "kv_set",
                        lambda k, v: store.__setitem__(k, v))
    monkeypatch.setattr(dist, "kv_get",
                        lambda k, timeout_ms: store.get(k))
    return store


def _peer_record_and_file(staging, w_full):
    """Stage tensor ``w`` the way a live peer that owns all its index
    windows would (rank 0 contributes no ``w`` shard here)."""
    os.makedirs(staging, exist_ok=True)
    fpath = os.path.join(staging, "arrays-p1.npz")
    with open(fpath, "wb") as f:
        np.savez(f, **{"w@p1.s0": w_full})
    return {
        "file": "arrays-p1.npz", "process_index": 1, "world_size": 2,
        "size": os.path.getsize(fpath),
        "arrays": {"w@p1.s0": {"shape": list(w_full.shape),
                               "dtype": str(w_full.dtype),
                               "crc32": _crc(w_full),
                               "nbytes": int(w_full.nbytes)}},
        "tensors": {"w": {"kind": "sharded",
                          "shape": list(w_full.shape),
                          "dtype": str(w_full.dtype),
                          "mesh": {"data": 2}, "spec": "('data',)",
                          "shards": [{"key": "w@p1.s0",
                                      "index": [None, None],
                                      "process_index": 1}]}},
    }


def test_pod_write_retry_preserves_peer_files(tmp_path, monkeypatch):
    """Regression (review finding): a transient IO error on rank 0
    must NOT delete the shared staging dir — the peer stays blocked on
    the commit key and never rewrites its shard file, so a retry that
    had wiped it would commit a manifest referencing a vanished file.
    The retry must instead reuse the staging dir and commit a FULLY
    LOADABLE checkpoint."""
    store = _fake_ckpt_kv(monkeypatch)
    w = np.arange(8, dtype=np.float32).reshape(2, 4)
    staging = str(tmp_path / ".tmp-ckpt-0000000003.pod.g0")
    rec = _peer_record_and_file(staging, w)
    store["mxnet_ckpt/g0/s0000000003/p1"] = json.dumps(rec)
    monkeypatch.setenv("MXNET_TPU_CKPT_POD_TIMEOUT", "2")
    tensors = {"w0_full": w[:1]}     # rank 0's own (full) tensor
    faults.install("ckpt.arrays_write@1:eio")
    with pytest.raises(OSError):
        ckpt_format._write_checkpoint_pod(str(tmp_path), 3, tensors,
                                          None, rank=0, world=2)
    # the peer's file survived the failed attempt
    assert os.path.exists(os.path.join(staging, "arrays-p1.npz"))
    # the retry (same staging dir, peer record still cached) commits
    path = ckpt_format._write_checkpoint_pod(str(tmp_path), 3, tensors,
                                             None, rank=0, world=2)
    assert probe_valid(path)
    loaded, man = read_checkpoint(path)
    np.testing.assert_array_equal(loaded["w"], w)
    np.testing.assert_array_equal(loaded["w0_full"], w[:1])
    assert man["world_size"] == 2


def test_pod_commit_audits_staged_files(tmp_path, monkeypatch):
    """Rank 0 must refuse to commit when a record's file is missing or
    the wrong size on disk — a 'successful' save that cannot load is
    worse than an aborted one."""
    from mxnet_tpu.checkpoint import CheckpointPodError
    store = _fake_ckpt_kv(monkeypatch)
    w = np.arange(8, dtype=np.float32).reshape(2, 4)
    staging = str(tmp_path / ".tmp-ckpt-0000000004.pod.g0")
    rec = _peer_record_and_file(staging, w[1:])
    os.unlink(os.path.join(staging, "arrays-p1.npz"))   # file vanished
    store["mxnet_ckpt/g0/s0000000004/p1"] = json.dumps(rec)
    monkeypatch.setenv("MXNET_TPU_CKPT_POD_TIMEOUT", "2")
    with pytest.raises(CheckpointPodError, match="vanished"):
        ckpt_format._write_checkpoint_pod(str(tmp_path), 4,
                                          {"v": w[:1]}, None,
                                          rank=0, world=2)
    assert not list_checkpoints(str(tmp_path))


def test_monitor_terminated_delivers_preemption_notice(tmp_path,
                                                       monkeypatch):
    """Regression (review finding): the terminated branch must SIGTERM
    the child ITSELF — the signal forwarder only reaches whatever child
    existed at signal time, and a child spawned just after would
    otherwise be hard-killed without its preemption save."""
    monkeypatch.setattr(dist, "reset_liveness", lambda: None)
    monkeypatch.setattr(dist, "kv_set", lambda k, v: None)
    monkeypatch.setattr(dist, "kv_get", lambda k, timeout_ms: None)
    monkeypatch.setattr(dist, "dead_ranks", lambda **kw: [])
    coord = _coordinator(monkeypatch, 0, 2)
    coord.drain_grace = 10.0
    child = subprocess.Popen([sys.executable, "-c", (
        "import signal, sys, time\n"
        "signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))\n"
        "print('up', flush=True)\n"
        "time.sleep(60)\n")], stdout=subprocess.PIPE)
    child.stdout.readline()            # child is up, handler installed
    coord._child = child
    coord._gen = 0
    coord._terminated = True           # SIGTERM landed before the spawn
    assert coord._monitor([0, 1]) == "terminated"
    assert child.returncode == 143     # notice delivered, clean save rc


def test_monitor_control_plane_loss_is_not_self_death(tmp_path,
                                                      monkeypatch):
    """When the control plane is unreachable, dead_ranks reports EVERY
    rank — including the caller. The monitor adjudicates over the probe
    ring (ISSUE 12): here the peer is UNREACHABLE (no probe info — a
    partition and a dead host look identical), so this side is a
    1-of-2 minority and must end the pod with an rc for a JOB restart —
    never SELF_DEAD_RC (nothing says this machine is broken), and never
    a fail-over (a split-brain election from the minority side). The
    majority/fail-over sides live in tests/test_failover.py."""
    monkeypatch.setattr(dist, "reset_liveness", lambda: None)
    monkeypatch.setattr(dist, "kv_set", lambda k, v: None)
    monkeypatch.setattr(dist, "kv_get", lambda k, timeout_ms: None)
    monkeypatch.setattr(dist, "dead_ranks", lambda **kw: [0, 1])
    coord = _coordinator(monkeypatch, 1, 2)
    coord.drain_grace = 5.0
    child = subprocess.Popen([sys.executable, "-c", (
        "import signal, sys, time\n"
        "signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))\n"
        "print('up', flush=True)\n"
        "time.sleep(60)\n")], stdout=subprocess.PIPE)
    child.stdout.readline()
    coord._child = child
    coord._gen = 0
    assert coord._monitor([0, 1]) == "control-plane-lost"
    assert child.returncode == 143     # drained with the notice


def test_pod_tmp_residue_reaped_by_gc(tmp_path):
    write_checkpoint(str(tmp_path), 1, {"w": np.ones(4, np.float32)})
    stale = tmp_path / ".tmp-ckpt-0000000001.pod.g0"
    stale.mkdir()
    (stale / "arrays-p1.npz").write_bytes(b"partial")
    ckpt_format.collect_garbage(str(tmp_path), keep_last=5)
    assert not stale.exists()
    assert list_checkpoints(str(tmp_path))


def test_pod_info_single_process():
    assert pod_info() == (0, 1)


# --------------------------------------------------- kvstore-state resume

def test_update_on_kvstore_fit_checkpoints_and_resumes(tmp_path):
    """Optimizer state living on the kvstore (the pod children's path:
    dist_sync forces update_on_kvstore) must checkpoint and resume
    bit-identically. Exercised single-process through a kvstore
    INSTANCE, which forces update_on_kvstore the same way."""
    rng = np.random.RandomState(0)
    X = rng.uniform(-1, 1, (64, 16)).astype(np.float32)
    Y = rng.randint(0, 8, (64,)).astype(np.float32)

    def fit(num_epoch, ckpt=None, resume=None):
        mx.random.seed(11)
        sym = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=8,
                                  name="fc1"), name="softmax")
        it = mx.io.NDArrayIter(X, Y, batch_size=8)
        mod = mx.mod.Module(sym, context=mx.cpu())
        kv = mx.kv.create("local")
        kw = {}
        if ckpt is not None:
            kw["checkpoint"] = mx.checkpoint.CheckpointConfig(
                ckpt, period_epochs=1, async_save=False)
        if resume is not None:
            kw["resume_from"] = resume
        mod.fit(it, num_epoch=num_epoch, kvstore=kv, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1,
                                  "momentum": 0.9}, **kw)
        assert mod._update_on_kvstore      # the branch under test
        return {k: v.asnumpy().copy()
                for k, v in mod.get_params()[0].items()}

    base = str(tmp_path)
    fit(2, ckpt=base)                         # interrupted after epoch 2
    resumed = fit(4, ckpt=base, resume=base)  # resumes epochs 2..3
    reference = fit(4)
    assert set(resumed) == set(reference)
    for k in sorted(reference):
        np.testing.assert_array_equal(resumed[k], reference[k],
                                      err_msg=k)


# ---------------------------------------------------------- obs labels

def test_render_prometheus_carries_pod_labels(monkeypatch):
    from mxnet_tpu import obs
    from mxnet_tpu.obs import prometheus as prom
    profiler.incr_counter("pod_label_probe")
    monkeypatch.setattr(ckpt_format, "pod_info", lambda: (3, 4))
    assert prom.pod_labels() == {"process_index": "3",
                                 "world_size": "4"}
    text = obs.render_prometheus()
    samples = obs.parse_prometheus(text)     # grammar must still hold
    v = samples.get(("mxnet_tpu_pod_label_probe_total",
                     (("process_index", "3"), ("world_size", "4"))))
    assert v is not None and v >= 1
    rep = obs.report()
    assert rep["process"] == {"process_index": 3, "world_size": 4}


def test_render_prometheus_single_process_is_bare():
    from mxnet_tpu import obs
    profiler.incr_counter("pod_label_probe2")
    samples = obs.parse_prometheus(obs.render_prometheus())
    assert samples.get(("mxnet_tpu_pod_label_probe2_total", ())) >= 1


# ------------------------------------------------------------ end-to-end

@pytest.mark.slow
def test_launch_round_trip_env_and_barrier(tmp_path):
    """Satellite: tools/launch.py -n 2 CPU workers — both ranks see the
    same cluster_env() and a dist.barrier() completes (the env protocol
    had no test at all)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    cmd = [sys.executable, os.path.join(REPO, "tools", "launch.py"),
           "-n", "2", "--launcher", "local",
           sys.executable,
           os.path.join(REPO, "tests", "_launch_env_worker.py"),
           str(tmp_path)]
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=300)
    assert proc.returncode == 0, \
        "launcher failed:\n%s\n%s" % (proc.stdout[-4000:],
                                      proc.stderr[-4000:])
    recs = [json.load(open(tmp_path / ("env_rank%d.json" % r)))
            for r in range(2)]
    assert recs[0]["coordinator"] == recs[1]["coordinator"]
    assert [r["rank"] for r in recs] == [0, 1]
    assert all(r["num_workers"] == 2 for r in recs)


@pytest.mark.slow
def test_pod_smoke_script():
    """The CI multihost drill end-to-end: 2-host pod, host.die
    (hostkill AND silent-wedge) plus a child-only SIGKILL fired
    mid-epoch; surviving world reshards and resumes bit-identically;
    process-local sharded checkpoint phase; zero-cost gate
    (tools/pod_smoke.py)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "pod_smoke.py")],
        capture_output=True, text=True, timeout=1500,
        env={**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout[-6000:] + proc.stderr[-3000:]
    assert "POD-DRILL-OK" in proc.stdout

"""CI ``compile-time`` job: the ISSUE 9 compile/memory levers, gated.

Three checks:

1. **Bind-time regression gate (scan-over-layers)** — a deep (32-layer)
   transformer must bind + compile its first fused step inside a hard
   budget with scan ON, the plan must actually apply
   (``scan_applied``/``scan_layers``), and two scan-off comparisons
   hold: the deterministic one (the unrolled forward jaxpr carries >= 2x
   the equations of the scanned one at this depth — eqn count cannot be
   gamed by a fast box) and the wall-clock one (bind+first-step speedup
   >= 1.8x here; the >= 5x acceptance number is the deep regime, L=96+,
   measured out-of-band because a CI box should not burn 80s on the
   control arm's unrolled XLA compile... which is exactly the point).
2. **AOT warm-start smoke (MXNET_TPU_COMPILE_CACHE)** — process A
   trains 2 steps and must serialize the fused-step executable
   (``aot_store``); process B repeats the identical program and must
   deserialize it (``aot_hit``), record ZERO backend-compile phases for
   the ``fused_step`` scope in the obs compile accounting, and land
   bit-identical parameters.
3. **Zero-cost gate** — with all three knobs off
   (``MXNET_TPU_SCAN_LAYERS=off``, ``MXNET_TPU_REMAT=off``,
   ``MXNET_TPU_COMPILE_CACHE=``) a bind + fused step must import NONE of
   the new modules (scan / remat / aot / analysis) and bump none of
   their counters.

Exit code 0 = all gates passed.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

BIND_BUDGET_SECS = float(os.environ.get("COMPILE_TIME_BIND_BUDGET", "90"))


def _env(**extra):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ""   # the remote-TPU plugin rides PYTHONPATH
    env.update(extra)
    return env


def _run_child(code, **env):
    proc = subprocess.run([sys.executable, "-c", code], text=True,
                          capture_output=True, env=_env(**env),
                          timeout=600)
    if proc.returncode != 0:
        raise SystemExit("child failed (rc %d):\n%s\n%s"
                         % (proc.returncode, proc.stdout[-2000:],
                            proc.stderr[-4000:]))
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("{"):
            return json.loads(line)
    raise SystemExit("child produced no JSON:\n%s" % proc.stdout[-2000:])


# ------------------------------------------------------------- 1. scan

def check_scan_bind_time():
    import jax
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.models import transformer

    L, D, H, T, V, B = 32, 128, 4, 64, 256, 4
    sym = transformer.get_symbol(vocab_size=V, num_layers=L, d_model=D,
                                 n_heads=H, seq_len=T)
    jax.jit(lambda x: x * 2)(np.ones(4))   # warm jax itself

    def arm(mode):
        mx.config.set("MXNET_TPU_SCAN_LAYERS", mode)
        t0 = time.perf_counter()
        mod = mx.mod.Module(sym, context=mx.cpu(0))
        mod.bind(data_shapes=[("data", (B, T))],
                 label_shapes=[("softmax_label", (B, T))])
        mod.init_params(mx.init.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.01})
        x = np.random.RandomState(0).randint(0, V, (B, T)).astype(
            np.float32)
        y = np.random.RandomState(1).randint(0, V, (B, T)).astype(
            np.float32)
        db = mx.io.DataBatch(data=[mx.nd.array(x)],
                             label=[mx.nd.array(y)])
        mod._fit_step(db)
        float(np.asarray(mod._exec.arg_dict["lm_head_weight"].data[0, 0]))
        return mod, time.perf_counter() - t0

    mod_on, secs_on = arm("auto")
    assert mod_on._exec._scan_plan is not None, "scan plan did not apply"
    assert mx.profiler.gauges().get("scan_layers") == L
    assert secs_on <= BIND_BUDGET_SECS, \
        "deep transformer bind+first-step %.1fs exceeds %.0fs budget " \
        "with scan on" % (secs_on, BIND_BUDGET_SECS)

    mod_off, secs_off = arm("off")
    assert mod_off._exec._scan_plan is None

    # deterministic program-size gate: trace both forwards
    ex = mod_off._exec
    args = {n: a.data for n, a in ex.arg_dict.items()}
    aux = {n: a.data for n, a in ex.aux_dict.items()}
    key = jax.random.PRNGKey(0)
    n_off = len(jax.make_jaxpr(
        lambda a: mod_off._exec._fn(a, aux, key, True))(args).jaxpr.eqns)
    n_on = len(jax.make_jaxpr(
        lambda a: mod_on._exec._fn(a, aux, key, True))(args).jaxpr.eqns)
    assert n_off >= 2.0 * n_on, \
        "unrolled/scan eqn ratio %.2f < 2 (off %d, on %d)" \
        % (n_off / n_on, n_off, n_on)
    speedup = secs_off / secs_on
    assert speedup >= 1.8, \
        "scan bind+first-step speedup %.2fx < 1.8x (on %.1fs off %.1fs)" \
        % (speedup, secs_on, secs_off)
    mx.config.set("MXNET_TPU_SCAN_LAYERS", "auto")
    print("scan gate: L=%d on %.1fs off %.1fs speedup %.1fx "
          "eqns %d->%d (%.1fx)"
          % (L, secs_on, secs_off, speedup, n_off, n_on, n_off / n_on))


# -------------------------------------------------------------- 2. AOT

_AOT_CHILD = """
import json, os, sys
sys.path.insert(0, %(root)r)
import numpy as np
import mxnet_tpu as mx
mx.config.set("MXNET_TPU_COMPILE_CACHE", %(cache)r)
np.random.seed(0)
X = np.random.uniform(-1, 1, (64, 16)).astype(np.float32)
Y = (X.sum(axis=1) > 0).astype(np.float32)
it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=8,
                            name="fc1")
net = mx.sym.Activation(net, act_type="relu")
net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
net = mx.sym.SoftmaxOutput(net, mx.sym.Variable("softmax_label"),
                           name="softmax")
mod = mx.mod.Module(net, context=mx.cpu(0))
init = {"fc1_weight": mx.nd.array(np.full((8, 16), 0.01, np.float32)),
        "fc1_bias": mx.nd.zeros((8,)),
        "fc2_weight": mx.nd.array(np.full((2, 8), 0.01, np.float32)),
        "fc2_bias": mx.nd.zeros((2,))}
mod.fit(it, num_epoch=1, arg_params=init,
        optimizer_params={"learning_rate": 0.1})
c = mx.profiler.counters()
fused_compiles = [r for r in mx.obs.compiles.snapshot()
                  if r.get("scope") == "fused_step"]
print(json.dumps({
    "aot_hit": c.get("aot_hit", 0), "aot_store": c.get("aot_store", 0),
    "aot_error": c.get("aot_error", 0),
    "fused_backend_compiles": len(fused_compiles),
    "w00": repr(mod.get_params()[0]["fc1_weight"].asnumpy()[0, 0])}))
"""


def check_aot_warm_start():
    cache = tempfile.mkdtemp(prefix="aot_smoke_")
    child = _AOT_CHILD % {"root": ROOT, "cache": cache}
    cold = _run_child(child)
    assert cold["aot_store"] >= 1, "first process stored nothing: %r" % cold
    assert cold["aot_error"] == 0, cold
    warm = _run_child(child)
    assert warm["aot_hit"] >= 1, "second process missed the cache: %r" % warm
    assert warm["aot_error"] == 0, warm
    assert warm["fused_backend_compiles"] == 0, \
        "warm process backend-compiled the fused step: %r" % warm
    assert warm["w00"] == cold["w00"], \
        "warm-start params diverged: %r vs %r" % (cold["w00"], warm["w00"])
    print("aot gate: cold store=%d warm hit=%d fused compiles warm=%d"
          % (cold["aot_store"], warm["aot_hit"],
             warm["fused_backend_compiles"]))


# -------------------------------------------------------- 3. zero cost

_ZERO_CHILD = """
import json, sys
sys.path.insert(0, %(root)r)
import numpy as np
import mxnet_tpu as mx
net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
    mx.sym.Variable("data"), num_hidden=4, name="fc1"), name="softmax")
mod = mx.mod.Module(net, context=mx.cpu(0))
mod.bind(data_shapes=[("data", (4, 8))],
         label_shapes=[("softmax_label", (4,))])
mod.init_params(mx.init.Xavier())
mod.init_optimizer(optimizer="sgd")
db = mx.io.DataBatch(data=[mx.nd.array(np.zeros((4, 8), np.float32))],
                     label=[mx.nd.array(np.zeros((4,), np.float32))])
mod._fit_step(db)
bad_modules = [m for m in sys.modules
               if m in ("mxnet_tpu.symbol.scan", "mxnet_tpu.remat",
                        "mxnet_tpu.aot")
               or m.startswith("mxnet_tpu.analysis")]
c = mx.profiler.counters()
bad_counters = {k: v for k, v in c.items()
                if k.startswith(("scan_", "remat_", "aot_", "accum_"))
                and v}
print(json.dumps({"bad_modules": bad_modules,
                  "bad_counters": bad_counters}))
"""


def check_zero_cost():
    rec = _run_child(_ZERO_CHILD % {"root": ROOT},
                     MXNET_TPU_SCAN_LAYERS="off", MXNET_TPU_REMAT="off",
                     MXNET_TPU_COMPILE_CACHE="", MXNET_TPU_ANALYZE="off")
    assert not rec["bad_modules"], \
        "knobs off but modules imported: %r" % rec["bad_modules"]
    assert not rec["bad_counters"], \
        "knobs off but counters bumped: %r" % rec["bad_counters"]
    print("zero-cost gate: no scan/remat/aot/analysis import, "
          "no counters")


def main():
    check_zero_cost()
    check_aot_warm_start()
    check_scan_bind_time()
    print("compile-time smoke: all gates passed")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Cluster launcher (reference: tools/launch.py:50-80 + dmlc tracker).

Spawns N worker processes wired together by the DMLC_* env protocol the
reference's ps-lite used; here the variables point every worker at the
jax.distributed coordinator (rank 0's host:port) instead of a scheduler
process, and there are no server processes (-s is accepted for CLI parity
and ignored — the SPMD design has no server role).

Launchers:
  local — N processes on this host (reference `--launcher local`, the
          tests/nightly/dist_sync_kvstore.py pattern)
  ssh   — one process per line of --hostfile via passwordless ssh
          (reference `--launcher ssh`)

Usage:
  python tools/launch.py -n 4 python train.py --kv-store dist_sync
  python tools/launch.py -n 2 --launcher ssh -H hosts python train.py

Pod mode (`--coordinated`): each worker becomes a per-host elastic
coordinator (`python -m mxnet_tpu.elastic --coordinated -- cmd`) — the
pod survives ANY host dying or wedging mid-run, including the host
carrying the control plane (the survivors adjudicate over a
peer-to-peer probe ring, elect the lowest live rank, and re-host the
coordination KV service on its published fail-over port), by draining,
re-forming at the surviving world size, and resuming the training
command from the newest complete checkpoint
(docs/architecture/elastic.md). Hosts advertise the address peers
reach them at via MXNET_TPU_POD_HOST (defaults to the hostname; the
pod drills pin 127.0.0.1):

  python tools/launch.py -n 3 --coordinated -- python train.py
"""
import argparse
import os
import signal
import socket
import subprocess
import sys


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env(base, rank, n, uri, port):
    env = dict(base)
    env.update({
        "DMLC_ROLE": "worker",
        "DMLC_PS_ROOT_URI": uri,
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_WORKER": str(n),
        "DMLC_NUM_SERVER": "0",
        "DMLC_WORKER_ID": str(rank),
    })
    return env


def launch_local(args, command):
    port = args.port or _free_port()
    procs = []
    for rank in range(args.num_workers):
        env = _worker_env(os.environ, rank, args.num_workers,
                          "127.0.0.1", port)
        procs.append(subprocess.Popen(command, env=env))
    return _wait(procs)


def launch_ssh(args, command):
    import shlex
    with open(args.hostfile) as fin:
        hosts = [h.strip() for h in fin if h.strip()
                 and not h.startswith("#")]
    if len(hosts) < args.num_workers:
        sys.exit("hostfile has %d hosts, need %d" % (len(hosts),
                                                     args.num_workers))
    if args.port is None:
        # a port probed locally says nothing about hosts[0], where the
        # coordinator actually binds
        sys.exit("--launcher ssh needs an explicit --port free on the "
                 "first host (the jax.distributed coordinator binds there)")
    port = args.port
    uri = hosts[0]
    cwd = os.getcwd()
    procs = []
    for rank in range(args.num_workers):
        envs = " ".join("%s=%s" % (k, shlex.quote(str(v))) for k, v in
                        _worker_env({}, rank, args.num_workers, uri,
                                    port).items())
        remote = "cd %s; env %s %s" % (
            shlex.quote(cwd), envs,
            " ".join(shlex.quote(str(c)) for c in command))
        procs.append(subprocess.Popen(
            ["ssh", "-o", "StrictHostKeyChecking=no", hosts[rank], remote]))
    return _wait(procs)


def _wait(procs):
    rc = 0
    try:
        for p in procs:
            r = p.wait()
            rc = rc or r
    except KeyboardInterrupt:
        rc = 1
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="launch a distributed training job",
        usage="launch.py [-h] -n NUM_WORKERS [opts] command ...")
    ap.add_argument("-n", "--num-workers", type=int, required=True,
                    help="number of worker processes")
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for reference-CLI parity; the SPMD "
                         "design has no server processes")
    ap.add_argument("--launcher", choices=("local", "ssh"), default="local")
    ap.add_argument("-H", "--hostfile", help="hostfile for --launcher ssh")
    ap.add_argument("--port", type=int, default=None,
                    help="coordinator port (default: pick a free one)")
    ap.add_argument("--coordinated", action="store_true",
                    help="wrap the command in the per-host elastic pod "
                         "coordinator (python -m mxnet_tpu.elastic "
                         "--coordinated): the pod survives host death "
                         "by drain/reshard/resume")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no command given")
    command = [c for c in args.command if c != "--"]
    if args.coordinated:
        command = [sys.executable, "-m", "mxnet_tpu.elastic",
                   "--coordinated", "--"] + command
    if args.launcher == "local":
        rc = launch_local(args, command)
    else:
        if not args.hostfile:
            ap.error("--launcher ssh needs --hostfile")
        rc = launch_ssh(args, command)
    sys.exit(rc)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Measure collective-communication bandwidth over the device mesh.

Reference: ``tools/bandwidth/measure.py`` — times kvstore push/pull of
ResNet-sized gradients to estimate aggregation bandwidth. The TPU twin
times the collectives XLA actually emits (psum / all_gather /
reduce_scatter under shard_map over a Mesh) — on real hardware these ride
the ICI links; on the CPU rig they exercise the same code path for
plumbing checks.

Usage:
    python tools/bandwidth.py --size-mb 64 --iters 10
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/bandwidth.py
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--size-mb", type=float, default=64.0,
                    help="payload per device, megabytes")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--collectives", type=str,
                    default="psum,all_gather,reduce_scatter")
    args = ap.parse_args(argv)

    import jax
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(devs, ("x",))

    def smap(fn, in_specs, out_specs):
        # the replication checker can't infer psum outputs; disable it
        # (kwarg name varies across jax versions). The bare call runs
        # outside try so a genuine signature error propagates.
        for kw in ({"check_vma": False}, {"check_rep": False}):
            try:
                return shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kw)
            except TypeError:
                continue
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs)
    elems = int(args.size_mb * 1e6 / 4)
    elems -= elems % max(n, 1)
    x = jnp.ones((elems,), jnp.float32)

    def timed(fn, arr):
        jax.block_until_ready(fn(arr))              # compile + warm up
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = fn(arr)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / args.iters

    results = {}
    wanted = args.collectives.split(",")

    if "psum" in wanted:
        f = jax.jit(smap(lambda v: jax.lax.psum(v, "x"), P("x"), P()))
        dt = timed(f, x)
        # ring all-reduce moves ~2*(n-1)/n of the buffer per device
        gb = x.nbytes * 2 * (n - 1) / max(n, 1) / 1e9
        results["psum"] = (dt, gb / dt)
    if "all_gather" in wanted:
        f = jax.jit(smap(lambda v: jax.lax.all_gather(v, "x", tiled=True),
                         P("x"), P()))
        dt = timed(f, x)
        gb = x.nbytes * (n - 1) / max(n, 1) / 1e9
        results["all_gather"] = (dt, gb / dt)
    if "reduce_scatter" in wanted:
        f = jax.jit(smap(lambda v: jax.lax.psum_scatter(v, "x",
                                                        tiled=True),
                         P("x"), P("x")))
        dt = timed(f, x)
        gb = x.nbytes * (n - 1) / max(n, 1) / 1e9
        results["reduce_scatter"] = (dt, gb / dt)

    print("devices: %d (%s), payload %.1f MB"
          % (n, devs[0].platform, x.nbytes / 1e6))
    for name, (dt, bw) in results.items():
        print("%-15s %8.3f ms   %8.2f GB/s algorithmic" %
              (name, dt * 1e3, bw))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""CI ``fleet`` job: multi-replica kill-mid-stream drill + zero-cost
gate (ISSUE 20 satellite).

Two checks, real model replicas (tiny zoo transformer, CPU backend),
every subprocess wait under a hard timeout (the PhaseGuard discipline —
a wedged drill must fail the job, not hang it):

1. **Fleet drill** — a gateway supervises THREE replica processes
   serving bit-identical weights off a shared executable cache.
   ``MXNET_TPU_FLEET_FAULT_REPLICA=1:replica.die@6:hostkill`` arms rank
   1 (first spawn only) to SIGKILL itself after its 6th emitted token
   frame. Under a concurrent request wave:

   - every stream — the victim's in-flight sequences included — must
     complete BIT-EQUAL to a single-server reference (exact at-most-once
     fail-over: re-prefill from prompt + delivered prefix, no token
     duplicated, none lost, ``fleet_dup_dropped == 0``);
   - survivors are undisturbed (their streams are part of the same
     bit-equality check);
   - the supervisor respawns rank 1, which rejoins with ZERO backend
     compiles (AOT warm restart through the shared cache) and serves
     real traffic in the next wave;
   - the federated ``/metrics`` text parses strictly and carries
     ``replica="0|1|2"`` labeled samples.

2. **Zero-cost gate** — a subprocess that imports ``mxnet_tpu``, runs a
   plain ``GenerativeServer`` request, and asserts the fleet package
   never imported and no ``fleet*`` counter exists in the registry: a
   plain serve process pays NOTHING for the fleet's existence.

Exit code 0 = all gates passed.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

GEO = dict(vocab_size=128, num_layers=2, d_model=32, n_heads=2, seq_len=32)
SPEC = {"kind": "transformer", "geo": GEO, "seed": 11, "slots": 2,
        "page": 8, "name": "fleetrep"}
PROMPTS = [[3, 1, 4], [1, 5, 9], [2, 6], [5, 3, 5], [8, 9, 7, 9], [3, 2]]
NEW_TOKENS = 12


def _reference_streams():
    """Single-server ground truth: same spec, same seeded init — what
    every fleet stream must equal bit-for-bit. Building it first also
    warms the shared executable cache, so replica spawns (and the
    respawn under test) start AOT-warm."""
    from mxnet_tpu.fleet.replica import build_from_spec
    srv = build_from_spec(dict(SPEC, name="fleetref"))
    try:
        return {tuple(p): srv.submit_generate(
                    p, max_new_tokens=NEW_TOKENS).result(timeout=600)
                for p in PROMPTS}
    finally:
        srv.close()


def _wave(gw, ref):
    handles = [(p, gw.submit_generate(p, max_new_tokens=NEW_TOKENS))
               for p in PROMPTS]
    for p, h in handles:
        got = h.result(timeout=600)
        assert got == ref[tuple(p)], (
            "stream for prompt %s diverged:\n got %s\nwant %s"
            % (p, got, ref[tuple(p)]))


def check_fleet_drill():
    from mxnet_tpu import config as _config
    from mxnet_tpu.obs.prometheus import parse_prometheus

    cache_dir = tempfile.mkdtemp(prefix="fleet_smoke_aot_")
    os.environ["MXNET_TPU_COMPILE_CACHE"] = cache_dir
    # rank 1, FIRST spawn only, dies after its 6th emitted token frame;
    # hostkill (with the coordinated-parent marker stripped by the
    # supervisor) SIGKILLs exactly the replica process — no cleanup,
    # the honest analog of a host loss
    os.environ["MXNET_TPU_FLEET_FAULT_REPLICA"] = "1:replica.die@6:hostkill"
    _config.set("MXNET_TPU_FLEET", True)
    _config.set("MXNET_TPU_ELASTIC_BACKOFF", 0.2)

    ref = _reference_streams()
    print("reference streams computed (%d prompts), cache warm"
          % len(ref))

    from mxnet_tpu.fleet import Gateway
    gw = Gateway(spec=SPEC, replicas=3, port=None, stats_period=0.2,
                 name="drill_fleet")
    try:
        t0 = time.monotonic()
        live = gw.wait_ready(3, timeout=600.0)
        assert live == 3, "only %d/3 replicas came up" % live
        print("3 replicas live in %.1fs" % (time.monotonic() - t0))

        # ---- wave 1: rank 1 dies mid-stream under this load
        t0 = time.monotonic()
        _wave(gw, ref)
        st = gw.stats()
        assert st["failover"] >= 1, \
            "the armed kill never triggered a fail-over: %s" % st
        assert st["replica_dead"] >= 1, st
        assert st["dup_dropped"] == 0, \
            "at-most-once violated: %d duplicate frames" % st["dup_dropped"]
        print("PASS kill drill: all %d streams bit-equal through the "
              "rank-1 death (failover=%d, dup_dropped=0) in %.1fs"
              % (len(PROMPTS), st["failover"], time.monotonic() - t0))

        # ---- respawn: rank 1 rejoins, AOT-warm (zero backend compiles)
        t0 = time.monotonic()
        deadline = time.monotonic() + 600.0
        while time.monotonic() < deadline:
            st = gw.stats()
            r1 = st["replicas"][1]
            if st["live"] == 3 and r1["state"] == "live" \
                    and r1["stats"].get("pid"):
                break
            time.sleep(0.2)
        else:
            raise AssertionError("rank 1 never rejoined: %s" % st)
        print("rank 1 respawned and live in %.1fs (restarts=%d)"
              % (time.monotonic() - t0, st["replicas"][1]["restarts"]))
        # heartbeat carries the respawned process's compile accounting
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            bc = gw.stats()["replicas"][1]["stats"].get("backend_compiles")
            if bc is not None:
                break
            time.sleep(0.2)
        assert bc == 0, \
            "respawned replica compiled %s serve programs (want 0: " \
            "AOT warm restart)" % bc
        print("PASS warm respawn: rank 1 rejoined with 0 backend compiles")

        # ---- wave 2: the healed world serves, rank 1 takes traffic
        _wave(gw, ref)
        r1_tokens = gw.stats()["replicas"][1]["stats"].get("tokens", 0)
        deadline = time.monotonic() + 30.0
        while r1_tokens == 0 and time.monotonic() < deadline:
            time.sleep(0.2)     # stats lag one heartbeat
            r1_tokens = gw.stats()["replicas"][1]["stats"].get("tokens", 0)
        assert r1_tokens > 0, "respawned replica never took traffic"
        print("PASS healed wave: all streams bit-equal, respawned "
              "replica decoded %d tokens" % r1_tokens)

        # ---- federated metrics
        text = gw.metrics_text()
        samples = parse_prometheus(text)    # strict parse
        replicas = {dict(lbls).get("replica") for _n, lbls in samples}
        assert {"0", "1", "2"} <= replicas, \
            "federation missing replica labels: %s" % replicas
        print("PASS federation: /metrics carries replica=0/1/2 samples "
              "(%d total)" % len(samples))
    finally:
        gw.close(drain=False, timeout=60.0)
        os.environ.pop("MXNET_TPU_FLEET_FAULT_REPLICA", None)


_GATE_CHILD = """
import sys
sys.path.insert(0, %(root)r)
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import mxnet_tpu as mx
from mxnet_tpu.models import transformer
net = transformer.get_symbol(**%(geo)r)
mod = mx.mod.Module(net, context=mx.cpu())
s = %(geo)r["seq_len"]
mod.bind(data_shapes=[("data", (1, s))],
         label_shapes=[("softmax_label", (1, s))])
mod.init_params(mx.init.Uniform(0.05))
srv = mx.serve.GenerativeServer(mod, n_heads=%(geo)r["n_heads"],
                                max_sequences=2, page=8, name="plain")
srv.submit_generate([3, 1, 4], max_new_tokens=4).result(timeout=300)
srv.close()
assert "mxnet_tpu.fleet" not in sys.modules, "plain serve imported fleet"
from mxnet_tpu import profiler
bad = [k for k in profiler.counters() if k.startswith("fleet")]
assert not bad, "plain serve grew fleet counters: %%s" %% bad
print("GATE-OK")
"""


def check_zero_cost_gate():
    env = dict(os.environ)
    env.pop("MXNET_TPU_FLEET", None)
    env.pop("MXNET_TPU_FLEET_FAULT_REPLICA", None)
    out = subprocess.run(
        [sys.executable, "-c", _GATE_CHILD % {"root": _ROOT, "geo": GEO}],
        capture_output=True, text=True, timeout=600, env=env)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "GATE-OK" in out.stdout, out.stdout + out.stderr
    print("PASS zero-cost gate: plain serve never imports the fleet and "
          "grows no fleet counters")


def main():
    check_fleet_drill()
    check_zero_cost_gate()
    print("fleet smoke: ALL PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Parse a training log into a per-epoch table (reference:
tools/parse_log.py — same log grammar: the Speedometer/fit lines
``Epoch[N] Batch [M] Speed: S samples/sec metric=V``,
``Epoch[N] Train-metric=V``, ``Epoch[N] Time cost=T`` and
``Epoch[N] Validation-metric=V``).

Usage: python tools/parse_log.py train.log [--format markdown|csv]
"""
from __future__ import annotations

import argparse
import re
import sys
from collections import defaultdict

RE_BATCH = re.compile(
    r"Epoch\[(\d+)\] Batch \[\d+\]\s+Speed: ([\d.]+) samples/sec")
RE_TRAIN = re.compile(r"Epoch\[(\d+)\] Train-([\w-]+)=([\d.naninf-]+)")
RE_VAL = re.compile(r"Epoch\[(\d+)\] Validation-([\w-]+)=([\d.naninf-]+)")
RE_TIME = re.compile(r"Epoch\[(\d+)\] Time cost=([\d.]+)")


def parse(lines):
    rows = defaultdict(dict)
    speeds = defaultdict(list)
    for line in lines:
        m = RE_BATCH.search(line)
        if m:
            speeds[int(m.group(1))].append(float(m.group(2)))
            continue
        m = RE_TRAIN.search(line)
        if m:
            rows[int(m.group(1))]["train-" + m.group(2)] = float(m.group(3))
            continue
        m = RE_VAL.search(line)
        if m:
            rows[int(m.group(1))]["val-" + m.group(2)] = float(m.group(3))
            continue
        m = RE_TIME.search(line)
        if m:
            rows[int(m.group(1))]["time"] = float(m.group(2))
    for e, ss in speeds.items():
        rows[e]["speed"] = sum(ss) / len(ss)
    return dict(rows)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("logfile")
    ap.add_argument("--format", choices=("markdown", "csv"),
                    default="markdown")
    args = ap.parse_args(argv)
    with open(args.logfile) as f:
        rows = parse(f)
    if not rows:
        print("no epochs found", file=sys.stderr)
        return 1
    cols = ["epoch"] + sorted({k for r in rows.values() for k in r})
    if args.format == "csv":
        print(",".join(cols))
        for e in sorted(rows):
            print(",".join([str(e)] + ["%g" % rows[e].get(c, float("nan"))
                                       for c in cols[1:]]))
    else:
        print("| " + " | ".join(cols) + " |")
        print("|" + "|".join("---" for _ in cols) + "|")
        for e in sorted(rows):
            vals = ["%g" % rows[e][c] if c in rows[e] else ""
                    for c in cols[1:]]
            print("| " + " | ".join([str(e)] + vals) + " |")
    return 0


if __name__ == "__main__":
    sys.exit(main())

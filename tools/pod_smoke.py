"""Multi-host pod drill: coordinated elastic training that survives
ANY host death — including the leader's (CI ``multihost`` job; also
driven by tests/test_pod.py::test_pod_smoke_script). Extends the
single-process kill/reshard/resume drill of tools/elastic_smoke.py to
a multi-HOST pod — processes wired by the tools/launch.py DMLC env
protocol, each running ``python -m mxnet_tpu.elastic --coordinated``
over a CPU backend (``JAX_PLATFORMS=cpu``), training data-parallel
through the dist kvstore.

2-host variants, all mid-epoch at a deterministic batch:

* ``hostkill`` — ``host.die@K:hostkill`` SIGKILLs host 1's supervisor
  AND child (the whole "host" vanishes, no cleanup). The survivor
  drains, re-rendezvous at world 1, and finishes; the dead host's
  supervisor must exit -SIGKILL.
* ``wedge``    — ``host.die@K:wedge``: host 1 freezes WHOLE (the
  supervisor is SIGSTOPped, the child spins) — nothing crashes, no
  socket closes, ONLY the heartbeat staleness deadline can catch it.
  Host 0 must count ``elastic_dead_host`` and resume at world 1 while
  host 1 is provably still frozen (the driver reaps it afterwards).
* ``sigkill-child`` — ``fit.batch@K:sigkill`` kills host 1's CHILD
  only (the supervisor survives): the pod must restart POD-WIDE at the
  same world (SPMD cannot restart one rank alone) and still finish.

3-host LEADER fail-over variants (ISSUE 12 acceptance):

* ``leader-kill`` — ``leader.die@K:hostkill`` on host 0, the one
  carrying the control plane: survivors 1 and 2 adjudicate over the
  probe ring, elect rank 1, re-host the KV control plane on its
  published fail-over port, resume at world 2, and finish
  bit-identical with ``elastic_leader_failover == 1``.
* ``leader-cascade`` — kills the gen-0 leader AND then the gen-1
  leader (rank 1): rank 2 alone fails over TWICE and finishes at
  world 1 (``elastic_leader_failover == 2``).
* ``coordsvc`` — ``leader.die@K:coordsvc`` kills ONLY the control-
  plane KV service (every host stays up — the split-brain shape): all
  three coordinators must adjudicate all-live over the probe ring,
  re-elect rank 0, re-host on its fail-over port, and recover IN
  PLACE at world 3 with zero dead hosts and zero reshards.

Every variant's final parameters must be BIT-IDENTICAL to an
uninterrupted 1-host-pod baseline, with zero steady-state recompiles
asserted at every batch of every generation. The model is the same
one-hot "lookup regression" as elastic_smoke (every FP reduction has
exactly one nonzero contributor, so cross-world sums are exact); each
host masks the global batch down to its stride-shard, so the W-host
gradient sum equals the 1-host gradient bit-for-bit.

Also here:

* process-local checkpoint phase: a 2-process pod with 4 virtual
  devices each writes a cross-process-sharded checkpoint — each host's
  ``arrays-p<rank>.npz`` must hold ONLY the index windows it owns; a
  second save SIGKILLed mid-write on one host must abort as a unit
  (rank 0 times out, nothing commits) and ``load_latest`` falls back;
  the driver then reshards the survivor onto a single-device world.
* mid-save LEADER death (both orderings): rank 0 SIGKILLed AFTER its
  shard record published but BEFORE the manifest commit → a successor
  deterministically FINALIZES the save from the file-backed records
  (``finalize_staged_pod_saves``; ``meta.pod_commit.path ==
  "successor"``); killed BEFORE its record → the successor provably
  ABORTS (staging left for GC) and ``load_latest`` never sees a torn
  manifest.
* zero-cost gate: a plain single-process fit must never import
  ``mxnet_tpu.parallel.dist`` (the probe ring and the fail-over
  machinery live there), arm the fault harness, or move any
  ``elastic_*`` / ``fault_injected`` / ``loop_nonfinite`` /
  ``dist_kv_retry`` counter.

Exit 0 + ``POD-DRILL-OK`` on success; any assertion kills CI. Every
subprocess wait carries a hard timeout (PhaseGuard discipline — a
wedged drill fails, it does not hang the pipeline).
"""
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

BATCH, NSAMP, FEAT, OUT = 8, 64, 64, 4
EPOCHS = 3
SEED = 5
DIE_AT = 12                       # batch of the injected host failure
PHASE_TIMEOUT = 420.0

KNOBS = {
    "MXNET_TPU_HEARTBEAT_PERIOD": "0.5",
    "MXNET_KVSTORE_HEARTBEAT_STALE_SECS": "3",
    "MXNET_TPU_ELASTIC_DRAIN_GRACE": "6",
    "MXNET_TPU_CKPT_POD_TIMEOUT": "8",
    "MXNET_TPU_DIST_TIMEOUT": "60",
    "MXNET_TPU_PROBE_TIMEOUT": "1",
    # every "host" of the drill is this machine: advertise a loopback
    # address so a re-hosted control plane / probe ring is reachable
    # (real clusters: the launcher exports each host's routable name)
    "MXNET_TPU_POD_HOST": "127.0.0.1",
}


def _free_port():
    from mxnet_tpu.parallel.dist import free_port
    return free_port()


def _data(rank, world):
    """One-hot lookup samples, masked to this rank's stride-shard: row
    s is e_s (NSAMP == FEAT), zeroed unless s %% world == rank (labels
    too). Every gradient element keeps exactly one nonzero contributor
    GLOBALLY, so the cross-host kvstore sum at world W is bit-identical
    to the 1-host full-batch gradient (see module docstring)."""
    x = np.eye(FEAT, dtype=np.float32)[np.arange(NSAMP) % FEAT]
    rng = np.random.RandomState(3)
    y = rng.uniform(-1, 1, (NSAMP, OUT)).astype(np.float32)
    mine = (np.arange(NSAMP) % world) == rank
    x = x * mine[:, None]
    y = y * mine[:, None]
    return x, y


def _symbol():
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=OUT, no_bias=True,
                               name="lut")
    return mx.sym.LinearRegressionOutput(fc, mx.sym.Variable("label"),
                                         name="reg")


# ------------------------------------------------------- training child

def _pod_child(ckpt_dir, out_path):
    import jax
    # the accelerator plugin can rewrite JAX_PLATFORMS at startup; the
    # config override keeps every pod worker on the CPU backend (the
    # same guard tests/_dist_worker.py carries)
    jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu import elastic, faults, profiler
    gen = int(os.environ.get("MXNET_TPU_POD_GEN", "0"))
    wid = os.environ.get("DMLC_WORKER_ID", "")
    spec = os.environ.get("POD_SMOKE_FAULT", "")
    if spec and gen == 0 and wid == "1":
        faults.install(spec)
    # leader drills: semicolon list of g<gen>w<worker>=<spec> — the
    # worker id is the GENERATION-renumbered one, so "g1w0" targets
    # whoever leads the post-fail-over world (the cascade variant)
    for item in os.environ.get("POD_SMOKE_FAULTS", "").split(";"):
        item = item.strip()
        if not item:
            continue
        cond, _, fspec = item.partition("=")
        g, _, w = cond.partition("w")
        if int(g.lstrip("g")) == gen and w == wid:
            faults.install(fspec)
    # the rendezvous must run before ANY device touch (backend pins the
    # process's device view) — so the kvstore comes before the seed
    kv = mx.kv.create("dist_sync")
    mx.random.seed(SEED)
    rank, world = kv.rank, kv.num_workers
    X, Y = _data(rank, world)
    it = mx.io.NDArrayIter({"data": X}, {"label": Y}, batch_size=BATCH)
    mod = mx.mod.Module(_symbol(), context=mx.cpu(),
                        data_names=("data",), label_names=("label",))

    slp = float(os.environ.get("POD_SMOKE_BATCH_SLEEP", "0"))

    def _no_recompiles(_param):
        n = profiler.get_counter("loop_recompile")
        assert n == 0, "steady-state recompile detected (%d)" % n
        if slp:
            # coordsvc variant: the data plane survives the fault, so
            # training must outlast the coordinators' dark-control-plane
            # detection + drain — pace the batches like a real workload
            time.sleep(slp)

    mod.fit(it, num_epoch=EPOCHS, eval_metric="mse", optimizer="sgd",
            optimizer_params={"learning_rate": 0.3, "momentum": 0.9,
                              "rescale_grad": 1.0 / BATCH},
            kvstore=kv,
            checkpoint=mx.checkpoint.CheckpointConfig(
                ckpt_dir, every_n_batches=2, period_epochs=1,
                keep_last=0),
            resume_from=elastic.resume_dir(ckpt_dir),
            batch_end_callback=_no_recompiles)
    arg, _aux = mod.get_params()
    if rank == 0:
        np.savez(out_path, **{k: v.asnumpy() for k, v in arg.items()})
    kv.barrier()
    print("POD-CHILD-DONE rank=%d world=%d gen=%d recompiles=%d"
          % (rank, world, gen, profiler.get_counter("loop_recompile")),
         flush=True)
    return 0


# -------------------------------------------------- sharded-ckpt child

def _ckpt_child(ckpt_dir):
    from mxnet_tpu import faults
    from mxnet_tpu.parallel import dist
    from mxnet_tpu.checkpoint import (CheckpointPodError, load_latest,
                                      read_checkpoint, write_checkpoint)
    dist.initialize()
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    r, world = dist.rank(), dist.num_workers()
    if r == 1:
        faults.install("ckpt.after_arrays@2:sigkill")
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    mesh = Mesh(np.array(devs), ("data",))
    full = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    arr = jax.make_array_from_callback(
        full.shape, NamedSharding(mesh, P("data", None)),
        lambda idx: full[idx])
    rep = np.arange(4, dtype=np.float32)
    path = write_checkpoint(ckpt_dir, 1, {"w": arr, "rep": rep})

    if r == 0:
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        assert manifest["world_size"] == 2, manifest["world_size"]
        assert set(manifest["writers"]) == {"0", "1"}, manifest["writers"]
        # per-host ownership: each file holds ONLY windows its process
        # owns; the replicated tensor lives on rank 0 alone
        z0 = np.load(os.path.join(path, "arrays-p0.npz"))
        z1 = np.load(os.path.join(path, "arrays-p1.npz"))
        assert sorted(z0.files) == ["rep", "w@p0.s0", "w@p0.s1",
                                    "w@p0.s2", "w@p0.s3"], z0.files
        assert sorted(z1.files) == ["w@p1.s0", "w@p1.s1", "w@p1.s2",
                                    "w@p1.s3"], z1.files
        rows = sorted(sh["index"][0][0]
                      for sh in manifest["tensors"]["w"]["shards"]
                      if sh["process_index"] == 1)
        assert rows == [4, 5, 6, 7], rows   # proc 1 owns rows 4..7 only
        for key, rec in manifest["arrays"].items():
            assert rec["file"] == "arrays-p%d.npz" % rec["process_index"]

    tensors, _m = read_checkpoint(path)          # reassemble everywhere
    np.testing.assert_array_equal(tensors["w"], full)
    np.testing.assert_array_equal(tensors["rep"], rep)

    # save 2: rank 1 is SIGKILLed after its arrays hit disk but BEFORE
    # its record publishes — rank 0 must time out and abort as a unit
    if r == 1:
        write_checkpoint(ckpt_dir, 2, {"w": arr, "rep": rep})
        raise AssertionError("rank 1 survived its injected SIGKILL")
    try:
        write_checkpoint(ckpt_dir, 2, {"w": arr, "rep": rep})
    except CheckpointPodError as exc:
        assert "never published" in str(exc), exc
    else:
        raise AssertionError("rank 0 committed a partial pod save")
    steps = []
    from mxnet_tpu.checkpoint import list_checkpoints
    steps = [s for s, _p in list_checkpoints(ckpt_dir)]
    assert steps == [1], steps                   # nothing partial landed
    path2, t2, _m2 = load_latest(ckpt_dir)
    assert path2 == path
    np.testing.assert_array_equal(t2["w"], full)
    print("POD-CKPT-CHILD-OK rank=%d world=%d" % (r, world), flush=True)
    sys.stdout.flush()
    os._exit(0)    # skip jax's clean shutdown: the peer is dead


# ----------------------------------------------------------- zero cost

def _zero_cost():
    import mxnet_tpu as mx
    from mxnet_tpu import faults, profiler
    assert not faults.ARMED, "fault harness armed with no knob set"
    mx.random.seed(SEED)
    X, Y = _data(0, 1)
    it = mx.io.NDArrayIter({"data": X}, {"label": Y}, batch_size=BATCH)
    mod = mx.mod.Module(_symbol(), context=mx.cpu(),
                        data_names=("data",), label_names=("label",))
    mod.fit(it, num_epoch=1, eval_metric="mse", optimizer="sgd",
            optimizer_params={"learning_rate": 0.3})
    assert "mxnet_tpu.parallel.dist" not in sys.modules, \
        "the pod stack was imported in a plain single-process fit"
    assert "mxnet_tpu.obs.blackbox" not in sys.modules, \
        "the flight recorder was imported with its knob off"
    assert "mxnet_tpu.obs.straggler" not in sys.modules, \
        "the straggler stack was imported in a single-process fit"
    from mxnet_tpu.checkpoint import pod_info
    assert pod_info() == (0, 1)
    for name in ("fault_injected", "elastic_restart", "elastic_reshard",
                 "elastic_dead_host", "ckpt_preempt_save_failed",
                 "elastic_leader_failover", "loop_nonfinite",
                 "dist_kv_retry", "ckpt_pod_finalized",
                 "obs_blackbox_flush", "obs_straggler",
                 "obs_straggler_publish_failed"):
        assert profiler.get_counter(name) == 0, name
    assert getattr(mod, "_nancheck_fn", None) is None, \
        "NANCHECK=off must chain nothing onto the fused step"
    print("ZERO-COST-OK", flush=True)
    return 0


# -------------------------------------------------------------- driver

def _run(cmd, env, timeout, check=True):
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)
    if check:
        assert proc.returncode == 0, (cmd, proc.stdout[-4000:],
                                      proc.stderr[-4000:])
    return proc


def _dmlc_env(base, rank, n, port):
    env = dict(base)
    env.update({"DMLC_ROLE": "worker", "DMLC_PS_ROOT_URI": "127.0.0.1",
                "DMLC_PS_ROOT_PORT": str(port),
                "DMLC_NUM_WORKER": str(n), "DMLC_NUM_SERVER": "0",
                "DMLC_WORKER_ID": str(rank)})
    return env


def _assert_blackbox(name, bbdir, base_env, expect_bb):
    """Post-mortem acceptance: after the drill, the merge CLI must name
    the first-dead rank, its last fault site, and produce a merged
    timeline that loads as valid chrome-trace JSON; fail-over
    transitions must be present and clock-ordered."""
    proc = _run([sys.executable, "-m", "mxnet_tpu.obs", "blackbox",
                 bbdir], base_env, 120.0)
    m = re.search(r"POD-BLACKBOX-VERDICT (\{.*\})", proc.stdout)
    assert m, "%s: no verdict in:\n%s" % (name, proc.stdout[-4000:])
    verdict = json.loads(m.group(1))
    assert verdict["first_dead"] == expect_bb["first_dead"], \
        (name, verdict)
    assert verdict.get("last_event"), (name, verdict)
    lf = verdict.get("last_fault")
    assert lf and lf["site"] == expect_bb["fault_site"], (name, verdict)
    assert any(expect_bb["fault_site"] in spec
               for spec in verdict.get("armed_faults", [])), \
        (name, verdict)
    with open(os.path.join(bbdir, "pod-timeline.json")) as f:
        timeline = json.load(f)
    assert isinstance(timeline.get("traceEvents"), list) \
        and timeline["traceEvents"], (name, "empty merged timeline")
    if expect_bb.get("failover_ranks"):
        fos = verdict.get("failovers") or []
        got = {fo["rank"] for fo in fos}
        assert got >= set(expect_bb["failover_ranks"]), (name, fos)
        ts = [fo["t"] for fo in fos]
        assert ts == sorted(ts), (name, "fail-overs not clock-ordered",
                                  fos)
        # clock-ordered ACROSS ranks: every survivor's fail-over comes
        # after the dead leader's last recorded event
        assert all(t >= verdict["last_event"]["t"] for t in ts), \
            (name, verdict["last_event"], fos)
    print("POD-BLACKBOX-OK %s (first_dead=%s fault=%s)"
          % (name, verdict["first_dead"], lf["site"]), flush=True)


def _counters_line(stdout):
    m = re.search(r"POD-COORDINATOR-EXIT rank=(\d+) rc=(-?\d+) "
                  r"restarts=(\d+) reshards=(\d+) dead_hosts=(\d+) "
                  r"failovers=(\d+) counters=(\{.*\})", stdout)
    assert m, "no coordinator exit record in:\n%s" % stdout[-4000:]
    return {"rank": int(m.group(1)), "rc": int(m.group(2)),
            "restarts": int(m.group(3)), "reshards": int(m.group(4)),
            "dead_hosts": int(m.group(5)), "failovers": int(m.group(6)),
            "counters": json.loads(m.group(7))}


def _variant(name, fault, base_env, work, baseline, expect):
    """One pod-failure variant: spawn 2 coordinated supervisors, inject
    the fault on host 1 at batch DIE_AT of generation 0, assert the
    survivor finishes with params bit-identical to the baseline."""
    vdir = os.path.join(work, name)
    os.makedirs(vdir)
    ckpt = os.path.join(vdir, "ckpts")
    out = os.path.join(vdir, "params.npz")
    marker = os.path.join(vdir, "faults.touched")
    bbdir = os.path.join(vdir, "blackbox")
    port = _free_port()
    env = dict(base_env)
    env.update({"POD_SMOKE_FAULT": fault,
                "MXNET_TPU_FAULTS_TOUCH": marker,
                # flight recorder on for every variant: the post-mortem
                # drill (expect["blackbox"]) asserts on the merged
                # timeline after the hostkill; a short heartbeat bounds
                # how stale a SIGKILL'd host's window can be
                "MXNET_TPU_OBS_BLACKBOX": bbdir,
                "MXNET_TPU_OBS_BLACKBOX_FLUSH_SECS": "0.5"})
    cmd = [sys.executable, "-m", "mxnet_tpu.elastic", "--coordinated",
           "--max-restarts", "4", "--",
           os.path.abspath(__file__), "--child", ckpt, out]
    # each supervisor leads its own process group so a frozen host
    # (SIGSTOPped supervisor + wedged child) can be reaped as a unit
    sups = [subprocess.Popen(cmd, env=_dmlc_env(env, r, 2, port),
                             stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True,
                             start_new_session=True)
            for r in range(2)]
    deadline = time.monotonic() + PHASE_TIMEOUT
    outs = [None, None]
    frozen = expect.get("frozen", False)
    try:
        outs[0] = sups[0].communicate(timeout=deadline - time.monotonic())
        if frozen:
            # the whole point of the wedge variant: host 1 is still
            # frozen AFTER the survivor finished — nothing but the
            # heartbeat deadline ever noticed it
            assert sups[1].poll() is None, \
                "%s: host 1 exited (%s) but was expected frozen" \
                % (name, sups[1].returncode)
            os.killpg(sups[1].pid, signal.SIGKILL)
        outs[1] = sups[1].communicate(
            timeout=max(1.0, deadline - time.monotonic()))
    except subprocess.TimeoutExpired:
        for p in sups:
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except OSError:
                    p.kill()
        raise AssertionError(
            "%s: pod drill wedged past %.0fs" % (name, PHASE_TIMEOUT))
    finally:
        for p in sups:
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except OSError:
                    p.kill()
                p.wait()

    rc0, rc1 = sups[0].returncode, sups[1].returncode
    dump = "\n".join("--- rank %d rc=%s\n%s\n%s"
                     % (i, p.returncode, o[-4000:], e[-4000:])
                     for i, (p, (o, e)) in enumerate(zip(sups, outs)))
    assert rc0 == 0, "%s: survivor failed\n%s" % (name, dump)
    assert rc1 in expect["rc1"], "%s: host-1 rc %s not in %s\n%s" \
        % (name, rc1, expect["rc1"], dump)

    rec0 = _counters_line(outs[0][0])
    assert rec0["restarts"] >= 1, dump
    assert rec0["reshards"] >= expect["reshards_min"], dump
    if expect.get("dead_hosts_min"):
        assert rec0["dead_hosts"] >= expect["dead_hosts_min"], dump

    with open(marker) as f:
        touched = f.read()
    assert expect["marker"] in touched, (name, touched)

    ref = dict(np.load(baseline))
    got = dict(np.load(out))
    assert set(ref) == set(got), (sorted(ref), sorted(got))
    for k in sorted(ref):
        np.testing.assert_array_equal(ref[k], got[k], err_msg=k)

    # a world-2 generation left process-local checkpoints behind:
    # rank 1 wrote ONLY its own (empty: DP params are replicated and
    # owned by rank 0) arrays file, and the manifest says so
    pod_manifests = []
    for d in sorted(os.listdir(ckpt)):
        mf = os.path.join(ckpt, d, "manifest.json")
        if d.startswith("ckpt-") and os.path.exists(mf):
            with open(mf) as f:
                man = json.load(f)
            if man.get("world_size") == 2:
                pod_manifests.append((os.path.join(ckpt, d), man))
    assert pod_manifests, "no world-2 checkpoint survived in %s" % ckpt
    d, man = pod_manifests[-1]
    assert set(man["writers"]) == {"0", "1"}
    assert os.path.exists(os.path.join(d, "arrays-p0.npz"))
    assert os.path.exists(os.path.join(d, "arrays-p1.npz"))
    assert all(rec["process_index"] == 0
               for rec in man["arrays"].values()), \
        "replicated DP params must all be owned by rank 0"
    if expect.get("blackbox"):
        _assert_blackbox(name, bbdir, base_env, expect["blackbox"])
    print("POD-VARIANT-OK %s (rc1=%s restarts=%d reshards=%d "
          "dead_hosts=%d)" % (name, rc1, rec0["restarts"],
                              rec0["reshards"], rec0["dead_hosts"]),
          flush=True)


def _leader_variant(name, faults_spec, world, base_env, work, baseline,
                    expect):
    """One leader fail-over variant: a ``world``-host pod with
    ``leader.die`` armed through the per-generation POD_SMOKE_FAULTS
    map. Asserts exit codes per rank, the election/fail-over counters
    from the survivors' exit records, the fault marker, and final
    params bit-identical to the uninterrupted baseline."""
    vdir = os.path.join(work, name)
    os.makedirs(vdir)
    ckpt = os.path.join(vdir, "ckpts")
    out = os.path.join(vdir, "params.npz")
    marker = os.path.join(vdir, "faults.touched")
    bbdir = os.path.join(vdir, "blackbox")
    port = _free_port()
    env = dict(base_env)
    env.update({"POD_SMOKE_FAULTS": faults_spec,
                "MXNET_TPU_FAULTS_TOUCH": marker,
                "MXNET_TPU_OBS_BLACKBOX": bbdir,
                "MXNET_TPU_OBS_BLACKBOX_FLUSH_SECS": "0.5"})
    env.update(expect.get("env", {}))
    # budget headroom: one leader loss can cost TWO restarts on a rank
    # whose child died before its monitor saw the dark control plane
    # (child crash + rendezvous fail-over both consume budget)
    cmd = [sys.executable, "-m", "mxnet_tpu.elastic", "--coordinated",
           "--max-restarts", "8", "--",
           os.path.abspath(__file__), "--child", ckpt, out]
    sups = [subprocess.Popen(cmd, env=_dmlc_env(env, r, world, port),
                             stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True,
                             start_new_session=True)
            for r in range(world)]
    deadline = time.monotonic() + PHASE_TIMEOUT
    outs = [None] * world
    try:
        # highest ranks outlive every fail-over: collect in reverse
        # (rank 0 is the first to die in every leader variant)
        for r in reversed(range(world)):
            outs[r] = sups[r].communicate(
                timeout=max(1.0, deadline - time.monotonic()))
    except subprocess.TimeoutExpired:
        for p in sups:
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except OSError:
                    p.kill()
        raise AssertionError(
            "%s: leader drill wedged past %.0fs" % (name, PHASE_TIMEOUT))
    finally:
        for p in sups:
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except OSError:
                    p.kill()
                p.wait()

    dump = "\n".join("--- rank %d rc=%s\n%s\n%s"
                     % (i, p.returncode, (o or ("", ""))[0][-4000:],
                        (o or ("", ""))[1][-4000:])
                     for i, (p, o) in enumerate(zip(sups, outs)))
    for r, want in expect["rc"].items():
        assert sups[r].returncode in want, \
            "%s: rank %d rc %s not in %s\n%s" \
            % (name, r, sups[r].returncode, want, dump)
    for r, want in expect["recs"].items():
        rec = _counters_line(outs[r][0])
        assert rec["failovers"] == want["failovers"], \
            "%s: rank %d failovers %d != %d\n%s" \
            % (name, r, rec["failovers"], want["failovers"], dump)
        assert rec["counters"].get("elastic_leader_failover", 0) \
            == want["failovers"], (name, r, rec["counters"], dump)
        assert rec["restarts"] >= want.get("restarts_min", 0), (name, dump)
        assert rec["reshards"] >= want.get("reshards_min", 0), (name, dump)
        if "reshards_max" in want:
            assert rec["reshards"] <= want["reshards_max"], (name, dump)
        if "dead_hosts_max" in want:
            assert rec["dead_hosts"] <= want["dead_hosts_max"], \
                (name, dump)
    with open(marker) as f:
        touched = f.read()
    for needle in expect["marker"]:
        assert needle in touched, (name, needle, touched)

    ref = dict(np.load(baseline))
    got = dict(np.load(out))
    assert set(ref) == set(got), (sorted(ref), sorted(got))
    for k in sorted(ref):
        np.testing.assert_array_equal(ref[k], got[k], err_msg=k)
    if expect.get("manifest_world"):
        worlds = set()
        for d in sorted(os.listdir(ckpt)):
            mf = os.path.join(ckpt, d, "manifest.json")
            if d.startswith("ckpt-") and os.path.exists(mf):
                with open(mf) as f:
                    worlds.add(json.load(f).get("world_size"))
        assert expect["manifest_world"] in worlds, (worlds, dump)
    if expect.get("blackbox"):
        _assert_blackbox(name, bbdir, base_env, expect["blackbox"])
    print("POD-LEADER-VARIANT-OK %s (rcs=%s)"
          % (name, [p.returncode for p in sups]), flush=True)


# --------------------------------------- mid-save leader death drill

def _ckpt_leader_child(ckpt_dir, mode):
    """2-process pod: save 1 commits normally; during save 2 rank 0 is
    SIGKILLed at the armed site (``after-record`` = between shard-
    record publication and manifest commit; ``after-arrays`` = before
    its record exists). Rank 1 must see the save abort as a unit — or
    die with the data plane (the jax client's fatal abort over the
    dead coordination service); both are the host-death shape. The
    DRIVER is the successor that audits."""
    import time as _t
    from mxnet_tpu import faults
    from mxnet_tpu.parallel import dist
    from mxnet_tpu.checkpoint import CheckpointPodError, write_checkpoint
    dist.initialize()
    import jax
    jax.config.update("jax_platforms", "cpu")
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    r, _world = dist.rank(), dist.num_workers()
    if r == 0:
        faults.install("ckpt.%s@2:sigkill" % mode.replace("-", "_"))
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    mesh = Mesh(np.array(devs), ("data",))
    full = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    arr = jax.make_array_from_callback(
        full.shape, NamedSharding(mesh, P("data", None)),
        lambda idx: full[idx])
    write_checkpoint(ckpt_dir, 1, {"w": arr}, meta={"step": 1})
    if r == 1:
        try:
            write_checkpoint(ckpt_dir, 2, {"w": arr}, meta={"step": 2})
        except CheckpointPodError:
            pass                        # the unit abort — expected
        print("POD-CKPT-LEADER-CHILD-OK rank=1", flush=True)
        sys.stdout.flush()
        os._exit(0)
    # rank 0: give rank 1 time to land its shard record FIRST (the
    # successor audit distinguishes the orderings by which records are
    # durable; a racing mid-write abort is the leave-for-GC case and is
    # covered by the after-arrays ordering)
    _t.sleep(1.5)
    write_checkpoint(ckpt_dir, 2, {"w": arr}, meta={"step": 2})
    raise AssertionError("rank 0 survived its injected SIGKILL")


def _ckpt_leader_phase(work, base_env):
    """Both orderings of the mid-save leader death, audited by the
    driver as the successor leader."""
    from mxnet_tpu.checkpoint import (finalize_staged_pod_saves,
                                      list_checkpoints, load_latest)
    full = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    for mode, expect_commit in (("after-record", True),
                                ("after-arrays", False)):
        cdir = os.path.join(work, "ckpt_leader_%s" % mode)
        port = _free_port()
        env = dict(base_env)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--ckpt-leader-child", cdir, mode],
            env=_dmlc_env(env, r, 2, port), stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True) for r in range(2)]
        outs = [p.communicate(timeout=PHASE_TIMEOUT) for p in procs]
        dump = "\n".join("--- rank %d rc=%s\n%s\n%s"
                         % (i, p.returncode, o[-4000:], e[-4000:])
                         for i, (p, (o, e)) in enumerate(zip(procs,
                                                             outs)))
        assert procs[0].returncode == -signal.SIGKILL, dump
        # clean unit-abort, or the data-plane client's fatal abort over
        # the dead coordination service — both are host-death shapes
        assert procs[1].returncode in (0, -signal.SIGABRT), dump
        steps = [s for s, _p in list_checkpoints(cdir)]
        assert steps == [1], (mode, steps, dump)   # nothing partial
        finalized = finalize_staged_pod_saves(cdir, by_rank=1)
        if expect_commit:
            assert len(finalized) == 1, (mode, finalized, dump)
            _p2, tensors, man = load_latest(cdir)
            assert man["step"] == 2, man["step"]
            assert man["meta"]["pod_commit"]["path"] == "successor", \
                man["meta"]["pod_commit"]
            assert man["meta"]["pod_commit"]["committed_by"] == 1
            np.testing.assert_array_equal(np.asarray(tensors["w"]), full)
        else:
            assert finalized == [], (mode, finalized, dump)
            _p2, _t2, man = load_latest(cdir)
            assert man["step"] == 1, man["step"]   # fell back, not torn
            assert any(n.startswith(".tmp-ckpt-0000000002.pod")
                       for n in os.listdir(cdir)), \
                "aborted staging was not left for GC"
        print("POD-CKPT-LEADER-OK %s" % mode, flush=True)


def main():
    if "--child" in sys.argv:
        i = sys.argv.index("--child")
        return _pod_child(sys.argv[i + 1], sys.argv[i + 2])
    if "--ckpt-child" in sys.argv:
        return _ckpt_child(sys.argv[sys.argv.index("--ckpt-child") + 1])
    if "--ckpt-leader-child" in sys.argv:
        i = sys.argv.index("--ckpt-leader-child")
        return _ckpt_leader_child(sys.argv[i + 1], sys.argv[i + 2])
    if "--baseline" in sys.argv:
        return _pod_child(*sys.argv[sys.argv.index("--baseline") + 1:][:2])
    if "--zero-cost" in sys.argv:
        return _zero_cost()

    work = tempfile.mkdtemp(prefix="pod_smoke_")
    base_env = {**os.environ, "PYTHONPATH": REPO,
                "JAX_PLATFORMS": "cpu", "XLA_FLAGS": "", **KNOBS}
    for k in ("MXNET_TPU_FAULTS", "MXNET_TPU_CKPT_TEST_CRASH",
              "MXNET_TPU_FAULTS_TOUCH", "POD_SMOKE_FAULT",
              "MXNET_TPU_OBS_BLACKBOX", "MXNET_TPU_POD_KV",
              "MXNET_TPU_POD_RANK"):
        base_env.pop(k, None)

    # ---- uninterrupted baseline: a 1-host pod over the full data -----
    baseline = os.path.join(work, "baseline.npz")
    env = _dmlc_env(base_env, 0, 1, _free_port())
    _run([sys.executable, os.path.abspath(__file__), "--baseline",
          os.path.join(work, "baseline_ckpts"), baseline],
         env, PHASE_TIMEOUT)
    assert os.path.exists(baseline)

    # ---- the three failure variants (one retry each: killing tasks
    # under a shared jax coordination service can rarely abort a
    # survivor before it reports — the same allowance test_dist makes)
    variants = [
        ("hostkill", "host.die@%d:hostkill" % DIE_AT,
         {"rc1": (-signal.SIGKILL,), "reshards_min": 1,
          "marker": "host.die@%d:hostkill" % DIE_AT,
          "blackbox": {"first_dead": 1, "fault_site": "host.die"}}),
        ("wedge", "host.die@%d:wedge" % DIE_AT,
         {"rc1": (-signal.SIGKILL,), "frozen": True, "reshards_min": 1,
          "dead_hosts_min": 1,
          "marker": "host.die@%d:wedge" % DIE_AT}),
        ("sigkill-child", "fit.batch@%d:sigkill" % DIE_AT,
         {"rc1": (0,), "reshards_min": 0,
          "marker": "fit.batch@%d:sigkill" % DIE_AT}),
    ]
    for name, fault, expect in variants:
        for attempt in range(2):
            try:
                _variant(name if attempt == 0 else name,
                         fault, base_env,
                         os.path.join(work, "a%d" % attempt), baseline,
                         expect)
                break
            except AssertionError:
                if attempt:
                    raise
                print("POD-VARIANT-RETRY %s" % name, flush=True)

    # ---- leader fail-over variants (3-host pod, ISSUE 12) ------------
    CASCADE_AT = 5
    leader_variants = [
        ("leader-kill", "g0w0=leader.die@%d:hostkill" % DIE_AT, 3,
         {"rc": {0: (-signal.SIGKILL,), 1: (0,), 2: (0,)},
          "recs": {1: {"failovers": 1, "restarts_min": 1,
                       "reshards_min": 1},
                   2: {"failovers": 1, "restarts_min": 1,
                       "reshards_min": 1}},
          "marker": ["leader.die@%d:hostkill" % DIE_AT],
          "manifest_world": 3,
          "blackbox": {"first_dead": 0, "fault_site": "leader.die",
                       "failover_ranks": [1, 2]}}),
        ("leader-cascade",
         "g0w0=leader.die@%d:hostkill;g1w0=leader.die@%d:hostkill"
         % (DIE_AT, CASCADE_AT), 3,
         {"rc": {0: (-signal.SIGKILL,), 1: (-signal.SIGKILL,), 2: (0,)},
          "recs": {2: {"failovers": 2, "restarts_min": 2,
                       "reshards_min": 2}},
          "marker": ["leader.die@%d:hostkill" % DIE_AT,
                     "leader.die@%d:hostkill" % CASCADE_AT]}),
        ("coordsvc", "g0w0=leader.die@%d:coordsvc" % DIE_AT, 3,
         {"rc": {0: (0,), 1: (0,), 2: (0,)},
          "recs": {r: {"failovers": 1, "restarts_min": 1,
                       "reshards_max": 0, "dead_hosts_max": 0}
                   for r in range(3)},
          "marker": ["leader.die@%d:coordsvc" % DIE_AT],
          "env": {"POD_SMOKE_BATCH_SLEEP": "0.3"}}),
    ]
    for name, spec, world, expect in leader_variants:
        for attempt in range(2):
            try:
                _leader_variant(name, spec, world, base_env,
                                os.path.join(work, "l%d" % attempt),
                                baseline, expect)
                break
            except AssertionError:
                if attempt:
                    raise
                print("POD-LEADER-VARIANT-RETRY %s" % name, flush=True)

    # ---- mid-save leader death (successor finalize/abort) ------------
    _ckpt_leader_phase(work, base_env)

    # ---- process-local sharded checkpoint phase ----------------------
    ckpt_dir = os.path.join(work, "sharded_ckpts")
    port = _free_port()
    env = dict(base_env)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--ckpt-child",
         ckpt_dir],
        env=_dmlc_env(env, r, 2, port), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True) for r in range(2)]
    outs = [p.communicate(timeout=PHASE_TIMEOUT) for p in procs]
    dump = "\n".join("--- rank %d rc=%s\n%s\n%s"
                     % (i, p.returncode, o[-4000:], e[-4000:])
                     for i, (p, (o, e)) in enumerate(zip(procs, outs)))
    assert procs[0].returncode == 0, dump
    assert procs[1].returncode == -signal.SIGKILL, dump
    assert "POD-CKPT-CHILD-OK rank=0" in outs[0][0], dump
    # the driver (a 1-process world) reshards the 2-host save onto one
    # device: "read_checkpoint reassembles or reshards across whatever
    # world resumes"
    import jax
    from jax.sharding import Mesh
    from mxnet_tpu.checkpoint import load_latest
    mesh = Mesh(np.array(jax.devices("cpu")[:1]), ("data",))
    _p, tensors, man = load_latest(ckpt_dir, mesh=mesh)
    assert man["world_size"] == 2
    np.testing.assert_array_equal(
        np.asarray(tensors["w"]),
        np.arange(8 * 16, dtype=np.float32).reshape(8, 16))
    print("POD-CKPT-PHASE-OK", flush=True)

    # ---- zero-cost gate ----------------------------------------------
    env = dict(base_env)
    for k in ("DMLC_PS_ROOT_URI", "DMLC_PS_ROOT_PORT",
              "DMLC_NUM_WORKER", "DMLC_WORKER_ID", "DMLC_ROLE"):
        env.pop(k, None)
    proc = _run([sys.executable, os.path.abspath(__file__),
                 "--zero-cost"], env, PHASE_TIMEOUT)
    assert "ZERO-COST-OK" in proc.stdout

    print("POD-DRILL-OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

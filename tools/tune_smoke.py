"""CI ``tune`` job: the ISSUE 19 autotuner, gated.

Four checks:

1. **Zero-cost gate** — with ``MXNET_TPU_TUNE`` unset, a full fit must
   import NO ``mxnet_tpu.tune`` module and bump no ``tune_*`` counter.
2. **Bounded search (tiny MLP)** — ``search()`` with probe subprocesses
   must return inside a hard wall-clock budget, probe the default, and
   pick a winner whose probe score is >= the default's (the default is
   always in the probe set, so this holds by construction — the gate
   asserts the construction).
3. **Bounded search (tiny transformer)** — same gates on the seq-model
   path (int32 embedding inputs, seq labels, Loss metric).
4. **Warm restart** — process A runs ``fit(tune="auto")`` with a config
   store + AOT cache: searches, persists, trains. Process B repeats the
   identical program: it must LOAD the stored config (``tune_store_hit``,
   zero probes, zero search), reach its first step with ZERO backend
   compiles for the fused step (obs compile accounting + ``aot_hit``),
   and finish with the tuned knobs applied (``tune_applied``).

Exit code 0 = all gates passed.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SEARCH_BUDGET_SECS = float(os.environ.get("TUNE_SEARCH_BUDGET", "300"))
# CPU probes need an explicit MFU denominator
os.environ.setdefault("MXNET_TPU_OBS_PEAK_FLOPS", "1e12")


def _env(**extra):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ""   # the remote-TPU plugin rides PYTHONPATH
    env.update(extra)
    return env


def _run_child(code, **env):
    proc = subprocess.run([sys.executable, "-c", code], text=True,
                          capture_output=True, env=_env(**env),
                          timeout=600)
    if proc.returncode != 0:
        raise SystemExit("child failed (rc %d):\n%s\n%s"
                         % (proc.returncode, proc.stdout[-2000:],
                            proc.stderr[-4000:]))
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("{"):
            return json.loads(line)
    raise SystemExit("child produced no JSON:\n%s" % proc.stdout[-2000:])


# -------------------------------------------------------- 1. zero cost

_ZERO_CHILD = """
import json, sys
sys.path.insert(0, %(root)r)
import numpy as np
import mxnet_tpu as mx
net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
    mx.sym.Variable("data"), num_hidden=4, name="fc1"), name="softmax")
X = np.zeros((16, 8), np.float32)
Y = np.zeros((16,), np.float32)
it = mx.io.NDArrayIter(X, Y, batch_size=8, label_name="softmax_label")
mod = mx.mod.Module(net, context=mx.cpu(0))
mod.fit(it, num_epoch=1, optimizer_params={"learning_rate": 0.01})
bad_modules = [m for m in sys.modules if m.startswith("mxnet_tpu.tune")]
bad_counters = {k: v for k, v in mx.profiler.counters().items()
                if k.startswith("tune") and v}
print(json.dumps({"bad_modules": bad_modules,
                  "bad_counters": bad_counters}))
"""


def check_zero_cost():
    env = {k: "" for k in os.environ if k.startswith("MXNET_TPU_TUNE")}
    rec = _run_child(_ZERO_CHILD % {"root": ROOT}, **env)
    assert not rec["bad_modules"], \
        "tuner off but modules imported: %r" % rec["bad_modules"]
    assert not rec["bad_counters"], \
        "tuner off but counters bumped: %r" % rec["bad_counters"]
    print("zero-cost gate: no tune import, no tune counters")


# -------------------------------------------- 2+3. bounded search gates

def check_bounded_search(net_name):
    from mxnet_tpu.tune import search
    from mxnet_tpu.tune.__main__ import _zoo
    batch = 8 if net_name == "transformer" else 32
    sym, data_shapes, label_shapes, dtypes = _zoo(net_name, batch)
    t0 = time.perf_counter()
    cfg = search(sym, data_shapes, label_shapes, optimizer="sgd",
                 mode="auto", probe_steps=4, max_probes=2,
                 probe_deadline_s=120, data_dtypes=dtypes,
                 use_store=False)
    wall = time.perf_counter() - t0
    assert wall <= SEARCH_BUDGET_SECS, \
        "%s search took %.0fs > %.0fs budget" \
        % (net_name, wall, SEARCH_BUDGET_SECS)
    assert cfg.n_probed >= 1, "no probe completed for %s" % net_name
    assert cfg.source in ("probe", "static"), cfg.source
    if cfg.source == "probe":
        assert cfg.baseline is not None, \
            "winner scored without a default baseline"
        win = cfg.score.get("steps_per_sec") or 0
        base = cfg.baseline.get("steps_per_sec") or 0
        assert win >= base, \
            "winner %.2f steps/s < default %.2f" % (win, base)
        assert int(cfg.score.get("loop_recompile") or 0) == 0
    print("bounded search gate (%s): %.1fs, %d probed, winner %s (%s)"
          % (net_name, wall, cfg.n_probed, cfg.candidate.to_dict(),
             cfg.source))


# ----------------------------------------------------- 4. warm restart

_TUNE_CHILD = """
import json, sys, time
sys.path.insert(0, %(root)r)
import numpy as np
import mxnet_tpu as mx
np.random.seed(0)
X = np.random.uniform(-1, 1, (64, 16)).astype(np.float32)
Y = (X.sum(axis=1) > 0).astype(np.float32)
it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=8,
                            name="fc1")
net = mx.sym.Activation(net, act_type="relu")
net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
net = mx.sym.SoftmaxOutput(net, mx.sym.Variable("softmax_label"),
                           name="softmax")
mod = mx.mod.Module(net, context=mx.cpu(0))
t0 = time.perf_counter()
mod.fit(it, num_epoch=1, tune="auto",
        optimizer_params={"learning_rate": 0.1})
wall = time.perf_counter() - t0
c = mx.profiler.counters()
fused_compiles = [r for r in mx.obs.compiles.snapshot()
                  if r.get("scope") == "fused_step"]
print(json.dumps({
    "wall_s": round(wall, 2),
    "tune_applied": c.get("tune_applied", 0),
    "tune_probe": c.get("tune_probe", 0),
    "tune_store_write": c.get("tune_store_write", 0),
    "tune_store_hit": c.get("tune_store_hit", 0),
    "aot_hit": c.get("aot_hit", 0),
    "fused_backend_compiles": len(fused_compiles),
    "loop_recompile": c.get("loop_recompile", 0)}))
"""


def check_warm_restart():
    cache = tempfile.mkdtemp(prefix="tune_smoke_")
    child = _TUNE_CHILD % {"root": ROOT}
    env = dict(MXNET_TPU_COMPILE_CACHE=cache,
               MXNET_TPU_TUNE_PROBE_STEPS="4",
               MXNET_TPU_TUNE_MAX_PROBES="2")
    cold = _run_child(child, **env)
    assert cold["tune_applied"] == 1, cold
    assert cold["tune_probe"] >= 1, "cold start probed nothing: %r" % cold
    assert cold["tune_store_write"] == 1, cold
    warm = _run_child(child, **env)
    assert warm["tune_store_hit"] == 1, \
        "restart did not read the stored config: %r" % warm
    assert warm["tune_probe"] == 0, \
        "restart re-searched (%d probes): %r" % (warm["tune_probe"], warm)
    assert warm["tune_applied"] == 1, warm
    # the acceptance bar: pre-tuned AND pre-compiled — the winning
    # probe's executable serves the tuned fit, zero backend compiles
    assert warm["aot_hit"] >= 1, "warm fit missed the AOT cache: %r" % warm
    assert warm["fused_backend_compiles"] == 0, \
        "warm fit backend-compiled the fused step: %r" % warm
    assert warm["loop_recompile"] == 0, warm
    print("warm-restart gate: cold %.1fs (%d probes, stored) -> "
          "warm %.1fs (store hit, aot hit, 0 compiles)"
          % (cold["wall_s"], cold["tune_probe"], warm["wall_s"]))


def main():
    check_zero_cost()
    check_bounded_search("mlp")
    check_bounded_search("transformer")
    check_warm_restart()
    print("tune smoke: all gates passed")


if __name__ == "__main__":
    main()
